"""spatialflink_tpu — a TPU-native spatial stream-processing framework.

A ground-up JAX/XLA re-design of the capabilities of GeoFlink/SpatialFlink
(reference: marianaGarcez/SpatialFlink, Java/Flink): continuous spatial
queries (range, kNN, join) over point/polygon/linestring streams, trajectory
operators (tRange/tKnn/tJoin/tAggregate/tStats/tFilter), a uniform-grid
spatial index with guaranteed/candidate cell pruning, GeoJSON/WKT/CSV/TSV
serde, the SNCB railway query suite, and an NES-compatible metrics layer.

Architecture (TPU-first, not a port):
  - ``ops/``       batched JAX kernels (distance, cell assignment, pruning,
                   range/kNN/join, segment ops) — everything the reference
                   computes per-record in JVM inner loops becomes one fused
                   XLA program over a padded window batch.
  - ``models/``    spatial object model (Point/Polygon/LineString/...) plus
                   structure-of-arrays batch containers that cross the
                   host→device boundary.
  - ``grid.py``    the UniformGrid index: host-side neighbor-layer math
                   producing per-cell flag arrays the kernels gather from.
  - ``streams/``   host control plane: event-time windows, watermarks,
                   sources/sinks, serde. Windowing stays on host; window
                   payloads are shipped to the TPU kernels as batches.
  - ``operators/`` the user-facing operator API mirroring the reference's
                   surface (RangeQuery/KNNQuery/JoinQuery per type pair,
                   QueryConfiguration, trajectory query classes).
  - ``parallel/``  jax.sharding Mesh + shard_map data-parallel kernels for
                   multi-chip scale-out (ICI collectives, not keyBy shuffle).
  - ``sncb/``      the Belgian-railway domain layer (Q1..Q5, MN_Q1..Q5).
  - ``mn/``        NES-compatible instrumentation/benchmark layer.
"""

__version__ = "0.1.0"

from spatialflink_tpu import runtime  # noqa: F401  (configures the XLA cache)
from spatialflink_tpu.grid import UniformGrid  # noqa: F401
