"""Spatial object model.

The host-side counterparts of the reference's
``GeoFlink/spatialObjects/{SpatialObject,Point,Polygon,LineString,
MultiPoint,MultiPolygon,MultiLineString,GeometryCollection}.java``.
Unlike the reference (JTS-wrapping POJOs with embedded Flink operators,
Point.java:40-125), these are thin numpy-backed records: single objects are
the serde/API currency, while all computation happens on structure-of-arrays
batches (models/batch.py). Grid-cell sets are computed lazily against a
UniformGrid rather than stored as string HashSets (Polygon.java:16-22).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np

from spatialflink_tpu.grid import UniformGrid
from spatialflink_tpu.ops.polygon import pack_polyline, pack_rings


@dataclass
class SpatialObject:
    """Base: objID + event timestamp (ms) — SpatialObject.java:27-33."""

    obj_id: Optional[str] = None
    timestamp: int = 0  # epoch millis, like timeStampMillisec
    ingestion_time: Optional[float] = None  # host wall time at ingest (s)


@dataclass
class Point(SpatialObject):
    """A 2-D point (Point.java:40-125, minus the embedded Flink helpers)."""

    x: float = 0.0
    y: float = 0.0

    @property
    def coords(self) -> np.ndarray:
        return np.array([self.x, self.y], np.float64)

    def grid_cell(self, grid: UniformGrid) -> int:
        return grid.flat_cell(self.x, self.y)

    def grid_cells(self, grid: UniformGrid) -> List[int]:
        return [self.grid_cell(grid)]

    def bbox(self) -> Tuple[float, float, float, float]:
        return (self.x, self.y, self.x, self.y)


def _bbox_of(arrays: Sequence[np.ndarray]) -> Tuple[float, float, float, float]:
    allv = np.concatenate([np.asarray(a, np.float64) for a in arrays], axis=0)
    return (
        float(allv[:, 0].min()),
        float(allv[:, 1].min()),
        float(allv[:, 0].max()),
        float(allv[:, 1].max()),
    )


@dataclass
class Polygon(SpatialObject):
    """Polygon with optional holes: rings[0] = exterior (Polygon.java:26-100).

    ``rings``: list of (R, 2) coordinate arrays. The bbox and the set of
    overlapped grid cells (the reference's gridIDsSet, Polygon.java:16-22)
    derive from the exterior ring's bbox, exactly like
    HelperClass.assignGridCellID(bBox, uGrid) (HelperClass.java:122-143).
    """

    rings: List[np.ndarray] = field(default_factory=list)

    def __post_init__(self):
        self.rings = [np.asarray(r, np.float64) for r in self.rings]

    def bbox(self) -> Tuple[float, float, float, float]:
        return _bbox_of(self.rings[:1])

    def grid_cells(self, grid: UniformGrid) -> List[int]:
        return grid.bbox_cells(*self.bbox()).tolist()

    def packed(self, pad_to: Optional[int] = None):
        return pack_rings(self.rings, pad_to=pad_to)

    @property
    def exterior(self) -> np.ndarray:
        return self.rings[0]

    def num_vertices_packed(self) -> int:
        return sum(
            len(r) + (0 if np.array_equal(r[0], r[-1]) else 1) for r in self.rings
        )


@dataclass
class LineString(SpatialObject):
    """Open polyline (LineString.java:24-113)."""

    coords: np.ndarray = field(default_factory=lambda: np.zeros((0, 2)))

    def __post_init__(self):
        self.coords = np.asarray(self.coords, np.float64)

    def bbox(self) -> Tuple[float, float, float, float]:
        return _bbox_of([self.coords])

    def grid_cells(self, grid: UniformGrid) -> List[int]:
        return grid.bbox_cells(*self.bbox()).tolist()

    def packed(self, pad_to: Optional[int] = None):
        return pack_polyline([self.coords], pad_to=pad_to)

    def num_vertices_packed(self) -> int:
        return len(self.coords)


@dataclass
class MultiPoint(SpatialObject):
    """Standalone point set (MultiPoint.java:14)."""

    coords: np.ndarray = field(default_factory=lambda: np.zeros((0, 2)))

    def __post_init__(self):
        self.coords = np.asarray(self.coords, np.float64)

    def bbox(self) -> Tuple[float, float, float, float]:
        return _bbox_of([self.coords])

    def grid_cells(self, grid: UniformGrid) -> List[int]:
        return grid.bbox_cells(*self.bbox()).tolist()


@dataclass
class MultiPolygon(Polygon):
    """List of polygons, each a ring list (MultiPolygon.java:13 extends
    Polygon — same here: ``rings`` holds all rings, ``parts`` records the
    ring count per member polygon)."""

    parts: List[int] = field(default_factory=list)  # rings per member

    @classmethod
    def from_polygons(cls, polys: Sequence[Sequence[np.ndarray]], **kw):
        rings: List[np.ndarray] = []
        parts = []
        for p in polys:
            parts.append(len(p))
            rings.extend(np.asarray(r, np.float64) for r in p)
        return cls(rings=rings, parts=parts, **kw)

    def bbox(self) -> Tuple[float, float, float, float]:
        # Exterior rings of every member.
        ext, i = [], 0
        for n in self.parts or [len(self.rings)]:
            ext.append(self.rings[i])
            i += n
        return _bbox_of(ext)

    def polygons(self) -> List[Polygon]:
        out, i = [], 0
        for n in self.parts or [len(self.rings)]:
            out.append(
                Polygon(
                    obj_id=self.obj_id,
                    timestamp=self.timestamp,
                    rings=self.rings[i : i + n],
                )
            )
            i += n
        return out


@dataclass
class MultiLineString(LineString):
    """Multiple polylines (MultiLineString.java:14 extends LineString)."""

    parts: List[np.ndarray] = field(default_factory=list)

    def __post_init__(self):
        self.parts = [np.asarray(p, np.float64) for p in self.parts]
        if len(self.parts) and self.coords.size == 0:
            self.coords = np.concatenate(self.parts, axis=0)
        super().__post_init__()

    def bbox(self) -> Tuple[float, float, float, float]:
        return _bbox_of(self.parts or [self.coords])

    def packed(self, pad_to: Optional[int] = None):
        return pack_polyline(self.parts or [self.coords], pad_to=pad_to)


@dataclass
class GeometryCollection(SpatialObject):
    """Heterogeneous geometry list (GeometryCollection.java:13)."""

    geometries: List[SpatialObject] = field(default_factory=list)

    def bbox(self) -> Tuple[float, float, float, float]:
        boxes = [g.bbox() for g in self.geometries]
        return (
            min(b[0] for b in boxes),
            min(b[1] for b in boxes),
            max(b[2] for b in boxes),
            max(b[3] for b in boxes),
        )

    def grid_cells(self, grid: UniformGrid) -> List[int]:
        cells: set = set()
        for g in self.geometries:
            cells.update(g.grid_cells(grid))
        return sorted(cells)
