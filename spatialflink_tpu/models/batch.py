"""Structure-of-arrays batches — the host↔device currency.

Window payloads cross the host→TPU boundary as fixed-shape SoA batches
(padded to bucket sizes, utils/padding.py) instead of the reference's
per-record POJOs. ``PointBatch`` carries point streams; ``GeometryBatch``
carries polygon/linestring streams as per-object packed boundary arrays.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import List, Optional, Sequence

import numpy as np

from spatialflink_tpu.grid import UniformGrid
from spatialflink_tpu.models.objects import LineString, Point, Polygon
from spatialflink_tpu.utils.interning import Interner
from spatialflink_tpu.utils.padding import next_bucket, pad_to_bucket


@dataclass
class PointBatch:
    """Padded point batch: xy (N,2), ts (N,), oid (N,), valid (N,), cell (N,)."""

    xy: np.ndarray
    ts: np.ndarray
    oid: np.ndarray
    valid: np.ndarray
    cell: Optional[np.ndarray] = None

    @property
    def capacity(self) -> int:
        return self.xy.shape[0]

    @property
    def count(self) -> int:
        return int(self.valid.sum())

    @classmethod
    def from_arrays(
        cls,
        xy: np.ndarray,
        ts: Optional[np.ndarray] = None,
        oid: Optional[np.ndarray] = None,
        bucket: Optional[int] = None,
        dtype=np.float64,
    ) -> "PointBatch":
        xy = np.asarray(xy, dtype).reshape(-1, 2)
        n = len(xy)
        ts = np.zeros(n, np.int64) if ts is None else np.asarray(ts, np.int64)
        oid = np.zeros(n, np.int32) if oid is None else np.asarray(oid, np.int32)
        b = bucket if bucket is not None else next_bucket(n)
        return cls(
            xy=pad_to_bucket(xy, b),
            ts=pad_to_bucket(ts, b),
            oid=pad_to_bucket(oid, b, fill=0),
            valid=pad_to_bucket(np.ones(n, bool), b, fill=False),
        )

    @classmethod
    def from_points(
        cls,
        points: Sequence[Point],
        interner: Optional[Interner] = None,
        bucket: Optional[int] = None,
        dtype=np.float64,
    ) -> "PointBatch":
        n = len(points)
        xy = np.array([[p.x, p.y] for p in points], dtype).reshape(n, 2)
        ts = np.array([p.timestamp for p in points], np.int64)
        if interner is not None:
            oid = interner.intern_many(p.obj_id for p in points)
        else:
            oid = np.zeros(n, np.int32)
        return cls.from_arrays(xy, ts, oid, bucket=bucket, dtype=dtype)

    def with_cells(self, grid: UniformGrid) -> "PointBatch":
        cell = grid.assign_cells_np(self.xy)
        # Padding lanes → out-of-grid so no flag table ever selects them.
        cell = np.where(self.valid, cell, grid.num_cells).astype(np.int32)
        return replace(self, cell=cell)

    def compact(self, mask: np.ndarray) -> "PointBatch":
        """Host-side compaction by a boolean mask (egress only)."""
        keep = mask & self.valid
        return PointBatch(
            xy=self.xy[keep],
            ts=self.ts[keep],
            oid=self.oid[keep],
            valid=np.ones(int(keep.sum()), bool),
            cell=None if self.cell is None else self.cell[keep],
        )


def flag_prefix_planes(grid: UniformGrid, flags: np.ndarray):
    """2-D prefix sums of the candidate/guaranteed indicator planes
    (zero-bordered: P[i, j] = count in [0:i, 0:j)). Build once per query;
    feed to GeometryBatch.any_cell_flagged per window."""
    n = grid.n
    plane = flags[: grid.num_cells].reshape(n, n)
    cand = np.zeros((n + 1, n + 1), np.int64)
    guar = np.zeros((n + 1, n + 1), np.int64)
    cand[1:, 1:] = np.cumsum(np.cumsum(plane == 1, axis=0), axis=1)
    guar[1:, 1:] = np.cumsum(np.cumsum(plane == 2, axis=0), axis=1)
    return cand, guar


@dataclass
class GeometryBatch:
    """Padded geometry batch: per-object packed boundary arrays.

    ``verts``: (N, V, 2); ``edge_valid``: (N, V-1); plus ts/oid/valid and a
    representative bbox per object (for cell assignment & bbox pruning).
    """

    verts: np.ndarray
    edge_valid: np.ndarray
    bbox: np.ndarray  # (N, 4) minx,miny,maxx,maxy
    ts: np.ndarray
    oid: np.ndarray
    valid: np.ndarray

    @property
    def capacity(self) -> int:
        return self.verts.shape[0]

    @classmethod
    def from_ragged(
        cls,
        ts: np.ndarray,
        oid: np.ndarray,
        lengths: np.ndarray,
        verts_flat: np.ndarray,
        edge_valid_flat: Optional[np.ndarray] = None,
        bucket: Optional[int] = None,
        vert_bucket: Optional[int] = None,
        dtype=np.float64,
    ) -> "GeometryBatch":
        """Vectorized batch build from ragged SoA arrays — the geometry
        analog of the point SoA fast path: no per-object Python.

        ``lengths[i]`` vertices of object ``i`` occupy the corresponding
        run of ``verts_flat`` as one PACKED boundary chain (closed rings
        for polygons — ``pack_rings``' contract — open for polylines).
        ``edge_valid_flat``: optional flat per-object (length−1)-run edge
        mask — REQUIRED for multi-ring chains (ring seam edges invalid,
        pack_rings' layout; the native WKT parser emits it); omitted, all
        within-chain edges are valid (single-chain objects).
        ``oid`` must already be dense int32.
        """
        n = len(ts)
        lengths = np.asarray(lengths, np.int64)
        if n and int(lengths.min()) < 2:
            raise ValueError(
                "from_ragged requires every chain length >= 2 (a zero-"
                "length run would corrupt the reduceat bboxes silently)"
            )
        verts_flat = np.asarray(verts_flat, np.float64)
        offsets = np.concatenate([[0], np.cumsum(lengths)])
        total = int(offsets[-1])
        vmax = int(lengths.max()) if n else 2
        if vert_bucket is not None and vert_bucket < vmax:
            raise ValueError(
                f"vert_bucket {vert_bucket} < longest chain {vmax}: chains "
                "would be silently truncated"
            )
        v = vert_bucket if vert_bucket is not None else next_bucket(
            max(vmax, 2), minimum=8)

        lane = np.arange(v)
        gather = np.minimum(offsets[:-1, None] + lane[None, :],
                            max(total - 1, 0))
        mask = lane[None, :] < lengths[:, None]  # (n, v)
        verts = np.where(
            mask[:, :, None], verts_flat[gather], 0.0
        ).astype(dtype)
        if edge_valid_flat is None:
            ev = lane[None, : v - 1] < (lengths - 1)[:, None]
        else:
            edge_valid_flat = np.asarray(edge_valid_flat, bool)
            e_lens = lengths - 1
            if int(e_lens.sum()) != len(edge_valid_flat):
                raise ValueError(
                    f"edge mask has {len(edge_valid_flat)} entries; "
                    f"lengths-1 sums to {int(e_lens.sum())}"
                )
            e_off = np.concatenate([[0], np.cumsum(e_lens)])
            e_total = int(e_off[-1])
            e_gather = np.minimum(e_off[:-1, None] + lane[None, : v - 1],
                                  max(e_total - 1, 0))
            in_run = lane[None, : v - 1] < e_lens[:, None]
            src = (edge_valid_flat[e_gather] if e_total
                   else np.zeros((n, v - 1), bool))
            ev = in_run & src

        # Per-object bbox via ragged reduceat (empty-safe: n>0 runs only).
        if n:
            red_idx = offsets[:-1]
            mins = np.minimum.reduceat(verts_flat, red_idx, axis=0)
            maxs = np.maximum.reduceat(verts_flat, red_idx, axis=0)
            boxes = np.concatenate([mins, maxs], axis=1).astype(dtype)
        else:
            boxes = np.zeros((0, 4), dtype)

        b = bucket if bucket is not None else next_bucket(n, minimum=8)
        return cls(
            verts=pad_to_bucket(verts, b),
            edge_valid=pad_to_bucket(ev, b, fill=False),
            bbox=pad_to_bucket(boxes, b),
            ts=pad_to_bucket(np.asarray(ts, np.int64), b),
            oid=pad_to_bucket(np.asarray(oid, np.int32), b),
            valid=pad_to_bucket(np.ones(n, bool), b, fill=False),
        )

    @classmethod
    def from_objects(
        cls,
        objs: Sequence[Polygon | LineString],
        interner: Optional[Interner] = None,
        bucket: Optional[int] = None,
        vert_bucket: Optional[int] = None,
        dtype=np.float64,
    ) -> "GeometryBatch":
        n = len(objs)
        vmax = max((o.num_vertices_packed() for o in objs), default=2)
        v = vert_bucket if vert_bucket is not None else next_bucket(vmax, minimum=8)
        verts = np.zeros((n, v, 2), dtype)
        ev = np.zeros((n, v - 1), bool)
        boxes = np.zeros((n, 4), dtype)
        for i, o in enumerate(objs):
            pv, pe = o.packed(pad_to=v)
            verts[i] = pv
            ev[i] = pe
            boxes[i] = o.bbox()
        ts = np.array([o.timestamp for o in objs], np.int64)
        if interner is not None:
            oid = interner.intern_many(o.obj_id for o in objs)
        else:
            oid = np.zeros(n, np.int32)
        b = bucket if bucket is not None else next_bucket(n, minimum=8)
        return cls(
            verts=pad_to_bucket(verts, b),
            edge_valid=pad_to_bucket(ev, b, fill=False),
            bbox=pad_to_bucket(boxes, b),
            ts=pad_to_bucket(ts, b),
            oid=pad_to_bucket(oid, b),
            valid=pad_to_bucket(np.ones(n, bool), b, fill=False),
        )

    def centroid_cells(self, grid: UniformGrid) -> np.ndarray:
        """Flat cell of each object's bbox center (its keyBy cell).

        The reference keys replicated polygons by each overlapped cell; for
        batched pruning we flag *all* cells of each object via
        ``grid.bbox_cells`` host-side instead (operator layer).
        """
        cx = (self.bbox[:, 0] + self.bbox[:, 2]) / 2
        cy = (self.bbox[:, 1] + self.bbox[:, 3]) / 2
        cell = grid.assign_cells_np(np.stack([cx, cy], axis=1))
        return np.where(self.valid, cell, grid.num_cells).astype(np.int32)

    def any_cell_flagged(
        self, grid: UniformGrid, flags: np.ndarray, prefix=None
    ) -> np.ndarray:
        """Per-object max flag over all cells its bbox overlaps (host-side,
        vectorized).

        Mirrors the reference's per-object gridIDsSet ∩ neighbor-set test
        for polygon/linestring streams (e.g. PolygonPointRangeQuery filter).
        The rectangle max over the flag grid is answered with 2-D prefix
        sums of the candidate/guaranteed indicator planes: a flag level is
        present in a bbox iff its indicator count over the rectangle is
        positive — O(cells + objects) instead of per-object cell loops.
        Pass ``prefix=flag_prefix_planes(grid, flags)`` to amortize the
        O(cells) plane build across windows of the same query.
        """
        n = grid.n
        cand, guar = prefix if prefix is not None else flag_prefix_planes(grid, flags)

        ci = grid.cell_xy_indices_np(self.bbox[:, 0:2])  # (N, 2) min corner
        cj = grid.cell_xy_indices_np(self.bbox[:, 2:4])  # (N, 2) max corner
        x1 = np.clip(ci[:, 0], 0, n - 1)
        y1 = np.clip(ci[:, 1], 0, n - 1)
        x2 = np.clip(cj[:, 0], 0, n - 1)
        y2 = np.clip(cj[:, 1], 0, n - 1)
        # Bboxes entirely outside the grid contribute nothing.
        inside = (cj[:, 0] >= 0) & (cj[:, 1] >= 0) & (ci[:, 0] < n) & (ci[:, 1] < n)

        def rect_count(p):
            return (
                p[x2 + 1, y2 + 1] - p[x1, y2 + 1] - p[x2 + 1, y1] + p[x1, y1]
            )

        has_guar = rect_count(guar) > 0
        has_cand = rect_count(cand) > 0
        out = np.where(has_guar, 2, np.where(has_cand, 1, 0)).astype(np.uint8)
        return np.where(self.valid & inside, out, 0).astype(np.uint8)
