from spatialflink_tpu.models.objects import (  # noqa: F401
    SpatialObject,
    Point,
    Polygon,
    LineString,
    MultiPoint,
    MultiPolygon,
    MultiLineString,
    GeometryCollection,
)
from spatialflink_tpu.models.batch import PointBatch, GeometryBatch  # noqa: F401
