"""Overload control — graceful degradation for sustained bursts.

PR 8 made the pipeline crash-resilient; this module answers the OTHER
production failure mode: load the pipeline cannot drain. GeoFlink
inherits Flink's credit-based backpressure for free (CIKM 2020 §V); our
host-driven pull loop has no channel credits to exhaust, so overload
shows up as unbounded watermark lag instead. This layer turns that into
explicit, bounded behavior, in four parts (all opt-in — with no
controller installed every hook is one global read + None check, and
default-config runs are bit-identical to the pre-overload build):

- **Bounded admission** (:meth:`OverloadController.admit_item`): a
  byte/event budget on the ingest burst between consecutive window
  firings. Replayable sources (the driver's ``skip_on_resume`` world)
  get explicit BACKPRESSURE signaling — the data is safe at the source,
  so the pull loop simply runs behind while the transition is recorded
  (``overload_backpressure:engaged``/``released``). Non-replayable
  sources (sockets, live brokers) SPILL to a counted shed path instead:
  every shed lands in ``snapshot()["overload"]``.
- **Watermark-aware load shedding**: when the event-time lag of fired
  windows crosses ``lag_shed_ceiling_ms``, the controller enters shed
  mode (``overload_shedding:lag``) and sheds LATE-first — out-of-order
  stragglers contribute the least fresh value — escalating to
  OLDEST-first (events destined only for the already-behind windows,
  ``overload_shedding:oldest``) if lag keeps growing. Recovery below
  ``lag_recover_ms`` emits ``overload_recovered:lag``. All triggers are
  event-time/count based, so a fixed input stream sheds DETERMINISTICALLY
  — which is what lets the chaos matrix kill a shedding run mid-burst
  and still demand byte-identical resumed egress.
- **SLO-driven degradation ladder**: declarative rungs stepped DOWN by
  live SLO violations (`spatialflink_tpu/slo.py` calls
  :func:`on_slo_evaluation`) or the controller's own shed/backpressure
  transitions, and stepped back UP after ``recover_after`` consecutive
  healthy fired windows. Every rung is RESULT-PRESERVING — the ladder
  trades latency/compile-churn, never answers:

  - ``{"action": "clamp_compaction", "cap": N}`` — pin the live-slot
    capacity ladder (ops/compaction.py:pick_capacity) at or above a
    floor (``cap`` 0/absent = the top rung) so occupancy churn stops
    costing ~1-2 s XLA recompiles mid-overload;
  - ``{"action": "batch_slides", "n": N}`` — the wire pane path
    (KnnQuery.run_wire_panes) batches N windows' result fetches into
    one device→host sync (the tunnel round trip per window is the
    overload cost there);
  - ``{"action": "pane_backend", "to": "native"}`` — bias the
    ``backend="auto"`` pane engines (traj_stats_sliding,
    TJoinQuery.run_soa_panes) toward the native/host route, freeing
    the device path (a no-op where the native library is missing —
    never a crash).

- **Device-path circuit breaker** (:class:`CircuitBreaker`): the
  generalization of the driver's PR 8 per-window failover. After
  ``breaker_failures`` consecutive window failures — or a DEGRADED
  LinkProbe bandwidth ratio — the circuit OPENS and whole windows route
  to the numpy twin without paying per-window retry/timeout; every
  ``breaker_probe_every``-th window HALF-OPENS the circuit for a single
  bounded re-dial probe, and a probe success closes it. Unlike PR 8's
  permanent failover, a recovered tunnel gets the device path back
  mid-run.

Wiring follows the telemetry/slo singleton idiom: :func:`install` puts
one controller in the module slot, the window-fire sites
(streams/windows.py, streams/soa.py) feed :func:`on_window_fired`, the
dataflow driver (driver.py) threads admission/breaker/checkpoint state,
and ``telemetry.snapshot()["overload"]`` carries the counters (so they
ride ledger-stream checkpoints and survive a crash — `sfprof recover`
reconstructs every shed/degradation/circuit transition). The
``overload.admit`` fault-injection point lives in the admit path;
``tests/test_chaos_matrix.py`` covers it like every other point.

``python -m spatialflink_tpu.overload --smoke`` is the per-commit proof
(tools/ci's overload-smoke stage): a toy burst past a tiny admission
budget must shed deterministically, step the ladder down and back up,
carry the budgets through the SLO verdict, and seal every transition in
the ledger stream.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, fields
from typing import Any, Dict, Optional, Tuple

from spatialflink_tpu.faults import faults
from spatialflink_tpu.telemetry import telemetry

#: Snapshot-block schema version (``snapshot()["overload"]["version"]``).
OVERLOAD_VERSION = 1

#: Ladder rung actions this build knows how to apply. Parsing an unknown
#: action raises — a typo'd rung that silently never engages is the
#: worst failure mode a degradation ladder can have (the fault-plan /
#: SLO-spec strict-parse rule).
RUNG_ACTIONS = ("clamp_compaction", "batch_slides", "pane_backend")

_RUNG_KEYS = {
    "clamp_compaction": {"action", "cap"},
    "batch_slides": {"action", "n"},
    "pane_backend": {"action", "to"},
}


#: Per-tenant-class budget keys (``OverloadPolicy.tenant_budgets``).
#: ``max_queries`` bounds STANDING queries a class may keep registered
#: (qserve registration admission); ``max_results_per_window`` bounds
#: the result rows a class may emit per fired window. Both controls
#: scope to the class — a firehose tenant degrades ITSELF, never the
#: fleet (tenant sheds deliberately do NOT feed the global degradation
#: ladder).
TENANT_BUDGET_KEYS = ("max_queries", "max_results_per_window")


def validate_budget_map(tb, keys, what: str = "tenant_budgets"):
    """Strict parse of a ``{class: {budget-key: int}}`` map — ONE home
    for the per-class budget validation (this module's
    ``OverloadPolicy.tenant_budgets`` and ``slo.SloSpec.tenant_budgets``
    both accept this shape with different key tuples; two hand-rolled
    copies would drift). Unknown keys and non-int/negative/bool values
    raise at parse time — a malformed budget crashing mid-run (or
    silently ignored) is the failure mode the strict parse prevents."""
    if tb is None:
        return None
    if not isinstance(tb, dict):
        raise ValueError(f"{what} must be an object, got {tb!r}")
    out = {}
    for cls, b in tb.items():
        if not isinstance(b, dict):
            raise ValueError(f"{what}[{cls!r}] is not an object: {b!r}")
        unknown = sorted(set(b) - set(keys))
        if unknown:
            raise ValueError(
                f"{what}[{cls!r}] has unknown keys {unknown} "
                f"(keys: {tuple(keys)})"
            )
        for key, v in b.items():
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                raise ValueError(
                    f"{what}[{cls!r}].{key} must be a "
                    f"non-negative int, got {v!r}"
                )
        out[str(cls)] = dict(b)
    return out


def _parse_tenant_budgets(tb):
    return validate_budget_map(tb, TENANT_BUDGET_KEYS)


def _parse_ladder(ladder) -> Tuple[Dict[str, Any], ...]:
    if ladder is None:
        return ()
    out = []
    for i, rung in enumerate(ladder):
        if not isinstance(rung, dict):
            raise ValueError(f"ladder rung #{i} is not an object: {rung!r}")
        action = rung.get("action")
        if action not in RUNG_ACTIONS:
            raise ValueError(
                f"ladder rung #{i} has unknown action {action!r} "
                f"(actions: {RUNG_ACTIONS})"
            )
        unknown = sorted(set(rung) - _RUNG_KEYS[action])
        if unknown:
            raise ValueError(
                f"ladder rung #{i} ({action}) has unknown keys {unknown}"
            )
        # Value validation belongs HERE, not at the first step-down: a
        # typo'd value would otherwise be a silent no-op (pane_backend
        # targets nothing) or a mid-overload crash inside the window-fire
        # hook (non-int cap/n) — the exact failure modes the strict
        # parse exists to reject at SFT_OVERLOAD_POLICY load.
        if action == "pane_backend":
            to = rung.get("to", "native")
            if to not in ("native", "numpy"):
                raise ValueError(
                    f"ladder rung #{i} (pane_backend) has unknown "
                    f"target {to!r} (targets: native, numpy)"
                )
        elif action == "clamp_compaction":
            cap = rung.get("cap", 0)
            if not isinstance(cap, int) or isinstance(cap, bool) or cap < 0:
                raise ValueError(
                    f"ladder rung #{i} (clamp_compaction) cap must be a "
                    f"non-negative int, got {cap!r}"
                )
        elif action == "batch_slides":
            n = rung.get("n", 4)
            if not isinstance(n, int) or isinstance(n, bool) or n < 1:
                raise ValueError(
                    f"ladder rung #{i} (batch_slides) n must be a "
                    f"positive int, got {n!r}"
                )
        out.append(dict(rung))
    return tuple(out)


@dataclass(frozen=True)
class OverloadPolicy:
    """Declarative overload policy; ``None`` disables a control.

    - ``max_buffered_events`` / ``max_buffered_bytes``: admission budget
      on the ingest burst — events/bytes arriving within one
      ``admission_window_ms`` event-time horizon OR between consecutive
      window firings, whichever drains first (bytes are measured where
      items carry arrays — SoA chunks; object events count events
      only). The event-time horizon is what makes shedding
      self-recovering: shed events never advance the watermark, so a
      fires-only reset would starve forever once the budget blew;
    - ``lag_shed_ceiling_ms``: fired-window event-time lag that enters
      shed mode; ``lag_recover_ms`` exits it (default ``ceiling // 2``);
    - ``shed_oldest_after_windows``: fired windows still over the
      ceiling before late-first shedding escalates to oldest-first;
    - ``ladder``: degradation rungs, mildest first (see module doc);
    - ``degrade_cooldown`` / ``recover_after``: unhealthy observations
      between consecutive step-downs / consecutive healthy fired windows
      before a step-up;
    - ``breaker_failures``: consecutive window failures that open the
      device-path circuit (0 disables the breaker — the driver keeps
      its PR 8 permanent-failover semantics);
    - ``breaker_probe_every``: fallback windows between half-open
      re-dial probes while the circuit is open;
    - ``breaker_link_ratio``: LinkProbe bandwidth ratio (last/p50)
      below which the circuit opens preemptively;
    - ``tenant_budgets``: per-tenant-class QoS scoping (qserve) —
      ``{class: {"max_queries": N, "max_results_per_window": M}}``.
      Excess registrations are rejected and excess result rows shed,
      counted PER CLASS (``snapshot()["tenants"]``); tenant sheds never
      step the global ladder — one firehose tenant degrades itself,
      never the fleet.
    """

    max_buffered_events: Optional[int] = None
    max_buffered_bytes: Optional[int] = None
    admission_window_ms: int = 1000
    lag_shed_ceiling_ms: Optional[int] = None
    lag_recover_ms: Optional[int] = None
    shed_oldest_after_windows: int = 2
    ladder: Tuple[Dict[str, Any], ...] = ()
    degrade_cooldown: int = 2
    recover_after: int = 5
    breaker_failures: int = 0
    breaker_probe_every: int = 8
    breaker_link_ratio: Optional[float] = None
    tenant_budgets: Optional[Dict[str, Dict[str, int]]] = None

    def __post_init__(self):
        object.__setattr__(self, "ladder", _parse_ladder(self.ladder))
        object.__setattr__(
            self, "tenant_budgets",
            _parse_tenant_budgets(self.tenant_budgets),
        )

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "OverloadPolicy":
        """Strict parse — unknown keys raise (the SLO-spec rule: a
        typo'd control silently disabled is worse than an error)."""
        d = dict(d)
        ver = d.pop("overload_version", OVERLOAD_VERSION)
        if ver != OVERLOAD_VERSION:
            raise ValueError(
                f"overload_version {ver} != supported {OVERLOAD_VERSION}"
            )
        known = {f.name for f in fields(cls)}
        unknown = sorted(set(d) - known)
        if unknown:
            raise ValueError(f"unknown overload policy keys: {unknown}")
        return cls(**d)

    @classmethod
    def from_env(cls, spec: str) -> "OverloadPolicy":
        """``SFT_OVERLOAD_POLICY``: inline JSON or a path to a JSON file
        (the ``SFT_FAULT_PLAN`` convention)."""
        text = spec.strip()
        if not text.startswith("{"):
            with open(text) as f:
                text = f.read()
        return cls.from_dict(json.loads(text))

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"overload_version": OVERLOAD_VERSION}
        for f in fields(self):
            v = getattr(self, f.name)
            out[f.name] = list(v) if f.name == "ladder" else v
        return out


class CircuitBreaker:
    """Device-path circuit: closed → (consecutive failures | degraded
    link) → open → (half-open probe success) → closed.

    The driver consults :meth:`route` once per window — "device" runs
    the normal path, "fallback" skips it entirely (no retry, no
    timeout), "probe" grants ONE bounded device attempt. State is
    process-local and deliberately NOT checkpointed: device health is a
    property of the resumed process, not of the stream position.
    """

    def __init__(self, policy: OverloadPolicy, tel=telemetry):
        self.policy = policy
        self.tel = tel
        self.state = "closed"
        self.consecutive_failures = 0
        self.opens = 0
        self.probes = 0
        self._fallback_windows = 0  # since the circuit last opened
        # LinkProbe sample count at the last probe-success close: the
        # ratio check only re-arms on a FRESHER sample (probes only run
        # at bench phase boundaries, so within a phase the gauges are
        # stale — re-reading them would instantly re-open a circuit a
        # successful probe just closed, flapping forever).
        self._link_samples_seen = 0

    def route(self) -> str:
        if self.state == "closed":
            ratio = self.policy.breaker_link_ratio
            if ratio is not None:
                link = self.tel.link_gauges()
                if (link and link.get("roundtrip_mbps_p50")
                        and int(link.get("samples", 0))
                        > self._link_samples_seen):
                    r = (link["roundtrip_mbps_last"]
                         / link["roundtrip_mbps_p50"])
                    if r < ratio:
                        self._open(f"link degraded (ratio {float(r):.3f} "
                                   f"< {float(ratio):g})")
                        return "fallback"
            return "device"
        # open: every breaker_probe_every-th fallback window half-opens
        # for one re-dial probe (count-based — bounded and replayable).
        self._fallback_windows += 1
        if self._fallback_windows % max(1, self.policy.breaker_probe_every) \
                == 0:
            self.probes += 1
            self.state = "half_open"
            self.tel.emit_instant("circuit_half_open",
                                  probe=int(self.probes))
            return "probe"
        return "fallback"

    def record_success(self):
        if self.state == "half_open":
            self.state = "closed"
            link = self.tel.link_gauges()
            self._link_samples_seen = int(link["samples"]) if link else 0
            self.tel.emit_instant("circuit_closed", probe=int(self.probes))
            self.tel.maybe_flush_stream(force=True)
        self.consecutive_failures = 0

    def record_failure(self, window_start: int = 0, error: str = ""):
        if self.state == "half_open":
            # probe failed — straight back to open, schedule the next one
            self.state = "open"
            self.tel.emit_instant(
                "circuit_open", reason="probe failed",
                window_start=int(window_start), error=str(error)[:200],
            )
            self.tel.maybe_flush_stream(force=True)
            return
        self.consecutive_failures += 1
        # breaker_failures == 0 disables count-based opening (the
        # breaker may still exist for link-ratio-only policies).
        if self.state == "closed" and self.policy.breaker_failures > 0 \
                and self.consecutive_failures >= self.policy.breaker_failures:
            self._open(f"{int(self.consecutive_failures)} consecutive "
                       f"window failures", window_start, error)

    def _open(self, reason: str, window_start: int = 0, error: str = ""):
        self.state = "open"
        self.opens += 1
        self._fallback_windows = 0
        self.tel.emit_instant(
            "circuit_open", reason=reason, window_start=int(window_start),
            error=str(error)[:200],
        )
        self.tel.maybe_flush_stream(force=True)

    def snapshot(self) -> Dict[str, Any]:
        return {
            "state": self.state,
            "opens": int(self.opens),
            "probes": int(self.probes),
            "consecutive_failures": int(self.consecutive_failures),
        }


def _measure_item(item) -> Tuple[Optional[int], int, int]:
    """(max event ts | None, n_events, nbytes) of one ingest item —
    object events (``.timestamp``) or SoA chunks (dict of arrays).
    CONTROL-PLANE items (``control_plane`` attr True — e.g. qserve's
    registration commands) measure as zero events: they are commands,
    not load, and shedding one would silently diverge the registry from
    the command stream for the rest of the run (duck-typed, because
    this module must not import qserve)."""
    if getattr(item, "control_plane", False):
        return None, 0, 0
    ts = getattr(item, "timestamp", None)
    if ts is not None:
        return int(ts), 1, 0
    if isinstance(item, dict) and "ts" in item:
        import numpy as np

        t = np.asarray(item["ts"])
        if len(t) == 0:
            return None, 0, 0
        nbytes = sum(
            np.asarray(v).nbytes for v in item.values()
            if hasattr(v, "__len__")
        )
        return int(t.max()), int(len(t)), int(nbytes)
    return None, 1, 0


class OverloadController:
    """One policy's live state: admission backlog, shed counters, the
    degradation rung, and (optionally) the circuit breaker.

    Thread-safety: counter updates take the lock; the module-level hooks
    are free when no controller is installed (one global read).
    """

    def __init__(self, policy: OverloadPolicy, tel=telemetry):
        self.policy = policy
        self.tel = tel
        # Telemetry's stream-flush checkpoint calls back into this
        # controller's snapshot (overload_provider) under telemetry's
        # lock, so transition events are QUEUED under this lock and
        # emitted after it is released (the slo.py transition idiom) —
        # neither lock is ever requested while the other is held.
        self._lock = threading.RLock()
        self._pending_emits: list = []
        # Shed charges for telemetry.record_shed (the per-node/global
        # conservation twin of self.shed) — queued under the lock,
        # drained after release exactly like _pending_emits: record_shed
        # takes the telemetry lock, which must never nest inside ours.
        self._pending_sheds: list = []
        self.breaker = (CircuitBreaker(policy, tel)
                        if policy.breaker_failures > 0
                        or policy.breaker_link_ratio is not None else None)
        # admission backlog = the current burst (bounded in event time
        # by admission_window_ms, drained early by window fires)
        self._backlog_events = 0
        self._backlog_bytes = 0
        self._backlog_start_ts: Optional[int] = None
        self._backpressured = False
        self.backpressure_engaged = 0
        # shed counters by reason → {"events", "bytes"}
        self.shed: Dict[str, Dict[str, int]] = {}
        self._admission_shedding = False
        self._sheds_since_fire = 0
        # watermark-aware shed mode
        self._max_ts: Optional[int] = None
        self._last_window_end: Optional[int] = None
        self._slide_ms = 0  # learned from consecutive fired ends
        self._shedding = False
        self._shed_oldest = False
        self._shed_windows = 0  # fired windows while in shed mode
        # per-tenant-class QoS (tenant_budgets): class → counters.
        # Tenant sheds are deliberately ISOLATED from the global health
        # machinery — a class over ITS budget degrades itself only.
        self.tenant: Dict[str, Dict[str, int]] = {}
        self._tenant_shedding: set = set()
        self._tenant_shed_this_window: set = set()
        # class → (window_start, last results charge): the retry-
        # idempotence marker for tenant_result_allowance.
        self._tenant_window_charge: Dict[str, Tuple[int, int]] = {}
        # degradation ladder
        self.rung = 0
        self.rung_transitions = 0
        self._unhealthy_streak = 0
        self._healthy_streak = 0
        self._apply_effects()
        # degraded windows: processed by a non-device path (breaker-open
        # routing or post-failover) — the SLO ``degraded_window_budget``
        self.degraded_windows = 0

    # -- admission + shedding --------------------------------------------------

    def admit_item(self, item, pausable: bool = True) -> bool:
        """One ingest item at the source→assembler boundary (the driver
        calls this). Returns False when the item is SHED — the caller
        skips it (still counting it consumed, for resume determinism).
        """
        if faults.armed:  # chaos injection point (faults.py)
            faults.hit("overload.admit")
        ts, n_events, nbytes = _measure_item(item)
        if n_events == 0:
            return True
        try:
            return self._admit_locked(ts, n_events, nbytes, pausable)
        finally:
            self._drain_emits()

    def _admit_locked(self, ts, n_events, nbytes, pausable) -> bool:
        with self._lock:
            if ts is not None and (self._max_ts is None
                                   or ts > self._max_ts):
                self._max_ts = ts
            # Watermark-aware shed mode. Escalated OLDEST-first is the
            # wider horizon and is classified first: events destined
            # for the already-behind oldest open windows (up to one
            # learned slide past the last fired end) shed so the
            # watermark can race ahead and fire them light. LATE-first
            # is the base tier: out-of-order stragglers behind the
            # stream head — the least fresh value per shed event.
            if self._shedding and ts is not None:
                if self._shed_oldest and self._last_window_end is not None \
                        and ts <= self._last_window_end + self._slide_ms:
                    return not self._shed_locked("oldest", n_events, nbytes)
                if self._max_ts is not None and ts < self._max_ts:
                    return not self._shed_locked("late", n_events, nbytes)
            # Bounded admission on the current burst. The burst horizon
            # is EVENT TIME: once the stream head moves past the burst's
            # start by admission_window_ms, a new burst begins — sheds
            # must not starve the budget forever (shed events never
            # advance the watermark, so fires alone cannot reset it).
            if ts is not None and (
                    self._backlog_start_ts is None
                    or ts > self._backlog_start_ts
                    + self.policy.admission_window_ms):
                self._backlog_start_ts = ts
                self._backlog_events = 0
                self._backlog_bytes = 0
            self._backlog_events += n_events
            self._backlog_bytes += nbytes
            pol = self.policy
            over = (
                (pol.max_buffered_events is not None
                 and self._backlog_events > pol.max_buffered_events)
                or (pol.max_buffered_bytes is not None
                    and self._backlog_bytes > pol.max_buffered_bytes)
            )
            if not over:
                return True
            if pausable:
                # Replayable source: data is safe at the source — signal
                # backpressure (transition, not spam) and admit.
                if not self._backpressured:
                    self._backpressured = True
                    self.backpressure_engaged += 1
                    self._emit_locked("overload_backpressure:engaged",
                                      events=int(self._backlog_events),
                                      bytes=int(self._backlog_bytes))
                    self._observe_health_locked(False)
                return True
            # Non-replayable source: spill to the counted shed path.
            self._backlog_events -= n_events
            self._backlog_bytes -= nbytes
            return not self._shed_locked("admission", n_events, nbytes)

    def _shed_locked(self, reason: str, n_events: int, nbytes: int) -> bool:
        rec = self.shed.setdefault(reason, {"events": 0, "bytes": 0})
        rec["events"] += int(n_events)
        rec["bytes"] += int(nbytes)
        self._pending_sheds.append((int(n_events), int(nbytes)))
        self._sheds_since_fire += 1
        if reason == "admission" and not self._admission_shedding:
            self._admission_shedding = True
            self._emit_locked("overload_shedding:admission",
                              events=int(n_events))
            self._observe_health_locked(False)
        return True

    @property
    def shed_total(self) -> int:
        with self._lock:
            return sum(r["events"] for r in self.shed.values())

    # -- per-tenant-class QoS (qserve) -----------------------------------------

    def _tenant_rec_locked(self, cls: str) -> Dict[str, int]:
        return self.tenant.setdefault(str(cls), {
            "queries_live": 0, "queries_shed": 0,
            "results_shed": 0, "degraded_windows": 0,
        })

    def _tenant_budget(self, cls: str) -> Optional[Dict[str, int]]:
        return (self.policy.tenant_budgets or {}).get(str(cls))

    def admit_tenant_query(self, cls: str) -> bool:
        """One standing-query registration for tenant class ``cls``
        (qserve's registry calls this). False = the class is at its
        ``max_queries`` budget — the registration is rejected and
        counted against THE CLASS (``queries_shed``), with a per-class
        shedding transition event. Never feeds the global ladder."""
        try:
            with self._lock:
                rec = self._tenant_rec_locked(cls)
                b = self._tenant_budget(cls)
                limit = None if b is None else b.get("max_queries")
                if limit is not None and rec["queries_live"] >= limit:
                    rec["queries_shed"] += 1
                    self._pending_sheds.append((1, 0))
                    self._tenant_shed_this_window.add(str(cls))
                    if cls not in self._tenant_shedding:
                        self._tenant_shedding.add(str(cls))
                        self._emit_locked(f"overload_tenant_shed:{cls}",
                                          control="queries",
                                          limit=int(limit))
                    return False
                rec["queries_live"] += 1
                return True
        finally:
            self._drain_emits()

    def release_tenant_query(self, cls: str):
        """One standing-query unregistration for class ``cls``."""
        with self._lock:
            rec = self._tenant_rec_locked(cls)
            rec["queries_live"] = max(0, rec["queries_live"] - 1)

    def tenant_result_allowance(self, cls: str, n: int,
                                window_start: Optional[int] = None) -> int:
        """Result rows class ``cls`` may emit this window: ``n`` when
        under its ``max_results_per_window`` budget, else the budget —
        the excess is counted as ``results_shed`` and the window as a
        per-class degraded window. Other classes are untouched.

        ``window_start`` makes the charge RETRY-IDEMPOTENT: re-charging
        the same (class, window) — a driver retry re-running the
        window's process — replaces the previous charge instead of
        accumulating it (the qserve record_range_overflow contract)."""
        try:
            with self._lock:
                rec = self._tenant_rec_locked(cls)
                b = self._tenant_budget(cls)
                limit = (None if b is None
                         else b.get("max_results_per_window"))
                if limit is None or n <= limit:
                    return int(n)
                shed = int(n) - int(limit)
                shed_delta = shed  # telemetry twin charge (see below)
                if window_start is not None:
                    prev = self._tenant_window_charge.get(str(cls))
                    if prev is not None and prev[0] == int(window_start):
                        rec["results_shed"] -= prev[1]
                        rec["degraded_windows"] -= 1
                        # Retry re-charge: the twin must replace too, so
                        # queue the NET delta (may be negative).
                        shed_delta = shed - prev[1]
                    self._tenant_window_charge[str(cls)] = (
                        int(window_start), shed,
                    )
                rec["results_shed"] += shed
                if shed_delta:
                    self._pending_sheds.append((shed_delta, 0))
                rec["degraded_windows"] += 1
                self._tenant_shed_this_window.add(str(cls))
                if cls not in self._tenant_shedding:
                    self._tenant_shedding.add(str(cls))
                    self._emit_locked(f"overload_tenant_shed:{cls}",
                                      control="results",
                                      limit=int(limit))
                return int(limit)
        finally:
            self._drain_emits()

    def tenant_shed_total(self, cls: str) -> int:
        """Queries rejected + result rows shed for class ``cls`` (the
        SLO ``tenant_budgets`` shed metric; 0 for an unseen class)."""
        with self._lock:
            rec = self.tenant.get(str(cls))
            return 0 if rec is None \
                else rec["queries_shed"] + rec["results_shed"]

    def tenant_degraded_windows(self, cls: str) -> int:
        with self._lock:
            rec = self.tenant.get(str(cls))
            return 0 if rec is None else rec["degraded_windows"]

    # -- window-fire hook ------------------------------------------------------

    def on_window_fired(self, n_events: int = 0,
                        lag_ms: Optional[float] = None,
                        end: Optional[int] = None):
        """Every fired window: drain the admission burst, run the lag
        shed-mode state machine, and feed the ladder a health sample.
        All event-time/count based — deterministic over a fixed stream.
        """
        try:
            self._on_window_fired_locked(n_events, lag_ms, end)
        finally:
            self._drain_emits()

    def _on_window_fired_locked(self, n_events, lag_ms, end):
        pol = self.policy
        with self._lock:
            self._backlog_events = 0
            self._backlog_bytes = 0
            self._backlog_start_ts = None
            if end is not None:
                if self._last_window_end is not None \
                        and end > self._last_window_end:
                    self._slide_ms = int(end) - self._last_window_end
                self._last_window_end = int(end)
            # Capture the cycle's distress BEFORE the per-fire resets:
            # the health sample below must see what happened SINCE the
            # last fire, not the just-cleared state (a fired window amid
            # sustained admission sheds counted as healthy otherwise —
            # the ladder un-degraded mid-overload; r9 code review).
            was_backpressured = self._backpressured
            shed_this_cycle = self._sheds_since_fire > 0
            if self._backpressured:
                self._backpressured = False
                self._emit_locked("overload_backpressure:released")
            if self._admission_shedding and self._sheds_since_fire == 0:
                self._admission_shedding = False
                self._emit_locked("overload_recovered:admission")
            self._sheds_since_fire = 0
            # Per-tenant shed transitions recover per fired window: a
            # class that shed nothing since the last fire leaves shed
            # mode (transition event, not per-shed spam). Class-local —
            # the global health sample below never sees tenant sheds.
            for cls in sorted(self._tenant_shedding
                              - self._tenant_shed_this_window):
                self._tenant_shedding.discard(cls)
                self._emit_locked(f"overload_tenant_recovered:{cls}")
            self._tenant_shed_this_window = set()
            lag_ok = True
            if pol.lag_shed_ceiling_ms is not None and lag_ms is not None:
                ceiling = pol.lag_shed_ceiling_ms
                recover = (pol.lag_recover_ms if pol.lag_recover_ms
                           is not None else ceiling // 2)
                if not self._shedding and lag_ms > ceiling:
                    self._shedding = True
                    self._shed_windows = 0
                    self._emit_locked("overload_shedding:lag",
                                      lag_ms=float(lag_ms),
                                      ceiling_ms=float(ceiling))
                elif self._shedding:
                    self._shed_windows += 1
                    if lag_ms <= recover:
                        self._shedding = False
                        self._shed_oldest = False
                        self._emit_locked("overload_recovered:lag",
                                          lag_ms=float(lag_ms))
                    elif (not self._shed_oldest and lag_ms > ceiling
                          and self._shed_windows
                          >= pol.shed_oldest_after_windows):
                        # Late-first didn't catch the lag up — escalate
                        # to oldest-first.
                        self._shed_oldest = True
                        self._emit_locked("overload_shedding:oldest",
                                          lag_ms=float(lag_ms))
                lag_ok = lag_ms <= recover
            if self._shedding or shed_this_cycle or was_backpressured:
                self._observe_health_locked(False)
            elif lag_ok:
                self._observe_health_locked(True)
            else:
                # Mid-band lag (recover < lag ≤ ceiling, no shed mode):
                # NOT a step-down trigger — the ladder steps down on
                # shed/backpressure transitions and live SLO violations
                # only (the PARITY.md trigger table) — but not recovered
                # either: break the healthy streak so a step-up still
                # waits for sustained lag ≤ recover.
                self._healthy_streak = 0

    # -- degradation ladder ----------------------------------------------------

    def on_slo_evaluation(self, ok: bool):
        """Live SLO verdict hook (slo.SloEngine.evaluate): a violating
        evaluation is an unhealthy observation — the ladder steps down.
        Healthy evaluations don't step it back up (sustained recovery is
        measured in fired windows, the signal overload actually moves).
        """
        if not ok:
            with self._lock:
                self._observe_health_locked(False)
            self._drain_emits()

    def _observe_health_locked(self, healthy: bool):
        pol = self.policy
        if healthy:
            self._unhealthy_streak = 0
            self._healthy_streak += 1
            if self.rung > 0 and self._healthy_streak >= pol.recover_after:
                self._healthy_streak = 0
                self.rung -= 1
                self.rung_transitions += 1
                restored = pol.ladder[self.rung]["action"]
                self._apply_effects()
                self._emit_locked(f"overload_rung_up:{restored}",
                                  rung=int(self.rung))
            return
        self._healthy_streak = 0
        self._unhealthy_streak += 1
        if self.rung < len(pol.ladder) \
                and self._unhealthy_streak >= pol.degrade_cooldown:
            self._unhealthy_streak = 0
            action = pol.ladder[self.rung]["action"]
            self.rung += 1
            self.rung_transitions += 1
            self._apply_effects()
            self._emit_locked(f"overload_rung_down:{action}",
                              rung=int(self.rung))

    def _apply_effects(self):
        """Recompute the active rung effects (rungs 1..current are
        cumulative). Each effect is a RESULT-PRESERVING knob read by the
        hot paths through the module-level getters."""
        clamp = None
        backend = None
        slides = 1
        for rung in self.policy.ladder[: self.rung]:
            action = rung["action"]
            if action == "clamp_compaction":
                clamp = int(rung.get("cap", 0))
            elif action == "batch_slides":
                slides = max(1, int(rung.get("n", 4)))
            elif action == "pane_backend":
                backend = str(rung.get("to", "native"))
        self.effect_compaction_clamp = clamp
        self.effect_pane_backend = backend
        self.effect_batch_slides = slides

    # -- driver integration ----------------------------------------------------

    def count_degraded_window(self):
        with self._lock:
            self.degraded_windows += 1

    # -- telemetry / persistence ----------------------------------------------

    def _emit_locked(self, name: str, **args):
        """Queue one transition event (caller holds the lock); a public
        entry point drains the queue after releasing it. Transition
        events are exactly the records that must survive the overload
        killing the run — the drain force-flushes the ledger stream
        (the PR 7 SLO-violation idiom)."""
        self._pending_emits.append((name, args))

    def _drain_emits(self):
        while True:
            with self._lock:
                sheds, self._pending_sheds = self._pending_sheds, []
            for n_events, nbytes in sheds:
                # Outside our lock (record_shed takes telemetry's).
                self.tel.record_shed(n_events, nbytes)
            with self._lock:
                if not self._pending_emits:
                    if self._pending_sheds:
                        continue  # an emit raced in a shed; re-drain
                    return
                name, args = self._pending_emits.pop(0)
            if self.tel.enabled:
                self.tel.emit_instant(name, **args)
                self.tel.maybe_flush_stream(force=True)

    def snapshot(self) -> Dict[str, Any]:
        """The ``snapshot()["overload"]`` block (telemetry installs this
        as ``overload_provider``) — rides every ledger-stream checkpoint
        so `sfprof recover` reconstructs the overload story."""
        with self._lock:
            out: Dict[str, Any] = {
                "version": OVERLOAD_VERSION,
                "shed": {k: dict(v) for k, v in sorted(self.shed.items())},
                "shed_total": sum(r["events"] for r in self.shed.values()),
                "degraded_windows": int(self.degraded_windows),
                "backpressure_engaged": int(self.backpressure_engaged),
                "shedding": bool(self._shedding),
                "rung": int(self.rung),
                "ladder_depth": len(self.policy.ladder),
                "rung_transitions": int(self.rung_transitions),
                # Always present (possibly empty): the sfprof twin reads
                # an unseen class as 0 sheds, while a MISSING overload
                # block fails on silence — the twin mirrors exactly that.
                "tenants": {cls: dict(rec)
                            for cls, rec in sorted(self.tenant.items())},
            }
        if self.breaker is not None:
            out["breaker"] = self.breaker.snapshot()
        return out

    def state(self) -> Dict[str, Any]:
        """Checkpointable state — everything a deterministic resume
        needs to reproduce the exact shed schedule of an uninterrupted
        run (the driver publishes it with each checkpoint). Breaker
        state is deliberately excluded: device health belongs to the
        process, not the stream position."""
        with self._lock:
            return {
                "shed": {k: dict(v) for k, v in self.shed.items()},
                "max_ts": self._max_ts,
                "last_window_end": self._last_window_end,
                "slide_ms": self._slide_ms,
                "shedding": self._shedding,
                "shed_oldest": self._shed_oldest,
                "shed_windows": self._shed_windows,
                "admission_shedding": self._admission_shedding,
                "backlog_events": self._backlog_events,
                "backlog_bytes": self._backlog_bytes,
                "backlog_start_ts": self._backlog_start_ts,
                "degraded_windows": self.degraded_windows,
                "backpressure_engaged": self.backpressure_engaged,
                "rung": self.rung,
                "rung_transitions": self.rung_transitions,
                "tenant": {cls: dict(rec)
                           for cls, rec in self.tenant.items()},
                "tenant_shedding": sorted(self._tenant_shedding),
                "tenant_window_charge": {
                    cls: [int(w), int(c)]
                    for cls, (w, c) in self._tenant_window_charge.items()
                },
            }

    def restore(self, state: Dict[str, Any]):
        with self._lock:
            self.shed = {k: dict(v) for k, v in state["shed"].items()}
            self._max_ts = state["max_ts"]
            self._last_window_end = state["last_window_end"]
            self._slide_ms = int(state.get("slide_ms", 0))
            self._shedding = bool(state["shedding"])
            self._shed_oldest = bool(state["shed_oldest"])
            self._shed_windows = int(state["shed_windows"])
            self._admission_shedding = bool(state["admission_shedding"])
            self._backlog_events = int(state["backlog_events"])
            self._backlog_bytes = int(state["backlog_bytes"])
            self._backlog_start_ts = state.get("backlog_start_ts")
            self.degraded_windows = int(state["degraded_windows"])
            self.backpressure_engaged = int(state["backpressure_engaged"])
            self.rung = int(state["rung"])
            self.rung_transitions = int(state["rung_transitions"])
            # Pre-qserve checkpoints carry no tenant block (fresh state).
            self.tenant = {cls: dict(rec)
                           for cls, rec in state.get("tenant", {}).items()}
            self._tenant_shedding = set(state.get("tenant_shedding", ()))
            self._tenant_shed_this_window = set()
            self._tenant_window_charge = {
                cls: (int(w), int(c))
                for cls, (w, c) in state.get(
                    "tenant_window_charge", {}).items()
            }
            self._apply_effects()


# -- module-level wiring (the telemetry/slo singleton idiom) -------------------

_controller: Optional[OverloadController] = None


def install(ctrl: OverloadController) -> OverloadController:
    """Make ``ctrl`` the process-global overload controller: the
    window-fire sites feed it, the hot-path getters read its rung
    effects, and ``telemetry.snapshot()["overload"]`` carries it."""
    global _controller
    _controller = ctrl
    ctrl.tel.overload_provider = ctrl.snapshot
    return ctrl


def uninstall():
    global _controller
    if _controller is not None:
        _controller.tel.overload_provider = None
    _controller = None


def controller() -> Optional[OverloadController]:
    return _controller


def on_window_fired(n_events: int = 0, lag_ms: Optional[float] = None,
                    end: Optional[int] = None):
    """The window-fire hook (streams/windows.py, streams/soa.py — the
    same sites as slo.on_window_fired): free when no controller is
    installed — one global read and a None check."""
    ctrl = _controller
    if ctrl is not None:
        ctrl.on_window_fired(n_events, lag_ms, end)


def on_slo_evaluation(ok: bool):
    """slo.SloEngine.evaluate's hook — free when uninstalled."""
    ctrl = _controller
    if ctrl is not None:
        ctrl.on_slo_evaluation(ok)


def admit_tenant_query(cls: str) -> bool:
    """qserve's registration-admission hook: True (admit) when no
    controller is installed — one global read + None check."""
    ctrl = _controller
    return True if ctrl is None else ctrl.admit_tenant_query(cls)


def release_tenant_query(cls: str):
    """qserve's unregistration hook — free when uninstalled."""
    ctrl = _controller
    if ctrl is not None:
        ctrl.release_tenant_query(cls)


def tenant_result_allowance(cls: str, n: int,
                            window_start: Optional[int] = None) -> int:
    """Result rows class ``cls`` may emit this window (``n`` = no
    controller / no budget); ``window_start`` keys the retry-idempotent
    charge."""
    ctrl = _controller
    return int(n) if ctrl is None else ctrl.tenant_result_allowance(
        cls, n, window_start=window_start)


def compaction_clamp() -> Optional[int]:
    """Active ``clamp_compaction`` floor (None = rung inactive);
    ops/compaction.py:pick_capacity consults this. 0 = pin to the top
    rung."""
    ctrl = _controller
    return None if ctrl is None else ctrl.effect_compaction_clamp


def pane_backend() -> Optional[str]:
    """Active ``pane_backend`` bias for the ``backend="auto"`` engines
    (None = rung inactive)."""
    ctrl = _controller
    return None if ctrl is None else ctrl.effect_pane_backend


def batch_slides() -> int:
    """Active ``batch_slides`` fetch-batch width (1 = rung inactive)."""
    ctrl = _controller
    return 1 if ctrl is None else ctrl.effect_batch_slides


# ---------------------------------------------------------------------------
# Overload smoke: the burst → shed → degrade → recover round trip
# tools/ci runs on every commit.


def _smoke_tenant_leg(fail) -> Optional[int]:
    """The per-tenant-class QoS walk (smoke leg 2, same ledger stream):
    registration rejection at ``max_queries``, retry-idempotent result
    truncation at ``max_results_per_window``, class-local accounting
    (the GLOBAL ladder must not move), per-class recovery on a clean
    window fire, and the per-class SLO budgets answering. Swaps its own
    controller/engine into the module slots — the caller's ``finally``
    uninstalls whatever is current. Returns None on success, the
    ``fail(...)`` exit code otherwise."""
    from spatialflink_tpu import slo

    tctrl = install(OverloadController(OverloadPolicy(tenant_budgets={
        "bulk": {"max_queries": 1, "max_results_per_window": 5},
    })))
    slo.install(slo.SloEngine(slo.SloSpec(
        name="overload-smoke-tenants", eval_interval_s=0.0,
        tenant_budgets={"bulk": {"shed_budget": 3,
                                 "degraded_window_budget": 0}},
    )))
    tengine = slo.engine()
    if not tctrl.admit_tenant_query("bulk"):
        return fail("tenant leg: first registration rejected")
    if tctrl.admit_tenant_query("bulk"):
        return fail("tenant leg: budget-exceeding registration admitted")
    kept = tctrl.tenant_result_allowance("bulk", 9, window_start=1000)
    # Retry-idempotence: re-charging the SAME window must replace the
    # previous charge, not accumulate it.
    kept2 = tctrl.tenant_result_allowance("bulk", 9, window_start=1000)
    if (kept, kept2) != (5, 5):
        return fail(f"tenant leg: allowance ({kept}, {kept2}) != (5, 5)")
    if tctrl.tenant_shed_total("bulk") != 1 + 4:
        return fail(f"tenant leg: shed_total "
                    f"{tctrl.tenant_shed_total('bulk')} != 5 (1 "
                    "rejected query + 4 shed rows, charged once)")
    if tctrl.rung != 0 or tctrl.rung_transitions != 0:
        return fail("tenant leg: class-local sheds moved the GLOBAL "
                    "ladder")
    # Two fired windows: the first clears the shed-this-window marker
    # the charges above set; the second — clean — recovers the class
    # (the overload_tenant_recovered transition, sealed in the stream).
    tctrl.on_window_fired(n_events=1, lag_ms=0.0, end=2000)
    tctrl.on_window_fired(n_events=1, lag_ms=0.0, end=3000)
    trows = {r["check"]: r for r in tengine.evaluate()}
    srow = trows.get("tenant_shed_budget:bulk")
    drow = trows.get("tenant_degraded_window_budget:bulk")
    if srow is None or srow["ok"] is not False:
        # 5 sheds > the 3 budget — the per-class check must violate.
        return fail(f"tenant leg: shed-budget row wrong: {srow}")
    if drow is None or drow["ok"] is not False:
        # 1 class-degraded window > the 0 budget — must violate too.
        return fail(f"tenant leg: degraded-window row wrong: {drow}")
    return None


def smoke() -> int:
    """Deterministic toy burst against a tiny admission budget and a
    low lag ceiling: sheds must be counted, the ladder must step down
    AND back up, the SLO verdict must carry the shed/degradation
    budgets, and every transition must be recoverable from the sealed
    ledger stream. A second leg walks the PER-TENANT-CLASS machinery
    (``tenant_budgets``): an over-budget class must have its
    registration rejected and its result rows truncated — counted
    against THE CLASS, never stepping the global ladder — with the
    per-class transition events sealed in the same stream and the
    per-class SLO budgets in a verdict. Exit 0 on success."""
    import tempfile

    import numpy as np

    from spatialflink_tpu import slo
    from spatialflink_tpu.driver import WindowedDataflowDriver, RetryPolicy
    from spatialflink_tpu.models.objects import Point
    from spatialflink_tpu.operators.query_config import (
        QueryConfiguration,
        QueryType,
    )
    from spatialflink_tpu.operators.trajectory import TStatsQuery
    from spatialflink_tpu.grid import UniformGrid

    def fail(msg: str) -> int:
        print(f"overload-smoke: {msg}")
        return 1

    grid = UniformGrid(8, 0.0, 8.0, 0.0, 8.0)
    conf = QueryConfiguration(QueryType.WindowBased, window_size=2.0,
                              slide_step=1.0)
    rng = np.random.default_rng(17)

    def source():
        """Smooth cadence → a 20 s event-time jump (the backlog fires
        with huge lag → shed mode) → an out-of-order burst (late sheds
        + an admission burst past the budget) → smooth recovery."""
        i = 0

        def pt(ts):
            nonlocal i
            i += 1
            return Point(obj_id=f"o{i % 5}", timestamp=int(ts),
                         x=float(rng.uniform(0, 8)),
                         y=float(rng.uniform(0, 8)))

        for t in range(0, 6000, 200):          # phase A: smooth
            yield pt(t)
        yield pt(26_000)                       # phase B: the jump
        for t in range(6200, 9000, 100):       # stragglers: late sheds
            yield pt(t)
        for j in range(24):                    # dense burst at one ts:
            yield pt(27_000 + j)               # admission budget blows
        for t in range(28_000, 48_000, 200):   # phase C: recovery
            yield pt(t)

    policy = OverloadPolicy(
        max_buffered_events=8,
        lag_shed_ceiling_ms=5_000,
        lag_recover_ms=1_000,
        shed_oldest_after_windows=2,
        ladder=(
            {"action": "clamp_compaction", "cap": 0},
            {"action": "pane_backend", "to": "native"},
        ),
        degrade_cooldown=1,
        recover_after=6,
    )
    spec = slo.SloSpec(name="overload-smoke", shed_budget=10_000,
                       degraded_window_budget=0, eval_interval_s=0.0)

    with tempfile.TemporaryDirectory(prefix="sft_overload_") as tmp:
        stream_path = os.path.join(tmp, "smoke.stream.jsonl")
        telemetry.enable(stream_path=stream_path,
                         stream_flush_interval_s=0.0)
        ctrl = install(OverloadController(policy))
        engine = slo.install(slo.SloEngine(spec))
        max_rung = 0
        try:
            op = TStatsQuery(conf, grid)
            driver = WindowedDataflowDriver(
                retry=RetryPolicy(max_retries=0), failover=False,
                overload=ctrl, source_pausable=False,
            )
            for _ in op.run(source(), driver=driver):
                max_rung = max(max_rung, ctrl.rung)
            verdict = engine.verdict()
            snap = telemetry.snapshot()
            tenant_fail = _smoke_tenant_leg(fail)
            if tenant_fail is not None:
                return tenant_fail
        finally:
            slo.uninstall()
            uninstall()
            telemetry.disable()  # seals the stream

        ov = snap.get("overload")
        if not ov:
            return fail("snapshot() carries no overload block")
        if ov["shed_total"] <= 0 or "late" not in ov["shed"] \
                or "admission" not in ov["shed"]:
            return fail(f"expected late+admission sheds, got {ov['shed']}")
        if max_rung < 1:
            return fail("degradation ladder never stepped down")
        if ctrl.rung != 0:
            return fail(f"ladder did not recover (rung {ctrl.rung})")
        checks = {row["check"] for row in verdict["checks"]}
        if not {"shed_budget", "degraded_window_budget"} <= checks:
            return fail(f"SLO verdict misses overload budgets: {checks}")

        names = []
        with open(stream_path) as f:
            for line in f:
                rec = json.loads(line)
                if rec.get("t") == "spans":
                    names.extend(e.get("name", "") for e in rec["events"])
                sealed = rec.get("t") == "epilogue"
        want = ("overload_shedding:lag", "overload_shedding:admission",
                "overload_recovered:lag", "overload_rung_down:",
                "overload_rung_up:", "overload_tenant_shed:bulk",
                "overload_tenant_recovered:bulk")
        missing = [w for w in want
                   if not any(n.startswith(w) for n in names)]
        if missing:
            return fail(f"stream misses transition events: {missing}")
        if not sealed:
            return fail("ledger stream was not sealed")

    shed = ", ".join(f"{k}={v['events']}" for k, v in sorted(ov["shed"].items()))
    print(f"overload-smoke: sheds ({shed}), rung peaked at {int(max_rung)} "
          "and recovered, transitions sealed in the stream — OK")
    return 0


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m spatialflink_tpu.overload",
        description="overload-control burst/shed/degrade/recover smoke",
    )
    ap.add_argument("--smoke", action="store_true",
                    help="run the deterministic overload round trip")
    args = ap.parse_args(argv)
    if args.smoke:
        return smoke()
    ap.error("pass --smoke")
    return 2


if __name__ == "__main__":
    import sys

    # ``python -m spatialflink_tpu.overload`` executes this file as
    # __main__ while the driver/assembler hooks import the CANONICAL
    # spatialflink_tpu.overload — two module instances, two controller
    # slots. Delegate to the canonical one so install()/the hooks/the
    # getters all share one slot.
    from spatialflink_tpu.overload import main as _canonical_main

    sys.exit(_canonical_main())
