from spatialflink_tpu.apps.checkin import CheckInEvent, check_in_query  # noqa: F401
from spatialflink_tpu.apps.staytime import (  # noqa: F401
    cell_stay_time,
    cell_sensor_range_intersection,
    normalized_cell_stay_time,
)
