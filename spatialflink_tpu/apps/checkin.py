"""CheckIn app — room-occupancy tracking (``GeoFlink/apps/CheckIn.java``).

Pipeline parity with CheckIn.CheckInQuery (CheckIn.java:26-60):
  1. per-user count windows (2, 1): two consecutive events from the same
     door sensor (e.g. two "roomX-in" in a row) imply a missed opposite
     event — synthesize it at the midpoint timestamp
     (ProcessWinForInsertingMissingValues, CheckIn.java:251-321);
  2. per-room count window (1) with a running occupancy counter:
     "-in" increments, "-out" decrements; emit
     (room, capacity, occupancy, wallclock) per event
     (ProcessForCountingObjects, CheckIn.java:208-249).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, Iterable, Iterator, List, Optional, Tuple


@dataclass
class CheckInEvent:
    """The reference's check-in Point variant (eventID, deviceID like
    "room1-in", userID, ts, x, y)."""

    event_id: str
    device_id: str  # "<room>-in" | "<room>-out"
    user_id: str
    timestamp: int
    x: float = 0.0
    y: float = 0.0

    @property
    def room(self) -> str:
        return self.device_id[: self.device_id.index("-")]

    @property
    def direction(self) -> str:
        return self.device_id[self.device_id.index("-") + 1:]


def _insert_missing(events: Iterable[CheckInEvent]) -> Iterator[CheckInEvent]:
    """Per-user sliding count(2,1) pass inserting missing in/out events.
    Only the previous event per user is needed (bounded state — the
    reference's count window holds 2)."""
    last: Dict[str, CheckInEvent] = {}
    for ev in events:
        prev = last.get(ev.user_id)
        last[ev.user_id] = ev
        if prev is None:
            # First window holds a single event → emit as-is
            # (CheckIn.java:272-276).
            yield ev
            continue
        if prev.device_id == ev.device_id:
            # Two consecutive same-door events → synthesize the opposite
            # event at the midpoint timestamp (CheckIn.java:286-305).
            mid_ts = (prev.timestamp + ev.timestamp) // 2
            flip = "out" if prev.direction == "in" else "in"
            yield CheckInEvent(
                ev.event_id, f"{prev.room}-{flip}", ev.user_id, mid_ts,
                ev.x, ev.y,
            )
        yield ev


def check_in_query(
    events: Iterable[CheckInEvent],
    room_capacities: Dict[str, int],
) -> Iterator[Tuple[str, Optional[int], int, float]]:
    """Yield (room, capacity, occupancy, wallclock) per processed event."""
    occupancy: Dict[str, int] = {}
    for ev in _insert_missing(events):
        room = ev.room
        occupancy[room] = occupancy.get(room, 0) + (
            1 if ev.direction == "in" else -1
        )
        yield (room, room_capacities.get(room), occupancy[room], time.time())
