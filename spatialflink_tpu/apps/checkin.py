"""CheckIn app — room-occupancy tracking (``GeoFlink/apps/CheckIn.java``).

Pipeline parity with CheckIn.CheckInQuery (CheckIn.java:26-60):
  1. per-user count windows (2, 1): two consecutive events from the same
     door sensor (e.g. two "roomX-in" in a row) imply a missed opposite
     event — synthesize it at the midpoint timestamp
     (ProcessWinForInsertingMissingValues, CheckIn.java:251-321);
  2. per-room count window (1) with a running occupancy counter:
     "-in" increments, "-out" decrements; emit
     (room, capacity, occupancy, wallclock) per event
     (ProcessForCountingObjects, CheckIn.java:208-249).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, Iterable, Iterator, List, Optional, Tuple


@dataclass
class CheckInEvent:
    """The reference's check-in Point variant (eventID, deviceID like
    "room1-in", userID, ts, x, y)."""

    event_id: str
    device_id: str  # "<room>-in" | "<room>-out"
    user_id: str
    timestamp: int
    x: float = 0.0
    y: float = 0.0

    @property
    def room(self) -> str:
        return self.device_id[: self.device_id.index("-")]

    @property
    def direction(self) -> str:
        return self.device_id[self.device_id.index("-") + 1:]


def _insert_missing(events: Iterable[CheckInEvent],
                    last: Optional[Dict[str, CheckInEvent]] = None,
                    ) -> Iterator[CheckInEvent]:
    """Per-user sliding count(2,1) pass inserting missing in/out events.
    Only the previous event per user is needed (bounded state — the
    reference's count window holds 2). ``last`` (mutated in place)
    carries that per-user state ACROSS calls — the composed DAG's
    CheckIn node (dag.py) processes one window pane per call and
    checkpoints the dict; the default (fresh state per call) is the
    batch contract the standalone queries use."""
    if last is None:
        last = {}
    for ev in events:
        prev = last.get(ev.user_id)
        last[ev.user_id] = ev
        if prev is None:
            # First window holds a single event → emit as-is
            # (CheckIn.java:272-276).
            yield ev
            continue
        if prev.device_id == ev.device_id:
            # Two consecutive same-door events → synthesize the opposite
            # event at the midpoint timestamp (CheckIn.java:286-305).
            mid_ts = (prev.timestamp + ev.timestamp) // 2
            flip = "out" if prev.direction == "in" else "in"
            yield CheckInEvent(
                ev.event_id, f"{prev.room}-{flip}", ev.user_id, mid_ts,
                ev.x, ev.y,
            )
        yield ev


def check_in_query(
    events: Iterable[CheckInEvent],
    room_capacities: Dict[str, int],
) -> Iterator[Tuple[str, Optional[int], int, float]]:
    """Yield (room, capacity, occupancy, wallclock) per processed event."""
    occupancy: Dict[str, int] = {}
    for ev in _insert_missing(events):
        room = ev.room
        occupancy[room] = occupancy.get(room, 0) + (
            1 if ev.direction == "in" else -1
        )
        yield (room, room_capacities.get(room), occupancy[room], time.time())


def check_in_query_soa(
    events: Iterable[CheckInEvent],
    room_capacities: Dict[str, int],
) -> Iterator[Tuple[str, Optional[int], int, float]]:
    """Device SoA path: the same (room, capacity, occupancy, wallclock)
    stream as ``check_in_query``, computed as ONE jitted kernel dispatch
    (ops/checkin.py:check_in_kernel — stable-sort consecutive-per-user
    detection + segmented-cumsum occupancy) instead of the per-event
    host walk. Bit-parity test: tests/test_apps.py. Bounded batches
    (the count-window state is two events deep, so stream chunking at
    any boundary per user is exact only within a batch — same contract
    as the host path restarted per batch)."""
    import jax.numpy as jnp
    import numpy as np

    from spatialflink_tpu.operators.base import jitted
    from spatialflink_tpu.ops.checkin import check_in_kernel
    from spatialflink_tpu.utils.padding import next_bucket

    events = list(events)
    if not events:
        return
    n = len(events)
    rooms: Dict[str, int] = {}
    users: Dict[str, int] = {}
    nb = next_bucket(n, minimum=8)
    room_id = np.zeros(nb, np.int32)
    user_id = np.zeros(nb, np.int32)
    dirn = np.zeros(nb, np.int32)
    ts = np.zeros(nb, np.int64)
    for i, ev in enumerate(events):
        room_id[i] = rooms.setdefault(ev.room, len(rooms))
        user_id[i] = users.setdefault(ev.user_id, len(users))
        dirn[i] = 1 if ev.direction == "in" else -1
        ts[i] = ev.timestamp
    valid = np.zeros(nb, bool)
    valid[:n] = True
    k = jitted(check_in_kernel, "num_rooms")
    out_room, _d, _t, out_valid, occ = k(
        jnp.asarray(user_id), jnp.asarray(room_id), jnp.asarray(dirn),
        jnp.asarray(ts), jnp.asarray(valid), num_rooms=len(rooms),
    )
    names = {v: name for name, v in rooms.items()}
    ov = np.asarray(out_valid)
    orm = np.asarray(out_room)
    oc = np.asarray(occ)
    for s in np.nonzero(ov)[0]:
        room = names[int(orm[s])]
        yield (room, room_capacities.get(room), int(oc[s]), time.time())
