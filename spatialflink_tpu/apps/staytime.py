"""StayTime app — per-cell dwell-time heatmaps (``GeoFlink/apps/StayTime.java``).

Three queries, matching StayTime.java:35-150:
  - ``cell_stay_time``: per trajectory per window, walk ts-ordered points
    and attribute each consecutive time gap to the earlier point's grid
    cell; then sum per cell (CellStayTimeWinFunction :216-396 +
    CellStayTimeAggregateWinFunction :433-447). Output per window:
    {cellName: totalStayTimeMs}.
  - ``cell_sensor_range_intersection``: per window, count sensor polygons
    whose geometry intersects each cell's boundary box
    (CellSensorIntersectionWinFunction :398-430).
  - ``normalized_cell_stay_time``: join on cell:
    (stayTime/1000 / sensorCount) * windowSize
    (normalizedCellStayTimeWinFunction :189-213).
"""

from __future__ import annotations

from typing import Dict, Iterable, Iterator, Optional, Sequence, Set, Tuple

import numpy as np

from spatialflink_tpu.grid import UniformGrid
from spatialflink_tpu.models.objects import Point, Polygon
from spatialflink_tpu.streams.windows import SlidingEventTimeWindows, WindowAssembler


def _windows(events, window_s: int, slide_s: int, lateness_s: int):
    asm = WindowAssembler(
        SlidingEventTimeWindows(window_s * 1000, slide_s * 1000),
        timestamp_fn=lambda e: e.timestamp,
        max_out_of_orderness_ms=lateness_s * 1000,
    )
    yield from asm.stream(events)


def _any_edge_hits_rect(p: np.ndarray, q: np.ndarray,
                        x1: float, y1: float, x2: float, y2: float) -> bool:
    """True if any segment p[i]→q[i] intersects the axis-aligned rectangle
    (Liang–Barsky clip, vectorized over segments)."""
    if len(p) == 0:
        return False
    d = q - p
    t0 = np.zeros(len(p))
    t1 = np.ones(len(p))
    ok = np.ones(len(p), bool)
    for dim, lo, hi in ((0, x1, x2), (1, y1, y2)):
        dd = d[:, dim]
        pp = p[:, dim]
        with np.errstate(divide="ignore", invalid="ignore"):
            tlo = (lo - pp) / dd
            thi = (hi - pp) / dd
        enter = np.where(dd >= 0, tlo, thi)
        exit_ = np.where(dd >= 0, thi, tlo)
        par = dd == 0
        ok &= ~(par & ((pp < lo) | (pp > hi)))
        t0 = np.where(par, t0, np.maximum(t0, enter))
        t1 = np.where(par, t1, np.minimum(t1, exit_))
    return bool((ok & (t0 <= t1)).any())


def cell_stay_time(
    points: Iterable[Point],
    traj_ids: Set[str],
    allowed_lateness_s: int,
    window_s: int,
    slide_s: int,
    grid: UniformGrid,
) -> Iterator[Tuple[int, int, Dict[str, float]]]:
    """Yield (winStart, winEnd, {cellName: stayTimeMs}) per fired window.

    Consecutive-point time gaps are attributed to the earlier point's cell
    (vectorized with numpy over the ts-sorted per-trajectory arrays — the
    same walk as CellStayTimeWinFunction's loop)."""
    for win in _windows(points, window_s, slide_s, allowed_lateness_s):
        evs = [p for p in win.events if not traj_ids or p.obj_id in traj_ids]
        if not evs:
            continue
        yield (win.start, win.end, stay_time_window(evs, grid))


def stay_time_window(evs, grid: UniformGrid) -> Dict[str, float]:
    """One window's {cellName: stayTimeMs} — the host walk shared by
    the streaming generator above and the composed DAG's StayTime node
    fallback route (dag.py). ``evs`` carries ``obj_id``/``timestamp``/
    ``x``/``y`` attributes (Points or GpsEvent-likes adapted by the
    caller)."""
    per_cell: Dict[str, float] = {}
    by_obj: Dict[str, list] = {}
    for p in evs:
        by_obj.setdefault(p.obj_id, []).append(p)
    for pts in by_obj.values():
        pts.sort(key=lambda p: p.timestamp)
        if len(pts) < 2:
            continue
        ts = np.array([p.timestamp for p in pts], np.int64)
        cells = grid.assign_cells_np(
            np.array([[p.x, p.y] for p in pts], float)
        )
        gaps = ts[1:] - ts[:-1]
        for cell, gap in zip(cells[:-1], gaps):
            name = grid.cell_name(int(cell)) if cell < grid.num_cells else "out"
            per_cell[name] = per_cell.get(name, 0.0) + float(gap)
    return per_cell


def cell_stay_time_soa(
    chunks,
    window_s: int,
    slide_s: int,
    grid: UniformGrid,
    allowed_lateness_s: int = 0,
    oid_allow: Optional[np.ndarray] = None,
) -> Iterator[Tuple[int, int, np.ndarray, np.ndarray]]:
    """SoA/device fast path for ``cell_stay_time``: point chunks
    {"ts","x","y","oid"} (dense int32 oids) → per window
    (start, end, cell_ids, dwell_ms) raw arrays via ONE segment-sum
    kernel per window (ops/trajectory.py:stay_time_cells_kernel) — the
    per-trajectory Python walk of the object path collapses into a
    device reduction (apps/StayTime.java:216-396). ``cell_ids`` may
    include ``grid.num_cells`` (the object path's "out" bucket);
    ``oid_allow``: optional bool mask over dense oids (the trajIdSet
    filter) — filtered points are COMPACTED out before pairing, exactly
    like the object path's pre-filter (masking alone would break
    consecutive pairs differently). Parity test: tests/test_apps.py."""
    import jax.numpy as jnp

    from spatialflink_tpu.operators.base import jitted
    from spatialflink_tpu.ops.trajectory import stay_time_cells_kernel
    from spatialflink_tpu.streams.soa import SoaWindowAssembler
    from spatialflink_tpu.utils.padding import next_bucket

    kernel = jitted(stay_time_cells_kernel, "num_cells")
    asm = SoaWindowAssembler(
        window_s * 1000, slide_s * 1000,
        ooo_ms=allowed_lateness_s * 1000,
    )
    for win in asm.stream(chunks):
        ts = np.asarray(win.arrays["ts"], np.int64)[:win.count]
        oid = np.asarray(win.arrays["oid"], np.int64)[:win.count]
        xy = np.stack(
            [np.asarray(win.arrays["x"], np.float64)[:win.count],
             np.asarray(win.arrays["y"], np.float64)[:win.count]],
            axis=1,
        )
        if oid_allow is not None:
            keep = oid_allow[oid]
            ts, oid, xy = ts[keep], oid[keep], xy[keep]
        if len(ts) == 0:
            # Object-path parity: a window with no surviving events is
            # SUPPRESSED (cell_stay_time's `if not evs: continue`), while
            # one with events but no pairs fires empty.
            continue
        hit, dwell = stay_time_window_soa(ts, oid, xy, grid, kernel)
        yield (win.start, win.end, hit, dwell)


def stay_time_window_soa(ts, oid, xy, grid: UniformGrid, kernel):
    """One window's (cell_ids, dwell_ms) via the segment-sum kernel —
    the device core shared by ``cell_stay_time_soa`` and the composed
    DAG's StayTime node (dag.py). ``ts``/``oid`` int64 arrays, ``xy``
    (N, 2) float64; ``kernel`` a jitted stay_time_cells_kernel."""
    import jax.numpy as jnp

    from spatialflink_tpu.utils.padding import next_bucket

    if len(ts) < 2:
        return np.empty(0, np.int32), np.empty(0, np.int64)
    order = np.lexsort((ts, oid))
    cells = grid.assign_cells_np(xy[order])
    nb = next_bucket(len(ts), minimum=8)
    pad = nb - len(ts)
    t_rel = ts[order] - int(ts.min())  # int32-safe on non-x64 devices
    tp = np.concatenate([t_rel, np.zeros(pad, np.int64)]).astype(np.int32)
    op_ = np.concatenate(
        [oid[order], np.full(pad, -1, np.int64)]).astype(np.int32)
    cp = np.concatenate(
        [cells, np.full(pad, grid.num_cells, np.int64)]).astype(np.int32)
    vp = np.concatenate([np.ones(len(ts), bool), np.zeros(pad, bool)])
    dwell, cnt = kernel(
        jnp.asarray(tp), jnp.asarray(cp), jnp.asarray(op_),
        jnp.asarray(vp), num_cells=grid.num_cells,
    )
    dwell = np.asarray(dwell).astype(np.int64)
    hit = np.nonzero(np.asarray(cnt))[0].astype(np.int32)
    return hit, dwell[hit]


def cell_sensor_range_intersection(
    polygons: Iterable[Polygon],
    traj_ids: Set[str],
    allowed_lateness_s: int,
    window_s: int,
    slide_s: int,
    grid: UniformGrid,
) -> Iterator[Tuple[int, int, Dict[str, int]]]:
    """Yield (winStart, winEnd, {cellName: intersectingSensorCount}).

    A sensor-range polygon counts for every cell whose square its bbox
    geometry intersects; the reference replicates each polygon to its
    gridIDsSet and then exact-tests intersection against the cell boundary
    polygon — bbox-vs-cell intersection reproduces that for the rectangular
    sensor ranges the app targets, with an exact edge/containment test for
    the general case."""
    from spatialflink_tpu.ops.polygon import pack_rings, points_in_polygon
    import jax.numpy as jnp

    for win in _windows(polygons, window_s, slide_s, allowed_lateness_s):
        evs = [p for p in win.events if not traj_ids or p.obj_id in traj_ids]
        per_cell: Dict[str, int] = {}
        for poly in evs:
            for cell in poly.grid_cells(grid):
                xi, yi = divmod(int(cell), grid.n)
                x1 = grid.min_x + xi * grid.cell_length
                y1 = grid.min_y + yi * grid.cell_length
                x2, y2 = x1 + grid.cell_length, y1 + grid.cell_length
                # Exact test: any cell corner in polygon, any polygon vertex
                # in cell, or any polygon edge crossing the cell rectangle
                # (covers thin strips passing through with no vertex inside).
                verts, ev = poly.packed()
                corners = jnp.asarray(
                    [[x1, y1], [x2, y1], [x2, y2], [x1, y2]], float
                )
                corner_in = bool(
                    np.asarray(
                        points_in_polygon(corners, jnp.asarray(verts), jnp.asarray(ev))
                    ).any()
                )
                pv = np.concatenate(poly.rings, axis=0)
                vert_in = bool(
                    ((pv[:, 0] >= x1) & (pv[:, 0] <= x2)
                     & (pv[:, 1] >= y1) & (pv[:, 1] <= y2)).any()
                )
                edge_cross = corner_in or vert_in or _any_edge_hits_rect(
                    verts[:-1][ev], verts[1:][ev], x1, y1, x2, y2
                )
                if corner_in or vert_in or edge_cross:
                    name = grid.cell_name(int(cell))
                    per_cell[name] = per_cell.get(name, 0) + 1
        yield (win.start, win.end, per_cell)


def normalized_cell_stay_time(
    points: Iterable[Point],
    traj_ids_point: Set[str],
    polygons: Iterable[Polygon],
    traj_ids_sensor: Set[str],
    allowed_lateness_s: int,
    window_s: int,
    slide_s: int,
    grid: UniformGrid,
) -> Iterator[Tuple[str, int, int, float]]:
    """Join stay time with sensor coverage per (cell, window):
    normalized = (stayTimeMs/1000 / sensorCount) * windowSize
    (normalizedCellStayTimeWinFunction, StayTime.java:199-211).
    Yields (cellName, winStart, winEnd, normalizedStayTime)."""
    stay = {
        (s, e): cells
        for s, e, cells in cell_stay_time(
            points, traj_ids_point, allowed_lateness_s, window_s, slide_s, grid
        )
    }
    sensors = {
        (s, e): cells
        for s, e, cells in cell_sensor_range_intersection(
            polygons, traj_ids_sensor, allowed_lateness_s, window_s, slide_s, grid
        )
    }
    for span in sorted(set(stay) & set(sensors)):
        for cell, st in sorted(stay[span].items()):
            cnt = sensors[span].get(cell)
            if cnt:
                yield (cell, span[0], span[1], (st / 1000.0 / cnt) * window_s)
