"""Per-interval CSV metrics sink (``sncb/metrics/MetricsSink.java:13-101``).

Rows: ``seconds,count,bytesMB,eps,throughputMBps,avgLatencyMs`` per
reporting interval, where latency = now − event/window timestamp.
``include_opcounters=True`` appends a ``distComp`` column fed by the
kernel-level counter registry (ops/counters.py — the Point.java:220-235
distance-computation analog); ``include_telemetry=True`` appends
``wmLagMs,lateDrops`` fed by the runtime telemetry layer (telemetry.py:
max watermark lag gauge + per-interval late-drop delta). Both off by
default to preserve the reference's exact column set.
"""

from __future__ import annotations

import os
import time
from typing import Optional


class MetricsSink:
    """Count records per wall-clock interval and append CSV rows."""

    HEADER = "seconds,count,bytesMB,eps,throughputMBps,avgLatencyMs"

    def __init__(
        self,
        name: str,
        path: Optional[str] = None,
        interval_s: float = 1.0,
        bytes_per_record: int = 128,
        include_opcounters: bool = False,
        include_telemetry: bool = False,
    ):
        self.name = name
        self.interval_s = interval_s
        self.bytes_per_record = bytes_per_record
        self.include_opcounters = include_opcounters
        self.include_telemetry = include_telemetry
        self._last_dist_comp = 0
        self._last_late_drops = 0
        if include_opcounters:
            self.HEADER = self.HEADER + ",distComp"
            # Baseline at construction: earlier runs' tallies must not leak
            # into this sink's first interval.
            from spatialflink_tpu.ops.counters import counters as opcounters

            self._last_dist_comp = opcounters.dist_computations
        if include_telemetry:
            self.HEADER = self.HEADER + ",wmLagMs,lateDrops"
            from spatialflink_tpu.telemetry import telemetry

            self._last_late_drops = telemetry.late_drops
        self._t0 = time.time()
        self._interval_start = self._t0
        self._count = 0
        self._latency_sum_ms = 0.0
        self.rows = []
        self._f = None
        if path:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._f = open(path, "w")
            self._f.write(self.HEADER + "\n")

    def record(self, event_ts_ms: Optional[int] = None, n: int = 1):
        now = time.time()
        self._count += n
        if event_ts_ms is not None:
            self._latency_sum_ms += max(0.0, now * 1000 - event_ts_ms) * n
        if now - self._interval_start >= self.interval_s:
            self._flush_interval(now)

    def _flush_interval(self, now: float):
        dt = now - self._interval_start
        if dt <= 0:
            return
        eps = self._count / dt
        mb = self._count * self.bytes_per_record / 1e6
        avg_lat = self._latency_sum_ms / self._count if self._count else 0.0
        # float() wraps: numpy ≥2 scalars would print np.float64(…) into
        # the CSV row (sfcheck fstring-numpy).
        row = (
            f"{float(now - self._t0):.1f},{self._count},{float(mb):.3f},"
            f"{float(eps):.1f},{float(mb / dt):.3f},{float(avg_lat):.2f}"
        )
        if self.include_opcounters:
            from spatialflink_tpu.ops.counters import counters as opcounters

            total = opcounters.dist_computations
            row += f",{total - self._last_dist_comp}"
            self._last_dist_comp = total
        if self.include_telemetry:
            from spatialflink_tpu.telemetry import telemetry

            late = telemetry.late_drops
            if late < self._last_late_drops:
                # telemetry.enable() reset the gauge mid-run: re-baseline
                # instead of printing a negative delta.
                self._last_late_drops = 0
            row += f",{telemetry.max_watermark_lag_ms},{late - self._last_late_drops}"
            self._last_late_drops = late
        self.rows.append(row)
        if self._f:
            self._f.write(row + "\n")
            self._f.flush()
        self._interval_start = now
        self._count = 0
        self._latency_sum_ms = 0.0

    def close(self):
        self._flush_interval(time.time())
        if self._f:
            self._f.close()
