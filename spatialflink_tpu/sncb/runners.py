"""SNCB test/benchmark runners (``GeoFlink/sncb/tests/``).

- ``local_test_runner``: handcrafted fixture events with per-query
  expectations (LocalTestRunner.java:21-115);
- ``benchmark_runner``: seeded synthetic GPS load at a target EPS with
  per-second metrics (BenchmarkRunner.java:22-105 + SyntheticGpsSource);
- ``mobility_query_runner``: CSV replay of the MN_Q1..Q5 suite with an
  execution-stats report (MobilityQueryRunner.java:33-150).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional

from spatialflink_tpu.sncb import mobility
from spatialflink_tpu.sncb.common import GpsEvent, PolygonLoader, csv_to_gps_event
from spatialflink_tpu.sncb.metrics import MetricsSink
from spatialflink_tpu.sncb.queries import (
    q1_high_risk,
    q2_brake_monitor,
    q3_trajectory,
    q5_traj_speed_fence,
)
from spatialflink_tpu.streams.sources import SyntheticGpsSource

# Brussels-area bbox used by the synthetic benchmark source
# (BenchmarkRunner.java:35: lon 4.25..4.50, lat 50.75..50.95).
BRUSSELS_BBOX = (4.25, 4.50, 50.75, 50.95)


def sample_gps_events() -> List[GpsEvent]:
    """The reference's own golden fixture, verbatim data
    (LocalTestRunner.sampleData, LocalTestRunner.java:86-115), against the
    reference's own bundled zones (src/main/resources). The Java comments
    encode the expectations asserted in tests/test_sncb.py:

      A — inside the high-risk zone (Q1 hits);
      B — outside the maintenance area, varFA 0.7 > 0.6, varFF 0.2 ≤ 0.5
          (Q2 alert; A's FA/FF spreads qualify too);
      C/D — simple two-device trajectories (Q3/Q4);
      E — inside the Q5 fence, avg speed 51.7 > 50, min 40 > 20.

    t0 is fixed (the reference uses wall-clock currentTimeMillis).
    """
    t0 = 1_700_000_000_000
    return [
        GpsEvent("A", 4.352, 50.852, t0 + 1000, 10.0, 0.1, 0.1),
        GpsEvent("A", 4.355, 50.855, t0 + 2000, 11.0, 0.2, 0.2),
        GpsEvent("A", 4.358, 50.858, t0 + 3000, 12.0, 0.8, 0.4),
        GpsEvent("B", 4.370, 50.852, t0 + 1100, 8.0, 0.1, 0.5),
        GpsEvent("B", 4.372, 50.853, t0 + 2100, 8.5, 0.8, 0.4),
        GpsEvent("B", 4.374, 50.854, t0 + 3100, 9.0, 0.7, 0.3),
        GpsEvent("C", 4.40, 50.10, t0 + 1200, 15.0, None, None),
        GpsEvent("C", 4.41, 50.11, t0 + 2200, 15.5, None, None),
        GpsEvent("C", 4.42, 50.12, t0 + 3200, 16.0, None, None),
        GpsEvent("D", 4.31, 50.20, t0 + 1300, 17.0, None, None),
        GpsEvent("D", 4.33, 50.22, t0 + 2300, 18.0, None, None),
        GpsEvent("D", 4.35, 50.24, t0 + 3300, 19.0, None, None),
        GpsEvent("E", 4.405, 50.855, t0 + 1400, 60.0, None, None),
        GpsEvent("E", 4.406, 50.856, t0 + 2400, 55.0, None, None),
        GpsEvent("E", 4.407, 50.857, t0 + 3400, 40.0, None, None),
    ]


def local_test_runner(verbose: bool = False) -> Dict[str, list]:
    """Run Q1/Q2/Q3/Q5 over the fixture; return per-query results."""
    risk = PolygonLoader.load_geojson_buffered("high_risk_zones.geojson", 20.0)
    maint = PolygonLoader.load_geojson_buffered("maintenance_areas.geojson", 0.0)
    fence = PolygonLoader.load_wkt_buffered("q5_fence.wkt", 20.0)

    out = {
        "q1": list(q1_high_risk(iter(sample_gps_events()), risk)),
        "q2": list(
            q2_brake_monitor(iter(sample_gps_events()), maint, slide_ms=500)
        ),
        "q3": list(q3_trajectory(iter(sample_gps_events()), slide_ms=1000)),
        "q5": list(q5_traj_speed_fence(iter(sample_gps_events()), fence)),
    }
    if verbose:
        for q, res in out.items():
            print(f"{q}: {len(res)} results")
            for r in res[:5]:
                print("  ", r)
    return out


@dataclass
class BenchmarkReport:
    query: str
    events: int
    duration_s: float
    eps: float
    results: int
    source_metrics: List[str]
    sink_metrics: List[str]


def benchmark_runner(
    query: str = "q1",
    target_eps: int = 20_000,
    duration_ms: int = 30_000,
    num_devices: int = 10,
    out_dir: Optional[str] = None,
) -> BenchmarkReport:
    """BenchmarkRunner.main analog: synthetic load through one query with
    1 s CSV metrics at source and sink (BenchmarkRunner.java:22-105)."""
    min_x, max_x, min_y, max_y = BRUSSELS_BBOX
    src = SyntheticGpsSource(
        min_x, max_x, min_y, max_y,
        target_eps=target_eps, duration_ms=duration_ms,
        num_devices=num_devices, seed=42,
        start_ts=1_700_000_000_000,
        make_event=lambda device_id, x, y, timestamp, speed: GpsEvent(
            device_id, x, y, timestamp, speed, 5.0, 5.0
        ),
    )
    from spatialflink_tpu.ops.counters import counters as opcounters

    source_sink = MetricsSink(
        "source", f"{out_dir}/source.csv" if out_dir else None
    )
    # The sink CSV gains a distComp column when the kernel counter registry
    # is on (ops/counters.enable()) — the distCompCounter analog.
    result_sink = MetricsSink(
        f"sink-{query}", f"{out_dir}/sink-{query}.csv" if out_dir else None,
        include_opcounters=opcounters.enabled,
    )

    def counted(it):
        for e in it:
            source_sink.record(e.ts)
            yield e

    risk = PolygonLoader.load_geojson_buffered("high_risk_zones.geojson", 20.0)
    maint = PolygonLoader.load_geojson_buffered("maintenance_areas.geojson", 0.0)
    fence = PolygonLoader.load_wkt_buffered("q5_fence.wkt", 20.0)

    t0 = time.time()
    n_results = 0
    if query == "q1":
        it = q1_high_risk(counted(src), risk)
    elif query == "q2":
        it = q2_brake_monitor(counted(src), maint, slide_ms=1000)
    elif query == "q3":
        it = q3_trajectory(counted(src), slide_ms=1000)
    elif query == "q5":
        it = q5_traj_speed_fence(counted(src), fence)
    else:
        raise ValueError(query)
    for res in it:
        ts = getattr(res, "win_end", None)
        if ts is None and hasattr(res, "raw"):
            ts = res.raw.ts
        result_sink.record(ts)
        n_results += 1
    dt = time.time() - t0
    source_sink.close()
    result_sink.close()
    n_events = src.total_events
    return BenchmarkReport(
        query=query, events=n_events, duration_s=dt, eps=n_events / dt,
        results=n_results, source_metrics=source_sink.rows,
        sink_metrics=result_sink.rows,
    )


def mobility_query_runner(
    csv_path: str, queries: Iterable[str] = ("q1", "q2", "q3", "q4", "q5"),
    limit: Optional[int] = None,
) -> Dict[str, BenchmarkReport]:
    """CSV replay of MN_Q1..Q5 (MobilityQueryRunner.java:33-150):
    14-column schema, per-query timing + result counts."""
    reports = {}
    for q in queries:
        with open(csv_path) as f:
            lines = [ln for ln in f if ln.strip()]
        if limit:
            lines = lines[:limit]
        t0 = time.time()
        rows = mobility.mobility_runner(q, iter(lines))
        dt = time.time() - t0
        reports[q] = BenchmarkReport(
            query=q, events=len(lines), duration_s=dt,
            eps=len(lines) / dt if dt > 0 else 0.0,
            results=len(rows), source_metrics=[], sink_metrics=[],
        )
    return reports
