"""SNCB test/benchmark runners (``GeoFlink/sncb/tests/``).

- ``local_test_runner``: handcrafted fixture events with per-query
  expectations (LocalTestRunner.java:21-115);
- ``benchmark_runner``: seeded synthetic GPS load at a target EPS with
  per-second metrics (BenchmarkRunner.java:22-105 + SyntheticGpsSource);
- ``mobility_query_runner``: CSV replay of the MN_Q1..Q5 suite with an
  execution-stats report (MobilityQueryRunner.java:33-150).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional

from spatialflink_tpu.sncb import mobility
from spatialflink_tpu.sncb.common import GpsEvent, PolygonLoader, csv_to_gps_event
from spatialflink_tpu.sncb.metrics import MetricsSink
from spatialflink_tpu.sncb.queries import (
    q1_high_risk,
    q2_brake_monitor,
    q3_trajectory,
    q5_traj_speed_fence,
)
from spatialflink_tpu.streams.sources import SyntheticGpsSource

# Brussels-area bbox used by the synthetic benchmark source
# (BenchmarkRunner.java:35: lon 4.25..4.50, lat 50.75..50.95).
BRUSSELS_BBOX = (4.25, 4.50, 50.75, 50.95)


def sample_gps_events() -> List[GpsEvent]:
    """Fixture in the spirit of LocalTestRunner.sampleData
    (LocalTestRunner.java:86-115): events crafted to trip each query.
    Zones are this package's bundled resources."""
    t0 = 1_700_000_000_000
    evs = [
        # Inside high_risk "Schaerbeek yard approach" polygon (Q1 hits).
        GpsEvent("trainA", 4.375, 50.865, t0 + 0, 30.0, 5.0, 5.0),
        GpsEvent("trainA", 4.378, 50.867, t0 + 1000, 31.0, 5.1, 5.0),
        # Far from any zone.
        GpsEvent("trainB", 4.50, 50.90, t0 + 1500, 40.0, 5.0, 5.0),
        # Q2: trainC has FA variation 0.8 (>0.6) and FF variation 0.3 (<=0.5).
        GpsEvent("trainC", 4.45, 50.90, t0 + 2000, 20.0, 4.0, 5.0),
        GpsEvent("trainC", 4.45, 50.90, t0 + 2500, 21.0, 4.8, 5.3),
        # Q2 negative: trainD varies FF too much (0.9 > 0.5).
        GpsEvent("trainD", 4.46, 50.91, t0 + 2000, 20.0, 4.0, 5.0),
        GpsEvent("trainD", 4.46, 50.91, t0 + 2500, 21.0, 4.8, 5.9),
        # Inside maintenance zone (excluded from Q2).
        GpsEvent("trainE", 4.315, 50.810, t0 + 3000, 10.0, 1.0, 9.0),
        GpsEvent("trainE", 4.316, 50.811, t0 + 3500, 11.0, 9.0, 1.0),
        # Q5: inside fence with high speeds (avg>50, min>20).
        GpsEvent("trainF", 4.410, 50.850, t0 + 4000, 80.0, 5.0, 5.0),
        GpsEvent("trainF", 4.412, 50.852, t0 + 5000, 90.0, 5.0, 5.0),
        # Q5 negative: inside fence but slow.
        GpsEvent("trainG", 4.410, 50.855, t0 + 4000, 5.0, 5.0, 5.0),
        GpsEvent("trainG", 4.411, 50.856, t0 + 5000, 6.0, 5.0, 5.0),
        # Late straggler advancing watermarks past all windows.
        GpsEvent("trainB", 4.50, 50.90, t0 + 70_000, 40.0, 5.0, 5.0),
    ]
    return evs


def local_test_runner(verbose: bool = False) -> Dict[str, list]:
    """Run Q1/Q2/Q3/Q5 over the fixture; return per-query results."""
    risk = PolygonLoader.load_geojson_buffered("high_risk_zones.geojson", 20.0)
    maint = PolygonLoader.load_geojson_buffered("maintenance_areas.geojson", 0.0)
    fence = PolygonLoader.load_wkt_buffered("q5_fence.wkt", 20.0)

    out = {
        "q1": list(q1_high_risk(iter(sample_gps_events()), risk)),
        "q2": list(
            q2_brake_monitor(iter(sample_gps_events()), maint, slide_ms=500)
        ),
        "q3": list(q3_trajectory(iter(sample_gps_events()), slide_ms=1000)),
        "q5": list(q5_traj_speed_fence(iter(sample_gps_events()), fence)),
    }
    if verbose:
        for q, res in out.items():
            print(f"{q}: {len(res)} results")
            for r in res[:5]:
                print("  ", r)
    return out


@dataclass
class BenchmarkReport:
    query: str
    events: int
    duration_s: float
    eps: float
    results: int
    source_metrics: List[str]
    sink_metrics: List[str]


def benchmark_runner(
    query: str = "q1",
    target_eps: int = 20_000,
    duration_ms: int = 30_000,
    num_devices: int = 10,
    out_dir: Optional[str] = None,
) -> BenchmarkReport:
    """BenchmarkRunner.main analog: synthetic load through one query with
    1 s CSV metrics at source and sink (BenchmarkRunner.java:22-105)."""
    min_x, max_x, min_y, max_y = BRUSSELS_BBOX
    src = SyntheticGpsSource(
        min_x, max_x, min_y, max_y,
        target_eps=target_eps, duration_ms=duration_ms,
        num_devices=num_devices, seed=42,
        start_ts=1_700_000_000_000,
        make_event=lambda device_id, x, y, timestamp, speed: GpsEvent(
            device_id, x, y, timestamp, speed, 5.0, 5.0
        ),
    )
    source_sink = MetricsSink(
        "source", f"{out_dir}/source.csv" if out_dir else None
    )
    result_sink = MetricsSink(
        f"sink-{query}", f"{out_dir}/sink-{query}.csv" if out_dir else None
    )

    def counted(it):
        for e in it:
            source_sink.record(e.ts)
            yield e

    risk = PolygonLoader.load_geojson_buffered("high_risk_zones.geojson", 20.0)
    maint = PolygonLoader.load_geojson_buffered("maintenance_areas.geojson", 0.0)
    fence = PolygonLoader.load_wkt_buffered("q5_fence.wkt", 20.0)

    t0 = time.time()
    n_results = 0
    if query == "q1":
        it = q1_high_risk(counted(src), risk)
    elif query == "q2":
        it = q2_brake_monitor(counted(src), maint, slide_ms=1000)
    elif query == "q3":
        it = q3_trajectory(counted(src), slide_ms=1000)
    elif query == "q5":
        it = q5_traj_speed_fence(counted(src), fence)
    else:
        raise ValueError(query)
    for res in it:
        ts = getattr(res, "win_end", None)
        if ts is None and hasattr(res, "raw"):
            ts = res.raw.ts
        result_sink.record(ts)
        n_results += 1
    dt = time.time() - t0
    source_sink.close()
    result_sink.close()
    n_events = src.total_events
    return BenchmarkReport(
        query=query, events=n_events, duration_s=dt, eps=n_events / dt,
        results=n_results, source_metrics=source_sink.rows,
        sink_metrics=result_sink.rows,
    )


def mobility_query_runner(
    csv_path: str, queries: Iterable[str] = ("q1", "q2", "q3", "q4", "q5"),
    limit: Optional[int] = None,
) -> Dict[str, BenchmarkReport]:
    """CSV replay of MN_Q1..Q5 (MobilityQueryRunner.java:33-150):
    14-column schema, per-query timing + result counts."""
    reports = {}
    for q in queries:
        with open(csv_path) as f:
            lines = [ln for ln in f if ln.strip()]
        if limit:
            lines = lines[:limit]
        t0 = time.time()
        rows = mobility.mobility_runner(q, iter(lines))
        dt = time.time() - t0
        reports[q] = BenchmarkReport(
            query=q, events=len(lines), duration_s=dt,
            eps=len(lines) / dt if dt > 0 else 0.0,
            results=len(rows), source_metrics=[], sink_metrics=[],
        )
    return reports
