"""SNCB window aggregations — counterparts of ``GeoFlink/sncb/ops/``.

The reference implements these as Flink AggregateFunction + ProcessWindow
pairs (VariationAgg/VariationWindowFn, VarianceAgg, TrajectoryAgg,
TrajSpeedAgg — sncb/ops/*.java). Here each is a pure function over a
window's event list plus a mergeable accumulator form used by the
vectorized pane engine (mn/panes.py).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import List, Optional, Sequence

from spatialflink_tpu.sncb.common import GpsEvent


@dataclass
class VarOut:
    """VariationWindowFn.VarOut / VarianceWindowFn.VarOut."""

    device_id: str
    var_fa: float
    var_ff: float
    win_start: int
    win_end: int
    count: int = 0


@dataclass
class TrajOut:
    """TrajectoryWindowFn.TrajOut: per device-window WKT trajectory."""

    device_id: str
    wkt: str
    win_start: int
    win_end: int


@dataclass
class TrajSpeedOut:
    """TrajSpeedWindowFn.TrajSpeedOut."""

    device_id: str
    wkt: str
    avg_speed: float
    min_speed: float
    win_start: int
    win_end: int


def variation(events: Sequence[GpsEvent]) -> tuple:
    """max−min range of FA and FF over the window (VariationAgg.java:6-47);
    None values skipped; empty → -inf ranges like the untouched accumulator."""
    min_fa = min_ff = math.inf
    max_fa = max_ff = -math.inf
    for e in events:
        if e.fa is not None:
            min_fa = min(min_fa, e.fa)
            max_fa = max(max_fa, e.fa)
        if e.ff is not None:
            min_ff = min(min_ff, e.ff)
            max_ff = max(max_ff, e.ff)
    var_fa = max_fa - min_fa if max_fa >= min_fa else -math.inf
    var_ff = max_ff - min_ff if max_ff >= min_ff else -math.inf
    return var_fa, var_ff


def variance(events: Sequence[GpsEvent]) -> tuple:
    """Population variance of FA/FF via sum/sumSq (VarianceAgg.java:6-44).
    Parity detail: ``n`` counts every event (the reference increments n
    unconditionally), while sums skip None fields."""
    n = 0
    sum_fa = sum_sq_fa = sum_ff = sum_sq_ff = 0.0
    for e in events:
        if e.fa is not None:
            sum_fa += e.fa
            sum_sq_fa += e.fa * e.fa
        if e.ff is not None:
            sum_ff += e.ff
            sum_sq_ff += e.ff * e.ff
        n += 1
    return n, _variance(n, sum_fa, sum_sq_fa), _variance(n, sum_ff, sum_sq_ff)


def _variance(n: int, s: float, sq: float) -> float:
    """VarianceAgg.variance (VarianceAgg.java:38-43): 0 for n<=1, clamped."""
    if n <= 1:
        return 0.0
    mean = s / n
    return max(0.0, sq / n - mean * mean)


def trajectory_wkt(events: Sequence[GpsEvent]) -> str:
    """Window trajectory as WKT, points sorted by timestamp
    (TrajectoryAgg/TrajectoryWindowFn: POINT EMPTY / POINT / LINESTRING)."""
    pts = sorted(events, key=lambda e: e.ts)
    if not pts:
        return "POINT EMPTY"
    # float() wraps: event coords may be numpy scalars (SoA decode), and
    # numpy ≥2 would print np.float64(…) into the WKT (sfcheck
    # fstring-numpy).
    if len(pts) == 1:
        return f"POINT ({float(pts[0].lon):g} {float(pts[0].lat):g})"
    return ("LINESTRING ("
            + ", ".join(f"{float(e.lon):g} {float(e.lat):g}" for e in pts)
            + ")")


def traj_speed(events: Sequence[GpsEvent]) -> tuple:
    """(wkt, avg_speed, min_speed) — TrajSpeedAgg/TrajSpeedWindowFn:
    avg 0.0 and min NaN when no speeds present."""
    wkt = trajectory_wkt(events)
    speeds = [e.gps_speed for e in events if e.gps_speed is not None]
    if speeds:
        return wkt, sum(speeds) / len(speeds), min(speeds)
    return wkt, 0.0, math.nan
