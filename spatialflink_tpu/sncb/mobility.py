"""MobilityNebula-compatible queries MN_Q1–MN_Q5 (``GeoFlink/sncb/mobility/``)
and the MobilityRunner CLI.

These are the socket/CSV variants of the five SNCB queries with hardcoded
Brussels parameters and 2 s watermark lateness. They operate on raw WGS84
coordinates with no CRS transform, exactly like the reference (including
its quirk of treating the MN_Q1 ``tol_meters`` argument as a *degree*
radius — MN_Q1.java:36-79 passes it straight into the range query; the
instrumented variants in ``mn/`` apply the ×111320 degree→meter fix,
InstrumentedMN_Q1.java:176-190).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Iterator, List, Optional, Sequence

import numpy as np

from spatialflink_tpu.sncb.common import GpsEvent, csv_to_gps_event
from spatialflink_tpu.sncb.ops import (
    TrajOut,
    TrajSpeedOut,
    VarOut,
    traj_speed,
    trajectory_wkt,
    variance,
)
from spatialflink_tpu.sncb.queries import keyed_windows, _windows

_MN_LATENESS_MS = 2_000  # Time.seconds(2) in every MN_Q*


@dataclass
class CountOut:
    """MN_Q1.CountOut (MN_Q1.java:37-46)."""

    start: int
    end: int
    cnt: int


def mn_q1(
    events: Iterable[GpsEvent],
    lon: float = 4.3658,
    lat: float = 50.6456,
    tol: float = 2.0,
    window_s: int = 5,
) -> Iterator[CountOut]:
    """MN_Q1: count points within ``tol`` of the query point per 5 s
    tumbling window (MN_Q1.java:36-79). ``tol`` is in the stream's
    coordinate units — degrees, reproducing the reference's
    tolMeters-as-degrees behavior. Defaults are MobilityRunner's
    (MobilityRunner.java q1 case: 4.3658, 50.6456, 2.0)."""
    for win in _windows(events, window_s * 1000, window_s * 1000, _MN_LATENESS_MS):
        if not win.events:
            continue
        xy = np.array([[e.lon, e.lat] for e in win.events])
        d = np.hypot(xy[:, 0] - lon, xy[:, 1] - lat)
        yield CountOut(win.start, win.end, int((d <= tol).sum()))


def mn_q2(
    events: Iterable[GpsEvent],
    window_s: float = 10.0,
    slide_ms: int = 200,
) -> Iterator[VarOut]:
    """MN_Q2: global ("ALL"-keyed) FA/FF variance over 10s/200ms sliding
    windows, excluding the 4.0–4.6 × 50.0–50.8 degree box
    (MN_Q2.java: exclude polygon + keyBy "ALL" + VarianceAgg)."""
    filtered = (
        e for e in events
        if not (4.0 <= e.lon <= 4.6 and 50.0 <= e.lat <= 50.8)
    )
    for _, start, end, evs in keyed_windows(
        filtered, int(window_s * 1000), slide_ms, key_fn=lambda e: "ALL",
        lateness_ms=_MN_LATENESS_MS,
    ):
        n, var_fa, var_ff = variance(evs)
        yield VarOut("ALL", var_fa, var_ff, start, end, n)


def mn_q3(
    events: Iterable[GpsEvent], window_s: float = 3.0, slide_s: float = 1.0
) -> Iterator[TrajOut]:
    """MN_Q3: global 3s/1s sliding-window trajectory (MN_Q3.java)."""
    for _, start, end, evs in keyed_windows(
        events, int(window_s * 1000), int(slide_s * 1000),
        key_fn=lambda e: "ALL", lateness_ms=_MN_LATENESS_MS,
    ):
        yield TrajOut("ALL", trajectory_wkt(evs), start, end)


def mn_q4(
    events: Iterable[GpsEvent],
    min_lon: float, min_lat: float, max_lon: float, max_lat: float,
    t_min: int, t_max: int,
    window_s: float = 20.0, slide_s: float = 2.0,
) -> Iterator[TrajOut]:
    """MN_Q4: bbox/time filter → global 20s/2s trajectory (MN_Q4.java)."""
    filtered = (
        e for e in events
        if min_lon <= e.lon <= max_lon and min_lat <= e.lat <= max_lat
        and t_min <= e.ts <= t_max
    )
    yield from mn_q3(filtered, window_s, slide_s)


def mn_q5(
    events: Iterable[GpsEvent],
    poly_lonlat: Sequence[Sequence[float]],
    tol: float,
    window_s: float = 20.0, slide_s: float = 2.0,
    avg_below: float = 100.0, min_below: float = 20.0,
) -> Iterator[TrajSpeedOut]:
    """MN_Q5: degree-space buffered geofence include → per-device 20s/2s
    trajectory+speed, filter avg < 100 ∨ min < 20 (MN_Q5.java — including
    the degree-units ``buffer(tolMeters)`` quirk: containment = inside the
    polygon or within ``tol`` coordinate units of its boundary)."""
    from spatialflink_tpu.sncb.common import BufferedZone

    # Degree-space buffered fence (rings in lon/lat, buffer in degrees —
    # the reference's unit quirk).
    fence = BufferedZone(rings_metric=[np.asarray(poly_lonlat, float)], buffer_m=tol)

    def in_fence(evs: List[GpsEvent]) -> List[GpsEvent]:
        if not evs:
            return []
        xy = np.array([[e.lon, e.lat] for e in evs])
        keep = fence.contains_batch(xy)
        return [e for e, k in zip(evs, keep) if k]

    def fenced():
        buf: List[GpsEvent] = []
        for e in events:
            buf.append(e)
            if len(buf) >= 8192:
                yield from in_fence(buf)
                buf = []
        yield from in_fence(buf)

    for dev, start, end, evs in keyed_windows(
        fenced(), int(window_s * 1000), int(slide_s * 1000),
        key_fn=lambda e: e.device_id, lateness_ms=_MN_LATENESS_MS,
    ):
        wkt, avg_speed, min_speed = traj_speed(evs)
        if avg_speed < avg_below or (min_speed == min_speed and min_speed < min_below):
            yield TrajSpeedOut(dev, wkt, avg_speed, min_speed, start, end)


# Class-style aliases.
class MN_Q1:
    CountOut = CountOut
    build = staticmethod(mn_q1)


class MN_Q2:
    build = staticmethod(mn_q2)


class MN_Q3:
    build = staticmethod(mn_q3)


class MN_Q4:
    build = staticmethod(mn_q4)


class MN_Q5:
    build = staticmethod(mn_q5)


# Default Q5 fence used by MobilityRunner (a central-Brussels quadrilateral).
Q5_FENCE = [[4.405, 50.846], [4.418, 50.846], [4.418, 50.858], [4.405, 50.858]]


def mobility_runner(
    query: str,
    source: Iterable[str],
    out_path: Optional[str] = None,
    delimiter: str = ",",
    collect: bool = True,
):
    """MobilityRunner.main analog (MobilityRunner.java:14-73): CSV lines →
    GpsEvents → query q1..q5 → CSV rows (returned, and written if
    ``out_path`` given). ``collect=False`` streams to the file only and
    returns the row count — O(1) memory for unbounded socket feeds."""
    events = (csv_to_gps_event(ln, delimiter) for ln in source if ln.strip())
    q = query.lower()
    if q == "q1":
        rows = (f"{o.start},{o.end},{o.cnt}" for o in mn_q1(events, 4.3658, 50.6456, 2.0))
    elif q == "q2":
        rows = (
            f"{o.win_start},{o.win_end},{o.var_fa},{o.var_ff},{o.count}"
            for o in mn_q2(events)
        )
    elif q == "q3":
        rows = (f"{o.win_start},{o.win_end},{o.device_id},{o.wkt}" for o in mn_q3(events))
    elif q == "q4":
        rows = (
            f"{o.win_start},{o.win_end},{o.device_id},{o.wkt}"
            for o in mn_q4(events, 4.0, 50.0, 5.0, 51.0, 0, 2**62)
        )
    elif q == "q5":
        rows = (
            f"{o.win_start},{o.win_end},{o.device_id},{o.avg_speed},{o.min_speed},{o.wkt}"
            for o in mn_q5(events, Q5_FENCE, 0.001)
        )
    else:
        raise ValueError(f"unknown query {query!r}")

    sink = open(out_path, "w") if out_path else None
    collected = [] if collect else None
    n = 0
    try:
        for row in rows:
            n += 1
            if collected is not None:
                collected.append(row)
            if sink:
                sink.write(row + "\n")
    finally:
        if sink:
            sink.close()
    return collected if collected is not None else n


def main(argv=None):
    """MobilityRunner.main CLI parity (MobilityRunner.java:14-73):
    ``python -m spatialflink_tpu.sncb.mobility [q1..q5] [host] [port] [outDir]``
    — socket text stream → CSV parse → query → per-query CSV file.

    Documented deviation: defaults are host ``localhost`` and outDir
    ``Output`` (the reference defaults to ``host.docker.internal`` and
    ``/workspace/Output`` — container-specific paths that don't apply
    here)."""
    import os
    import sys

    from spatialflink_tpu.streams.sources import socket_source

    args = list(sys.argv[1:] if argv is None else argv)
    q = (args[0] if args else "q1").lower()
    host = args[1] if len(args) > 1 else "localhost"
    port = int(args[2]) if len(args) > 2 else 32323
    out_dir = args[3] if len(args) > 3 else "Output"
    os.makedirs(out_dir, exist_ok=True)
    lines = socket_source(host, port, parser=lambda s: s)
    out_path = os.path.join(out_dir, f"output_query{q[1:]}.csv")
    n = mobility_runner(q, lines, out_path=out_path, collect=False)
    print(f"{q}: {n} rows -> {out_path}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
