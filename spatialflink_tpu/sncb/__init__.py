from spatialflink_tpu.sncb.common import (  # noqa: F401
    GpsEvent,
    EnrichedEvent,
    CRSUtils,
    BufferedZone,
    PolygonLoader,
    csv_to_gps_event,
    gps_events_to_points,
)
