"""SNCB railway domain: event types, CRS enrichment, zone loading.

Counterparts of ``GeoFlink/sncb/common/``: GpsEvent (GpsEvent.java:3-23),
EnrichedEvent (EnrichedEvent.java:5-17), CRSUtils (CRSUtils.java:19-56),
CSVToGpsEventMapFunction (CSVToGpsEventMapFunction.java:13-31),
PolygonLoader (PolygonLoader.java:24-138) — plus the ``MnGpsEvent`` type the
reference's com.mn layer imports but never defines (see SURVEY.md §2.5).

Buffered zones: the reference buffers metric polygons by N meters with JTS
``buffer()`` and tests PreparedGeometry containment. Geometric buffering is
unnecessary for containment semantics — a point is inside
``poly.buffer(r)`` iff it is inside ``poly`` or within ``r`` of its
boundary — so ``BufferedZone`` stores the metric polygon + radius and the
batched containment test runs as one TPU kernel (ops/polygon.py).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np

from spatialflink_tpu.models.objects import Point, Polygon
from spatialflink_tpu.ops.polygon import pack_rings
from spatialflink_tpu.streams.serde import parse_wkt
from spatialflink_tpu.utils.crs import wgs84_to_epsg25831

RESOURCE_DIR = os.path.join(os.path.dirname(__file__), "resources")


@dataclass
class GpsEvent:
    """deviceId, lon, lat, ts(ms), gpsSpeed(m/s), brake pressures FA/FF (bar)."""

    device_id: str = ""
    lon: float = 0.0
    lat: float = 0.0
    ts: int = 0
    gps_speed: Optional[float] = None
    fa: Optional[float] = None
    ff: Optional[float] = None

    # Window assembler compatibility.
    @property
    def timestamp(self) -> int:
        return self.ts

    @property
    def obj_id(self) -> str:
        return self.device_id


# The type com.mn imports but the reference never defines
# (InstrumentedMN_Q1.java:3; usage at :67-72,128-133).
MnGpsEvent = GpsEvent


@dataclass
class EnrichedEvent:
    """raw + WGS84 coords + metric (EPSG:25831) coords."""

    raw: GpsEvent
    x_wgs84: float = 0.0
    y_wgs84: float = 0.0
    x_metric: float = 0.0
    y_metric: float = 0.0

    @property
    def timestamp(self) -> int:
        return self.raw.ts


def csv_to_gps_event(line: str, delimiter: str = ",") -> GpsEvent:
    """14-column CSV schema: ts(0, already ms in the data replay), deviceId(1),
    PCFA(3), PCFF(4), speed(11), lat(12), lon(13)
    (CSVToGpsEventMapFunction.java:13-31; unparseable numerics → 0 like the
    reference's catch-all)."""
    f = line.split(delimiter)

    def flt(i):
        try:
            return float(f[i].strip())
        except (ValueError, IndexError):
            return 0.0

    def lng(i):
        try:
            return int(f[i].strip())
        except (ValueError, IndexError):
            return 0

    return GpsEvent(
        device_id=f[1].strip() if len(f) > 1 else "",
        lon=flt(13),
        lat=flt(12),
        ts=lng(0),
        gps_speed=flt(11),
        fa=flt(3),
        ff=flt(4),
    )


class CRSUtils:
    """EPSG:4326 → EPSG:25831 enrichment (CRSUtils.java:19-56)."""

    @staticmethod
    def to_metric(lon, lat):
        return wgs84_to_epsg25831(lon, lat)

    @staticmethod
    def enrich(ev: GpsEvent) -> EnrichedEvent:
        e, n = wgs84_to_epsg25831(ev.lon, ev.lat)
        return EnrichedEvent(
            raw=ev, x_wgs84=ev.lon, y_wgs84=ev.lat,
            x_metric=float(e), y_metric=float(n),
        )

    @staticmethod
    def enrich_batch(events: Sequence[GpsEvent]) -> np.ndarray:
        """(N, 2) metric coordinates for a batch (vectorized transform)."""
        lon = np.array([e.lon for e in events])
        lat = np.array([e.lat for e in events])
        east, north = wgs84_to_epsg25831(lon, lat)
        return np.stack([east, north], axis=1)


@dataclass
class BufferedZone:
    """A metric-CRS polygon with a buffer radius.

    Containment test (≡ PreparedGeometry.contains over the buffered
    geometry): inside the polygon OR within ``buffer_m`` of its boundary.
    ``contains_batch`` runs as one kernel over a metric point batch.
    """

    rings_metric: List[np.ndarray]
    buffer_m: float = 0.0
    name: str = ""

    def packed(self, pad_to=None):
        return pack_rings(self.rings_metric, pad_to=pad_to)

    def contains_batch(self, xy_metric: np.ndarray) -> np.ndarray:
        return contains_any_zone([self], xy_metric)


def _zone_hit_kernel(pts, verts, evs, bufs):
    import jax
    import jax.numpy as jnp

    from spatialflink_tpu.ops.polygon import point_polygon_distance

    hit = jax.vmap(
        lambda vz, ez, bz: point_polygon_distance(pts, vz, ez) <= bz
    )(verts, evs, bufs)
    return jnp.any(hit, axis=0)


_zone_hit_jit = None


def contains_any_zone(zones: Sequence[BufferedZone], xy_metric: np.ndarray) -> np.ndarray:
    """(N,) bool: point within any buffered zone — one jitted program
    (compiled per point-bucket/zone-shape, cached)."""
    global _zone_hit_jit
    import jax
    import jax.numpy as jnp

    from spatialflink_tpu.utils.padding import next_bucket, pad_to_bucket

    if not zones or not len(xy_metric):
        return np.zeros(len(xy_metric), bool)
    if _zone_hit_jit is None:
        _zone_hit_jit = jax.jit(_zone_hit_kernel)
    vmax = max(sum(len(r) + 1 for r in z.rings_metric) for z in zones)
    v = next_bucket(vmax, minimum=8)
    verts = np.zeros((len(zones), v, 2))
    evs = np.zeros((len(zones), v - 1), bool)
    bufs = np.zeros(len(zones))
    for i, z in enumerate(zones):
        pv, pe = z.packed(pad_to=v)
        verts[i] = pv
        evs[i] = pe
        bufs[i] = z.buffer_m
    n = len(xy_metric)
    # Pad the point batch to a bucket so window-size jitter reuses programs;
    # padded lanes land far outside every zone (coordinates 1e12 m).
    b = next_bucket(n)
    pts = pad_to_bucket(np.asarray(xy_metric, float), b, fill=1e12)
    hit = _zone_hit_jit(
        jnp.asarray(pts), jnp.asarray(verts), jnp.asarray(evs), jnp.asarray(bufs)
    )
    return np.asarray(hit)[:n]


def contains_any_zone_np(zones: Sequence[BufferedZone],
                         xy_metric: np.ndarray) -> np.ndarray:
    """Numpy twin of :func:`contains_any_zone` — the fallback route the
    composed-DAG nodes fail over to when the device path dies (dag.py's
    per-node ladder). Same semantics: inside any zone's polygon OR
    within its ``buffer_m`` of the boundary; results match the device
    kernel to float ulps (tests/test_dag.py pins set parity)."""
    if not zones or not len(xy_metric):
        return np.zeros(len(xy_metric), bool)
    pts = np.asarray(xy_metric, np.float64)
    hit = np.zeros(len(pts), bool)
    for z in zones:
        verts, ev = z.packed()
        x, y = pts[:, 0:1], pts[:, 1:2]
        x1, y1 = verts[:-1, 0][None, :], verts[:-1, 1][None, :]
        x2, y2 = verts[1:, 0][None, :], verts[1:, 1][None, :]
        # Even-odd ray cast (ops/polygon.py:points_in_polygon, host form).
        spans = (y1 > y) != (y2 > y)
        dy = y2 - y1
        t = np.where(dy != 0, (y - y1) / np.where(dy != 0, dy, 1.0), 0.0)
        inside = (
            np.sum(spans & (x < x1 + t * (x2 - x1)) & ev[None, :], axis=1)
            % 2 == 1
        )
        # Min distance to any valid edge (segment projection clamp).
        exy = np.stack([x2 - x1, y2 - y1], axis=-1)[0]  # (E, 2)
        p1 = verts[:-1]  # (E, 2)
        seg_len2 = np.maximum(np.sum(exy * exy, axis=-1), 1e-300)
        rel = pts[:, None, :] - p1[None, :, :]  # (N, E, 2)
        tt = np.clip(np.sum(rel * exy[None, :, :], axis=-1)
                     / seg_len2[None, :], 0.0, 1.0)
        near = p1[None, :, :] + tt[..., None] * exy[None, :, :]
        d2 = np.sum((pts[:, None, :] - near) ** 2, axis=-1)
        d2 = np.where(ev[None, :], d2, np.inf)
        hit |= inside | (np.sqrt(np.min(d2, axis=1)) <= z.buffer_m)
    return hit


class PolygonLoader:
    """Load GeoJSON FeatureCollections / WKT files, reproject rings to
    EPSG:25831, attach a buffer radius (PolygonLoader.java:24-138)."""

    @staticmethod
    def _reproject_rings(rings: Sequence[np.ndarray]) -> List[np.ndarray]:
        out = []
        for r in rings:
            r = np.asarray(r, float)
            e, n = wgs84_to_epsg25831(r[:, 0], r[:, 1])
            out.append(np.stack([e, n], axis=1))
        return out

    @classmethod
    def load_geojson_buffered(cls, path: str, buffer_m: float) -> List[BufferedZone]:
        with open(cls._resolve(path)) as f:
            obj = json.load(f)
        zones: List[BufferedZone] = []
        feats = (
            obj["features"] if obj.get("type") == "FeatureCollection"
            else [obj] if obj.get("type") == "Feature" else [{"geometry": obj}]
        )
        for feat in feats:
            geom = feat.get("geometry", feat)
            name = (feat.get("properties") or {}).get("name", "")
            gtype = geom.get("type")
            if gtype == "Polygon":
                ring_sets = [geom["coordinates"]]
            elif gtype == "MultiPolygon":
                ring_sets = geom["coordinates"]
            else:
                continue
            for rings in ring_sets:
                zones.append(
                    BufferedZone(
                        rings_metric=cls._reproject_rings(
                            [np.asarray(r, float) for r in rings]
                        ),
                        buffer_m=buffer_m,
                        name=name,
                    )
                )
        return zones

    @classmethod
    def load_wkt_buffered(cls, path: str, buffer_m: float) -> List[BufferedZone]:
        with open(cls._resolve(path)) as f:
            text = f.read().strip()
        obj = parse_wkt(text)
        polys = obj.polygons() if hasattr(obj, "polygons") else [obj]
        return [
            BufferedZone(
                rings_metric=cls._reproject_rings(p.rings), buffer_m=buffer_m
            )
            for p in polys
        ]

    @staticmethod
    def _resolve(path: str) -> str:
        """Accept absolute paths or names of bundled resources."""
        if os.path.exists(path):
            return path
        cand = os.path.join(RESOURCE_DIR, path)
        if os.path.exists(cand):
            return cand
        raise FileNotFoundError(path)


def gps_events_to_points(events: Sequence[GpsEvent]) -> List[Point]:
    """GpsEvent → spatial Point on WGS84 coords (the per-query map functions
    in Q1..Q5, e.g. Q1_HighRisk.java:39-49)."""
    return [
        Point(obj_id=e.device_id, timestamp=e.ts, x=e.lon, y=e.lat) for e in events
    ]
