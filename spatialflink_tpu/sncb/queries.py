"""SNCB domain queries Q1–Q5 (``GeoFlink/sncb/queries/``).

Each ``build(events, ...)`` consumes an iterable of GpsEvents and yields
result records, mirroring the reference's ``Q*.build(env, events, …)``
DataStream pipelines. All use event-time windows with 5 s
bounded-out-of-orderness (each reference query assigns
``BoundedOutOfOrdernessTimestampExtractor(Time.seconds(5))``).

CRS note: the reference mixes metric (EPSG:25831-buffered) polygons with
raw lon/lat points inside a single degree-based grid (Q1_HighRisk.java:52-78
feeds metric PreparedGeometry rings into a WGS84 UniformGrid) — geometrically
inconsistent. This build does what the query *means*: points are enriched
to metric coordinates (vectorized UTM on device) and all zone containment /
proximity tests run in meters.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, Iterator, List, Sequence

import numpy as np

from spatialflink_tpu.sncb.common import (
    BufferedZone,
    CRSUtils,
    EnrichedEvent,
    GpsEvent,
    contains_any_zone,
)
from spatialflink_tpu.sncb.ops import (
    TrajOut,
    TrajSpeedOut,
    VarOut,
    traj_speed,
    trajectory_wkt,
    variation,
)
from spatialflink_tpu.streams.windows import (
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
    WindowAssembler,
)

_LATENESS_MS = 5_000  # Time.seconds(5) in every Q*.build


def _windows(events, size_ms, slide_ms, lateness_ms=_LATENESS_MS):
    asm = WindowAssembler(
        SlidingEventTimeWindows(size_ms, slide_ms),
        timestamp_fn=lambda e: e.timestamp,
        max_out_of_orderness_ms=lateness_ms,
    )
    yield from asm.stream(events)


def keyed_windows(events, size_ms, slide_ms, key_fn, lateness_ms=_LATENESS_MS):
    """keyBy(key).window(...) analog: per fired window, per key present."""
    for win in _windows(events, size_ms, slide_ms, lateness_ms):
        groups: Dict[str, List] = {}
        for e in win.events:
            groups.setdefault(key_fn(e), []).append(e)
        for key in sorted(groups):
            yield key, win.start, win.end, groups[key]


def _zone_filter(events: Sequence[GpsEvent], zones, keep_inside: bool,
                 backend: str = "device") -> List[GpsEvent]:
    """Batched zone containment filter over metric coordinates.
    ``backend="numpy"`` routes the host twin (contains_any_zone_np) —
    the per-node failover route of the composed DAG (dag.py)."""
    if not events:
        return []
    from spatialflink_tpu.ops.counters import counters

    if counters.enabled:
        # Each event is distance/containment-tested against every zone —
        # the distCompCounter analog for the SNCB zone kernels.
        counters.record_candidates(len(events), len(events) * len(zones))
    xy = CRSUtils.enrich_batch(events)
    if backend == "numpy":
        from spatialflink_tpu.sncb.common import contains_any_zone_np

        inside = contains_any_zone_np(zones, xy)
    else:
        inside = contains_any_zone(zones, xy)
    keep = inside if keep_inside else ~inside
    return [e for e, k in zip(events, keep) if k]


def q1_high_risk(
    events: Iterable[GpsEvent],
    high_risk_zones: Sequence[BufferedZone],
    radius_m: float = 20.0,
    window_s: int = 10,
) -> Iterator[EnrichedEvent]:
    """Q1: events near buffered high-risk polygons, re-emitted per 10 s
    tumbling window (Q1_HighRisk.java:30-105; the range query at :73-78).

    ``radius_m`` is the proximity radius in meters (the reference's
    0.001-degree radius against metric polygons is the CRS inconsistency
    described in the module docstring; 0.001° ≈ tens of meters at Brussels
    latitudes, hence the 20 m default).
    """
    zones = buffer_q1_zones(high_risk_zones, radius_m)
    for win in _windows(events, window_s * 1000, window_s * 1000):
        yield from q1_window(win.events, zones)


def q2_brake_monitor(
    events: Iterable[GpsEvent],
    maintenance_zones: Sequence[BufferedZone],
    window_s: float = 10.0,
    slide_ms: int = 10,
    var_fa_min: float = 0.6,
    var_ff_max: float = 0.5,
) -> Iterator[VarOut]:
    """Q2: exclude maintenance areas → per-device 10s/10ms sliding windows →
    brake-pressure variation filter varFA > 0.6 ∧ varFF ≤ 0.5
    (Q2_BrakeMonitor.java:25-103).

    Parity note: the reference's Point→EnrichedEvent remap drops the FA/FF
    fields before VariationAgg reads them (Q2_BrakeMonitor.java maps a fresh
    GpsEvent carrying only id/ts/lon/lat), so upstream every window computes
    variation of nothing. This build keeps the fields — the behavior the
    query obviously intends.
    """
    filtered = _batchwise_zone_exclude(events, maintenance_zones)
    for dev, start, end, evs in keyed_windows(
        filtered, int(window_s * 1000), slide_ms, key_fn=lambda e: e.device_id
    ):
        var_fa, var_ff = variation(evs)
        if var_fa > var_fa_min and var_ff <= var_ff_max:
            yield VarOut(dev, var_fa, var_ff, start, end, len(evs))


def _batchwise_zone_exclude(events, zones, chunk=8192):
    """Stream-preserving batched exclude filter (PolygonExcludeFn analog)."""
    buf: List[GpsEvent] = []
    for e in events:
        buf.append(e)
        if len(buf) >= chunk:
            yield from _zone_filter(buf, zones, keep_inside=False)
            buf = []
    if buf:
        yield from _zone_filter(buf, zones, keep_inside=False)


def _batchwise_zone_include(events, zones, chunk=8192):
    buf: List[GpsEvent] = []
    for e in events:
        buf.append(e)
        if len(buf) >= chunk:
            yield from _zone_filter(buf, zones, keep_inside=True)
            buf = []
    if buf:
        yield from _zone_filter(buf, zones, keep_inside=True)


def q3_trajectory(
    events: Iterable[GpsEvent], window_s: float = 10.0, slide_ms: int = 10
) -> Iterator[TrajOut]:
    """Q3: per-device sliding-window trajectory WKT
    (Q3_Trajectory.java:17-58)."""
    for dev, start, end, evs in keyed_windows(
        events, int(window_s * 1000), slide_ms, key_fn=lambda e: e.device_id
    ):
        yield TrajOut(dev, trajectory_wkt(evs), start, end)


def q4_trajectory_restricted(
    events: Iterable[GpsEvent],
    min_lon: float, max_lon: float, min_lat: float, max_lat: float,
    t_min: int, t_max: int,
    window_s: float = 10.0, slide_ms: int = 10,
) -> Iterator[TrajOut]:
    """Q4: Q3 with bbox/time-range predicate pushdown
    (Q4_TrajectoryRestricted.java:18-70)."""
    filtered = (
        e for e in events
        if min_lon <= e.lon <= max_lon and min_lat <= e.lat <= max_lat
        and t_min <= e.ts <= t_max
    )
    yield from q3_trajectory(filtered, window_s, slide_ms)


def q5_traj_speed_fence(
    events: Iterable[GpsEvent],
    fence_zones: Sequence[BufferedZone],
    avg_threshold: float = 50.0,
    min_threshold: float = 20.0,
    window_s: float = 45.0,
    slide_s: float = 5.0,
) -> Iterator[TrajSpeedOut]:
    """Q5: geofence include → per-device 45s/5s windows → trajectory + speed
    stats, threshold filter avg > a ∨ min > m (Q5_TrajAndSpeedFence.java:25-104)."""
    fenced = _batchwise_zone_include(events, fence_zones)
    for dev, start, end, evs in keyed_windows(
        fenced, int(window_s * 1000), int(slide_s * 1000),
        key_fn=lambda e: e.device_id,
    ):
        wkt, avg_speed, min_speed = traj_speed(evs)
        if avg_speed > avg_threshold or (
            min_speed == min_speed and min_speed > min_threshold
        ):
            yield TrajSpeedOut(dev, wkt, avg_speed, min_speed, start, end)


def q2_brake_monitor_batch(
    events: Sequence[GpsEvent],
    maintenance_zones: Sequence[BufferedZone],
    window_s: float = 10.0,
    slide_ms: int = 10,
    var_fa_min: float = 0.6,
    var_ff_max: float = 0.5,
) -> List[VarOut]:
    """Vectorized replay of Q2 over a bounded stream: identical outputs to
    ``q2_brake_monitor`` but computed via pane decomposition
    (streams/panes.py) — O(events) instead of O(events × overlap). This is
    what makes the reference's 10s/10ms window config (1000× overlap)
    tractable at benchmark rates.
    """
    from spatialflink_tpu.streams.panes import sliding_aggregate
    from spatialflink_tpu.utils.interning import Interner

    events = list(events)
    filtered = _zone_filter(events, maintenance_zones, keep_inside=False)
    if not filtered:
        return []
    interner = Interner()
    key = interner.intern_many(e.device_id for e in filtered)
    ts = np.array([e.ts for e in filtered], np.int64)
    fa = np.array([e.fa if e.fa is not None else np.nan for e in filtered])
    ff = np.array([e.ff if e.ff is not None else np.nan for e in filtered])
    # None fields are skipped by the reference accumulator: use ±inf-neutral
    # values (NaN-safe min/max via masking).
    fa_min_in = np.where(np.isnan(fa), np.inf, fa)
    fa_max_in = np.where(np.isnan(fa), -np.inf, fa)
    ff_min_in = np.where(np.isnan(ff), np.inf, ff)
    ff_max_in = np.where(np.isnan(ff), -np.inf, ff)

    win = sliding_aggregate(
        ts, key, interner.num_segments,
        int(window_s * 1000), slide_ms,
        min_fields={"fa_min": fa_min_in, "ff_min": ff_min_in},
        max_fields={"fa_max": fa_max_in, "ff_max": ff_max_in},
    )
    var_fa = win.maxs["fa_max"] - win.mins["fa_min"]
    var_ff = win.maxs["ff_max"] - win.mins["ff_min"]
    hit = (win.count > 0) & (var_fa > var_fa_min) & (var_ff <= var_ff_max)
    out: List[VarOut] = []
    size_ms = int(window_s * 1000)
    for w, k in zip(*np.nonzero(hit)):
        out.append(
            VarOut(
                interner.lookup(int(k)), float(var_fa[w, k]), float(var_ff[w, k]),
                int(win.starts[w]), int(win.starts[w]) + size_ms,
                int(win.count[w, k]),
            )
        )
    out.sort(key=lambda o: (o.win_start, o.device_id))
    return out


# ---------------------------------------------------------------------------
# Window-scoped query cores — one fired window's events in, result
# records out. These are the node bodies of the composed SNCB DAG
# (spatialflink_tpu/dag.py): the DAG shares ONE window clock across all
# queries (amortizing ingest/interning — the deliberate deviation from
# the per-query window configs above, PARITY.md "Composed dataflow"),
# so each query's per-window core is factored out here. ``backend``
# routes the zone kernels: "device" (contains_any_zone) or "numpy"
# (contains_any_zone_np) — the per-node failover route; results match
# to float ulps.


def _by_device(events: Sequence[GpsEvent]) -> Dict[str, List[GpsEvent]]:
    groups: Dict[str, List[GpsEvent]] = {}
    for e in events:
        groups.setdefault(e.device_id, []).append(e)
    return groups


def buffer_q1_zones(high_risk_zones: Sequence[BufferedZone],
                    radius_m: float = 20.0) -> List[BufferedZone]:
    """Q1's proximity widening (build once, not per window)."""
    return [
        BufferedZone(z.rings_metric, z.buffer_m + radius_m, z.name)
        for z in high_risk_zones
    ]


def q1_window(events: Sequence[GpsEvent],
              zones: Sequence[BufferedZone],
              backend: str = "device") -> List[EnrichedEvent]:
    """Q1 core: events near the (pre-buffered) high-risk zones,
    enriched to metric coords (Q1_HighRisk.java:73-78)."""
    return [
        CRSUtils.enrich(e)
        for e in _zone_filter(events, zones, keep_inside=True,
                              backend=backend)
    ]


def q2_window(events: Sequence[GpsEvent],
              maintenance_zones: Sequence[BufferedZone],
              start: int, end: int,
              var_fa_min: float = 0.6, var_ff_max: float = 0.5,
              backend: str = "device") -> List[VarOut]:
    """Q2 core: maintenance-zone exclude → per-device brake-pressure
    variation → varFA > a ∧ varFF ≤ b filter (Q2_BrakeMonitor.java)."""
    kept = _zone_filter(events, maintenance_zones, keep_inside=False,
                        backend=backend)
    out: List[VarOut] = []
    for dev in sorted(groups := _by_device(kept)):
        evs = groups[dev]
        var_fa, var_ff = variation(evs)
        if var_fa > var_fa_min and var_ff <= var_ff_max:
            out.append(VarOut(dev, var_fa, var_ff, start, end, len(evs)))
    return out


def q3_window(events: Sequence[GpsEvent],
              start: int, end: int) -> List[TrajOut]:
    """Q3 core: per-device window trajectory WKT (Q3_Trajectory.java)."""
    groups = _by_device(events)
    return [
        TrajOut(dev, trajectory_wkt(groups[dev]), start, end)
        for dev in sorted(groups)
    ]


def q4_window(events: Sequence[GpsEvent], start: int, end: int,
              min_lon: float, max_lon: float,
              min_lat: float, max_lat: float,
              t_min: int, t_max: int) -> List[TrajOut]:
    """Q4 core: Q3 with bbox/time-range predicate pushdown
    (Q4_TrajectoryRestricted.java)."""
    return q3_window(
        [e for e in events
         if min_lon <= e.lon <= max_lon and min_lat <= e.lat <= max_lat
         and t_min <= e.ts <= t_max],
        start, end,
    )


def q5_window(events: Sequence[GpsEvent],
              fence_zones: Sequence[BufferedZone],
              start: int, end: int,
              avg_threshold: float = 50.0, min_threshold: float = 20.0,
              backend: str = "device") -> List[TrajSpeedOut]:
    """Q5 core: geofence include → per-device trajectory + speed stats,
    avg > a ∨ min > m filter (Q5_TrajAndSpeedFence.java)."""
    fenced = _zone_filter(events, fence_zones, keep_inside=True,
                          backend=backend)
    out: List[TrajSpeedOut] = []
    for dev in sorted(groups := _by_device(fenced)):
        wkt, avg_speed, min_speed = traj_speed(groups[dev])
        if avg_speed > avg_threshold or (
            min_speed == min_speed and min_speed > min_threshold
        ):
            out.append(
                TrajSpeedOut(dev, wkt, avg_speed, min_speed, start, end)
            )
    return out


# Class-style aliases mirroring the reference entry points.
class Q1_HighRisk:
    build = staticmethod(q1_high_risk)


class Q2_BrakeMonitor:
    build = staticmethod(q2_brake_monitor)


class Q3_Trajectory:
    build = staticmethod(q3_trajectory)


class Q4_TrajectoryRestricted:
    build = staticmethod(q4_trajectory_restricted)


class Q5_TrajAndSpeedFence:
    build = staticmethod(q5_traj_speed_fence)
