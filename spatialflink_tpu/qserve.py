"""qserve — multi-tenant continuous-query serving.

GeoFlink's execution model is one spatial query per Flink job (CIKM 2020
§IV; the IEEE Access 2022 evaluation never goes beyond per-operator
grids). The production shape of the ROADMAP north star is the opposite:
THOUSANDS of standing range/kNN queries — registered and unregistered by
many tenants while the stream runs — against ONE object stream. This
module is that serving layer:

- **Standing-query registry** (:class:`QueryRegistry`): tenants register
  / unregister :class:`StandingQuery`\\ s via :class:`QServeCommand`\\ s
  riding the event stream (commands apply at window boundaries, in
  event-time order, exactly once — the ``_applied`` uid set makes
  refires and crash/retry replays idempotent). Registration strings
  (qid, tenant) intern into the OPERATOR's objID table — one intern
  home, never a second string table.
- **Bucketed batched evaluation**: live queries group by
  ``(kind, k-rung, radius-class)`` and each bucket pads onto a
  power-of-two capacity rung via the existing compaction ladder
  (``ops/compaction.py:pick_capacity`` — the overload
  ``clamp_compaction`` rung floors qserve rungs too), then evaluates as
  ONE vmapped fixed-shape program per window
  (``ops/query_registry.py:registry_bucket_kernel``; per-query radius
  is a traced operand, padding lanes are masked). Registration churn
  therefore moves between at most ladder-many compiled signatures per
  (rung, nseg) pair — the telemetry recompile detector is the guard,
  and the rung picks land in ``snapshot()["compaction"]`` under
  ``qserve_bucket``. On a mesh the same bucket runs through
  ``parallel/sharded.py:sharded_registry_bucket`` (bit-parity pinned).
- **Per-tenant QoS** (scoping PR 9's global machinery): registration
  admission and per-window result budgets come from
  ``overload.OverloadPolicy.tenant_budgets`` — a class over budget has
  its registrations rejected (``qserve_evicted``) or its result rows
  truncated, counted PER CLASS in ``snapshot()["overload"]["tenants"]``
  and budgeted by ``slo.SloSpec.tenant_budgets`` (post-hoc twin:
  ``sfprof health --slo``) — one firehose tenant degrades itself, never
  the fleet.
- **Crash safety**: the registry state (queries + applied-command uids
  + counters) snapshots with the operator (checkpoint.py), so a kill
  mid-registration-churn resumes to byte-identical per-tenant egress
  (``qserve.register`` injection point, chaos-matrix leg).

Wiring follows the telemetry idiom: :func:`install` puts one registry in
the module slot and ``telemetry.snapshot()["qserve"]`` carries its
counters (registered/evicted/bucket occupancy/recompiles) on every
ledger-stream checkpoint. ``SFT_QSERVE`` (inline JSON or a path —
``envvars.py``) supplies a serving config to ``streaming_job`` option 9
and the bench harness: ``{"queries": [...], "tenant_budgets": {...},
"cap_max": N}``.

PARITY.md "Continuous-query serving" documents the deliberate deviations
from the reference's one-query-per-job model.
"""

from __future__ import annotations

import json
import math
import os
from dataclasses import asdict, dataclass
from typing import Any, Dict, Iterable, Iterator, List, Optional, Tuple

import numpy as np

from spatialflink_tpu import overload
from spatialflink_tpu.faults import faults
from spatialflink_tpu.operators.base import (
    SpatialOperator,
    flags_for_queries,
    jitted,
    ship,
)
from spatialflink_tpu.models.objects import Point
from spatialflink_tpu.telemetry import telemetry
from spatialflink_tpu.utils.padding import next_bucket

QSERVE_VERSION = 1

#: Smallest bucket-capacity / result rung. Matches the compaction
#: ladder's floor so the per-bucket compile bound is len(capacity_ladder
#: (cap_max, 8)) ≤ 8 programs per (rung, nseg) pair.
QUERY_RUNG_MIN = 8

#: Default bucket-capacity ceiling (one bucket never exceeds this many
#: query lanes; a class's registrations beyond it are evicted, counted).
QUERY_CAP_MAX = 1024

#: Radius-class base (degrees ≈ 110 m): queries whose radii fall in the
#: same power-of-two band share a bucket. Grouping-only — the radius is
#: a TRACED per-query operand, so the class never keys a compile; it
#: keeps a bucket's pruning tables (and therefore its candidate
#: densities) homogeneous so one fat-radius query cannot dominate a
#: bucket of tight ones.
RADIUS_CLASS_BASE = 0.001

_KINDS = ("range", "knn")


@dataclass(frozen=True)
class StandingQuery:
    """One registered continuous query.

    ``k``: for ``knn`` the neighbor count; for ``range`` the result
    capacity (max matches returned per window — distinct in-radius
    objects beyond it are counted per window into the registry's
    ``range_result_overflow`` via the kernel's unclamped ``within``).
    """

    qid: str
    tenant: str
    kind: str
    x: float
    y: float
    radius: float
    k: int = 10
    tenant_class: str = "default"

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(
                f"unknown query kind {self.kind!r} (kinds: {_KINDS})"
            )
        if not self.qid:
            raise ValueError("qid must be non-empty")
        if not (float(self.radius) > 0.0):
            raise ValueError(f"radius must be positive, got {self.radius!r}")
        if int(self.k) < 1:
            raise ValueError(f"k must be >= 1, got {self.k!r}")

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)


@dataclass(frozen=True)
class QServeCommand:
    """A registration command riding the event stream. ``uid`` must be
    unique per logical command: it is the exactly-once key — sliding-
    window refires and crash/retry replays of a window re-apply commands
    through the registry's ``_applied`` set, so a duplicate uid is a
    no-op by construction."""

    timestamp: int
    action: str  # "register" | "unregister"
    uid: str
    query: Optional[StandingQuery] = None  # register
    qid: Optional[str] = None  # unregister

    #: Overload admission treats control-plane items as zero load and
    #: NEVER sheds them (overload._measure_item): shedding a command
    #: would silently diverge the registry from the command stream.
    control_plane = True

    def __post_init__(self):
        if self.action not in ("register", "unregister"):
            raise ValueError(f"unknown qserve action {self.action!r}")
        if self.action == "register" and self.query is None:
            raise ValueError("register command needs a query")
        if self.action == "unregister" and not self.qid:
            raise ValueError("unregister command needs a qid")
        if not self.uid:
            raise ValueError("command uid must be non-empty")


def query_rung(q: StandingQuery) -> int:
    """Result-capacity rung: smallest power of two ≥ k (floor 8) — the
    ONLY per-query value that becomes a compile-time static."""
    return int(next_bucket(max(int(q.k), 1), minimum=QUERY_RUNG_MIN))


def radius_class(radius: float) -> int:
    """Power-of-two radius band above ``RADIUS_CLASS_BASE`` (grouping
    key only — never a static; see the module docstring)."""
    r = float(radius)
    if r <= RADIUS_CLASS_BASE:
        return 0
    return max(0, int(math.ceil(math.log2(r / RADIUS_CLASS_BASE))))


def bucket_key(q: StandingQuery) -> Tuple[str, int, int]:
    return (q.kind, query_rung(q), radius_class(q.radius))


def bucket_key_str(key: Tuple[str, int, int]) -> str:
    return f"{key[0]}_k{int(key[1])}_rc{int(key[2])}"


class QueryRegistry:
    """The standing-query set + exactly-once command application.

    Single-threaded by design (driver-thread confined, like operator
    state) — no lock, so the telemetry provider can never deadlock.
    ``interner`` is the OWNING OPERATOR's objID interner: qid/tenant
    strings intern there on successful registration (one intern home —
    asserted by tests/test_qserve.py)."""

    def __init__(self, grid, interner, cap_max: int = QUERY_CAP_MAX):
        self.grid = grid
        self.interner = interner
        self.cap_max = int(cap_max)
        self._queries: Dict[str, StandingQuery] = {}
        self._flags: Dict[str, np.ndarray] = {}  # qid → neighbor table
        self._versions: Dict[Tuple[str, int, int], int] = {}
        self._bucket_live: Dict[Tuple[str, int, int], int] = {}
        #: command uid → command event-time (the exactly-once set;
        #: pruned behind the watermark by ``prune_applied``)
        self._applied: Dict[str, int] = {}
        #: bumped on restore so operator-side device caches keyed on
        #: (epoch, version) can never serve a pre-restore array.
        self.epoch = 0
        self.registered_total = 0
        self.unregistered_total = 0
        self.evicted_total = 0
        self.range_result_overflow = 0
        # Last window charged to the overflow counter — a driver RETRY
        # re-runs the same window's process(), and without this marker
        # the re-run would double-count (the _applied-set idea applied
        # to a per-window accumulator).
        self._overflow_window: Optional[int] = None
        self._overflow_last = 0

    def __len__(self) -> int:
        return len(self._queries)

    def query(self, qid: str) -> Optional[StandingQuery]:
        return self._queries.get(qid)

    def flags(self, qid: str) -> np.ndarray:
        return self._flags[qid]

    def version(self, key: Tuple[str, int, int]) -> int:
        return self._versions.get(key, 0)

    def _bump(self, key: Tuple[str, int, int]):
        self._versions[key] = self._versions.get(key, 0) + 1

    # -- command application (exactly once) ------------------------------------

    def apply(self, cmd: QServeCommand) -> bool:
        """Apply one command; returns True iff it changed the registry.
        Duplicate uids (window refires, crash/retry replays) are
        no-ops — THE exactly-once contract the chaos matrix pins."""
        if faults.armed:  # chaos injection point (faults.py)
            faults.hit("qserve.register")
        if cmd.uid in self._applied:
            return False
        self._applied[cmd.uid] = int(cmd.timestamp)
        if cmd.action == "register":
            return self._register(cmd.query)
        return self._unregister(cmd.qid)

    def prune_applied(self, watermark_ts: int, horizon_ms: int):
        """Drop applied-uid entries whose command timestamp is older
        than ``watermark - horizon``: a command can only replay via a
        sliding-window refire or a checkpoint-resume replay, both of
        which reach back at most one window span (+ lateness) behind
        the watermark — older uids can never be re-seen, so keeping
        them would grow the set (and every checkpoint serializing it)
        linearly with the run's LIFETIME command count."""
        cut = int(watermark_ts) - int(horizon_ms)
        stale = [uid for uid, ts in self._applied.items() if ts < cut]
        for uid in stale:
            del self._applied[uid]

    def record_range_overflow(self, window_start: int, count: int):
        """Charge one window's range-result truncation (distinct
        in-radius objects beyond each range query's ``k`` cap) to the
        running counter, idempotently: re-charging the SAME window (a
        driver retry re-running ``process``) replaces the previous
        charge instead of accumulating it."""
        if self._overflow_window == int(window_start):
            self.range_result_overflow -= self._overflow_last
        self._overflow_window = int(window_start)
        self._overflow_last = int(count)
        self.range_result_overflow += int(count)

    def _register(self, q: StandingQuery) -> bool:
        if q.qid in self._queries:
            return False  # idempotent re-register
        key = bucket_key(q)
        if self._bucket_live.get(key, 0) >= self.cap_max:
            # The rung ladder tops out at cap_max — beyond it the bucket
            # cannot hold another lane. Deterministic eviction, counted.
            self.evicted_total += 1
            if telemetry.enabled:
                telemetry.emit_instant(
                    "qserve_evicted", qid=q.qid,
                    tenant_class=q.tenant_class, reason="bucket_full",
                )
            return False
        if not overload.admit_tenant_query(q.tenant_class):
            # Per-tenant-class admission budget (overload.py
            # tenant_budgets): the CLASS is over its standing-query
            # budget — reject and count, fleet untouched.
            self.evicted_total += 1
            if telemetry.enabled:
                telemetry.emit_instant(
                    "qserve_evicted", qid=q.qid,
                    tenant_class=q.tenant_class, reason="tenant_budget",
                )
            return False
        # ONE intern home: registration strings join the operator's
        # objID table (dense ids reused for deterministic routing).
        self.interner.intern(q.tenant)
        self.interner.intern(q.qid)
        self._queries[q.qid] = q
        self._flags[q.qid] = flags_for_queries(
            self.grid, q.radius, [Point(x=q.x, y=q.y)]
        )
        self._bucket_live[key] = self._bucket_live.get(key, 0) + 1
        self.registered_total += 1
        self._bump(key)
        if telemetry.enabled:
            telemetry.emit_instant(
                "qserve_registered", qid=q.qid, tenant=q.tenant,
                tenant_class=q.tenant_class, kind=q.kind,
            )
        return True

    def _unregister(self, qid: str) -> bool:
        q = self._queries.pop(qid, None)
        if q is None:
            return False  # idempotent re-unregister
        self._flags.pop(qid, None)
        key = bucket_key(q)
        self._bucket_live[key] = max(0, self._bucket_live.get(key, 1) - 1)
        overload.release_tenant_query(q.tenant_class)
        self.unregistered_total += 1
        self._bump(key)
        if telemetry.enabled:
            telemetry.emit_instant(
                "qserve_unregistered", qid=qid,
                tenant_class=q.tenant_class,
            )
        return True

    # -- bucketing -------------------------------------------------------------

    def buckets(self) -> Dict[Tuple[str, int, int], List[StandingQuery]]:
        """Live queries grouped by (kind, k-rung, radius-class), qid-
        sorted within each bucket — the deterministic evaluation order
        the byte-identical-egress contract rides on."""
        out: Dict[Tuple[str, int, int], List[StandingQuery]] = {}
        for qid in sorted(self._queries):
            q = self._queries[qid]
            out.setdefault(bucket_key(q), []).append(q)
        return out

    # -- checkpoint state ------------------------------------------------------

    def state(self) -> Dict[str, Any]:
        return {
            "version": QSERVE_VERSION,
            "queries": [
                self._queries[qid].to_dict()
                for qid in sorted(self._queries)
            ],
            "applied": sorted(
                [uid, int(ts)] for uid, ts in self._applied.items()
            ),
            "counters": {
                "registered_total": int(self.registered_total),
                "unregistered_total": int(self.unregistered_total),
                "evicted_total": int(self.evicted_total),
                "range_result_overflow": int(self.range_result_overflow),
                "overflow_window": self._overflow_window,
                "overflow_last": int(self._overflow_last),
            },
        }

    def restore(self, state: Dict[str, Any]):
        ver = state.get("version", QSERVE_VERSION)
        if ver != QSERVE_VERSION:
            raise ValueError(
                f"qserve state version {ver} != supported {QSERVE_VERSION}"
            )
        self._queries = {}
        self._flags = {}
        for d in state["queries"]:
            q = StandingQuery(**d)
            self._queries[q.qid] = q
            # Flag tables are derived data — rebuilt from the grid (the
            # join-pane-carry restore idiom in checkpoint.py).
            self._flags[q.qid] = flags_for_queries(
                self.grid, q.radius, [Point(x=q.x, y=q.y)]
            )
        self._applied = {uid: int(ts) for uid, ts in state["applied"]}
        self._bucket_live = {}
        for q in self._queries.values():
            key = bucket_key(q)
            self._bucket_live[key] = self._bucket_live.get(key, 0) + 1
        c = state["counters"]
        self.registered_total = int(c["registered_total"])
        self.unregistered_total = int(c["unregistered_total"])
        self.evicted_total = int(c["evicted_total"])
        self.range_result_overflow = int(c["range_result_overflow"])
        ow = c.get("overflow_window")
        self._overflow_window = None if ow is None else int(ow)
        self._overflow_last = int(c.get("overflow_last", 0))
        self._versions = {}
        self.epoch += 1  # invalidate any operator-side device caches

    # -- telemetry provider ----------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """The ``snapshot()["qserve"]`` block (telemetry installs this
        as ``qserve_provider``): registered/evicted counters, per-bucket
        occupancy vs its current rung, and the bucket kernel's compiled-
        signature count — the ≤K churn contract made visible."""
        from spatialflink_tpu.ops.compaction import pick_capacity

        buckets = {
            bucket_key_str(key): {
                "live": len(qs),
                "capacity": int(pick_capacity(
                    len(qs), self.cap_max, minimum=QUERY_RUNG_MIN
                )),
            }
            for key, qs in sorted(self.buckets().items())
        }
        return {
            "version": QSERVE_VERSION,
            "registered": len(self._queries),
            "registered_total": int(self.registered_total),
            "unregistered_total": int(self.unregistered_total),
            "evicted_total": int(self.evicted_total),
            "range_result_overflow": int(self.range_result_overflow),
            "buckets": buckets,
            "recompiles": telemetry.distinct_shapes(
                "registry_bucket_kernel"
            ),
        }


def bucket_host_arrays(grid, queries: List[StandingQuery], cap: int,
                       flags_of=None):
    """Padded host arrays for one bucket: (qxy (cap, 2) f64 UNcentered,
    radius (cap,), qvalid (cap,), tables (cap, num_cells+1) uint8).
    Shared by the operator (which centers qxy at its device boundary)
    and the bench harness. ``flags_of(q)`` overrides the per-query
    neighbor-table source (default: compute from the grid)."""
    if len(queries) > cap:
        raise ValueError(f"{len(queries)} queries exceed the {cap} rung")
    qxy = np.zeros((cap, 2), np.float64)
    radius = np.zeros(cap, np.float64)
    qvalid = np.zeros(cap, bool)
    tables = np.zeros((cap, grid.num_cells + 1), np.uint8)
    for i, q in enumerate(queries):
        qxy[i] = (q.x, q.y)
        radius[i] = float(q.radius)
        qvalid[i] = True
        tables[i] = (
            flags_of(q) if flags_of is not None
            else flags_for_queries(grid, q.radius, [Point(x=q.x, y=q.y)])
        )
    return qxy, radius, qvalid, tables


@dataclass
class QServeWindowResult:
    """One window's served results, routed per tenant.

    ``rows``: (tenant_class, tenant, qid, objID, dist) in deterministic
    bucket/qid/rank order — AFTER per-tenant-class result budgets
    truncated each class's rows (overload.tenant_result_allowance)."""

    start: int
    end: int
    rows: List[Tuple[str, str, str, Any, float]]
    window_count: int

    def lines(self) -> Iterator[str]:
        """The per-tenant egress line format (streaming_job option 9 and
        the chaos harness byte-compare these)."""
        for cls, tenant, qid, obj, dist in self.rows:
            yield (f"{tenant},{qid},{self.start},{self.end},"
                   f"{obj},{float(dist)!r}")

    def by_tenant(self) -> Dict[str, List[Tuple[str, Any, float]]]:
        out: Dict[str, List[Tuple[str, Any, float]]] = {}
        for _cls, tenant, qid, obj, dist in self.rows:
            out.setdefault(tenant, []).append((qid, obj, float(dist)))
        return out


class QServeOperator(SpatialOperator):
    """The serving operator: Point events + QServeCommands in, per-
    tenant standing-query results out, on the shared dataflow driver
    (checkpoint/retry/chaos semantics identical to the query operators).
    """

    def __init__(self, conf, grid, mesh=None, cap_max: int = QUERY_CAP_MAX):
        super().__init__(conf, grid, mesh=mesh)
        self.qserve_registry = QueryRegistry(
            grid, self.interner, cap_max=cap_max
        )
        self._bucket_dev: Dict[Tuple[str, int, int], Dict[str, Any]] = {}
        self._last_rung: Dict[Tuple[str, int, int], int] = {}

    @property
    def registry(self) -> QueryRegistry:
        return self.qserve_registry

    def _eval_bucket(self, kernel, mesh, xy_d, valid_d, cell_d, oid_d,
                     arrays, key, rung, cap, nseg, dtype):
        """Dispatch ONE bucket's vmapped program (mesh or single-chip)
        under its ``compute`` span — the bucket-evaluation unit the
        node-attribution scope tags."""
        with telemetry.span("compute", bucket=bucket_key_str(key)):
            if mesh is not None:
                from spatialflink_tpu.parallel.sharded \
                    import sharded_registry_bucket

                return sharded_registry_bucket(
                    mesh, xy_d, valid_d, cell_d,
                    arrays["tables"], oid_d,
                    arrays["qxy"], arrays["radius"],
                    arrays["qvalid"],
                    k=rung, num_segments=nseg,
                )
            return kernel(
                xy_d, valid_d, cell_d,
                arrays["tables"], oid_d,
                arrays["qxy"], arrays["radius"],
                arrays["qvalid"],
                k=rung, num_segments=nseg,
                query_block=min(cap, 32),
            )

    def _bucket_device_arrays(self, key, qs, cap, dtype):
        """Device-cached bucket operand set, keyed on (registry epoch,
        bucket version, rung, dtype) — churnless windows re-ship
        NOTHING; a register/unregister in the bucket bumps its version
        and rebuilds once."""
        reg = self.qserve_registry
        ck = (reg.epoch, reg.version(key), int(cap), np.dtype(dtype).str)
        hit = self._bucket_dev.get(key)
        if hit is not None and hit["ck"] == ck:
            return hit
        qxy, radius, qvalid, tables = bucket_host_arrays(
            self.grid, qs, cap, flags_of=lambda q: reg.flags(q.qid)
        )
        tables_d, radius_d, qvalid_d = ship(tables, radius, qvalid)
        dev = {
            "ck": ck,
            "qxy": self.device_q(qxy, dtype),  # centered like the points
            "tables": tables_d,
            "radius": radius_d,
            "qvalid": qvalid_d,
        }
        self._bucket_dev[key] = dev
        return dev

    def run(
        self,
        stream: Iterable,
        dtype=np.float64,
        mesh=None,
        driver=None,
    ) -> Iterator[QServeWindowResult]:
        """Serve the stream: commands apply at window fires (event-time
        order, exactly once), every bucket evaluates as one program, and
        results route per tenant under the per-class result budgets.
        ``driver=`` opts into checkpointing/retry exactly like the other
        operators; registry state rides the operator checkpoint."""
        from spatialflink_tpu.driver import strict_driver
        from spatialflink_tpu.ops.compaction import pick_capacity
        from spatialflink_tpu.ops.query_registry import (
            registry_bucket_kernel,
        )

        if self.conf.allowed_lateness_ms > 0:
            # The query_panes rule: a late-event REFIRE re-runs a
            # window already charged to the per-window QoS/overflow
            # accumulators (whose retry-idempotence markers only cover
            # consecutive re-charges), double-counting sheds — and the
            # applied-uid pruning horizon assumes refires reach at most
            # one window span back. Reject rather than drift.
            raise ValueError(
                "QServeOperator does not support allowed_lateness "
                "(late-window refires would double-charge the per-"
                "tenant shed and range-overflow accumulators)"
            )
        mesh = mesh if mesh is not None else self.mesh
        drv = driver if driver is not None else strict_driver()
        drv.attach(self)
        reg = self.qserve_registry
        if registry() is not reg:
            # Module slot for ledger/stream checkpoints — THIS run's
            # registry becomes the provider (a stale previous run's
            # counters must never ride this run's checkpoints), and it
            # stays installed for the seal (the driver-controller
            # idiom; tests clean the slot via qserve.uninstall()).
            install(reg)
        kernel = jitted(
            registry_bucket_kernel, "k", "num_segments", "query_block"
        )

        def process(win) -> QServeWindowResult:
            return self.serve_window(win, kernel, dtype=dtype, mesh=mesh)

        drv.bind(self, process, fallback=None)
        yield from drv.run(stream)

    def serve_window(self, win, kernel, dtype=np.float64,
                     mesh=None) -> QServeWindowResult:
        """One window's serving pass: apply the window's commands
        exactly once, evaluate every bucket as one program, ONE true
        sync for all buckets, per-tenant-class result budgets. The
        shared core of :meth:`run`'s process and the composed DAG's
        qserve node (dag.py) — both route retries through the
        retry-idempotent accumulators (record_range_overflow,
        tenant_result_allowance), so re-running a window is safe."""
        from spatialflink_tpu.ops.compaction import pick_capacity

        reg = self.qserve_registry
        with telemetry.span("window.qserve", start=win.start,
                            events=len(win.events)):
            cmds = sorted(
                (e for e in win.events
                 if isinstance(e, QServeCommand)),
                key=lambda c: (c.timestamp, c.uid),
            )
            for cmd in cmds:
                reg.apply(cmd)
            # The exactly-once uid set only needs to reach as far
            # back as a refire/resume can (one window span +
            # lateness + slide behind this fire) — prune beyond it
            # so checkpoints don't grow with lifetime command count.
            reg.prune_applied(
                win.start,
                self.conf.window_size_ms
                + self.conf.allowed_lateness_ms
                + self.conf.slide_step_ms,
            )
            pts = [e for e in win.events
                   if not isinstance(e, QServeCommand)]
            buckets = reg.buckets()
            # Evict device arrays of buckets churn has emptied —
            # a dead bucket must not pin its (cap, num_cells+1)
            # tables in device memory for the rest of the run.
            for key in [k for k in self._bucket_dev
                        if k not in buckets]:
                del self._bucket_dev[key]
            rows: List[Tuple[str, str, str, Any, float]] = []
            win_overflow = 0
            if pts and buckets:
                with telemetry.span("assemble"):
                    batch = self.point_batch(pts)
                    nseg = next_bucket(
                        max(self.interner.num_segments, 1),
                        minimum=64,
                    )
                with telemetry.span("ship"):
                    valid_d, cell_d, oid_d = ship(
                        batch.valid, batch.cell, batch.oid
                    )
                    xy_d = self.device_xy(batch, dtype)
                pending = []
                # Bucket-level attribution only when STANDALONE: under
                # the DAG the whole window already carries the "qserve"
                # node scope, and splintering it per bucket would break
                # the per-node conservation rollup into bucket shards.
                standalone = telemetry.current_node() is None
                for key in sorted(buckets):
                    qs = buckets[key]
                    bucket_node = (f"qserve:{bucket_key_str(key)}"
                                   if standalone else None)
                    with telemetry.scope(bucket_node):
                        cap = pick_capacity(
                            len(qs), reg.cap_max,
                            minimum=QUERY_RUNG_MIN
                        )
                        telemetry.record_compaction(
                            "qserve_bucket", cap, len(qs)
                        )
                        if self._last_rung.get(key) != cap:
                            # A rung move is one (bounded) XLA compile
                            # — worth an instant marker in the stream.
                            self._last_rung[key] = cap
                            telemetry.emit_instant(
                                f"qserve_rung:{bucket_key_str(key)}",
                                capacity=int(cap), live=len(qs),
                            )
                        arrays = self._bucket_device_arrays(
                            key, qs, cap, dtype
                        )
                        rung = int(key[1])
                        res = self._eval_bucket(
                            kernel, mesh, xy_d, valid_d, cell_d,
                            oid_d, arrays, key, rung, cap, nseg,
                            dtype,
                        )
                    pending.append((qs, res))
                # ONE true sync for ALL buckets (the flush_pending
                # idiom): every bucket's dispatch is in flight
                # before the window pays its single device→host
                # round trip — per-bucket fetches would serialize
                # ~bucket-count tunnel syncs per window.
                with telemetry.span("fetch"):
                    fetched = telemetry.fetch([
                        (r.num_valid, r.within, r.segment, r.dist)
                        for _qs, r in pending
                    ])
                for (qs, _r), (nvs, within, segs, dists) in zip(
                        pending, fetched):
                    for lane, q in enumerate(qs):
                        nv = int(nvs[lane])
                        if q.kind == "range":
                            # Truncation against the QUERY's own
                            # result cap (k ≤ rung): any distinct
                            # in-radius object beyond the k rows
                            # returned is an incomplete range
                            # result, counted.
                            win_overflow += max(
                                int(within[lane]) - int(q.k), 0
                            )
                        for r_ in range(min(nv, int(q.k))):
                            rows.append((
                                q.tenant_class, q.tenant, q.qid,
                                self.interner.lookup(
                                    int(segs[lane, r_])
                                ),
                                float(dists[lane, r_]),
                            ))
            reg.record_range_overflow(win.start, win_overflow)
            # Per-tenant-class result budgets: each class keeps its
            # first `allowance` rows (deterministic bucket/qid/rank
            # order), the excess is counted against THE CLASS only.
            counts: Dict[str, int] = {}
            for row in rows:
                counts[row[0]] = counts.get(row[0], 0) + 1
            allow = {
                cls: overload.tenant_result_allowance(
                    cls, n, window_start=win.start)
                for cls, n in sorted(counts.items())
            }
            kept: List[Tuple[str, str, str, Any, float]] = []
            used: Dict[str, int] = {}
            for row in rows:
                used[row[0]] = used.get(row[0], 0) + 1
                if used[row[0]] <= allow[row[0]]:
                    kept.append(row)
            return QServeWindowResult(
                win.start, win.end, kept, len(win.events)
            )



# -- module-level wiring (the telemetry/overload singleton idiom) --------------

_registry: Optional[QueryRegistry] = None


def install(reg: QueryRegistry) -> QueryRegistry:
    """Make ``reg`` the process-global registry:
    ``telemetry.snapshot()["qserve"]`` carries its counters on every
    ledger-stream checkpoint."""
    global _registry
    _registry = reg
    telemetry.qserve_provider = reg.snapshot
    return reg


def uninstall():
    global _registry
    if _registry is not None:
        telemetry.qserve_provider = None
    _registry = None


def registry() -> Optional[QueryRegistry]:
    return _registry


# -- SFT_QSERVE serving config -------------------------------------------------

_CONFIG_KEYS = ("queries", "tenant_budgets", "cap_max")


def config_from_env() -> Optional[Dict[str, Any]]:
    """``SFT_QSERVE``: inline JSON or a path to a JSON file (the
    SFT_FAULT_PLAN convention). Strict parse — an unknown key is a
    config typo, and a typo'd budget silently ignored is the worst
    failure mode a QoS config can have."""
    spec = os.environ.get("SFT_QSERVE")
    if not spec:
        return None
    text = spec.strip()
    if not text.startswith("{"):
        with open(text) as f:
            text = f.read()
    cfg = json.loads(text)
    if not isinstance(cfg, dict):
        raise ValueError(f"SFT_QSERVE must be a JSON object, got {cfg!r}")
    unknown = sorted(set(cfg) - set(_CONFIG_KEYS))
    if unknown:
        raise ValueError(
            f"unknown SFT_QSERVE keys: {unknown} (keys: {_CONFIG_KEYS})"
        )
    return cfg


def queries_from_config(cfg: Dict[str, Any]) -> List[StandingQuery]:
    return [StandingQuery(**d) for d in cfg.get("queries", [])]


def boot_commands(queries: List[StandingQuery],
                  timestamp: int = 0) -> List[QServeCommand]:
    """Registration commands for a static startup query set (uids are
    deterministic — replayable, so --checkpoint resumes stay exact)."""
    return [
        QServeCommand(timestamp=int(timestamp), action="register",
                      uid=f"boot:{q.qid}", query=q)
        for q in queries
    ]
