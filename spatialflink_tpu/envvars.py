"""The SFT_* environment-variable registry — one owner and hazard class
per var.

Every ``SFT_*`` variable the codebase reads is declared here;
``tools/sfcheck``'s ``env-registry`` pass fails the tree on any
unregistered ``os.environ``/``getenv`` read site, and on any registered
var nothing reads (drift cuts both ways). ``tools/ci.py`` derives its
gate-stage ambient-environment scrub from :func:`gate_scrub_vars`, so a
new armed-plan var registered here is scrubbed automatically — it can
never leak an injected fault into a healthy gate run the way an ambient
``SFT_FAULT_PLAN`` once could.

Hazard classes:

- ``armed`` — arms faults/policies or forces failures; ambient values
  SABOTAGE any run that did not set them (the chaos/overload plans, the
  bench failure-forcing test knobs). The CI gate scrubs these from
  every stage.
- ``capture`` — selects artifact outputs (ledgers, streams, traces);
  ambient values redirect captures but never change verdicts, and gate
  stages that capture set their own.
- ``tuning`` — behavior knobs with safe defaults (deadlines, smoke
  sizing, cache dirs); gate stages pin the ones they depend on.
- ``internal`` — process-internal markers set by a parent for its own
  children; never user-facing.

This module is deliberately **stdlib-only and import-free** so the CI
gate can load it by file path without importing the package (whose
``__init__`` configures jax — the sfprof no-cross-import rule).
"""

from __future__ import annotations

HAZARD_CLASSES = ("armed", "capture", "tuning", "internal")

#: name → {"owner": reading module, "hazard": class, "doc": one line}
ENV_VARS = {
    "SFT_FAULT_PLAN": {
        "owner": "spatialflink_tpu/faults.py", "hazard": "armed",
        "doc": "fault plan (inline JSON or path), armed at import",
    },
    "SFT_OVERLOAD_POLICY": {
        "owner": "spatialflink_tpu/overload.py", "hazard": "armed",
        "doc": "overload policy (inline JSON or path) the driver installs",
    },
    "SFT_PIPELINE": {
        "owner": "spatialflink_tpu/pipeline.py", "hazard": "armed",
        "doc": "pipelined-ingest policy (inline JSON or path), armed at "
               "import; results stay bit-identical but an ambient value "
               "would flip the gate's pipeline-off baselines",
    },
    "SFT_QSERVE": {
        "owner": "spatialflink_tpu/qserve.py", "hazard": "armed",
        "doc": "qserve serving config (inline JSON or path): standing "
               "queries + per-tenant-class budgets; an ambient value "
               "would register ghost queries / arm QoS budgets in runs "
               "that never asked for them",
    },
    "SFT_SLO_SPEC": {
        "owner": "bench.py", "hazard": "armed",
        "doc": "SLO spec evaluated LIVE during a bench run",
    },
    "SFT_ABLATE": {
        "owner": "spatialflink_tpu/ablation.py", "hazard": "armed",
        "doc": "kernel-ablation spec (comma list, inline JSON, or "
               "path), armed at import; substituted kernels return "
               "cached zeros, so an ambient value silently falsifies "
               "every measurement (the run is tainted, but the gate "
               "must never run tainted in the first place)",
    },
    "SFT_BENCH_FORCE_FAIL": {
        "owner": "bench.py", "hazard": "armed",
        "doc": "forces the bench child to fail (contract tests)",
    },
    "SFT_BENCH_HANG": {
        "owner": "bench.py", "hazard": "armed",
        "doc": "wedges the bench child (supervisor-deadline tests)",
    },
    "SFT_BENCH_DIAL_HANG": {
        "owner": "bench.py", "hazard": "armed",
        "doc": "wedges the axon dial (dial-deadline tests)",
    },
    "SFT_BENCH_FAKE_RECORD": {
        "owner": "bench.py", "hazard": "armed",
        "doc": "substitutes a canned bench record (contract tests)",
    },
    "SFT_BENCH_CHILD": {
        "owner": "bench.py", "hazard": "armed",
        "doc": "marks the supervised bench child; ambient value would "
               "make a fresh bench run skip its own supervisor",
    },
    "SFT_LEDGER_PATH": {
        "owner": "bench.py", "hazard": "capture",
        "doc": "run-ledger output path",
    },
    "SFT_LEDGER_STREAM": {
        "owner": "spatialflink_tpu/telemetry.py", "hazard": "capture",
        "doc": "append-only JSONL ledger stream path",
    },
    "SFT_LEDGER_STREAM_INTERVAL_S": {
        "owner": "spatialflink_tpu/telemetry.py", "hazard": "capture",
        "doc": "stream flush pacing (seconds)",
    },
    "SFT_BLACKBOX": {
        "owner": "spatialflink_tpu/telemetry.py", "hazard": "capture",
        "doc": "flight-recorder ring size (last-N window summaries + "
               "instants dumped to <stream>.blackbox.json on fault "
               "fire / stream seal; '0' disables, default 64)",
    },
    "SFT_LEDGER_DIR": {
        "owner": "bench_suite.py", "hazard": "capture",
        "doc": "per-config ledger directory for suite runs",
    },
    "SFT_TRACE_PATH": {
        "owner": "bench.py", "hazard": "capture",
        "doc": "Chrome-trace JSONL output path",
    },
    "SFT_PROFILE_DIR": {
        "owner": "bench.py", "hazard": "capture",
        "doc": "jax profiler trace directory",
    },
    "SFT_BENCH_LAST_GOOD": {
        "owner": "bench.py", "hazard": "capture",
        "doc": "last-good bench record store (gate uses a toy copy)",
    },
    "SFT_BENCH_SMOKE": {
        "owner": "bench.py", "hazard": "tuning",
        "doc": "toy-size smoke mode for the CI gate",
    },
    "SFT_BENCH_BACKOFFS": {
        "owner": "bench.py", "hazard": "tuning",
        "doc": "supervisor retry backoff schedule (seconds, comma-sep)",
    },
    "SFT_BENCH_DEADLINE": {
        "owner": "bench.py", "hazard": "tuning",
        "doc": "per-attempt bench supervisor deadline (seconds)",
    },
    "SFT_DIAL_DEADLINE_S": {
        "owner": "bench.py", "hazard": "tuning",
        "doc": "axon dial deadline; timeout seals the stream. Also read "
               "by spatialflink_tpu/driver.py: when SET it bounds the "
               "driver's first device-path window (the --checkpoint "
               "resume-on-a-down-tunnel hang), same dial_timeout seal",
    },
    "SFT_NO_LINK_PROBE": {
        "owner": "bench.py", "hazard": "tuning",
        "doc": "disables the tunnel link-health probe",
    },
    "SFT_NO_PALLAS_DIGEST": {
        "owner": "bench.py", "hazard": "tuning",
        "doc": "disables the pallas digest path on TPU",
    },
    "SFT_JAX_CACHE_DIR": {
        "owner": "spatialflink_tpu/runtime.py", "hazard": "tuning",
        "doc": "persistent XLA compile cache dir ('off' disables)",
    },
    "_SFT_DRYRUN_CLEAN": {
        "owner": "__graft_entry__.py", "hazard": "internal",
        "doc": "marks the re-execed CPU-clean multichip dryrun child",
    },
}


def gate_scrub_vars() -> list:
    """The vars the CI gate must remove from every stage's ambient
    environment: everything hazard-class ``armed``."""
    return sorted(n for n, meta in ENV_VARS.items()
                  if meta["hazard"] == "armed")


def _selfcheck() -> None:
    for name, meta in ENV_VARS.items():
        if meta["hazard"] not in HAZARD_CLASSES:
            raise ValueError(
                f"ENV_VARS[{name!r}]: unknown hazard class "
                f"{meta['hazard']!r} (classes: {HAZARD_CLASSES})"
            )


_selfcheck()
