"""Runtime configuration for the JAX backend.

XLA compilation on this class of host (remote-compile TPU tunnels, modest
CPUs) costs ~1-2 s per program; without a persistent cache every process
pays it again. Importing ``spatialflink_tpu`` configures JAX's persistent
compilation cache (override the location with SFT_JAX_CACHE_DIR, disable
with SFT_JAX_CACHE_DIR=off).
"""

from __future__ import annotations

import os


def configure_jax_cache() -> None:
    cache_dir = os.environ.get(
        "SFT_JAX_CACHE_DIR", os.path.expanduser("~/.cache/jax_sft")
    )
    if cache_dir.lower() == "off":
        return
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:  # pragma: no cover - older jax without these flags
        pass


configure_jax_cache()
