"""Measured kernel ablation — what does each kernel actually COST?

The tjoin long-pole hunt (ROADMAP item 5) and every future kernel
optimization start with the same question: if this kernel were free,
how much faster would the config run? The XLA cost model answers with
estimated flops; this module answers with a MEASUREMENT: arm a kernel
name and ``telemetry.instrument_jit`` substitutes its dispatch with a
cached correct-aval zero result, so the config's EPS with the kernel
"free" minus its baseline EPS is the kernel's *marginal* cost
(``bench_suite.py --ablate`` drives the sweep and prints the table).

Mechanics per (kernel, abstract signature):

- the FIRST call runs the real kernel once (the *learning* call): it
  compiles, produces a structurally-correct output, and that output's
  zero-filled mirror (same pytree, shapes, dtypes — built by ONE jitted
  ``zeros_like`` program, never eager per-leaf ops) is cached;
- every later call returns a fresh jitted copy of the cached zeros.
  Fresh — never the cached buffers themselves — because a downstream
  jit with ``donate_argnums`` may consume what we hand it, and a
  donated cache would poison every subsequent window. The copy is one
  trivial dispatch: it IS the substituted kernel's residual cost, which
  is exactly what a marginal measurement wants left in place.

**Ablated runs are deliberately WRONG** (windows see zeros). They exist
only to be timed, so every capture they touch is tainted: while armed
(or after any substituted call since the capture began) the taint block
rides ``telemetry.snapshot()["tainted"]``, the ledger's top level, the
ledger-stream checkpoints (so a recovered stream stays tainted), and
the bench record itself — and ``sfprof diff --gate`` / ``trend
--gate`` / the last-good store / the CPU_BASELINE writer all
hard-reject it. A stubbed run can never pollute the perf record.

Arming (the faults/pipeline idiom): ``SFT_ABLATE`` at import —
a comma-separated kernel-name list, inline JSON (``["k1","k2"]`` or
``{"kernels": [...]}``) or a path to such JSON — or ``ablation.arm``
in-process. Disarmed cost is one attribute check per dispatch
(``if ablation.armed``). Import order note: this module is imported by
``telemetry`` at module scope, so it must never import telemetry at
module scope itself — all telemetry touches are lazy per-call imports
(the faults.py rule, inverted).
"""

from __future__ import annotations

import functools
import json
import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple


@functools.lru_cache(maxsize=1)
def _jit_helpers():
    """(zeros_like, fresh_copy) as jitted programs — compiled once per
    output pytree structure by jax.jit's own cache, so the substituted
    path never issues eager per-leaf ops from a per-window loop."""
    import jax
    import jax.numpy as jnp

    zeros = jax.jit(
        lambda t: jax.tree_util.tree_map(jnp.zeros_like, t))
    copy = jax.jit(lambda t: jax.tree_util.tree_map(jnp.copy, t))
    return zeros, copy


class KernelAblation:
    """Process-global ablation controller (the faults/telemetry
    singleton idiom). ``armed`` is the ONLY state the disarmed hot path
    reads; the lock guards the cache/counters, never a dispatch."""

    def __init__(self):
        self.armed = False
        self.kernels: frozenset = frozenset()
        self._lock = threading.Lock()
        # (kernel label, abstract signature) → cached zero pytree.
        self._cache: Dict[Tuple[str, Tuple], Any] = {}
        self._hits: Dict[str, int] = {}     # substituted calls
        self._learned: Dict[str, int] = {}  # real learning calls

    # -- arming ----------------------------------------------------------------

    def arm(self, kernels: Sequence[str]):
        """Arm ablation for the named kernel labels (the names
        ``instrument_jit`` was given). Re-arming replaces the set and
        resets the cache — each sweep leg learns fresh."""
        ks = frozenset(str(k) for k in kernels if str(k))
        with self._lock:
            self.kernels = ks
            self._cache.clear()
            self.armed = bool(ks)
        if self.armed:
            self._emit_armed()

    def disarm(self):
        with self._lock:
            self.armed = False
            self.kernels = frozenset()
            self._cache.clear()

    def reset_counters(self):
        """Start-of-capture reset (``telemetry.enable`` calls this): a
        fresh capture's taint must reflect THIS capture's substitutions,
        not a previous sweep leg's."""
        with self._lock:
            self._hits.clear()
            self._learned.clear()
            self._cache.clear()

    def _emit_armed(self):
        # Lazy + guarded: at import-time arming, telemetry may be mid-
        # import (it imports THIS module at module scope) — the skipped
        # emit is re-issued by telemetry.enable()'s armed check, the
        # same both-sides coverage faults.py uses.
        try:
            from spatialflink_tpu.telemetry import telemetry
        except Exception:
            return
        if telemetry.enabled:
            telemetry.emit_instant(
                "ablation_armed", kernels=sorted(self.kernels))

    # -- the substituted dispatch ----------------------------------------------

    def matches(self, label: str) -> bool:
        return label in self.kernels

    def dispatch(self, label: str, fn, args: tuple, kwargs: dict):
        """Substitute one instrumented-kernel call (see module doc):
        learning call per (kernel, signature), cached-zero copies after."""
        from spatialflink_tpu.telemetry import abstract_signature

        key = (label, abstract_signature(args, kwargs))
        with self._lock:
            cached = self._cache.get(key)
        if cached is None:
            out = fn(*args, **kwargs)  # learning call: the real kernel
            zeros_fn, _copy_fn = _jit_helpers()
            zeros = zeros_fn(out)
            with self._lock:
                self._cache.setdefault(key, zeros)
                self._learned[label] = self._learned.get(label, 0) + 1
            return out
        with self._lock:
            self._hits[label] = self._hits.get(label, 0) + 1
        _zeros_fn, copy_fn = _jit_helpers()
        return copy_fn(cached)

    # -- taint -----------------------------------------------------------------

    def taint_block(self) -> Optional[Dict[str, Any]]:
        """The taint record (None while clean): armed now, or any
        substituted/learning call since the capture began. Rides every
        snapshot/ledger/stream checkpoint and the bench record."""
        with self._lock:
            if not self.armed and not self._hits and not self._learned:
                return None
            return {
                "kind": "ablation",
                "kernels": sorted(self.kernels),
                "substituted_calls": dict(self._hits),
                "learning_calls": dict(self._learned),
            }


ablation = KernelAblation()


def _parse_spec(text: str) -> List[str]:
    """SFT_ABLATE value → kernel list: inline JSON (list or
    ``{"kernels": [...]}``), a path to such JSON, or a comma list."""
    text = text.strip()
    if not text:
        return []
    if not text.startswith(("[", "{")) and os.path.isfile(text):
        with open(text) as f:
            text = f.read().strip()
    if text.startswith(("[", "{")):
        spec = json.loads(text)
        if isinstance(spec, dict):
            spec = spec.get("kernels") or []
        if not isinstance(spec, list):
            raise ValueError(
                f"SFT_ABLATE JSON must be a list or {{'kernels': [...]}}, "
                f"got {type(spec).__name__}")
        return [str(k) for k in spec]
    return [k.strip() for k in text.split(",") if k.strip()]


def maybe_arm_from_env():
    """Arm from ``SFT_ABLATE`` when set (called at import, the
    faults/pipeline idiom — ablation subprocesses arm with zero code).
    A malformed spec raises: a sweep that silently measures the
    UN-ablated program is worse than a crash."""
    spec = os.environ.get("SFT_ABLATE")
    if spec:
        kernels = _parse_spec(spec)
        if not kernels:
            raise ValueError(f"SFT_ABLATE set but names no kernels: "
                             f"{spec!r}")
        ablation.arm(kernels)


maybe_arm_from_env()
