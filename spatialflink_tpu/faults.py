"""Deterministic fault injection — rehearse the failure modes on demand.

The failure modes this repo has actually been bitten by (CLAUDE.md: the
r3–r5 tunnel outages, the axon dial hanging interpreter boot, children
SIGKILLed mid-capture) could only be reproduced by waiting for the next
outage. This module makes them a *scheduled, replayable* event: named
injection points threaded through the host/device boundary
(``operators/base.py`` ship / jitted dispatch / ``telemetry.fetch``),
the Kafka fetch and leader paths, window assembly, sink commits, and the
dataflow driver, armed by a JSON *fault plan*.

Contract (the telemetry idiom): **disarmed-free** — every injection
point costs ONE attribute check while no plan is armed::

    if faults.armed:
        faults.hit("device.ship")

Plans arm via ``SFT_FAULT_PLAN`` (inline JSON or a path to a JSON file,
read once at import so chaos *subprocesses* arm with zero code) or
``faults.arm(...)`` in-process. A plan is a list of rules::

    [{"point": "device.dispatch", "at": 3, "times": 2, "kind": "raise"}]

- ``point``: a registered injection point (arming an unknown point is an
  error — a typo'd plan that silently never fires is worse than none);
- ``at``: fire on the Nth hit of that point (1-based, default 1);
- ``times``: how many consecutive hits fire (default 1; a value larger
  than the driver's retry budget defeats retries, forcing the
  crash/failover paths);
- ``kind``: ``raise`` (InjectedFault), ``hang`` (sleep ``hang_s`` then
  raise — the bounded-timeout analog of a wedged tunnel), ``abort``
  (``os._exit(137)`` — the SIGKILL analog: no handlers, no flush, no
  epilogue), or ``partial_write`` (sink commits only: write a byte
  prefix, then raise — a torn append).

Determinism: triggers are hit-count based, so a fixed input stream
replays the exact same fault schedule; an optional ``prob``/``seed``
pair draws per-hit from a dedicated ``random.Random(seed)`` so even
probabilistic chaos replays bit-identically. Every firing is recorded
(``faults.fired``) and — when telemetry is enabled — emitted as a
``fault_fired:<point>`` instant event and force-flushed to the ledger
stream (a fault is exactly the record that must survive the crash it
causes).

This module imports nothing at module scope beyond the stdlib, so every
layer (telemetry included) can import it without cycles.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


#: Registered injection points — the chaos matrix
#: (tests/test_chaos_matrix.py) covers EVERY entry: inject → crash →
#: resume → exact egress equality. Add a point here only with a matching
#: matrix entry.
INJECTION_POINTS: Dict[str, str] = {
    "device.ship": "operators/base.py:ship — host→device batch transfer",
    "device.dispatch": "telemetry.instrument_jit — instrumented kernel "
                       "dispatch (jitted, mesh window programs, bench "
                       "steps)",
    "device.fetch": "telemetry.fetch — device→host true-sync fetch",
    "window.feed": "streams/windows.py:WindowAssembler.feed — per-event "
                   "window assembly",
    "soa.feed": "streams/soa.py sliding assemblers — per-chunk SoA "
                "window assembly",
    "kafka.fetch": "streams/kafka.py:WireKafkaSource — per-partition "
                   "fetch round",
    "kafka.leader": "streams/kafka_wire.py:_with_leader_retry — "
                    "leader-routed request attempt",
    "sink.write": "streams/sinks.py:TransactionalFileSink.commit — "
                  "egress append (supports partial_write)",
    "driver.window": "driver.py — device-path window processing",
    "overload.admit": "overload.py:OverloadController.admit_item — "
                      "source→assembler admission decision",
    "source.stall": "driver.py:_drive — per-item source pull (the "
                    "slow-consumer / wedged-upstream hang point)",
    "pipeline.ship": "pipeline.py:PipelinedExecutor — overlapped "
                     "host→device pane ship (encode + stage ahead)",
    "pipeline.fetch": "pipeline.py:PipelinedExecutor — lagged "
                      "device→host result fetch (ordered drain)",
    "qserve.register": "qserve.py:QueryRegistry.apply — standing-query "
                       "register/unregister command application (the "
                       "kill-mid-registration-churn point)",
    "dag.node": "dag.py:DataflowDAG — per-node device-path window "
                "processing (the per-node retry/failover ladder's "
                "crash point)",
    "dag.commit": "streams/sinks.py:MultiSink.commit — per-sink append "
                  "inside the atomic unit commit (`at: 2` lands BETWEEN "
                  "one sink's commit and the next — the cut the unit "
                  "checkpoint must survive)",
    "shard.exchange": "parallel/halo.py — grid-partitioned halo "
                      "exchange dispatch (boundary-cell pane ppermute; "
                      "the kill-mid-exchange point the sharded "
                      "kill/resume leg cuts at)",
}

#: Points whose callers implement the cooperative ``partial_write`` kind.
PARTIAL_WRITE_POINTS = frozenset({"sink.write"})

FAULT_KINDS = ("raise", "hang", "partial_write", "abort")

#: The exit code the ``abort`` kind dies with — 128+SIGKILL, the code a
#: real ``kill -9`` produces, so supervisors treat both identically.
ABORT_EXIT_CODE = 137


class InjectedFault(RuntimeError):
    """A deliberately injected failure (never raised by real code paths)."""

    def __init__(self, point: str, kind: str = "raise", hit: int = 0):
        super().__init__(
            f"injected fault at {point!r} (kind={kind}, hit #{hit})"
        )
        self.point = point
        self.kind = kind
        self.hit = hit


@dataclass
class FaultRule:
    """One armed fault: fires on hits ``at .. at+times-1`` of ``point``."""

    point: str
    kind: str = "raise"
    at: int = 1
    times: int = 1
    hang_s: float = 0.05
    prob: float = 1.0
    seed: int = 0
    _rng: random.Random = field(init=False, repr=False, default=None)

    def __post_init__(self):
        if self.point not in INJECTION_POINTS:
            raise ValueError(
                f"unknown injection point {self.point!r} "
                f"(registered: {sorted(INJECTION_POINTS)})"
            )
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r} (kinds: {FAULT_KINDS})"
            )
        if self.kind == "partial_write" \
                and self.point not in PARTIAL_WRITE_POINTS:
            raise ValueError(
                f"kind 'partial_write' is only supported at "
                f"{sorted(PARTIAL_WRITE_POINTS)}, not {self.point!r}"
            )
        if self.at < 1 or self.times < 1:
            raise ValueError("`at` and `times` must be >= 1")
        # Dedicated, seeded stream per rule: probabilistic plans replay
        # bit-identically regardless of global RNG use elsewhere.
        self._rng = random.Random(self.seed)

    def should_fire(self, hit: int) -> bool:
        if not (self.at <= hit < self.at + self.times):
            return False
        if self.prob >= 1.0:
            return True
        return self._rng.random() < self.prob

    def to_dict(self) -> Dict[str, Any]:
        return {
            "point": self.point, "kind": self.kind, "at": self.at,
            "times": self.times, "hang_s": self.hang_s, "prob": self.prob,
            "seed": self.seed,
        }


_RULE_KEYS = {"point", "kind", "at", "times", "hang_s", "prob", "seed"}


def parse_plan(plan) -> List[FaultRule]:
    """A plan is a JSON list of rule objects (a single object is accepted
    as a 1-rule plan). Unknown keys raise — a typo'd trigger that
    silently never fires is the worst failure mode a chaos tool can
    have."""
    if isinstance(plan, dict):
        plan = [plan]
    if not isinstance(plan, list):
        raise ValueError(f"fault plan must be a list of rules, got "
                         f"{type(plan).__name__}")
    rules = []
    for i, r in enumerate(plan):
        if not isinstance(r, dict):
            raise ValueError(f"fault rule #{i} is not an object: {r!r}")
        unknown = sorted(set(r) - _RULE_KEYS)
        if unknown:
            raise ValueError(f"fault rule #{i} has unknown keys {unknown}")
        rules.append(FaultRule(**r))
    return rules


class FaultInjector:
    """Process-global injector (the ops/counters.py one-singleton idiom).

    ``armed`` is the ONLY state the disarmed hot path reads.
    """

    def __init__(self):
        self.armed = False
        self.rules: List[FaultRule] = []
        self.counts: Dict[str, int] = {}
        self.fired: List[Dict[str, Any]] = []
        self._lock = threading.Lock()

    # -- arming ----------------------------------------------------------------

    def arm(self, plan) -> "FaultInjector":
        """Arm a plan (list/dict, JSON string, or a path to a JSON file).
        Resets hit counts — arming IS the start of a chaos schedule."""
        if isinstance(plan, str):
            text = plan.strip()
            if not text.startswith(("[", "{")):
                with open(text) as f:
                    text = f.read()
            plan = json.loads(text)
        with self._lock:
            self.rules = parse_plan(plan)
            self.counts = {}
            self.fired = []
            self.armed = bool(self.rules)
        if self.armed:
            self._telemetry_instant(
                "fault_armed", plan=[r.to_dict() for r in self.rules]
            )
        return self

    def arm_from_env(self) -> bool:
        """Arm from ``SFT_FAULT_PLAN`` (inline JSON or file path); no-op
        when unset. Called once at import so chaos subprocesses arm with
        zero code."""
        spec = os.environ.get("SFT_FAULT_PLAN")
        if not spec:
            return False
        self.arm(spec)
        return True

    def disarm(self):
        with self._lock:
            self.armed = False
            self.rules = []
            self.counts = {}
            self.fired = []

    # -- the hot-path hook -----------------------------------------------------

    def hit(self, point: str) -> Optional[str]:
        """One pass through an injection point. Callers gate on
        ``faults.armed`` so the disarmed cost is one attribute check.

        Raises :class:`InjectedFault` (``raise``/``hang`` kinds), kills
        the process (``abort``), or returns ``"partial_write"`` for the
        caller to cooperate with. Returns ``None`` when nothing fires.
        """
        with self._lock:
            count = self.counts.get(point, 0) + 1
            self.counts[point] = count
            rule = None
            for r in self.rules:
                if r.point == point and r.should_fire(count):
                    rule = r
                    break
        if rule is None:
            return None
        return self._fire(rule, count)

    def _fire(self, rule: FaultRule, count: int) -> Optional[str]:
        # WHETHER a fault fires is the deterministic hit-count rule; the
        # wall timestamp below only annotates the fired-fault telemetry
        # record, and nothing downstream feeds egress/checkpoint bytes.
        rec = {"point": rule.point, "kind": rule.kind, "hit": count,
               "unix": time.time()}  # sfcheck: ok=replay-determinism -- annotation only
        with self._lock:
            self.fired.append(rec)
        self._telemetry_fired(rule.point, rule.kind, count)
        if rule.kind == "abort":
            # The SIGKILL analog: no atexit, no finally, no flush — the
            # process vanishes mid-thought like a real kill -9 / power
            # loss. Crash-consistency is exactly what this rehearses.
            os._exit(ABORT_EXIT_CODE)
        if rule.kind == "hang":
            # Hang-with-timeout: a wedged-but-bounded stall (the tunnel
            # half-open mode), then the failure surfaces.
            time.sleep(rule.hang_s)
            raise InjectedFault(rule.point, "hang", count)
        if rule.kind == "partial_write":
            return "partial_write"
        raise InjectedFault(rule.point, "raise", count)

    # -- telemetry (lazy import: telemetry itself imports this module) ---------

    @staticmethod
    def _telemetry_instant(name: str, **args):
        try:
            from spatialflink_tpu.telemetry import telemetry
        except Exception:  # partial interpreter teardown
            return
        if telemetry.enabled:
            telemetry.emit_instant(name, **args)

    @staticmethod
    def _telemetry_fired(point: str, kind: str, count: int):
        try:
            from spatialflink_tpu.telemetry import telemetry
        except Exception:
            return
        if telemetry.enabled:
            telemetry.record_fault(point, kind=kind, hit=count)


faults = FaultInjector()

# Subprocess arming: a chaos child only needs SFT_FAULT_PLAN in its env.
faults.arm_from_env()
