"""Device-mesh helpers.

The reference scales by Flink keyBy-hash partitioning over a cluster
(`env.setParallelism(n)`, StreamingJob.java:177; conf default 15). The TPU
equivalent is a ``jax.sharding.Mesh`` over the chip slice: window batches
are sharded along a ``data`` axis (the analog of hash partitioning — but
with no shuffle: the grid prune is a flag gather, not a key exchange), and
query sets can shard along a second ``query`` axis. Collectives ride ICI
(psum/pmin/all_gather inside shard_map), not a network stack.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(
    shape: Sequence[int], axis_names: Sequence[str], devices=None
) -> Mesh:
    """Build a mesh of the given logical shape over the first
    prod(shape) devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = int(np.prod(shape))
    if len(devices) < n:
        raise ValueError(f"need {n} devices, have {len(devices)}")
    arr = np.array(devices[:n]).reshape(tuple(shape))
    return Mesh(arr, tuple(axis_names))


def data_mesh(num_devices: Optional[int] = None, devices=None) -> Mesh:
    """1-D data-parallel mesh over all (or the first N) devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = num_devices if num_devices is not None else len(devices)
    return make_mesh((n,), ("data",), devices)


def payload_nbytes(*arrays) -> int:
    """Logical payload bytes of the given arrays, from static shape/dtype
    metadata only — never touches device buffers, so it is safe in
    per-window host paths (the ``telemetry.account_collective`` feeder;
    a replicated operand's bytes ARE its broadcast payload)."""
    total = 0
    for a in arrays:
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        if shape is None or dtype is None:
            continue
        total += int(np.prod(shape, dtype=np.int64)) * int(
            np.dtype(dtype).itemsize
        )
    return total
