from spatialflink_tpu.parallel.mesh import make_mesh, data_mesh  # noqa: F401
from spatialflink_tpu.parallel.sharded import (  # noqa: F401
    sharded_range_query,
    sharded_range_query_2d,
    sharded_knn,
    sharded_knn_multi,
    sharded_join,
    sharded_traj_stats,
)
