"""Multi-host initialization — the DCN scale-out seam.

The reference scales out through Flink's network stack + Kafka
(StreamingJob.java:188-191; conf parallelism 15 at geoflink-conf.yml:55).
Here the distributed backend is JAX itself: after
``jax.distributed.initialize``, ``jax.devices()`` spans every host's
chips, the SAME ``jax.sharding.Mesh`` construction (parallel/mesh.py)
lays a global mesh over them, and every shard_mapped kernel in
``parallel/sharded.py`` runs unchanged — XLA routes intra-slice
collectives over ICI and cross-slice traffic over DCN. No NCCL/MPI and
no code changes in the operator layer: multi-host is a mesh-shape
decision, exactly like single-host multi-chip.

This environment exposes one chip and no second host, so this module is
exercised only for its no-op single-process path; the contract it wraps
(jax.distributed) is the standard JAX multi-host bootstrap.
"""

from __future__ import annotations

import os


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Join a multi-host JAX job; no-op for single-process runs.

    Arguments default from the standard env vars
    (``JAX_COORDINATOR_ADDRESS``, ``JAX_NUM_PROCESSES``,
    ``JAX_PROCESS_ID`` — also set by TPU pod runtimes automatically).
    Returns True when a multi-process group was joined. After a True
    return, build meshes from ``jax.devices()`` (global across hosts) as
    usual; ``mesh_from_config`` device products may then exceed one
    host's chip count.
    """
    addr = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    nproc = num_processes if num_processes is not None else int(
        os.environ.get("JAX_NUM_PROCESSES", "1"))
    if not addr and nproc <= 1:
        return False
    if not addr or nproc <= 1:
        # A half-configured job must not silently run single-host.
        raise ValueError(
            "partial multi-host config: need BOTH a coordinator address "
            f"and num_processes > 1 (got address={addr!r}, "
            f"num_processes={nproc})"
        )
    pid_env = os.environ.get("JAX_PROCESS_ID")
    if process_id is None and pid_env is None:
        raise ValueError(
            "multi-host config without a process id: set JAX_PROCESS_ID "
            "(unique per host) or pass process_id — defaulting every host "
            "to 0 would deadlock the coordinator barrier"
        )
    pid = process_id if process_id is not None else int(pid_env)
    import jax

    jax.distributed.initialize(
        coordinator_address=addr, num_processes=nproc, process_id=pid
    )
    return True
