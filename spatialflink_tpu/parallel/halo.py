"""Grid-partitioned mesh kernels — halo exchange replaces all-gather.

Execution shape (parallel/partition.py has the placement math): window
rows partition by owning shard (contiguous flat-cell ranges), and each
kernel ships ONLY the boundary-cell pane lanes to the two adjacent
shards via ``lax.ppermute`` — two open-chain permutations (no ring
wraparound; edge shards receive ppermute's zero fill, which is an
all-invalid pane) instead of replicating total window state::

    perm_right = [(i, i+1)]  — my RIGHT-boundary pane → right neighbor
    perm_left  = [(i, i-1)]  — my LEFT-boundary pane  → left neighbor

Every wrapper here

- is a public ``(mesh, plan, …)`` kernel with a bit-identical
  single-device counterpart in ``ops/halo.py`` (8-device CPU-mesh
  parity pinned in tests/test_partition.py);
- feeds ``telemetry.account_collective`` from STATIC pane shapes and
  ``telemetry.account_halo_state`` with the unpadded boundary-row bytes
  (the replication-ratio denominator in ``sfprof report``);
- passes the ``shard.exchange`` chaos point before dispatch (the
  kill-mid-exchange leg in tests/test_chaos_matrix.py);
- records per-shard watermarks when given event times (the cross-shard
  watermark gauges + merged min-watermark in telemetry).

Host in, host out: wrappers take numpy arrays, partition on the host
(control plane), dispatch ONE cached jitted shard_map program, fetch,
and scatter results back to original row order — so callers never see
the placement.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from spatialflink_tpu.utils.shardmap_compat import shard_map

from spatialflink_tpu.faults import faults
from spatialflink_tpu.ops.halo import (
    join_partitioned_kernel,
    range_partitioned_kernel,
    registry_bucket_partitioned_kernel,
)
from spatialflink_tpu.parallel.mesh import payload_nbytes
from spatialflink_tpu.parallel.partition import (
    PartitionPlan,
    gather_rows,
    scatter_rows,
    shard_layout,
)
from spatialflink_tpu.telemetry import instrument_jit, telemetry

__all__ = [
    "sharded_range_halo",
    "sharded_join_halo",
    "sharded_tjoin_panes_halo",
    "sharded_registry_bucket_halo",
]


def _perms(n_shards: int):
    """Open-chain halo permutations (static per mesh)."""
    perm_r = tuple((i, i + 1) for i in range(n_shards - 1))
    perm_l = tuple((i, i - 1) for i in range(1, n_shards))
    return perm_r, perm_l


def _exchange(fields, perm):
    """ppermute each pane field; uncovered shards (chain ends) receive
    ppermute's zero fill — an all-invalid pane, no masking needed."""
    return tuple(jax.lax.ppermute(f, "data", list(perm)) for f in fields)


def _check_plan(mesh: Mesh, plan: PartitionPlan):
    n_shards = int(mesh.shape["data"])
    if n_shards != plan.n_shards:
        raise ValueError(
            f"partition plan is for {plan.n_shards} shard(s) but the "
            f"mesh data axis has {n_shards}"
        )
    return n_shards


# Each wrapper below accounts its own exchange INLINE (never via a
# helper): the collective-accounting pass seeds coverage at the
# function that calls account_collective, so the accounting must live
# in the same function whose call graph reaches the ppermute sites.
# The ppermute payload is the padded pane stacks (static metadata); the
# halo-state bytes are the unpadded boundary rows — the state the
# exchange exists to move (replication-ratio denominator).


def _record_shard_watermarks(plan: PartitionPlan, cells, valid, ts):
    """Per-shard watermark gauges from one window's event times (host
    side, telemetry only)."""
    if not telemetry.enabled or ts is None:
        return
    live = np.asarray(valid, bool)
    if not live.any():
        return
    t = np.asarray(ts)[live]
    sh = plan.shard_of(np.asarray(cells)[live])
    for s in np.unique(sh):
        telemetry.record_shard_watermark(int(s), int(t[sh == s].max()))


# -- range -------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _cached_range_halo(mesh, grid_n, layers, guaranteed, approximate):
    n_shards = int(mesh.shape["data"])
    perm_r, perm_l = _perms(n_shards)

    def local(pxy, pok, pcell, qxy, qok, qcell, lqxy, lqok, lqcell,
              rqxy, rqok, rqcell, radius):
        if n_shards > 1:
            # Boundary QUERIES halo: my left neighbor's right pane and
            # my right neighbor's left pane probe my own points.
            flxy, flok, flcell = _exchange(
                (rqxy[0], rqok[0], rqcell[0]), perm_r)
            frxy, frok, frcell = _exchange(
                (lqxy[0], lqok[0], lqcell[0]), perm_l)
            q_xy = jnp.concatenate([qxy[0], flxy, frxy], axis=0)
            q_ok = jnp.concatenate([qok[0], flok, frok], axis=0)
            q_cell = jnp.concatenate([qcell[0], flcell, frcell], axis=0)
        else:
            q_xy, q_ok, q_cell = qxy[0], qok[0], qcell[0]
        keep, dist = range_partitioned_kernel(
            pxy[0], pok[0], pcell[0], q_xy, q_cell, q_ok, radius,
            grid_n=grid_n, layers=layers, guaranteed=guaranteed,
            approximate=approximate,
        )
        return keep[None], dist[None]

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P("data"),) * 12 + (P(),),
        out_specs=(P("data"), P("data")),
        check_vma=False,
    )
    return instrument_jit(jax.jit(fn), name="sharded:range_halo")


def sharded_range_halo(
    mesh: Mesh,
    plan: PartitionPlan,
    xy: np.ndarray,
    valid: np.ndarray,
    cell: np.ndarray,
    query_xy: np.ndarray,
    query_cell: np.ndarray,
    query_valid: np.ndarray,
    radius,
    approximate: bool = False,
    ts=None,
):
    """Grid-partitioned range query: points AND queries partition by
    cell; only boundary-cell QUERY panes halo-exchange (the query side
    is what the replicated path broadcasts whole). Bit-identical to
    ``ops/halo.py:range_partitioned_kernel`` on the full arrays.
    Returns numpy (keep, dist) in original row order."""
    n_shards = _check_plan(mesh, plan)
    xy = np.asarray(xy)
    n = xy.shape[0]
    lp = shard_layout(plan, cell, valid)
    lq = shard_layout(plan, query_cell, query_valid)
    sentinel = plan.num_cells

    def pane(index_map, src_xy, src_cell):
        return (
            gather_rows(index_map, src_xy, 0.0),
            index_map >= 0,
            gather_rows(index_map, src_cell, sentinel).astype(np.int32),
        )

    pxy, pok, pcell = pane(lp.own, xy, cell)
    qxy, qok, qcell = pane(lq.own, query_xy, query_cell)
    lqxy, lqok, lqcell = pane(lq.left, query_xy, query_cell)
    rqxy, rqok, rqcell = pane(lq.right, query_xy, query_cell)
    if faults.armed:
        faults.hit("shard.exchange")
    if n_shards > 1:
        panes = (lqxy, lqok, lqcell, rqxy, rqok, rqcell)
        telemetry.account_collective(
            "ppermute", payload_nbytes(*panes), axis="data",
            calls=len(panes),
        )
        row_bytes = 2 * xy.dtype.itemsize + 4 + 1
        telemetry.account_halo_state(lq.live_boundary_rows * row_bytes)
    _record_shard_watermarks(plan, cell, valid, ts)
    fn = _cached_range_halo(mesh, plan.grid_n, plan.layers,
                            plan.guaranteed, approximate)
    keep2, dist2 = fn(
        jnp.asarray(pxy), jnp.asarray(pok), jnp.asarray(pcell),
        jnp.asarray(qxy), jnp.asarray(qok), jnp.asarray(qcell),
        jnp.asarray(lqxy), jnp.asarray(lqok), jnp.asarray(lqcell),
        jnp.asarray(rqxy), jnp.asarray(rqok), jnp.asarray(rqcell),
        radius,
    )
    dist2 = np.asarray(dist2)
    # Unassigned rows take the kernel's no-active-pair fill — the RESULT
    # dtype's max (the program may run f32 when x64 is off).
    big = np.finfo(dist2.dtype).max
    keep = scatter_rows(lp.own, np.asarray(keep2), n, False)
    dist = scatter_rows(lp.own, dist2, n, big)
    return keep, dist


# -- join --------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _cached_join_halo(mesh, grid_n, layers, budget):
    n_shards = int(mesh.shape["data"])
    perm_r, perm_l = _perms(n_shards)

    def local(lxy, lok, lcell, lgid, rxy, rok, rcell, rgid,
              blxy, blok, blcell, blgid, brxy, brok, brcell, brgid,
              radius):
        if n_shards > 1:
            flxy, flok, flcell, flgid = _exchange(
                (brxy[0], brok[0], brcell[0], brgid[0]), perm_r)
            frxy, frok, frcell, frgid = _exchange(
                (blxy[0], blok[0], blcell[0], blgid[0]), perm_l)
            r_xy = jnp.concatenate([rxy[0], flxy, frxy], axis=0)
            r_ok = jnp.concatenate([rok[0], flok, frok], axis=0)
            r_cell = jnp.concatenate([rcell[0], flcell, frcell], axis=0)
            r_gid = jnp.concatenate([rgid[0], flgid, frgid], axis=0)
        else:
            r_xy, r_ok, r_cell, r_gid = rxy[0], rok[0], rcell[0], rgid[0]
        li, ri, dist, count, over = join_partitioned_kernel(
            lxy[0], lok[0], lcell[0], r_xy, r_ok, r_cell, radius,
            grid_n=grid_n, layers=layers, budget=budget,
        )
        found_l = li >= 0
        found_r = ri >= 0
        lg = jnp.where(found_l, lgid[0][jnp.maximum(li, 0)], -1)
        rg = jnp.where(found_r, r_gid[jnp.maximum(ri, 0)], -1)
        return lg[None], rg[None], dist[None], count[None], over[None]

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P("data"),) * 16 + (P(),),
        out_specs=(P("data"),) * 5,
        check_vma=False,
    )
    return instrument_jit(jax.jit(fn), name="sharded:join_halo")


def sharded_join_halo(
    mesh: Mesh,
    plan: PartitionPlan,
    left_xy: np.ndarray,
    left_valid: np.ndarray,
    left_cell: np.ndarray,
    right_xy: np.ndarray,
    right_valid: np.ndarray,
    right_cell: np.ndarray,
    radius,
    max_pairs: int,
    ts=None,
):
    """Grid-partitioned point ⋈ point join: both sides partition by
    cell; only the RIGHT side's boundary-cell panes halo-exchange (the
    side the replicated join broadcasts whole), with global row ids
    riding the panes. Returns numpy (left_idx, right_idx, dist) of the
    found pairs canonically sorted by (left, right) — the same order the
    single-device ``ops/halo.py:join_partitioned_kernel`` pairs sort to
    — plus (count, overflow) totals."""
    n_shards = _check_plan(mesh, plan)
    left_xy = np.asarray(left_xy)
    right_xy = np.asarray(right_xy)
    ll = shard_layout(plan, left_cell, left_valid)
    lr = shard_layout(plan, right_cell, right_valid)
    sentinel = plan.num_cells

    def pane(index_map, src_xy, src_cell):
        return (
            gather_rows(index_map, src_xy, 0.0),
            index_map >= 0,
            gather_rows(index_map, src_cell, sentinel).astype(np.int32),
            index_map.astype(np.int32),  # global row id (−1 padding)
        )

    lp = pane(ll.own, left_xy, left_cell)
    rp = pane(lr.own, right_xy, right_cell)
    blp = pane(lr.left, right_xy, right_cell)
    brp = pane(lr.right, right_xy, right_cell)
    if faults.armed:
        faults.hit("shard.exchange")
    if n_shards > 1:
        telemetry.account_collective(
            "ppermute", payload_nbytes(*(blp + brp)), axis="data",
            calls=len(blp + brp),
        )
        row_bytes = 2 * right_xy.dtype.itemsize + 4 + 1 + 4
        telemetry.account_halo_state(lr.live_boundary_rows * row_bytes)
    _record_shard_watermarks(plan, left_cell, left_valid, ts)
    budget = int(max_pairs)
    fn = _cached_join_halo(mesh, plan.grid_n, plan.layers, budget)
    out = fn(*(jnp.asarray(a) for a in lp + rp + blp + brp),
             radius)
    lg, rg, dist, count, over = (np.asarray(o) for o in out)
    found = lg.reshape(-1) >= 0
    li = lg.reshape(-1)[found]
    ri = rg.reshape(-1)[found]
    dv = dist.reshape(-1)[found]
    order = np.lexsort((ri, li))
    return (
        li[order], ri[order], dv[order],
        int(count.sum()), int(over.sum()),
    )


def sharded_tjoin_panes_halo(
    mesh: Mesh,
    plan: PartitionPlan,
    ts,
    left_panes,
    right_panes,
    radius,
    ppw: int,
    max_pairs: int,
):
    """Grid-partitioned tjoin pane scan: per slide, the sliding window
    (last ``ppw`` panes per side) joins via :func:`sharded_join_halo` —
    boundary panes halo-exchange instead of the replicated scan's
    all-gather of every pane field. ``left_panes``/``right_panes`` are
    sequences of ``(xy, valid, cell)`` host pane arrays, ``ts`` the
    per-slide window-end times (feeds the per-shard watermark gauges).
    Returns the per-slide list of ``sharded_join_halo`` results."""
    ts = np.asarray(ts)
    results = []
    for i in range(ts.shape[0]):
        lo = max(0, i - int(ppw) + 1)
        lxy, lok, lcell = (
            np.concatenate([p[j] for p in left_panes[lo: i + 1]], axis=0)
            for j in range(3)
        )
        rxy, rok, rcell = (
            np.concatenate([p[j] for p in right_panes[lo: i + 1]], axis=0)
            for j in range(3)
        )
        slide_ts = np.full(lcell.shape[0], int(ts[i]), np.int64)
        results.append(sharded_join_halo(
            mesh, plan, lxy, lok, lcell, rxy, rok, rcell, radius,
            max_pairs, ts=slide_ts,
        ))
    return results


# -- registry bucket (qserve) ------------------------------------------------


@functools.lru_cache(maxsize=None)
def _cached_registry_halo(mesh, grid_n, layers, k, num_segments):
    n_shards = int(mesh.shape["data"])
    perm_r, perm_l = _perms(n_shards)

    def local(pxy, pok, pcell, poid, bl_xy, bl_ok, bl_cell, bl_oid,
              br_xy, br_ok, br_cell, br_oid, qxy, qok, qcell, rad):
        if n_shards > 1:
            flxy, flok, flcell, floid = _exchange(
                (br_xy[0], br_ok[0], br_cell[0], br_oid[0]), perm_r)
            frxy, frok, frcell, froid = _exchange(
                (bl_xy[0], bl_ok[0], bl_cell[0], bl_oid[0]), perm_l)
            p_xy = jnp.concatenate([pxy[0], flxy, frxy], axis=0)
            p_ok = jnp.concatenate([pok[0], flok, frok], axis=0)
            p_cell = jnp.concatenate([pcell[0], flcell, frcell], axis=0)
            p_oid = jnp.concatenate([poid[0], floid, froid], axis=0)
        else:
            p_xy, p_ok, p_cell, p_oid = pxy[0], pok[0], pcell[0], poid[0]
        dist, segment, num_valid, within = \
            registry_bucket_partitioned_kernel(
                p_xy, p_ok, p_cell, p_oid, qxy[0], qcell[0], rad[0],
                qok[0], grid_n=grid_n, layers=layers, k=k,
                num_segments=num_segments,
            )
        return dist[None], segment[None], num_valid[None], within[None]

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P("data"),) * 16,
        out_specs=(P("data"),) * 4,
        check_vma=False,
    )
    return instrument_jit(jax.jit(fn), name="sharded:registry_halo")


def sharded_registry_bucket_halo(
    mesh: Mesh,
    plan: PartitionPlan,
    xy: np.ndarray,
    valid: np.ndarray,
    cell: np.ndarray,
    oid: np.ndarray,
    query_xy: np.ndarray,
    query_cell: np.ndarray,
    radius: np.ndarray,
    query_valid: np.ndarray,
    k: int,
    num_segments: int,
):
    """Grid-partitioned standing-query bucket (qserve): QUERIES partition
    by cell (per-query output ownership), and the POINT side's
    boundary-cell panes halo-exchange so each query is answered entirely
    on its owner shard — replacing the replicated bucket's broadcast of
    the whole standing bucket + per-query flag tables AND its per-lane
    pmin reduction. ``plan`` must be built for the bucket's radius-class
    ceiling (qserve's radius-class bucketing gives one static halo width
    per bucket). Bit-identical to
    ``ops/halo.py:registry_bucket_partitioned_kernel`` on the full
    arrays; returns numpy (dist (Q, k), segment (Q, k), num_valid (Q,),
    within (Q,)) in original query order."""
    n_shards = _check_plan(mesh, plan)
    xy = np.asarray(xy)
    q = np.asarray(query_xy).shape[0]
    lp = shard_layout(plan, cell, valid)
    lq = shard_layout(plan, query_cell, query_valid)
    sentinel = plan.num_cells

    def ppane(index_map):
        return (
            gather_rows(index_map, xy, 0.0),
            index_map >= 0,
            gather_rows(index_map, cell, sentinel).astype(np.int32),
            gather_rows(index_map, oid, 0).astype(np.int32),
        )

    pp = ppane(lp.own)
    blp = ppane(lp.left)
    brp = ppane(lp.right)
    qp = (
        gather_rows(lq.own, query_xy, 0.0),
        lq.own >= 0,
        gather_rows(lq.own, query_cell, sentinel).astype(np.int32),
        gather_rows(lq.own, radius, 0.0),
    )
    if faults.armed:
        faults.hit("shard.exchange")
    if n_shards > 1:
        telemetry.account_collective(
            "ppermute", payload_nbytes(*(blp + brp)), axis="data",
            calls=len(blp + brp),
        )
        row_bytes = 2 * xy.dtype.itemsize + 4 + 1 + 4
        telemetry.account_halo_state(lp.live_boundary_rows * row_bytes)
    fn = _cached_registry_halo(mesh, plan.grid_n, plan.layers, int(k),
                               int(num_segments))
    dist2, seg2, nv2, win2 = fn(
        *(jnp.asarray(a) for a in pp + blp + brp),
        jnp.asarray(qp[0]), jnp.asarray(qp[1]), jnp.asarray(qp[2]),
        jnp.asarray(qp[3]),
    )
    dist2 = np.asarray(dist2)
    big = np.finfo(dist2.dtype).max
    return (
        scatter_rows(lq.own, dist2, q, big),
        scatter_rows(lq.own, np.asarray(seg2), q, -1),
        scatter_rows(lq.own, np.asarray(nv2), q, 0),
        scatter_rows(lq.own, np.asarray(win2), q, 0),
    )
