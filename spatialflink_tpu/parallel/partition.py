"""Grid-partitioned placement — the host-side partition planner.

The reference scales out by hashing grid-cell keys across Flink
key-groups (``keyBy(gridID)``, StreamingJob.java:177): neighboring cells
land on arbitrary workers, so every neighbor-cell probe is a network
shuffle. Here placement follows the GRID instead: each shard owns a
*contiguous range of flat cell ids* (cells sorted by grid index,
balanced by live occupancy), so a query's candidate square — every cell
within Chebyshev distance L_c of its own cell — maps to a bounded range
of *flat* positions::

    flat = xi * n + yi      ⇒      |Δflat| ≤ L · (n + 1)   when  cheb ≤ L

That bound is the **halo width** ``H = L_c · (n + 1)``: a shard owning
flat cells ``[lo, hi)`` can answer every one of its probes from its own
rows plus its neighbors' boundary rows in ``[lo − H, lo)`` and
``[hi, hi + H)``. Neighbor-cell probes therefore become a fixed-shape
``lax.ppermute`` of boundary-cell pane lanes (parallel/halo.py) instead
of an all-gather of total window state.

Single-hop contract: the halo only reaches ADJACENT shards, so every
shard's cell range must span at least ``H`` flat positions —
``plan_partition`` enforces it (clamping occupancy-skewed cuts, raising
when the grid is too small for the shard count at this radius).

Everything here is host-side numpy (control plane); the module imports
no jax so ``checkpoint.py`` can restore a serialized plan without
touching the device runtime.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

import numpy as np

PLAN_VERSION = 1

_PLAN_KEYS = frozenset({
    "version", "n_shards", "grid_n", "num_cells", "layers",
    "guaranteed", "halo", "bounds",
})


def halo_width(grid_n: int, layers: int) -> int:
    """Flat-position halo width for a Chebyshev layer count:
    ``cheb(a, b) ≤ L  ⇒  |flat(a) − flat(b)| ≤ L·(n+1)``."""
    return max(int(layers), 0) * (int(grid_n) + 1)


@dataclass(frozen=True, eq=False)
class PartitionPlan:
    """Contiguous flat-cell ranges per shard.

    ``bounds`` is ``(n_shards + 1,)`` int64 with ``bounds[0] == 0`` and
    ``bounds[-1] == num_cells``: shard ``s`` owns flat cells
    ``[bounds[s], bounds[s+1])``. The out-of-grid sentinel cell
    (``num_cells``) is assigned to the LAST shard — its rows never probe
    (pair activity requires both cells in-grid), they just need a home.

    ``layers``/``guaranteed`` are the candidate / guaranteed Chebyshev
    layer counts the plan was built for (grid.py layer math); ``halo``
    is the derived flat-position width.
    """

    n_shards: int
    grid_n: int
    num_cells: int
    layers: int
    guaranteed: int
    halo: int
    bounds: np.ndarray

    def shard_of(self, cells: np.ndarray) -> np.ndarray:
        """Owning shard per flat cell id (out-of-grid → last shard)."""
        cells = np.asarray(cells)
        return np.searchsorted(
            self.bounds[1:-1], cells, side="right"
        ).astype(np.int32)

    def shard_widths(self) -> np.ndarray:
        return np.diff(self.bounds)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "version": PLAN_VERSION,
            "n_shards": int(self.n_shards),
            "grid_n": int(self.grid_n),
            "num_cells": int(self.num_cells),
            "layers": int(self.layers),
            "guaranteed": int(self.guaranteed),
            "halo": int(self.halo),
            "bounds": [int(b) for b in self.bounds],
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PartitionPlan":
        if not isinstance(d, dict):
            raise ValueError(
                f"partition plan must be a dict, got {type(d).__name__}"
            )
        unknown = sorted(set(d) - _PLAN_KEYS)
        if unknown:
            raise ValueError(f"partition plan has unknown keys {unknown}")
        missing = sorted(_PLAN_KEYS - set(d))
        if missing:
            raise ValueError(f"partition plan is missing keys {missing}")
        if int(d["version"]) != PLAN_VERSION:
            raise ValueError(
                f"partition plan version {d['version']} != {PLAN_VERSION}"
            )
        bounds = np.asarray(d["bounds"], np.int64)
        n_shards = int(d["n_shards"])
        num_cells = int(d["num_cells"])
        if bounds.shape != (n_shards + 1,):
            raise ValueError(
                f"partition plan bounds shape {bounds.shape} does not "
                f"match n_shards={n_shards}"
            )
        if bounds[0] != 0 or bounds[-1] != num_cells \
                or np.any(np.diff(bounds) < 0):
            raise ValueError("partition plan bounds are not a monotone "
                             "cover of [0, num_cells]")
        return cls(
            n_shards=n_shards,
            grid_n=int(d["grid_n"]),
            num_cells=num_cells,
            layers=int(d["layers"]),
            guaranteed=int(d["guaranteed"]),
            halo=int(d["halo"]),
            bounds=bounds,
        )


def plan_partition(
    grid,
    n_shards: int,
    radius: float,
    occupancy: Optional[np.ndarray] = None,
) -> PartitionPlan:
    """Assign contiguous flat-cell ranges to shards.

    Cells are already sorted by grid index (flat id); cuts balance
    *cumulative live occupancy* (per-cell live counts from the
    compaction planner's view of the window; uniform when ``None``).
    Cuts are then clamped so every shard spans at least the halo width —
    the single-hop halo-exchange contract.
    """
    n_shards = int(n_shards)
    if n_shards < 1:
        raise ValueError("n_shards must be >= 1")
    lc = grid.candidate_layers(radius)
    lg = grid.guaranteed_layers(radius)
    halo = halo_width(grid.n, lc)
    num_cells = grid.num_cells
    min_width = max(halo, 1)
    if n_shards * min_width > num_cells:
        raise ValueError(
            f"grid of {num_cells} cells cannot give {n_shards} shard(s) "
            f"a minimum width of {min_width} (halo for radius {radius!r})"
            f" — use a finer grid or fewer shards"
        )
    if occupancy is None:
        weights = np.ones(num_cells, np.float64)
    else:
        # Accepts (num_cells,) or (num_cells + 1,) — the compaction
        # planner's live counts include the out-of-grid sentinel bucket,
        # which carries no placement weight.
        weights = np.zeros(num_cells, np.float64)
        occ = np.asarray(occupancy, np.float64).reshape(-1)
        k = min(occ.shape[0], num_cells)
        weights[:k] = occ[:k]
    csum = np.cumsum(weights)
    total = float(csum[-1]) if csum.size else 0.0
    if total <= 0:
        cuts = np.linspace(0, num_cells, n_shards + 1)[1:-1]
        cuts = np.round(cuts).astype(np.int64)
    else:
        targets = total * np.arange(1, n_shards, dtype=np.float64) / n_shards
        cuts = (np.searchsorted(csum, targets, side="left") + 1).astype(
            np.int64
        )
    bounds = np.empty(n_shards + 1, np.int64)
    bounds[0] = 0
    bounds[1:-1] = cuts
    bounds[-1] = num_cells
    # Forward then backward clamp: every shard keeps >= min_width cells,
    # so occupancy skew can narrow a shard only down to the halo width.
    for s in range(1, n_shards):
        bounds[s] = max(bounds[s], bounds[s - 1] + min_width)
    for s in range(n_shards - 1, 0, -1):
        bounds[s] = min(bounds[s], bounds[s + 1] - min_width)
    return PartitionPlan(
        n_shards=n_shards,
        grid_n=int(grid.n),
        num_cells=int(num_cells),
        layers=int(lc),
        guaranteed=int(lg),
        halo=int(halo),
        bounds=bounds,
    )


@dataclass(frozen=True, eq=False)
class ShardLayout:
    """Host index maps for one partitioned window.

    ``own``: (n_shards, cap) int64 original-row indices per shard (−1
    padding); ``left``/``right``: (n_shards, halo_cap) boundary-pane
    rows — ``left[s]`` are shard ``s``'s rows within the halo of its
    LEFT edge (shipped to ``s−1``), ``right[s]`` within its RIGHT edge
    (shipped to ``s+1``). Capacities ride ``pick_capacity`` rungs so
    shard-count and occupancy churn stay on the ladder.
    """

    plan: PartitionPlan
    cap: int
    halo_cap: int
    own: np.ndarray
    left: np.ndarray
    right: np.ndarray
    counts: np.ndarray

    @property
    def live_boundary_rows(self) -> int:
        """Unpadded boundary-pane rows — the true boundary-state lanes
        the halo exchange exists to ship (replication-ratio
        denominator)."""
        return int((self.left >= 0).sum() + (self.right >= 0).sum())


def _index_map(rows_per_shard, n_shards: int, cap: int) -> np.ndarray:
    out = np.full((n_shards, cap), -1, np.int64)
    for s, rows in enumerate(rows_per_shard):
        out[s, : rows.shape[0]] = rows
    return out


def shard_layout(
    plan: PartitionPlan, cells: np.ndarray, valid: np.ndarray
) -> ShardLayout:
    """Partition one window's live rows by owning shard and extract the
    boundary panes. Original row order is preserved within each shard
    (stable), so the layout — and everything scattered back through it —
    is replay-deterministic."""
    from spatialflink_tpu.ops.compaction import pick_capacity

    cells = np.asarray(cells)
    live = np.asarray(valid, bool)
    n = cells.shape[0]
    idx = np.nonzero(live)[0]
    shard = plan.shard_of(cells[idx])
    order = np.argsort(shard, kind="stable")
    sidx = idx[order]
    scell = cells[idx][order]
    counts = np.bincount(shard, minlength=plan.n_shards)
    starts = np.concatenate([[0], np.cumsum(counts)])
    own_rows, left_rows, right_rows = [], [], []
    for s in range(plan.n_shards):
        rows = sidx[starts[s]: starts[s + 1]]
        rcell = scell[starts[s]: starts[s + 1]]
        own_rows.append(rows)
        left_rows.append(rows[rcell < plan.bounds[s] + plan.halo])
        right_rows.append(rows[rcell >= plan.bounds[s + 1] - plan.halo])
    cap_top = max(n, 1)
    cap = pick_capacity(max(int(counts.max()) if counts.size else 1, 1),
                        cap_top)
    hmax = max(
        [max(int(lr.shape[0]), int(rr.shape[0]))
         for lr, rr in zip(left_rows, right_rows)] + [1]
    )
    halo_cap = pick_capacity(hmax, cap_top)
    return ShardLayout(
        plan=plan,
        cap=int(cap),
        halo_cap=int(halo_cap),
        own=_index_map(own_rows, plan.n_shards, int(cap)),
        left=_index_map(left_rows, plan.n_shards, int(halo_cap)),
        right=_index_map(right_rows, plan.n_shards, int(halo_cap)),
        counts=counts,
    )


def gather_rows(index_map: np.ndarray, arr: np.ndarray, fill) -> np.ndarray:
    """(n_shards, cap) index map + (N, …) array → (n_shards, cap, …)
    per-shard stack; −1 padding lanes take ``fill``."""
    arr = np.asarray(arr)
    safe = np.maximum(index_map, 0)
    out = arr[safe].copy()
    out[index_map < 0] = fill
    return out


def scatter_rows(
    index_map: np.ndarray, values: np.ndarray, n_rows: int, fill
) -> np.ndarray:
    """Inverse of :func:`gather_rows`: per-shard (n_shards, cap, …)
    outputs → (n_rows, …) in original row order (unassigned rows take
    ``fill``)."""
    values = np.asarray(values)
    out = np.full((n_rows,) + values.shape[2:], fill, values.dtype)
    m = index_map >= 0
    out[index_map[m]] = values[m]
    return out
