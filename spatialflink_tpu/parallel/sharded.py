"""Multi-chip sharded query kernels via ``shard_map``.

Sharding layout (the scaling-book recipe: pick a mesh, annotate shardings,
let XLA insert collectives):

  - **range**: points sharded over ``data``; optionally queries sharded
    over ``query`` with a psum-OR across the query axis. Fully local
    compute, no collective in the 1-D case — the analog of the reference's
    keyBy(gridID) partitioning minus the shuffle.
  - **kNN**: points sharded over ``data``; each shard computes its local
    per-object segment-min, then a ``pmin`` collective over ``data``
    reduces object minima across shards and the (replicated) top-k runs on
    the reduced table. This replaces the reference's single-subtask
    windowAll merge bottleneck (KNNQuery.java:204-308) with one ICI
    all-reduce.
  - **join**: left side sharded over ``data``, cell-sorted right side
    replicated (broadcast once per window) — each shard joins its left
    slice; pair outputs stay sharded.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

# Handles both the symbol's home and the check_rep→check_vma rename.
from spatialflink_tpu.utils.shardmap_compat import shard_map

from spatialflink_tpu.ops.distances import point_point_distance
from spatialflink_tpu.ops.join import JoinResult, join_kernel
from spatialflink_tpu.ops.knn import KnnResult
from spatialflink_tpu.ops.range import _emit_mask
from spatialflink_tpu.parallel.mesh import payload_nbytes
from spatialflink_tpu.telemetry import telemetry


def _itemsize(dtype) -> int:
    return int(np.dtype(dtype).itemsize)


def mesh_from_config(shape):
    """Build the runtime mesh from the config's ``deviceMesh`` list
    (config.py: Params.device_mesh — the ``parallelism`` analog of
    conf/geoflink-conf.yml:55). 1-D → ("data",); 2-D → ("data", "query").
    A product of 1 means single-device: returns None.

    The data axis must be a power of two: window batches are padded to
    power-of-two buckets (utils/padding.py), so only power-of-two axes
    divide every batch.
    """
    import numpy as np

    from spatialflink_tpu.parallel.mesh import make_mesh

    shape = [int(s) for s in shape]
    total = int(np.prod(shape)) if shape else 1
    if total <= 1:
        return None
    if shape[0] & (shape[0] - 1):
        raise ValueError(
            f"deviceMesh data axis must be a power of two (window batches "
            f"are padded to power-of-two buckets); got {shape[0]}"
        )
    names = ("data",) if len(shape) == 1 else ("data", "query")
    return make_mesh(tuple(shape), names[: len(shape)])


@functools.lru_cache(maxsize=None)
def _cached_sharded_window(mesh, kernel, data_idx, n_args, statics, topk,
                           reduce=False):
    skw = dict(statics)
    in_specs = tuple(
        P("data") if i in data_idx else P() for i in range(n_args)
    )
    if topk:
        def local(*args):
            base = jax.lax.axis_index("data") * args[data_idx[0]].shape[0]
            return kernel(*args, axis_name="data", index_base=base, **skw)

        out_specs = KnnResult(P(), P(), P(), P())
    elif reduce:
        # Segment-reduction kernels (e.g. tRange's per-trajectory hit
        # flags): the kernel's axis_name hook all-reduces its per-shard
        # segment reduction; the output is replicated.
        def local(*args):
            return kernel(*args, axis_name="data", **skw)

        out_specs = P()
    else:
        def local(*args):
            return kernel(*args, **skw)

        out_specs = (P("data"), P("data"))
    fn = shard_map(
        local, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=False,
    )
    return jax.jit(fn)


def sharded_window_kernel(mesh, kernel, data_idx, n_args, topk=False,
                          reduce=False, **statics):
    """jit + shard_map a fused window kernel over a mesh's ``data`` axis.

    This is how the operator layer executes on a mesh: the SAME fused
    per-window program the single-device path jits is shard_mapped with the
    stream-axis arguments (positions ``data_idx``) split over ``data`` and
    everything else replicated — the moral equivalent of the reference's
    keyBy partitioning (StreamingJob.java:177, parallelism default 15 at
    conf/geoflink-conf.yml:55) without the shuffle.

    ``topk=False``: elementwise kernels, outputs (keep, dist) stay sharded.
    ``topk=True``: kNN kernels — the kernel's ``axis_name``/``index_base``
    hooks pmin-reduce per-object minima across shards (one ICI all-reduce
    replacing the reference's single-subtask windowAll merge,
    KNNQuery.java:204-308); outputs are replicated.

    Wrappers are cached per (mesh, kernel, statics) so repeated windows
    reuse the compiled program.
    """
    fn = _cached_sharded_window(
        mesh, kernel, tuple(data_idx), n_args,
        tuple(sorted(statics.items())), topk, reduce,
    )
    return _AccountedProgram(fn, tuple(data_idx), topk, reduce,
                             dict(statics))


class _AccountedProgram:
    """Accounts the generic mesh program's collective footprint at call
    time (host-side, from the concrete args' static shapes), then calls
    the cached jitted program. Attribute access forwards to the jit
    object so ``instrument_jit``'s lower()/cost hooks keep working.

    topk → the kernel's axis_name hook pmin-reduces its per-object
    minima + representative tables ((num_segments,) each); reduce → a
    psum of the replicated segment reduction; elementwise → no explicit
    collective, so the replicated operands' broadcast is the traffic.
    """

    __slots__ = ("_fn", "_data_idx", "_topk", "_reduce", "_statics")

    def __init__(self, fn, data_idx, topk, reduce, statics):
        self._fn = fn
        self._data_idx = frozenset(data_idx)
        self._topk = topk
        self._reduce = reduce
        self._statics = statics

    def __call__(self, *args, **kwargs):
        if telemetry.enabled:
            rep = payload_nbytes(*(
                a for i, a in enumerate(args) if i not in self._data_idx
            ))
            if self._topk or self._reduce:
                nseg = int(self._statics.get("num_segments", 0))
                ref = (args[min(self._data_idx)]
                       if self._data_idx and args else None)
                elem = (_itemsize(ref.dtype)
                        if ref is not None and hasattr(ref, "dtype") else 8)
                table = 2 * nseg * elem if nseg else max(rep, elem)
                telemetry.account_collective(
                    "pmin" if self._topk else "psum", table, axis="data"
                )
            if rep:
                telemetry.account_collective("broadcast", rep, axis="data")
        return self._fn(*args, **kwargs)

    def __getattr__(self, attr):
        return getattr(self._fn, attr)


def sharded_range_query(
    mesh: Mesh,
    xy: jnp.ndarray,
    valid: jnp.ndarray,
    flags: jnp.ndarray,
    query_xy: jnp.ndarray,
    radius,
    approximate: bool = False,
):
    """Data-parallel range query. ``xy``/``valid``/``flags`` shard over
    ``data``; the query set is replicated. Returns (keep, min_dist) sharded
    like the inputs."""
    # Fully local compute: the replicated query set's broadcast is the
    # only cross-chip traffic.
    telemetry.account_collective(
        "broadcast", payload_nbytes(query_xy), axis="data"
    )

    def local(xy_l, valid_l, flags_l, q):
        d = point_point_distance(xy_l[:, None, :], q[None, :, :])
        min_dist = jnp.min(d, axis=1)
        return _emit_mask(valid_l, flags_l, min_dist, radius, approximate), min_dist

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P("data"), P("data"), P("data"), P()),
        out_specs=(P("data"), P("data")),
    )
    return fn(xy, valid, flags, query_xy)


def sharded_range_query_2d(
    mesh: Mesh,
    xy: jnp.ndarray,
    valid: jnp.ndarray,
    flags: jnp.ndarray,
    query_xy: jnp.ndarray,
    radius,
    approximate: bool = False,
):
    """2-D sharded range query: points over ``data``, query set over
    ``query``. Each (data, query) tile evaluates its query slice; a psum-OR
    over the ``query`` axis merges per-slice hits — the collective pattern
    for large query sets (e.g. 1k query polygons sharded across chips).
    Returns (keep sharded over data, min_dist sharded over data)."""
    # pmin of each data tile's per-point min-dist vector across the
    # query axis (one lane per point).
    telemetry.account_collective(
        "pmin", int(xy.shape[0]) * _itemsize(xy.dtype), axis="query"
    )

    def local(xy_l, valid_l, flags_l, q_l):
        d = point_point_distance(xy_l[:, None, :], q_l[None, :, :])
        local_min = jnp.min(d, axis=1)
        # Min distance across the query shards (ICI all-reduce on "query").
        min_dist = jax.lax.pmin(local_min, axis_name="query")
        keep = _emit_mask(valid_l, flags_l, min_dist, radius, approximate)
        return keep, min_dist

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P("data"), P("data"), P("data"), P("query")),
        out_specs=(P("data"), P("data")),
        check_vma=False,
    )
    return fn(xy, valid, flags, query_xy)


def sharded_knn(
    mesh: Mesh,
    xy: jnp.ndarray,
    valid: jnp.ndarray,
    flags: jnp.ndarray,
    oid: jnp.ndarray,
    query_xy: jnp.ndarray,
    radius,
    k: int,
    num_segments: int,
) -> KnnResult:
    """Multi-chip kNN: local segment-min per shard → pmin over ``data`` →
    replicated top-k. Object ids are global dense ints (host interning),
    so the (num_segments,) minima table is the only cross-chip traffic —
    one psum-sized all-reduce instead of the reference's windowAll
    re-shuffle of every candidate."""
    # Two (num_segments,) pmin tables (minima + packed representatives)
    # plus the replicated query point's broadcast.
    telemetry.account_collective(
        "pmin", 2 * int(num_segments) * _itemsize(xy.dtype), axis="data"
    )
    telemetry.account_collective(
        "broadcast", payload_nbytes(query_xy), axis="data"
    )

    from spatialflink_tpu.ops.knn import _topk_from_point_dists

    def local(xy_l, valid_l, flags_l, oid_l, q):
        dist = point_point_distance(xy_l, q[None, :])
        # Same top-k core as the single-chip kernel, with the per-object
        # minima/representatives pmin-reduced over the data axis and local
        # indices offset to global ones.
        base = jax.lax.axis_index("data") * xy_l.shape[0]
        return _topk_from_point_dists(
            dist, valid_l, flags_l, oid_l, radius, k, num_segments,
            axis_name="data", index_base=base,
        )

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P("data"), P("data"), P("data"), P("data"), P()),
        out_specs=KnnResult(P(), P(), P(), P()),
        check_vma=False,
    )
    return fn(xy, valid, flags, oid, query_xy)


@functools.lru_cache(maxsize=None)
def _cached_knn_multi(mesh, k, num_segments, query_sharded):
    from spatialflink_tpu.ops.cells import gather_cell_flags
    from spatialflink_tpu.ops.knn import _topk_from_point_dists

    def local(xy_l, valid_l, cell_l, ft_l, oid_l, q_l, radius):
        base = jax.lax.axis_index("data") * xy_l.shape[0]

        def one(q_xy, ftab):
            dist = point_point_distance(xy_l, q_xy[None, :])
            return _topk_from_point_dists(
                dist, valid_l, gather_cell_flags(cell_l, ftab), oid_l,
                radius, k, num_segments,
                axis_name="data", index_base=base,
            )

        # Same query blocking as knn_multi_query_kernel: vmap only
        # ``block`` query lanes at a time under lax.map so peak memory is
        # O(block × N_local), not O(Q_local × N_local).
        q_total = q_l.shape[0]
        block = next(b for b in (32, 16, 8, 4, 2, 1) if q_total % b == 0)

        def blk(args):
            q_b, f_b = args
            return jax.vmap(one)(q_b, f_b)

        res = jax.lax.map(
            blk,
            (
                q_l.reshape(-1, block, 2),
                ft_l.reshape(q_total // block, block, -1),
            ),
        )
        return KnnResult(*[x.reshape((q_total,) + x.shape[2:]) for x in res])

    qspec = P("query") if query_sharded else P()
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(
            P("data"), P("data"), P("data"), qspec, P("data"), qspec, P(),
        ),
        out_specs=KnnResult(qspec, qspec, qspec, qspec),
        check_vma=False,
    )
    return jax.jit(fn)


def sharded_knn_multi(
    mesh: Mesh,
    xy: jnp.ndarray,
    valid: jnp.ndarray,
    cell: jnp.ndarray,
    flags_tables: jnp.ndarray,
    oid: jnp.ndarray,
    query_xy: jnp.ndarray,
    radius,
    k: int,
    num_segments: int,
) -> KnnResult:
    """Sharded MULTI-query kNN: points over ``data``; with a 2-D mesh the
    query batch (and its per-query flag tables) additionally shards over
    ``query``. Each (data[, query]) tile answers its query slice against
    its point shard; per-object minima pmin-reduce over ``data`` (batched
    collective under vmap — one ICI all-reduce per query lane), and the
    (Q, k) results stay sharded over ``query`` (replicated on 1-D
    meshes). The scale-out form of ops/knn.py:knn_multi_query_kernel for
    query sets too large for one chip's flag-table memory. On a 2-D mesh
    Q must divide the query-axis size."""
    query_sharded = "query" in mesh.shape
    # One batched pmin per query lane (two (num_segments,) tables each);
    # on 1-D meshes the query batch + flag tables replicate (broadcast).
    lanes = int(query_xy.shape[0])
    telemetry.account_collective(
        "pmin", 2 * lanes * int(num_segments) * _itemsize(xy.dtype),
        axis="data", calls=lanes,
    )
    if not query_sharded:
        telemetry.account_collective(
            "broadcast", payload_nbytes(query_xy, flags_tables),
            axis="data",
        )
    fn = _cached_knn_multi(mesh, k, num_segments, query_sharded)
    return fn(xy, valid, cell, flags_tables, oid, query_xy, radius)


@functools.lru_cache(maxsize=None)
def _cached_registry_bucket(mesh, k, num_segments):
    from spatialflink_tpu.ops.query_registry import (
        RegistryBucketResult,
        registry_bucket_query,
    )

    def local(xy_l, valid_l, cell_l, ft, oid_l, q, r, qok):
        base = jax.lax.axis_index("data") * xy_l.shape[0]

        def one(q_xy, ftab, rad, ok):
            return registry_bucket_query(
                xy_l, valid_l, cell_l, ftab, oid_l, q_xy, rad, ok,
                k=k, num_segments=num_segments,
                axis_name="data", index_base=base,
            )

        # Same query blocking as the single-device bucket kernel: vmap
        # only ``block`` query lanes at a time under lax.map so peak
        # memory is O(block × N_local).
        q_total = q.shape[0]
        block = next(b for b in (32, 16, 8, 4, 2, 1) if q_total % b == 0)

        def blk(args):
            q_b, f_b, r_b, ok_b = args
            return jax.vmap(one)(q_b, f_b, r_b, ok_b)

        res = jax.lax.map(
            blk,
            (
                q.reshape(-1, block, 2),
                ft.reshape(q_total // block, block, -1),
                r.reshape(-1, block),
                qok.reshape(-1, block),
            ),
        )
        return RegistryBucketResult(
            *[x.reshape((q_total,) + x.shape[2:]) for x in res]
        )

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(
            P("data"), P("data"), P("data"), P(), P("data"), P(), P(), P(),
        ),
        out_specs=RegistryBucketResult(P(), P(), P(), P(), P()),
        check_vma=False,
    )
    return jax.jit(fn)


def sharded_registry_bucket(
    mesh: Mesh,
    xy: jnp.ndarray,
    valid: jnp.ndarray,
    cell: jnp.ndarray,
    flags_tables: jnp.ndarray,
    oid: jnp.ndarray,
    query_xy: jnp.ndarray,
    radius: jnp.ndarray,
    query_valid: jnp.ndarray,
    k: int,
    num_segments: int,
):
    """Sharded standing-query bucket (qserve): points over ``data``, the
    query bucket (coords, per-query radii, flag tables, validity lanes)
    replicated. Per-object minima pmin-reduce over ``data`` inside
    ``ops/query_registry.py:registry_bucket_query`` — the same one-ICI-
    all-reduce shape as ``sharded_knn_multi`` — and the ``within``
    exactness counter is computed on the REDUCED table, so results
    (top-k rows, counts, overflow) are bit-identical to the
    single-device ``registry_bucket_kernel`` (CPU-mesh parity pinned in
    tests/test_qserve.py)."""
    # Same batched-pmin shape as sharded_knn_multi; the whole standing
    # bucket (coords, radii, flag tables, validity) replicates.
    lanes = int(query_xy.shape[0])
    telemetry.account_collective(
        "pmin", 2 * lanes * int(num_segments) * _itemsize(xy.dtype),
        axis="data", calls=lanes,
    )
    telemetry.account_collective(
        "broadcast",
        payload_nbytes(query_xy, radius, flags_tables, query_valid),
        axis="data",
    )
    fn = _cached_registry_bucket(mesh, k, num_segments)
    return fn(xy, valid, cell, flags_tables, oid, query_xy, radius,
              query_valid)


def sharded_traj_stats(
    mesh: Mesh,
    xy: jnp.ndarray,
    ts: jnp.ndarray,
    oid: jnp.ndarray,
    valid: jnp.ndarray,
    num_segments: int,
):
    """Sequence-parallel trajectory statistics with halo exchange.

    The long-trajectory analog of sequence/context parallelism: the
    (oid, ts)-sorted point sequence is sharded over ``data``; each shard
    computes consecutive-point contributions locally, and the one pair that
    straddles each shard boundary is recovered by passing every shard's
    *last* point to its right neighbor via ``lax.ppermute`` (a ring halo
    exchange over ICI). Per-object partials are then psum'd. Exactly equals
    the single-device ops.trajectory.traj_stats_kernel.
    """
    from spatialflink_tpu.ops.distances import point_point_distance

    # Ring halo (every shard ships its last xy/ts/oid/valid row) plus
    # three (num_segments,) psum tables (spatial, temporal, count).
    ndev = int(mesh.shape["data"])
    halo = ndev * (2 * _itemsize(xy.dtype) + _itemsize(ts.dtype)
                   + _itemsize(oid.dtype) + 1)
    telemetry.account_collective("ppermute", halo, axis="data", calls=4)
    telemetry.account_collective(
        "psum", int(num_segments) * (2 * _itemsize(xy.dtype) + 4),
        axis="data", calls=3,
    )

    def local(xy_l, ts_l, oid_l, valid_l):
        # The ppermute ring needs a STATIC shard count; read it from the
        # mesh (lax.axis_size only exists on newer jax releases — same era
        # as the check_vma rename, see utils/shardmap_compat.py).
        n_shards = int(mesh.shape["data"])
        # Ring halo: receive the previous shard's last (xy, ts, oid, valid).
        perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
        prev_xy = jax.lax.ppermute(xy_l[-1], "data", perm)
        prev_ts = jax.lax.ppermute(ts_l[-1], "data", perm)
        prev_oid = jax.lax.ppermute(oid_l[-1], "data", perm)
        prev_valid = jax.lax.ppermute(valid_l[-1], "data", perm)
        # Shard 0 has no predecessor: mask its halo pair.
        first = jax.lax.axis_index("data") == 0
        prev_valid = prev_valid & ~first

        xy_ext = jnp.concatenate([prev_xy[None, :], xy_l], axis=0)
        ts_ext = jnp.concatenate([prev_ts[None], ts_l], axis=0)
        oid_ext = jnp.concatenate([prev_oid[None], oid_l], axis=0)
        valid_ext = jnp.concatenate([prev_valid[None], valid_l], axis=0)

        same_traj = (oid_ext[1:] == oid_ext[:-1]) & valid_ext[1:] & valid_ext[:-1]
        seg_d = point_point_distance(xy_ext[1:], xy_ext[:-1])
        seg_t = (ts_ext[1:] - ts_ext[:-1]).astype(seg_d.dtype)
        spatial = jax.ops.segment_sum(
            jnp.where(same_traj, seg_d, 0), oid_l, num_segments=num_segments
        )
        temporal = jax.ops.segment_sum(
            jnp.where(same_traj, seg_t, 0), oid_l, num_segments=num_segments
        )
        count = jax.ops.segment_sum(
            valid_l.astype(jnp.int32), oid_l, num_segments=num_segments
        )
        spatial = jax.lax.psum(spatial, "data")
        temporal = jax.lax.psum(temporal, "data")
        count = jax.lax.psum(count, "data")
        speed = jnp.where(
            temporal > 0, spatial / jnp.where(temporal > 0, temporal, 1), 0.0
        )
        return spatial, temporal, count, speed

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P("data"), P("data"), P("data"), P("data")),
        out_specs=(P(), P(), P(), P()),
        check_vma=False,
    )
    return fn(xy, ts, oid, valid)


@functools.lru_cache(maxsize=None)
def _cached_sharded_join_compact(mesh, grid_n, cap, max_pairs):
    n_shards = int(mesh.shape["data"])
    local_budget = max_pairs // n_shards

    def fn(left_xy, left_valid, left_ci,
           right_xy, right_valid, right_cells, offsets, radius):
        # Cell-sort the right side INSIDE the jitted program (an eager
        # argsort per window would pay a dispatch round trip — CLAUDE.md
        # hot-path rule).
        order = jnp.argsort(right_cells).astype(jnp.int32)

        def local(lxy, lvalid, lci, rxy, rvalid, rcells, rorder, offs, r):
            res = join_kernel(
                lxy, lvalid, lci, rxy, rvalid, rcells, rorder, offs,
                grid_n=grid_n, radius=r, cap=cap,
            )
            # Compact PER SHARD: jnp.nonzero over a sharded value hangs the
            # SPMD partitioner (cross-shard cumsum), so each shard extracts
            # its own hits into a local budget of max_pairs / n_shards.
            n_loc, kc = res.pair_mask.shape
            flat = res.pair_mask.reshape(-1)
            (hit,) = jnp.nonzero(flat, size=local_budget, fill_value=-1)
            found = hit >= 0
            hit_c = jnp.maximum(hit, 0)
            base = jax.lax.axis_index("data") * n_loc
            left_idx = jnp.where(
                found, (hit_c // kc).astype(jnp.int32) + base, -1
            )
            right_idx = jnp.where(
                found, res.right_index.reshape(-1)[hit_c], -1
            )
            dist = jnp.where(found, res.dist.reshape(-1)[hit_c], jnp.inf)
            local_count = jnp.sum(flat.astype(jnp.int32))
            total = jax.lax.psum(local_count, "data")
            max_local = jax.lax.pmax(local_count, "data")
            over = jax.lax.psum(res.overflow, "data")
            return left_idx, right_idx, dist, total, max_local, over

        left_idx, right_idx, dist, total, max_local, over = shard_map(
            local,
            mesh=mesh,
            in_specs=(
                P("data"), P("data"), P("data"),
                P(), P(), P(), P(), P(), P(),
            ),
            out_specs=(P("data"), P("data"), P("data"), P(), P(), P()),
            check_vma=False,
        )(
            left_xy, left_valid, left_ci,
            right_xy[order], right_valid[order], right_cells[order], order,
            offsets, radius,
        )
        # Shard outputs concatenate with per-shard padding tails; compact
        # valid pairs to the front so the caller's [:count] slice works.
        perm = jnp.argsort(left_idx < 0, stable=True)
        # A shard whose hits exceeded its local budget dropped pairs even
        # if the global total fits; inflating the reported count past
        # max_pairs makes the caller's retry-with-doubled-budget kick in.
        count = jnp.maximum(total, max_local * n_shards)
        from spatialflink_tpu.ops.join import CompactJoinResult

        return CompactJoinResult(
            left_idx[perm], right_idx[perm], dist[perm], count, over
        )

    return jax.jit(fn)


def sharded_join_window_compact(
    mesh: Mesh,
    left_xy, left_valid, left_cell_xy_idx,
    right_xy, right_valid, right_cells,
    neighbor_offsets, grid_n: int, radius, cap: int, max_pairs: int,
):
    """Multi-chip grid-hash join for the operator layer: left side sharded
    over ``data``, right side replicated, pairs compacted per shard on
    device (O(max_pairs) egress, same CompactJoinResult/retry contract as
    the single-device compact and Pallas paths). One cached jitted program
    per (mesh, grid_n, cap, max_pairs); ``max_pairs`` is rounded up to a
    multiple of the data-axis size."""
    n_shards = int(mesh.shape["data"])
    max_pairs = int(max_pairs) + (-int(max_pairs)) % n_shards
    # Replicated right side broadcast once per window; the compaction
    # protocol all-reduces three int32 scalars (total, max_local, over).
    telemetry.account_collective(
        "broadcast",
        payload_nbytes(right_xy, right_valid, right_cells,
                       neighbor_offsets),
        axis="data",
    )
    telemetry.account_collective("psum", 8, axis="data", calls=2)
    telemetry.account_collective("pmax", 4, axis="data")
    return _cached_sharded_join_compact(mesh, grid_n, cap, max_pairs)(
        left_xy, left_valid, left_cell_xy_idx,
        right_xy, right_valid, right_cells, neighbor_offsets, radius,
    )


def sharded_join(
    mesh: Mesh,
    left_xy: jnp.ndarray,
    left_valid: jnp.ndarray,
    left_cell_xy_idx: jnp.ndarray,
    right_xy_sorted: jnp.ndarray,
    right_valid_sorted: jnp.ndarray,
    right_cells_sorted: jnp.ndarray,
    right_order: jnp.ndarray,
    neighbor_offsets: jnp.ndarray,
    grid_n: int,
    radius,
    cap: int,
) -> JoinResult:
    """Grid-hash join with the left side sharded over ``data`` and the
    (smaller) cell-sorted right side replicated."""
    # Replicated right-side broadcast + the overflow-scalar psum.
    telemetry.account_collective(
        "broadcast",
        payload_nbytes(right_xy_sorted, right_valid_sorted,
                       right_cells_sorted, right_order, neighbor_offsets),
        axis="data",
    )
    telemetry.account_collective("psum", 4, axis="data")

    def local(lxy, lvalid, lci, rxy, rvalid, rcells, rorder, offs):
        res = join_kernel(
            lxy, lvalid, lci, rxy, rvalid, rcells, rorder, offs,
            grid_n=grid_n, radius=radius, cap=cap,
        )
        # Per-shard overflow counts differ; psum them so the scalar output
        # is replicated (its out_spec is P()).
        return res._replace(overflow=jax.lax.psum(res.overflow, "data"))

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(
            P("data"), P("data"), P("data"), P(), P(), P(), P(), P(),
        ),
        out_specs=JoinResult(P("data"), P("data"), P("data"), P()),
        check_vma=False,
    )
    return fn(
        left_xy, left_valid, left_cell_xy_idx,
        right_xy_sorted, right_valid_sorted, right_cells_sorted, right_order,
        neighbor_offsets,
    )


@functools.lru_cache(maxsize=None)
def _cached_sharded_pg_join(mesh: Mesh, polygonal: bool, block: int,
                            cand: int, max_pairs: int, pair_cap: int,
                            approx: bool = False):
    from spatialflink_tpu.ops.join import (
        PrunedJoinPairs,
        point_geometry_join_pruned_kernel,
    )

    def local(pxy, pvalid, gverts, gev, gvalid, gbbox, radius):
        res = point_geometry_join_pruned_kernel(
            pxy, pvalid, gverts, gev, gvalid, gbbox, radius,
            polygonal=polygonal, block=block, cand=cand,
            max_pairs=max_pairs, pair_cap=pair_cap, approx=approx,
        )
        base = jax.lax.axis_index("data") * pxy.shape[0]
        left = jnp.where(res.left_index >= 0, res.left_index + base, -1)
        return PrunedJoinPairs(
            left, res.right_index, res.dist,
            res.count[None],  # (1,) per shard → (n_shards,) stacked
            jax.lax.psum(res.cand_overflow, "data"),
            jax.lax.psum(res.pair_overflow, "data"),
        )

    return jax.jit(shard_map(
        local,
        mesh=mesh,
        in_specs=(P("data"), P("data"), P(), P(), P(), P(), P()),
        out_specs=PrunedJoinPairs(
            P("data"), P("data"), P("data"), P("data"), P(), P()
        ),
        check_vma=False,
    ))


def sharded_point_geometry_join_pruned(
    mesh: Mesh,
    pxy, pvalid, gverts, gev, gvalid, gbbox, radius,
    polygonal: bool, block: int, cand: int, max_pairs: int,
    pair_cap: int = 8, approx: bool = False,
):
    """Multi-chip grid-pruned point ⋈ geometry join: the (host-locality-
    sorted) point side shards over ``data``, the geometry batch
    replicates; each shard runs point_geometry_join_pruned_kernel on its
    contiguous slice (sorted order is preserved by contiguous sharding,
    so tile locality survives) and compacts its own pairs.

    ``left_index`` entries are global input positions; ``count`` comes
    back as a per-shard (n_shards,) vector (``max_pairs`` is PER SHARD —
    a shard truncates when its own count exceeds it); both overflow
    counters are psum-replicated. Bit-parity with single-device up to
    pair order (tests/test_join_pruned.py)."""
    # Replicated geometry batch broadcast + two overflow-scalar psums.
    telemetry.account_collective(
        "broadcast", payload_nbytes(gverts, gev, gvalid, gbbox),
        axis="data",
    )
    telemetry.account_collective("psum", 8, axis="data", calls=2)
    return _cached_sharded_pg_join(
        mesh, polygonal, block, cand, max_pairs, pair_cap, approx
    )(pxy, pvalid, gverts, gev, gvalid, gbbox, radius)


@functools.lru_cache(maxsize=None)
def _cached_sharded_gg_join(mesh: Mesh, a_polygonal: bool, b_polygonal: bool,
                            block: int, cand: int, max_pairs: int,
                            pair_cap: int, approx: bool = False):
    from spatialflink_tpu.ops.join import (
        PrunedJoinPairs,
        geometry_geometry_join_pruned_kernel,
    )

    def local(averts, aev, avalid, abbox, bverts, bev, bvalid, bbox, radius):
        res = geometry_geometry_join_pruned_kernel(
            averts, aev, avalid, abbox, bverts, bev, bvalid, bbox, radius,
            a_polygonal=a_polygonal, b_polygonal=b_polygonal,
            block=block, cand=cand, max_pairs=max_pairs, pair_cap=pair_cap,
            approx=approx,
        )
        base = jax.lax.axis_index("data") * averts.shape[0]
        left = jnp.where(res.left_index >= 0, res.left_index + base, -1)
        return PrunedJoinPairs(
            left, res.right_index, res.dist, res.count[None],
            jax.lax.psum(res.cand_overflow, "data"),
            jax.lax.psum(res.pair_overflow, "data"),
        )

    return jax.jit(shard_map(
        local,
        mesh=mesh,
        in_specs=(
            P("data"), P("data"), P("data"), P("data"),
            P(), P(), P(), P(), P(),
        ),
        out_specs=PrunedJoinPairs(
            P("data"), P("data"), P("data"), P("data"), P(), P()
        ),
        check_vma=False,
    ))


@functools.lru_cache(maxsize=None)
def _cached_sharded_tstats_pane(mesh: Mesh, kb: int, slide_ms: int,
                                ppw: int, n_panes: int):
    from spatialflink_tpu.ops.trajectory import (
        TrajPaneStats,
        traj_stats_pane_kernel,
    )

    def local(tp, xp, yp, op_, vp):
        # (1, nmax) point slice in, (kb, n_starts) oid-block rows out —
        # P("data") on the output concatenates the blocks into the
        # global (num_oids, n_starts) tables.
        base = jax.lax.axis_index("data") * kb
        return traj_stats_pane_kernel(
            tp[0], xp[0], yp[0], (op_[0] - base).astype(jnp.int32), vp[0],
            num_oids=kb, slide_ms=slide_ms, ppw=ppw, n_panes=n_panes,
        )

    return jax.jit(shard_map(
        local,
        mesh=mesh,
        in_specs=(P("data"), P("data"), P("data"), P("data"), P("data")),
        out_specs=TrajPaneStats(P("data"), P("data"), P("data")),
        check_vma=False,
    ))


def sharded_traj_stats_pane(
    mesh: Mesh,
    ts_rel: "np.ndarray",
    x: "np.ndarray",
    y: "np.ndarray",
    oid: "np.ndarray",
    valid: "np.ndarray",
    num_oids: int,
    slide_ms: int,
    ppw: int,
    n_panes: int,
):
    """Trajectory-parallel device tStats panes — the mesh execution of
    ``ops/trajectory.py:traj_stats_pane_kernel``.

    Sharding axis: TRAJECTORIES, not points. Every per-pane quantity in
    the kernel (segment sums, cumsum windows, boundary corrections) is
    per-oid independent, so contiguous oid BLOCKS shard over ``data``
    with zero collectives and the per-oid rows come back bit-identical
    to the single-device kernel (x64 parity:
    tests/test_parallel_operators.py) — the trajectory analog of the
    reference's keyBy(objID) partitioning (tStats pipelines key by
    trajectory id; SURVEY §2.2).

    Inputs are the single-device kernel's HOST arrays, sorted by
    (oid, ts) with padding at the end (``valid`` False). The host half
    here re-partitions them into per-shard contiguous slices (sorted
    order makes each oid block a contiguous slice) padded to a common
    bucket. ``num_oids`` must divide by the mesh's ``data`` axis."""
    # Deliberately NO account_collective here: this is the documented
    # zero-collective kernel (per-oid blocks are fully independent), and
    # the mesh parity test asserts its accounted bytes are exactly zero.
    from spatialflink_tpu.utils.padding import next_bucket

    ndev = int(mesh.shape["data"])
    if num_oids % ndev:
        raise ValueError(
            f"num_oids ({num_oids}) must divide by the data axis ({ndev})"
        )
    kb = num_oids // ndev
    tp = np.asarray(ts_rel)
    xp = np.asarray(x)
    yp = np.asarray(y)
    op_ = np.asarray(oid)
    vp = np.asarray(valid)
    live = vp.astype(bool)
    shard_of = op_[live] // kb
    counts = np.bincount(shard_of, minlength=ndev)
    nmax = next_bucket(max(int(counts.max()), 1), minimum=8)
    sh = (ndev, nmax)
    t2 = np.zeros(sh, tp.dtype)
    x2 = np.zeros(sh, xp.dtype)
    y2 = np.zeros(sh, yp.dtype)
    o2 = np.zeros(sh, op_.dtype)
    v2 = np.zeros(sh, bool)
    tl, xl, yl, ol = tp[live], xp[live], yp[live], op_[live]
    start = 0
    for s in range(ndev):
        c = int(counts[s])
        sl = slice(start, start + c)  # oid-sorted ⇒ contiguous block
        t2[s, :c] = tl[sl]
        x2[s, :c] = xl[sl]
        y2[s, :c] = yl[sl]
        o2[s, :c] = ol[sl]
        v2[s, :c] = True
        o2[s, c:] = (s + 1) * kb - 1  # local padding stays in-shard
        start += c
    fn = _cached_sharded_tstats_pane(mesh, kb, slide_ms, ppw, n_panes)
    return fn(
        jnp.asarray(t2), jnp.asarray(x2), jnp.asarray(y2),
        jnp.asarray(o2), jnp.asarray(v2),
    )


def sharded_geometry_geometry_join_pruned(
    mesh: Mesh,
    averts, aev, avalid, abbox, bverts, bev, bvalid, bbbox, radius,
    a_polygonal: bool, b_polygonal: bool,
    block: int, cand: int, max_pairs: int, pair_cap: int = 8,
    approx: bool = False,
):
    """Multi-chip grid-pruned geometry ⋈ geometry join — left side (host-
    locality-sorted) sharded over ``data``, right side replicated; same
    contracts as sharded_point_geometry_join_pruned."""
    # Replicated right geometry batch broadcast + two overflow psums.
    telemetry.account_collective(
        "broadcast", payload_nbytes(bverts, bev, bvalid, bbbox),
        axis="data",
    )
    telemetry.account_collective("psum", 8, axis="data", calls=2)
    return _cached_sharded_gg_join(
        mesh, a_polygonal, b_polygonal, block, cand, max_pairs, pair_cap,
        approx,
    )(averts, aev, avalid, abbox, bverts, bev, bvalid, bbbox, radius)


@functools.lru_cache(maxsize=None)
def _cached_tjoin_pane_scan(mesh, grid_n, cap_w, layers, ppw, num_ids,
                            pair_sel, cap_c):
    from spatialflink_tpu.ops.tjoin_panes import tjoin_pane_scan
    from spatialflink_tpu.telemetry import instrument_jit

    def fn(carry, ts, lps, rps, radius, lps_expire, rps_expire):
        return tjoin_pane_scan(
            carry, ts, lps, rps, radius, grid_n=grid_n, cap_w=cap_w,
            layers=layers, ppw=ppw, num_ids=num_ids, pair_sel=pair_sel,
            cap_c=cap_c, lps_expire=lps_expire, rps_expire=rps_expire,
            mesh=mesh,
        )

    # Same recompile-detector label convention as window_program's mesh
    # path, so bucket churn on the pane scan stays visible.
    return instrument_jit(jax.jit(fn), name="sharded:tjoin_pane_scan")


def sharded_tjoin_pane_scan(
    mesh: Mesh,
    carry,
    ts,
    lps,
    rps,
    radius,
    lps_expire=None,
    rps_expire=None,
    *,
    grid_n: int,
    cap_w: int,
    layers: int,
    ppw: int,
    num_ids: int,
    pair_sel: int,
    cap_c: int = 0,
):
    """Accounted mesh entry for ``ops/tjoin_panes.tjoin_pane_scan``.

    Probe-parallel: pane POINTS shard over ``data``; per slide each
    shard probes its chunk against the replicated window planes, then
    the 8 pane field arrays of BOTH sides and the (flat idx, dist)
    contribution pairs of both probe directions all-gather so every
    shard applies the identical digest scatter, and the 4 overflow
    scalars psum (tjoin_pane_step's axis_name hooks). Bit-identical to
    the single-device scan (tests/test_parallel_operators.py).

    The collective footprint is computed HERE, host-side from static
    shapes, per scan invocation — the ``telemetry.account_collective``
    feeder contract (PARITY.md "Observability"): per slide, both panes'
    fields (x, y at the field dtype; xi/yi/cell/rank/oid int32; valid
    bool) plus ``2·PC·pair_sel`` gathered contribution lanes, and four
    int32 psums.
    """
    n_slides = int(ts.shape[0])
    pc = int(lps[0].shape[1])
    fb = _itemsize(lps[0].dtype)
    per_side = pc * (2 * fb + 5 * 4 + 1)
    contrib = 2 * pc * pair_sel * (4 + fb)
    telemetry.account_collective(
        "all_gather", n_slides * (2 * per_side + contrib), axis="data",
        calls=n_slides * 20,
    )
    telemetry.account_collective(
        "psum", n_slides * 16, axis="data", calls=n_slides * 4,
    )
    fn = _cached_tjoin_pane_scan(
        mesh, grid_n, cap_w, layers, ppw, num_ids, pair_sel, cap_c,
    )
    return fn(carry, ts, lps, rps, radius, lps_expire, rps_expire)
