"""Multi-chip sharded query kernels via ``shard_map``.

Sharding layout (the scaling-book recipe: pick a mesh, annotate shardings,
let XLA insert collectives):

  - **range**: points sharded over ``data``; optionally queries sharded
    over ``query`` with a psum-OR across the query axis. Fully local
    compute, no collective in the 1-D case — the analog of the reference's
    keyBy(gridID) partitioning minus the shuffle.
  - **kNN**: points sharded over ``data``; each shard computes its local
    per-object segment-min, then a ``pmin`` collective over ``data``
    reduces object minima across shards and the (replicated) top-k runs on
    the reduced table. This replaces the reference's single-subtask
    windowAll merge bottleneck (KNNQuery.java:204-308) with one ICI
    all-reduce.
  - **join**: left side sharded over ``data``, cell-sorted right side
    replicated (broadcast once per window) — each shard joins its left
    slice; pair outputs stay sharded.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

from spatialflink_tpu.ops.distances import point_point_distance
from spatialflink_tpu.ops.join import JoinResult, join_kernel
from spatialflink_tpu.ops.knn import KnnResult
from spatialflink_tpu.ops.range import _emit_mask


def sharded_range_query(
    mesh: Mesh,
    xy: jnp.ndarray,
    valid: jnp.ndarray,
    flags: jnp.ndarray,
    query_xy: jnp.ndarray,
    radius,
    approximate: bool = False,
):
    """Data-parallel range query. ``xy``/``valid``/``flags`` shard over
    ``data``; the query set is replicated. Returns (keep, min_dist) sharded
    like the inputs."""

    def local(xy_l, valid_l, flags_l, q):
        d = point_point_distance(xy_l[:, None, :], q[None, :, :])
        min_dist = jnp.min(d, axis=1)
        return _emit_mask(valid_l, flags_l, min_dist, radius, approximate), min_dist

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P("data"), P("data"), P("data"), P()),
        out_specs=(P("data"), P("data")),
    )
    return fn(xy, valid, flags, query_xy)


def sharded_range_query_2d(
    mesh: Mesh,
    xy: jnp.ndarray,
    valid: jnp.ndarray,
    flags: jnp.ndarray,
    query_xy: jnp.ndarray,
    radius,
    approximate: bool = False,
):
    """2-D sharded range query: points over ``data``, query set over
    ``query``. Each (data, query) tile evaluates its query slice; a psum-OR
    over the ``query`` axis merges per-slice hits — the collective pattern
    for large query sets (e.g. 1k query polygons sharded across chips).
    Returns (keep sharded over data, min_dist sharded over data)."""

    def local(xy_l, valid_l, flags_l, q_l):
        d = point_point_distance(xy_l[:, None, :], q_l[None, :, :])
        local_min = jnp.min(d, axis=1)
        # Min distance across the query shards (ICI all-reduce on "query").
        min_dist = jax.lax.pmin(local_min, axis_name="query")
        keep = _emit_mask(valid_l, flags_l, min_dist, radius, approximate)
        return keep, min_dist

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P("data"), P("data"), P("data"), P("query")),
        out_specs=(P("data"), P("data")),
        check_vma=False,
    )
    return fn(xy, valid, flags, query_xy)


def sharded_knn(
    mesh: Mesh,
    xy: jnp.ndarray,
    valid: jnp.ndarray,
    flags: jnp.ndarray,
    oid: jnp.ndarray,
    query_xy: jnp.ndarray,
    radius,
    k: int,
    num_segments: int,
) -> KnnResult:
    """Multi-chip kNN: local segment-min per shard → pmin over ``data`` →
    replicated top-k. Object ids are global dense ints (host interning),
    so the (num_segments,) minima table is the only cross-chip traffic —
    one psum-sized all-reduce instead of the reference's windowAll
    re-shuffle of every candidate."""

    from spatialflink_tpu.ops.knn import _topk_from_point_dists

    def local(xy_l, valid_l, flags_l, oid_l, q):
        dist = point_point_distance(xy_l, q[None, :])
        # Same top-k core as the single-chip kernel, with the per-object
        # minima/representatives pmin-reduced over the data axis and local
        # indices offset to global ones.
        base = jax.lax.axis_index("data") * xy_l.shape[0]
        return _topk_from_point_dists(
            dist, valid_l, flags_l, oid_l, radius, k, num_segments,
            axis_name="data", index_base=base,
        )

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P("data"), P("data"), P("data"), P("data"), P()),
        out_specs=KnnResult(P(), P(), P(), P()),
        check_vma=False,
    )
    return fn(xy, valid, flags, oid, query_xy)


def sharded_traj_stats(
    mesh: Mesh,
    xy: jnp.ndarray,
    ts: jnp.ndarray,
    oid: jnp.ndarray,
    valid: jnp.ndarray,
    num_segments: int,
):
    """Sequence-parallel trajectory statistics with halo exchange.

    The long-trajectory analog of sequence/context parallelism: the
    (oid, ts)-sorted point sequence is sharded over ``data``; each shard
    computes consecutive-point contributions locally, and the one pair that
    straddles each shard boundary is recovered by passing every shard's
    *last* point to its right neighbor via ``lax.ppermute`` (a ring halo
    exchange over ICI). Per-object partials are then psum'd. Exactly equals
    the single-device ops.trajectory.traj_stats_kernel.
    """
    from spatialflink_tpu.ops.distances import point_point_distance

    def local(xy_l, ts_l, oid_l, valid_l):
        n_shards = jax.lax.axis_size("data")
        # Ring halo: receive the previous shard's last (xy, ts, oid, valid).
        perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
        prev_xy = jax.lax.ppermute(xy_l[-1], "data", perm)
        prev_ts = jax.lax.ppermute(ts_l[-1], "data", perm)
        prev_oid = jax.lax.ppermute(oid_l[-1], "data", perm)
        prev_valid = jax.lax.ppermute(valid_l[-1], "data", perm)
        # Shard 0 has no predecessor: mask its halo pair.
        first = jax.lax.axis_index("data") == 0
        prev_valid = prev_valid & ~first

        xy_ext = jnp.concatenate([prev_xy[None, :], xy_l], axis=0)
        ts_ext = jnp.concatenate([prev_ts[None], ts_l], axis=0)
        oid_ext = jnp.concatenate([prev_oid[None], oid_l], axis=0)
        valid_ext = jnp.concatenate([prev_valid[None], valid_l], axis=0)

        same_traj = (oid_ext[1:] == oid_ext[:-1]) & valid_ext[1:] & valid_ext[:-1]
        seg_d = point_point_distance(xy_ext[1:], xy_ext[:-1])
        seg_t = (ts_ext[1:] - ts_ext[:-1]).astype(seg_d.dtype)
        spatial = jax.ops.segment_sum(
            jnp.where(same_traj, seg_d, 0), oid_l, num_segments=num_segments
        )
        temporal = jax.ops.segment_sum(
            jnp.where(same_traj, seg_t, 0), oid_l, num_segments=num_segments
        )
        count = jax.ops.segment_sum(
            valid_l.astype(jnp.int32), oid_l, num_segments=num_segments
        )
        spatial = jax.lax.psum(spatial, "data")
        temporal = jax.lax.psum(temporal, "data")
        count = jax.lax.psum(count, "data")
        speed = jnp.where(
            temporal > 0, spatial / jnp.where(temporal > 0, temporal, 1), 0.0
        )
        return spatial, temporal, count, speed

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P("data"), P("data"), P("data"), P("data")),
        out_specs=(P(), P(), P(), P()),
        check_vma=False,
    )
    return fn(xy, ts, oid, valid)


def sharded_join(
    mesh: Mesh,
    left_xy: jnp.ndarray,
    left_valid: jnp.ndarray,
    left_cell_xy_idx: jnp.ndarray,
    right_xy_sorted: jnp.ndarray,
    right_valid_sorted: jnp.ndarray,
    right_cells_sorted: jnp.ndarray,
    right_order: jnp.ndarray,
    neighbor_offsets: jnp.ndarray,
    grid_n: int,
    radius,
    cap: int,
) -> JoinResult:
    """Grid-hash join with the left side sharded over ``data`` and the
    (smaller) cell-sorted right side replicated."""

    def local(lxy, lvalid, lci, rxy, rvalid, rcells, rorder, offs):
        res = join_kernel(
            lxy, lvalid, lci, rxy, rvalid, rcells, rorder, offs,
            grid_n=grid_n, radius=radius, cap=cap,
        )
        # Per-shard overflow counts differ; psum them so the scalar output
        # is replicated (its out_spec is P()).
        return res._replace(overflow=jax.lax.psum(res.overflow, "data"))

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(
            P("data"), P("data"), P("data"), P(), P(), P(), P(), P(),
        ),
        out_specs=JoinResult(P("data"), P("data"), P("data"), P()),
        check_vma=False,
    )
    return fn(
        left_xy, left_valid, left_cell_xy_idx,
        right_xy_sorted, right_valid_sorted, right_cells_sorted, right_order,
        neighbor_offsets,
    )
