"""Two-process jax.distributed dryrun — proof the DCN seam runs.

The reference scales out to a 15-task Flink cluster
(conf/geoflink-conf.yml:55); this framework's scale-out is
``jax.distributed`` + a global mesh (parallel/multihost.py). This module
DEMONSTRATES that seam end to end on CPU, no second host required:

- ``run_dryrun()`` spawns ``num_processes`` child interpreters on this
  machine, each with ``local_devices`` virtual CPU devices;
- every child joins the job through ``initialize_distributed`` (the
  exact production entry point), builds ONE global mesh spanning all
  processes' devices, and runs a real package kernel —
  ``parallel/sharded.py:sharded_knn`` — over a globally-sharded point
  batch (cross-process pmin/psum ride the gloo CPU collectives standing
  in for DCN);
- each child asserts the distributed result matches the single-device
  ``ops/knn.py:knn_kernel`` on its full local copy, then prints an OK
  line the parent verifies.

Run: ``python -m spatialflink_tpu.parallel.multihost_dryrun``
Test: tests/test_multihost.py (slow marker — spawns 2 jax processes).
"""

from __future__ import annotations

import os
import subprocess
import sys

OK_TAG = "MULTIHOST_DRYRUN_OK"


def child_main(process_id: int, port: int, num_processes: int,
               local_devices: int) -> None:
    # JAX_PLATFORMS/XLA_FLAGS are set by run_dryrun in the SPAWNING env:
    # ``python -m`` imports the package (which configures jax) before
    # this function runs, so in-process env edits would come too late.
    from spatialflink_tpu.parallel.multihost import initialize_distributed

    joined = initialize_distributed(
        f"127.0.0.1:{port}", num_processes, process_id
    )
    assert joined, "initialize_distributed returned False for a 2-proc job"

    import jax
    import jax.numpy as jnp
    import numpy as np

    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    n_global = num_processes * local_devices
    assert len(jax.devices()) == n_global, jax.devices()
    assert jax.process_index() == process_id

    from spatialflink_tpu.grid import UniformGrid
    from spatialflink_tpu.ops.cells import gather_cell_flags
    from spatialflink_tpu.ops.knn import knn_kernel
    from spatialflink_tpu.parallel.sharded import sharded_knn

    grid = UniformGrid(20, 0.0, 10.0, 0.0, 10.0)
    rng = np.random.default_rng(5)  # identical stream on every process
    n, nseg, k, radius = 4096, 64, 8, np.float64(3.0)
    xy = rng.uniform(0, 10, (n, 2))
    oid = rng.integers(0, nseg, n).astype(np.int32)
    cell = grid.assign_cells_np(xy)
    flags = gather_cell_flags(
        jnp.asarray(cell),
        jnp.asarray(grid.neighbor_flags(float(radius),
                                        [grid.flat_cell(5.0, 5.0)])),
    )
    q = np.asarray([5.0, 5.0])

    mesh = Mesh(np.asarray(jax.devices()).reshape(n_global), ("data",))
    sh = NamedSharding(mesh, P("data"))

    def gput(a, sharding):
        return jax.make_array_from_callback(
            a.shape, sharding, lambda idx: a[idx]
        )

    res = sharded_knn(
        mesh,
        gput(xy, sh),
        gput(np.ones(n, bool), sh),
        gput(np.asarray(flags), sh),
        gput(oid, sh),
        gput(q, NamedSharding(mesh, P())),
        radius, k=k, num_segments=nseg,
    )

    ref = knn_kernel(
        jnp.asarray(xy), jnp.ones(n, bool), flags, jnp.asarray(oid),
        jnp.asarray(q), radius, k=k, num_segments=nseg,
    )

    def fetch(x):
        return np.asarray(jax.device_get(x.addressable_data(0)))

    nv = int(fetch(res.num_valid))
    assert nv == int(jax.device_get(ref.num_valid)), (
        nv, int(jax.device_get(ref.num_valid)))
    assert nv == k, f"degenerate dryrun: top-k underfilled ({nv})"
    np.testing.assert_array_equal(
        fetch(res.segment)[:nv], np.asarray(ref.segment)[:nv]
    )
    np.testing.assert_array_equal(
        fetch(res.dist)[:nv], np.asarray(ref.dist)[:nv]
    )
    np.testing.assert_array_equal(
        fetch(res.index)[:nv], np.asarray(ref.index)[:nv]
    )
    print(f"{OK_TAG} pid={process_id} devices={n_global} "
          f"procs={num_processes} k={nv}", flush=True)


def run_dryrun(num_processes: int = 2, local_devices: int = 2,
               timeout: float = 240.0, port: int = 0) -> str:
    """Spawn the children, wait, and return their combined stdout.

    Raises RuntimeError (with both children's output) unless every
    child printed its OK line and exited 0."""
    import socket

    if port == 0:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
    env = {**os.environ}
    env.pop("PALLAS_AXON_POOL_IPS", None)  # no device dial in children
    env["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    env["XLA_FLAGS"] = " ".join(
        flags + [f"--xla_force_host_platform_device_count={local_devices}"]
    )
    env["PYTHONPATH"] = (
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        + os.pathsep + env.get("PYTHONPATH", "")
    )
    procs = [
        subprocess.Popen(
            [sys.executable, "-m",
             "spatialflink_tpu.parallel.multihost_dryrun",
             "--child", str(pid), str(port), str(num_processes),
             str(local_devices)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(num_processes)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    except subprocess.TimeoutExpired:
        # Kill AND drain every child: the hung child's partial output is
        # the diagnostic (e.g. which side of the coordinator barrier it
        # reached), and un-reaped children would leak zombies + pipes.
        drained = []
        for p in procs:
            p.kill()
            try:
                out, _ = p.communicate(timeout=10)
            except Exception:
                out = "<unreadable>"
            drained.append(f"[child rc={p.returncode}]\n{out}")
        raise RuntimeError(
            "multihost dryrun timed out\n" + "\n".join(drained)
        )
    combined = "\n".join(outs)
    rcs = [p.returncode for p in procs]
    if any(rcs) or combined.count(OK_TAG) != num_processes:
        raise RuntimeError(
            f"multihost dryrun failed (rcs={rcs}):\n{combined}"
        )
    return combined


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv[:1] == ["--child"]:
        child_main(int(argv[1]), int(argv[2]), int(argv[3]), int(argv[4]))
        return 0
    out = run_dryrun()
    sys.stdout.write(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
