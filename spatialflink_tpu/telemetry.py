"""Runtime telemetry: spans, device-boundary accounting, recompile
detection, watermark-lag gauges.

The reference instruments its pipelines with Flink/NES metrics (``com/mn/``);
those host-side counters are ported in ``mn/``. This module adds the layer
the JVM build never needed: visibility at the HOST↔DEVICE boundary, where
every perf pathology this codebase has hit lives —

- per-window eager-op recompiles (~1-2 s each over the tunnel) → the
  recompile detector keyed by (kernel, abstract shape signature);
- transfers over a ±50% ~28 MB/s tunnel → host→device / device→host byte
  accounting at the batch-shipping entry points (``operators/base.py``);
- ``jax.block_until_ready`` being a NO-OP over the axon tunnel → the
  ``fetch`` true-sync helper times via a real ``jax.device_get`` (the only
  actual synchronization point; the bug that once produced a bogus
  106M pts/s number);
- windows firing late / events dropped → watermark-lag and late-drop
  gauges fed by the ``streams/`` assemblers.

Contract: **disabled by default and free when disabled** (operator hot
paths do one ``telemetry.enabled`` attribute check per window, nothing
per event); when enabled, instrumentation adds **zero device round trips**
beyond the operator's own fetches — byte accounting reads host-array
``nbytes`` before shipping, and ``fetch`` REPLACES (never duplicates) the
operator's existing device→host materialization.

Spans emit Chrome-trace/Perfetto-compatible complete events ("ph": "X",
microsecond ts/dur) as JSON-lines; ``load_trace`` wraps a trace file into
the standard ``{"traceEvents": [...]}`` document. Spans named
``window.*`` additionally feed a ``FixedBucketLatency`` histogram, so
p50/p95 window latency lands in NES reporter lines and bench.py's JSON.
"""

from __future__ import annotations

import json
import os
import threading
import time
import warnings
from typing import Any, Dict, Optional, Tuple

from spatialflink_tpu.mn.metrics import FixedBucketLatency, json_safe


class RecompileWarning(UserWarning):
    """One kernel crossed the distinct-abstract-shape threshold — bucket
    churn or an accidentally dynamic shape is forcing XLA recompiles."""


def _arg_signature(a):
    """One argument's contribution to the abstract signature. Arrays →
    (shape, dtype) — the aval; tuples/lists recurse (jit flattens pytrees,
    so a container of arrays recompiles whenever ANY leaf's shape changes
    — e.g. the knn pane digests repadded to a grown nseg); other leaves
    contribute only their type (jit treats distinct Python scalars of one
    type as one aval)."""
    shape = getattr(a, "shape", None)
    dtype = getattr(a, "dtype", None)
    if shape is not None and dtype is not None:
        return (tuple(shape), str(dtype))
    if isinstance(a, (tuple, list)):
        return (type(a).__name__, tuple(_arg_signature(x) for x in a))
    return type(a).__name__


def abstract_signature(args: tuple, kwargs: Optional[dict] = None) -> Tuple:
    """Hashable proxy of jax.jit's cache key for a call.

    Positional arguments go through ``_arg_signature`` (avals for arrays,
    recursive for containers); keyword arguments holding arrays or
    containers of arrays do too (e.g. the pane scan's ``lps_expire``
    array tuples — repr would MATERIALIZE the arrays, a device fetch
    per call), while every other kwarg contributes (name, repr(value))
    because scalar/string kwargs in this codebase are static arguments,
    where the VALUE keys the compile cache.
    """
    parts = [_arg_signature(a) for a in args]
    for k in sorted(kwargs or ()):
        v = kwargs[k]
        shape = getattr(v, "shape", None)
        dtype = getattr(v, "dtype", None)
        if shape is not None and dtype is not None:
            parts.append((k, (tuple(shape), str(dtype))))
        elif isinstance(v, (tuple, list)):
            parts.append((k, _arg_signature(v)))
        else:
            parts.append((k, repr(v)))
    return tuple(parts)


class _NullSpan:
    """No-op context manager returned while telemetry is disabled — one
    shared instance, so the disabled-path cost is a truthiness check."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tel", "name", "args", "_t0")

    def __init__(self, tel: "Telemetry", name: str, args: Dict[str, Any]):
        self._tel = tel
        self.name = name
        self.args = args

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        self._tel._emit_span(
            self.name, self._t0, time.perf_counter_ns() - self._t0, self.args
        )
        return False


class Telemetry:
    """Process-global telemetry registry (the ``ops/counters.py`` idiom:
    one module singleton, ``enable()`` to opt in)."""

    def __init__(self, max_events: int = 262_144):
        self.enabled = False
        self.max_events = max_events
        self.recompile_warn_threshold = 8
        self.trace_path: Optional[str] = None
        self._trace_file = None
        self._lock = threading.RLock()
        self._reset_state()

    def _reset_state(self):
        self.events: list = []
        self.dropped_events = 0
        self._since_flush = 0
        self.h2d_bytes = 0
        self.h2d_transfers = 0
        self.d2h_bytes = 0
        self.d2h_transfers = 0
        self.compile_events: list = []  # (kernel, signature), append order
        self._shapes_seen: Dict[str, set] = {}
        self._warned_kernels: set = set()
        self.max_watermark_lag_ms = 0
        self.late_drops = 0
        self.window_latency = FixedBucketLatency()
        # engine → {capacity bucket → {"picks", "max_live"}} — the
        # compaction control plane's pick log (ops/compaction.py).
        self._compaction: Dict[str, Dict[int, Dict[str, int]]] = {}

    # -- lifecycle ------------------------------------------------------------

    def enable(self, trace_path: Optional[str] = None,
               recompile_warn_threshold: int = 8):
        """Reset all state and start recording. ``trace_path``: optional
        Chrome-trace JSON-lines file (events also buffer in memory, capped
        at ``max_events``)."""
        with self._lock:
            self.disable()
            self._reset_state()
            self.recompile_warn_threshold = int(recompile_warn_threshold)
            self.trace_path = trace_path
            if trace_path:
                d = os.path.dirname(os.path.abspath(trace_path))
                os.makedirs(d, exist_ok=True)
                self._trace_file = open(trace_path, "w")
            self.enabled = True

    def disable(self):
        with self._lock:
            self.enabled = False
            if self._trace_file is not None:
                self._trace_file.close()  # close flushes buffered events
                self._trace_file = None

    FLUSH_EVERY = 256

    def _write_trace(self, event: dict):
        """Buffered trace write (caller holds the lock). No per-event
        flush — a synchronous flush per span would serialize operator
        threads through disk I/O and distort the spans being measured;
        the buffer drains every FLUSH_EVERY events and on disable()."""
        self._trace_file.write(json.dumps(event) + "\n")
        self._since_flush += 1
        if self._since_flush >= self.FLUSH_EVERY:
            self._trace_file.flush()
            self._since_flush = 0

    # -- spans ----------------------------------------------------------------

    def span(self, name: str, **args):
        """Context manager timing one phase. Nesting renders naturally in
        Chrome tracing (same tid, contained ts/dur). ``window.*`` spans
        also feed the window-latency histogram."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, args)

    def _emit_span(self, name, t0_ns, dur_ns, args):
        if not self.enabled:  # disabled mid-span
            return
        ev = {
            "name": name,
            "cat": "telemetry",
            "ph": "X",
            "ts": t0_ns // 1000,
            "dur": dur_ns // 1000,
            "pid": os.getpid(),
            "tid": threading.get_ident(),
        }
        if args:
            ev["args"] = json_safe(args)
        self._emit(ev)
        if name.startswith("window"):
            with self._lock:
                self.window_latency.observe(dur_ns / 1e6)

    def _emit(self, event: dict):
        with self._lock:
            if len(self.events) < self.max_events:
                self.events.append(event)
            else:
                self.dropped_events += 1
            if self._trace_file is not None:
                self._write_trace(event)

    # -- device-boundary accounting -------------------------------------------

    def account_h2d(self, nbytes: int):
        """Bytes about to ship host→device (read from the HOST array before
        the transfer — no device round trip)."""
        if not self.enabled:
            return
        with self._lock:
            self.h2d_bytes += int(nbytes)
            self.h2d_transfers += 1
            if self._trace_file is not None:
                self._write_trace({
                    "name": "h2d_bytes", "ph": "C",
                    "ts": time.perf_counter_ns() // 1000,
                    "pid": os.getpid(), "args": {"bytes": self.h2d_bytes},
                })

    def account_d2h(self, nbytes: int):
        if not self.enabled:
            return
        with self._lock:
            self.d2h_bytes += int(nbytes)
            self.d2h_transfers += 1

    def fetch(self, x):
        """True-sync device→host fetch with timing + byte accounting.

        ``jax.block_until_ready`` is a NO-OP over the axon tunnel — it
        returns before transfers/compute finish (CLAUDE.md) — so a real
        ``jax.device_get`` is the ONLY honest synchronization point.
        Accepts any pytree; returns host numpy. Use this IN PLACE OF the
        operator's ``np.asarray``/``device_get`` so accounting rides the
        fetch the operator was doing anyway (zero extra round trips).
        """
        import jax

        if not self.enabled:
            return jax.device_get(x)
        t0 = time.perf_counter_ns()
        out = jax.device_get(x)
        dur_ns = time.perf_counter_ns() - t0
        nbytes = 0
        for leaf in jax.tree_util.tree_leaves(out):
            nbytes += getattr(leaf, "nbytes", 0)
        self.account_d2h(nbytes)
        self._emit({
            "name": "fetch", "cat": "telemetry", "ph": "X",
            "ts": t0 // 1000, "dur": dur_ns // 1000,
            "pid": os.getpid(), "tid": threading.get_ident(),
            "args": {"bytes": int(nbytes)},
        })
        return out

    # -- recompile detection --------------------------------------------------

    def record_jit_call(self, kernel: str, signature: Tuple):
        """Record a call into a jitted kernel. A signature not seen before
        for this kernel is one XLA compile (jit's cache key is the abstract
        shapes + statics this signature proxies). Crossing
        ``recompile_warn_threshold`` distinct signatures warns once —
        catching bucket-size churn and accidentally dynamic shapes."""
        if not self.enabled:
            return
        warn_n = None
        with self._lock:
            seen = self._shapes_seen.setdefault(kernel, set())
            if signature in seen:
                return
            seen.add(signature)
            self.compile_events.append((kernel, signature))
            if (len(seen) >= self.recompile_warn_threshold
                    and kernel not in self._warned_kernels):
                self._warned_kernels.add(kernel)
                warn_n = len(seen)
        self._emit({
            "name": f"compile:{kernel}", "cat": "telemetry", "ph": "i",
            "ts": time.perf_counter_ns() // 1000, "pid": os.getpid(),
            "tid": threading.get_ident(), "s": "t",
            "args": {"signature": repr(signature)},
        })
        if warn_n is not None:
            warnings.warn(
                f"kernel '{kernel}' has compiled for {warn_n} distinct "
                f"abstract shapes (threshold "
                f"{self.recompile_warn_threshold}): each is ~1-2 s of XLA "
                "compile + a tunnel round trip — check for bucket-size "
                "churn or an un-bucketed dynamic dimension",
                RecompileWarning,
                stacklevel=3,
            )

    @property
    def compile_count(self) -> int:
        return len(self.compile_events)

    def distinct_shapes(self, kernel: str) -> int:
        with self._lock:
            return len(self._shapes_seen.get(kernel, ()))

    # -- compaction bucket accounting -----------------------------------------

    def record_compaction(self, engine: str, capacity: int, live: int):
        """One host-side bucket pick by the live-slot compaction control
        plane (ops/compaction.py): ``engine`` compiled/ran at static
        capacity ``capacity`` for an observed live occupancy of
        ``live``. Per-(engine, bucket) pick counts + max observed live
        land in ``snapshot()`` — occupancy drift shows up as bucket
        churn here, and as at most ladder-many distinct signatures in
        the recompile detector (the bucket is a static of the scan)."""
        if not self.enabled:
            return
        with self._lock:
            d = self._compaction.setdefault(engine, {}).setdefault(
                int(capacity), {"picks": 0, "max_live": 0}
            )
            d["picks"] += 1
            d["max_live"] = max(d["max_live"], int(live))
        self._emit({
            "name": f"compaction:{engine}", "cat": "telemetry", "ph": "i",
            "ts": time.perf_counter_ns() // 1000, "pid": os.getpid(),
            "tid": threading.get_ident(), "s": "t",
            "args": {"capacity": int(capacity), "live": int(live)},
        })

    def compaction_buckets(self, engine: str) -> Dict[int, Dict[str, int]]:
        with self._lock:
            return {
                k: dict(v)
                for k, v in self._compaction.get(engine, {}).items()
            }

    # -- watermark / lateness gauges ------------------------------------------

    def record_watermark_lag(self, lag_ms: int):
        """Event-time ms between a fired window's end and the watermark at
        fire time — how late the window fired relative to its span."""
        if not self.enabled:
            return
        with self._lock:
            if lag_ms > self.max_watermark_lag_ms:
                self.max_watermark_lag_ms = int(lag_ms)

    def record_late_drop(self, n: int = 1):
        if not self.enabled:
            return
        with self._lock:
            self.late_drops += int(n)

    # -- export ---------------------------------------------------------------

    def register_metrics(self, registry):
        """Wire the telemetry gauges into an ``mn.metrics.MetricRegistry``
        so ``snapshot()`` (and anything reading it — NES reporter lines,
        sink-owned registries) carries the new columns."""
        registry.gauge("watermark_lag_ms_max",
                       lambda: self.max_watermark_lag_ms)
        registry.gauge("late_dropped_total", lambda: self.late_drops)
        registry.gauge("telemetry_compiles_total",
                       lambda: len(self.compile_events))
        registry.gauge("h2d_bytes_total", lambda: self.h2d_bytes)
        registry.gauge("d2h_bytes_total", lambda: self.d2h_bytes)
        registry.gauge(
            "compaction_buckets_total",
            lambda: sum(len(v) for v in self._compaction.values()),
        )

    def summary(self) -> Dict[str, Any]:
        """The bench.py JSON block: strictly JSON-safe (numpy scalars →
        builtins, NaN percentiles → None so strict parsers never choke)."""
        with self._lock:
            p50 = self.window_latency.percentile(0.50)
            p95 = self.window_latency.percentile(0.95)
            out = {
                "compiles": len(self.compile_events),
                "bytes_h2d": self.h2d_bytes,
                "bytes_d2h": self.d2h_bytes,
                "window_latency_p50_ms": None if p50 != p50 else p50,
                "window_latency_p95_ms": None if p95 != p95 else p95,
                "max_watermark_lag_ms": self.max_watermark_lag_ms,
                "late_dropped": self.late_drops,
            }
        return json_safe(out)

    def snapshot(self) -> Dict[str, Any]:
        """Full JSON-safe state dump (summary + transfer/trace counts)."""
        out = self.summary()
        with self._lock:
            out.update(
                h2d_transfers=self.h2d_transfers,
                d2h_transfers=self.d2h_transfers,
                events=len(self.events),
                dropped_events=self.dropped_events,
                kernels={k: len(v) for k, v in self._shapes_seen.items()},
                compaction={
                    eng: {str(cap): dict(st) for cap, st in caps.items()}
                    for eng, caps in self._compaction.items()
                },
            )
        return json_safe(out)


telemetry = Telemetry()


def enable(trace_path: Optional[str] = None, recompile_warn_threshold: int = 8):
    telemetry.enable(trace_path, recompile_warn_threshold)


def disable():
    telemetry.disable()


def span(name: str, **args):
    return telemetry.span(name, **args)


def fetch(x):
    return telemetry.fetch(x)


def instrument_jit(fn, name: Optional[str] = None):
    """Wrap a compiled callable with recompile-signature tracking.

    ``operators/base.py:jitted`` routes every operator kernel through this;
    bench.py wraps its hand-jitted steps the same way. Disabled-path cost:
    one attribute check per call (calls here are per WINDOW, never per
    record). Attributes of the underlying jit object (``lower``, …) pass
    through.
    """
    label = name or getattr(fn, "__name__", repr(fn))

    class _Instrumented:
        __slots__ = ()

        def __call__(self, *args, **kwargs):
            if telemetry.enabled:
                telemetry.record_jit_call(
                    label, abstract_signature(args, kwargs)
                )
            return fn(*args, **kwargs)

        def __getattr__(self, attr):
            return getattr(fn, attr)

    wrapped = _Instrumented()
    return wrapped


def load_trace(path: str) -> Dict[str, Any]:
    """Read a JSON-lines trace file into the standard Chrome-trace document
    ``{"traceEvents": [...]}`` (loadable by chrome://tracing / Perfetto)."""
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                events.append(json.loads(line))
    return {"traceEvents": events}
