"""Runtime telemetry: spans, device-boundary accounting, recompile
detection, watermark-lag gauges.

The reference instruments its pipelines with Flink/NES metrics (``com/mn/``);
those host-side counters are ported in ``mn/``. This module adds the layer
the JVM build never needed: visibility at the HOST↔DEVICE boundary, where
every perf pathology this codebase has hit lives —

- per-window eager-op recompiles (~1-2 s each over the tunnel) → the
  recompile detector keyed by (kernel, abstract shape signature);
- transfers over a ±50% ~28 MB/s tunnel → host→device / device→host byte
  accounting at the batch-shipping entry points (``operators/base.py``);
- ``jax.block_until_ready`` being a NO-OP over the axon tunnel → the
  ``fetch`` true-sync helper times via a real ``jax.device_get`` (the only
  actual synchronization point; the bug that once produced a bogus
  106M pts/s number);
- windows firing late / events dropped → watermark-lag and late-drop
  gauges fed by the ``streams/`` assemblers.

Contract: **disabled by default and free when disabled** (operator hot
paths do one ``telemetry.enabled`` attribute check per window, nothing
per event); when enabled, instrumentation adds **zero device round trips**
beyond the operator's own fetches — byte accounting reads host-array
``nbytes`` before shipping, and ``fetch`` REPLACES (never duplicates) the
operator's existing device→host materialization.

Spans emit Chrome-trace/Perfetto-compatible complete events ("ph": "X",
microsecond ts/dur) as JSON-lines; ``load_trace`` wraps a trace file into
the standard ``{"traceEvents": [...]}`` document. Spans named
``window.*`` additionally feed a ``FixedBucketLatency`` histogram, so
p50/p95 window latency lands in NES reporter lines and bench.py's JSON.

On top of the raw signals sits the **run ledger** (``write_ledger``): a
per-(kernel, signature) runtime table fed by ``instrument_jit`` (call
count, cumulative dispatch wall-ns, first-call compile-inclusive
latency) plus lazy host-side XLA cost capture (``capture_costs`` —
AOT ``lower().compile().cost_analysis()/memory_analysis()`` from
recorded avals, never live arrays, zero device round trips), exported
as ONE schema-versioned JSON document that ``tools/sfprof`` reports,
diffs, and gates on.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import warnings
from collections import deque
from typing import Any, Dict, Optional, Tuple

from spatialflink_tpu.ablation import ablation
from spatialflink_tpu.faults import faults
from spatialflink_tpu.mn.metrics import FixedBucketLatency, json_safe


#: Run-ledger schema version (bump on any breaking change to the document
#: layout). Twin constant: tools/sfprof/ledger.py:LEDGER_VERSION — the
#: validator deliberately doesn't import this package, so bump BOTH
#: (tests/test_sfprof.py cross-pins them). v2: per-node attribution
#: (snapshot ``nodes`` block, kernel-row ``node`` column) + collective
#: accounting (snapshot ``collectives`` block); v3: event-time
#: end-to-end latency (snapshot ``e2e`` block — per-stage + per-node
#: FixedBucketLatency gauges). v1/v2 documents remain readable (the new
#: blocks are additive and appear only when their producers ran).
LEDGER_VERSION = 3

#: Ledger-STREAM record-layout version (the JSONL segment format behind
#: ``SFT_LEDGER_STREAM``). Twin constant: tools/sfprof/stream.py:
#: STREAM_VERSION — same no-cross-import rule, same cross-pin test.
#: v2: checkpoints carry the v2 snapshot blocks above; v3: checkpoints
#: may carry the ``e2e`` block and a ``<stream>.blackbox.json`` flight-
#: recorder dump may sit beside the stream (``sfprof recover`` folds a
#: present dump in). The grammar itself is unchanged, so v1/v2 streams
#: still recover.
STREAM_VERSION = 3


def _sanitize_nonfinite(value):
    """(sanitized, count): every non-finite float (NaN/±Inf) anywhere in
    the structure becomes ``None``, counted. A NaN at the very END of a
    run used to raise out of ``write_ledger`` (``allow_nan=False``) and
    lose the whole capture — sanitize-and-count keeps the artifact and
    makes the corruption visible (``nonfinite_values`` field) instead."""
    if isinstance(value, float):
        if value != value or value in (float("inf"), float("-inf")):
            return None, 1
        return value, 0
    if isinstance(value, dict):
        n = 0
        out = {}
        for k, v in value.items():
            out[k], dn = _sanitize_nonfinite(v)
            n += dn
        return out, n
    if isinstance(value, (list, tuple)):
        n = 0
        out = []
        for v in value:
            sv, dn = _sanitize_nonfinite(v)
            out.append(sv)
            n += dn
        return out, n
    return value, 0


class RecompileWarning(UserWarning):
    """One kernel crossed the distinct-abstract-shape threshold — bucket
    churn or an accidentally dynamic shape is forcing XLA recompiles."""


def _arg_signature(a):
    """One argument's contribution to the abstract signature. Arrays →
    (shape, dtype) — the aval; tuples/lists/dicts recurse (jit flattens
    pytrees, so a container of arrays recompiles whenever ANY leaf's
    shape changes — e.g. the knn pane digests repadded to a grown nseg;
    repr of a container would MATERIALIZE its arrays, a device fetch per
    call); other leaves contribute only their type (jit treats distinct
    Python scalars of one type as one aval)."""
    shape = getattr(a, "shape", None)
    dtype = getattr(a, "dtype", None)
    if shape is not None and dtype is not None:
        return (tuple(shape), str(dtype))
    if isinstance(a, (tuple, list)):
        return (type(a).__name__, tuple(_arg_signature(x) for x in a))
    if isinstance(a, dict):
        return ("dict", tuple(
            (str(k), _arg_signature(v)) for k, v in sorted(a.items())
        ))
    return type(a).__name__


def abstract_signature(args: tuple, kwargs: Optional[dict] = None) -> Tuple:
    """Hashable proxy of jax.jit's cache key for a call.

    Positional arguments go through ``_arg_signature`` (avals for arrays,
    recursive for containers); keyword arguments holding arrays or
    containers of arrays do too (e.g. the pane scan's ``lps_expire``
    array tuples — repr would MATERIALIZE the arrays, a device fetch
    per call), while every other kwarg contributes (name, repr(value))
    because scalar/string kwargs in this codebase are static arguments,
    where the VALUE keys the compile cache.
    """
    parts = [_arg_signature(a) for a in args]
    for k in sorted(kwargs or ()):
        v = kwargs[k]
        shape = getattr(v, "shape", None)
        dtype = getattr(v, "dtype", None)
        if shape is not None and dtype is not None:
            parts.append((k, (tuple(shape), str(dtype))))
        elif isinstance(v, (tuple, list, dict)):
            parts.append((k, _arg_signature(v)))
        else:
            parts.append((k, repr(v)))
    return tuple(parts)


class _NullSpan:
    """No-op context manager returned while telemetry is disabled — one
    shared instance, so the disabled-path cost is a truthiness check."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tel", "name", "args", "_t0")

    def __init__(self, tel: "Telemetry", name: str, args: Dict[str, Any]):
        self._tel = tel
        self.name = name
        self.args = args

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        self._tel._emit_span(
            self.name, self._t0, time.perf_counter_ns() - self._t0, self.args
        )
        return False


class _Scope:
    """Node-attribution scope: pushes a node name onto the emitting
    thread's scope stack for the duration of the ``with`` block.
    Innermost wins (``current_node`` reads the top), so the DAG's
    per-node scopes override the driver's operator-level one."""

    __slots__ = ("_tel", "node")

    def __init__(self, tel: "Telemetry", node: str):
        self._tel = tel
        self.node = node

    def __enter__(self):
        tls = self._tel._tls
        stack = getattr(tls, "stack", None)
        if stack is None:
            stack = tls.stack = []
        stack.append(self.node)
        return self

    def __exit__(self, *exc):
        self._tel._tls.stack.pop()
        return False


class Telemetry:
    """Process-global telemetry registry (the ``ops/counters.py`` idiom:
    one module singleton, ``enable()`` to opt in)."""

    def __init__(self, max_events: int = 262_144):
        self.enabled = False
        self.max_events = max_events
        self.recompile_warn_threshold = 8
        self.trace_path: Optional[str] = None
        self._trace_file = None
        # Append-only ledger stream (SFT_LEDGER_STREAM): JSONL segments —
        # versioned prologue, window-boundary checkpoint/span-batch
        # flushes, sealing epilogue. tools/sfprof recover rebuilds a
        # gateable ledger from a truncated stream.
        self.stream_path: Optional[str] = None
        self._stream_file = None
        self._stream_sealed = False
        self.stream_flush_interval_s = 1.0
        # Optional verdict callback installed by slo.install(): called at
        # ledger-write/seal time to embed the live SLO verdict block.
        self.slo_provider = None
        # Optional overload-state callback installed by
        # overload.install(): snapshot() embeds it as ["overload"], so
        # shed/degradation/circuit counters ride every ledger-stream
        # checkpoint and survive a mid-overload crash.
        self.overload_provider = None
        # Optional standing-query-registry callback installed by
        # qserve.install(): snapshot() embeds it as ["qserve"], so
        # registered/evicted/bucket-occupancy/recompile counters ride
        # ledger-stream checkpoints like the overload block does.
        self.qserve_provider = None
        # Optional composed-dataflow callback installed by
        # dag.install(): snapshot() embeds it as ["dag"] — per-node
        # backend/retry/failover/degraded/lag counters, the post-hoc
        # half of the per-node SLO twin (tools/sfprof/slo.py
        # node_budgets).
        self.dag_provider = None
        self._lock = threading.RLock()
        # Node-attribution scope stack: THREAD-CONFINED (a scope entered
        # on the driver thread tags only that thread's emissions) so
        # concurrent operator threads can never cross-tag each other.
        self._tls = threading.local()
        self._reset_state()

    def _reset_state(self):
        self.events: list = []
        self.dropped_events = 0
        self._since_flush = 0
        self.h2d_bytes = 0
        self.h2d_transfers = 0
        self.d2h_bytes = 0
        self.d2h_transfers = 0
        self.compile_events: list = []  # (kernel, signature), append order
        self._shapes_seen: Dict[str, set] = {}
        self._warned_kernels: set = set()
        self.max_watermark_lag_ms = 0
        self.late_drops = 0
        self.window_latency = FixedBucketLatency()
        # Watermark-lag distribution (not just the max): the SLO engine's
        # p99-freshness checks and the ledger's watermark_lag_p99_ms ride
        # this histogram.
        self.watermark_lag = FixedBucketLatency()
        # Link-probe rolling samples (LinkProbe.sample → record_link_sample):
        # bounded; snapshot() exports p50/last gauges.
        self._link_samples: list = []
        # Ledger-stream bookkeeping: events since the last stream flush,
        # monotonically increasing segment seq, flush pacing clock, and
        # the running count of sanitized non-finite values.
        self._stream_pending: list = []
        self._stream_seq = 0
        self._stream_last_flush = time.monotonic()
        self.nonfinite_values = 0
        # Fault-tolerance counters: injected-fault firings per point
        # (faults.py) and the driver's self-healing actions (driver.py) —
        # retries of a failed window and device→fallback failovers. All
        # land in snapshot()["driver"]/["faults"] so sfprof health and
        # the SLO engine's budgets can see them in any ledger.
        self.fault_fires: Dict[str, int] = {}
        self.driver_retries = 0
        self.driver_failovers = 0
        # engine → {capacity bucket → {"picks", "max_live"}} — the
        # compaction control plane's pick log (ops/compaction.py).
        self._compaction: Dict[str, Dict[int, Dict[str, int]]] = {}
        # (kernel, signature) → {"calls", "dispatch_ns", "first_call_ns",
        # "cost", "lower"} — the per-kernel runtime table behind
        # kernel_table()/capture_costs() (fed by instrument_jit).
        self._kernel_stats: Dict[Tuple[str, Tuple], Dict[str, Any]] = {}
        # Wire-codec compression gauges (ops/wire_codec.py via
        # account_wire): raw vs post-codec bytes per encoded pane — the
        # h2d counter keeps counting what actually ships, these keep the
        # what-it-WOULD-have-cost denominator.
        self.wire_raw_bytes = 0
        self.wire_coded_bytes = 0
        self.wire_panes = 0
        # Pipelined-ingest executor counters (spatialflink_tpu/
        # pipeline.py via record_pipeline): overlapped vs collapsed
        # windows, checkpoint drains — sfprof health's stall notes.
        self._pipeline: Dict[str, int] = {}
        # tids already named via a ph:"M" thread_name metadata event.
        self._named_tids: set = set()
        # Per-node attribution buckets: node name (or None = unscoped) →
        # counter dict. EVERY accounting site below updates exactly one
        # bucket, so bucket totals sum EXACTLY to the untagged globals —
        # the conservation invariant tests/test_dag.py asserts. The
        # snapshot exports them (None → "(unscoped)") only once a real
        # node has been seen, keeping un-scoped ledgers byte-compatible
        # with the v1 reader.
        self._node_acct: Dict[Optional[str], Dict[str, Any]] = {}
        # Mesh-collective accounting (account_collective): kind →
        # {"calls", "bytes"} plus per-axis byte totals — host-side
        # trace-time estimates from static shapes, never a device
        # round trip.
        self._collectives: Dict[str, Dict[str, int]] = {}
        self._collective_axes: Dict[str, int] = {}
        # Grid-partitioned halo accounting (parallel/halo.py): unpadded
        # boundary-state bytes the halo exchanges existed to move — the
        # denominator of sfprof's replication-ratio line (accounted
        # collective bytes ÷ boundary-state bytes).
        self._halo_state_bytes = 0
        # Cross-shard watermark coordination (parallel/halo.py /
        # operators' partitioned paths): shard id → max event-time seen.
        # The merged min over shards is the source-clock watermark the
        # composed DAG may safely advance to.
        self._shard_watermarks: Dict[int, int] = {}
        # Overload shed accounting (record_shed): global twin of the
        # per-node "shed_events"/"shed_bytes" bucket columns.
        self.shed_events = 0
        self.shed_bytes = 0
        # Event-time end-to-end latency (record_e2e): how stale a
        # committed result is relative to the event time that produced
        # it — the real-time criterion, not processing latency. One
        # FixedBucketLatency per stage globally plus per (node, stage);
        # open per-window entries are bounded (E2E_OPEN_MAX, evictions
        # counted) so the gauge stays fixed-memory like everything else
        # here. The anchor pins the capture's wall↔event-time mapping:
        # synthetic event clocks (bench replays) get honest staleness
        # instead of a wall-minus-epoch-zero absurdity.
        self._e2e_anchor: Optional[Tuple[float, float]] = None
        self._e2e_open: Dict[int, Dict[str, float]] = {}
        self._e2e_evicted = 0
        self._e2e_stages: Dict[str, FixedBucketLatency] = {}
        self._e2e_nodes: Dict[str, Dict[str, FixedBucketLatency]] = {}
        # Flight recorder (the crash black box): bounded ring of the
        # last-N window-span summaries + instant events, dumped to
        # <stream>.blackbox.json on fault fire and stream seal (which
        # covers dial timeout, disable, and normal completion) — the
        # r3–r5 lesson that the most valuable telemetry is whatever
        # survived the crash. SFT_BLACKBOX sizes the ring; "0" disables.
        try:
            bb_n = int(os.environ.get("SFT_BLACKBOX", "64"))
        except ValueError:
            bb_n = 64
        self._blackbox: Optional[deque] = (
            deque(maxlen=bb_n) if bb_n > 0 else None
        )

    # -- lifecycle ------------------------------------------------------------

    def enable(self, trace_path: Optional[str] = None,
               recompile_warn_threshold: int = 8,
               stream_path: Optional[str] = None,
               stream_flush_interval_s: Optional[float] = None):
        """Reset all state and start recording. ``trace_path``: optional
        Chrome-trace JSON-lines file (events also buffer in memory, capped
        at ``max_events``). ``stream_path``: optional append-only ledger
        stream (JSONL) — a versioned prologue now, checkpoint + span-batch
        segments at window boundaries (paced by
        ``stream_flush_interval_s``, default 1 s or the
        ``SFT_LEDGER_STREAM_INTERVAL_S`` env), a sealing epilogue at
        ``write_ledger``/``disable``. A run killed mid-stream loses at
        most one flush interval; ``tools/sfprof recover`` rebuilds the
        ledger from the truncated stream."""
        with self._lock:
            self.disable()
            self._reset_state()
            self.recompile_warn_threshold = int(recompile_warn_threshold)
            self.trace_path = trace_path
            self.stream_path = stream_path
            self._stream_sealed = False
            if stream_path:
                if stream_flush_interval_s is None:
                    stream_flush_interval_s = float(os.environ.get(
                        "SFT_LEDGER_STREAM_INTERVAL_S", "1.0"))
                self.stream_flush_interval_s = float(stream_flush_interval_s)
                d = os.path.dirname(os.path.abspath(stream_path))
                os.makedirs(d, exist_ok=True)
                self._stream_file = open(stream_path, "w")
                # Prologue env is deliberately jax-free: enable() must
                # not import jax (bench enables before the backend is
                # settled in some paths); the full env block rides the
                # epilogue's ledger / the recovered document notes the
                # difference.
                self._write_stream({
                    "t": "prologue",
                    "stream_version": STREAM_VERSION,
                    "ledger_version": LEDGER_VERSION,
                    "created_unix": time.time(),
                    "env": {
                        "python": sys.version.split()[0],
                        "pid": os.getpid(),
                        "argv0": os.path.basename(sys.argv[0] or "python"),
                    },
                })
                self._stream_file.flush()
            if trace_path:
                d = os.path.dirname(os.path.abspath(trace_path))
                os.makedirs(d, exist_ok=True)
                self._trace_file = open(trace_path, "w")
                # Chrome-trace metadata: name the process once per pid so
                # Perfetto shows the program, not a bare number. Threads
                # are named lazily — one ph:"M" per NEW tid at its first
                # event (_emit) — because operator threads don't exist yet
                # at enable() time.
                self._write_trace({
                    "name": "process_name", "ph": "M", "pid": os.getpid(),
                    "args": {"name": "spatialflink_tpu:"
                             + os.path.basename(sys.argv[0] or "python")},
                })
            self.enabled = True
        # A plan armed BEFORE telemetry came up (the SFT_FAULT_PLAN
        # import-time path every chaos subprocess uses) would otherwise
        # never record its fault_armed event — emit it now so any
        # telemetry-enabled chaos run carries the armed schedule, not
        # just the firings (faults.arm() covers the arm-after-enable
        # order).
        if faults.armed:
            self.emit_instant(
                "fault_armed", plan=[r.to_dict() for r in faults.rules]
            )
        # Fresh capture, fresh ablation taint scope: counters reset so
        # the taint block reflects THIS capture's substitutions; the
        # armed marker is re-emitted for the same arm-before-enable
        # reason as fault_armed above (SFT_ABLATE arms at import).
        ablation.reset_counters()
        if ablation.armed:
            self.emit_instant(
                "ablation_armed", kernels=sorted(ablation.kernels)
            )

    def disable(self):
        """Stop recording and SEAL both sinks: the ledger stream gets its
        epilogue (a disable() with no ``write_ledger`` used to leave the
        stream unsealed — indistinguishable from a crash), and the trace
        file is explicitly flushed before close so a mid-run disable can
        never strand ``_since_flush`` buffered events."""
        with self._lock:
            self.enabled = False
            self.seal_stream("disabled")
            if self._trace_file is not None:
                self._trace_file.flush()
                self._since_flush = 0
                self._trace_file.close()
                self._trace_file = None

    FLUSH_EVERY = 256

    def flush_trace(self):
        """Drain the buffered trace writer NOW. Call before a timed
        region: emits inside it then start from a fresh FLUSH_EVERY
        budget, so the periodic disk flush can't land mid-measurement
        (bench.py's latency probe)."""
        with self._lock:
            if self._trace_file is not None:
                self._trace_file.flush()
                self._since_flush = 0

    def _write_trace(self, event: dict):
        """Buffered trace write (caller holds the lock). No per-event
        flush — a synchronous flush per span would serialize operator
        threads through disk I/O and distort the spans being measured;
        the buffer drains every FLUSH_EVERY events and on disable()."""
        self._trace_file.write(json.dumps(event) + "\n")
        self._since_flush += 1
        if self._since_flush >= self.FLUSH_EVERY:
            self._trace_file.flush()
            self._since_flush = 0

    # -- ledger stream ---------------------------------------------------------

    def _write_stream(self, record: dict):
        """One JSONL stream record (caller holds the lock). Non-finite
        floats are sanitized to null and counted — a strict-JSON raise
        here would lose the stream's whole point (crash resilience)."""
        record, n = _sanitize_nonfinite(json_safe(record))
        if n:
            self.nonfinite_values += n
        self._stream_file.write(json.dumps(record, allow_nan=False) + "\n")

    def maybe_flush_stream(self, force: bool = False):
        """Window-boundary stream flush: a span batch (events since the
        last flush) + a full checkpoint (snapshot + kernel table), paced
        by ``stream_flush_interval_s`` so the disk work stays off the
        per-window hot path. ``force=True`` flushes regardless — phase
        boundaries and SLO violations use it."""
        with self._lock:
            if self._stream_file is None or self._stream_sealed:
                return
            now = time.monotonic()
            if (not force and now - self._stream_last_flush
                    < self.stream_flush_interval_s):
                return
            self._stream_last_flush = now
            self._flush_stream_locked()

    def _flush_stream_locked(self):
        self._stream_seq += 1
        seq = self._stream_seq
        if self._stream_pending:
            self._write_stream({
                "t": "spans", "seq": seq, "events": self._stream_pending,
            })
            self._stream_pending = []
        ck = {
            "t": "checkpoint", "seq": seq, "unix": time.time(),
            "snapshot": self.snapshot(), "kernels": self.kernel_table(),
        }
        if self.nonfinite_values:
            ck["nonfinite_values"] = self.nonfinite_values
        self._write_stream(ck)
        self._stream_file.flush()

    def seal_stream(self, reason: str, bench: Optional[dict] = None,
                    slo: Optional[dict] = None):
        """Terminal stream segment: final span batch + checkpoint, then
        the epilogue carrying the termination ``reason`` (and the bench
        record / SLO verdict when the run completed normally). Idempotent
        — the first seal wins; later calls (e.g. ``disable()`` after
        ``write_ledger``) are no-ops."""
        with self._lock:
            if self._stream_file is None or self._stream_sealed:
                return
            # Flight-recorder dump rides EVERY seal — dial_timeout,
            # disable, and normal completion alike (ISSUE: the black box
            # is cheapest exactly when nobody thinks they need it). The
            # marker instant lands in the final span batch below.
            bb = self.dump_blackbox(reason)
            if bb is not None and self.enabled:
                self.emit_instant("blackbox_dumped",
                                  reason=str(reason), path=bb)
            self._flush_stream_locked()
            if slo is None and self.slo_provider is not None:
                try:
                    slo = self.slo_provider()  # sfcheck: ok=lock-discipline -- documented one-way lock order: the SLO engine re-enters this RLock on the same thread (safe) and the overload controller queues its emits (overload._emit_locked) instead of ever taking this lock
                except Exception:  # a broken verdict must not block the seal
                    slo = None
            ep = {
                "t": "epilogue", "seq": self._stream_seq,
                "unix": time.time(), "reason": str(reason),
            }
            if bench is not None:
                ep["bench"] = bench
            if slo is not None:
                ep["slo"] = slo
            if self.nonfinite_values:
                ep["nonfinite_values"] = self.nonfinite_values
            self._write_stream(ep)
            self._stream_file.flush()
            self._stream_file.close()
            self._stream_file = None
            self._stream_sealed = True

    # -- node-attribution scope ------------------------------------------------

    def scope(self, node: Optional[str]):
        """Tag everything emitted by THIS thread inside the ``with``
        block with ``node``: spans, instant events, h2d/d2h/wire bytes,
        recompile detections, fault firings, shed counts, collective
        bytes, and kernel-table rows. ``None`` is a no-op (the qserve
        standalone-vs-DAG conditional), and an unset scope costs one
        thread-local read at each accounting site — nothing per event."""
        if node is None:
            return _NULL_SPAN
        return _Scope(self, str(node))

    def current_node(self) -> Optional[str]:
        """The innermost active scope's node name on this thread."""
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else None

    def _node_bucket(self, node: Optional[str]) -> Dict[str, Any]:
        """This node's accounting bucket (caller holds the lock)."""
        b = self._node_acct.get(node)
        if b is None:
            b = self._node_acct[node] = {
                "spans": 0, "span_us": 0, "windows": 0, "events": 0,
                "window_latency": FixedBucketLatency(),
                "h2d_bytes": 0, "h2d_transfers": 0,
                "d2h_bytes": 0, "d2h_transfers": 0,
                "wire_raw_bytes": 0, "wire_coded_bytes": 0,
                "wire_panes": 0,
                "compiles": 0, "instants": 0, "fault_fires": 0,
                "shed_events": 0, "shed_bytes": 0,
                "collective_calls": 0, "collective_bytes": 0,
                "dispatch_ns": 0, "kernel_calls": 0,
            }
        return b

    def node_rollup(self) -> Dict[str, Dict[str, Any]]:
        """JSON-safe per-node counter rollup (the snapshot ``nodes``
        block): one row per seen node, ``(unscoped)`` for emissions made
        outside any scope. Empty dict while no real node has been
        scoped — the byte-compat contract for un-scoped runs."""
        with self._lock:
            if not any(k is not None for k in self._node_acct):
                return {}
            out: Dict[str, Dict[str, Any]] = {}
            for node, b in self._node_acct.items():
                lat = b["window_latency"]
                p50 = lat.percentile(0.50)
                p95 = lat.percentile(0.95)
                row = {k: v for k, v in b.items()
                       if k != "window_latency"}
                row["window_latency_p50_ms"] = None if p50 != p50 else p50
                row["window_latency_p95_ms"] = None if p95 != p95 else p95
                out[node if node is not None else "(unscoped)"] = row
        return json_safe(out)

    # -- spans ----------------------------------------------------------------

    def span(self, name: str, **args):
        """Context manager timing one phase. Nesting renders naturally in
        Chrome tracing (same tid, contained ts/dur). ``window.*`` spans
        also feed the window-latency histogram."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, args)

    def _emit_span(self, name, t0_ns, dur_ns, args):
        if not self.enabled:  # disabled mid-span
            return
        node = self.current_node()
        ev = {
            "name": name,
            "cat": "telemetry",
            "ph": "X",
            "ts": t0_ns // 1000,
            "dur": dur_ns // 1000,
            "pid": os.getpid(),
            "tid": threading.get_ident(),
        }
        if node is not None:
            args = dict(args or ())
            args.setdefault("node", node)
        if args:
            ev["args"] = json_safe(args)
        self._emit(ev)
        with self._lock:
            b = self._node_bucket(node)
            b["spans"] += 1
            b["span_us"] += dur_ns // 1000
            if name.startswith("node."):
                # The DAG's per-node container spans: per-node window
                # count / event count / latency (window.* spans nested
                # inside would double-count the same wall time).
                b["windows"] += 1
                ev_n = (args or {}).get("events")
                if isinstance(ev_n, (int, float)):
                    b["events"] += int(ev_n)
                b["window_latency"].observe(dur_ns / 1e6)
        if name.startswith("window"):
            with self._lock:
                self.window_latency.observe(dur_ns / 1e6)
                # Flight recorder: the ring keeps the last-N window
                # summaries so a crash dump shows what the run was DOING,
                # not just its counters.
                self._blackbox_append({
                    "t": "window", "name": name, "ts": ev["ts"],
                    "dur_us": ev["dur"], "args": ev.get("args", {}),
                })
            # Window boundary = the stream's flush point (interval-paced
            # inside, so per-window cost is one clock read + a compare).
            self.maybe_flush_stream()

    def emit_instant(self, name: str, **args):
        """Structured instant event (``ph:"i"``) into the buffer, trace
        file, and ledger stream — the SLO engine's violation events and
        any other out-of-band markers ride this."""
        if not self.enabled:
            return
        node = self.current_node()
        if node is not None:
            args = dict(args)
            args.setdefault("node", node)
        ts = time.perf_counter_ns() // 1000
        safe_args = json_safe(args)
        with self._lock:
            self._node_bucket(node)["instants"] += 1
            # Flight recorder: instants ride the ring too — a crash dump
            # without the fault/failover markers around it is useless.
            self._blackbox_append({"t": "instant", "name": name,
                                   "ts": ts, "args": safe_args})
        self._emit({
            "name": name, "cat": "telemetry", "ph": "i",
            "ts": ts, "pid": os.getpid(),
            "tid": threading.get_ident(), "s": "t",
            "args": safe_args,
        })

    def _emit(self, event: dict):
        with self._lock:
            if len(self.events) < self.max_events:
                self.events.append(event)
            else:
                self.dropped_events += 1
            if self._stream_file is not None and not self._stream_sealed:
                # The stream keeps EVERY event (like the trace file): the
                # max_events cap bounds memory, not the artifact; pending
                # drains into a span batch at each stream flush.
                self._stream_pending.append(event)
            if self._trace_file is not None:
                tid = event.get("tid")
                if tid is not None and tid not in self._named_tids:
                    # First event from this thread: emit its thread_name
                    # metadata so the trace row reads e.g. "MainThread"
                    # / the operator thread's name instead of a raw
                    # ident. _emit runs on the emitting thread, so
                    # current_thread() IS the thread being named.
                    self._named_tids.add(tid)
                    self._write_trace({
                        "name": "thread_name", "ph": "M",
                        "pid": event.get("pid", os.getpid()), "tid": tid,
                        "args": {"name": threading.current_thread().name},
                    })
                self._write_trace(event)

    # -- device-boundary accounting -------------------------------------------

    def account_h2d(self, nbytes: int):
        """Bytes about to ship host→device (read from the HOST array before
        the transfer — no device round trip)."""
        if not self.enabled:
            return
        with self._lock:
            self.h2d_bytes += int(nbytes)
            self.h2d_transfers += 1
            b = self._node_bucket(self.current_node())
            b["h2d_bytes"] += int(nbytes)
            b["h2d_transfers"] += 1
            if self._trace_file is not None:
                self._write_trace({
                    "name": "h2d_bytes", "ph": "C",
                    "ts": time.perf_counter_ns() // 1000,
                    "pid": os.getpid(), "args": {"bytes": self.h2d_bytes},
                })

    def account_d2h(self, nbytes: int):
        """Bytes fetched device→host (counted at the true-sync fetch).
        Mirrors ``account_h2d`` exactly — including the Chrome-trace
        ``ph:"C"`` counter event, so d2h traffic renders as a Perfetto
        counter track too (the h2d/d2h asymmetry hid egress bytes)."""
        if not self.enabled:
            return
        with self._lock:
            self.d2h_bytes += int(nbytes)
            self.d2h_transfers += 1
            b = self._node_bucket(self.current_node())
            b["d2h_bytes"] += int(nbytes)
            b["d2h_transfers"] += 1
            if self._trace_file is not None:
                self._write_trace({
                    "name": "d2h_bytes", "ph": "C",
                    "ts": time.perf_counter_ns() // 1000,
                    "pid": os.getpid(), "args": {"bytes": self.d2h_bytes},
                })

    def fetch(self, x):
        """True-sync device→host fetch with timing + byte accounting.

        ``jax.block_until_ready`` is a NO-OP over the axon tunnel — it
        returns before transfers/compute finish (CLAUDE.md) — so a real
        ``jax.device_get`` is the ONLY honest synchronization point.
        Accepts any pytree; returns host numpy. Use this IN PLACE OF the
        operator's ``np.asarray``/``device_get`` so accounting rides the
        fetch the operator was doing anyway (zero extra round trips).
        """
        import jax

        if faults.armed:  # chaos injection point (faults.py)
            faults.hit("device.fetch")
        if not self.enabled:
            return jax.device_get(x)
        t0 = time.perf_counter_ns()
        out = jax.device_get(x)
        dur_ns = time.perf_counter_ns() - t0
        nbytes = 0
        for leaf in jax.tree_util.tree_leaves(out):
            nbytes += getattr(leaf, "nbytes", 0)
        self.account_d2h(nbytes)
        fetch_args: Dict[str, Any] = {"bytes": int(nbytes)}
        node = self.current_node()
        if node is not None:
            fetch_args["node"] = node
        self._emit({
            "name": "fetch", "cat": "telemetry", "ph": "X",
            "ts": t0 // 1000, "dur": dur_ns // 1000,
            "pid": os.getpid(), "tid": threading.get_ident(),
            "args": fetch_args,
        })
        return out

    # -- recompile detection --------------------------------------------------

    def record_jit_call(self, kernel: str, signature: Tuple) -> bool:
        """Record a call into a jitted kernel. A signature not seen before
        for this kernel is one XLA compile (jit's cache key is the abstract
        shapes + statics this signature proxies). Crossing
        ``recompile_warn_threshold`` distinct signatures warns once —
        catching bucket-size churn and accidentally dynamic shapes.
        Returns True iff the signature is NEW (so the caller can do
        first-call-only work, e.g. stash avals for cost capture)."""
        if not self.enabled:
            return False
        node = self.current_node()
        warn_n = None
        with self._lock:
            seen = self._shapes_seen.setdefault(kernel, set())
            if signature in seen:
                return False
            seen.add(signature)
            self.compile_events.append((kernel, signature))
            # A compile is charged to the node whose call triggered it
            # (XLA compiles once per signature, so exactly one bucket
            # gets it — node compile totals sum to the global count).
            self._node_bucket(node)["compiles"] += 1
            if (len(seen) >= self.recompile_warn_threshold
                    and kernel not in self._warned_kernels):
                self._warned_kernels.add(kernel)
                warn_n = len(seen)
        compile_args: Dict[str, Any] = {"signature": repr(signature)}
        if node is not None:
            compile_args["node"] = node
        self._emit({
            "name": f"compile:{kernel}", "cat": "telemetry", "ph": "i",
            "ts": time.perf_counter_ns() // 1000, "pid": os.getpid(),
            "tid": threading.get_ident(), "s": "t",
            "args": compile_args,
        })
        if warn_n is not None:
            warnings.warn(
                f"kernel '{kernel}' has compiled for {warn_n} distinct "
                f"abstract shapes (threshold "
                f"{self.recompile_warn_threshold}): each is ~1-2 s of XLA "
                "compile + a tunnel round trip — check for bucket-size "
                "churn or an un-bucketed dynamic dimension",
                RecompileWarning,
                stacklevel=3,
            )
        return True

    @property
    def compile_count(self) -> int:
        return len(self.compile_events)

    def distinct_shapes(self, kernel: str) -> int:
        with self._lock:
            return len(self._shapes_seen.get(kernel, ()))

    # -- per-kernel runtime table + cost capture -------------------------------

    def record_kernel_time(self, kernel: str, signature: Tuple,
                           dur_ns: int, lower_ctx=None):
        """One dispatch into an instrumented kernel: accumulate call count
        and dispatch wall-ns per (kernel, signature); the first call's
        duration is kept separately (it includes the XLA compile).
        ``lower_ctx`` — a ``(fn, abstract_args, abstract_kwargs)`` triple
        built from ShapeDtypeStructs, never live arrays — is stashed so
        ``capture_costs`` can lower/compile host-side LATER, strictly off
        the hot path."""
        if not self.enabled:
            return
        node = self.current_node()
        with self._lock:
            # Keyed per (kernel, signature, node): one kernel dispatched
            # by two DAG nodes gets one row EACH, so per-node dispatch
            # totals sum to the global table (conservation) instead of
            # blending into one unattributable row.
            key = (kernel, signature, node)
            st = self._kernel_stats.get(key)
            if st is None:
                st = self._kernel_stats[key] = {
                    "calls": 0,
                    "dispatch_ns": 0,
                    "first_call_ns": int(dur_ns),
                    "cost": None,
                    "lower": lower_ctx,
                }
            elif lower_ctx is not None and st["lower"] is None \
                    and st["cost"] is None:
                st["lower"] = lower_ctx
            st["calls"] += 1
            st["dispatch_ns"] += int(dur_ns)
            b = self._node_bucket(node)
            b["kernel_calls"] += 1
            b["dispatch_ns"] += int(dur_ns)

    def capture_costs(self):
        """Lazy host-side XLA cost/memory analysis, once per (kernel,
        signature). AOT ``fn.lower(*avals).compile()`` never executes the
        program and moves no data, so this adds ZERO device round trips
        (pinned under ``jax.transfer_guard`` in tests) — it only costs
        host compile time, which is why it runs here (write_ledger /
        explicit call) and never on the hot path. Idempotent; a kernel
        that won't lower records ``{"error": ...}`` instead of blocking
        the ledger."""
        with self._lock:
            pending = [
                st for st in self._kernel_stats.values()
                if st["cost"] is None and st["lower"] is not None
            ]
        for st in pending:
            fn, a_args, a_kwargs = st["lower"]
            cost = _analyze_cost(fn, a_args, a_kwargs)
            with self._lock:
                st["cost"] = cost
                st["lower"] = None

    def kernel_table(self) -> list:
        """JSON-safe per-(kernel, signature) rows: calls, cumulative
        dispatch wall-ns, first-call (compile-inclusive) ns, the derived
        ``steady_ns`` (cumulative MINUS the first call — a compile here
        is ~1-2 s against sub-ms dispatches, so ranking by the raw
        cumulative would just rank compiles), and the captured cost
        block (None until ``capture_costs`` runs). Sorted by steady
        dispatch time, heaviest first."""
        with self._lock:
            rows = []
            for (kernel, sig, node), st in self._kernel_stats.items():
                row = {
                    "kernel": kernel,
                    "signature": repr(sig),
                    "calls": st["calls"],
                    "dispatch_ns": st["dispatch_ns"],
                    "first_call_ns": st["first_call_ns"],
                    "steady_ns": max(
                        st["dispatch_ns"] - st["first_call_ns"], 0
                    ),
                    "cost": st["cost"],
                }
                if node is not None:
                    # v2 column, present only on scoped rows — un-scoped
                    # runs emit the exact v1 row shape.
                    row["node"] = node
                rows.append(row)
        rows.sort(key=lambda r: (-r["steady_ns"], -r["dispatch_ns"],
                                 r["kernel"]))
        return json_safe(rows)

    # -- run ledger ------------------------------------------------------------

    def write_ledger(self, path: str, bench: Optional[dict] = None,
                     mesh=None, capture_costs: bool = True) -> str:
        """One schema-versioned JSON run-ledger document: environment
        (python/jax/backend/devices, optional mesh shape), the full
        ``snapshot()``, the per-kernel runtime table (costs captured
        lazily here unless ``capture_costs=False``), the buffered span
        events (so ``tools/sfprof report`` can attribute phases without
        a separate trace file), and the caller's bench record. Strict
        JSON (``allow_nan=False``) — but a NaN/Inf anywhere is sanitized
        to null and COUNTED (``nonfinite_values``) rather than raised: a
        raise at the very end of a run used to lose the whole capture.
        Seals the ledger stream (``reason: complete``) when one is open.
        Consumed by ``python -m tools.sfprof`` (report / diff --gate /
        health)."""
        import jax

        if capture_costs:
            self.capture_costs()
        env = {
            "python": sys.version.split()[0],
            "jax": jax.__version__,
            "backend": jax.default_backend(),
            "device_count": jax.device_count(),
            "devices": [str(d) for d in jax.devices()[:8]],
            "x64": bool(jax.config.jax_enable_x64),
            "pid": os.getpid(),
            "argv0": os.path.basename(sys.argv[0] or "python"),
        }
        if mesh is not None:
            env["mesh"] = {str(k): int(v)
                           for k, v in dict(mesh.shape).items()}
        with self._lock:
            events = list(self.events)
        slo_block = None
        if self.slo_provider is not None:
            try:
                slo_block = json_safe(self.slo_provider())
            except Exception:  # a broken verdict must not block the ledger
                slo_block = None
        doc = {
            "ledger_version": LEDGER_VERSION,
            "created_unix": time.time(),
            "env": env,
            "snapshot": self.snapshot(),
            "kernels": self.kernel_table(),
            "events": events,
            "bench": json_safe(bench) if bench is not None else None,
        }
        if slo_block is not None:
            doc["slo"] = slo_block
        taint = ablation.taint_block()
        if taint is not None:
            # Top-level mirror of the snapshot taint: gates must reject
            # without digging into the snapshot, and a hand-edited
            # snapshot must not untaint the document.
            doc["tainted"] = taint
        doc, nonfinite = _sanitize_nonfinite(doc)
        if nonfinite:
            doc["nonfinite_values"] = nonfinite
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(doc, f, allow_nan=False)
            f.write("\n")
        self.seal_stream("complete", bench=doc["bench"], slo=slo_block)
        return path

    # -- compaction bucket accounting -----------------------------------------

    def record_compaction(self, engine: str, capacity: int, live: int):
        """One host-side bucket pick by the live-slot compaction control
        plane (ops/compaction.py): ``engine`` compiled/ran at static
        capacity ``capacity`` for an observed live occupancy of
        ``live``. Per-(engine, bucket) pick counts + max observed live
        land in ``snapshot()`` — occupancy drift shows up as bucket
        churn here, and as at most ladder-many distinct signatures in
        the recompile detector (the bucket is a static of the scan)."""
        if not self.enabled:
            return
        with self._lock:
            d = self._compaction.setdefault(engine, {}).setdefault(
                int(capacity), {"picks": 0, "max_live": 0}
            )
            d["picks"] += 1
            d["max_live"] = max(d["max_live"], int(live))
        self._emit({
            "name": f"compaction:{engine}", "cat": "telemetry", "ph": "i",
            "ts": time.perf_counter_ns() // 1000, "pid": os.getpid(),
            "tid": threading.get_ident(), "s": "t",
            "args": {"capacity": int(capacity), "live": int(live)},
        })

    def compaction_buckets(self, engine: str) -> Dict[int, Dict[str, int]]:
        with self._lock:
            return {
                k: dict(v)
                for k, v in self._compaction.get(engine, {}).items()
            }

    # -- pipelined ingest (spatialflink_tpu/pipeline.py) -----------------------

    def account_wire(self, raw_bytes: int, coded_bytes: int):
        """One encoded wire pane: what the raw 6 B/pt wire would have
        shipped vs what the codec actually did (header included). The
        ship-site ``account_h2d`` keeps counting the true shipped bytes
        — this pair exists so the compression ratio has an honest
        denominator in the record/ledger (``snapshot()["wire_codec"]``)."""
        if not self.enabled:
            return
        with self._lock:
            self.wire_raw_bytes += int(raw_bytes)
            self.wire_coded_bytes += int(coded_bytes)
            self.wire_panes += 1
            b = self._node_bucket(self.current_node())
            b["wire_raw_bytes"] += int(raw_bytes)
            b["wire_coded_bytes"] += int(coded_bytes)
            b["wire_panes"] += 1

    def record_pipeline(self, **counts: int):
        """Accumulate pipelined-executor counters (windows, overlapped,
        sync, drains, collapses — pipeline.py documents each). Lands in
        ``snapshot()["pipeline"]`` so `sfprof health` can note stalls."""
        if not self.enabled:
            return
        with self._lock:
            for key, n in counts.items():
                self._pipeline[key] = self._pipeline.get(key, 0) + int(n)

    def pipeline_counters(self) -> Dict[str, int]:
        """Current executor counters (empty dict before the first
        pipelined window) — bench.py stamps these into its record."""
        with self._lock:
            return dict(self._pipeline)

    def wire_codec_gauges(self) -> Optional[Dict[str, Any]]:
        """Compression summary (None before the first encoded pane)."""
        with self._lock:
            if not self.wire_panes:
                return None
            return {
                "panes": self.wire_panes,
                "raw_bytes": self.wire_raw_bytes,
                "coded_bytes": self.wire_coded_bytes,
                "ratio": (self.wire_raw_bytes / self.wire_coded_bytes
                          if self.wire_coded_bytes else None),
            }

    # -- mesh-collective accounting (parallel/) --------------------------------

    def account_collective(self, kind: str, nbytes: int,
                           axis: Optional[str] = None,
                           calls: int = 1):
        """Logical bytes one mesh collective moves (psum / pmin / pmax /
        ppermute / broadcast), accounted HOST-SIDE from static trace-time
        shapes by the ``parallel/`` wrappers — never a device round trip.
        These are the all-gather/halo baselines ROADMAP item 2's
        grid-partitioned scale-out must beat; ``sfprof report`` surfaces
        them as the ``collective`` phase and roofline signal."""
        if not self.enabled:
            return
        with self._lock:
            st = self._collectives.setdefault(
                kind, {"calls": 0, "bytes": 0}
            )
            st["calls"] += int(calls)
            st["bytes"] += int(nbytes)
            if axis is not None:
                self._collective_axes[axis] = (
                    self._collective_axes.get(axis, 0) + int(nbytes)
                )
            b = self._node_bucket(self.current_node())
            b["collective_calls"] += int(calls)
            b["collective_bytes"] += int(nbytes)

    def account_halo_state(self, nbytes: int):
        """Unpadded boundary-state bytes one halo exchange shipped
        (parallel/halo.py) — the true lanes behind the padded ppermute
        payload, and the denominator of sfprof's replication-ratio line.
        Host-side static metadata, same contract as
        :meth:`account_collective`."""
        if not self.enabled:
            return
        with self._lock:
            self._halo_state_bytes += int(nbytes)

    def collective_gauges(self) -> Optional[Dict[str, Any]]:
        """Collective summary (None before the first accounted
        collective): total calls/bytes, per-kind and per-axis splits,
        plus the halo boundary-state bytes once a halo kernel has run
        (absent otherwise — the additive-keys compat contract)."""
        with self._lock:
            if not self._collectives:
                return None
            out = {
                "calls": sum(s["calls"]
                             for s in self._collectives.values()),
                "bytes": sum(s["bytes"]
                             for s in self._collectives.values()),
                "by_kind": {k: dict(s)
                            for k, s in self._collectives.items()},
                "by_axis": dict(self._collective_axes),
            }
            if self._halo_state_bytes:
                out["halo_state_bytes"] = self._halo_state_bytes
            return json_safe(out)

    # -- overload shed accounting (overload.py) --------------------------------

    def record_shed(self, n_events: int, nbytes: int = 0):
        """Events the overload controller shed before they reached an
        assembler. The controller keeps its own per-reason/per-tenant
        breakdown (snapshot ``overload`` block); this global + per-node
        twin exists so shed counts obey the same conservation invariant
        as bytes and dispatch time (DAG sheds happen at the SHARED
        source, so they land in the ``(unscoped)`` bucket)."""
        if not self.enabled:
            return
        with self._lock:
            self.shed_events += int(n_events)
            self.shed_bytes += int(nbytes)
            b = self._node_bucket(self.current_node())
            b["shed_events"] += int(n_events)
            b["shed_bytes"] += int(nbytes)

    # -- watermark / lateness gauges ------------------------------------------

    def record_watermark_lag(self, lag_ms: int):
        """Event-time ms between a fired window's end and the watermark at
        fire time — how late the window fired relative to its span. Feeds
        both the max gauge and the lag histogram (the SLO engine's p99
        freshness checks read the distribution, not just the worst case)."""
        if not self.enabled:
            return
        with self._lock:
            self.watermark_lag.observe(float(lag_ms))
            if lag_ms > self.max_watermark_lag_ms:
                self.max_watermark_lag_ms = int(lag_ms)

    def record_shard_watermark(self, shard: int, watermark_ms: int):
        """Per-shard event-time high-water mark on the grid-partitioned
        path (parallel/halo.py feeds it from each window's owned rows).
        The MERGED watermark — min over shards — is what the source
        clock may advance to: one straggling shard holds the whole
        partitioned pipeline's event time, which is exactly what these
        gauges make visible."""
        if not self.enabled:
            return
        with self._lock:
            prev = self._shard_watermarks.get(int(shard))
            if prev is None or int(watermark_ms) > prev:
                self._shard_watermarks[int(shard)] = int(watermark_ms)

    def shard_watermark_gauges(self) -> Optional[Dict[str, Any]]:
        """Cross-shard watermark summary (None before the first
        partitioned window): per-shard high-water marks (sorted string
        keys — the JSON-stable shape), the merged min-watermark, and the
        shard count."""
        with self._lock:
            if not self._shard_watermarks:
                return None
            return json_safe({
                "per_shard": {
                    str(s): self._shard_watermarks[s]
                    for s in sorted(self._shard_watermarks)
                },
                "merged_min": min(self._shard_watermarks.values()),
                "shards": len(self._shard_watermarks),
            })

    # -- link-health probe gauges ----------------------------------------------

    LINK_SAMPLES_MAX = 256

    def record_link_sample(self, latency_ms: float, roundtrip_mbps: float,
                           payload_bytes: int):
        """One LinkProbe round trip: rolling host↔device latency/bandwidth
        gauges (bounded window), an instant trace event, and — because a
        probe sample is exactly the moment to persist — a paced stream
        flush."""
        if not self.enabled:
            return
        sample = {
            "unix": time.time(),
            "latency_ms": float(latency_ms),
            "roundtrip_mbps": float(roundtrip_mbps),
            "payload_bytes": int(payload_bytes),
        }
        with self._lock:
            self._link_samples.append(sample)
            if len(self._link_samples) > self.LINK_SAMPLES_MAX:
                del self._link_samples[0]
        self.emit_instant("link_probe", latency_ms=float(latency_ms),
                          roundtrip_mbps=float(roundtrip_mbps))
        self.maybe_flush_stream()

    def link_gauges(self) -> Optional[Dict[str, Any]]:
        """Rolling link-health summary (None before the first sample):
        sample count + p50/last latency and round-trip bandwidth. bench.py
        stamps this into its record; ``sfprof diff`` uses it to ANNOTATE
        (never widen) its tolerance bands — a degraded tunnel explains an
        e2e EPS drop without excusing a device-resident one."""
        with self._lock:
            samples = list(self._link_samples)
        if not samples:
            return None
        lat = sorted(s["latency_ms"] for s in samples)
        bw = sorted(s["roundtrip_mbps"] for s in samples)
        mid = len(samples) // 2
        return json_safe({
            "samples": len(samples),
            "latency_ms_p50": lat[mid],
            "latency_ms_last": samples[-1]["latency_ms"],
            "roundtrip_mbps_p50": bw[mid],
            "roundtrip_mbps_last": samples[-1]["roundtrip_mbps"],
            "payload_bytes": samples[-1]["payload_bytes"],
        })

    def record_late_drop(self, n: int = 1):
        if not self.enabled:
            return
        with self._lock:
            self.late_drops += int(n)

    # -- event-time end-to-end latency (latency lineage) -----------------------

    #: Stage vocabulary, pipeline order. ``assemble`` = window fired at
    #: the source clock; ``ship``/``compute``/``fetch`` = the pipelined
    #: boundary crossings; ``commit`` = the sink's transactional append
    #: — the only number that answers "how stale is a committed result
    #: relative to the event time that produced it?".
    E2E_STAGES = ("assemble", "ship", "compute", "fetch", "commit")

    #: Open per-window entries are bounded: a window that never commits
    #: (shed, crashed, replaced) must not leak memory forever. Oldest
    #: win-end evicts first; evictions are counted in the ``e2e`` block.
    E2E_OPEN_MAX = 4096

    def record_e2e(self, win_end_ms, stage: str,
                   node: Optional[str] = None) -> Optional[float]:
        """One stage boundary of one window's latency lineage.

        The first stamp for a window anchors it: its ``assemble``
        latency is the anchored event-time staleness — wall-now minus
        the *virtual* wall time of the window's end event, where the
        capture-wide anchor (first stamp ever) maps event-time ms onto
        the wall clock. Synthetic event clocks (bench replays running
        faster or slower than real time) therefore measure honest
        pipeline staleness instead of wall-minus-epoch nonsense. Every
        later stage records ``assemble latency + wall elapsed since the
        window's first stamp`` — monotone by construction, so per-stage
        differences are real wall durations and the critical-path
        conservation receipt (segments sum ≤ commit e2e) holds per
        window. ``commit`` closes the entry. Returns the observed
        latency in ms (None while disabled)."""
        if not self.enabled:
            return None
        now_mono = time.monotonic()
        with self._lock:
            key = int(win_end_ms)
            entry = self._e2e_open.get(key)
            if entry is None:
                wall = time.time()
                if self._e2e_anchor is None:
                    self._e2e_anchor = (float(wall), float(win_end_ms))
                a_wall, a_ev = self._e2e_anchor
                virtual_wall = a_wall + (float(win_end_ms) - a_ev) / 1e3
                entry = {
                    "assemble_ms": max((wall - virtual_wall) * 1e3, 0.0),
                    "t0": now_mono,
                }
                if len(self._e2e_open) >= self.E2E_OPEN_MAX:
                    self._e2e_open.pop(min(self._e2e_open))
                    self._e2e_evicted += 1
                self._e2e_open[key] = entry
            if stage == "assemble":
                lat_ms = entry["assemble_ms"]
            else:
                lat_ms = (entry["assemble_ms"]
                          + (now_mono - entry["t0"]) * 1e3)
            self._e2e_bucket(None, stage).observe(lat_ms)
            if node is None:
                node = self.current_node()
            if node is not None:
                self._e2e_bucket(node, stage).observe(lat_ms)
            if stage == "commit":
                self._e2e_open.pop(key, None)
        return float(lat_ms)

    def _e2e_bucket(self, node: Optional[str],
                    stage: str) -> FixedBucketLatency:
        """The (node, stage) latency histogram (caller holds the lock);
        ``node=None`` is the global per-stage gauge."""
        d = (self._e2e_stages if node is None
             else self._e2e_nodes.setdefault(str(node), {}))
        b = d.get(stage)
        if b is None:
            b = d[stage] = FixedBucketLatency()
        return b

    def e2e_stage_percentiles(self, stage: str,
                              node: Optional[str] = None):
        """(p50_ms, p99_ms) for one stage's gauge — global when ``node``
        is None, the node's own otherwise; (None, None) before the first
        observation (the SLO engine's silence-fails rule handles it)."""
        with self._lock:
            d = (self._e2e_stages if node is None
                 else self._e2e_nodes.get(str(node), {}))
            lat = d.get(stage)
            if lat is None or not lat.count:
                return (None, None)
            p50 = lat.percentile(0.50)
            p99 = lat.percentile(0.99)
        return (None if p50 != p50 else float(p50),
                None if p99 != p99 else float(p99))

    def e2e_gauges(self) -> Optional[Dict[str, Any]]:
        """The snapshot ``e2e`` block (None before the first stamp —
        un-armed runs keep the v2 snapshot shape byte-compatible):
        per-stage count/sum/p50/p99 globally and per node, the capture
        anchor, and the open-entry gauge + eviction count."""
        with self._lock:
            if not self._e2e_stages and not self._e2e_nodes:
                return None

            def block(d: Dict[str, FixedBucketLatency]) -> Dict[str, Any]:
                out = {}
                for stage, lat in d.items():
                    p50 = lat.percentile(0.50)
                    p99 = lat.percentile(0.99)
                    out[stage] = {
                        "count": lat.count,
                        "sum_ms": lat.sum_ms,
                        "p50_ms": None if p50 != p50 else p50,
                        "p99_ms": None if p99 != p99 else p99,
                    }
                return out

            out: Dict[str, Any] = {"stages": block(self._e2e_stages)}
            if self._e2e_nodes:
                out["nodes"] = {n: block(d)
                                for n, d in self._e2e_nodes.items()}
            if self._e2e_anchor is not None:
                out["anchor"] = {"wall_unix": self._e2e_anchor[0],
                                 "event_ms": self._e2e_anchor[1]}
            out["open_windows"] = len(self._e2e_open)
            if self._e2e_evicted:
                out["evicted"] = self._e2e_evicted
        return json_safe(out)

    # -- flight recorder (the crash black box) ---------------------------------

    def dump_blackbox(self, reason: str) -> Optional[str]:
        """Write the flight-recorder ring beside the ledger stream as
        ``<stream>.blackbox.json`` — the last-N window summaries +
        instants plus a counter snapshot, strict JSON so a truncation-
        proof reader (``sfprof blackbox`` / ``recover``) always parses
        it. No-op without a ring (SFT_BLACKBOX=0) or a stream path (the
        dump names its stream — a black box with no flight is noise).
        Best-effort on a dying process: an OSError is swallowed, never
        raised into the crash path that triggered the dump."""
        with self._lock:
            if self._blackbox is None or self.stream_path is None:
                return None
            path = self.stream_path + ".blackbox.json"
            doc = {
                "blackbox_version": 1,
                "reason": str(reason),
                "unix": time.time(),
                "stream": self.stream_path,
                "ring": list(self._blackbox),
                "counters": {
                    "events": len(self.events),
                    "dropped_events": self.dropped_events,
                    "h2d_bytes": self.h2d_bytes,
                    "d2h_bytes": self.d2h_bytes,
                    "compiles": len(self.compile_events),
                    "late_drops": self.late_drops,
                    "fault_fires": dict(self.fault_fires),
                    "driver_retries": self.driver_retries,
                    "driver_failovers": self.driver_failovers,
                },
            }
            e2e = self.e2e_gauges()
            if e2e is not None:
                doc["e2e"] = e2e
            doc, _ = _sanitize_nonfinite(json_safe(doc))
            try:
                with open(path, "w") as f:
                    json.dump(doc, f, allow_nan=False)
                    f.write("\n")
            except OSError:
                return None
        return path

    def _blackbox_append(self, rec: Dict[str, Any]):
        """Ring append (caller holds the lock; no-op when disabled)."""
        if self._blackbox is not None:
            self._blackbox.append(rec)

    # -- fault tolerance (faults.py / driver.py) -------------------------------

    def record_fault(self, point: str, kind: str = "raise", hit: int = 0):
        """One injected fault fired. NB the telemetry↔faults cycle runs
        ONE way: this module imports ``faults`` at module scope (for the
        armed checks), so faults.py must reach telemetry only through
        its lazy per-call imports — never at import time. The instant
        event is force-flushed: a fault is exactly the record that must
        survive the crash it is about to cause."""
        if not self.enabled:
            return
        with self._lock:
            self.fault_fires[point] = self.fault_fires.get(point, 0) + 1
            self._node_bucket(self.current_node())["fault_fires"] += 1
        self.emit_instant(f"fault_fired:{point}", kind=kind, hit=int(hit))
        self.maybe_flush_stream(force=True)
        # Flight-recorder dump AFTER the force flush (the stream already
        # has the fault record) and BEFORE faults._fire's os._exit on
        # the abort kind — this call is the last code an aborting
        # process runs with its telemetry intact.
        bb = self.dump_blackbox(f"fault:{point}")
        if bb is not None:
            self.emit_instant("blackbox_dumped",
                              reason=f"fault:{point}", path=bb)
            self.maybe_flush_stream(force=True)

    def record_driver_retry(self, window_start: int, attempt: int,
                            error: str):
        """The driver retried a failed window on the same backend."""
        if not self.enabled:
            return
        with self._lock:
            self.driver_retries += 1
        self.emit_instant("driver_retry", window_start=int(window_start),
                          attempt=int(attempt), error=str(error)[:200])

    def record_driver_failover(self, window_start: int, error: str):
        """The driver switched device → fallback backend mid-stream.
        Force-flushed for the same reason as faults: the failover marker
        must survive whatever killed the device path."""
        if not self.enabled:
            return
        with self._lock:
            self.driver_failovers += 1
        self.emit_instant("failover", window_start=int(window_start),
                          to="fallback", error=str(error)[:200])
        self.maybe_flush_stream(force=True)

    # -- export ---------------------------------------------------------------

    def register_metrics(self, registry):
        """Wire the telemetry gauges into an ``mn.metrics.MetricRegistry``
        so ``snapshot()`` (and anything reading it — NES reporter lines,
        sink-owned registries) carries the new columns."""
        registry.gauge("watermark_lag_ms_max",
                       lambda: self.max_watermark_lag_ms)
        registry.gauge("late_dropped_total", lambda: self.late_drops)
        registry.gauge("telemetry_compiles_total",
                       lambda: len(self.compile_events))
        registry.gauge("h2d_bytes_total", lambda: self.h2d_bytes)
        registry.gauge("d2h_bytes_total", lambda: self.d2h_bytes)
        registry.gauge(
            "compaction_buckets_total",
            lambda: sum(len(v) for v in self._compaction.values()),
        )

    def summary(self) -> Dict[str, Any]:
        """The bench.py JSON block: strictly JSON-safe (numpy scalars →
        builtins, NaN percentiles → None so strict parsers never choke)."""
        with self._lock:
            p50 = self.window_latency.percentile(0.50)
            p95 = self.window_latency.percentile(0.95)
            lag99 = self.watermark_lag.percentile(0.99)
            out = {
                "compiles": len(self.compile_events),
                "bytes_h2d": self.h2d_bytes,
                "bytes_d2h": self.d2h_bytes,
                "window_latency_p50_ms": None if p50 != p50 else p50,
                "window_latency_p95_ms": None if p95 != p95 else p95,
                "max_watermark_lag_ms": self.max_watermark_lag_ms,
                "watermark_lag_p99_ms": None if lag99 != lag99 else lag99,
                "late_dropped": self.late_drops,
            }
        return json_safe(out)

    def snapshot(self) -> Dict[str, Any]:
        """Full JSON-safe state dump (summary + transfer/trace counts)."""
        out = self.summary()
        with self._lock:
            out.update(
                h2d_transfers=self.h2d_transfers,
                d2h_transfers=self.d2h_transfers,
                events=len(self.events),
                dropped_events=self.dropped_events,
                kernels={k: len(v) for k, v in self._shapes_seen.items()},
                compaction={
                    eng: {str(cap): dict(st) for cap, st in caps.items()}
                    for eng, caps in self._compaction.items()
                },
                # Self-healing visibility: always present so sfprof
                # health / SLO budgets can gate on zero, not on absence.
                driver={
                    "retries": self.driver_retries,
                    "failovers": self.driver_failovers,
                },
            )
            if self.fault_fires:
                out["faults"] = dict(self.fault_fires)
            if self.shed_events or self.shed_bytes:
                out["shed"] = {"events": self.shed_events,
                               "bytes": self.shed_bytes}
            if self._pipeline:
                out["pipeline"] = dict(self._pipeline)
            if self.wire_panes:
                out["wire_codec"] = {
                    "panes": self.wire_panes,
                    "raw_bytes": self.wire_raw_bytes,
                    "coded_bytes": self.wire_coded_bytes,
                    "ratio": (
                        self.wire_raw_bytes / self.wire_coded_bytes
                        if self.wire_coded_bytes else None
                    ),
                }
        if self.overload_provider is not None:
            try:
                out["overload"] = json_safe(self.overload_provider())  # sfcheck: ok=lock-discipline -- stream-flush checkpoints call this under Telemetry._lock by design; the provider contract (documented at overload.OverloadController._lock) forbids providers from taking telemetry's lock — overload queues transition emits for after release
            except Exception:  # a broken provider must not break snapshots
                pass
        if self.qserve_provider is not None:
            try:
                out["qserve"] = json_safe(self.qserve_provider())  # sfcheck: ok=lock-discipline -- same provider contract as overload_provider above: the qserve registry is lock-free host state and only re-enters this RLock on the same thread (distinct_shapes)
            except Exception:  # a broken provider must not break snapshots
                pass
        if self.dag_provider is not None:
            try:
                out["dag"] = json_safe(self.dag_provider())  # sfcheck: ok=lock-discipline -- same provider contract: the DAG's node-state dicts are driver-thread confined host state; the provider takes no locks
            except Exception:  # a broken provider must not break snapshots
                pass
        link = self.link_gauges()
        if link is not None:
            out["link_probe"] = link
        # v3 block: event-time end-to-end latency — additive, absent
        # until the first record_e2e stamp, so un-armed runs keep the
        # v2 snapshot shape byte-compatible.
        e2e = self.e2e_gauges()
        if e2e is not None:
            out["e2e"] = e2e
        # v2 blocks, both strictly additive and absent until their
        # producers run — an un-scoped, collective-free run snapshots
        # the exact v1 shape (the byte-compat contract for old readers).
        nodes = self.node_rollup()
        if nodes:
            out["nodes"] = nodes
        coll = self.collective_gauges()
        if coll is not None:
            out["collectives"] = coll
        shard_wm = self.shard_watermark_gauges()
        if shard_wm is not None:
            out["shard_watermarks"] = shard_wm
        # Ablation taint rides EVERY snapshot — including the ledger-
        # stream checkpoints, so a recovered stream stays tainted and
        # sfprof's gates keep rejecting it after a crash.
        taint = ablation.taint_block()
        if taint is not None:
            out["tainted"] = taint
        return json_safe(out)


telemetry = Telemetry()


def enable(trace_path: Optional[str] = None, recompile_warn_threshold: int = 8):
    telemetry.enable(trace_path, recompile_warn_threshold)


def disable():
    telemetry.disable()


def span(name: str, **args):
    return telemetry.span(name, **args)


def scope(node: Optional[str]):
    return telemetry.scope(node)


def fetch(x):
    return telemetry.fetch(x)


def write_ledger(path: str, bench: Optional[dict] = None, mesh=None,
                 capture_costs: bool = True) -> str:
    return telemetry.write_ledger(path, bench=bench, mesh=mesh,
                                  capture_costs=capture_costs)


class LinkProbe:
    """Tunnel/link-health probe: a tiny FIXED-SHAPE device round trip
    measuring host↔device latency (8-float RTT) and bandwidth (one fixed
    payload, default 256 KiB, shipped out and fetched back).

    True sync is the ``jax.device_get`` — ``block_until_ready`` is a
    NO-OP over the axon tunnel (CLAUDE.md), so the fetch IS the
    measurement. The two transfer directions cannot be timed separately
    over the tunnel (there is no honest put-only sync), so bandwidth is
    reported as the ROUND-TRIP aggregate: ``2·payload/elapsed``.

    Call ``sample()`` only at phase boundaries — never inside a window
    span — so probe traffic lands in host gaps, not in measured windows.
    Samples feed the rolling gauges in ``telemetry`` (snapshot's
    ``link_probe`` block); bench.py stamps them into its record, and
    ``sfprof diff`` annotates its verdicts with the link ratio so "chip
    slow" is distinguishable from "tunnel degraded"."""

    def __init__(self, device=None, payload_bytes: int = 262_144,
                 tel: Optional[Telemetry] = None):
        import numpy as np

        self.device = device
        self.payload_bytes = int(payload_bytes)
        self._tel = tel
        # Fixed shapes, allocated once: the probe must never cause an
        # XLA compile (device_put/get are pure transfers) nor shape churn.
        self._tiny = np.zeros(8, np.float32)
        self._payload = np.zeros(max(self.payload_bytes // 4, 1),
                                 np.float32)

    def sample(self) -> Dict[str, float]:
        """One probe round trip; records into the telemetry gauges (when
        enabled) and returns the raw sample."""
        import jax

        t0 = time.perf_counter()
        jax.device_get(jax.device_put(self._tiny, self.device))
        latency_ms = (time.perf_counter() - t0) * 1e3
        t1 = time.perf_counter()
        got = jax.device_get(jax.device_put(self._payload, self.device))
        dt = max(time.perf_counter() - t1, 1e-9)
        roundtrip_mbps = 2.0 * float(got.nbytes) / dt / 1e6
        tel = self._tel if self._tel is not None else telemetry
        tel.record_link_sample(latency_ms, roundtrip_mbps,
                               int(got.nbytes))
        return {
            "latency_ms": float(latency_ms),
            "roundtrip_mbps": float(roundtrip_mbps),
            "payload_bytes": int(got.nbytes),
        }


def _abstract_leaf(a):
    """ShapeDtypeStruct mirror of one call argument for DEFERRED AOT
    lowering: arrays become avals (no reference to the device buffer is
    retained — keeping donated inputs alive would defeat
    ``donate_argnums``), tuple/list/NamedTuple/dict containers recurse,
    static scalars/strings keep their value (it keys the compile cache),
    and any other leaf type raises — an object we can't prove
    buffer-free must not be pinned in the stats table."""
    shape = getattr(a, "shape", None)
    dtype = getattr(a, "dtype", None)
    if shape is not None and dtype is not None:
        import jax

        return jax.ShapeDtypeStruct(tuple(shape), dtype)
    if isinstance(a, (tuple, list)):
        parts = [_abstract_leaf(x) for x in a]
        if hasattr(a, "_fields"):  # NamedTuple carries (pane scans, …)
            return type(a)(*parts)  # positional ctor, not an iterable
        return type(a)(parts)
    if isinstance(a, dict):
        return {k: _abstract_leaf(v) for k, v in a.items()}
    if a is None or isinstance(
            a, (bool, int, float, complex, str, bytes, type)):
        return a  # static scalar: the value keys the compile cache
    # Anything else (custom pytree, exotic object) could hide a device
    # buffer — refuse rather than pin it in _kernel_stats (the caller
    # records cost as unavailable instead).
    raise TypeError(
        f"unsupported leaf for deferred lowering: {type(a).__name__}"
    )


def _lower_ctx(fn, args, kwargs):
    """(fn, abstract args, abstract kwargs) for a later host-side
    ``fn.lower(...)`` — or None when ``fn`` has no AOT surface (e.g. a
    plain callable wrapped for signature tracking only)."""
    if not hasattr(fn, "lower"):
        return None
    try:
        return (
            fn,
            tuple(_abstract_leaf(a) for a in args),
            {k: _abstract_leaf(v) for k, v in kwargs.items()},
        )
    except Exception:  # exotic arg types: skip cost capture, pin nothing
        return None


def _analyze_cost(fn, args, kwargs) -> Dict[str, Any]:
    """Host-side XLA cost + memory analysis of one (kernel, signature).

    AOT lower/compile from avals: nothing executes, nothing crosses the
    device boundary. Failures come back as ``{"error": ...}`` so one
    unlowerable program never blocks the ledger."""
    try:
        compiled = fn.lower(*args, **kwargs).compile()
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}
    out: Dict[str, Any] = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):  # older jax: one dict per device
            ca = ca[0] if ca else {}
        for src, dst in (("flops", "flops"),
                         ("bytes accessed", "bytes_accessed"),
                         ("transcendentals", "transcendentals")):
            if ca and src in ca:
                out[dst] = float(ca[src])
    except Exception:  # pragma: no cover - backend without cost analysis
        pass
    try:
        mem = compiled.memory_analysis()
        for attr, dst in (("temp_size_in_bytes", "temp_bytes"),
                          ("argument_size_in_bytes", "argument_bytes"),
                          ("output_size_in_bytes", "output_bytes"),
                          ("generated_code_size_in_bytes", "code_bytes")):
            v = getattr(mem, attr, None)
            if v is not None:
                out[dst] = int(v)
        if "temp_bytes" in out:
            # Peak working set of one dispatch: arguments + outputs +
            # XLA temp buffers (the quantity that overflows HBM).
            out["peak_memory_bytes"] = (out["temp_bytes"]
                                        + out.get("argument_bytes", 0)
                                        + out.get("output_bytes", 0))
    except Exception:  # pragma: no cover - backend without memory stats
        pass
    return out or {"error": "cost analysis unavailable on this backend"}


def instrument_jit(fn, name: Optional[str] = None):
    """Wrap a compiled callable with recompile-signature tracking and the
    per-(kernel, signature) runtime table.

    ``operators/base.py:jitted`` routes every operator kernel through this;
    bench.py wraps its hand-jitted steps the same way. Disabled-path cost:
    one attribute check per call (calls here are per WINDOW, never per
    record). Enabled, each call adds two clock reads and a locked table
    update; a NEW signature additionally stashes ShapeDtypeStruct avals
    so ``telemetry.capture_costs()`` can lower/compile host-side later —
    nothing device-facing happens on the call path. Attributes of the
    underlying jit object (``lower``, …) pass through.

    This is also the ``device.dispatch`` chaos injection point
    (faults.py): it lives HERE — not in ``jitted`` — so the mesh window
    programs and bench steps that skip ``jitted`` are injectable too.
    """
    label = name or getattr(fn, "__name__", repr(fn))

    class _Instrumented:
        __slots__ = ()

        def __call__(self, *args, **kwargs):
            if faults.armed:  # chaos injection point (faults.py)
                faults.hit("device.dispatch")
            if ablation.armed and ablation.matches(label):
                # Profiling-only substitution (ablation.py): cached
                # correct-aval zeros after one real learning call.
                # Deliberately OUTSIDE the runtime table — the numbers
                # are wrong by construction and the capture is tainted.
                return ablation.dispatch(label, fn, args, kwargs)
            if not telemetry.enabled:
                return fn(*args, **kwargs)
            sig = abstract_signature(args, kwargs)
            is_new = telemetry.record_jit_call(label, sig)
            t0 = time.perf_counter_ns()
            out = fn(*args, **kwargs)
            dur_ns = time.perf_counter_ns() - t0
            telemetry.record_kernel_time(
                label, sig, dur_ns,
                lower_ctx=_lower_ctx(fn, args, kwargs) if is_new else None,
            )
            return out

        def __getattr__(self, attr):
            return getattr(fn, attr)

    wrapped = _Instrumented()
    return wrapped


def load_trace(path: str) -> Dict[str, Any]:
    """Read a JSON-lines trace file into the standard Chrome-trace document
    ``{"traceEvents": [...]}`` (loadable by chrome://tracing / Perfetto)."""
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                events.append(json.loads(line))
    return {"traceEvents": events}
