"""Pipelined ingest runtime — the async ship/compute/fetch executor.

ROADMAP item 1's overlap half, promoted from bench.py's ad-hoc slide
double-buffering into a real runtime subsystem: a bounded-depth
pipeline that keeps the tunnel and the chip busy at the same time by
overlapping

- **ship(N+1)** — encode (ops/wire_codec.py, when armed) + stage the
  next pane's host→device transfer (``device_put``/``jnp.asarray`` are
  async: the DMA rides the tunnel while the host moves on),
- **compute(N)** — dispatch the current window's program (async too —
  XLA queues it behind the transfer), and
- **fetch(N−1)** — the lagged, ORDERED device→host result sync
  (``jax.device_get`` — the only true synchronization on the axon
  tunnel, CLAUDE.md), so a fetch drains windows the device already
  finished instead of stalling the stream per window.

Ordering and results are bit-identical to the synchronous path: the
same programs run in the same order, only the host's sync points move
(tests/test_pipeline.py pins byte-identical egress). Donation stays
safe by construction: a shipped buffer is handed to exactly one compute
and the executor drops its reference immediately (no use-after-donate;
sfcheck's donation-safety pass guards the lifecycle), and carry-donating
steps chain ``x = step(x)`` — the sanctioned form.

**Opt-in** via ``SFT_PIPELINE`` (inline JSON or a path, read once at
import like ``SFT_FAULT_PLAN``; ``"1"``/``"on"`` = defaults) or
:func:`install` in-process. Default-off runs take the exact synchronous
code paths of PR 10 and earlier.

**Failure containment**: ``pipeline.ship`` / ``pipeline.fetch`` are
registered fault-injection points (faults.py) with chaos-matrix
kill/resume legs; consumers publish their checkpoint carry only when a
window's result is actually yielded, so a kill mid-overlap replays the
in-flight windows instead of losing them. When the overload circuit
breaker (overload.py) reports the device path open — tunnel dead or
degraded — the executor COLLAPSES to the synchronous cadence (depth 1,
no fetch lag; ``pipeline_collapsed``/``pipeline_resumed`` instant
events, force-flushed) and re-opens when the breaker closes.
"""

from __future__ import annotations

import contextlib
import json
import os
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Iterable, Iterator, List, Optional

from spatialflink_tpu.faults import faults
from spatialflink_tpu.telemetry import telemetry

_POLICY_KEYS = {"depth", "fetch_lag", "codec", "codec_strategy"}

CODECS = ("off", "delta")


@dataclass(frozen=True)
class PipelinePolicy:
    """Declarative pipeline configuration (strict parse — unknown keys
    raise, the fault-plan rule: a typo'd knob that silently does nothing
    is worse than none).

    - ``depth``: panes shipped but not yet computed, INCLUDING the one
      about to compute — depth d keeps d−1 panes staged beyond the
      in-flight item (≥1; 1 = no ship-ahead);
    - ``fetch_lag``: computed windows left in flight before the oldest
      is fetched (0 = fetch every window immediately — the synchronous
      cadence with the executor's bookkeeping);
    - ``codec``: ``"delta"`` arms the delta-bitpacked wire-pane codec
      (ops/wire_codec.py) on paths that ship wire panes; ``"off"``
      ships raw planes;
    - ``codec_strategy``: decode extraction impl (``auto``/``jnp``/
      ``pallas`` — the ops/wire_knn.py self-check contract).
    """

    depth: int = 2
    fetch_lag: int = 2
    codec: str = "off"
    codec_strategy: str = "auto"

    def __post_init__(self):
        if int(self.depth) < 1:
            raise ValueError(f"depth must be >= 1, got {self.depth}")
        if int(self.fetch_lag) < 0:
            raise ValueError(
                f"fetch_lag must be >= 0, got {self.fetch_lag}"
            )
        if self.codec not in CODECS:
            raise ValueError(
                f"unknown codec {self.codec!r} (codecs: {CODECS})"
            )
        if self.codec_strategy not in ("auto", "jnp", "pallas"):
            raise ValueError(
                f"codec_strategy must be auto|jnp|pallas, got "
                f"{self.codec_strategy!r}"
            )

    @classmethod
    def from_dict(cls, d: dict) -> "PipelinePolicy":
        if not isinstance(d, dict):
            raise ValueError(
                f"pipeline policy must be an object, got "
                f"{type(d).__name__}"
            )
        unknown = sorted(set(d) - _POLICY_KEYS)
        if unknown:
            raise ValueError(f"pipeline policy has unknown keys {unknown}")
        return cls(**d)

    @classmethod
    def from_env(cls, spec: str) -> "PipelinePolicy":
        """``SFT_PIPELINE`` forms: ``1``/``on``/``true`` (defaults),
        inline JSON object, or a path to a JSON file."""
        text = spec.strip()
        if text.lower() in ("1", "on", "true", "yes"):
            return cls()
        if not text.startswith("{"):
            with open(text) as f:
                text = f.read()
        return cls.from_dict(json.loads(text))

    def to_dict(self) -> dict:
        return {
            "depth": int(self.depth), "fetch_lag": int(self.fetch_lag),
            "codec": self.codec, "codec_strategy": self.codec_strategy,
        }


# ---------------------------------------------------------------------------
# Module policy slot (the overload.py install idiom; no __main__ here)


_policy: Optional[PipelinePolicy] = None


def install(policy: PipelinePolicy) -> PipelinePolicy:
    """Make ``policy`` the process-global pipeline policy: the pane
    engines and the dataflow driver consult :func:`policy` when no
    explicit one is passed."""
    global _policy
    _policy = policy
    return policy


def uninstall():
    global _policy
    _policy = None


def policy() -> Optional[PipelinePolicy]:
    return _policy


def arm_from_env() -> bool:
    """Arm from ``SFT_PIPELINE``; no-op when unset. Called once at
    import so pipelined chaos subprocesses arm with zero code."""
    spec = os.environ.get("SFT_PIPELINE")
    if not spec:
        return False
    install(PipelinePolicy.from_env(spec))
    return True


# ---------------------------------------------------------------------------
# The executor


def breaker_collapsed() -> bool:
    """True while the overload circuit breaker holds the device path
    open — the pipeline must not stack windows onto a dead tunnel."""
    from spatialflink_tpu import overload

    ctrl = overload.controller()
    if ctrl is None or ctrl.breaker is None:
        return False
    return ctrl.breaker.state == "open"


class PipelinedExecutor:
    """Generic bounded overlap over an item stream.

    Stage contracts (all host callables):

    - ``ship(item) -> staged``: encode + begin the async host→device
      transfer; may return ``None`` for items with nothing to ship
      (trailing flush panes). The executor passes ``staged`` to exactly
      ONE compute call and drops its reference — hand the buffer to a
      donating kernel freely.
    - ``compute(item, staged) -> work | None``: dispatch the window
      program; ``None`` = no window fired (gap pane). Must not sync.
    - ``fetch(works: list) -> iterable``: the ONE true-sync point —
      materialize the listed windows' results IN ORDER and return the
      values to yield. Mid-stream the list has one element; the final
      drain passes everything still in flight so the whole tail costs
      one tunnel round trip (the flush_pending idiom).

    ``spans=True`` wraps each processed item in a ``window.pipeline``
    span with ``ship``/``compute``/``fetch`` children, so the overlap
    shows up in sfprof attribution as vanishing inter-window host gap —
    ingest rides INSIDE window spans instead of the dead time between
    them.
    """

    def __init__(self, pol: PipelinePolicy, *,
                 ship: Callable[[Any], Any],
                 compute: Callable[[Any, Any], Any],
                 fetch: Callable[[List[Any]], Iterable],
                 label: str = "pipeline",
                 spans: bool = False,
                 node: Optional[str] = None,
                 e2e_end: Optional[Callable[[Any], Any]] = None):
        self.pol = pol
        self._ship_fn = ship
        self._compute_fn = compute
        self._fetch_fn = fetch
        self.label = label
        self.spans = spans
        #: Latency-lineage hook: extracts an item's event-time window
        #: end (ms) — when set, each stage boundary feeds its own
        #: telemetry ``record_e2e`` bucket (ship/compute/fetch; the
        #: driver stamps assemble/commit around its executor). None =
        #: items are not windows (segmented scans) — no stamps.
        self._e2e_end = e2e_end
        #: Node-attribution tag for the per-item work (None inherits the
        #: caller's ambient scope — the executor runs on its thread, so
        #: a driver/DAG scope already propagates; set it for standalone
        #: pane engines with no driver above them).
        self.node = node
        self.collapsed = False

    # -- stages (fault points live here) ---------------------------------------

    def _ship(self, item):
        if faults.armed:  # chaos injection point (faults.py)
            faults.hit("pipeline.ship")
        return self._ship_fn(item)

    def _fetch(self, works: List[Any]) -> Iterable:
        if faults.armed:  # chaos injection point (faults.py)
            faults.hit("pipeline.fetch")
        return self._fetch_fn(works)

    def _stamp_e2e(self, item, stage):
        """Latency-lineage stage stamp when an ``e2e_end`` extractor is
        wired; returns the item's event-time end so the fetch stage can
        stamp without re-extracting."""
        if self._e2e_end is None or not telemetry.enabled:
            return None
        end = self._e2e_end(item)
        if end is not None:
            telemetry.record_e2e(end, stage)
        return end

    def _sync_collapse_state(self):
        want = breaker_collapsed()
        if want == self.collapsed:
            return
        self.collapsed = want
        if telemetry.enabled:
            # Literal event-name heads per branch — the contract-twin
            # pass statically diffs emit names against the sfprof
            # consumer registry (the slo.py transition idiom).
            if want:
                telemetry.record_pipeline(collapses=1)
                telemetry.emit_instant("pipeline_collapsed",
                                       label=self.label)
            else:
                telemetry.record_pipeline(resumes=1)
                telemetry.emit_instant("pipeline_resumed",
                                       label=self.label)
            telemetry.maybe_flush_stream(force=True)

    # -- the loop --------------------------------------------------------------

    def run(self, items: Iterable) -> Iterator:
        """Drive ``items`` through the three stages; yield fetch results
        in item order. The in-flight window count never exceeds
        ``fetch_lag`` and the ship-ahead never exceeds ``depth``; while
        the circuit is open both clamp to the synchronous cadence."""
        shipped: deque = deque()
        inflight: deque = deque()
        ends: deque = deque()  # event-time ends aligned with inflight
        it = iter(items)
        exhausted = False

        def refill(depth: int):
            nonlocal exhausted
            while not exhausted and len(shipped) < depth:
                try:
                    item = next(it)
                except StopIteration:
                    exhausted = True
                    break
                shipped.append((item, self._ship(item)))
                self._stamp_e2e(item, "ship")

        def maybe_span(name: str):
            return (telemetry.span(name) if self.spans
                    else contextlib.nullcontext())

        self._sync_collapse_state()
        # Prime the ship-ahead once, outside any window span (the
        # warm-up transfer); each iteration afterwards tops it up by
        # one INSIDE its window span — ingest rides the window, not
        # the gap between windows.
        refill(1 if self.collapsed else max(1, int(self.pol.depth)))
        while True:
            if not shipped:
                refill(1)  # depth-1 cadence: probe for the next item
                if not shipped:
                    break
            depth = 1 if self.collapsed else max(1, int(self.pol.depth))
            lag = 0 if self.collapsed else max(0, int(self.pol.fetch_lag))
            out: list = []
            # Scope covers the item's work only, never a yield — a
            # suspended generator must not leak its tag to the consumer.
            with telemetry.scope(self.node), \
                    maybe_span(f"window.{self.label}"):
                with maybe_span("ship"):
                    refill(depth)
                item, staged = shipped.popleft()
                with maybe_span("compute"):
                    work = self._compute_fn(item, staged)
                del staged  # the one compute owns (and may donate) it
                if work is not None:
                    inflight.append(work)
                    ends.append(self._stamp_e2e(item, "compute"))
                    if telemetry.enabled:
                        telemetry.record_pipeline(
                            windows=1,
                            **({"sync": 1} if self.collapsed
                               else {"overlapped": 1}),
                        )
                while len(inflight) > lag:
                    with maybe_span("fetch"):
                        out.extend(self._fetch([inflight.popleft()]))
                    end = ends.popleft()
                    if end is not None and telemetry.enabled:
                        telemetry.record_e2e(end, "fetch")
            yield from out
            self._sync_collapse_state()
        if inflight:  # final drain: ONE true sync for the whole tail
            with telemetry.scope(self.node):
                tail = list(self._fetch(list(inflight)))
                if telemetry.enabled:
                    for end in ends:
                        if end is not None:
                            telemetry.record_e2e(end, "fetch")
            yield from tail
            inflight.clear()
            ends.clear()


# Subprocess arming: a pipelined chaos child only needs SFT_PIPELINE in
# its env (the faults.py idiom).
arm_from_env()
