"""Bucketed padding — static shapes for XLA.

Window point-counts vary wildly between firings; recompiling the query
program per window size would dominate runtime. All batches are padded to
the next bucket size (powers of two above a floor), so the whole stream
reuses a handful of compiled programs.
"""

from __future__ import annotations

import numpy as np

_MIN_BUCKET = 256


def next_bucket(n: int, minimum: int = _MIN_BUCKET) -> int:
    """Smallest power-of-two bucket >= max(n, 1), floored at ``minimum``."""
    b = minimum
    while b < n:
        b <<= 1
    return b


def pad_to_bucket(arr: np.ndarray, bucket: int, fill=0) -> np.ndarray:
    """Pad axis 0 of ``arr`` to ``bucket`` with ``fill``."""
    n = arr.shape[0]
    if n == bucket:
        return arr
    if n > bucket:
        raise ValueError(f"array length {n} exceeds bucket {bucket}")
    pad_shape = (bucket - n,) + arr.shape[1:]
    return np.concatenate([arr, np.full(pad_shape, fill, dtype=arr.dtype)], axis=0)
