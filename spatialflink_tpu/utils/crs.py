"""Coordinate reference system transforms — EPSG:4326 ⇄ EPSG:25831.

The reference uses proj4j (``sncb/common/CRSUtils.java:19-56``) to project
WGS84 lon/lat into ETRS89 / UTM zone 31N meters. No proj library is
available here, so the transverse-Mercator projection is implemented
directly with the Krüger n-series (6th order), which agrees with proj to
sub-millimeter over the UTM validity range — far inside the sub-meter
parity the SNCB queries need. Pure ``numpy``/``jax.numpy`` (dtype- and
backend-polymorphic): the forward transform runs vectorized on TPU as part
of ingest enrichment.

EPSG:25831: ETRS89 on GRS80, central meridian 3°E, k0 = 0.9996,
false easting 500 000 m. ETRS89≈WGS84 (no datum shift, like proj4j).
"""

from __future__ import annotations

import numpy as np

# GRS80 ellipsoid (ETRS89; WGS84 differs by <0.1 mm in flattening).
_A = 6378137.0
_F = 1.0 / 298.257222101
_N = _F / (2.0 - _F)
_E = np.sqrt(_F * (2.0 - _F))  # first eccentricity

# Rectifying radius A and Krüger series coefficients to n^6
# (standard Karney 2011 series).
_n = _N
_RECT_A = _A / (1 + _n) * (1 + _n**2 / 4 + _n**4 / 64 + _n**6 / 256)
_ALPHA = (
    _n / 2 - 2 * _n**2 / 3 + 5 * _n**3 / 16 + 41 * _n**4 / 180
    - 127 * _n**5 / 288 + 7891 * _n**6 / 37800,
    13 * _n**2 / 48 - 3 * _n**3 / 5 + 557 * _n**4 / 1440 + 281 * _n**5 / 630
    - 1983433 * _n**6 / 1935360,
    61 * _n**3 / 240 - 103 * _n**4 / 140 + 15061 * _n**5 / 26880
    + 167603 * _n**6 / 181440,
    49561 * _n**4 / 161280 - 179 * _n**5 / 168 + 6601661 * _n**6 / 7257600,
    34729 * _n**5 / 80640 - 3418889 * _n**6 / 1995840,
    212378941 * _n**6 / 319334400,
)
_BETA = (
    _n / 2 - 2 * _n**2 / 3 + 37 * _n**3 / 96 - _n**4 / 360 - 81 * _n**5 / 512
    + 96199 * _n**6 / 604800,
    _n**2 / 48 + _n**3 / 15 - 437 * _n**4 / 1440 + 46 * _n**5 / 105
    - 1118711 * _n**6 / 3870720,
    17 * _n**3 / 480 - 37 * _n**4 / 840 - 209 * _n**5 / 4480
    + 5569 * _n**6 / 90720,
    4397 * _n**4 / 161280 - 11 * _n**5 / 504 - 830251 * _n**6 / 7257600,
    4583 * _n**5 / 161280 - 108847 * _n**6 / 3991680,
    20648693 * _n**6 / 638668800,
)

K0 = 0.9996
FALSE_EASTING = 500_000.0


def utm_forward(lon_deg, lat_deg, lon0_deg: float = 3.0, xp=np):
    """WGS84/ETRS89 lon, lat (degrees) → (easting, northing) meters.

    ``xp`` selects the array backend (numpy by default, pass ``jax.numpy``
    to trace it on device). Default lon0 = 3°E is UTM zone 31N (EPSG:25831).
    """
    lat = xp.deg2rad(lat_deg)
    lam = xp.deg2rad(lon_deg - lon0_deg)
    s = xp.sin(lat)
    # Conformal latitude.
    t = xp.sinh(xp.arctanh(s) - _E * xp.arctanh(_E * s))
    xi_p = xp.arctan2(t, xp.cos(lam))
    eta_p = xp.arcsinh(xp.sin(lam) / xp.sqrt(t * t + xp.cos(lam) ** 2))
    xi = xi_p
    eta = eta_p
    for j, a in enumerate(_ALPHA, start=1):
        xi = xi + a * xp.sin(2 * j * xi_p) * xp.cosh(2 * j * eta_p)
        eta = eta + a * xp.cos(2 * j * xi_p) * xp.sinh(2 * j * eta_p)
    easting = FALSE_EASTING + K0 * _RECT_A * eta
    northing = K0 * _RECT_A * xi
    return easting, northing


def utm_inverse(easting, northing, lon0_deg: float = 3.0, xp=np):
    """(easting, northing) meters → WGS84/ETRS89 lon, lat degrees."""
    xi = northing / (K0 * _RECT_A)
    eta = (easting - FALSE_EASTING) / (K0 * _RECT_A)
    xi_p = xi
    eta_p = eta
    for j, b in enumerate(_BETA, start=1):
        xi_p = xi_p - b * xp.sin(2 * j * xi) * xp.cosh(2 * j * eta)
        eta_p = eta_p - b * xp.cos(2 * j * xi) * xp.sinh(2 * j * eta)
    chi = xp.arcsin(xp.sin(xi_p) / xp.cosh(eta_p))  # conformal latitude
    lam = xp.arctan2(xp.sinh(eta_p), xp.cos(xi_p))
    # Conformal → geodetic latitude by fixed-point on sin(lat):
    # artanh(sin lat) = artanh(sin chi) + e·artanh(e·sin lat).
    psi0 = xp.arctanh(xp.sin(chi))
    s = xp.sin(chi)
    for _ in range(6):
        s = xp.tanh(psi0 + _E * xp.arctanh(_E * s))
    lat = xp.arcsin(xp.clip(s, -1.0, 1.0))
    return xp.rad2deg(lam) + lon0_deg, xp.rad2deg(lat)


def wgs84_to_epsg25831(lon_deg, lat_deg, xp=np):
    """The CRSUtils.toMetric transform (CRSUtils.java:40-46)."""
    return utm_forward(lon_deg, lat_deg, lon0_deg=3.0, xp=xp)


def epsg25831_to_wgs84(easting, northing, xp=np):
    return utm_inverse(easting, northing, lon0_deg=3.0, xp=xp)
