from spatialflink_tpu.utils.interning import Interner  # noqa: F401
from spatialflink_tpu.utils.padding import pad_to_bucket, next_bucket  # noqa: F401
