"""Host-side string→dense-int interning for object IDs.

The reference keys everything on string objIDs (SpatialObject.java:27-33)
and dedups via HashMaps/HashSets inside window functions
(KNNQuery.java:221-268). TPU segment reductions need dense int32 segment
ids, so object IDs are interned once at ingest and decoded at egress.
"""

from __future__ import annotations

from typing import Hashable, Iterable, List

import numpy as np


class Interner:
    """Bidirectional Hashable↔int32 mapping, append-only."""

    def __init__(self):
        self._to_int: dict = {}
        self._to_key: List[Hashable] = []

    def __len__(self) -> int:
        return len(self._to_key)

    def intern(self, key: Hashable) -> int:
        i = self._to_int.get(key)
        if i is None:
            i = len(self._to_key)
            self._to_int[key] = i
            self._to_key.append(key)
        return i

    def intern_many(self, keys: Iterable[Hashable]) -> np.ndarray:
        return np.fromiter(
            (self.intern(k) for k in keys), dtype=np.int32, count=-1
        )

    def lookup(self, i: int) -> Hashable:
        return self._to_key[i]

    def decode(self, ids: Iterable[int]) -> List[Hashable]:
        return [self._to_key[i] for i in ids]

    @property
    def num_segments(self) -> int:
        return len(self._to_key)
