"""shard_map import shim across the jax 0.4→0.8 API moves.

Two things moved under us: the symbol's home
(``jax.experimental.shard_map`` → ``jax.shard_map``) and the
replication-check kwarg's name (``check_rep`` → ``check_vma``). Callers
here write the NEW spelling (``check_vma``); on an older jax the shim
forwards it as ``check_rep`` so one codebase runs on both.
"""

from __future__ import annotations

import inspect

try:  # jax >= 0.8
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

if "check_vma" in inspect.signature(_shard_map).parameters:
    shard_map = _shard_map
else:
    def shard_map(*args, check_vma=None, **kwargs):
        if check_vma is not None:
            kwargs["check_rep"] = check_vma
        return _shard_map(*args, **kwargs)
