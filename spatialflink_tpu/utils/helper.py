"""Misc helpers mirroring ``GeoFlink/utils/HelperClass.java`` leftovers."""

from __future__ import annotations

from typing import List, Set

import numpy as np

from spatialflink_tpu.grid import UniformGrid
from spatialflink_tpu.models.objects import Polygon


def generate_query_polygons(
    num: int,
    min_x: float,
    min_y: float,
    max_x: float,
    max_y: float,
    grid_size: int = 100,
    seed: int = 0,
) -> List[Polygon]:
    """Random small rectangular query polygons inside a bbox
    (HelperClass.generateQueryPolygons, HelperClass.java:387-439: polygon
    side = bbox span / grid_size, uniformly placed)."""
    rng = np.random.default_rng(seed)
    len_x = (max_x - min_x) / grid_size
    len_y = (max_y - min_y) / grid_size
    out = []
    for i in range(num):
        x0 = rng.uniform(min_x, max_x - len_x)
        y0 = rng.uniform(min_y, max_y - len_y)
        ring = np.array(
            [[x0, y0], [x0 + len_x, y0], [x0 + len_x, y0 + len_y],
             [x0, y0 + len_y], [x0, y0]]
        )
        out.append(Polygon(obj_id=f"qpoly{i}", rings=[ring]))
    return out


def pad_leading_zeroes(value: int, width: int = 5) -> str:
    """HelperClass.padLeadingZeroesToInt."""
    return f"{value:0{width}d}"


def cells_of_polygon_set(grid: UniformGrid, polygons) -> Set[int]:
    cells: Set[int] = set()
    for p in polygons:
        cells.update(p.grid_cells(grid))
    return cells
