"""ctypes bindings for the native ingest runtime (``native/sfnative.cpp``).

``NativeGpsParser`` parses whole CSV buffers into the SoA arrays the batch
kernels consume, with persistent device-id interning. Falls back to the
pure-Python serde if the shared library isn't built; ``ensure_built()``
compiles it on demand with the in-image toolchain (g++, no pybind11 —
plain C ABI via ctypes).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Dict, List, Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libsfnative.so")

_lib: Optional[ctypes.CDLL] = None
_build_failed = False
_abi_mismatch = False
_ABI_VERSION = 4  # must match sf_abi_version() in sfnative.cpp


def ensure_built(quiet: bool = True) -> bool:
    """(Re)build the shared library. Returns availability.

    Always invokes make (an incremental no-op when up to date): merely
    checking for the .so would leave a STALE prebuilt library fatal when
    _load() looks up a newly added symbol (AttributeError instead of the
    documented graceful fallback)."""
    global _build_failed
    if _build_failed:
        return False
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR],
            check=True,
            capture_output=quiet,
        )
        return os.path.exists(_LIB_PATH)
    except (subprocess.CalledProcessError, FileNotFoundError):
        _build_failed = True
        return os.path.exists(_LIB_PATH)


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _abi_mismatch
    if _lib is not None:
        return _lib
    if _abi_mismatch:
        return None
    if not ensure_built():
        return None
    lib = ctypes.CDLL(_LIB_PATH)
    # ABI guard: a stale prebuilt .so with the right symbols but an older
    # signature would corrupt memory through mismatched argtypes.
    try:
        lib.sf_abi_version.restype = ctypes.c_int32
        abi = int(lib.sf_abi_version())
    except AttributeError:
        abi = -1
    if abi != _ABI_VERSION:
        # A rebuilt-from-this-tree .so can't fix itself mid-process; cache
        # the rejection so available() stops paying make+CDLL per call.
        _abi_mismatch = True
        return None
    lib.sf_interner_new.restype = ctypes.c_void_p
    lib.sf_interner_free.argtypes = [ctypes.c_void_p]
    lib.sf_interner_size.argtypes = [ctypes.c_void_p]
    lib.sf_interner_size.restype = ctypes.c_int32
    lib.sf_interner_get.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_char_p, ctypes.c_int64,
    ]
    lib.sf_interner_get.restype = ctypes.c_int64
    dbl_p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
    i64_p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    i32_p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    lib.sf_parse_gps_csv.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_char,
        ctypes.c_int64, i64_p, dbl_p, dbl_p, dbl_p, dbl_p, dbl_p, i32_p,
    ]
    lib.sf_parse_gps_csv.restype = ctypes.c_int64
    lib.sf_parse_points_csv.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_char,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int64, i64_p, dbl_p, dbl_p, i32_p,
    ]
    lib.sf_parse_points_csv.restype = ctypes.c_int64
    u8_p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
    lib.sf_parse_wkt_geoms.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_char,
        ctypes.c_int64, ctypes.c_int64, i64_p, i32_p, i64_p, u8_p, dbl_p,
        u8_p,
        np.ctypeslib.ndpointer(np.int64, shape=(1,), flags="C_CONTIGUOUS"),
    ]
    lib.sf_parse_wkt_geoms.restype = ctypes.c_int64
    lib.sf_traj_stats.argtypes = [
        i64_p, dbl_p, dbl_p, i32_p, ctypes.c_int64, ctypes.c_int32,
        ctypes.c_int64, ctypes.c_int64, dbl_p, i64_p, i64_p,
    ]
    lib.sf_traj_stats.restype = ctypes.c_int64
    lib.sf_tjoin_panes.argtypes = [
        i32_p, dbl_p, dbl_p, i32_p, i32_p, ctypes.c_int64,
        i32_p, dbl_p, dbl_p, i32_p, i32_p, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_double, dbl_p,
    ]
    lib.sf_tjoin_panes.restype = ctypes.c_int64
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def traj_stats_native(ts, x, y, oid, num_oids: int, size_ms: int,
                      slide_ms: int):
    """Single-pass pane-decomposed sliding trajectory stats
    (sf_traj_stats) — the native engine behind
    streams/panes.py:traj_stats_sliding. ``ts`` must be ascending.
    Returns (n_starts, spatial, temporal, count) as full
    (n_starts, num_oids) matrices, or None when the library is
    unavailable. Bit-identical to the numpy path (same float association
    order; tests/test_native.py)."""
    lib = _load()
    if lib is None:
        return None
    ts = np.ascontiguousarray(ts, np.int64)
    x = np.ascontiguousarray(x, np.float64)
    y = np.ascontiguousarray(y, np.float64)
    oid32 = np.ascontiguousarray(oid, np.int32)
    n = len(ts)
    ppw = size_ms // slide_ms
    if n == 0:
        return 0, *(np.zeros((0, num_oids), d)
                    for d in (np.float64, np.int64, np.int64))
    p_lo = int(np.floor_divide(int(ts[0]), slide_ms))
    p_hi = int(np.floor_divide(int(ts[-1]), slide_ms))
    n_starts = (p_hi - p_lo + 1) + ppw - 1
    spatial = np.empty((n_starts, num_oids), np.float64)
    temporal = np.empty((n_starts, num_oids), np.int64)
    count = np.empty((n_starts, num_oids), np.int64)
    rc = lib.sf_traj_stats(
        ts, x, y, oid32, n, num_oids, size_ms, slide_ms,
        spatial.reshape(-1), temporal.reshape(-1), count.reshape(-1),
    )
    if rc < 0:
        raise ValueError(f"oid out of [0, {num_oids}) in traj_stats_native")
    assert rc == n_starts
    return n_starts, spatial, temporal, count


def tjoin_panes_native(l_pane, l_x, l_y, l_cell, l_oid,
                       r_pane, r_x, r_y, r_cell, r_oid,
                       n_slides: int, grid_n: int, layers: int, ppw: int,
                       num_ids: int, radius: float):
    """Pane-carry tJoin (sf_tjoin_panes) — the native CPU engine behind
    TJoinQuery.run_soa_panes(backend='native'). Events must be sorted by
    pane index (rebased to 0) and in-grid. EXACT by construction (no
    capW/pair_sel budgets); returns the (n_slides, num_ids²) per-window
    trajectory-pair min-distance matrix (+inf = no pair), or None when
    the library is unavailable. Parity with the device engine at 1e-12
    (FMA contraction freedom; tests/test_tjoin_panes.py)."""
    lib = _load()
    if lib is None:
        return None
    c32 = lambda a: np.ascontiguousarray(a, np.int32)
    c64 = lambda a: np.ascontiguousarray(a, np.float64)
    out = np.empty((n_slides, num_ids * num_ids), np.float64)
    rc = lib.sf_tjoin_panes(
        c32(l_pane), c64(l_x), c64(l_y), c32(l_cell), c32(l_oid),
        len(l_pane),
        c32(r_pane), c64(r_x), c64(r_y), c32(r_cell), c32(r_oid),
        len(r_pane),
        n_slides, grid_n, layers, ppw, num_ids, float(radius),
        out.reshape(-1),
    )
    if rc < 0:
        raise ValueError(
            "tjoin_panes_native: oid/cell/pane out of range or panes "
            "not sorted"
        )
    return out


class _NativeInternerParser:
    """Shared ctypes lifecycle for the native parsers: library handle,
    interner ownership, id→string lookups, delimiter encoding."""

    def __init__(self, delimiter: str = ","):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.sf_interner_new()
        self.delimiter = delimiter.encode()[:1]

    def __del__(self):
        if getattr(self, "_h", None) and self._lib is not None:
            self._lib.sf_interner_free(self._h)
            self._h = None

    @property
    def num_objects(self) -> int:
        return int(self._lib.sf_interner_size(self._h))

    def object_name(self, oid: int) -> str:
        buf = ctypes.create_string_buffer(256)
        n = self._lib.sf_interner_get(self._h, oid, buf, 256)
        if n < 0:
            raise KeyError(oid)
        return buf.value.decode()


class NativeGpsParser(_NativeInternerParser):
    """Buffer-at-a-time 14-column GPS CSV parser with device interning.

    ``parse(data)`` → dict of SoA numpy arrays (ts, lon, lat, speed, fa,
    ff, dev). Device ids are dense int32, stable across calls; decode with
    ``device_name(id)`` / ``device_table()``.
    """

    def parse(self, data: bytes | str) -> Dict[str, np.ndarray]:
        if isinstance(data, str):
            data = data.encode()
        max_rows = data.count(b"\n") + 1
        ts = np.empty(max_rows, np.int64)
        lon = np.empty(max_rows, np.float64)
        lat = np.empty(max_rows, np.float64)
        speed = np.empty(max_rows, np.float64)
        fa = np.empty(max_rows, np.float64)
        ff = np.empty(max_rows, np.float64)
        dev = np.empty(max_rows, np.int32)
        n = self._lib.sf_parse_gps_csv(
            self._h, data, len(data), self.delimiter, max_rows,
            ts, lon, lat, speed, fa, ff, dev,
        )
        return {
            "ts": ts[:n], "lon": lon[:n], "lat": lat[:n], "speed": speed[:n],
            "fa": fa[:n], "ff": ff[:n], "dev": dev[:n],
        }

    @property
    def num_devices(self) -> int:
        return int(self._lib.sf_interner_size(self._h))

    def device_name(self, dev_id: int) -> str:
        buf = ctypes.create_string_buffer(256)
        n = self._lib.sf_interner_get(self._h, dev_id, buf, 256)
        if n < 0:
            raise KeyError(dev_id)
        return buf.value.decode()

    def device_table(self) -> List[str]:
        return [self.device_name(i) for i in range(self.num_devices)]


class NativePointParser(_NativeInternerParser):
    """Schema-positional point CSV parser (csvTsvSchemaAttr semantics)."""

    def __init__(self, schema=(0, 1, 2, 3), delimiter: str = ","):
        super().__init__(delimiter)
        self.schema = tuple(int(i) for i in schema)

    def parse(self, data: bytes | str) -> Dict[str, np.ndarray]:
        if isinstance(data, str):
            data = data.encode()
        max_rows = data.count(b"\n") + 1
        ts = np.empty(max_rows, np.int64)
        x = np.empty(max_rows, np.float64)
        y = np.empty(max_rows, np.float64)
        oid = np.empty(max_rows, np.int32)
        i_oid, i_ts, i_x, i_y = self.schema
        n = self._lib.sf_parse_points_csv(
            self._h, data, len(data), self.delimiter,
            i_oid, i_ts, i_x, i_y, max_rows, ts, x, y, oid,
        )
        return {"ts": ts[:n], "x": x[:n], "y": y[:n], "oid": oid[:n]}


class NativeWktParser(_NativeInternerParser):
    """WKT geometry-line parser → ragged SoA chunks.

    Wire format: ``objID<delim>timestamp<delim>WKT`` (the reference's WKT
    trajectory lines — Deserialization.java's WKTToTSpatial reads what the
    WKT output schemas write). POLYGONs — any ring count, holes included —
    and LINESTRINGs parse natively into the exact chunk layout
    ``RaggedSoaWindowAssembler``/``GeometryBatch.from_ragged`` take
    (rings closed + seam edges invalidated, pack_rings' contract, via the
    flat ``edge_valid`` mask); other/malformed lines are skipped and
    counted (``last_skipped``) for the Python object path to handle.
    """

    def __init__(self, delimiter: str = ","):
        super().__init__(delimiter)
        self.last_skipped = 0

    def parse(self, data: bytes | str) -> Dict[str, np.ndarray]:
        if isinstance(data, str):
            data = data.encode()
        max_rows = data.count(b"\n") + 1
        # Vertex upper bound: every parsed vertex is followed by a ',' or
        # ')' and ring closing can add one vertex PER RING (each ring ends
        # with its own ')') — counting both keeps the kernel's capacity
        # early-stop unreachable by construction.
        max_verts = data.count(b",") + data.count(b")") + 2 * max_rows + 2
        ts = np.empty(max_rows, np.int64)
        oid = np.empty(max_rows, np.int32)
        lengths = np.empty(max_rows, np.int64)
        polygonal = np.empty(max_rows, np.uint8)
        verts = np.empty((max_verts, 2), np.float64)
        edges = np.empty(max_verts, np.uint8)
        skipped = np.zeros(1, np.int64)
        n = self._lib.sf_parse_wkt_geoms(
            self._h, data, len(data), self.delimiter,
            max_rows, max_verts, ts, oid, lengths, polygonal,
            verts.reshape(-1), edges, skipped,
        )
        self.last_skipped = int(skipped[0])
        total = int(lengths[:n].sum())
        return {
            "ts": ts[:n].copy(),
            "oid": oid[:n].copy(),
            "lengths": lengths[:n].copy(),
            "polygonal": polygonal[:n].copy(),
            "verts": verts[:total].copy(),
            "edge_valid": edges[:total - n].astype(bool) if n else
            np.zeros(0, bool),
        }
