"""StreamingJob — the yml-config-driven entry point
(``GeoFlink/StreamingJob.java:68-280``).

``python -m spatialflink_tpu.streaming_job --config conf.yml [--source ...]``
loads the reference-schema config, builds the grid and query objects, wires
a source (the reference's Kafka consumer becomes file/socket/synthetic —
there is no Kafka broker in this environment; the seam is the same
line-record boundary) and dispatches on ``query.option``:

  1 = Range query, window-based, Point stream × Point query set
      (StreamingJob.java:254-263)
  2 = Range query, real-time, Point stream × Point query set (:265-275)
  (extensions) 3 = window kNN, 4 = realtime kNN, 5 = window join,
  6 = tStats, 7 = tAggregate, 8 = multi-query window kNN (one fused
  program answers the whole queryPoints set per window) — the operator
  families the reference keeps in its commented-out cases —
  9 = qserve, the multi-tenant standing-query serving layer
  (spatialflink_tpu/qserve.py): the query set comes from ``SFT_QSERVE``
  (queries + per-tenant-class budgets) or falls back to one range + one
  kNN standing query per yml queryPoint; registration commands ride the
  stream and intern into the operator's objID table (one intern home);
  and 10 = the composed SNCB DAG (spatialflink_tpu/dag.py): Q1–Q5 +
  StayTime + qserve on ONE source/interner/window clock, one
  transactional sink per node under the ``--output`` DIRECTORY, and —
  with ``--checkpoint`` — the atomic unit checkpoint (kill -9 anywhere
  resumes byte-identical on every sink).
"""

from __future__ import annotations

import argparse
import sys
from typing import Iterable, Iterator, Optional

from spatialflink_tpu.config import Params
from spatialflink_tpu.models.objects import Point
from spatialflink_tpu.operators import (
    PointPointJoinQuery,
    PointPointKNNQuery,
    PointPointRangeQuery,
    QueryConfiguration,
    QueryType,
    TAggregateQuery,
    TStatsQuery,
)
from spatialflink_tpu.streams.serde import parse_csv_point, parse_geojson
from spatialflink_tpu.streams.sinks import CsvFileSink, PrintSink
from spatialflink_tpu.streams.sources import (
    SyntheticGpsSource,
    collection_source,
    csv_source,
    socket_source,
)


def build_source(params: Params, source_arg: str) -> Iterator[Point]:
    """``--source`` forms: ``csv:<path>``, ``geojson:<path>``,
    ``socket:<host>:<port>``, ``synthetic[:eps[:seconds]]``, or
    ``kafka[:<topic>[@<bootstrap>]]`` — the reference's DEFAULT transport
    (StreamingJob.java:188-191), consumed through the built-in wire
    client (streams/kafka_wire.py); topic/bootstrap default to the yml's
    ``inputStream1.topicName`` / ``kafkaBootStrapServers``, the record
    format to ``inputStream1.format``."""
    sc = params.input_stream1
    kind, _, rest = source_arg.partition(":")
    if kind == "kafka":
        from spatialflink_tpu.streams.kafka import kafka_source

        topic, _, bootstrap = rest.partition("@")
        topic = topic or sc.topic_name
        bootstrap = bootstrap or params.kafka_bootstrap_servers
        if not topic or not bootstrap:
            raise ValueError(
                "kafka source needs a topic and bootstrap servers (CLI "
                "kafka:<topic>@<bootstrap> or yml inputStream1.topicName "
                "+ kafkaBootStrapServers)"
            )
        if sc.format == "GeoJSON":
            def parse(line):
                return parse_geojson(
                    line,
                    timestamp_property=sc.geojson_schema_attr[1],
                    objid_property=sc.geojson_schema_attr[0],
                    date_format=sc.date_format,
                )
        elif sc.format in ("CSV", "TSV"):
            def parse(line):
                return parse_csv_point(
                    line, schema=sc.csv_tsv_schema_attr,
                    delimiter=sc.delimiter, date_format=sc.date_format,
                )
        else:
            # Fail up front: kafka_source silently skips unparseable
            # records, so a wrong parser would hang forever with zero
            # output instead of erroring.
            raise ValueError(
                f"kafka source supports GeoJSON/CSV/TSV records for point "
                f"streams, not inputStream1.format={sc.format!r}"
            )
        return kafka_source(topic, bootstrap, parse)
    if kind == "csv":
        return csv_source(
            rest,
            lambda ln: parse_csv_point(
                ln, schema=sc.csv_tsv_schema_attr, delimiter=sc.delimiter,
                date_format=sc.date_format,
            ),
        )
    if kind == "geojson":
        def gen():
            with open(rest) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        try:
                            yield parse_geojson(
                                line,
                                timestamp_property=sc.geojson_schema_attr[1],
                                objid_property=sc.geojson_schema_attr[0],
                                date_format=sc.date_format,
                            )
                        except (ValueError, KeyError):
                            continue
        return gen()
    if kind == "socket":
        host, _, port = rest.partition(":")
        return socket_source(
            host, int(port),
            lambda ln: parse_csv_point(
                ln, schema=sc.csv_tsv_schema_attr, delimiter=sc.delimiter
            ),
        )
    if kind == "synthetic":
        parts = [p for p in rest.split(":") if p]
        eps = int(parts[0]) if parts else 20_000
        secs = float(parts[1]) if len(parts) > 1 else 10.0
        min_x, min_y, max_x, max_y = sc.grid_bbox
        return iter(
            SyntheticGpsSource(
                min_x, max_x, min_y, max_y, target_eps=eps,
                duration_ms=int(secs * 1000),
            )
        )
    raise ValueError(f"unknown source spec {source_arg!r}")


def _run_sncb_dag(params: Params, source, output_dir, driver) -> int:
    """Query option 10: the composed SNCB DAG (spatialflink_tpu/dag.py)
    — Q1–Q5 + StayTime + qserve on one source/interner/window clock,
    one transactional sink per node under ``output_dir``. Point events
    from the generic sources adapt to GpsEvents (obj_id → deviceId,
    x/y → lon/lat); qserve's standing-query set comes from
    ``SFT_QSERVE`` or the built-in Brussels default, registered via
    deterministic boot commands ON the stream (so a ``--checkpoint``
    resume replays them exactly)."""
    import itertools

    from spatialflink_tpu import dag as dag_mod
    from spatialflink_tpu import qserve as qserve_mod
    from spatialflink_tpu.sncb.common import GpsEvent

    if not output_dir:
        raise SystemExit(
            "query option 10 (the SNCB DAG) needs --output <directory> "
            "— one transactional sink per node lands there"
        )
    cfg = qserve_mod.config_from_env()
    if cfg and cfg.get("queries"):
        queries = qserve_mod.queries_from_config(cfg)
    else:
        queries = dag_mod.default_sncb_queries()
    w = params.window
    dag = dag_mod.build_sncb_dag(
        output_dir,
        window_s=float(w.interval), slide_s=float(w.step),
        grid=params.input_stream1.make_grid(),
        qserve_queries=queries,
        cap_max=(cfg or {}).get("cap_max"),
    )

    def gps(src):
        for p in src:
            if isinstance(p, GpsEvent):
                yield p
            else:
                yield GpsEvent(
                    device_id=p.obj_id, lon=float(p.x), lat=float(p.y),
                    ts=int(p.timestamp),
                    gps_speed=getattr(p, "speed", None),
                )

    stream = itertools.chain(dag.qserve_boot, gps(source))
    n = 0
    for res in dag.run(stream, driver=driver):
        n += sum(res.counts.values())
    return n


def run_job(params: Params, source: Iterable[Point], sink,
            driver=None, output_dir=None) -> int:
    """Dispatch on ``query.option``. ``driver=`` (a configured
    spatialflink_tpu.driver.WindowedDataflowDriver) routes the windowed
    query options through the self-healing dataflow driver —
    auto-checkpoint + exactly-once egress + retry/failover; supported
    for the driver-wired operators (options 1, 3, 5, 6, 9 and 10).
    ``output_dir`` is option 10's egress directory (the composed DAG
    owns one transactional sink per node; ``sink`` is ignored there)."""
    grid = params.input_stream1.make_grid()
    q = params.query
    window_conf = QueryConfiguration(
        QueryType.WindowBased,
        window_size=params.window.interval,
        slide_step=params.window.step,
        approximate_query=q.approximate,
    )
    realtime_conf = QueryConfiguration(
        QueryType.RealTime, approximate_query=q.approximate
    )
    q_points = [Point(x=p[0], y=p[1]) for p in q.query_points]
    n = 0
    option = q.option
    # The yml's deviceMesh (parallelism analog, conf/geoflink-conf.yml:55):
    # a product > 1 executes every windowed kernel shard_mapped over the
    # mesh's data axis, with results identical to single-device.
    from spatialflink_tpu.parallel.sharded import mesh_from_config

    mesh = mesh_from_config(params.device_mesh)

    # query.incremental (extension): pane/ListState-carry execution —
    # range rides query_incremental (PointPointRangeQuery.java:195-296's
    # analog), kNN/join ride the pane-digest/pane-block carries. Sliding
    # windows only; incompatible with a mesh (the carries are
    # single-device paths). Configurations the carries cannot serve
    # (size not a slide multiple) fall back to full recomputation rather
    # than erroring. NB the carry contracts (documented on each method):
    # in-order streams, and for the join exactness only at overflow == 0
    # (the per-cell cap applies per pane) — same results as run() within
    # those contracts, not beyond them.
    incremental = (
        bool(getattr(q, "incremental", False))
        and mesh is None
        and window_conf.window_size_ms
        % max(window_conf.slide_step_ms, 1) == 0
    )

    if driver is not None and option not in (1, 3, 5, 6, 9, 10):
        raise SystemExit(
            f"--checkpoint (the dataflow driver) supports query options "
            f"1, 3, 5, 6, 9 and 10, not {option} — the remaining "
            "operators keep their own loops until they are driver-wired"
        )

    if option == 10:
        return _run_sncb_dag(params, source, output_dir, driver)

    if option in (1, 2):
        conf = window_conf if option == 1 else realtime_conf
        op = PointPointRangeQuery(conf, grid, mesh=mesh)
        if option == 1 and incremental and len(q_points) == 1:
            if driver is not None:
                raise SystemExit(
                    "--checkpoint is incompatible with query.incremental "
                    "(the carry protocol is not driver-wired)"
                )
            # The carry protocol is single-query (like the reference's
            # one incremental variant); query sets take the full path.
            results = op.query_incremental(source, q_points[0], q.radius)
        else:
            results = op.run(source, q_points, q.radius, driver=driver)
        # ONE home for the option-1 line format (driver.render_range_result
        # — the same renderer the per-commit chaos gate byte-compares):
        from spatialflink_tpu.driver import render_range_result

        for res in results:
            for line in render_range_result(res):
                sink(line)
                n += 1
    elif option in (3, 4):
        conf = window_conf if option == 3 else realtime_conf
        op = PointPointKNNQuery(conf, grid, mesh=mesh)
        if option == 3 and incremental:
            if driver is not None:
                raise SystemExit(
                    "--checkpoint is incompatible with query.incremental "
                    "(the pane-carry protocol is not driver-wired)"
                )
            results = op.query_panes(source, q_points[0], q.radius, q.k)
        else:
            results = op.run(source, q_points[0], q.radius, q.k,
                             driver=driver)
        for res in results:
            for oid, d, p in res.neighbors:
                sink(f"{res.start},{res.end},{oid},{float(d)!r}")
                n += 1
    elif option == 5:
        op = PointPointJoinQuery(window_conf, grid, mesh=mesh)
        # Both halves re-materialize deterministically from the replayed
        # source, so the merged two-stream sequence is itself replayable
        # — what the driver's resume-skip needs.
        events = list(source)
        half = len(events) // 2
        left, right = iter(events[:half]), iter(events[half:])
        if incremental:
            if driver is not None:
                raise SystemExit(
                    "--checkpoint is incompatible with query.incremental "
                    "(the pane-carry protocol is not driver-wired)"
                )
            results = op.query_panes(left, right, q.radius)
        else:
            results = op.run(left, right, q.radius, driver=driver)
        for res in results:
            for a, b, d in res.pairs:
                sink(f"{res.start},{res.end},{a.obj_id},{b.obj_id},{float(d)!r}")
                n += 1
    elif option == 8:
        op = PointPointKNNQuery(window_conf, grid, mesh=mesh)
        for res in op.run_multi(source, q_points, q.radius, q.k):
            for qi, r_ in enumerate(res.results):
                for oid, d, p in r_.neighbors:
                    sink(f"{res.start},{res.end},{qi},{oid},{float(d)!r}")
                    n += 1
    elif option == 6:
        op = TStatsQuery(window_conf, grid, mesh=mesh)
        for res in op.run(source, driver=driver):
            for oid, (sp, tp, ratio) in sorted(res.stats.items()):
                sink(f"{res.start},{res.end},{oid},{float(sp)!r},{tp},{float(ratio)!r}")
                n += 1
    elif option == 9:
        import itertools

        from spatialflink_tpu import overload as overload_mod
        from spatialflink_tpu import qserve as qserve_mod

        cfg = qserve_mod.config_from_env()
        if cfg and cfg.get("queries"):
            queries = qserve_mod.queries_from_config(cfg)
        else:
            # No SFT_QSERVE query set: one range + one kNN standing
            # query per yml queryPoint, all under the default tenant.
            queries = []
            for i, p in enumerate(q_points):
                queries.append(qserve_mod.StandingQuery(
                    qid=f"range{i}", tenant="default", kind="range",
                    x=p.x, y=p.y, radius=q.radius, k=64,
                ))
                queries.append(qserve_mod.StandingQuery(
                    qid=f"knn{i}", tenant="default", kind="knn",
                    x=p.x, y=p.y, radius=q.radius, k=q.k,
                ))
        budgets = (cfg or {}).get("tenant_budgets")
        prev_ctrl = overload_mod.controller()
        installed = False
        if budgets:
            ctrl = overload_mod.OverloadController(
                overload_mod.OverloadPolicy(tenant_budgets=budgets)
            )
            if driver is not None:
                driver.overload = ctrl
            else:
                overload_mod.install(ctrl)
                installed = True
        op = qserve_mod.QServeOperator(
            window_conf, grid, mesh=mesh,
            cap_max=int((cfg or {}).get("cap_max",
                                        qserve_mod.QUERY_CAP_MAX)),
        )
        try:
            # Registration commands ride the SAME stream (deterministic
            # uids), so a --checkpoint resume replays them exactly; the
            # registry's applied-uid set keeps the replay idempotent.
            stream = itertools.chain(qserve_mod.boot_commands(queries),
                                     source)
            for res in op.run(stream, driver=driver):
                for line in res.lines():
                    sink(line)
                    n += 1
        finally:
            # The non-driver install must not outlive the run: restore
            # whatever controller was global before (the driver path
            # does this itself — driver._installed_controller).
            if installed:
                if prev_ctrl is not None:
                    overload_mod.install(prev_ctrl)
                else:
                    overload_mod.uninstall()
    elif option == 7:
        op = TAggregateQuery(
            window_conf, grid, aggregate=q.aggregate_function,
            inactive_threshold_ms=q.traj_deletion_threshold * 1000,
        )
        for res in op.run(source):
            for cell, (cnt, lens) in sorted(res.cells.items()):
                sink(f"{res.start},{res.end},{cell},{cnt},{lens}")
                n += 1
    else:
        raise SystemExit(f"Unrecognized query option {option}. Use 1-10.")
    return n


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", required=True, help="geoflink-conf.yml path")
    ap.add_argument(
        "--source", default="synthetic",
        help="csv:<path> | geojson:<path> | socket:<host>:<port> | "
             "synthetic[:eps[:secs]] | kafka[:<topic>[@<bootstrap>]]",
    )
    ap.add_argument(
        "--output", default=None,
        help="output CSV path, or kafka[:<topic>[@<bootstrap>]] (the "
             "reference's producer side, StreamingJob.java:255; defaults "
             "from the yml's outputStream); default stdout",
    )
    ap.add_argument(
        "--max-records", type=int, default=None,
        help="stop after N input records (unbounded sources like kafka/"
             "socket run forever otherwise)",
    )
    ap.add_argument(
        "--checkpoint", default=None, metavar="PATH",
        help="pipeline checkpoint file: runs through the self-healing "
             "dataflow driver with exactly-once checkpointed egress "
             "(requires a file --output and a REPLAYABLE --source — "
             "csv/geojson; a run killed at any instant resumes from "
             "PATH with byte-identical concatenated output)",
    )
    ap.add_argument(
        "--checkpoint-every", type=int, default=8, metavar="N",
        help="auto-checkpoint cadence in fired windows (default 8)",
    )
    args = ap.parse_args(argv)

    params = Params.load(args.config)
    source = build_source(params, args.source)
    if args.max_records is not None:
        import itertools

        source = itertools.islice(source, args.max_records)
    if args.checkpoint:
        # Exactly-once pipeline: records stage in the transactional sink
        # and publish atomically with each driver checkpoint; on restart
        # the driver restores operator/assembler state, truncates any
        # uncommitted egress tail, and skips the already-consumed prefix
        # of the (replayed) source.
        if not args.output or args.output == "kafka" \
                or args.output.startswith("kafka:"):
            raise SystemExit(
                "--checkpoint requires a file --output (the exactly-once "
                "egress protocol is file-based; option 10 takes a "
                "directory — one sink per DAG node)"
            )
        if args.source.partition(":")[0] not in ("csv", "geojson"):
            raise SystemExit(
                "--checkpoint requires a replayable --source "
                "(csv:<path> or geojson:<path>) — resume replays the "
                "consumed prefix"
            )
        from spatialflink_tpu.driver import WindowedDataflowDriver
        from spatialflink_tpu.streams.sinks import TransactionalFileSink

        if params.query.option == 10:
            # The composed DAG wires its own MultiSink (one
            # transactional sink per node under the --output dir) into
            # the driver; the unit checkpoint covers them all.
            driver = WindowedDataflowDriver(
                checkpoint_path=args.checkpoint,
                checkpoint_every=args.checkpoint_every,
                sink=None,
            )
            n = run_job(params, source, None, driver=driver,
                        output_dir=args.output)
        else:
            sink = TransactionalFileSink(args.output)
            driver = WindowedDataflowDriver(
                checkpoint_path=args.checkpoint,
                checkpoint_every=args.checkpoint_every,
                sink=sink,
            )
            n = run_job(params, source, sink, driver=driver)
        print(f"StreamingJob done: {n} result records", file=sys.stderr)
        return 0
    if params.query.option == 10:
        n = run_job(params, source, None, output_dir=args.output)
        print(f"StreamingJob done: {n} result records", file=sys.stderr)
        return 0
    if args.output and (args.output == "kafka"
                        or args.output.startswith("kafka:")):
        from spatialflink_tpu.streams.kafka import KafkaSink

        rest = args.output.partition(":")[2]
        topic, _, bootstrap = rest.partition("@")
        topic = topic or params.output_topic
        bootstrap = bootstrap or params.kafka_bootstrap_servers
        if not topic or not bootstrap:
            raise ValueError(
                "kafka output needs a topic and bootstrap servers (CLI "
                "kafka:<topic>@<bootstrap> or yml outputStream.topicName "
                "+ kafkaBootStrapServers)"
            )
        sink = KafkaSink(topic, bootstrap)
        try:
            n = run_job(params, source, sink)
        finally:
            sink.close()
    elif args.output:
        with CsvFileSink(args.output) as sink:
            n = run_job(params, source, sink)
    else:
        n = run_job(params, source, PrintSink())
    print(f"StreamingJob done: {n} result records", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
