"""YAML configuration system.

Re-designs the reference's snakeyaml-bean config
(``conf/geoflink-conf.yml`` tagged ``!!GeoFlink.utils.ConfigType`` →
``utils/ConfigType.java`` bean → ``utils/Params.java`` validation with hard
failures on missing/invalid keys, Params.java:75+). Same YAML schema (the
reference's conf files load unchanged, minus the Java type tag), same
validation strictness, plus the TPU-backend extensions (``backend``,
``device_mesh``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import yaml

_FORMATS = {"GeoJSON", "WKT", "CSV", "TSV"}
_AGGREGATES = {"ALL", "SUM", "AVG", "MIN", "MAX"}
_WINDOW_TYPES = {"TIME", "COUNT"}


class ConfigError(ValueError):
    pass


@dataclass
class StreamConfig:
    """One input stream section (inputStream1/2 in geoflink-conf.yml:10-45)."""

    topic_name: str = ""
    format: str = "GeoJSON"
    date_format: Optional[str] = None
    geojson_schema_attr: List[str] = field(default_factory=lambda: ["oID", "timestamp"])
    csv_tsv_schema_attr: List[int] = field(default_factory=lambda: [0, 1, 2, 3])
    grid_bbox: List[float] = field(default_factory=lambda: [0.0, 0.0, 1.0, 1.0])
    num_grid_cells: int = 100
    cell_length: float = 0.0
    delimiter: str = ","
    charset: str = "UTF-8"

    @classmethod
    def from_dict(cls, d: Dict[str, Any], name: str) -> "StreamConfig":
        fmt = d.get("format", "GeoJSON")
        if fmt not in _FORMATS:
            raise ConfigError(f"{name}.format must be one of {_FORMATS}, got {fmt!r}")
        bbox = d.get("gridBBox")
        if not bbox or len(bbox) != 4:
            raise ConfigError(f"{name}.gridBBox must be [minX, minY, maxX, maxY]")
        if not (bbox[0] < bbox[2] and bbox[1] < bbox[3]):
            raise ConfigError(f"{name}.gridBBox is degenerate: {bbox}")
        ncells = int(d.get("numGridCells", 0) or 0)
        clen = float(d.get("cellLength", 0) or 0)
        if ncells <= 0 and clen <= 0:
            raise ConfigError(f"{name}: one of numGridCells/cellLength must be > 0")
        date_format = d.get("dateFormat")
        if date_format in ("null", "None", ""):
            date_format = None
        return cls(
            topic_name=d.get("topicName", ""),
            format=fmt,
            date_format=date_format,
            geojson_schema_attr=list(d.get("geoJSONSchemaAttr", ["oID", "timestamp"])),
            csv_tsv_schema_attr=[int(i) for i in d.get("csvTsvSchemaAttr", [0, 1, 2, 3])],
            grid_bbox=[float(v) for v in bbox],
            num_grid_cells=ncells,
            cell_length=clen,
            delimiter=d.get("delimiter", ","),
            charset=d.get("charset", "UTF-8"),
        )

    def make_grid(self):
        from spatialflink_tpu.grid import UniformGrid

        min_x, min_y, max_x, max_y = self.grid_bbox
        if self.cell_length > 0:
            return UniformGrid.from_cell_length(
                self.cell_length, min_x, max_x, min_y, max_y
            )
        return UniformGrid(self.num_grid_cells, min_x, max_x, min_y, max_y)


@dataclass
class QueryConfig:
    """query: section (geoflink-conf.yml:52-77)."""

    option: int = 1
    parallelism: int = 1
    approximate: bool = False
    radius: float = 0.0
    aggregate_function: str = "SUM"
    k: int = 1
    omega_duration: int = 1
    traj_ids: List[str] = field(default_factory=list)
    query_points: List[List[float]] = field(default_factory=list)
    query_polygons: List[List[List[float]]] = field(default_factory=list)
    query_linestrings: List[List[List[float]]] = field(default_factory=list)
    traj_deletion_threshold: int = 0
    out_of_order_tuples: int = 0
    incremental: bool = False  # extension: pane/ListState-carry execution

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "QueryConfig":
        agg = d.get("aggregateFunction", "SUM")
        if agg not in _AGGREGATES:
            raise ConfigError(f"query.aggregateFunction must be in {_AGGREGATES}")
        k = int(d.get("k", 1))
        if k < 1:
            raise ConfigError("query.k must be >= 1")
        th = d.get("thresholds", {}) or {}
        return cls(
            option=int(d.get("option", 1)),
            parallelism=int(d.get("parallelism", 1)),
            approximate=bool(d.get("approximate", False)),
            radius=float(d.get("radius", 0.0)),
            aggregate_function=agg,
            k=k,
            omega_duration=int(d.get("omegaDuration", 1)),
            traj_ids=[str(t) for t in d.get("trajIDs", [])],
            query_points=[[float(c) for c in p] for p in d.get("queryPoints", [])],
            query_polygons=[
                [[float(c) for c in pt] for pt in poly]
                for poly in d.get("queryPolygons", [])
            ],
            query_linestrings=[
                [[float(c) for c in pt] for pt in ls]
                for ls in d.get("queryLineStrings", [])
            ],
            traj_deletion_threshold=int(th.get("trajDeletion", 0)),
            out_of_order_tuples=int(th.get("outOfOrderTuples", 0)),
            incremental=bool(d.get("incremental", False)),
        )


@dataclass
class WindowConfig:
    """window: section (geoflink-conf.yml:79-82). interval/step in seconds."""

    type: str = "TIME"
    interval: float = 5.0
    step: float = 5.0

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "WindowConfig":
        wtype = d.get("type", "TIME")
        if wtype not in _WINDOW_TYPES:
            raise ConfigError(f"window.type must be in {_WINDOW_TYPES}")
        interval = float(d.get("interval", 5))
        step = float(d.get("step", interval))
        if interval <= 0 or step <= 0:
            raise ConfigError("window.interval/step must be positive")
        return cls(type=wtype, interval=interval, step=step)

    @property
    def interval_ms(self) -> int:
        return int(self.interval * 1000)

    @property
    def step_ms(self) -> int:
        return int(self.step * 1000)


@dataclass
class Params:
    """Validated top-level parameters (utils/Params.java)."""

    cluster_mode: bool = False
    kafka_bootstrap_servers: str = ""
    input_stream1: StreamConfig = field(default_factory=StreamConfig)
    input_stream2: Optional[StreamConfig] = None
    output_topic: str = ""
    output_delimiter: str = ","
    query: QueryConfig = field(default_factory=QueryConfig)
    window: WindowConfig = field(default_factory=WindowConfig)
    # TPU-backend extensions (the `backend: tpu` seam from BASELINE.json).
    backend: str = "tpu"
    device_mesh: List[int] = field(default_factory=lambda: [1])

    @classmethod
    def load(cls, path: str) -> "Params":
        with open(path) as f:
            text = f.read()
        return cls.loads(text)

    @classmethod
    def loads(cls, text: str) -> "Params":
        # Strip the Java bean type tag if present (geoflink-conf.yml:1).
        lines = [
            ln for ln in text.splitlines() if not ln.strip().startswith("!!")
        ]
        raw = yaml.safe_load("\n".join(lines)) or {}
        if "inputStream1" not in raw:
            raise ConfigError("missing required section: inputStream1")
        out_raw = raw.get("outputStream", {}) or {}
        backend = str(raw.get("backend", "tpu")).lower()
        if backend not in ("tpu", "cpu"):
            raise ConfigError(f"backend must be tpu or cpu, got {backend!r}")
        return cls(
            cluster_mode=bool(raw.get("clusterMode", False)),
            kafka_bootstrap_servers=str(raw.get("kafkaBootStrapServers", "")),
            input_stream1=StreamConfig.from_dict(raw["inputStream1"], "inputStream1"),
            input_stream2=(
                StreamConfig.from_dict(raw["inputStream2"], "inputStream2")
                if raw.get("inputStream2")
                else None
            ),
            output_topic=out_raw.get("topicName", ""),
            output_delimiter=out_raw.get("delimiter", ","),
            query=QueryConfig.from_dict(raw.get("query", {}) or {}),
            window=WindowConfig.from_dict(raw.get("window", {}) or {}),
            backend=backend,
            device_mesh=[int(v) for v in raw.get("deviceMesh", [1])],
        )
