"""Online SLO engine — declarative per-run objectives evaluated WHILE
the stream runs, not after it dies.

The observability stack so far is post-hoc: sfprof renders verdicts from
a ledger written at run end (and the r3–r5 chip captures showed what
that costs when the run doesn't reach its end). This module inverts it:
a declarative :class:`SloSpec` — watermark-lag p99 freshness ceiling,
EPS floor, late-drop/overflow budgets, recompile ceiling (the
"per-query freshness SLOs" of ROADMAP item 5) — is evaluated
incrementally from telemetry gauge deltas as windows fire. Violations
become structured ``slo_violation:*`` instant events in the trace and
ledger stream (flushed immediately — a violation is exactly what must
survive a crash) plus a verdict block in the ledger, and ``python -m
tools.sfprof health --slo <spec>`` applies the SAME spec post-hoc, so
one JSON file gates both the live run and the recovered artifact.

Wiring follows the telemetry idiom: a module-level engine slot,
``install()`` to opt in, and a free-when-disabled hook
(:func:`on_window_fired`) at the window-fire sites where
``record_watermark_lag`` already lives (streams/windows.py,
streams/soa.py) — one global read + None check per fired window while
no engine is installed.

Spec schema twin: ``tools/sfcheck``-style no-cross-import rule — the
validator-side mirror lives in ``tools/sfprof/slo.py`` (same
``SLO_VERSION``, same field names; tests/test_slo.py cross-pins them).
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, fields
from typing import Any, Dict, List, Optional

from spatialflink_tpu import overload
from spatialflink_tpu.mn.metrics import FixedBucketLatency, json_safe
from spatialflink_tpu.telemetry import telemetry

#: Spec schema version. Twin: tools/sfprof/slo.py:SLO_VERSION.
SLO_VERSION = 1


@dataclass(frozen=True)
class SloSpec:
    """Declarative SLO thresholds; ``None`` means unchecked.

    - ``watermark_lag_p99_ms``: freshness — p99 of the event-time lag
      between a window's end and the watermark that fired it;
    - ``eps_floor``: sustained events/sec over the run so far (checked
      only after ``warmup_windows`` fired windows — the first windows
      pay XLA compiles);
    - ``late_drop_budget`` / ``overflow_budget``: counter ceilings (ANY
      excess violates);
    - ``recompile_ceiling``: total distinct-signature compiles — bucket
      ladders are bounded, churn is not;
    - ``retry_budget`` / ``failover_budget``: ceilings on the dataflow
      driver's self-healing actions (driver.py) — a run that survived on
      retries or finished on the numpy fallback is a DEGRADED run, and
      these budgets let a spec say how much degradation still counts as
      meeting the objective (``failover_budget: 0`` = any failover
      violates);
    - ``shed_budget`` / ``degraded_window_budget``: ceilings on the
      overload controller's actions (overload.py) — total events shed
      (admission + late + oldest) and windows answered by a non-device
      path (circuit-open routing or post-failover). A spec naming these
      against a run with NO controller installed VIOLATES — silence
      must fail the gate, the ``eps_floor`` rule;
    - ``tenant_budgets``: per-tenant-class QoS budgets (the qserve
      scoping of the two overload budgets above) — ``{class:
      {"shed_budget": N, "degraded_window_budget": M}}`` checked against
      the controller's PER-CLASS counters (queries rejected + result
      rows shed / class-degraded windows). A spec naming a class against
      a run with NO controller installed violates — silence fails;
    - ``node_budgets``: per-DAG-node freshness/health budgets (the
      composed-dataflow scoping, spatialflink_tpu/dag.py) — ``{node:
      {"watermark_lag_p99_ms": L, "retry_budget": N,
      "failover_budget": M, "degraded_window_budget": K,
      "e2e_p50_ms": P, "e2e_p99_ms": Q}}`` checked against the
      installed DAG's PER-NODE counters, so each query's watermark lag
      (and event-time end-to-end staleness, from the node's "compute"
      lineage stage) is budgeted separately. A spec naming a node
      against a run with NO DAG installed (or an unknown node name)
      violates — silence fails;
    - ``e2e_p50_ms`` / ``e2e_p99_ms``: event-time end-to-end latency
      ceilings on the GLOBAL "commit" lineage stage (telemetry
      ``record_e2e``: window event-time end → sink/checkpoint commit).
      Checked only after ``warmup_windows`` (the eps_floor grace);
      past warm-up, a spec naming them against a run that never
      stamped a commit violates — silence fails;
    - ``eval_interval_s``: pacing of the incremental evaluation (the
      per-window cost between evaluations is counter updates only).
    """

    name: str = "default"
    watermark_lag_p99_ms: Optional[float] = None
    eps_floor: Optional[float] = None
    late_drop_budget: Optional[int] = None
    overflow_budget: Optional[int] = None
    recompile_ceiling: Optional[int] = None
    retry_budget: Optional[int] = None
    failover_budget: Optional[int] = None
    shed_budget: Optional[int] = None
    degraded_window_budget: Optional[int] = None
    e2e_p50_ms: Optional[float] = None
    e2e_p99_ms: Optional[float] = None
    tenant_budgets: Optional[Dict[str, Dict[str, int]]] = None
    node_budgets: Optional[Dict[str, Dict[str, int]]] = None
    eval_interval_s: float = 1.0
    warmup_windows: int = 8

    #: Per-class budget keys ``tenant_budgets`` accepts (the strict-
    #: parse rule applies inside the mapping too).
    TENANT_BUDGET_KEYS = ("shed_budget", "degraded_window_budget")

    #: Per-node budget keys ``node_budgets`` accepts (integer ms /
    #: counts — same strict map shape).
    NODE_BUDGET_KEYS = ("watermark_lag_p99_ms", "retry_budget",
                        "failover_budget", "degraded_window_budget",
                        "e2e_p50_ms", "e2e_p99_ms")

    def __post_init__(self):
        # ONE validation home (overload.validate_budget_map): same
        # map shape as OverloadPolicy.tenant_budgets, different keys.
        overload.validate_budget_map(
            self.tenant_budgets, self.TENANT_BUDGET_KEYS
        )
        overload.validate_budget_map(
            self.node_budgets, self.NODE_BUDGET_KEYS,
            what="node_budgets",
        )

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SloSpec":
        """Strict parse: an unknown key is a spec typo, and a typo'd
        threshold silently unchecked is the worst failure mode a gate can
        have — raise instead. ``slo_version`` (when present) must match."""
        d = dict(d)
        ver = d.pop("slo_version", SLO_VERSION)
        if ver != SLO_VERSION:
            raise ValueError(
                f"slo_version {ver} != supported {SLO_VERSION}"
            )
        known = {f.name for f in fields(cls)}
        unknown = sorted(set(d) - known)
        if unknown:
            raise ValueError(f"unknown SLO spec keys: {unknown}")
        return cls(**d)

    @classmethod
    def from_file(cls, path: str) -> "SloSpec":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"slo_version": SLO_VERSION}
        for f in fields(self):
            out[f.name] = getattr(self, f.name)
        return out


def _find_overflows(value, out: List[int]):
    """Sum every numeric counter whose key mentions ``overflow`` — the
    same substring contract ``sfprof health`` applies to ledgers."""
    if isinstance(value, dict):
        for k, v in value.items():
            if ("overflow" in str(k)
                    and isinstance(v, (int, float))
                    and not isinstance(v, bool)):
                out.append(int(v))
            else:
                _find_overflows(v, out)


class SloEngine:
    """Incremental evaluator of one :class:`SloSpec` against the live
    telemetry gauges.

    ``observe_window`` is the per-window hook: counter updates under a
    lock, and — at most every ``eval_interval_s`` — a full check pass.
    Each check TRANSITION into violation appends a violation record and
    emits a ``slo_violation:<check>`` instant event (stream-flushed
    immediately); recovery transitions emit ``slo_recovered:<check>``
    without clearing the recorded violation — the verdict is about the
    run, not the final second."""

    def __init__(self, spec: SloSpec, tel=telemetry):
        self.spec = spec
        self.tel = tel
        self._lock = threading.Lock()
        self.windows = 0
        self.points = 0
        self.evaluations = 0
        self.violations: List[dict] = []
        self.lag = FixedBucketLatency()
        self._violated: Dict[str, bool] = {}
        self._last_checks: List[dict] = []
        # EPS clock starts at the FIRST fired window, not at engine
        # construction: install() happens before warm-up (XLA compiles,
        # probe samples), and a floor calibrated from bench throughput
        # would spuriously violate if that dead time counted as elapsed.
        self._t0: Optional[float] = None
        self._last_eval = time.monotonic()

    # -- per-window hook -------------------------------------------------------

    def observe_window(self, n_events: int = 0,
                       lag_ms: Optional[float] = None):
        now = time.monotonic()
        with self._lock:
            if self._t0 is None:
                self._t0 = now
            self.windows += 1
            self.points += int(n_events)
            if lag_ms is not None:
                self.lag.observe(float(lag_ms))
            due = now - self._last_eval >= self.spec.eval_interval_s
            if due:
                self._last_eval = now
        if due:
            self.evaluate()

    # -- evaluation ------------------------------------------------------------

    def _checks(self) -> List[dict]:
        sp = self.spec
        out: List[dict] = []

        def check(name, value, bound, ok):
            out.append({"check": name, "value": json_safe(value),
                        "bound": bound, "ok": bool(ok)})

        with self._lock:
            windows, points = self.windows, self.points
            t0 = self._t0
            lag_count = self.lag.count
            lag_p99 = self.lag.percentile(0.99) if lag_count else None
        if sp.watermark_lag_p99_ms is not None and lag_p99 is not None:
            check("watermark_lag_p99_ms", lag_p99,
                  f"<= {float(sp.watermark_lag_p99_ms):g}",
                  lag_p99 <= sp.watermark_lag_p99_ms)
        if sp.eps_floor is not None and t0 is not None \
                and windows > sp.warmup_windows:
            elapsed = max(time.monotonic() - t0, 1e-9)
            eps = points / elapsed
            check("eps_floor", eps, f">= {float(sp.eps_floor):g}",
                  eps >= sp.eps_floor)
        if sp.late_drop_budget is not None:
            late = self.tel.late_drops
            check("late_drop_budget", late,
                  f"<= {int(sp.late_drop_budget)}",
                  late <= sp.late_drop_budget)
        if sp.recompile_ceiling is not None:
            compiles = self.tel.compile_count
            check("recompile_ceiling", compiles,
                  f"<= {int(sp.recompile_ceiling)}",
                  compiles <= sp.recompile_ceiling)
        if sp.retry_budget is not None:
            retries = self.tel.driver_retries
            check("retry_budget", retries, f"<= {int(sp.retry_budget)}",
                  retries <= sp.retry_budget)
        if sp.failover_budget is not None:
            fo = self.tel.driver_failovers
            check("failover_budget", fo, f"<= {int(sp.failover_budget)}",
                  fo <= sp.failover_budget)
        if sp.shed_budget is not None:
            ctrl = overload.controller()
            shed = None if ctrl is None else ctrl.shed_total
            check("shed_budget", shed, f"<= {int(sp.shed_budget)}",
                  # No controller installed = the budget is unanswerable
                  # — silence fails (the eps_floor rule).
                  shed is not None and shed <= sp.shed_budget)
        if sp.degraded_window_budget is not None:
            ctrl = overload.controller()
            dw = None if ctrl is None else ctrl.degraded_windows
            check("degraded_window_budget", dw,
                  f"<= {int(sp.degraded_window_budget)}",
                  dw is not None and dw <= sp.degraded_window_budget)
        if (sp.e2e_p50_ms is not None or sp.e2e_p99_ms is not None) \
                and windows > sp.warmup_windows:
            # Event-time end-to-end staleness on the global "commit"
            # lineage stage. Past warm-up, a run that never stamped a
            # commit leaves the ceiling unanswerable — silence fails
            # (the eps_floor rule).
            e2e_p50, e2e_p99 = self.tel.e2e_stage_percentiles("commit")
            if sp.e2e_p50_ms is not None:
                check("e2e_p50_ms", e2e_p50,
                      f"<= {float(sp.e2e_p50_ms):g}",
                      e2e_p50 is not None and e2e_p50 <= sp.e2e_p50_ms)
            if sp.e2e_p99_ms is not None:
                check("e2e_p99_ms", e2e_p99,
                      f"<= {float(sp.e2e_p99_ms):g}",
                      e2e_p99 is not None and e2e_p99 <= sp.e2e_p99_ms)
        if sp.tenant_budgets:
            ctrl = overload.controller()
            for cls, b in sorted(sp.tenant_budgets.items()):
                sb = b.get("shed_budget")
                if sb is not None:
                    shed = (None if ctrl is None
                            else ctrl.tenant_shed_total(cls))
                    check(f"tenant_shed_budget:{cls}", shed,
                          f"<= {int(sb)}",
                          # No controller = the per-class budget is
                          # unanswerable — silence fails (eps_floor rule).
                          shed is not None and shed <= sb)
                dwb = b.get("degraded_window_budget")
                if dwb is not None:
                    dw = (None if ctrl is None
                          else ctrl.tenant_degraded_windows(cls))
                    check(f"tenant_degraded_window_budget:{cls}", dw,
                          f"<= {int(dwb)}",
                          dw is not None and dw <= dwb)
        if sp.node_budgets:
            from spatialflink_tpu import dag as dag_mod

            d = dag_mod.active()
            for node, b in sorted(sp.node_budgets.items()):
                stats = None if d is None else d.node_stats(node)
                # ONE (key, head, metric) table — the same triple shape
                # as the post-hoc twin's (tools/sfprof/slo.py).
                for key, head, metric in (
                    ("watermark_lag_p99_ms", "node_watermark_lag_p99_ms",
                     "watermark_lag_p99_ms"),
                    ("retry_budget", "node_retry_budget", "retries"),
                    ("failover_budget", "node_failover_budget",
                     "failovers"),
                    ("degraded_window_budget",
                     "node_degraded_window_budget", "degraded_windows"),
                    ("e2e_p50_ms", "node_e2e_p50_ms", "e2e_p50_ms"),
                    ("e2e_p99_ms", "node_e2e_p99_ms", "e2e_p99_ms"),
                ):
                    bound = b.get(key)
                    if bound is None:
                        continue
                    if key.startswith("e2e_") \
                            and windows <= sp.warmup_windows:
                        # e2e lineage needs a committed window — give
                        # warm-up the same grace eps_floor gets before
                        # the silence-fails rule bites.
                        continue
                    val = None if stats is None else stats[metric]
                    check(f"{head}:{node}", val, f"<= {int(bound)}",
                          # No DAG installed / unknown node = the
                          # per-node budget is unanswerable — silence
                          # fails (the eps_floor rule).
                          val is not None and val <= bound)
        if sp.overflow_budget is not None:
            counts: List[int] = []
            _find_overflows(self.tel.snapshot(), counts)
            total = sum(counts)
            check("overflow_budget", total,
                  f"<= {int(sp.overflow_budget)}",
                  total <= sp.overflow_budget)
        return out

    def evaluate(self) -> List[dict]:
        """One full check pass; returns the check rows. Violation events
        are emitted on TRANSITIONS only (a stall that lasts a thousand
        windows is one violation, not a thousand)."""
        rows = self._checks()
        transitions = []
        with self._lock:
            self.evaluations += 1
            self._last_checks = rows
            for row in rows:
                was = self._violated.get(row["check"], False)
                now_bad = not row["ok"]
                self._violated[row["check"]] = now_bad
                if now_bad and not was:
                    rec = {
                        "check": row["check"], "value": row["value"],
                        "bound": row["bound"], "unix": time.time(),
                        "window_seq": self.windows,
                    }
                    self.violations.append(rec)
                    transitions.append(("slo_violation", rec))
                elif was and not now_bad:
                    transitions.append(("slo_recovered", {
                        "check": row["check"], "value": row["value"],
                        "bound": row["bound"], "unix": time.time(),
                        "window_seq": self.windows,
                    }))
        for kind, rec in transitions:
            # Two literal branches, not one f"{kind}:…": the event-name
            # HEAD must be a static literal so sfcheck's contract-twin
            # pass can hold it against the sfprof consumer registry —
            # a dynamic head is statically uncheckable.
            if kind == "slo_violation":
                self.tel.emit_instant(f"slo_violation:{rec['check']}",
                                      value=rec["value"],
                                      bound=rec["bound"],
                                      window_seq=rec["window_seq"])
            else:
                self.tel.emit_instant(f"slo_recovered:{rec['check']}",
                                      value=rec["value"],
                                      bound=rec["bound"],
                                      window_seq=rec["window_seq"])
        if any(kind == "slo_violation" for kind, _ in transitions):
            # A violation is exactly the record that must survive the
            # run dying right after it — force the stream segment out.
            self.tel.maybe_flush_stream(force=True)
        if rows:
            # Live verdict → degradation ladder: a violating evaluation
            # steps the overload controller's rung down (free when no
            # controller is installed).
            overload.on_slo_evaluation(all(r["ok"] for r in rows))
        return rows

    def verdict(self) -> Dict[str, Any]:
        """The ledger/epilogue block: spec, final check states (one last
        evaluation), every violation recorded over the run, and the
        boolean gate (``ok`` == zero violations EVER)."""
        rows = self.evaluate()
        with self._lock:
            return json_safe({
                "slo_version": SLO_VERSION,
                "spec": self.spec.to_dict(),
                "ok": not self.violations,
                "windows": self.windows,
                "points": self.points,
                "evaluations": self.evaluations,
                "checks": rows,
                "violations": list(self.violations),
            })


# -- module-level wiring (the telemetry singleton idiom) -----------------------

_engine: Optional[SloEngine] = None


def install(engine: SloEngine) -> SloEngine:
    """Make ``engine`` the process-global SLO engine: window-fire sites
    start feeding it, and ``telemetry.write_ledger``/``seal_stream``
    embed its verdict."""
    global _engine
    _engine = engine
    engine.tel.slo_provider = engine.verdict
    return engine


def uninstall():
    global _engine
    if _engine is not None:
        _engine.tel.slo_provider = None
    _engine = None


def engine() -> Optional[SloEngine]:
    return _engine


def on_window_fired(n_events: int = 0, lag_ms: Optional[float] = None):
    """The window-fire hook (streams/windows.py, streams/soa.py): free
    when no engine is installed — one global read and a None check."""
    eng = _engine
    if eng is not None:
        eng.observe_window(n_events, lag_ms)
