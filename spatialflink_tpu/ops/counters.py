"""Kernel-level op counters + throughput meter.

The analog of the reference's per-operator Flink metrics: the
distance-computation counter (spatialObjects/Point.java:220-235) and the
Dropwizard throughput meters (Point.java:237-253), re-designed for the
batched execution model: instead of incrementing a counter inside the hot
loop (which on TPU would mean an extra device fetch per window), the
operator layer reports per-window tallies computed from HOST-side arrays
(flag tables, cell ids, validity) — zero device round trips, exact counts.

Disabled by default so the hot path pays nothing; ``enable()`` turns it
on. The NES reporter (mn/reporter.py) appends ``dist_comp_total`` to its
METRICS lines while enabled, and MetricsSink can emit an opcounter column.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict

import numpy as np


@dataclass
class KernelCounters:
    enabled: bool = False
    windows: int = 0
    points_in: int = 0
    candidate_lanes: int = 0  # lanes surviving the grid prune
    dist_computations: int = 0  # distance evaluations issued to the kernel
    started_at: float = field(default_factory=time.time)

    def record_window(self, points: int, candidates: int, dist_comps: int):
        if not self.enabled:
            return
        self.windows += 1
        self.points_in += int(points)
        self.candidate_lanes += int(candidates)
        self.dist_computations += int(dist_comps)

    def record_candidates(self, candidates: int, dist_comps: int):
        """Candidate/dist tallies reported separately from window/point
        counts (the SoA assembler owns the latter — see
        operators.base.soa_point_batches)."""
        if not self.enabled:
            return
        self.candidate_lanes += int(candidates)
        self.dist_computations += int(dist_comps)

    def throughput_eps(self, now: float | None = None) -> float:
        elapsed = max((now if now is not None else time.time()) - self.started_at, 1e-9)
        return self.points_in / elapsed

    def snapshot(self) -> Dict[str, float]:
        from spatialflink_tpu.mn.metrics import json_safe

        # json_safe at the boundary: tallies may arrive as numpy ints and
        # json.dumps of a snapshot must never raise.
        return json_safe({
            "windows": self.windows,
            "points_in": self.points_in,
            "candidate_lanes": self.candidate_lanes,
            "dist_computations": self.dist_computations,
            "throughput_eps": round(self.throughput_eps(), 2),
        })

    def reset(self):
        self.windows = 0
        self.points_in = 0
        self.candidate_lanes = 0
        self.dist_computations = 0
        self.started_at = time.time()


counters = KernelCounters()


def enable():
    counters.reset()
    counters.enabled = True


def disable():
    counters.enabled = False


def count_candidates(flags: np.ndarray, cells: np.ndarray, n: int) -> int:
    """Points whose cell flag is nonzero — the lanes the fused kernels
    evaluate distances for (everything else is masked by the prune)."""
    return int(np.count_nonzero(flags[np.minimum(cells[:n], len(flags) - 1)] > 0))


def count_join_candidates(
    grid, left_cells: np.ndarray, n_left: int, right_cells: np.ndarray,
    n_right: int, layers: int,
) -> int:
    """Exact candidate PAIR count of a grid-hash join window: for each
    in-grid left point, the number of in-grid right points in its
    (2·layers+1)² neighbor square — via a 2-D box-sum (integral image) over
    the right-side cell histogram, O(cells + n). This is what the
    reference's replicate+equi-join would enumerate (JoinQuery.java:73-137)
    and what the dense-bucket kernels evaluate (before per-cell caps)."""
    g = grid.n
    lc = left_cells[:n_left]
    rc = right_cells[:n_right]
    lc = lc[lc < grid.num_cells]
    rc = rc[rc < grid.num_cells]
    if not len(lc) or not len(rc):
        return 0
    hist = np.bincount(rc, minlength=grid.num_cells).reshape(g, g)
    integral = np.zeros((g + 1, g + 1), np.int64)
    integral[1:, 1:] = hist.cumsum(0).cumsum(1)

    xi, yi = np.divmod(lc, g)
    x1 = np.clip(xi - layers, 0, g)
    x2 = np.clip(xi + layers + 1, 0, g)
    y1 = np.clip(yi - layers, 0, g)
    y2 = np.clip(yi + layers + 1, 0, g)
    box = (
        integral[x2, y2] - integral[x1, y2] - integral[x2, y1]
        + integral[x1, y1]
    )
    return int(box.sum())
