"""Batched spatial-join kernels.

The reference joins two streams by replicating every query object to all of
its neighbor cells (a flatMap that multiplies the query stream by the
neighbor-cell count, JoinQuery.java:73-137), equi-joining on gridID over a
window, then distance-filtering (join/PointPointJoinQuery.java:124-183).

The TPU design inverts this: no replication. The query side is sorted by
cell once per window (a device sort); for each ordinary-side point we gather
the query points of its (2L+1)² neighbor cells through a CSR-style
searchsorted index and evaluate distances in one block — a grid-hash join
that rides the MXU instead of exploding the shuffle.

``cross_join_kernel`` is the RealTimeNaive path (constant-key cross join,
join/PointPointJoinQuery.java:186-243).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from spatialflink_tpu.ops.distances import point_point_distance


class JoinResult(NamedTuple):
    """For each left point: matching right-side indices within radius.

    ``pair_mask``: (N, K*cap) bool; ``right_index``: (N, K*cap) int32 index
    into the *original* right batch (-1 where masked); ``dist``: (N, K*cap);
    ``overflow``: () int32 — number of right points dropped because a cell
    exceeded ``cap`` (0 means the join is exact).
    """

    pair_mask: jnp.ndarray
    right_index: jnp.ndarray
    dist: jnp.ndarray
    overflow: jnp.ndarray


def sort_by_cell(cells: jnp.ndarray, n_total_cells: int):
    """Sort a batch by cell id; returns (sorted_cells, order).

    Invalid/out-of-grid entries must already carry cell id n_total_cells so
    they sort to the end.
    """
    order = jnp.argsort(cells)
    return cells[order], order.astype(jnp.int32)


def join_kernel(
    left_xy: jnp.ndarray,
    left_valid: jnp.ndarray,
    left_cell_xy_idx: jnp.ndarray,
    right_xy_sorted: jnp.ndarray,
    right_valid_sorted: jnp.ndarray,
    right_cells_sorted: jnp.ndarray,
    right_order: jnp.ndarray,
    neighbor_offsets: jnp.ndarray,
    grid_n: int,
    radius,
    cap: int,
) -> JoinResult:
    """Grid-hash join: left points vs cell-sorted right points.

    ``left_cell_xy_idx``: (N, 2) int32 (xi, yi) cell indices of left points;
    ``right_*_sorted``: right batch pre-sorted by flat cell id (see
    ``sort_by_cell``), ``right_order`` maps sorted position → original index;
    ``neighbor_offsets``: (K, 2) static (dx, dy) covering the candidate
    square (grid.neighbor_offsets — the same cells the reference's
    replication flatMap targets, JoinQuery.java:73-90); ``cap``: static max
    right points gathered per cell.
    """
    n = left_xy.shape[0]
    k = neighbor_offsets.shape[0]
    num_cells = grid_n * grid_n

    # Neighbor flat cell ids per left point: (N, K); invalid → num_cells+1
    # (past every real right cell, so searchsorted yields an empty span).
    nx = left_cell_xy_idx[:, 0:1] + neighbor_offsets[None, :, 0]
    ny = left_cell_xy_idx[:, 1:2] + neighbor_offsets[None, :, 1]
    in_grid = (nx >= 0) & (nx < grid_n) & (ny >= 0) & (ny < grid_n)
    ncell = jnp.where(in_grid, nx * grid_n + ny, num_cells + 1)

    start = jnp.searchsorted(right_cells_sorted, ncell.reshape(-1), side="left")
    end = jnp.searchsorted(right_cells_sorted, ncell.reshape(-1), side="right")
    start = start.reshape(n, k).astype(jnp.int32)
    end = end.reshape(n, k).astype(jnp.int32)
    span = end - start

    m = right_xy_sorted.shape[0]
    lane = jnp.arange(cap, dtype=jnp.int32)  # (cap,)
    pos = start[:, :, None] + lane[None, None, :]  # (N, K, cap)
    lane_ok = lane[None, None, :] < span[:, :, None]
    pos_c = jnp.clip(pos, 0, m - 1)

    # Gather x and y planes separately: a (N, K, cap, 2) gather would be
    # tiled to 128 lanes on its trailing dim-2 axis on TPU (64× HBM waste).
    cand_x = right_xy_sorted[:, 0][pos_c]  # (N, K, cap)
    cand_y = right_xy_sorted[:, 1][pos_c]
    cand_valid = right_valid_sorted[pos_c] & lane_ok
    dx = cand_x - left_xy[:, 0][:, None, None]
    dy = cand_y - left_xy[:, 1][:, None, None]
    d = jnp.sqrt(dx * dx + dy * dy)
    pair = cand_valid & left_valid[:, None, None] & (d <= radius)

    right_idx = jnp.where(cand_valid, right_order[pos_c], -1)
    # Only real (valid) left lanes claim overflow: padding lanes map to an
    # arbitrary cell (often the grid origin) and would otherwise report
    # phantom drops, breaking the overflow==0 exactness contract.
    overflow = jnp.sum(
        jnp.where(left_valid[:, None], jnp.maximum(span - cap, 0), 0)
    )
    return JoinResult(
        pair.reshape(n, k * cap),
        right_idx.reshape(n, k * cap),
        d.reshape(n, k * cap),
        overflow,
    )


class CompactJoinResult(NamedTuple):
    """Device-compacted join output: only the matching pairs cross the
    host boundary (the dense (N, K·cap) mask stays on device).

    ``left_index``/``right_index``: (max_pairs,) original-batch indices,
    -1 padding; ``dist``: (max_pairs,); ``count``: () true number of pairs
    (> max_pairs means truncation); ``overflow``: () cell-capacity drops.
    """

    left_index: jnp.ndarray
    right_index: jnp.ndarray
    dist: jnp.ndarray
    count: jnp.ndarray
    overflow: jnp.ndarray


def join_kernel_compact(
    left_xy: jnp.ndarray,
    left_valid: jnp.ndarray,
    left_cell_xy_idx: jnp.ndarray,
    right_xy_sorted: jnp.ndarray,
    right_valid_sorted: jnp.ndarray,
    right_cells_sorted: jnp.ndarray,
    right_order: jnp.ndarray,
    neighbor_offsets: jnp.ndarray,
    grid_n: int,
    radius,
    cap: int,
    max_pairs: int,
) -> CompactJoinResult:
    """Grid-hash join with on-device pair compaction (static ``max_pairs``).

    Fetching the dense pair mask costs O(N·K·cap) transfer per window;
    real joins are sparse, so compacting on device turns egress into
    O(max_pairs)."""
    res = join_kernel(
        left_xy, left_valid, left_cell_xy_idx,
        right_xy_sorted, right_valid_sorted, right_cells_sorted, right_order,
        neighbor_offsets, grid_n=grid_n, radius=radius, cap=cap,
    )
    n, kc = res.pair_mask.shape
    flat = res.pair_mask.reshape(-1)
    (hit_idx,) = jnp.nonzero(flat, size=max_pairs, fill_value=-1)
    found = hit_idx >= 0
    hit_c = jnp.maximum(hit_idx, 0)
    left_idx = jnp.where(found, (hit_c // kc).astype(jnp.int32), -1)
    right_idx = jnp.where(found, res.right_index.reshape(-1)[hit_c], -1)
    dist = jnp.where(found, res.dist.reshape(-1)[hit_c], jnp.inf)
    count = jnp.sum(flat.astype(jnp.int32))
    return CompactJoinResult(left_idx, right_idx, dist, count, res.overflow)


def join_window_compact(
    left_xy: jnp.ndarray,
    left_valid: jnp.ndarray,
    left_cell_xy_idx: jnp.ndarray,
    right_xy: jnp.ndarray,
    right_valid: jnp.ndarray,
    right_cells: jnp.ndarray,
    neighbor_offsets: jnp.ndarray,
    grid_n: int,
    radius,
    cap: int,
    max_pairs: int,
) -> CompactJoinResult:
    """One fused program for a whole join window: cell-sort the right side,
    grid-hash join, compact pairs — a single dispatch per window (separate
    eager sort/gather steps each cost a host round trip)."""
    order = jnp.argsort(right_cells).astype(jnp.int32)
    return join_kernel_compact(
        left_xy, left_valid, left_cell_xy_idx,
        right_xy[order], right_valid[order], right_cells[order], order,
        neighbor_offsets, grid_n=grid_n, radius=radius, cap=cap,
        max_pairs=max_pairs,
    )


def pallas_join_supported() -> bool:
    """True when the Pallas hit-extraction join can run compiled — TPU
    backends only (incl. the axon PJRT plugin). CPU uses the XLA bucketed
    kernel (faster there than the Pallas interpreter)."""
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:  # pragma: no cover
        return False


def bucketize_planes(xy, valid, cells, grid_n: int, cap: int):
    """Scatter a cell-assigned point batch into dense (grid_n, grid_n, cap)
    bucket planes: x, y, original-index (-1 = empty slot), plus the count of
    in-grid points dropped beyond ``cap`` (overflow).

    Rank within a cell comes from a stable argsort, so slot order is
    deterministic. Invalid/out-of-grid points (cell >= grid_n²) land in a
    discard slot and are neither stored nor counted as overflow, matching
    the reference's key semantics (out-of-grid objects never join,
    HelperClass.assignGridCellID)."""
    num_cells = grid_n * grid_n
    f_dtype = xy.dtype
    n = xy.shape[0]
    cells = jnp.where(valid, cells, num_cells)
    order = jnp.argsort(cells).astype(jnp.int32)
    sorted_cells = cells[order]
    # Rank within cell = position − first position of that cell.
    first = jnp.searchsorted(sorted_cells, sorted_cells, side="left")
    rank = (jnp.arange(n, dtype=jnp.int32) - first).astype(jnp.int32)
    ok = (sorted_cells < num_cells) & (rank < cap)
    overflow = jnp.sum((sorted_cells < num_cells) & (rank >= cap))
    slot = jnp.where(ok, sorted_cells * cap + rank, num_cells * cap)
    bx = jnp.zeros(num_cells * cap + 1, f_dtype).at[slot].set(xy[order, 0])
    by = jnp.zeros(num_cells * cap + 1, f_dtype).at[slot].set(xy[order, 1])
    bidx = jnp.full(num_cells * cap + 1, -1, jnp.int32).at[slot].set(order)
    shape = (grid_n, grid_n, cap)
    return (
        bx[:-1].reshape(shape), by[:-1].reshape(shape),
        bidx[:-1].reshape(shape), overflow,
    )


def join_window_bucketed(
    left_xy: jnp.ndarray,
    left_valid: jnp.ndarray,
    left_cells: jnp.ndarray,
    right_xy: jnp.ndarray,
    right_valid: jnp.ndarray,
    right_cells: jnp.ndarray,
    grid_n: int,
    layers: int,
    radius,
    cap_left: int,
    cap_right: int,
    max_pairs: int,
) -> CompactJoinResult:
    """Dense-bucket grid join — the TPU-native formulation.

    TPU gathers with computed indices run on the scalar core (~10⁸
    elements/s), so the searchsorted+gather join costs seconds per
    million-point window. Here BOTH sides scatter once into dense
    (grid_n, grid_n, cap) bucket planes and every neighbor lookup becomes a
    static ``jnp.roll`` shift — fully vectorized, no per-candidate gather.
    Per (2·layers+1)² shift: one (cells, capL, capR) distance block on the
    VPU, compacted with ``jnp.nonzero(size=max_pairs)``.

    ``left_cells``/``right_cells``: flat cell ids (num_cells = out-of-grid).
    Overflow counts points beyond a side's bucket capacity (result is exact
    iff overflow == 0, same contract as join_kernel).
    """
    num_cells = grid_n * grid_n
    span = 2 * layers + 1
    f_dtype = left_xy.dtype

    lx, ly, lidx, l_over = bucketize_planes(
        left_xy, left_valid, left_cells, grid_n, cap_left
    )
    rx, ry, ridx, r_over = bucketize_planes(
        right_xy, right_valid, right_cells, grid_n, cap_right
    )
    lvalid = lidx >= 0

    # One pair-mask plane per neighbor shift, stacked: (span², cells, capL,
    # capR) bools. Distances are NOT materialized — they're recomputed only
    # at the compacted hit positions.
    masks = []
    ii = jnp.arange(grid_n)
    for dx in range(-layers, layers + 1):
        for dy in range(-layers, layers + 1):
            sx = jnp.roll(rx, (-dx, -dy), axis=(0, 1))
            sy = jnp.roll(ry, (-dx, -dy), axis=(0, 1))
            sidx = jnp.roll(ridx, (-dx, -dy), axis=(0, 1))
            row_ok = (ii + dx >= 0) & (ii + dx < grid_n)
            col_ok = (ii + dy >= 0) & (ii + dy < grid_n)
            edge_ok = row_ok[:, None] & col_ok[None, :]
            ddx = lx[:, :, :, None] - sx[:, :, None, :]
            ddy = ly[:, :, :, None] - sy[:, :, None, :]
            d2 = ddx * ddx + ddy * ddy
            pair = (
                lvalid[:, :, :, None]
                & (sidx[:, :, None, :] >= 0)
                & edge_ok[:, :, None, None]
                & (d2 <= radius * radius)
            )
            masks.append(pair.reshape(-1))

    flat = jnp.concatenate(masks)  # (span² · cells · capL · capR,)
    count = jnp.sum(flat.astype(jnp.int32))
    (hit,) = jnp.nonzero(flat, size=max_pairs, fill_value=-1)
    found = hit >= 0
    hit_c = jnp.maximum(hit, 0)
    capl, capr = cap_left, cap_right
    block = num_cells * capl * capr
    shift_id = hit_c // block
    within = hit_c % block
    cell = within // (capl * capr)
    l_lane = (within // capr) % capl
    r_lane = within % capr
    # Decode shifted right slot back to the unshifted plane: the shift
    # mapped cell (i, j) → right cell (i+dx, j+dy).
    sdx = shift_id // span - layers
    sdy = shift_id % span - layers
    ci = cell // grid_n
    cj = cell % grid_n
    rcell = (ci + sdx) * grid_n + (cj + sdy)
    l_slot = cell * capl + l_lane
    r_slot = jnp.clip(rcell, 0, num_cells - 1) * capr + r_lane
    left_out = jnp.where(found, lidx.reshape(-1)[l_slot], -1)
    right_out = jnp.where(found, ridx.reshape(-1)[r_slot], -1)
    # Recompute distances at the (≤ max_pairs) hits only.
    dlx = lx.reshape(-1)[l_slot]
    dly = ly.reshape(-1)[l_slot]
    drx = rx.reshape(-1)[r_slot]
    dry = ry.reshape(-1)[r_slot]
    dist_out = jnp.where(
        found,
        jnp.sqrt((dlx - drx) ** 2 + (dly - dry) ** 2),
        jnp.asarray(jnp.inf, f_dtype),
    )
    return CompactJoinResult(left_out, right_out, dist_out, count, l_over + r_over)


def point_geometry_join_kernel(
    pxy: jnp.ndarray,
    pvalid: jnp.ndarray,
    gverts: jnp.ndarray,
    gev: jnp.ndarray,
    gvalid: jnp.ndarray,
    radius,
    polygonal: bool = True,
):
    """Point batch ⋈ geometry batch: (M, N) mask + distances.

    JTS semantics: distance 0 for points inside polygonal geometries. The
    batched form of join/PointPolygonJoinQuery's window loop. Note the grid
    prune of the reference is purely a shuffle optimization — the distance
    filter decides membership, so the dense masked evaluation returns the
    identical pair set.
    """
    from spatialflink_tpu.ops.polygon import points_in_polygon
    from spatialflink_tpu.ops.distances import point_polyline_distance

    def one_geom(verts, ev):
        d = point_polyline_distance(pxy, verts, ev)
        if polygonal:
            inside = points_in_polygon(pxy, verts, ev)
            d = jnp.where(inside, jnp.zeros((), d.dtype), d)
        return d

    d = jax.vmap(one_geom)(gverts, gev)  # (M, N)
    mask = (d <= radius) & pvalid[None, :] & gvalid[:, None]
    return mask, d


def geometry_geometry_join_kernel(
    averts: jnp.ndarray,
    aev: jnp.ndarray,
    avalid: jnp.ndarray,
    bverts: jnp.ndarray,
    bev: jnp.ndarray,
    bvalid: jnp.ndarray,
    radius,
    a_polygonal: bool = True,
    b_polygonal: bool = True,
):
    """Geometry ⋈ geometry: (L, R) mask + JTS-compatible distances
    (overlap/containment → 0 via geometry_pair_distance)."""
    from spatialflink_tpu.ops.range import geometry_pair_distance

    def pair(av, ae):
        return jax.vmap(
            lambda bv, be: geometry_pair_distance(
                av, ae, bv, be, a_polygonal, b_polygonal
            )
        )(bverts, bev)

    d = jax.vmap(pair)(averts, aev)  # (L, R)
    mask = (d <= radius) & avalid[:, None] & bvalid[None, :]
    return mask, d


def _onehot_select_preferred() -> bool:
    from spatialflink_tpu.ops.select import onehot_select_preferred

    return onehot_select_preferred()


def _block_candidates(block_bbox, gbbox, gvalid, radius, cand: int):
    """Block-level bbox pruning + per-block candidate compaction.

    ``block_bbox``: (NB, 4) minx,miny,maxx,maxy per block (±inf when the
    block is empty); ``gbbox``: (M, 4) per-geometry bboxes. A geometry is
    a candidate for a block iff the bboxes overlap after expanding the
    geometry's by ``radius``. Returns (gids (NB, cand) int32, cvalid
    (NB, cand) bool, overflow () int32) — overflow counts candidates
    dropped beyond ``cand`` (the caller's retry contract: exact iff 0).
    """
    gx0 = gbbox[:, 0] - radius
    gy0 = gbbox[:, 1] - radius
    gx1 = gbbox[:, 2] + radius
    gy1 = gbbox[:, 3] + radius
    ov = (
        (block_bbox[:, 0:1] <= gx1[None, :])
        & (block_bbox[:, 2:3] >= gx0[None, :])
        & (block_bbox[:, 1:2] <= gy1[None, :])
        & (block_bbox[:, 3:4] >= gy0[None, :])
        & gvalid[None, :]
    )  # (NB, M)
    # First-cand selection per row, ascending geometry id — strategy per
    # backend (identical results; see _onehot_select_preferred).
    m = ov.shape[1]
    if _onehot_select_preferred():
        from spatialflink_tpu.ops.select import first_k_onehot

        hit, ncand, overflow = first_k_onehot(ov, cand)  # (NB, M, cand)
        gids = jnp.sum(
            hit * jnp.arange(m, dtype=jnp.int32)[None, :, None], axis=1,
            dtype=jnp.int32,
        )  # (NB, cand)
    else:
        ncand = jnp.sum(ov.astype(jnp.int32), axis=1)
        overflow = jnp.sum(jnp.maximum(ncand - cand, 0))
        # top_k over the 0/1 mask: ones first, ties by ascending index —
        # the indices ARE the candidate geometry ids.
        _vals, gids = jax.lax.top_k(ov.astype(jnp.int32), cand)
        gids = gids.astype(jnp.int32)
    c_ids = jnp.arange(cand, dtype=jnp.int32)
    cvalid = c_ids[None, :] < jnp.minimum(ncand, cand)[:, None]
    return gids, cvalid, overflow


def _masked_block_bbox(x, y, valid):
    """(NB, B) coords + validity → (NB, 4) bbox over valid lanes."""
    big = jnp.asarray(jnp.finfo(x.dtype).max, x.dtype)
    return jnp.stack([
        jnp.min(jnp.where(valid, x, big), axis=1),
        jnp.min(jnp.where(valid, y, big), axis=1),
        jnp.max(jnp.where(valid, x, -big), axis=1),
        jnp.max(jnp.where(valid, y, -big), axis=1),
    ], axis=1)


class PrunedJoinPairs(NamedTuple):
    """Output of the pruned geometry joins: compacted pairs + the TWO
    exactness counters of the retry contract — ``cand_overflow`` (a tile
    had more than ``cand`` bbox-overlapping geometries; grow ``cand``)
    and ``pair_overflow`` (a single left item matched more than
    ``pair_cap`` geometries; grow ``pair_cap``). Exact iff both are 0.
    """

    left_index: jnp.ndarray
    right_index: jnp.ndarray
    dist: jnp.ndarray
    count: jnp.ndarray
    cand_overflow: jnp.ndarray
    pair_overflow: jnp.ndarray


def _compact_pairs(mask, dmat, borig, gids, pair_cap: int, max_pairs: int):
    """(NB, cand, B) mask/dists → flat pairs via PER-ITEM selection.

    A single jnp.nonzero over the full NB·cand·B domain costs ~9 ns/lane
    on TPU (~86 ms at 131k-point windows) — the same pathology the
    Pallas join avoids. Instead: a prefix-sum one-hot select keeps up to
    ``pair_cap`` matches per left item (domain NB·cand·B, but pure VPU
    compare/select — no serialization), then the final nonzero runs over
    only N·pair_cap lanes (cand/pair_cap-fold smaller). Items matching
    more than ``pair_cap`` geometries report pair_overflow (retry).
    Returns (left, right, dist, count, pair_overflow).
    """
    b = mask.shape[2]
    # Per-item selection along the candidate axis (moved last for the
    # shared selection primitives).
    mask_t = jnp.moveaxis(mask, 1, -1)  # (NB, B, cand)
    dmat_t = jnp.moveaxis(dmat, 1, -1)  # (NB, B, cand)
    slots = jnp.arange(pair_cap, dtype=jnp.int32)
    if _onehot_select_preferred():
        from spatialflink_tpu.ops.select import first_k_onehot

        hit, per_item, pair_overflow = first_k_onehot(mask_t, pair_cap)
        # hit: (NB, B, cand, pair_cap); one-hot sums select exactly one
        # term — bit-exact for the distance.
        gsel = jnp.sum(
            hit * gids[:, None, :, None], axis=2, dtype=jnp.int32
        )  # (NB, B, pair_cap)
        dsel = jnp.sum(
            jnp.where(hit, dmat_t[:, :, :, None],
                      jnp.zeros((), dmat.dtype)),
            axis=2,
        )
    else:
        # CPU & friends: top_k over the 0/1 mask (the one-hot tensor is
        # measurably slower than the vectorized sort on XLA:CPU — same
        # per-backend gate as ops/knn.py's compact digest; identical
        # selection, ties broken by ascending candidate slot).
        per_item = jnp.sum(mask_t.astype(jnp.int32), axis=-1)
        pair_overflow = jnp.sum(jnp.maximum(per_item - pair_cap, 0))
        _vals, csel = jax.lax.top_k(mask_t.astype(jnp.int8), pair_cap)
        gsel = jnp.take_along_axis(
            jnp.broadcast_to(gids[:, None, :], mask_t.shape), csel, axis=-1
        ).astype(jnp.int32)
        dsel = jnp.take_along_axis(dmat_t, csel, axis=-1)
    svalid = (
        slots[None, None, :] < jnp.minimum(per_item, pair_cap)[:, :, None]
    )  # (NB, B, pair_cap)

    flat = svalid.reshape(-1)
    count = jnp.sum(per_item, dtype=jnp.int32)
    (hit_i,) = jnp.nonzero(flat, size=max_pairs, fill_value=-1)
    found = hit_i >= 0
    h = jnp.maximum(hit_i, 0)
    bi = h // (b * pair_cap)
    li = (h // pair_cap) % b
    left = jnp.where(found, borig[bi, li], -1)
    right = jnp.where(found, gsel.reshape(-1)[h], -1)
    dist = jnp.where(found, dsel.reshape(-1)[h],
                     jnp.asarray(jnp.inf, dmat.dtype))
    return left, right, dist, count, pair_overflow


def point_geometry_join_pruned_kernel(
    pxy: jnp.ndarray,
    pvalid: jnp.ndarray,
    gverts: jnp.ndarray,
    gev: jnp.ndarray,
    gvalid: jnp.ndarray,
    gbbox: jnp.ndarray,
    radius,
    polygonal: bool,
    block: int,
    cand: int,
    max_pairs: int,
    pair_cap: int = 8,
    approx: bool = False,
) -> PrunedJoinPairs:
    """Grid-pruned point ⋈ geometry join, device-extracted.

    The dense kernel (point_geometry_join_kernel) evaluates every
    (point, geometry) V-vertex distance — O(N·M·V). This is the device-
    side form of the reference's gridIDsSet replication
    (join/JoinQuery.java:73-137) re-designed for TPU:

      1. sort points by grid cell (spatial locality — one device argsort),
      2. split into ``block``-point tiles; per tile, a 4-compare bbox test
         against every geometry's radius-expanded bbox (O(N/B · M), cheap),
      3. compact ≤ ``cand`` candidate geometries per tile (lax.top_k),
      4. exact V-vertex distances tile × candidates — O(N·cand·V), a
         M/cand-fold cut,
      5. per-item selection (≤ ``pair_cap`` matches per point) + one
         small jnp.nonzero so only pairs cross the host boundary.

    Exact iff BOTH overflow counters are 0 (PrunedJoinPairs: grow
    ``cand`` on cand_overflow — at cand == M the prune is a no-op — and
    ``pair_cap`` on pair_overflow — at pair_cap == cand a point cannot
    exceed it). Pair set identical to the dense kernel (parity test
    tests/test_join_pruned.py); JTS semantics kept (inside polygonal → 0).

    The caller orders the points for spatial locality HOST-side (numpy
    argsort by cell, ~1 ms at 131k and overlapped with device work — a
    device argsort measured 13 ms on v5e, 2.5× the rest of this kernel);
    ``left_index`` refers to input positions (map back through the host
    order). Locality only affects pruning EFFICIENCY, never correctness.
    """
    from spatialflink_tpu.ops.distances import point_polyline_distance
    from spatialflink_tpu.ops.polygon import points_in_polygon

    # Static clamps: cand cannot exceed the geometry count, pair_cap
    # cannot exceed cand (an item's matches come from its tile's cand
    # list) — unclamped values would crash only on the top_k backends.
    # Clamp keys on gbbox so approximate callers may pass dummy verts.
    cand = min(cand, gbbox.shape[0])
    pair_cap = min(pair_cap, cand)
    n = pxy.shape[0]
    nb = -(-n // block)
    npad = nb * block
    pad = npad - n
    order = jnp.arange(n, dtype=jnp.int32)
    sx = jnp.pad(pxy, ((0, pad), (0, 0)))
    sv = jnp.pad(pvalid, (0, pad))
    so = jnp.pad(order, (0, pad), constant_values=-1)
    bx = sx.reshape(nb, block, 2)
    bvalid = sv.reshape(nb, block)
    borig = so.reshape(nb, block)

    bbox = _masked_block_bbox(bx[:, :, 0], bx[:, :, 1], bvalid)
    gids, cvalid, overflow = _block_candidates(
        bbox, gbbox, gvalid, radius, cand
    )

    if approx:
        # Approximate mode: per-pair distance = point → candidate's
        # BOUNDING BOX (ops/distances.py:bbox_point_min_distance), the
        # device form of the reference's approximateQuery branches
        # (join/PolygonPointJoinQuery.java, getPoint*BBoxMinEuclidean-
        # Distance). The operator also routes the point-ordinary
        # "emit all grid candidates" semantics here by passing
        # CELL-INDEX coordinates + layer-expanded cell boxes with
        # radius 0 (see join_query._PointGeometryJoinQuery).
        from spatialflink_tpu.ops.distances import bbox_point_min_distance

        cgb = gbbox[gids]  # (NB, cand, 4)
        dmat = bbox_point_min_distance(
            bx[:, None, :, :], cgb[:, :, None, :]
        )  # (NB, cand, block)
    else:
        cgv = gverts[gids]  # (NB, cand, V, 2)
        cge = gev[gids]  # (NB, cand, V-1)

        def one_geom(bxy, verts, ev):
            d = point_polyline_distance(bxy, verts, ev)
            if polygonal:
                inside = points_in_polygon(bxy, verts, ev)
                d = jnp.where(inside, jnp.zeros((), d.dtype), d)
            return d

        dmat = jax.vmap(
            lambda bxy, gv, ge: jax.vmap(
                lambda v, e: one_geom(bxy, v, e)
            )(gv, ge)
        )(bx, cgv, cge)  # (NB, cand, block)

    mask = (
        (dmat <= radius)
        & bvalid[:, None, :]
        & cvalid[:, :, None]
    )
    left, right, dist, count, pair_over = _compact_pairs(
        mask, dmat, borig, gids, pair_cap, max_pairs
    )
    return PrunedJoinPairs(left, right, dist, count, overflow, pair_over)


def geometry_geometry_join_pruned_kernel(
    averts: jnp.ndarray,
    aev: jnp.ndarray,
    avalid: jnp.ndarray,
    abbox: jnp.ndarray,
    bverts: jnp.ndarray,
    bev: jnp.ndarray,
    bvalid: jnp.ndarray,
    bbbox: jnp.ndarray,
    radius,
    a_polygonal: bool,
    b_polygonal: bool,
    block: int,
    cand: int,
    max_pairs: int,
    pair_cap: int = 8,
    approx: bool = False,
) -> PrunedJoinPairs:
    """Grid-pruned geometry ⋈ geometry join, device-extracted.

    Same tile/candidate scheme as the point version: the caller orders
    the left side for locality HOST-side (the operator sorts by quantized
    bbox center — join_query._GeometryGeometryJoinQuery._window_pairs,
    the single home of that key logic); tile bboxes are unioned over
    member bboxes. ``left_index`` refers to input positions. Exact iff
    BOTH ``cand_overflow`` AND ``pair_overflow`` are 0 (PrunedJoinPairs
    retry contract — grow ``cand`` / ``pair_cap`` respectively); parity
    with geometry_geometry_join_kernel incl. overlap→0 distances
    (tests/test_join_pruned.py).
    """
    from spatialflink_tpu.ops.range import geometry_pair_distance

    cand = min(cand, bbbox.shape[0])  # see point kernel's clamps
    pair_cap = min(pair_cap, cand)
    la = averts.shape[0]
    nb = -(-la // block)
    npad = nb * block
    order = jnp.arange(la, dtype=jnp.int32)
    pad = npad - la

    s_bbox = jnp.pad(abbox, ((0, pad), (0, 0)))
    sv = jnp.pad(avalid, (0, pad))
    so = jnp.pad(order, (0, pad), constant_values=-1)
    t_bbox = s_bbox.reshape(nb, block, 4)
    bval = sv.reshape(nb, block)
    borig = so.reshape(nb, block)

    big = jnp.asarray(jnp.finfo(t_bbox.dtype).max, t_bbox.dtype)
    tile_bbox = jnp.stack([
        jnp.min(jnp.where(bval, t_bbox[:, :, 0], big), axis=1),
        jnp.min(jnp.where(bval, t_bbox[:, :, 1], big), axis=1),
        jnp.max(jnp.where(bval, t_bbox[:, :, 2], -big), axis=1),
        jnp.max(jnp.where(bval, t_bbox[:, :, 3], -big), axis=1),
    ], axis=1)
    gids, cvalid, overflow = _block_candidates(
        tile_bbox, bbbox, bvalid, radius, cand
    )

    if approx:
        # Approximate mode: per-pair distance = bbox ↔ bbox min distance
        # (the reference's getBBoxBBoxMinEuclideanDistance branches in
        # every geometry-geometry join, e.g.
        # join/LineStringLineStringJoinQuery.java:173-180).
        from spatialflink_tpu.ops.distances import bbox_bbox_min_distance

        cbb = bbbox[gids]  # (NB, cand, 4)
        dmat = bbox_bbox_min_distance(
            t_bbox[:, None, :, :], cbb[:, :, None, :]
        )  # (NB, cand, block)
    else:
        sav = jnp.pad(averts, ((0, pad), (0, 0), (0, 0)))
        sae = jnp.pad(aev, ((0, pad), (0, 0)))
        tav = sav.reshape(nb, block, averts.shape[1], 2)
        tae = sae.reshape(nb, block, aev.shape[1])
        cbv = bverts[gids]  # (NB, cand, Vb, 2)
        cbe = bev[gids]

        def pair_d(av, ae, bv, be):
            return geometry_pair_distance(av, ae, bv, be, a_polygonal,
                                          b_polygonal)

        # (NB, cand, block): for each tile, candidate × member distances.
        dmat = jax.vmap(
            lambda avs, aes, bvs, bes: jax.vmap(
                lambda bv, be: jax.vmap(
                    lambda av, ae: pair_d(av, ae, bv, be)
                )(avs, aes)
            )(bvs, bes)
        )(tav, tae, cbv, cbe)

    mask = (
        (dmat <= radius)
        & bval[:, None, :]
        & cvalid[:, :, None]
    )
    left, right, dist, count, pair_over = _compact_pairs(
        mask, dmat, borig, gids, pair_cap, max_pairs
    )
    return PrunedJoinPairs(left, right, dist, count, overflow, pair_over)


def cross_join_kernel(
    left_xy: jnp.ndarray,
    left_valid: jnp.ndarray,
    right_xy: jnp.ndarray,
    right_valid: jnp.ndarray,
    radius,
) -> JoinResult:
    """Naive all-pairs join (the reference's RealTimeNaive mode,
    join/PointPointJoinQuery.java:186-243). (N, M) distance matrix, masked."""
    d = point_point_distance(left_xy[:, None, :], right_xy[None, :, :])
    pair = left_valid[:, None] & right_valid[None, :] & (d <= radius)
    m = right_xy.shape[0]
    right_idx = jnp.broadcast_to(jnp.arange(m, dtype=jnp.int32)[None, :], d.shape)
    return JoinResult(pair, right_idx, d, jnp.zeros((), jnp.int32))
