"""Pallas TPU fused wire→candidate extraction for the kNN pane digest.

The XLA compact digest (ops/knn.py:_digest_from_point_dists_compact)
materializes either a full per-pane sort (top_k) or an ~N·per_block
one-hot tensor (blocked select) just to find the few-thousand in-radius
points of a 500k-point slide. This kernel walks the wire planes ONCE:
dequantize → distance → radius mask on the VPU, then an argmin-peel
while-loop extracts each hit in time ∝ matches (the pallas_join
extraction idiom — one-hot lane accumulate + 128-lane row flush; scalar
VMEM stores don't exist on TPU). The segment-min digest over the ≤
``max_cand`` compacted hits stays in (tested) XLA.

BASELINE.md roofline: after the r4 layout/donation levers the blocked
select's one-hot is the largest remaining term (~8M lanes/slide); this
kernel replaces it with one streaming pass (~3 MB wire read) + O(hits)
peeling — the "select-while-dequantizing" lever.

Exactness contract: ``count`` > ``max_cand`` means truncation — the
caller must fall back to the XLA digest (same retry family as the
compact path's ``cand``). Distances are the same explicit
mul-add/sqrt f32 ops as the headline step; XLA's FMA fusion may differ
by ≤1 ulp from Mosaic's, so the bench self-checks one slide against the
XLA path before trusting the kernel (bench.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# (max_cand // 128) × 128 rows of dist/oid/idx stay VMEM-resident: 12 B
# per slot, same budget math as pallas_join.
PALLAS_DIGEST_MAX_CAND = 16_384


def pallas_digest_supported() -> bool:
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:  # pragma: no cover
        return False


def _extract_kernel(
    consts_ref,  # (1, 8) f32: radius, sx, ox, qx, sy, oy, qy, n_valid
    xq_ref, yq_ref, oid_ref,  # (1, BLK) i32 rows
    outd_ref, outoid_ref, outidx_ref, cnt_ref,
    sm, accd, acco, acci,
    blk: int, max_cand: int,
):
    i = pl.program_id(0)
    max_rows = max_cand // 128
    lane_iota = jax.lax.broadcasted_iota(jnp.int32, (1, 128), 1)

    @pl.when(i == 0)
    def _init():
        outd_ref[:] = jnp.full((max_rows, 128), jnp.inf, jnp.float32)
        outoid_ref[:] = jnp.zeros((max_rows, 128), jnp.int32)
        outidx_ref[:] = jnp.full((max_rows, 128), -1, jnp.int32)
        sm[0] = 0  # total hits
        sm[1] = 0  # flushed count (multiple of 128)

    radius = consts_ref[0, 0]
    sx = consts_ref[0, 1]
    ox = consts_ref[0, 2]
    qx = consts_ref[0, 3]
    sy = consts_ref[0, 4]
    oy = consts_ref[0, 5]
    qy = consts_ref[0, 6]

    xf = xq_ref[0, :].astype(jnp.float32) * sx + ox
    yf = yq_ref[0, :].astype(jnp.float32) * sy + oy
    dx = xf - qx
    dy = yf - qy
    # Same predicate as the XLA digest (sqrt THEN compare, knn.py) — a
    # d² <= r² test would classify radius-boundary points differently
    # within f32 rounding and break the set-parity self-check.
    dist = jnp.sqrt(dx * dx + dy * dy).reshape(1, blk)
    # consts slot 7 carries the logical point count (f32, exact for
    # counts < 2^24 — far above any pane size): positions >= n_valid are
    # bucket padding from a variable-size pane and can never match.
    # The wrapper always writes this slot.
    n_valid = consts_ref[0, 7]
    gidx = (jnp.float32(i * blk)
            + jax.lax.broadcasted_iota(jnp.float32, (1, blk), 1))
    mask = (dist <= radius) & (gidx < n_valid)
    nhit = jnp.sum(mask.astype(jnp.int32))

    @pl.when(nhit > 0)
    def _extract():
        code_iota = jax.lax.broadcasted_iota(jnp.int32, (1, blk), 1)
        oid_row = oid_ref[0, :].reshape(1, blk)
        big = blk

        def cond(st):
            return st[1] > 0

        def body(st):
            last, remaining = st
            code = jnp.min(jnp.where(mask & (code_iota > last),
                                     code_iota, big))
            hot = code_iota == code
            dval = jnp.sum(jnp.where(hot, dist, 0.0))
            oval = jnp.sum(jnp.where(hot, oid_row, 0))
            s = sm[0]
            base = sm[1]
            lane = s - base
            lane_hot = lane_iota == lane
            accd[:] = jnp.where(lane_hot, dval.astype(jnp.float32), accd[:])
            acco[:] = jnp.where(lane_hot, oval, acco[:])
            acci[:] = jnp.where(lane_hot, i * blk + code, acci[:])
            sm[0] = s + 1

            @pl.when((lane == 127) & (base // 128 < max_rows))
            def _flush():
                row = base // 128
                outd_ref[pl.ds(row, 1), :] = accd[:]
                outoid_ref[pl.ds(row, 1), :] = acco[:]
                outidx_ref[pl.ds(row, 1), :] = acci[:]
                sm[1] = base + 128

            return (code, remaining - 1)

        jax.lax.while_loop(cond, body, (jnp.int32(-1), nhit))

    @pl.when(i == pl.num_programs(0) - 1)
    def _fin():
        cnt = sm[0]
        base = sm[1]

        @pl.when((cnt > base) & (base // 128 < max_rows))
        def _partial_flush():
            ok = lane_iota < (cnt - base)
            row = base // 128
            outd_ref[pl.ds(row, 1), :] = jnp.where(ok, accd[:], jnp.inf)
            outoid_ref[pl.ds(row, 1), :] = jnp.where(ok, acco[:], 0)
            outidx_ref[pl.ds(row, 1), :] = jnp.where(ok, acci[:], -1)

        cnt_ref[0, 0] = cnt


@functools.partial(
    jax.jit,
    static_argnames=("blk", "max_cand", "interpret"),
)
def wire_candidates_pallas(
    xq: jnp.ndarray,
    yq: jnp.ndarray,
    oid: jnp.ndarray,
    consts: jnp.ndarray,
    blk: int = 2048,
    max_cand: int = PALLAS_DIGEST_MAX_CAND,
    interpret: bool = False,
    n_valid=None,
):
    """Wire planes → compacted in-radius (dist, oid, index) + count.

    ``xq``/``yq``/``oid``: (N,) int32 (u16 wire values widened by XLA —
    Mosaic-friendly); ``consts``: (1, 8) f32 [radius, sx, ox, qx, sy,
    oy, qy, n_valid]. N is padded to a ``blk`` multiple internally
    (padding lanes sit at an astronomical distance). ``n_valid`` (traced
    scalar, default N) marks the logical point count when the caller
    bucket-padded a variable-size pane — positions past it never match;
    slot 7 of ``consts`` is overwritten with it either way. ``count`` >
    ``max_cand`` ⇒ truncated (caller falls back); indices are original
    positions, -1 padding.
    """
    n = xq.shape[0]
    if n_valid is None:
        n_valid = n
    consts = consts.at[0, 7].set(jnp.asarray(n_valid, jnp.float32))
    pad = (-n) % blk
    if pad:
        # Padding lanes carry a coordinate far outside any grid extent
        # (2^30 quantized units): dequantized distance is astronomically
        # large, so they can never pass the radius mask — the headline
        # SLIDE (500k) need not divide by blk.
        far = jnp.int32(1 << 30)
        xq = jnp.concatenate([xq, jnp.full((pad,), far, jnp.int32)])
        yq = jnp.concatenate([yq, jnp.full((pad,), far, jnp.int32)])
        oid = jnp.concatenate([oid, jnp.zeros((pad,), jnp.int32)])
        n = n + pad
    nb = n // blk
    max_rows = max_cand // 128
    grid = (nb,)
    row = lambda a: a.reshape(nb, 1, blk)
    outd, outoid, outidx, cnt = pl.pallas_call(
        functools.partial(_extract_kernel, blk=blk, max_cand=max_cand),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 8), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, blk), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, blk), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, blk), lambda i: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((max_rows, 128), lambda i: (0, 0)),
            pl.BlockSpec((max_rows, 128), lambda i: (0, 0)),
            pl.BlockSpec((max_rows, 128), lambda i: (0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((max_rows, 128), jnp.float32),
            jax.ShapeDtypeStruct((max_rows, 128), jnp.int32),
            jax.ShapeDtypeStruct((max_rows, 128), jnp.int32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.SMEM((2,), jnp.int32),
            pltpu.VMEM((1, 128), jnp.float32),
            pltpu.VMEM((1, 128), jnp.int32),
            pltpu.VMEM((1, 128), jnp.int32),
        ],
        interpret=interpret,
    )(consts, row(xq), row(yq), row(oid))
    return (
        outd.reshape(-1), outoid.reshape(-1), outidx.reshape(-1),
        cnt[0, 0],
    )


def digest_from_candidates(d, o, idx, num_segments: int):
    """Compacted (dist, oid, index) candidates → KnnPaneDigest — ONE
    home for the candidate segment-min reduction (shared by
    wire_digest_pallas and bench.py's pallas step; the sentinel clamp
    and representative tie-break must stay bit-identical between the
    library path and the measured path)."""
    from spatialflink_tpu.ops.knn import KnnPaneDigest

    valid = idx >= 0
    big = jnp.asarray(jnp.finfo(jnp.float32).max, jnp.float32)
    int_big = jnp.iinfo(jnp.int32).max
    dm = jnp.where(valid, d, big)
    om = jnp.where(valid, o, 0)
    sm = jnp.minimum(
        jax.ops.segment_min(dm, om, num_segments=num_segments), big
    )
    win = valid & (dm == sm[om])
    rep = jax.ops.segment_min(
        jnp.where(win, idx, int_big), om, num_segments=num_segments
    )
    return KnnPaneDigest(sm, rep)


def wire_digest_pallas(
    wire_s: jnp.ndarray,
    query_xy: jnp.ndarray,
    scale,
    origin,
    radius,
    num_segments: int,
    max_cand: int = PALLAS_DIGEST_MAX_CAND,
    interpret: bool = False,
    n_valid=None,
):
    """(3, N) u16 wire planes → KnnPaneDigest via the fused extraction.

    Returns (digest, count): exact iff ``count <= max_cand`` — the
    caller owns the fallback (ops/wire_knn.py wraps this with the
    in-program lax.cond fallback; bench.py additionally self-checks one
    slide and falls back to the XLA step wholesale)."""
    consts = jnp.asarray(
        [[radius, scale[0], origin[0], query_xy[0],
          scale[1], origin[1], query_xy[1], 0.0]], jnp.float32,
    )
    d, o, idx, cnt = wire_candidates_pallas(
        wire_s[0].astype(jnp.int32), wire_s[1].astype(jnp.int32),
        wire_s[2].astype(jnp.int32), consts,
        max_cand=max_cand, interpret=interpret, n_valid=n_valid,
    )
    return digest_from_candidates(d, o, idx, num_segments), cnt
