"""Point-in-polygon and point↔polygon distance kernels.

The reference delegates polygon predicates to JTS
(``point.distance(polygon)`` — DistanceFunctions.java:33-36 — returns 0 for
interior points, else the min boundary distance; containment via
PreparedGeometry in the SNCB layer, CRSUtils.java:19-56). Here polygons are
packed once on the host into padded edge arrays and both predicates are
single fused XLA ops over a point batch.

Packed polygon layout (see ``pack_rings``):
  - ``verts``: (V, 2) vertex array; rings are laid out back to back, each
    ring closed (first vertex repeated last).
  - ``edge_valid``: (V-1,) bool — True for real ring edges, False for the
    seam between consecutive rings and for padding.
Holes need no special casing: even-odd crossing counting over all rings
(exterior + holes) is the standard ray-cast containment with holes.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np
import jax.numpy as jnp

from spatialflink_tpu.ops.distances import point_polyline_distance


def pack_rings(
    rings: Sequence[np.ndarray], pad_to: int | None = None, dtype=np.float64
) -> Tuple[np.ndarray, np.ndarray]:
    """Pack polygon rings (or polyline parts) into (verts, edge_valid).

    Each ring is an (Ri, 2) array; rings are closed here if not already.
    Padding vertices repeat the last real vertex with ``edge_valid`` False,
    so padded shapes never change results.
    """
    closed = []
    for r in rings:
        r = np.asarray(r, dtype=dtype)
        if r.ndim != 2 or r.shape[1] != 2:
            raise ValueError("each ring must be (R, 2)")
        if not np.array_equal(r[0], r[-1]):
            r = np.concatenate([r, r[:1]], axis=0)
        closed.append(r)
    verts = np.concatenate(closed, axis=0)
    edge_valid = np.ones(len(verts) - 1, bool)
    # Invalidate seam edges between consecutive rings.
    pos = 0
    for r in closed[:-1]:
        pos += len(r)
        edge_valid[pos - 1] = False
    if pad_to is not None:
        if pad_to < len(verts):
            raise ValueError(f"pad_to={pad_to} < {len(verts)} vertices")
        pad = pad_to - len(verts)
        if pad:
            verts = np.concatenate([verts, np.repeat(verts[-1:], pad, axis=0)])
            edge_valid = np.concatenate([edge_valid, np.zeros(pad, bool)])
    return verts, edge_valid


def pack_polyline(
    parts: Sequence[np.ndarray], pad_to: int | None = None, dtype=np.float64
) -> Tuple[np.ndarray, np.ndarray]:
    """Pack open polyline part(s) into (verts, edge_valid) — no closing."""
    parts = [np.asarray(p, dtype=dtype) for p in parts]
    verts = np.concatenate(parts, axis=0)
    edge_valid = np.ones(len(verts) - 1, bool)
    pos = 0
    for p in parts[:-1]:
        pos += len(p)
        edge_valid[pos - 1] = False
    if pad_to is not None:
        if pad_to < len(verts):
            raise ValueError(f"pad_to={pad_to} < {len(verts)} vertices")
        pad = pad_to - len(verts)
        if pad:
            verts = np.concatenate([verts, np.repeat(verts[-1:], pad, axis=0)])
            edge_valid = np.concatenate([edge_valid, np.zeros(pad, bool)])
    return verts, edge_valid


def points_in_polygon(
    p: jnp.ndarray, verts: jnp.ndarray, edge_valid: jnp.ndarray
) -> jnp.ndarray:
    """Even-odd ray-cast containment for a batch of points.

    ``p``: (N, 2) → (N,) bool. Counts crossings of a +x ray against every
    valid edge of every ring; an odd count means inside (holes subtract
    naturally). Points exactly on a boundary edge may land either way, same
    as JTS's non-boundary-inclusive ``contains``.
    """
    x, y = p[:, 0:1], p[:, 1:2]  # (N, 1)
    x1, y1 = verts[:-1, 0][None, :], verts[:-1, 1][None, :]  # (1, E)
    x2, y2 = verts[1:, 0][None, :], verts[1:, 1][None, :]
    # Half-open vertical span test avoids double-counting shared vertices.
    spans = (y1 > y) != (y2 > y)
    dy = y2 - y1
    t = jnp.where(dy != 0, (y - y1) / jnp.where(dy != 0, dy, 1), 0.0)
    x_int = x1 + t * (x2 - x1)
    crossings = spans & (x < x_int) & edge_valid[None, :]
    return jnp.sum(crossings.astype(jnp.int32), axis=1) % 2 == 1


def point_polygon_distance(
    p: jnp.ndarray, verts: jnp.ndarray, edge_valid: jnp.ndarray
) -> jnp.ndarray:
    """JTS-compatible point→polygon distance: 0 inside, else min edge dist.

    Batched replacement for ``point.distance(polygon)``
    (DistanceFunctions.java:33-36) — the hot op of PointPolygonRangeQuery's
    window loop (range/PointPolygonRangeQuery.java:37-101).
    """
    inside = points_in_polygon(p, verts, edge_valid)
    d = point_polyline_distance(p, verts, edge_valid)
    return jnp.where(inside, jnp.zeros((), d.dtype), d)


def signed_area(ring: np.ndarray) -> float:
    """Shoelace signed area of a host-side ring (CCW positive)."""
    r = np.asarray(ring, np.float64)  # sfcheck: ok=trace-hygiene -- host-side geometry prep (docstring); rings are concrete numpy, never traced
    x, y = r[:, 0], r[:, 1]
    return 0.5 * float(np.sum(x * np.roll(y, -1) - np.roll(x, -1) * y))
