"""Trajectory segment kernels.

The reference's trajectory operators keep per-objID state in Flink keyed
state (ValueState/MapState) and iterate per record
(tStats/TStatsQuery.java:44-145, tAggregate/TAggregateQuery.java:53-250).
Here a window's points are sorted by (objID, ts) once on the host and every
per-trajectory statistic is a segment reduction over the interned objID —
one fused XLA program per window instead of per-record state mutation.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from spatialflink_tpu.ops.distances import point_point_distance


class TrajStats(NamedTuple):
    """Per-segment (per-objID) trajectory statistics for a window.

    Mirrors the output tuple of TStatsQuery (objID, spatialLength,
    temporalLength, spatialLength/temporalLength — TStatsQuery.java:137-144).
    """

    spatial_length: jnp.ndarray  # (U,)
    temporal_length: jnp.ndarray  # (U,) ms
    count: jnp.ndarray  # (U,) points per trajectory
    avg_speed: jnp.ndarray  # (U,) spatial/temporal (0 where temporal == 0)


def traj_stats_kernel(
    xy: jnp.ndarray,
    ts: jnp.ndarray,
    oid: jnp.ndarray,
    valid: jnp.ndarray,
    num_segments: int,
) -> TrajStats:
    """Inputs must be pre-sorted by (oid, ts); padding lanes carry
    oid = num_segments - 1 … any valid id with valid=False (they're masked).

    Consecutive-point distances within each trajectory are summed per
    segment; out-of-order duplicates (equal timestamps) contribute like the
    reference's window variant, which walks points in sorted order.
    """
    same_traj = (oid[1:] == oid[:-1]) & valid[1:] & valid[:-1]
    seg_d = point_point_distance(xy[1:], xy[:-1])
    seg_t = (ts[1:] - ts[:-1]).astype(seg_d.dtype)
    contrib_d = jnp.where(same_traj, seg_d, 0)
    contrib_t = jnp.where(same_traj, seg_t, 0)
    # Segment sums keyed by the *later* point's trajectory.
    spatial = jax.ops.segment_sum(contrib_d, oid[1:], num_segments=num_segments)
    temporal = jax.ops.segment_sum(contrib_t, oid[1:], num_segments=num_segments)
    count = jax.ops.segment_sum(
        valid.astype(jnp.int32), oid, num_segments=num_segments
    )
    speed = jnp.where(temporal > 0, spatial / jnp.where(temporal > 0, temporal, 1), 0.0)
    return TrajStats(spatial, temporal, count, speed)


def traj_stats_sorted_fused(
    xy: jnp.ndarray,
    ts: jnp.ndarray,
    oid: jnp.ndarray,
    valid: jnp.ndarray,
    num_segments: int,
) -> TrajStats:
    """traj_stats over an UNsorted batch: the (oid, ts) sort happens on
    device (lexsort) so SoA windows go straight from the assembler into one
    fused program — no host-side Python sort of event objects
    (the round-1 throughput cap, TStatsQuery.java:148-189's window walk).
    Invalid lanes sort to the end (oid forced past every real id)."""
    oid_sort = jnp.where(valid, oid, num_segments)
    order = jnp.lexsort((ts, oid_sort))
    return traj_stats_kernel(
        xy[order], ts[order], oid[order], valid[order],
        num_segments=num_segments,
    )


class TrajPairs(NamedTuple):
    """Deduped trajectory-pair join output (device-compacted).

    ``pair_key``: (max_tpairs,) int32 — left_local * num_right + right_local,
    -1 padding; ``dist``: (max_tpairs,) min point distance of the pair;
    ``count``: () number of distinct qualifying pairs (> max_tpairs means
    the budget must grow).
    """

    pair_key: jnp.ndarray
    dist: jnp.ndarray
    count: jnp.ndarray


def traj_pair_dedup_kernel(
    left_index: jnp.ndarray,
    right_index: jnp.ndarray,
    dist: jnp.ndarray,
    left_local: jnp.ndarray,
    right_local: jnp.ndarray,
    num_left: int,
    num_right: int,
    max_tpairs: int,
) -> TrajPairs:
    """Compact join pairs → distinct (trajectory, trajectory) pairs with
    min distance, entirely on device.

    Replaces the reference's per-record dedup map (latest pair per
    (traj, queryTraj), tJoin/TJoinQuery.java:60-154) — and round 1's host
    Python dict loop over every matching point pair — with a segment-min
    over window-local trajectory-pair keys + one small compaction.

    ``left_index``/``right_index``/``dist``: a CompactJoinResult's arrays
    (-1 padding); ``left_local``/``right_local``: (N,)/(M,) window-local
    dense trajectory ranks of each batch lane.
    """
    ok = left_index >= 0
    key = (
        left_local[jnp.maximum(left_index, 0)] * num_right
        + right_local[jnp.maximum(right_index, 0)]
    )
    n_keys = num_left * num_right
    key = jnp.where(ok, key, n_keys)
    big = jnp.asarray(jnp.finfo(dist.dtype).max, dist.dtype)
    best = jax.ops.segment_min(
        jnp.where(ok, dist, big), key, num_segments=n_keys + 1
    )[:n_keys]
    hit_mask = best < big
    (hit,) = jnp.nonzero(hit_mask, size=max_tpairs, fill_value=-1)
    found = hit >= 0
    pair_key = jnp.where(found, hit.astype(jnp.int32), -1)
    pair_dist = jnp.where(found, best[jnp.maximum(hit, 0)], big)
    count = jnp.sum(hit_mask.astype(jnp.int32))
    return TrajPairs(pair_key, pair_dist, count)


class TrajAggregate(NamedTuple):
    """Per-(cell, objID) temporal lengths for the heatmap aggregate."""

    min_ts: jnp.ndarray  # (P,) per unique (cell, objID) pair
    max_ts: jnp.ndarray  # (P,)


def traj_cell_spans_kernel(
    ts: jnp.ndarray,
    pair_id: jnp.ndarray,
    valid: jnp.ndarray,
    num_pairs: int,
    axis_name=None,
) -> TrajAggregate:
    """Min/max timestamp per dense (cell, objID) pair id.

    The batched form of TAggregateQuery's MapState min/max tracking
    (TAggregateQuery.java:150-250): pair ids are host-interned
    (np.unique over cell*U+oid), the kernel reduces timestamps. With
    ``axis_name`` (inside shard_map) the per-shard reductions
    pmin/pmax-reduce across the mesh axis.
    """
    big = jnp.iinfo(ts.dtype).max
    small = jnp.iinfo(ts.dtype).min
    mn = jax.ops.segment_min(
        jnp.where(valid, ts, big), pair_id, num_segments=num_pairs
    )
    mx = jax.ops.segment_max(
        jnp.where(valid, ts, small), pair_id, num_segments=num_pairs
    )
    if axis_name is not None:
        mn = jax.lax.pmin(mn, axis_name)
        mx = jax.lax.pmax(mx, axis_name)
    return TrajAggregate(mn, mx)


def traj_hits_kernel(
    inside_any: jnp.ndarray,
    oid: jnp.ndarray,
    valid: jnp.ndarray,
    num_segments: int,
    axis_name=None,
) -> jnp.ndarray:
    """(U,) bool: does any point of each trajectory satisfy the predicate?

    Used by tRange: 'if any point of the trajectory is inside any query
    polygon, the whole (windowed) trajectory qualifies'
    (tRange/PointPolygonTRangeQuery.java:53-177). With ``axis_name``
    (inside shard_map) the per-shard segment reduction pmax-reduces across
    the mesh axis — a trajectory's points may land on any shard.
    """
    hit = (inside_any & valid).astype(jnp.int32)
    seg = jax.ops.segment_max(hit, oid, num_segments=num_segments)
    if axis_name is not None:
        seg = jax.lax.pmax(seg, axis_name)
    return seg > 0


def traj_range_hits_fused(
    xy: jnp.ndarray,
    valid: jnp.ndarray,
    oid: jnp.ndarray,
    query_verts: jnp.ndarray,
    query_edge_valid: jnp.ndarray,
    num_segments: int,
    axis_name=None,
) -> jnp.ndarray:
    """tRange's fused per-window program: batched containment against the
    query polygon set + per-trajectory any-hit reduction — single- and
    multi-chip paths share it (the mesh path all-reduces via the
    traj_hits_kernel axis hook)."""
    from spatialflink_tpu.ops.polygon import points_in_polygon

    inside = jax.vmap(
        lambda v, e: points_in_polygon(xy, v, e)
    )(query_verts, query_edge_valid)
    return traj_hits_kernel(
        jnp.any(inside, axis=0), oid, valid, num_segments,
        axis_name=axis_name,
    )
