"""Trajectory segment kernels.

The reference's trajectory operators keep per-objID state in Flink keyed
state (ValueState/MapState) and iterate per record
(tStats/TStatsQuery.java:44-145, tAggregate/TAggregateQuery.java:53-250).
Here a window's points are sorted by (objID, ts) once on the host and every
per-trajectory statistic is a segment reduction over the interned objID —
one fused XLA program per window instead of per-record state mutation.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from spatialflink_tpu.ops.distances import point_point_distance


class TrajStats(NamedTuple):
    """Per-segment (per-objID) trajectory statistics for a window.

    Mirrors the output tuple of TStatsQuery (objID, spatialLength,
    temporalLength, spatialLength/temporalLength — TStatsQuery.java:137-144).
    """

    spatial_length: jnp.ndarray  # (U,)
    temporal_length: jnp.ndarray  # (U,) ms
    count: jnp.ndarray  # (U,) points per trajectory
    avg_speed: jnp.ndarray  # (U,) spatial/temporal (0 where temporal == 0)


def traj_stats_kernel(
    xy: jnp.ndarray,
    ts: jnp.ndarray,
    oid: jnp.ndarray,
    valid: jnp.ndarray,
    num_segments: int,
) -> TrajStats:
    """Inputs must be pre-sorted by (oid, ts); padding lanes carry
    oid = num_segments - 1 … any valid id with valid=False (they're masked).

    Consecutive-point distances within each trajectory are summed per
    segment; out-of-order duplicates (equal timestamps) contribute like the
    reference's window variant, which walks points in sorted order.
    """
    same_traj = (oid[1:] == oid[:-1]) & valid[1:] & valid[:-1]
    seg_d = point_point_distance(xy[1:], xy[:-1])
    seg_t = (ts[1:] - ts[:-1]).astype(seg_d.dtype)
    contrib_d = jnp.where(same_traj, seg_d, 0)
    contrib_t = jnp.where(same_traj, seg_t, 0)
    # Segment sums keyed by the *later* point's trajectory.
    spatial = jax.ops.segment_sum(contrib_d, oid[1:], num_segments=num_segments)
    temporal = jax.ops.segment_sum(contrib_t, oid[1:], num_segments=num_segments)
    count = jax.ops.segment_sum(
        valid.astype(jnp.int32), oid, num_segments=num_segments
    )
    speed = jnp.where(temporal > 0, spatial / jnp.where(temporal > 0, temporal, 1), 0.0)
    return TrajStats(spatial, temporal, count, speed)


def traj_stats_sorted_fused(
    xy: jnp.ndarray,
    ts: jnp.ndarray,
    oid: jnp.ndarray,
    valid: jnp.ndarray,
    num_segments: int,
) -> TrajStats:
    """traj_stats over an UNsorted batch: the (oid, ts) sort happens on
    device (lexsort) so SoA windows go straight from the assembler into one
    fused program — no host-side Python sort of event objects
    (the round-1 throughput cap, TStatsQuery.java:148-189's window walk).
    Invalid lanes sort to the end (oid forced past every real id)."""
    oid_sort = jnp.where(valid, oid, num_segments)
    order = jnp.lexsort((ts, oid_sort))
    return traj_stats_kernel(
        xy[order], ts[order], oid[order], valid[order],
        num_segments=num_segments,
    )


class TrajPaneStats(NamedTuple):
    """Device pane-sliding tStats output: (num_oids, n_starts) matrices,
    oid-major (the segment-sum layout); the host wrapper transposes and
    applies the alive-window filter. ``temporal``/``count`` are int32 —
    exact on every backend (per-oid ms totals are bounded by the stream
    span, which the wrapper checks fits int32)."""

    spatial: jnp.ndarray  # (K, n_starts)
    temporal: jnp.ndarray  # (K, n_starts) int32 ms
    count: jnp.ndarray  # (K, n_starts) int32


def traj_stats_pane_kernel(
    ts_rel: jnp.ndarray,
    x: jnp.ndarray,
    y: jnp.ndarray,
    oid: jnp.ndarray,
    valid: jnp.ndarray,
    num_oids: int,
    slide_ms: int,
    ppw: int,
    n_panes: int,
) -> TrajPaneStats:
    """Pane-decomposed sliding tStats ON DEVICE — the TPU form of
    streams/panes.py:traj_stats_sliding (itself the vectorized analog of
    the reference's per-record accumulator walk, TStatsQuery.java:44-145).

    Inputs are pre-sorted by (oid, ts) with padding at the end
    (valid=False). ``ts_rel`` is int32 REBASED time: the host wrapper
    subtracts ``p_lo·slide_ms`` so epoch-ms values survive the int32
    world of a non-x64 device (raw epoch ms ~1.7e12 would silently wrap;
    pane arithmetic is shift-invariant, so rebasing changes nothing).
    ``n_panes`` is a static bucket.

    Everything is expressed as SORTED segment sums + cumulative sums —
    no data-dependent scatters: the (oid, ts) sort makes every flat
    ``oid·n_panes + pane`` id non-decreasing, which XLA lowers to an
    efficient sorted-segment reduction instead of a serialized scatter.
    Window sums are cumsum differences gathered at STATIC row offsets,
    and the start-boundary corrections (a consecutive-point segment must
    not count for windows that begin after its earlier point) are two
    more sorted segment sums into a difference array + one cumsum —
    the interval-subtract of the host path, TPU-shaped. Temporal sums
    stay integer end to end (int32-exact; floats would round above
    2^24 on f32 devices).
    """
    k = num_oids
    n_starts = n_panes + ppw - 1
    nseg_flat = k * n_panes
    ts_rel = ts_rel.astype(jnp.int32)
    pane = jnp.clip(ts_rel // slide_ms, 0, n_panes - 1)
    sentinel = jnp.int32(nseg_flat)
    ids_pt = jnp.where(
        valid, oid.astype(jnp.int32) * n_panes + pane, sentinel
    )

    cnt = jax.ops.segment_sum(
        valid.astype(jnp.int32), ids_pt, num_segments=nseg_flat + 1,
        indices_are_sorted=True,
    )[:nseg_flat].reshape(k, n_panes)

    same = (oid[1:] == oid[:-1]) & valid[1:] & valid[:-1]
    dx = x[1:] - x[:-1]
    dy = y[1:] - y[:-1]
    f_dtype = x.dtype
    seg_d = jnp.where(same, jnp.sqrt(dx * dx + dy * dy),
                      jnp.zeros((), f_dtype))
    seg_dt = jnp.where(same, ts_rel[1:] - ts_rel[:-1], jnp.int32(0))
    ids_seg = ids_pt[1:]  # always the later point's id — stays sorted;
    # non-segments contribute zeros (cheaper than breaking sortedness
    # with a sentinel mid-stream).
    pane_d = jax.ops.segment_sum(
        seg_d, ids_seg, num_segments=nseg_flat + 1, indices_are_sorted=True,
    )[:nseg_flat].reshape(k, n_panes)
    pane_dt = jax.ops.segment_sum(
        seg_dt, ids_seg, num_segments=nseg_flat + 1, indices_are_sorted=True,
    )[:nseg_flat].reshape(k, n_panes)

    # Rolling window sums: one cumsum + static-offset row gathers.
    row = jnp.arange(n_starts, dtype=jnp.int32) - (ppw - 1)
    row_hi = jnp.clip(row + ppw, 0, n_panes)
    row_lo = jnp.clip(row, 0, n_panes)

    def rolling(a):
        c = jnp.concatenate(
            [jnp.zeros((k, 1), a.dtype), jnp.cumsum(a, axis=1)], axis=1
        )
        return c[:, row_hi] - c[:, row_lo]

    w_d = rolling(pane_d)
    w_dt = rolling(pane_dt)
    w_cnt = rolling(cnt)

    # Start-boundary corrections. t_prev_eff keeps ids monotone across
    # trajectory boundaries (those lanes carry zero data anyway).
    t_prev_eff = jnp.where(same, ts_rel[:-1], ts_rel[1:])
    seg_pane = ts_rel[1:] // slide_ms  # rebased pane of the later point
    first_b = jnp.maximum(t_prev_eff // slide_ms + 1,
                          seg_pane - ppw + 1)
    base = -(ppw - 1)  # rebased window-start pane of start-index 0
    si0 = jnp.clip(first_b - base, 0, n_starts)
    si1 = jnp.clip(seg_pane - base + 1, 0, n_starts)
    has = same & (si0 < si1) & valid[1:]
    d_corr = jnp.where(has, seg_d, jnp.zeros((), f_dtype))
    t_corr = jnp.where(has, seg_dt, jnp.int32(0))
    stride = n_starts + 1
    oid_b = oid[1:].astype(jnp.int32) * stride
    ids0 = jnp.where(valid[1:], oid_b + si0, jnp.int32(k * stride))
    ids1 = jnp.where(valid[1:], oid_b + si1, jnp.int32(k * stride))

    def interval(vals, ids):
        return jax.ops.segment_sum(
            vals, ids, num_segments=k * stride + 1, indices_are_sorted=True,
        )[:k * stride].reshape(k, stride)

    diff_d = interval(d_corr, ids0) - interval(d_corr, ids1)
    diff_t = interval(t_corr, ids0) - interval(t_corr, ids1)
    w_d = w_d - jnp.cumsum(diff_d, axis=1)[:, :n_starts]
    w_dt = w_dt - jnp.cumsum(diff_t, axis=1)[:, :n_starts]
    return TrajPaneStats(w_d, w_dt, w_cnt)


def stay_time_cells_kernel(
    ts: jnp.ndarray,
    cell: jnp.ndarray,
    oid: jnp.ndarray,
    valid: jnp.ndarray,
    num_cells: int,
) -> jnp.ndarray:
    """Per-cell dwell time for one window: consecutive same-trajectory
    time gaps attributed to the EARLIER point's grid cell, summed per
    cell — the device form of the StayTime app's per-trajectory walk
    (apps/StayTime.java:216-396 CellStayTimeWinFunction + :433-447
    aggregate). Inputs pre-sorted by (oid, ts), padding at the end;
    out-of-grid points carry ``cell == num_cells`` and land in the last
    ("out") bucket. Returns ((num_cells + 1,) int32 ms sums,
    (num_cells + 1,) int32 pair counts)."""
    same = (oid[1:] == oid[:-1]) & valid[1:] & valid[:-1]
    gaps = jnp.where(same, (ts[1:] - ts[:-1]).astype(jnp.int32),
                     jnp.int32(0))
    key = jnp.where(same & valid[:-1], cell[:-1].astype(jnp.int32),
                    jnp.int32(num_cells + 1))
    dwell = jax.ops.segment_sum(
        gaps, key, num_segments=num_cells + 2
    )[:num_cells + 1]
    # Pair counts distinguish "cell with only zero-length gaps" (the
    # object path still emits the key, value 0) from "no pairs".
    count = jax.ops.segment_sum(
        same.astype(jnp.int32), key, num_segments=num_cells + 2
    )[:num_cells + 1]
    return dwell, count


class TrajPairs(NamedTuple):
    """Deduped trajectory-pair join output (device-compacted).

    ``pair_key``: (max_tpairs,) int32 — left_local * num_right + right_local,
    -1 padding; ``dist``: (max_tpairs,) min point distance of the pair;
    ``count``: () number of distinct qualifying pairs (> max_tpairs means
    the budget must grow).
    """

    pair_key: jnp.ndarray
    dist: jnp.ndarray
    count: jnp.ndarray


def traj_pair_dedup_kernel(
    left_index: jnp.ndarray,
    right_index: jnp.ndarray,
    dist: jnp.ndarray,
    left_local: jnp.ndarray,
    right_local: jnp.ndarray,
    num_left: int,
    num_right: int,
    max_tpairs: int,
) -> TrajPairs:
    """Compact join pairs → distinct (trajectory, trajectory) pairs with
    min distance, entirely on device.

    Replaces the reference's per-record dedup map (latest pair per
    (traj, queryTraj), tJoin/TJoinQuery.java:60-154) — and round 1's host
    Python dict loop over every matching point pair — with a segment-min
    over window-local trajectory-pair keys + one small compaction.

    ``left_index``/``right_index``/``dist``: a CompactJoinResult's arrays
    (-1 padding); ``left_local``/``right_local``: (N,)/(M,) window-local
    dense trajectory ranks of each batch lane.
    """
    ok = left_index >= 0
    key = (
        left_local[jnp.maximum(left_index, 0)] * num_right
        + right_local[jnp.maximum(right_index, 0)]
    )
    n_keys = num_left * num_right
    key = jnp.where(ok, key, n_keys)
    big = jnp.asarray(jnp.finfo(dist.dtype).max, dist.dtype)
    best = jax.ops.segment_min(
        jnp.where(ok, dist, big), key, num_segments=n_keys + 1
    )[:n_keys]
    hit_mask = best < big
    (hit,) = jnp.nonzero(hit_mask, size=max_tpairs, fill_value=-1)
    found = hit >= 0
    pair_key = jnp.where(found, hit.astype(jnp.int32), -1)
    pair_dist = jnp.where(found, best[jnp.maximum(hit, 0)], big)
    count = jnp.sum(hit_mask.astype(jnp.int32))
    return TrajPairs(pair_key, pair_dist, count)


class TrajAggregate(NamedTuple):
    """Per-(cell, objID) temporal lengths for the heatmap aggregate."""

    min_ts: jnp.ndarray  # (P,) per unique (cell, objID) pair
    max_ts: jnp.ndarray  # (P,)


def traj_cell_spans_kernel(
    ts: jnp.ndarray,
    pair_id: jnp.ndarray,
    valid: jnp.ndarray,
    num_pairs: int,
    axis_name=None,
) -> TrajAggregate:
    """Min/max timestamp per dense (cell, objID) pair id.

    The batched form of TAggregateQuery's MapState min/max tracking
    (TAggregateQuery.java:150-250): pair ids are host-interned
    (np.unique over cell*U+oid), the kernel reduces timestamps. With
    ``axis_name`` (inside shard_map) the per-shard reductions
    pmin/pmax-reduce across the mesh axis.
    """
    big = jnp.iinfo(ts.dtype).max
    small = jnp.iinfo(ts.dtype).min
    mn = jax.ops.segment_min(
        jnp.where(valid, ts, big), pair_id, num_segments=num_pairs
    )
    mx = jax.ops.segment_max(
        jnp.where(valid, ts, small), pair_id, num_segments=num_pairs
    )
    if axis_name is not None:
        mn = jax.lax.pmin(mn, axis_name)
        mx = jax.lax.pmax(mx, axis_name)
    return TrajAggregate(mn, mx)


def traj_hits_kernel(
    inside_any: jnp.ndarray,
    oid: jnp.ndarray,
    valid: jnp.ndarray,
    num_segments: int,
    axis_name=None,
) -> jnp.ndarray:
    """(U,) bool: does any point of each trajectory satisfy the predicate?

    Used by tRange: 'if any point of the trajectory is inside any query
    polygon, the whole (windowed) trajectory qualifies'
    (tRange/PointPolygonTRangeQuery.java:53-177). With ``axis_name``
    (inside shard_map) the per-shard segment reduction pmax-reduces across
    the mesh axis — a trajectory's points may land on any shard.
    """
    hit = (inside_any & valid).astype(jnp.int32)
    seg = jax.ops.segment_max(hit, oid, num_segments=num_segments)
    if axis_name is not None:
        seg = jax.lax.pmax(seg, axis_name)
    return seg > 0


def traj_range_hits_fused(
    xy: jnp.ndarray,
    valid: jnp.ndarray,
    oid: jnp.ndarray,
    query_verts: jnp.ndarray,
    query_edge_valid: jnp.ndarray,
    num_segments: int,
    axis_name=None,
) -> jnp.ndarray:
    """tRange's fused per-window program: batched containment against the
    query polygon set + per-trajectory any-hit reduction — single- and
    multi-chip paths share it (the mesh path all-reduces via the
    traj_hits_kernel axis hook)."""
    from spatialflink_tpu.ops.polygon import points_in_polygon

    inside = jax.vmap(
        lambda v, e: points_in_polygon(xy, v, e)
    )(query_verts, query_edge_valid)
    return traj_hits_kernel(
        jnp.any(inside, axis=0), oid, valid, num_segments,
        axis_name=axis_name,
    )
