"""Sort-free first-k selection — the shared TPU selection primitive and
the per-backend strategy gate.

``lax.top_k`` lowers to a full sort on TPU; when only set-MEMBERSHIP
matters (the consumer's reduction is order-independent, e.g. min), the
first k set bits per row can be selected with a prefix-sum one-hot —
pure VPU compare/select/reduce, measured ~10× faster than top_k at the
shapes the kernels use. On XLA:CPU the relation inverts (the vectorized
sort wins; the one-hot tensor measured ~9× slower on the kNN headline),
so every consumer gates on ``onehot_select_preferred()``:

- ops/join.py:_block_candidates (candidate geometries per tile),
- ops/join.py:_compact_pairs (matches per left item),
- ops/knn.py compact-digest candidate select.

``first_k_prefix_indices`` is the third strategy — index extraction via
prefix sum + batched binary search, no sort and no one-hot tensor. It
is the CPU form of the compacted tJoin pane probe
(ops/tjoin_panes.py:_probe_compact), where ``lax.top_k`` over the
span²·cap candidate width was ~45% of the whole slide step.

The top_k alternative stays at each call site rather than behind one
index-returning API: the TPU consumers reduce the one-hot tensor
directly (sums — no gathers, which are the TPU-slow op this module
exists to avoid), while the CPU consumers gather by the top_k indices.
Both strategies select the identical set (ascending position, ties by
index) — parity-tested per consumer.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def onehot_select_preferred() -> bool:
    """True on backends where the prefix-sum one-hot select beats
    top_k — the ONE backend list every consumer shares."""
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:  # pragma: no cover - backend init failure
        return False


def first_k_prefix_indices(mask: jnp.ndarray, k: int):
    """First-``k`` set bits along the LAST axis as INDICES, sort-free.

    Returns ``(ci, count, overflow)``: ``ci`` is (..., k) int32 — slot
    ``s`` holds the lane index of the (s+1)-th set bit (clipped in-range
    garbage past the per-row count; mask with ``count`` downstream),
    ``count``/``overflow`` as in ``first_k_onehot``. Selects the
    IDENTICAL set as ``lax.top_k`` over the int8 mask (ascending lane
    order, complete iff overflow == 0) without the full per-row sort
    top_k lowers to on CPU (~45% of the tJoin pane slide step at the
    10s/10ms bench shape) and without the (..., C, k) one-hot tensor:
    one prefix sum plus a ⌈log₂ C⌉-step batched binary search over it
    (the prefix is nondecreasing, so ``ci[s]`` is the first lane where
    ``prefix ≥ s+1`` — k·log C tiny gathers instead of a C-wide sort).
    """
    prefix = jnp.cumsum(mask.astype(jnp.int32), axis=-1)
    count = prefix[..., -1]
    overflow = jnp.sum(jnp.maximum(count - k, 0))
    C = mask.shape[-1]
    target = jnp.arange(1, k + 1, dtype=jnp.int32)
    target = jnp.broadcast_to(target, count.shape + (k,))
    lo = jnp.zeros(count.shape + (k,), jnp.int32)
    hi = jnp.full(count.shape + (k,), C, jnp.int32)
    # The search interval is [0, C] — C+1 distinct answers, so
    # ⌈log₂(C+1)⌉ = C.bit_length() halvings (NOT (C-1).bit_length(),
    # which is one short exactly when C is a power of two).
    steps = max(int(C).bit_length(), 1)
    for _ in range(steps):  # static trip count — fully unrolled, no sort
        mid = (lo + hi) // 2
        v = jnp.take_along_axis(prefix, jnp.clip(mid, 0, C - 1), axis=-1)
        go = v < target
        lo = jnp.where(go, mid + 1, lo)
        hi = jnp.where(go, hi, mid)
    return jnp.clip(lo, 0, C - 1), count, overflow


def first_k_onehot(mask: jnp.ndarray, k: int):
    """Select the first ``k`` set bits along the LAST axis, ascending.

    Returns ``(hit, count, overflow)``: ``hit`` is a (..., C, k) one-hot
    bool tensor (slot ``s`` marks the (s+1)-th set bit of the row —
    consumers reduce it against index or value tensors; a one-hot sum
    selects exactly one term, so value selection is bit-exact),
    ``count`` the (...,) per-row set-bit totals, and ``overflow`` the
    scalar total of set bits beyond ``k`` (the callers' retry contract:
    selection is complete iff 0).
    """
    prefix = jnp.cumsum(mask.astype(jnp.int32), axis=-1)
    count = prefix[..., -1]
    slots = jnp.arange(k, dtype=jnp.int32)
    hit = mask[..., None] & (prefix[..., None] == slots + 1)
    overflow = jnp.sum(jnp.maximum(count - k, 0))
    return hit, count, overflow
