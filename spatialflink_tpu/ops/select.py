"""Sort-free first-k selection — the shared TPU selection primitive and
the per-backend strategy gate.

``lax.top_k`` lowers to a full sort on TPU; when only set-MEMBERSHIP
matters (the consumer's reduction is order-independent, e.g. min), the
first k set bits per row can be selected with a prefix-sum one-hot —
pure VPU compare/select/reduce, measured ~10× faster than top_k at the
shapes the kernels use. On XLA:CPU the relation inverts (the vectorized
sort wins; the one-hot tensor measured ~9× slower on the kNN headline),
so every consumer gates on ``onehot_select_preferred()``:

- ops/join.py:_block_candidates (candidate geometries per tile),
- ops/join.py:_compact_pairs (matches per left item),
- ops/knn.py compact-digest candidate select.

The top_k alternative stays at each call site rather than behind one
index-returning API: the TPU consumers reduce the one-hot tensor
directly (sums — no gathers, which are the TPU-slow op this module
exists to avoid), while the CPU consumers gather by the top_k indices.
Both strategies select the identical set (ascending position, ties by
index) — parity-tested per consumer.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def onehot_select_preferred() -> bool:
    """True on backends where the prefix-sum one-hot select beats
    top_k — the ONE backend list every consumer shares."""
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:  # pragma: no cover - backend init failure
        return False


def first_k_onehot(mask: jnp.ndarray, k: int):
    """Select the first ``k`` set bits along the LAST axis, ascending.

    Returns ``(hit, count, overflow)``: ``hit`` is a (..., C, k) one-hot
    bool tensor (slot ``s`` marks the (s+1)-th set bit of the row —
    consumers reduce it against index or value tensors; a one-hot sum
    selects exactly one term, so value selection is bit-exact),
    ``count`` the (...,) per-row set-bit totals, and ``overflow`` the
    scalar total of set bits beyond ``k`` (the callers' retry contract:
    selection is complete iff 0).
    """
    prefix = jnp.cumsum(mask.astype(jnp.int32), axis=-1)
    count = prefix[..., -1]
    slots = jnp.arange(k, dtype=jnp.int32)
    hit = mask[..., None] & (prefix[..., None] == slots + 1)
    overflow = jnp.sum(jnp.maximum(count - k, 0))
    return hit, count, overflow
