"""Pallas TPU grid-hash join — hit extraction in time ∝ matches.

The XLA dense-bucket join (ops.join.join_window_bucketed) evaluates the
pair predicate over span²·cells·capL·capR lanes essentially for free, but
compacting the hits with ``jnp.nonzero`` costs ~9 ns/lane on the TPU scalar
core (~2 s for a 131k×131k window at cap 48) because the cumsum+scatter
touches every lane. Real joins are sparse — ~68k hits out of 207M lanes —
so this kernel walks the bucket planes once and extracts each hit with an
argmin-over-mask loop whose cost is proportional to the HIT count:

  grid step = one cell row; per column, the (2L+1)² neighbor buckets of the
  right side are concatenated into one (capL, K) candidate block, the pair
  mask is evaluated on the VPU, and a while-loop peels off set lanes one at
  a time (vector min-reduce + scalar store via an SMEM cursor).

Replaces the reference's replicate+shuffle+filter join
(join/JoinQuery.java:73-137, join/PointPointJoinQuery.java:124-183) as the
windowBased fast path on TPU. Same contract as join_window_bucketed:
results are exact iff overflow == 0; count > max_pairs means the caller
must retry with a bigger budget.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from spatialflink_tpu.ops.join import CompactJoinResult, bucketize_planes

# The three (max_pairs,) outputs are VMEM-resident for the whole grid
# (12 B per pair slot). Auto backend selection falls back to the XLA
# compaction path past this budget (~6 MB of the ~16 MB VMEM).
PALLAS_JOIN_MAX_PAIRS = 524_288


def _extract_kernel(
    radius_ref,
    lx_ref, ly_ref, lidx_ref,
    *rest,
    grid_n: int, layers: int, cap_left: int, cap_right: int, max_pairs: int,
):
    span = 2 * layers + 1
    n_right = 3 * span  # rx, ry, ridx per dx
    right_refs = rest[:n_right]
    outl_ref, outr_ref, outd_ref, cnt_ref = rest[n_right:n_right + 4]
    sm, accl, accr, accd = rest[n_right + 4:]
    k_cand = span * span * cap_right
    max_rows = max_pairs // 128
    lane_iota = jax.lax.broadcasted_iota(jnp.int32, (1, 128), 1)

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        outl_ref[:] = jnp.full((max_rows, 128), -1, jnp.int32)
        outr_ref[:] = jnp.full((max_rows, 128), -1, jnp.int32)
        outd_ref[:] = jnp.full((max_rows, 128), jnp.inf, jnp.float32)
        sm[0] = 0  # total hit count
        sm[1] = 0  # flushed element count (multiple of 128)

    r2 = radius_ref[0, 0] * radius_ref[0, 0]
    row_any = jnp.sum((lidx_ref[0, :, :] >= 0).astype(jnp.int32)) > 0

    @pl.when(row_any)
    def _row():
        def col_body(j, carry):
            lxv = lx_ref[0, j, :].reshape(cap_left, 1)
            lyv = ly_ref[0, j, :].reshape(cap_left, 1)
            lidxv = lidx_ref[0, j, :].reshape(cap_left, 1)
            sx_parts, sy_parts, sidx_parts = [], [], []
            for di in range(span):
                rx_ref = right_refs[3 * di]
                ry_ref = right_refs[3 * di + 1]
                ridx_ref = right_refs[3 * di + 2]
                for dy in range(-layers, layers + 1):
                    c = j + layers + dy  # column in the col-padded plane
                    sx_parts.append(rx_ref[0, c, :].reshape(1, cap_right))
                    sy_parts.append(ry_ref[0, c, :].reshape(1, cap_right))
                    sidx_parts.append(ridx_ref[0, c, :].reshape(1, cap_right))
            sx = jnp.concatenate(sx_parts, axis=1)  # (1, k_cand)
            sy = jnp.concatenate(sy_parts, axis=1)
            sidx = jnp.concatenate(sidx_parts, axis=1)
            ddx = lxv - sx
            ddy = lyv - sy
            d2 = ddx * ddx + ddy * ddy
            mask = (lidxv >= 0) & (sidx >= 0) & (d2 <= r2)
            nhit = jnp.sum(mask.astype(jnp.int32))

            @pl.when(nhit > 0)
            def _extract():
                code_iota = (
                    jax.lax.broadcasted_iota(
                        jnp.int32, (cap_left, k_cand), 0
                    ) * k_cand
                    + jax.lax.broadcasted_iota(
                        jnp.int32, (cap_left, k_cand), 1
                    )
                )
                big = cap_left * k_cand

                def cond(st):
                    return st[1] > 0

                def body(st):
                    # Scalar-only carry (last extracted code): Mosaic cannot
                    # carry the (capL, k_cand) i1 mask through a while loop.
                    last, remaining = st
                    code = jnp.min(
                        jnp.where(mask & (code_iota > last), code_iota, big)
                    )
                    # One-hot reduces instead of dynamic_slice (which Mosaic
                    # does not lower): exactly one lane has code_iota == code.
                    hot = code_iota == code
                    lval = jnp.sum(jnp.where(hot, lidxv, 0))
                    rval = jnp.sum(jnp.where(hot, sidx, 0))
                    dval = jnp.sqrt(jnp.sum(jnp.where(hot, d2, 0.0)))
                    # Scalar stores to VMEM are impossible on TPU; instead
                    # accumulate into a 128-lane register row (one-hot
                    # select) and flush full rows with a vector store.
                    s = sm[0]
                    base = sm[1]
                    lane = s - base  # 0..127 unless the budget overflowed
                    lane_hot = lane_iota == lane
                    accl[:] = jnp.where(lane_hot, lval, accl[:])
                    accr[:] = jnp.where(lane_hot, rval, accr[:])
                    accd[:] = jnp.where(
                        lane_hot, dval.astype(jnp.float32), accd[:]
                    )
                    sm[0] = s + 1

                    @pl.when((lane == 127) & (base // 128 < max_rows))
                    def _flush():
                        row = base // 128
                        outl_ref[pl.ds(row, 1), :] = accl[:]
                        outr_ref[pl.ds(row, 1), :] = accr[:]
                        outd_ref[pl.ds(row, 1), :] = accd[:]
                        sm[1] = base + 128

                    return (code, remaining - 1)

                jax.lax.while_loop(cond, body, (jnp.int32(-1), nhit))

            return carry

        jax.lax.fori_loop(0, grid_n, col_body, 0)

    @pl.when(i == grid_n - 1)
    def _fin():
        cnt = sm[0]
        base = sm[1]

        @pl.when((cnt > base) & (base // 128 < max_rows))
        def _partial_flush():
            ok = lane_iota < (cnt - base)
            row = base // 128
            outl_ref[pl.ds(row, 1), :] = jnp.where(ok, accl[:], -1)
            outr_ref[pl.ds(row, 1), :] = jnp.where(ok, accr[:], -1)
            outd_ref[pl.ds(row, 1), :] = jnp.where(ok, accd[:], jnp.inf)

        cnt_ref[0, 0] = cnt


@functools.partial(
    jax.jit,
    static_argnames=(
        "grid_n", "layers", "cap_left", "cap_right", "max_pairs", "interpret"
    ),
)
def join_window_pallas(
    left_xy: jnp.ndarray,
    left_valid: jnp.ndarray,
    left_cells: jnp.ndarray,
    right_xy: jnp.ndarray,
    right_valid: jnp.ndarray,
    right_cells: jnp.ndarray,
    grid_n: int,
    layers: int,
    radius,
    cap_left: int,
    cap_right: int,
    max_pairs: int,
    interpret: bool = False,
) -> CompactJoinResult:
    """Dense-bucket grid join with Pallas hit extraction.

    Drop-in for ops.join.join_window_bucketed (same argument and result
    contract); float32 compute. ``interpret=True`` runs the Pallas
    interpreter for CPU testing.
    """
    f32 = jnp.float32
    max_pairs = int(max_pairs)  # sfcheck: ok=trace-hygiene -- static shape budget, a Python int at trace time (never traced)
    max_pairs += (-max_pairs) % 128  # whole 128-lane output rows
    max_rows = max_pairs // 128
    span = 2 * layers + 1
    lx, ly, lidx, l_over = bucketize_planes(
        left_xy.astype(f32), left_valid, left_cells, grid_n, cap_left
    )
    rx, ry, ridx, r_over = bucketize_planes(
        right_xy.astype(f32), right_valid, right_cells, grid_n, cap_right
    )
    # Pad the right planes by `layers` rows/cols so every neighbor access is
    # a static in-bounds slice; padding slots carry idx=-1 (never match).
    pad = ((layers, layers), (layers, layers), (0, 0))
    rxp = jnp.pad(rx, pad)
    ryp = jnp.pad(ry, pad)
    ridxp = jnp.pad(ridx, pad, constant_values=-1)

    cpad = grid_n + 2 * layers
    left_spec = lambda: pl.BlockSpec(
        (1, grid_n, cap_left), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
    )
    right_specs = []
    for dx in range(-layers, layers + 1):
        for _ in range(3):
            right_specs.append(
                pl.BlockSpec(
                    (1, cpad, cap_right),
                    lambda i, d=dx: (i + layers + d, 0, 0),
                    memory_space=pltpu.VMEM,
                )
            )
    right_args = []
    for _ in range(span):
        right_args.extend([rxp, ryp, ridxp])

    kernel = functools.partial(
        _extract_kernel,
        grid_n=grid_n, layers=layers,
        cap_left=cap_left, cap_right=cap_right, max_pairs=max_pairs,
    )
    outl, outr, outd, cnt = pl.pallas_call(
        kernel,
        grid=(grid_n,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            left_spec(), left_spec(), left_spec(),
            *right_specs,
        ],
        out_specs=[
            pl.BlockSpec(
                (max_rows, 128), lambda i: (0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (max_rows, 128), lambda i: (0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (max_rows, 128), lambda i: (0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((max_rows, 128), jnp.int32),
            jax.ShapeDtypeStruct((max_rows, 128), jnp.int32),
            jax.ShapeDtypeStruct((max_rows, 128), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.SMEM((2,), jnp.int32),
            pltpu.VMEM((1, 128), jnp.int32),
            pltpu.VMEM((1, 128), jnp.int32),
            pltpu.VMEM((1, 128), jnp.float32),
        ],
        interpret=interpret,
    )(
        jnp.asarray(radius, f32).reshape(1, 1),
        lx, ly, lidx,
        *right_args,
    )
    return CompactJoinResult(
        outl.reshape(-1), outr.reshape(-1), outd.reshape(-1),
        cnt[0, 0], l_over + r_over,
    )
