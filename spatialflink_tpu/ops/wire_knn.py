"""Wire-plane kNN pane digest — ONE program for operator, bench, suite.

The headline benchmark measures 6 B/pt wire ingest (streams/wire.py)
fused straight into the kNN pane digest. Round 4 left that program
living only in bench.py while the shipped operator
(operators/knn_query.py:run_soa_panes) digested SoA floats — exactly
the measured-vs-shipped drift ops/tjoin_panes.py warns about. This
module is the single home of the wire→digest step; bench.py's headline,
bench_suite's kNN configs, and PointPointKNNQuery.run_wire_panes all
call it, so the measured program IS the shipped program.

Two interchangeable strategies (bit-compatible candidate SETS, distance
values within 1 ulp — Mosaic vs XLA FMA freedom; tests/test_wire_knn.py
pins parity):

- ``xla``: plane dequant → distances → top-``cand`` compacted segment-
  min digest (ops/knn.py:_digest_from_point_dists_compact, with its
  built-in exact overflow fallback).
- ``pallas`` (TPU): the fused select-while-dequantizing extraction
  (ops/pallas_digest.py) with an IN-PROGRAM ``lax.cond`` fallback to
  the full XLA scatter digest whenever the hit count exceeds the
  candidate budget — exact either way.

``select_wire_digest_step`` implements the bench.py self-check contract
(run one pane both ways, require exact in-radius-set equality and ≤1 ulp
distances before trusting the Pallas lowering) for any caller.

Reference seam being replaced: Deserialization.java:149-211 (text
re-parse per record) feeding KNNQuery.java:204-308 (windowAll PQ merge).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from spatialflink_tpu.ops.knn import (
    _digest_from_point_dists,
    _digest_from_point_dists_compact,
)
from spatialflink_tpu.ops.pallas_digest import (
    PALLAS_DIGEST_MAX_CAND,
    wire_digest_pallas,
)


def wire_plane_coords(wire_s, scale, origin):
    """(3, N) u16 plane-major wire → (xf, yf, oid) device planes.

    Contiguous (N,) planes keep dequant + distance fully lane-parallel
    (the (N, 2) row-major layout tiles onto only 2 of the 128 TPU
    lanes — the plane-major lever, BASELINE.md). The f32 upcast is
    bit-exact by the wire format's m×2^e scale contract
    (streams/wire.py)."""
    xf = wire_s[0].astype(jnp.float32) * scale[0] + origin[0]
    yf = wire_s[1].astype(jnp.float32) * scale[1] + origin[1]
    # int16 oid bits travel as uint16: values < 32768 upcast bit-exact.
    oid = wire_s[2].astype(jnp.int32)
    return xf, yf, oid


def wire_digest_xla(wire_s, n_valid, query_xy, scale, origin, radius,
                    *, num_segments: int, cand: int = 8192):
    """XLA strategy: plane-major dequant + distance → compacted digest.

    ``wire_s``: (3, N) uint16; ``n_valid``: logical count (positions
    past it are bucket padding — excluded via the valid mask, so a
    variable-size pane stream reuses one compiled shape). All other
    args traced; ``num_segments``/``cand`` static. N is the caller's
    pane-capacity bucket (run_wire_panes pads through the shared
    ladder, ops/compaction.py:wire_pane_bucket — each pick lands in
    telemetry's per-bucket occupancy log), so the whole dequant →
    distance → candidate pipeline scans O(pane-rounded-up) lanes and
    the compact step's ``cand >= N`` compile-time branch already
    short-circuits small buckets straight to the scatter digest.
    """
    xf, yf, oid = wire_plane_coords(wire_s, scale, origin)
    dx = xf - query_xy[0]
    dy = yf - query_xy[1]
    dist = jnp.sqrt(dx * dx + dy * dy)
    n = wire_s.shape[1]
    valid = jnp.arange(n, dtype=jnp.int32) < n_valid
    return _digest_from_point_dists_compact(
        dist, valid, None, oid, radius, num_segments,
        index_base=jnp.int32(0), cand=cand,
    )


def wire_digest_pallas_step(wire_s, n_valid, query_xy, scale, origin,
                            radius, *, num_segments: int,
                            max_cand: int = PALLAS_DIGEST_MAX_CAND,
                            interpret: bool = False):
    """Pallas strategy: fused extraction, exact via in-program fallback.

    Delegates the extraction (consts packing included — ONE home,
    ops/pallas_digest.py) to ``wire_digest_pallas``; if the hit count
    exceeds ``max_cand`` (truncated output) a ``lax.cond`` reruns the
    pane through the full XLA scatter digest — the step is exact either
    way, matching bench.py's overflow contract."""
    d_pallas, cnt = wire_digest_pallas(
        wire_s, query_xy, scale, origin, radius, num_segments,
        max_cand=max_cand, interpret=interpret, n_valid=n_valid,
    )

    def from_candidates(_):
        return d_pallas

    def full_xla(_):
        xf, yf, oid = wire_plane_coords(wire_s, scale, origin)
        dx = xf - query_xy[0]
        dy = yf - query_xy[1]
        dist = jnp.sqrt(dx * dx + dy * dy)
        n = wire_s.shape[1]
        valid = jnp.arange(n, dtype=jnp.int32) < n_valid
        return _digest_from_point_dists(
            dist, valid, None, oid, radius, num_segments,
            index_base=jnp.int32(0),
        )

    return jax.lax.cond(cnt <= max_cand, from_candidates, full_xla, None)


def make_wire_digest_step(*, num_segments: int, cand: int = 8192,
                          strategy: str = "xla",
                          max_cand: int = PALLAS_DIGEST_MAX_CAND,
                          interpret: bool = False):
    """Bind the statics; returns ``fn(wire_s, n_valid, query_xy, scale,
    origin, radius) -> KnnPaneDigest`` ready for jax.jit / lax.scan
    embedding."""
    if strategy == "xla":
        return functools.partial(
            wire_digest_xla, num_segments=num_segments, cand=cand,
        )
    if strategy == "pallas":
        return functools.partial(
            wire_digest_pallas_step, num_segments=num_segments,
            max_cand=max_cand, interpret=interpret,
        )
    raise ValueError(f"strategy must be 'xla' or 'pallas', got {strategy!r}")


def digests_agree(seg_a, rep_a, seg_b, rep_b) -> bool:
    """The bench.py self-check predicate: identical in-radius object
    SETS, distances within 1 ulp (Mosaic vs XLA FMA freedom), and
    identical representatives wherever the distances agree exactly.
    Host-side (fetches both digests)."""
    sa, sb = jax.device_get((seg_a, seg_b))  # sfcheck: ok=trace-hygiene -- host-side self-check predicate (docstring): fetching both digests IS the job
    ra, rb = jax.device_get((rep_a, rep_b))  # sfcheck: ok=trace-hygiene -- same host-side self-check fetch as above
    big = np.asarray(np.finfo(sa.dtype).max, sa.dtype)
    live_a, live_b = sa != big, sb != big
    if not np.array_equal(live_a, live_b):
        return False
    if live_a.any():
        la, lb = sa[live_a], sb[live_a]
        ulp = np.spacing(np.maximum(np.abs(la), np.abs(lb)))
        if not np.all(np.abs(la - lb) <= ulp):
            return False
        exact = live_a & (sa == sb)
        if not np.array_equal(ra[exact], rb[exact]):  # sfcheck: ok=fixed-shape -- host-side numpy predicate (docstring), never traced
            return False
    return True


def select_wire_digest_step(sample_wire, sample_n, query_xy, scale,
                            origin, radius, *, num_segments: int,
                            cand: int = 8192,
                            max_cand: int = PALLAS_DIGEST_MAX_CAND,
                            interpret: bool = False,
                            strategy: str = "auto"):
    """Pick the digest strategy with bench.py's self-check contract.

    ``auto``: on TPU (or with ``interpret=True``), run ONE sample pane
    through both strategies and adopt Pallas only if ``digests_agree``;
    any lowering failure or disagreement logs to stderr and stays on
    the always-correct XLA step. Returns ``(kind, step_fn)``.
    """
    import sys

    xla_step = make_wire_digest_step(
        num_segments=num_segments, cand=cand, strategy="xla",
    )
    if strategy == "xla":
        return "xla", xla_step
    on_tpu = False
    try:
        on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    except Exception:  # pragma: no cover
        pass
    if strategy == "auto" and not (on_tpu or interpret):
        return "xla", xla_step
    try:
        pallas_step = make_wire_digest_step(
            num_segments=num_segments, strategy="pallas",
            max_cand=max_cand, interpret=interpret,
        )
        args = (sample_wire, sample_n, query_xy, jnp.asarray(scale),
                jnp.asarray(origin), jnp.asarray(radius, jnp.float32))
        d_p = jax.jit(pallas_step)(*args)
        d_x = jax.jit(xla_step)(*args)
        if digests_agree(d_p.seg_min, d_p.rep, d_x.seg_min, d_x.rep):
            return "pallas", pallas_step
        sys.stderr.write(
            "wire-digest self-check FAILED: pallas digest disagrees with "
            "the XLA step on the sample pane — staying on XLA\n"
        )
    except Exception as e:
        sys.stderr.write(f"pallas wire digest disabled: {e!r}\n")
    if strategy == "pallas":
        raise RuntimeError(
            "strategy='pallas' was forced but the Pallas step failed its "
            "self-check or lowering — see stderr"
        )
    return "xla", xla_step
