"""CheckIn device kernel — the count-window pipeline as array ops.

The reference's CheckIn demo (apps/CheckIn.java:26-60) is two count
windows: a per-user count(2,1) pass that synthesizes a missed opposite
door event between two consecutive same-door events
(ProcessWinForInsertingMissingValues, CheckIn.java:251-321), then a
per-room running occupancy counter (ProcessForCountingObjects,
CheckIn.java:208-249). The host path (apps/checkin.py) walks events one
by one; this kernel runs a whole batch as ONE fixed-shape jit program —
the app-layer analog of StayTime's ``stay_time_cells_kernel``:

- consecutive-per-user detection = stable sort by user (stream order
  survives within a user) + neighbor compare — no per-event Python;
- the emission sequence is modeled as 2n SLOTS (slot 2i = optional
  synthesized event, slot 2i+1 = event i), mask-don't-compact;
- per-room running occupancy = a segmented cumulative sum in slot
  order (stable sort by room, cumsum, per-segment rebase, scatter
  back) — no data-dependent loops.

Bit-parity with the host generator: tests/test_apps.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def check_in_kernel(
    user: jnp.ndarray,
    room: jnp.ndarray,
    dirn: jnp.ndarray,
    ts: jnp.ndarray,
    valid: jnp.ndarray,
    num_rooms: int,
):
    """(n,) interned event arrays → (2n,) emission-slot arrays.

    ``user``/``room``: dense int32 ids; ``dirn``: +1 ("-in") / -1
    ("-out"); ``valid``: padding mask. Returns (out_room, out_dir,
    out_ts, out_valid, occupancy) where slot 2i carries event i's
    synthesized opposite event (valid only when the per-user count(2,1)
    window saw two same-door events) and slot 2i+1 carries event i;
    ``occupancy`` is the room's running counter AFTER the slot's event —
    exactly the host walk's emission order and values.
    """
    n = user.shape[0]
    # Group by user, stream order preserved within each user (stable).
    order = jnp.argsort(
        jnp.where(valid, user, jnp.int32(jnp.iinfo(jnp.int32).max)),
        stable=True,
    )
    u_s = user[order]
    r_s = room[order]
    d_s = dirn[order]
    t_s = ts[order]
    v_s = valid[order]
    samep = jnp.concatenate([
        jnp.zeros((1,), bool),
        (u_s[1:] == u_s[:-1]) & (r_s[1:] == r_s[:-1])
        & (d_s[1:] == d_s[:-1]) & v_s[1:] & v_s[:-1],
    ])
    prev_t = jnp.concatenate([t_s[:1], t_s[:-1]])
    mid_s = (prev_t + t_s) // 2  # CheckIn.java:286-305 midpoint
    # Back to stream order.
    synth = jnp.zeros((n,), bool).at[order].set(samep)
    mid = jnp.zeros((n,), ts.dtype).at[order].set(mid_s)

    # Emission slots: [synth_0?, ev_0, synth_1?, ev_1, ...].
    out_room = jnp.stack([room, room], axis=1).reshape(-1)
    out_dir = jnp.stack([-dirn, dirn], axis=1).reshape(-1)
    out_ts = jnp.stack([mid, ts], axis=1).reshape(-1)
    out_valid = jnp.stack([synth & valid, valid], axis=1).reshape(-1)

    # Per-room running occupancy over the slot sequence: segmented
    # cumulative sum (invalid slots key to the drop segment num_rooms).
    contrib = jnp.where(out_valid, out_dir, 0).astype(jnp.int32)
    key = jnp.where(out_valid, out_room, num_rooms).astype(jnp.int32)
    so = jnp.argsort(key, stable=True)  # slot order survives per room
    c_s = contrib[so]
    k_s = key[so]
    cs = jnp.cumsum(c_s)
    seg_start = jnp.concatenate(
        [jnp.ones((1,), bool), k_s[1:] != k_s[:-1]]
    )
    segid = jnp.cumsum(seg_start.astype(jnp.int32)) - 1
    # Segment base = total before the segment's first slot (one nonzero
    # contribution per segment → segment_sum gathers it exactly).
    base = jax.ops.segment_sum(
        jnp.where(seg_start, cs - c_s, 0), segid,
        num_segments=2 * n, indices_are_sorted=True,
    )
    occ_sorted = cs - base[segid]
    occupancy = jnp.zeros((2 * n,), jnp.int32).at[so].set(occ_sorted)
    return out_room, out_dir, out_ts, out_valid, occupancy
