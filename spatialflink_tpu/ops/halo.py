"""Grid-partitioned probe kernels — per-PAIR flag activity from flat cells.

The flag-table kernels (ops/range.py, ops/query_registry.py) need a
(num_cells + 1,) uint8 table per query, which is exactly the state the
replicated mesh path must broadcast. The grid-partitioned path derives
the SAME layer math per (point, query) pair from the two flat cell ids
alone::

    xi = cell // n,  yi = cell % n
    cheb = max(|Δxi|, |Δyi|)
    pair candidate  ⇔  cheb ≤ L_c        (grid.candidate_layers)
    pair guaranteed ⇔  cheb ≤ L_g        (grid.guaranteed_layers; −1 → none)

so a shard holding only its own rows plus its neighbors' boundary-cell
pane lanes (parallel/partition.py halo math) evaluates every active pair
with no table and no broadcast. Reductions mask inactive pairs to the
dtype max, so the reduced values are independent of lane order/count —
the mesh variants (parallel/halo.py) are bit-identical to these kernels.

Deliberate deviation from the table kernels (PARITY.md
"Grid-partitioned placement"): the table path's candidate check uses the
min distance over ALL query lanes, the per-pair path over ACTIVE pairs
only. An inactive pair sits ≥ L_c·cell ≥ radius away, so the two differ
only when an inactive pair ties the radius *exactly* — a measure-zero
boundary case.

All kernels are pure, fixed-shape, mask-don't-compact, and safe under
jit/vmap/shard_map (CLAUDE.md "Architecture invariants").
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from spatialflink_tpu.ops.distances import point_point_distance

__all__ = [
    "pair_layers",
    "range_partitioned_kernel",
    "join_partitioned_kernel",
    "registry_bucket_partitioned_kernel",
]


def pair_layers(cell_a: jnp.ndarray, cell_b: jnp.ndarray, grid_n: int):
    """Chebyshev ring number between two flat cell ids, broadcasting —
    the vectorized HelperClass.getCellLayerWRTQueryCell
    (grid.py:cell_layer). Out-of-grid sentinel cells (== n²) produce
    garbage layers; callers mask them via the in-grid check."""
    ax, ay = cell_a // grid_n, cell_a % grid_n
    bx, by = cell_b // grid_n, cell_b % grid_n
    return jnp.maximum(jnp.abs(ax - bx), jnp.abs(ay - by))


def _pair_active(cell, valid, q_cell, q_valid, grid_n: int, layers: int):
    """(N, Q) bool — pair within ``layers`` Chebyshev rings, both lanes
    live and in-grid."""
    num_cells = grid_n * grid_n
    cheb = pair_layers(cell[:, None], q_cell[None, :], grid_n)
    return (
        valid[:, None] & q_valid[None, :]
        & (cell[:, None] < num_cells) & (q_cell[None, :] < num_cells)
        & (cheb <= layers)
    )


def range_partitioned_kernel(
    xy: jnp.ndarray,
    valid: jnp.ndarray,
    cell: jnp.ndarray,
    query_xy: jnp.ndarray,
    query_cell: jnp.ndarray,
    query_valid: jnp.ndarray,
    radius,
    *,
    grid_n: int,
    layers: int,
    guaranteed: int,
    approximate: bool = False,
):
    """Point stream vs point query set, per-pair grid pruning.

    ``xy``: (N, 2); ``cell``: (N,) flat ids; ``query_xy``: (Q, 2) with
    per-lane cells/validity (padding lanes are simply inactive).
    Returns (keep (N,) bool, dist (N,)) where ``dist`` is the min over
    ACTIVE pairs (dtype max when none) — emission semantics match
    ops/range.py:_emit_mask per-pair: guaranteed pairs emit with no
    distance check, candidate pairs emit iff within radius
    (``approximate`` drops the distance check, mirroring the reference's
    approximateQuery flag).
    """
    d = point_point_distance(xy[:, None, :], query_xy[None, :, :])
    cand = _pair_active(cell, valid, query_cell, query_valid, grid_n, layers)
    big = jnp.asarray(jnp.finfo(d.dtype).max, d.dtype)
    if approximate:
        keep = valid & jnp.any(cand, axis=1)
    else:
        guar = (
            _pair_active(cell, valid, query_cell, query_valid, grid_n,
                         guaranteed)
            if guaranteed >= 0 else jnp.zeros_like(cand)
        )
        keep = valid & (
            jnp.any(guar, axis=1) | jnp.any(cand & (d <= radius), axis=1)
        )
    dist = jnp.min(jnp.where(cand, d, big), axis=1)
    return keep, dist


def join_partitioned_kernel(
    left_xy: jnp.ndarray,
    left_valid: jnp.ndarray,
    left_cell: jnp.ndarray,
    right_xy: jnp.ndarray,
    right_valid: jnp.ndarray,
    right_cell: jnp.ndarray,
    radius,
    *,
    grid_n: int,
    layers: int,
    budget: int,
):
    """Grid-pruned point ⋈ point join over flat cells.

    Emits every (left, right) pair within ``layers`` Chebyshev rings AND
    within ``radius``, compacted to ``budget`` lanes (−1 padding).
    Returns (left_idx, right_idx, dist, count, overflow) with LOCAL lane
    indices — the mesh wrapper maps them through its global-id panes.
    ``count`` is the true hit count; ``overflow = max(count − budget,
    0)`` drives the caller's retry-with-doubled-budget contract (same as
    ops/join.py's compact path).
    """
    d = point_point_distance(left_xy[:, None, :], right_xy[None, :, :])
    act = _pair_active(left_cell, left_valid, right_cell, right_valid,
                       grid_n, layers)
    hitm = act & (d <= radius)
    flat = hitm.reshape(-1)
    (hit,) = jnp.nonzero(flat, size=budget, fill_value=-1)
    found = hit >= 0
    hc = jnp.maximum(hit, 0)
    m = right_xy.shape[0]
    left_idx = jnp.where(found, (hc // m).astype(jnp.int32), -1)
    right_idx = jnp.where(found, (hc % m).astype(jnp.int32), -1)
    dist = jnp.where(found, d.reshape(-1)[hc], jnp.inf)
    count = jnp.sum(flat.astype(jnp.int32))
    overflow = jnp.maximum(count - budget, 0)
    return left_idx, right_idx, dist, count, overflow


def registry_bucket_partitioned_kernel(
    xy: jnp.ndarray,
    valid: jnp.ndarray,
    cell: jnp.ndarray,
    oid: jnp.ndarray,
    query_xy: jnp.ndarray,
    query_cell: jnp.ndarray,
    radius: jnp.ndarray,
    query_valid: jnp.ndarray,
    *,
    grid_n: int,
    layers: int,
    k: int,
    num_segments: int,
    query_block: int = 32,
):
    """Standing-query bucket (qserve) with per-pair grid pruning.

    Per query lane: per-object min distance over active pairs within the
    query's radius (``.at[].min`` into a (num_segments,) table — the
    canonical segment indexing makes lane order irrelevant, so the mesh
    variant's local+halo lane set reduces to the SAME table bitwise),
    then top-k over the table. ``layers`` is the bucket's radius-class
    ceiling (qserve buckets by radius class, so one static halo width
    covers every query in the bucket). Returns (dist (Q, k),
    segment (Q, k) int32 — −1 beyond ``within`` — num_valid (Q,),
    within (Q,)).
    """
    big = jnp.asarray(jnp.finfo(xy.dtype).max, xy.dtype)
    seg = jnp.clip(oid.astype(jnp.int32), 0, num_segments - 1)

    def one(q_xy, q_cell, rad, q_ok):
        d = point_point_distance(xy, q_xy[None, :])
        act = _pair_active(cell, valid, q_cell[None], q_ok[None], grid_n,
                           layers)[:, 0]
        dm = jnp.where(act & (d <= rad), d, big)
        table = jnp.full((num_segments,), big, dm.dtype).at[seg].min(dm)
        neg_top, seg_idx = jax.lax.top_k(-table, k)
        top_d = -neg_top
        top_seg = jnp.where(top_d < big, seg_idx.astype(jnp.int32), -1)
        within = jnp.sum((table < big).astype(jnp.int32))
        return top_d, top_seg, jnp.minimum(within, k), within

    # Same query blocking as registry_bucket_kernel: vmap only ``block``
    # lanes at a time under lax.map so peak memory stays O(block × N).
    q_total = query_xy.shape[0]
    block = next(b for b in (query_block, 16, 8, 4, 2, 1)
                 if q_total % b == 0)

    def blk(args):
        return jax.vmap(one)(*args)

    res = jax.lax.map(
        blk,
        (
            query_xy.reshape(-1, block, 2),
            query_cell.reshape(-1, block),
            radius.reshape(-1, block),
            query_valid.reshape(-1, block),
        ),
    )
    return tuple(x.reshape((q_total,) + x.shape[2:]) for x in res)
