"""Bucketed standing-query evaluation kernels — the device half of
qserve (spatialflink_tpu/qserve.py).

GeoFlink's execution model is one spatial query per Flink job (CIKM 2020
§IV); serving THOUSANDS of standing range/kNN queries against one object
stream needs the batched form instead: every registered query in a
bucket evaluates as ONE vmapped fixed-shape program per window. This
module generalizes ``ops/knn.py:knn_multi_query_kernel`` along the two
axes a registry needs:

- **per-query radius**: the radius is a traced ``(Q,)`` operand, not a
  static — queries with different radii share one compiled program, so
  registration churn across radii never recompiles;
- **padded query lanes**: buckets are padded to a power-of-two capacity
  rung (ops/compaction.py ladder — the host picks the rung from the LIVE
  query count), and ``query_valid`` masks the padding lanes to empty
  results. Padding never changes results (the mask-don't-compact kernel
  invariant).

One result shape serves both query kinds: per query, the top-``k``
distinct objects by min distance within that query's radius
(``ops/knn.py``'s segment-min + top-k core — the same dedup contract as
the reference's PQ/HashSet merge, KNNQuery.java:204-308). A kNN query
reads its first ``k_q ≤ k`` rows; a range query reads all ``num_valid``
rows (every row is within radius by construction) with ``within`` — the
UNCLAMPED count of distinct in-radius objects — as its exactness
counter: ``within > k`` means the rung truncated a range result
(``range_bucket_overflow``), the standard overflow-and-retry contract.

Per-query results are bit-identical to running ``ops/knn.py:
knn_points_fused`` once per query with that query's own flag table and
radius (parity pinned in tests/test_qserve.py); the mesh counterpart is
``parallel/sharded.py:sharded_registry_bucket`` (same pmin-reduce as the
other kNN kernels, CPU-mesh parity test alongside).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from spatialflink_tpu.ops.distances import point_point_distance
from spatialflink_tpu.ops.knn import _digest_from_point_dists, _finish_topk

__all__ = [
    "RegistryBucketResult",
    "registry_bucket_query",
    "registry_bucket_kernel",
    "range_bucket_overflow",
]


class RegistryBucketResult(NamedTuple):
    """Per-query top-k over a bucket. Leading axis = query lane; padded
    lanes (``query_valid`` False) carry dist=+big, segment=-1, counts 0."""

    dist: jnp.ndarray  # (Q, k) ascending min-distance per winning object
    segment: jnp.ndarray  # (Q, k) interned objID (-1 = padding)
    index: jnp.ndarray  # (Q, k) winning point's index in the window batch
    num_valid: jnp.ndarray  # (Q,) min(within, k)
    within: jnp.ndarray  # (Q,) distinct objects within radius, UNCLAMPED


def registry_bucket_query(
    xy, valid, cell, flags_table, oid, q_xy, radius, q_ok,
    k: int, num_segments: int, axis_name=None, index_base=None,
):
    """ONE standing query against the window batch — the shared core the
    vmapped bucket kernel and the sharded mesh counterpart both call.

    ``radius`` is a traced scalar (per-query operand); ``q_ok`` masks a
    padded query lane to an empty result. For a live lane this is
    exactly ``ops/knn.py:knn_points_fused``'s digest + top-k (same
    masked segment-min, same lowest-index tie-break), so bucketed
    results are bit-identical to per-query sequential evaluation.
    """
    from spatialflink_tpu.ops.cells import gather_cell_flags

    dist = point_point_distance(xy, q_xy[None, :])
    flags = gather_cell_flags(cell, flags_table)
    d = _digest_from_point_dists(
        dist, valid & q_ok, flags, oid, radius, num_segments,
        axis_name=axis_name, index_base=index_base,
    )
    big = jnp.asarray(jnp.finfo(d.seg_min.dtype).max, d.seg_min.dtype)
    within = jnp.sum((d.seg_min < big).astype(jnp.int32))
    res = _finish_topk(d.seg_min, d.rep, k)
    return res.dist, res.segment, res.index, res.num_valid, within


def registry_bucket_kernel(
    xy: jnp.ndarray,
    valid: jnp.ndarray,
    cell: jnp.ndarray,
    flags_tables: jnp.ndarray,
    oid: jnp.ndarray,
    query_xy: jnp.ndarray,
    radius: jnp.ndarray,
    query_valid: jnp.ndarray,
    k: int,
    num_segments: int,
    query_block: int = 32,
) -> RegistryBucketResult:
    """One bucket of standing queries in ONE program per window.

    ``query_xy``: (Q, 2); ``flags_tables``: (Q, num_cells+1) per-query
    neighbor-cell tables; ``radius``: (Q,) per-query radii (traced);
    ``query_valid``: (Q,) bool — padded rung lanes. ``k`` is the
    bucket's result-capacity rung and ``num_segments`` the interner
    bucket — the ONLY query-derived statics, so a registry sweeping any
    occupancy compiles at most ladder-many programs (the recompile
    detector sees stable signatures, not churn). Queries run in
    ``query_block``-sized vmapped chunks under ``lax.map`` so peak
    memory is O(query_block × N); Q must divide into blocks (the rung is
    a power of two ≥ 8, so any power-of-two block ≤ Q divides).
    """
    q_total = query_xy.shape[0]
    if q_total % query_block != 0:
        raise ValueError("pad the query bucket to a multiple of query_block")

    def one(q_xy, ftab, r, ok):
        return registry_bucket_query(
            xy, valid, cell, ftab, oid, q_xy, r, ok,
            k=k, num_segments=num_segments,
        )

    def block(args):
        q_blk, f_blk, r_blk, ok_blk = args
        return jax.vmap(one)(q_blk, f_blk, r_blk, ok_blk)

    nb = q_total // query_block
    res = jax.lax.map(
        block,
        (
            query_xy.reshape(nb, query_block, 2),
            flags_tables.reshape(nb, query_block, -1),
            radius.reshape(nb, query_block),
            query_valid.reshape(nb, query_block),
        ),
    )
    return RegistryBucketResult(
        *[x.reshape((q_total,) + x.shape[2:]) for x in res]
    )


def range_bucket_overflow(within: jnp.ndarray, k: int) -> jnp.ndarray:
    """Total distinct in-radius objects the rung could NOT return across
    a bucket — the range-query exactness counter (0 ⇒ every range result
    in the bucket is complete; otherwise climb the result-cap rung)."""
    return jnp.sum(jnp.maximum(within - k, 0))
