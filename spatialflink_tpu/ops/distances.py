"""Vectorized distance kernels.

Re-designs the scalar JVM loops of the reference's
``GeoFlink/utils/DistanceFunctions.java`` (getDistance overloads at :15-54,
point–segment at :96-131, bbox min-distances at :150-421) and
``HelperClass.computeHaverSine`` (HelperClass.java:379-385) as batched JAX
ops. Coordinates are planar (degrees or meters — the framework is unit
agnostic, exactly like the reference, which calls JTS ``.distance()`` on raw
coordinates). All kernels preserve the input dtype (float32 on TPU,
float64 in CPU parity tests).
"""

from __future__ import annotations

import jax.numpy as jnp


def point_point_distance(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Euclidean distance between points, broadcasting over leading dims.

    ``a``, ``b``: (..., 2) arrays. Mirrors
    DistanceFunctions.getPointPointEuclideanDistance (DistanceFunctions.java:60-63).
    """
    d = a - b
    return jnp.sqrt(jnp.sum(d * d, axis=-1))


def pairwise_distance(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """All-pairs Euclidean distance matrix.

    ``a``: (N, 2), ``b``: (M, 2) → (N, M). The batched replacement for the
    reference's per-record ``getDistance(p, q)`` hot loops (e.g.
    range/PointPointRangeQuery.java:152-186). Computed via explicit
    differences (not the |a|²+|b|²-2ab trick) for numerical parity with the
    reference's float64 JTS results.
    """
    d = a[:, None, :] - b[None, :, :]
    return jnp.sqrt(jnp.sum(d * d, axis=-1))


def point_segment_distance(
    p: jnp.ndarray, s1: jnp.ndarray, s2: jnp.ndarray
) -> jnp.ndarray:
    """Min distance from point(s) to line segment(s), broadcasting.

    ``p``, ``s1``, ``s2``: (..., 2). Vectorized form of
    DistanceFunctions.getPointLineSegmentMinEuclideanDistance
    (DistanceFunctions.java:96-131): project onto the segment, clamp the
    parameter to [0, 1], except degenerate zero-length segments which use
    the first endpoint (the reference leaves param = -1 there).
    """
    ap = p - s1
    ab = s2 - s1
    len_sq = jnp.sum(ab * ab, axis=-1)
    dot = jnp.sum(ap * ab, axis=-1)
    # Degenerate segment → param -1 → clamps to endpoint s1 (reference behavior).
    param = jnp.where(len_sq > 0, dot / jnp.where(len_sq > 0, len_sq, 1), -1.0)
    t = jnp.clip(param, 0.0, 1.0)
    closest = s1 + t[..., None] * ab
    return point_point_distance(p, closest)


def point_polyline_distance(
    p: jnp.ndarray, verts: jnp.ndarray, edge_valid: jnp.ndarray
) -> jnp.ndarray:
    """Min distance from points to a padded polyline's edges.

    ``p``: (N, 2) points; ``verts``: (V, 2) padded vertex array whose
    consecutive pairs form edges; ``edge_valid``: (V-1,) bool mask of real
    edges (padding and ring breaks are False). Vectorized form of
    DistanceFunctions.getPointCoordinatesArrayMinEuclideanDistance
    (DistanceFunctions.java:71-85): the min over per-edge point–segment
    distances. Works for both LineStrings and Polygon boundaries (JTS
    point.distance(polygon) for an exterior point is exactly the min edge
    distance; interior points are handled by ops.polygon).
    """
    s1 = verts[:-1]  # (E, 2)
    s2 = verts[1:]
    d = point_segment_distance(p[:, None, :], s1[None, :, :], s2[None, :, :])
    big = jnp.asarray(jnp.finfo(d.dtype).max, d.dtype)
    d = jnp.where(edge_valid[None, :], d, big)
    return jnp.min(d, axis=-1)


_EARTH_RADIUS_M = 6371008.7714  # mean Earth radius, matches mEarthRadius intent


def haversine_distance(
    lonlat_a: jnp.ndarray, lonlat_b: jnp.ndarray, radius: float = _EARTH_RADIUS_M
) -> jnp.ndarray:
    """Great-circle distance in meters, broadcasting over leading dims.

    The reference's ``computeHaverSine`` (HelperClass.java:379-385) uses the
    spherical-law-of-cosines form; we use the numerically stable haversine
    formula (identical result in float64, far better conditioned in
    float32 for nearby points — which is the common case on TPU).
    """
    lon1, lat1 = jnp.deg2rad(lonlat_a[..., 0]), jnp.deg2rad(lonlat_a[..., 1])
    lon2, lat2 = jnp.deg2rad(lonlat_b[..., 0]), jnp.deg2rad(lonlat_b[..., 1])
    dlat = lat2 - lat1
    dlon = lon2 - lon1
    h = jnp.sin(dlat / 2) ** 2 + jnp.cos(lat1) * jnp.cos(lat2) * jnp.sin(dlon / 2) ** 2
    return 2 * radius * jnp.arcsin(jnp.sqrt(jnp.clip(h, 0.0, 1.0)))


def bbox_point_min_distance(p: jnp.ndarray, bbox: jnp.ndarray) -> jnp.ndarray:
    """Min distance from point(s) to axis-aligned box(es); 0 inside.

    ``p``: (..., 2); ``bbox``: (..., 4) as (minx, miny, maxx, maxy).
    The closed form of the reference's case analysis in
    DistanceFunctions.getPointPolygonBBoxMinEuclideanDistance
    (DistanceFunctions.java:150-200), used by approximate query mode.
    """
    dx = jnp.maximum(jnp.maximum(bbox[..., 0] - p[..., 0], 0), p[..., 0] - bbox[..., 2])
    dy = jnp.maximum(jnp.maximum(bbox[..., 1] - p[..., 1], 0), p[..., 1] - bbox[..., 3])
    return jnp.sqrt(dx * dx + dy * dy)


def bbox_bbox_min_distance(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Min distance between two axis-aligned boxes; 0 if overlapping.

    ``a``, ``b``: (..., 4) as (minx, miny, maxx, maxy). Closed form of
    DistanceFunctions.getBBoxBBoxMinEuclideanDistance
    (DistanceFunctions.java:298-421).
    """
    dx = jnp.maximum(jnp.maximum(b[..., 0] - a[..., 2], 0), a[..., 0] - b[..., 2])
    dy = jnp.maximum(jnp.maximum(b[..., 1] - a[..., 3], 0), a[..., 1] - b[..., 3])
    return jnp.sqrt(dx * dx + dy * dy)
