"""Batched k-nearest-neighbor kernels with per-object dedup.

The reference computes kNN in two stages: a size-k max-heap per grid cell
per window (knn/PointPointKNNQuery.java:153-192) and a single-subtask
``windowAll`` merge that dedups objIDs keeping the min distance per object
(KNNQuery.java:204-308) — the documented bottleneck. On TPU the whole thing
is one program over the window batch:

  masked distance → segment-min over interned objID → lax.top_k.

Object IDs are host-interned to dense int32 (utils/interning.py); the
segment-min replaces the PQ+HashSet dedup logic exactly (min distance per
object, then global top-k of objects by that min).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from spatialflink_tpu.ops.distances import point_point_distance, point_polyline_distance
from spatialflink_tpu.ops.polygon import points_in_polygon


class KnnResult(NamedTuple):
    """Top-k objects by min distance. Padded slots have dist = +inf/seg = -1."""

    dist: jnp.ndarray  # (k,) ascending min-distance per winning object
    segment: jnp.ndarray  # (k,) interned objID (-1 = padding)
    index: jnp.ndarray  # (k,) index into the window batch of the winning point
    num_valid: jnp.ndarray  # () number of distinct objects within radius


class KnnPaneDigest(NamedTuple):
    """Per-object minima for one slide pane — the carryable unit of the
    incremental sliding-window kNN (the ListState-carry idea of
    range/PointPointRangeQuery.java:195-296 applied to the kNN merge)."""

    seg_min: jnp.ndarray  # (num_segments,) min dist per object; +big absent
    rep: jnp.ndarray  # (num_segments,) lowest global index at the min; int32-max absent


def _digest_from_point_dists(
    dist, valid, flags, oid, radius, num_segments,
    axis_name=None, index_base=None,
) -> KnnPaneDigest:
    """Masked distances → per-object (min distance, representative index).

    The representative is the lowest index achieving the object's min
    distance (deterministic tie-break; the reference's PQ keeps the
    first-seen of equal distances, KNNQuery.java:221-268). ``index_base``
    offsets batch-local indices to stream/global ones so digests from
    different panes (or shards) share one tie-break contract.
    """
    big = jnp.asarray(jnp.finfo(dist.dtype).max, dist.dtype)
    mask = valid & (dist <= radius)
    if flags is not None:
        # Grid pruning is a work-reduction device in the reference
        # (HelperClass cell classification); in a dense masked kernel the
        # radius test subsumes it for correctness (candidate cells cover
        # the query circle), so single-query fast paths may pass None.
        mask = mask & (flags > 0)
    masked = jnp.where(mask, dist, big)

    # (U,) min dist per object; the `big` sentinel marks absent/out-of-
    # radius objects. segment_min's identity for a segment with NO points
    # at all is +inf — clamp it to `big` so every absent object carries
    # ONE sentinel (the carry machinery pads with big, and the compact
    # digest can then match this path bit-for-bit).
    seg_min = jnp.minimum(
        jax.ops.segment_min(
            masked, oid, num_segments=num_segments, indices_are_sorted=False
        ),
        big,
    )
    if axis_name is not None:
        seg_min = jax.lax.pmin(seg_min, axis_name=axis_name)

    n = dist.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    if index_base is not None:
        idx = idx + index_base
    is_winner = mask & (masked == seg_min[oid])
    int_big = jnp.iinfo(jnp.int32).max
    rep = jax.ops.segment_min(
        jnp.where(is_winner, idx, int_big), oid, num_segments=num_segments
    )
    if axis_name is not None:
        rep = jax.lax.pmin(rep, axis_name=axis_name)
    return KnnPaneDigest(seg_min, rep)


def _finish_topk(seg_min, rep, k) -> KnnResult:
    big = jnp.asarray(jnp.finfo(seg_min.dtype).max, seg_min.dtype)
    neg_top, seg_ids = jax.lax.top_k(-seg_min, k)  # smallest distances
    top_dist = -neg_top
    found = top_dist < big
    seg_out = jnp.where(found, seg_ids.astype(jnp.int32), -1)
    idx_out = jnp.where(found, rep[seg_ids], -1)
    num_valid = jnp.sum((seg_min < big).astype(jnp.int32))
    return KnnResult(top_dist, seg_out, idx_out, jnp.minimum(num_valid, k))


def _topk_from_point_dists(
    dist, valid, flags, oid, radius, k, num_segments,
    axis_name=None, index_base=None,
):
    """Shared top-k core. With ``axis_name`` set (inside shard_map), the
    per-object minima and representative indices are pmin-reduced across the
    named mesh axis, and ``index_base`` offsets local indices to global ones
    — the single- and multi-chip paths share one tie-break contract.
    """
    d = _digest_from_point_dists(
        dist, valid, flags, oid, radius, num_segments,
        axis_name=axis_name, index_base=index_base,
    )
    return _finish_topk(d.seg_min, d.rep, k)


def knn_pane_digest(
    xy, valid, cell, flags_table, oid, query_xy, radius, index_base,
    num_segments: int,
) -> KnnPaneDigest:
    """One slide pane → carryable per-object minima (point query).

    Fused cell-flag gather + distance + segment-min. A sliding window's
    result is ``knn_merge_digests`` over its ``size/slide`` pane digests —
    per-slide device work shrinks from O(window) to O(pane) + an
    O(panes × num_segments) merge.
    """
    from spatialflink_tpu.ops.cells import gather_cell_flags

    dist = point_point_distance(xy, query_xy[None, :])
    return _digest_from_point_dists(
        dist, valid, gather_cell_flags(cell, flags_table), oid, radius,
        num_segments, index_base=index_base,
    )


def _digest_from_point_dists_compact(
    dist, valid, flags, oid, radius, num_segments,
    index_base=None, cand: int = 4096, selection: str = "auto",
) -> KnnPaneDigest:
    """Top-``cand``-compacted digest — the TPU-fast form of
    ``_digest_from_point_dists``.

    The scatter digest pays two O(N)-update scatters plus two O(N)
    gathers; on TPU those serialize badly (measured 33 Mpts/s at N=500k,
    num_segments=16k on v5e). The radius cut typically leaves far fewer
    than N finite distances, so: masked distances → ``lax.top_k`` of the
    ``cand`` smallest (TPU-efficient, stable lowest-index tie-break, same
    contract as the scatter path) → the identical segment-min digest over
    ``cand`` elements (tiny scatters; measured 445 Mpts/s). Exactness: if
    more than ``cand`` points are in radius, a ``lax.cond`` falls back to
    the full scatter digest — results are ALWAYS bit-identical to
    ``_digest_from_point_dists`` (parity test
    tests/test_knn_compact.py)."""
    if selection not in ("auto", "blocked", "topk"):
        raise ValueError(
            f"selection must be 'auto', 'blocked' or 'topk', "
            f"got {selection!r}"
        )
    if cand >= dist.shape[0]:
        # Pane no larger than the compaction width: nothing to compact
        # (static shapes, so this is a compile-time decision).
        return _digest_from_point_dists(
            dist, valid, flags, oid, radius, num_segments,
            index_base=index_base,
        )
    big = jnp.asarray(jnp.finfo(dist.dtype).max, dist.dtype)
    mask = valid & (dist <= radius)
    if flags is not None:
        mask = mask & (flags > 0)
    masked = jnp.where(mask, dist, big)
    int_big = jnp.iinfo(jnp.int32).max

    # The digest needs the in-radius SET, not an ordering (min is exactly
    # commutative, so any candidate order yields the bit-identical
    # digest) — so the SELECTION strategy is a per-backend choice with
    # identical results (parity test exercises both explicitly):
    #   - "blocked" (TPU): sort-free prefix-sum one-hot select per
    #     512-lane block. lax.top_k lowers to a full per-pane sort on TPU
    #     — 0.63 ms of the 0.94 ms headline slide step (profiler trace,
    #     BASELINE.md); this costs ~0.1 ms. Exact when no block holds
    #     more than per_block in-radius points (scatter fallback below).
    #   - "topk" (CPU & default): lax.top_k — the blocked select's 8M-
    #     element one-hot tensor runs ~9× SLOWER than the AVX sort on
    #     XLA:CPU (measured 158M → 18M pts/s on the headline CPU
    #     baseline), so each backend gets its best program and the
    #     CPU-vs-TPU comparison stays honest.
    if selection == "auto":
        from spatialflink_tpu.ops.select import onehot_select_preferred

        selection = "blocked" if onehot_select_preferred() else "topk"

    def _finish(ci, cvalid):
        coid = oid[ci]
        cm = jnp.where(cvalid, masked[ci], big)
        # Segments receiving no candidate get segment_min's identity
        # (+inf); clamp to the scatter path's `big` sentinel for
        # bit-parity (real distances are ≤ radius, far below big).
        sm = jnp.minimum(
            jax.ops.segment_min(cm, coid, num_segments=num_segments), big
        )
        idx = ci if index_base is None else ci + index_base
        win = cvalid & (cm == sm[coid])
        rep = jax.ops.segment_min(
            jnp.where(win, idx, int_big), coid, num_segments=num_segments
        )
        return KnnPaneDigest(sm, rep)

    def full(_):
        return _digest_from_point_dists(
            dist, valid, flags, oid, radius, num_segments,
            index_base=index_base,
        )

    if selection == "blocked":
        from spatialflink_tpu.ops.select import first_k_onehot

        lane_block = 512
        n = masked.shape[0]
        nb = -(-n // lane_block)
        per_block = int(min(lane_block, max(16, cand // max(nb, 1))))
        npad = nb * lane_block
        m2 = jnp.pad(mask, (0, npad - n)).reshape(nb, lane_block)
        # Only the cheap counts decide the branch; the large one-hot is
        # built INSIDE compact() so the scatter fallback never pays it
        # (branch closures become cond operands, evaluated eagerly).
        cnt = jnp.sum(m2.astype(jnp.int32), axis=1)
        block_overflow = jnp.sum(jnp.maximum(cnt - per_block, 0))

        def compact(_):
            hit, _cnt, _of = first_k_onehot(m2, per_block)
            lanes = jnp.arange(lane_block, dtype=jnp.int32)
            in_block = jnp.sum(
                hit * lanes[None, :, None], axis=1, dtype=jnp.int32
            )  # (nb, per_block)
            base = (jnp.arange(nb, dtype=jnp.int32) * lane_block)[:, None]
            ci = jnp.minimum(
                (in_block + base).reshape(-1), jnp.int32(n - 1)
            )
            slots = jnp.arange(per_block, dtype=jnp.int32)
            cvalid = (
                slots[None, :] < jnp.minimum(cnt, per_block)[:, None]
            ).reshape(-1)
            return _finish(ci, cvalid)

        return jax.lax.cond(block_overflow == 0, compact, full, None)

    # selection == "topk"
    n_in = jnp.sum(mask.astype(jnp.int32))

    def compact_topk(_):
        negd, ci = jax.lax.top_k(-masked, cand)
        cvalid = -negd < big
        return _finish(ci, cvalid)

    return jax.lax.cond(n_in <= cand, compact_topk, full, None)


def knn_pane_digest_compact(
    xy, valid, cell, flags_table, oid, query_xy, radius, index_base,
    num_segments: int, cand: int = 4096, selection: str = "auto",
) -> KnnPaneDigest:
    """``knn_pane_digest`` via top-``cand`` compaction (TPU fast path).

    Pass ``cell``/``flags_table`` as None to skip the per-point flag
    gather: for a single point query the radius test subsumes the grid
    pruning (candidate cells cover the query circle), and the gather is
    the single most expensive op in the scatter digest on TPU. Bit-exact
    vs ``knn_pane_digest`` either way (automatic scatter fallback when
    over ``cand`` points are in radius)."""
    from spatialflink_tpu.ops.cells import gather_cell_flags

    dist = point_point_distance(xy, query_xy[None, :])
    flags = (
        None if flags_table is None else gather_cell_flags(cell, flags_table)
    )
    return _digest_from_point_dists_compact(
        dist, valid, flags, oid, radius, num_segments,
        index_base=index_base, cand=cand, selection=selection,
    )


def _geometry_query_dists(xy, query_verts, query_edge_valid,
                          query_polygonal: bool):
    edge_d = point_polyline_distance(xy, query_verts, query_edge_valid)
    if query_polygonal:
        inside = points_in_polygon(xy, query_verts, query_edge_valid)
        return jnp.where(inside, jnp.zeros((), edge_d.dtype), edge_d)
    return edge_d


def knn_pane_digest_geometry(
    xy, valid, cell, flags_table, oid, query_verts, query_edge_valid,
    radius, index_base, num_segments: int, query_polygonal: bool,
) -> KnnPaneDigest:
    """Pane digest for a polygon (containment → 0) or open-polyline query."""
    from spatialflink_tpu.ops.cells import gather_cell_flags

    dist = _geometry_query_dists(xy, query_verts, query_edge_valid,
                                 query_polygonal)
    return _digest_from_point_dists(
        dist, valid, gather_cell_flags(cell, flags_table), oid, radius,
        num_segments, index_base=index_base,
    )


def knn_pane_digest_geometry_compact(
    xy, valid, cell, flags_table, oid, query_verts, query_edge_valid,
    radius, index_base, num_segments: int, query_polygonal: bool,
    cand: int = 4096, selection: str = "auto",
) -> KnnPaneDigest:
    """Geometry-query pane digest via top-``cand`` compaction.

    Same exactness contract as ``knn_pane_digest_compact``; pass
    ``cell``/``flags_table`` as None to skip the flag gather — the
    candidate cells of ``neighbor_flags(radius, geometry cells)`` cover
    every point within ``radius`` of the geometry (containment included:
    an inside point lies in the geometry's own cells), so the radius test
    subsumes the pruning flags for correctness."""
    from spatialflink_tpu.ops.cells import gather_cell_flags

    dist = _geometry_query_dists(xy, query_verts, query_edge_valid,
                                 query_polygonal)
    flags = (
        None if flags_table is None else gather_cell_flags(cell, flags_table)
    )
    return _digest_from_point_dists_compact(
        dist, valid, flags, oid, radius, num_segments,
        index_base=index_base, cand=cand, selection=selection,
    )


def knn_merge_digests(seg_min_stack, rep_stack, k: int, bases=None) -> KnnResult:
    """(P, num_segments) stacked pane digests → window top-k.

    Per-object window minimum = min over panes; the representative is the
    lowest index among panes achieving that minimum — identical
    tie-breaking to the fused single-program kernel over the whole window
    (parity-tested), and to the reference's PQ merge (KNNQuery.java:204-308).

    ``bases``: optional (P,) int32 window-local offsets added to each
    pane's LOCAL representative indices (digests produced with
    index_base=0). Offsetting inside the merge keeps carried digests
    unbounded-stream-safe: indices never exceed the window's event count.
    Absent objects (rep == int32-max sentinel) stay at the sentinel.
    """
    int_big = jnp.iinfo(jnp.int32).max
    if bases is not None:
        rep_stack = jnp.where(
            rep_stack == int_big, int_big, rep_stack + bases[:, None]
        )
    gmin = jnp.min(seg_min_stack, axis=0)
    qual = seg_min_stack <= gmin[None, :]
    rep = jnp.min(jnp.where(qual, rep_stack, int_big), axis=0)
    return _finish_topk(gmin, rep, k)


def knn_merge_digest_list(seg_mins, reps, bases, k: int) -> KnnResult:
    """Tuple-of-digests form of ``knn_merge_digests`` — stacking happens
    INSIDE the jitted program, so a per-window merge is one dispatch with
    no eager device ops (the tuple length is static per window config)."""
    return knn_merge_digests(
        jnp.stack(seg_mins), jnp.stack(reps), k, bases=jnp.asarray(bases)
    )


def knn_kernel(
    xy: jnp.ndarray,
    valid: jnp.ndarray,
    flags: jnp.ndarray,
    oid: jnp.ndarray,
    query_xy: jnp.ndarray,
    radius,
    k: int,
    num_segments: int,
    axis_name=None,
    index_base=None,
) -> KnnResult:
    """Point-stream kNN around a single query point.

    ``xy``: (N, 2); ``oid``: (N,) interned int32 object ids in
    [0, num_segments); ``query_xy``: (2,). ``k`` and ``num_segments`` are
    static. Replaces the full two-stage pipeline of
    PointPointKNNQuery.windowBased (knn/PointPointKNNQuery.java:132-201) +
    KNNQuery.kNNWinAllEvaluation (KNNQuery.java:204-308).
    """
    dist = point_point_distance(xy, query_xy[None, :])
    return _topk_from_point_dists(
        dist, valid, flags, oid, radius, k, num_segments,
        axis_name=axis_name, index_base=index_base,
    )


def knn_polygon_query_kernel(
    xy: jnp.ndarray,
    valid: jnp.ndarray,
    flags: jnp.ndarray,
    oid: jnp.ndarray,
    query_verts: jnp.ndarray,
    query_edge_valid: jnp.ndarray,
    radius,
    k: int,
    num_segments: int,
    axis_name=None,
    index_base=None,
) -> KnnResult:
    """Point-stream kNN around a polygon query (JTS distance: 0 inside).

    Batched form of PointPolygonKNNQuery (knn/PointPolygonKNNQuery.java:67-88).
    """
    edge_d = point_polyline_distance(xy, query_verts, query_edge_valid)
    inside = points_in_polygon(xy, query_verts, query_edge_valid)
    dist = jnp.where(inside, jnp.zeros((), edge_d.dtype), edge_d)
    return _topk_from_point_dists(
        dist, valid, flags, oid, radius, k, num_segments,
        axis_name=axis_name, index_base=index_base,
    )


def knn_polyline_query_kernel(
    xy: jnp.ndarray,
    valid: jnp.ndarray,
    flags: jnp.ndarray,
    oid: jnp.ndarray,
    query_verts: jnp.ndarray,
    query_edge_valid: jnp.ndarray,
    radius,
    k: int,
    num_segments: int,
    axis_name=None,
    index_base=None,
) -> KnnResult:
    """Point-stream kNN around an open linestring query: min edge distance,
    NO containment (an open polyline encloses nothing) — the kNN analog of
    range_query_polylines_kernel (knn/PointLineStringKNNQuery.java)."""
    dist = point_polyline_distance(xy, query_verts, query_edge_valid)
    return _topk_from_point_dists(
        dist, valid, flags, oid, radius, k, num_segments,
        axis_name=axis_name, index_base=index_base,
    )


def knn_points_fused(xy, valid, cell, flags_table, oid, query_xy, radius,
                     k: int, num_segments: int,
                     axis_name=None, index_base=None) -> KnnResult:
    """Cell-flag gather + kNN in one jitted program (per-window fast path).

    ``axis_name``/``index_base`` thread through to the top-k core so the
    multi-chip path (shard_map over a mesh's ``data`` axis) runs this SAME
    program per shard — parity with single-device by construction."""
    from spatialflink_tpu.ops.cells import gather_cell_flags

    return knn_kernel(
        xy, valid, gather_cell_flags(cell, flags_table), oid, query_xy,
        radius, k=k, num_segments=num_segments,
        axis_name=axis_name, index_base=index_base,
    )


def knn_polygon_fused(xy, valid, cell, flags_table, oid, query_verts,
                      query_edge_valid, radius, k: int, num_segments: int,
                      axis_name=None, index_base=None) -> KnnResult:
    from spatialflink_tpu.ops.cells import gather_cell_flags

    return knn_polygon_query_kernel(
        xy, valid, gather_cell_flags(cell, flags_table), oid, query_verts,
        query_edge_valid, radius, k=k, num_segments=num_segments,
        axis_name=axis_name, index_base=index_base,
    )


def knn_polyline_fused(xy, valid, cell, flags_table, oid, query_verts,
                       query_edge_valid, radius, k: int, num_segments: int,
                       axis_name=None, index_base=None) -> KnnResult:
    from spatialflink_tpu.ops.cells import gather_cell_flags

    return knn_polyline_query_kernel(
        xy, valid, gather_cell_flags(cell, flags_table), oid, query_verts,
        query_edge_valid, radius, k=k, num_segments=num_segments,
        axis_name=axis_name, index_base=index_base,
    )


def knn_multi_query_kernel(
    xy: jnp.ndarray,
    valid: jnp.ndarray,
    cell: jnp.ndarray,
    flags_tables: jnp.ndarray,
    oid: jnp.ndarray,
    query_xy: jnp.ndarray,
    radius,
    k: int,
    num_segments: int,
    query_block: int = 32,
) -> KnnResult:
    """kNN for a BATCH of query points in one program — the multi-query
    vmap surface (one windowAll merge per query in the reference,
    KNNQuery.java:204-308; here one fused program for all of them).

    ``query_xy``: (Q, 2); ``flags_tables``: (Q, num_cells+1) per-query
    neighbor-cell flag tables (each query prunes by its own candidate
    cells, PointPointKNNQuery.java:134-150). Returns a KnnResult whose
    fields carry a leading Q axis. Queries are processed in
    ``query_block``-sized vmapped chunks under ``lax.map`` so peak memory
    is O(query_block × N) rather than O(Q × N); Q must divide into
    blocks (pad queries to a multiple of ``query_block``, extra lanes are
    cheap and discarded by the caller).
    """
    from spatialflink_tpu.ops.cells import gather_cell_flags

    q_total = query_xy.shape[0]
    if q_total % query_block != 0:
        raise ValueError("pad query batch to a multiple of query_block")

    def one(q_xy, flags_table):
        dist = point_point_distance(xy, q_xy[None, :])
        return _topk_from_point_dists(
            dist, valid, gather_cell_flags(cell, flags_table), oid,
            radius, k, num_segments,
        )

    def block(args):
        q_blk, f_blk = args
        return jax.vmap(one)(q_blk, f_blk)

    res = jax.lax.map(
        block,
        (
            query_xy.reshape(-1, query_block, 2),
            flags_tables.reshape(q_total // query_block, query_block, -1),
        ),
    )
    return KnnResult(*[x.reshape((q_total,) + x.shape[2:]) for x in res])


def knn_geometry_query_kernel(
    obj_verts: jnp.ndarray,
    obj_edge_valid: jnp.ndarray,
    valid: jnp.ndarray,
    flags: jnp.ndarray,
    oid: jnp.ndarray,
    query_verts: jnp.ndarray,
    query_edge_valid: jnp.ndarray,
    radius,
    k: int,
    num_segments: int,
    obj_polygonal: bool = False,
    query_polygonal: bool = False,
    axis_name=None,
    index_base=None,
) -> KnnResult:
    """Geometry-stream kNN with full JTS distance semantics.

    Distance per object = ``geometry_pair_distance`` (overlap/containment →
    0), matching the reference's ``DistanceFunctions.getDistance`` calls in
    the Polygon/LineString KNN window loops (DistanceFunctions.java:15-54 —
    JTS returns 0 whenever the geometries intersect, including a query
    point inside a polygon). A Point query packs as a degenerate one-edge
    boundary.
    """
    from spatialflink_tpu.ops.range import geometry_pair_distance

    def one_obj(verts, ev):
        return geometry_pair_distance(
            verts, ev, query_verts, query_edge_valid,
            obj_polygonal, query_polygonal,
        )

    dist = jax.vmap(one_obj)(obj_verts, obj_edge_valid)  # (N,)
    return _topk_from_point_dists(
        dist, valid, flags, oid, radius, k, num_segments,
        axis_name=axis_name, index_base=index_base,
    )


def knn_geometry_bbox_kernel(
    obj_bbox: jnp.ndarray,
    valid: jnp.ndarray,
    flags: jnp.ndarray,
    oid: jnp.ndarray,
    query_bbox: jnp.ndarray,
    radius,
    k: int,
    num_segments: int,
    axis_name=None,
    index_base=None,
) -> KnnResult:
    """Geometry-stream kNN in APPROXIMATE mode: per-object distance is the
    min distance between the object's bounding box and the query's
    (``bbox_bbox_min_distance``) — the reference's approximateQuery
    branches in every geometry-stream KNN variant
    (knn/LineStringLineStringKNNQuery.java:95-110 getBBoxBBox...,
    knn/PolygonPointKNNQuery.java:95 getPointPolygonBBox... — a Point
    query packs as a degenerate [x, y, x, y] box, which reduces
    bbox↔bbox to the reference's point↔bbox case analysis exactly).

    ``obj_bbox``: (N, 4) minx,miny,maxx,maxy (GeometryBatch.bbox, centered
    like the vertex coords); ``query_bbox``: (4,).
    """
    from spatialflink_tpu.ops.distances import bbox_bbox_min_distance

    dist = bbox_bbox_min_distance(obj_bbox, query_bbox[None, :])
    return _topk_from_point_dists(
        dist, valid, flags, oid, radius, k, num_segments,
        axis_name=axis_name, index_base=index_base,
    )
