"""Batched JAX kernels — the TPU compute path of the framework.

Every kernel here operates on fixed-shape, padded, structure-of-arrays
batches and is safe under ``jax.jit`` / ``jax.vmap`` / ``shard_map``:
no Python control flow on traced values, masking instead of compaction,
``lax.top_k`` / segment reductions instead of priority queues.
"""

from spatialflink_tpu.ops.distances import (  # noqa: F401
    point_point_distance,
    pairwise_distance,
    point_segment_distance,
    point_polyline_distance,
    haversine_distance,
    bbox_point_min_distance,
    bbox_bbox_min_distance,
)
from spatialflink_tpu.ops.cells import (  # noqa: F401
    assign_cells,
    gather_cell_flags,
)
from spatialflink_tpu.ops.polygon import (  # noqa: F401
    points_in_polygon,
    point_polygon_distance,
)
from spatialflink_tpu.ops.range import range_query_kernel  # noqa: F401
from spatialflink_tpu.ops.knn import knn_kernel  # noqa: F401
from spatialflink_tpu.ops.join import join_kernel, cross_join_kernel  # noqa: F401
