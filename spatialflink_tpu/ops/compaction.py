"""Fixed-shape live-slot compaction — the shared bucket-ladder control
plane for the pane engines.

The device pane engines keep window state in fixed-capacity structures
sized for the worst case: the tJoin ring planes hold ``cap_w`` slots per
cell (live AND expired — expiry is lazy), and the wire-kNN digest pads
every pane to a power-of-two bucket. Probing the worst-case shape is
where the XLA:CPU device scan lost ~50× to the native engine's
live-points-only loops (VERDICT r5 advice #4): every ring slot was
gathered, alive or dead, and the first-``pair_sel`` match selection ran
a full ``lax.top_k`` sort over that worst-case width.

This module is the HOST half of the fix — a small ladder of
power-of-two capacities and the occupancy math that picks a bucket from
the LIVE count:

- ``capacity_ladder(cap)`` / ``pick_capacity(live, cap)``: the static
  probe capacity ``cap_c`` the device program is compiled for. Because
  the ladder is tiny (≤6 powers of two between ``CAP_LADDER_MIN`` and
  ``cap_w``), a stream sweeping any occupancy compiles at most
  ladder-many programs per engine — the recompile detector
  (telemetry.py) sees a handful of STABLE signatures, not churn.
- ``max_window_cell_count``: exact per-cell window occupancy bound for
  a bounded stream (vectorized two-pointer over the (cell, pane)-sorted
  events), so ``run_soa_panes`` picks the bucket before the scan and
  the in-kernel ``cmp_overflow`` counter is a safety net, not a retry
  treadmill.
- ``wire_pane_bucket``: the wire-kNN pane-capacity bucket (one shared
  home for the operator and the benches), recorded per bucket in
  telemetry so occupancy drift is visible.

The DEVICE half lives in ops/tjoin_panes.py: the live slots of a ring
cell row are the contiguous ``[cursor - live, cursor)`` range (points
insert in pane order and expire in pane order — a FIFO), so the
compacted view needs no data movement at all: the probe gathers
``cap_c`` lanes starting at the per-cell head and masks by position.
Padding lanes past the live count stay masked — compaction is a
host-chosen static SHAPE, never a data-dependent one, so the
mask-don't-compact kernel invariant holds (PARITY.md "Fixed-shape
live-slot compaction").
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

#: Smallest probe capacity the ladder offers. Below this the per-point
#: gather is already trivially small; more rungs would only add compiles.
CAP_LADDER_MIN = 8

#: Wire-kNN panes bucket at this floor (the historical run_wire_panes
#: minimum — kept so existing compiled shapes and tests are unchanged).
PANE_BUCKET_MIN = 128


def capacity_ladder(cap: int, minimum: int = CAP_LADDER_MIN) -> Tuple[int, ...]:
    """Powers of two from ``minimum`` up to ``cap`` (inclusive; ``cap``
    itself is appended even when not a power of two so the full-ring
    probe is always the top rung). cap_w = 64 → (8, 16, 32, 64): 4
    buckets; cap_w = 256 → 6 buckets."""
    if cap < minimum:
        return (cap,)
    out = []
    b = minimum
    while b < cap:
        out.append(b)
        b <<= 1
    out.append(cap)
    return tuple(out)


def pick_capacity(live: int, cap: int, minimum: int = CAP_LADDER_MIN) -> int:
    """Smallest ladder rung ≥ ``live`` (the bucketed probe capacity).
    ``live`` beyond the ladder top clamps to ``cap`` — the ring capacity
    bounds live occupancy anyway (the cap_overflow retry contract).

    Under an active overload ``clamp_compaction`` rung
    (spatialflink_tpu/overload.py) the pick is FLOORED: occupancy churn
    below the clamp stops changing rungs — each fresh rung is a ~1-2 s
    XLA recompile, exactly the cost a loaded pipeline can't pay.
    Result-preserving: the rung only ever grows (padding stays masked),
    and a clamp of 0 pins the top rung (one program for the whole run).
    """
    from spatialflink_tpu import overload

    clamp = overload.compaction_clamp()
    if clamp is not None:
        live = cap if clamp <= 0 else max(live, clamp)
    for b in capacity_ladder(cap, minimum):
        if b >= live:
            return b
    return cap


def max_window_cell_count(pane: np.ndarray, cell: np.ndarray,
                          ppw: int) -> int:
    """Exact max, over every (cell, slide), of the number of events of
    one cell inside the window ``(t - ppw, t]`` — the live-occupancy
    bound the bucket pick needs.

    Vectorized: sort events by (cell, pane); for event i the window
    ending at its own pane holds ``i - lo + 1`` same-cell events, where
    ``lo`` is the first same-cell event with pane > pane_i - ppw
    (binary search on the composite key). The max over slides is
    attained at some event's own pane (occupancy only grows when an
    event enters), so the per-event max is the global max.
    """
    n = len(pane)
    if n == 0:
        return 0
    pane = np.asarray(pane, np.int64)  # sfcheck: ok=trace-hygiene -- HOST control plane by design (module docstring): the occupancy plan reads live counts on the host to pick the static bucket; never traced
    cell = np.asarray(cell, np.int64)  # sfcheck: ok=trace-hygiene -- same host-side occupancy plan as above
    span = int(pane.max()) + 1
    key = cell * span + pane
    order = np.argsort(key, kind="stable")
    ks = key[order]
    lo = np.searchsorted(
        ks, cell[order] * span + np.maximum(pane[order] - ppw + 1, 0)
    )
    return int((np.arange(n) - lo + 1).max())


def compact_probe_preferred() -> bool:
    """True on backends where the compacted positional probe (element
    gathers over ``cap_c`` live lanes + prefix-sum/binary-search
    selection) beats the full-ring row-gather probe. On TPU the row
    gather + one-hot select is the measured-preferred form (element
    gathers and per-lane masks are the TPU-slow ops — ops/select.py);
    everywhere else the compacted probe wins by avoiding the
    ``lax.top_k`` full sort (~45% of the XLA:CPU slide step)."""
    import jax

    try:
        return jax.default_backend() not in ("tpu", "axon")
    except Exception:  # pragma: no cover - backend init failure
        return True


def wire_pane_bucket(n: int, minimum: int = PANE_BUCKET_MIN) -> int:
    """Bucketed wire-pane capacity (power-of-two ladder above
    ``minimum``) — ONE home for run_wire_panes and the benches, with the
    pick recorded per bucket in telemetry (occupancy drift between
    panes shows up as bucket churn there, and as ≤log₂ many compiled
    digest shapes in the recompile detector)."""
    from spatialflink_tpu.telemetry import telemetry
    from spatialflink_tpu.utils.padding import next_bucket

    b = int(next_bucket(max(int(n), 1), minimum=minimum))  # sfcheck: ok=trace-hygiene -- host control plane (module docstring): pane length is a host int picking a static bucket, never a tracer
    telemetry.record_compaction("wire_pane_digest", b, int(n))  # sfcheck: ok=trace-hygiene -- same host-side bucket pick as above
    return b
