"""Pane-carry tJoin — the extreme-overlap sliding trajectory join.

The reference's windowBased tJoin re-walks the whole window per fire
(tJoin/PointPointTJoinQuery.java:183+); at the domain's extreme-overlap
configs (10 s windows sliding every 10 ms — Q2_BrakeMonitor's window
style, ppw = 1000) that is a 1000× redundant recompute per slide, and so
is this repo's ``run_soa`` (one full-window join per fire). This module
keeps the WINDOW STATE ON DEVICE and does only O(new-pane) join work per
slide:

- **Ring-buffer bucket planes** per stream side: (cells · capW) slots of
  x/y/oid/pane-tag with a per-cell write cursor. Inserting a pane is a
  small scatter; expiry is LAZY — probes mask slots whose pane tag left
  the window, and a slot is reused (cursor ring) long after it expired.
- **Min-pane-indexed pair digests**: ``D[m % ppw, lid·K + rid]`` = min
  point-pair distance among pairs whose EARLIER point sits in pane
  ``m``. A point pair (i ≤ j) is alive for window [s, s+ppw) iff i ≥ s,
  and every contribution discovered so far has j ≤ current pane — so at
  emission time ``min over m ∈ [s, t]`` of D is exactly the window's
  per-trajectory-pair min distance (the tStats min-pane argument,
  applied to a bilinear join).
- Per slide: probe the new LEFT pane against the RIGHT window planes,
  insert the left pane, probe the new RIGHT pane against the LEFT
  planes (now containing pane t — covers new×new exactly once), insert
  the right pane, then reduce the digest ring for the window ending at
  pane t. All of it is one ``lax.scan`` step — one dispatch per BATCH
  of slides, not per slide (the tunnel-dispatch lesson, CLAUDE.md).

- **Live-slot compaction** (``cap_c > 0``, the default off-TPU): the
  ring with lazy expiry is a per-cell FIFO — points insert in pane
  order and expire in pane order — so the LIVE slots of a cell row are
  always the contiguous ``[cursor - live, cursor)`` range (mod capW).
  The carry maintains per-cell live counts (two tiny scatter-adds per
  slide: subtract the expiring pane, add the new one), and the probe
  gathers only ``cap_c`` lanes from each neighbor cell's head instead
  of the full ``capW`` ring row, masking by POSITION (lane < live)
  instead of gathering and comparing pane tags. ``cap_c`` is a static
  bucket from the host-picked capacity ladder (ops/compaction.py — the
  host reads the live counts, the device program stays fixed-shape per
  bucket, ≤6 programs per engine), and first-``pair_sel`` selection is
  the sort-free prefix-sum binary search (ops/select.py:
  first_k_prefix_indices) — together they removed the ``lax.top_k``
  full sort and the dead-slot gathers that made the XLA:CPU scan ~50×
  slower than the native engine (VERDICT r5 advice #4). ``cap_c = 0``
  keeps the original full-ring row-gather probe (the TPU-preferred
  form, and the parity oracle for the compacted path).

Exactness contract (same family as the other join kernels): results
equal ``run_soa`` iff ``cap_overflow == 0`` (a live window slot was
never overwritten — grow ``capW``), ``sel_overflow == 0`` (no probe
point matched more than ``pair_sel`` window points — grow
``pair_sel``) and ``cmp_overflow == 0`` (no PROBED cell held more than
``cap_c`` live points — climb the capacity ladder; never fires when the
host planned ``cap_c`` from ops/compaction.py:max_window_cell_count).
Digest memory is ``ppw · K² · 4`` bytes (K = interned
trajectory ids per side): extreme overlap trades memory for the 1000×
work cut, sized for the domain's dozens-to-hundreds of vehicles.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

import numpy as np

from spatialflink_tpu.ops.select import (
    first_k_onehot,
    first_k_prefix_indices,
    onehot_select_preferred,
)


def pane_cell_ranks(pane: "np.ndarray", cell: "np.ndarray",
                    valid: "np.ndarray" = None) -> "np.ndarray":
    """Within-(pane, cell) slot ranks, vectorized — the host half of
    ``_insert``'s ring-slot contract (a pane's same-cell points need
    distinct slots). ONE home, shared by the operator wrapper and the
    benchmark staging (drift here would silently change collision
    behavior between the product path and the measured path).

    ``valid``: rank INVALID (out-of-grid) events in their own group, not
    the cell their placeholder id aliases. ``_insert`` drops invalid
    points and advances the cursor only by the valid count, so a valid
    point whose rank counted a preceding invalid same-cell event would
    land BEYOND the cursor — outside the ``[cursor - live, cursor)``
    range the compacted probe treats as the live slots (a silent missed
    pair; the full-ring probe's tag scan was immune, which is why this
    stayed latent until the positional probe — code review)."""
    n = len(pane)
    if valid is not None:
        cell = np.where(valid, cell, -1)
    order = np.lexsort((cell, pane))
    ps, cs = pane[order], cell[order]
    newrun = np.ones(n, bool)
    if n > 1:
        newrun[1:] = (ps[1:] != ps[:-1]) | (cs[1:] != cs[:-1])
    run_id = np.cumsum(newrun) - 1
    pos = np.arange(n)
    rank = np.empty(n, np.int64)
    rank[order] = pos - pos[newrun][run_id]
    return rank


class TJoinPaneCarry(NamedTuple):
    lwx: jnp.ndarray  # (cells*capW,) left window planes
    lwy: jnp.ndarray
    lwoid: jnp.ndarray  # int32
    lwtag: jnp.ndarray  # int32 pane index, very negative = empty
    lwcur: jnp.ndarray  # (cells,) int32 ring cursor
    lwlive: jnp.ndarray  # (cells,) int32 unexpired points in the ring
    rwx: jnp.ndarray
    rwy: jnp.ndarray
    rwoid: jnp.ndarray
    rwtag: jnp.ndarray
    rwcur: jnp.ndarray
    rwlive: jnp.ndarray
    digests: jnp.ndarray  # (ppw, K*K) min-pane-indexed pair min dists
    block_digests: jnp.ndarray  # (ppw/bs, K*K) per-block mins of `digests`
    cap_overflow: jnp.ndarray  # () int32
    sel_overflow: jnp.ndarray  # () int32
    cmp_overflow: jnp.ndarray  # () int32 — probed cell live > cap_c


def block_size(ppw: int) -> int:
    """Digest-ring block length for the hierarchical window reduce: the
    divisor of ``ppw`` closest to √ppw, so the per-slide reduce cost
    bs·K² (one block recompute) + (ppw/bs)·K² (block-row min) is
    ~2√ppw·K² instead of the flat ppw·K² (16× at the 10s/10ms shape).
    ppw prime degenerates to bs=1 ≡ the flat reduce."""
    best = 1
    for d in range(1, int(ppw ** 0.5) + 1):
        if ppw % d == 0:
            best = d
    return max(best, 1)


def tjoin_pane_init(
    num_cells: int, cap_w: int, ppw: int, num_ids: int, dtype,
) -> TJoinPaneCarry:
    """Fresh carry. ``num_ids`` = interned trajectory-id bucket (shared
    by both sides); digest row m holds pairs whose earlier pane is m.
    ``block_digests`` row b is maintained as the min over digest rows
    [b·bs, (b+1)·bs) — exact at every step because min-scatters update
    both levels and the one row reset per slide triggers exactly one
    block recompute (see tjoin_pane_step)."""
    slots = num_cells * cap_w
    empty_tag = jnp.int32(-(1 << 30))
    plane_f = jnp.zeros((slots,), dtype)
    plane_i = jnp.zeros((slots,), jnp.int32)
    tags = jnp.full((slots,), empty_tag, jnp.int32)
    cur = jnp.zeros((num_cells,), jnp.int32)
    inf = jnp.asarray(jnp.inf, dtype)
    bs = block_size(ppw)
    return TJoinPaneCarry(
        plane_f, plane_f, plane_i, tags, cur, cur,
        plane_f, plane_f, plane_i, tags, cur, cur,
        jnp.full((ppw, num_ids * num_ids), inf, dtype),
        jnp.full((ppw // bs, num_ids * num_ids), inf, dtype),
        jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32),
        jnp.zeros((), jnp.int32),
    )


def _cell_counts(live, pcell, pvalid, num_cells: int, sign: int):
    """live ± per-cell count of one pane's valid points (two tiny
    scatter-adds per slide keep the FIFO live-count invariant:
    live[c] == points of cell c inside the current window)."""
    return live.at[jnp.where(pvalid, pcell, num_cells)].add(
        jnp.int32(sign), mode="drop"
    )


def _probe(wx, wy, woid, wtag, t, px, py, pxi, pyi, poid, pvalid, radius,
           swap_pair, grid_n: int, cap_w: int, layers: int, ppw: int,
           num_ids: int, pair_sel: int):
    """New-pane points × window planes → (digest flat idx, dist,
    sel_overflow). Row gathers only (span² cell rows per point, never
    element gathers); per-point first-``pair_sel`` match selection is
    backend-gated (one-hot on TPU, top_k on CPU — ops/select.py)."""
    span = 2 * layers + 1
    offs = jnp.arange(-layers, layers + 1, dtype=jnp.int32)
    nx = pxi[:, None, None] + offs[None, :, None]  # (PC, span, 1)
    ny = pyi[:, None, None] + offs[None, None, :]  # (PC, 1, span)
    in_grid = (
        (nx >= 0) & (nx < grid_n) & (ny >= 0) & (ny < grid_n)
    ).reshape(-1, span * span)
    rows = jnp.clip(nx * grid_n + ny, 0, grid_n * grid_n - 1).reshape(
        -1, span * span
    )  # (PC, span²)

    w2 = lambda a: a.reshape(grid_n * grid_n, cap_w)
    gx = w2(wx)[rows]  # (PC, span², capW) — row gathers
    gy = w2(wy)[rows]
    gtag = w2(wtag)[rows]

    d = jnp.sqrt(
        (gx - px[:, None, None]) ** 2 + (gy - py[:, None, None]) ** 2
    )
    alive = (gtag > t - ppw) & (gtag <= t)
    mask = (
        pvalid[:, None, None] & in_grid[:, :, None] & alive & (d <= radius)
    ).reshape(len(px), -1)  # (PC, C)
    dflat = d.reshape(len(px), -1)
    tflat = gtag.reshape(len(px), -1)

    if onehot_select_preferred():
        goid = w2(woid)[rows]
        oflat = goid.reshape(len(px), -1)
        hit, count, sel_over = first_k_onehot(mask, pair_sel)
        # one-hot sums select exactly one lane — bit-exact values.
        sd = jnp.sum(jnp.where(hit, dflat[:, :, None], 0), axis=1)
        so = jnp.sum(hit * oflat[:, :, None], axis=1)
        st = jnp.sum(hit * tflat[:, :, None], axis=1)
    else:
        count = jnp.sum(mask.astype(jnp.int32), axis=1)
        sel_over = jnp.sum(jnp.maximum(count - pair_sel, 0))
        _v, ci = jax.lax.top_k(mask.astype(jnp.int8), pair_sel)
        sd = jnp.take_along_axis(dflat, ci, axis=1)
        st = jnp.take_along_axis(tflat, ci, axis=1)
        # oid only matters for the ≤ pair_sel SELECTED slots — an
        # element gather through the global slot ids replaces the third
        # (PC, span², capW) row gather (25% of probe gather traffic).
        grows = jnp.take_along_axis(rows, ci // cap_w, axis=1)
        so = woid[grows * cap_w + ci % cap_w]
    svalid = (
        jnp.arange(pair_sel, dtype=jnp.int32)[None, :]
        < jnp.minimum(count, pair_sel)[:, None]
    )

    # Digest key: earlier pane = window slot's tag (window panes ≤ t).
    ring = jnp.where(st >= 0, st % ppw, (st % ppw + ppw) % ppw)
    a = poid[:, None]
    b = so
    lid = jnp.where(swap_pair, b, a)
    rid = jnp.where(swap_pair, a, b)
    flat = ring * (num_ids * num_ids) + lid * num_ids + rid
    sentinel = ppw * num_ids * num_ids  # drop lane
    flat = jnp.where(svalid, flat, sentinel)
    return flat.reshape(-1), sd.reshape(-1), sel_over


def _probe_compact(wx, wy, woid, wtag, wcur, wlive, px, py, pxi, pyi, poid,
                   pvalid, radius, swap_pair, grid_n: int, cap_w: int,
                   cap_c: int, layers: int, ppw: int, num_ids: int,
                   pair_sel: int):
    """Compacted probe: O(cap_c) live lanes per neighbor cell, not
    O(cap_w) ring slots. The live slots of a ring row are the
    contiguous FIFO range ``[cursor - live, cursor)``, so the dense
    live-slot view is pure index arithmetic — no repack scatter (an
    XLA:CPU scatter costs ~100× a gather per element), no tag gathers
    for aliveness (position < live IS the alive test; tags are gathered
    only at the ≤ pair_sel SELECTED lanes for the digest ring key), and
    first-k selection by prefix-sum binary search instead of the
    ``lax.top_k`` sort. Identical selected sets and overflow counts as
    ``_probe`` (the occupancy-sweep parity tests), plus ``cmp_over``:
    live points beyond ``cap_c`` in a PROBED cell were invisible — the
    caller must climb the capacity ladder and re-scan."""
    span = 2 * layers + 1
    offs = jnp.arange(-layers, layers + 1, dtype=jnp.int32)
    nx = pxi[:, None, None] + offs[None, :, None]  # (PC, span, 1)
    ny = pyi[:, None, None] + offs[None, None, :]  # (PC, 1, span)
    in_grid = (
        (nx >= 0) & (nx < grid_n) & (ny >= 0) & (ny < grid_n)
    ).reshape(-1, span * span)
    rows = jnp.clip(nx * grid_n + ny, 0, grid_n * grid_n - 1).reshape(
        -1, span * span
    )  # (PC, span²)
    probed = pvalid[:, None] & in_grid
    ghead = (wcur[rows] - wlive[rows]) % cap_w  # (PC, span²)
    glive = jnp.where(probed, wlive[rows], 0)
    cmp_over = jnp.sum(jnp.maximum(glive - cap_c, 0)).astype(jnp.int32)

    lane = jnp.arange(cap_c, dtype=jnp.int32)
    slot = (ghead[:, :, None] + lane[None, None, :]) % cap_w
    gidx = rows[:, :, None] * cap_w + slot  # (PC, span², cap_c)
    gx = wx[gidx]
    gy = wy[gidx]
    d = jnp.sqrt(
        (gx - px[:, None, None]) ** 2 + (gy - py[:, None, None]) ** 2
    )
    mask = (
        probed[:, :, None]
        & (lane[None, None, :] < glive[:, :, None])
        & (d <= radius)
    ).reshape(len(px), -1)  # (PC, C)
    dflat = d.reshape(len(px), -1)
    iflat = gidx.reshape(len(px), -1)

    ci, count, sel_over = first_k_prefix_indices(mask, pair_sel)
    sd = jnp.take_along_axis(dflat, ci, axis=1)
    gsel = jnp.take_along_axis(iflat, ci, axis=1)  # global slot ids
    # tag/oid only for the SELECTED slots — two (PC, pair_sel) element
    # gathers replace two (PC, span², capW) plane gathers.
    st = wtag[gsel]
    so = woid[gsel]
    svalid = (
        jnp.arange(pair_sel, dtype=jnp.int32)[None, :]
        < jnp.minimum(count, pair_sel)[:, None]
    )

    # Digest key: identical arithmetic to _probe — bit-identical flats.
    ring = jnp.where(st >= 0, st % ppw, (st % ppw + ppw) % ppw)
    a = poid[:, None]
    b = so
    lid = jnp.where(swap_pair, b, a)
    rid = jnp.where(swap_pair, a, b)
    flat = ring * (num_ids * num_ids) + lid * num_ids + rid
    sentinel = ppw * num_ids * num_ids  # drop lane
    flat = jnp.where(svalid, flat, sentinel)
    return flat.reshape(-1), sd.reshape(-1), sel_over, cmp_over


def _insert(wx, wy, woid, wtag, wcur, t, px, py, pcell, prank, poid, pvalid,
            cap_w: int, ppw: int):
    """Scatter one pane into a side's ring planes; returns the updated
    planes + the count of LIVE slots overwritten (exactness counter)."""
    cur = wcur[pcell]  # (PC,) row gather of the cursor
    slot = (cur + prank) % cap_w
    fi = jnp.where(pvalid, pcell * cap_w + slot, wx.shape[0])
    # Two loss modes feed the exactness counter: overwriting a slot whose
    # point is still inside the window, AND a single pane putting more
    # than cap_w points in one cell (ranks wrap modulo cap_w and collide
    # within this very scatter — invisible to the old-tag check).
    overwritten = (
        jnp.sum(jnp.where(
            pvalid & (wtag[jnp.clip(fi, 0, wx.shape[0] - 1)] > t - ppw),
            1, 0,
        ))
        + jnp.sum(jnp.where(pvalid & (prank >= cap_w), 1, 0))
    ).astype(jnp.int32)
    wx = wx.at[fi].set(px, mode="drop")
    wy = wy.at[fi].set(py, mode="drop")
    woid = woid.at[fi].set(poid, mode="drop")
    wtag = wtag.at[fi].set(t, mode="drop")
    wcur = wcur.at[jnp.where(pvalid, pcell, wcur.shape[0])].add(
        1, mode="drop"
    )
    return wx, wy, woid, wtag, wcur, overwritten


def tjoin_pane_step(
    carry: TJoinPaneCarry,
    xs,
    radius,
    grid_n: int,
    cap_w: int,
    layers: int,
    ppw: int,
    num_ids: int,
    pair_sel: int,
    cap_c: int = 0,
    axis_name=None,
):
    """One slide: probe/insert both sides, emit the window digest.

    ``xs`` = (t, left pane, right pane, left expiring, right expiring)
    where each pane is (x, y, xi, yi, cell, rank, oid, valid)
    fixed-capacity arrays and each expiring pane is the (cell, valid)
    pair of the pane that left the window this slide (pane ``t - ppw``
    — what keeps the per-cell live counts exact). Returns (carry',
    per-pair window min dists (K²,)). Designed as a ``lax.scan`` body
    so a whole batch of slides is ONE dispatch.

    ``cap_c`` (static): > 0 routes both probes through the compacted
    positional probe (``_probe_compact`` — gathers ``cap_c`` live lanes
    per neighbor cell); 0 keeps the full-ring row-gather probe. Same
    results whenever the overflow counters are zero.

    ``axis_name`` (inside shard_map): PROBE-parallel mesh execution —
    each shard receives its contiguous chunk of the new panes' points,
    probes it against the REPLICATED window planes (the probe's
    gathers are the step's dominant cost and divide by the
    shard count), then all-gathers the (flat idx, dist) contributions
    so every shard applies the identical digest scatter and pane insert
    (tiled all_gather restores the original point order; scatter-min is
    order-free) — the carry stays replicated and bit-identical to the
    single-device step (tests/test_parallel_operators.py). The
    expiring panes arrive replicated, so the live counts (and with
    them the compacted probe's head/alive math) are identical on every
    shard — compaction commutes with the sharding.
    """
    t, lp, rp, lxp, rxp = xs
    if axis_name is not None:
        gather = lambda a: jax.lax.all_gather(a, axis_name, tiled=True)
        lp_full = tuple(gather(f) for f in lp)
        rp_full = tuple(gather(f) for f in rp)
    else:
        gather = lambda a: a
        lp_full, rp_full = lp, rp
    num_cells = grid_n * grid_n
    # Expire pane t-ppw on both sides BEFORE any probe: the window is
    # (t-ppw, t], so its points are dead for every probe of this slide.
    llive = _cell_counts(carry.lwlive, lxp[0], lxp[1], num_cells, -1)
    rlive = _cell_counts(carry.rwlive, rxp[0], rxp[1], num_cells, -1)
    P = num_ids * num_ids
    bs = block_size(ppw)
    inf = jnp.asarray(jnp.inf, carry.digests.dtype)
    r = t % ppw
    # Ring slot r held pane t-ppw — reset before this pane's writes.
    D = jax.lax.dynamic_update_index_in_dim(
        carry.digests, jnp.full((P,), inf, carry.digests.dtype),
        r, axis=0,
    )
    # Hierarchical reduce, level 2: the reset invalidated exactly one
    # block's min — recompute it from its bs digest rows (every other
    # block's invariant carries over; the scatter-mins below update both
    # levels, so Bd[b] == min over D rows of block b at every step and
    # the window min is the bs·K² recompute + (ppw/bs)·K² block min
    # instead of the flat ppw·K² (the r4 VERDICT throughput bound).
    blk = r // bs
    Bd = jax.lax.dynamic_update_index_in_dim(
        carry.block_digests,
        jnp.min(jax.lax.dynamic_slice(
            D, (blk * bs, jnp.zeros((), blk.dtype)), (bs, P)), axis=0),
        blk, axis=0,
    )
    Bf = Bd.reshape(-1)

    def block_flat(flat):
        # digest flat idx (ring·P + pair) → block flat idx; the drop
        # sentinel ppw·P maps to (ppw/bs)·P — also out of range, drops.
        return (flat // P) // bs * P + flat % P

    # Direction A: new LEFT pane × RIGHT window (panes < t).
    if cap_c > 0:
        fa, da, sa, ca = _probe_compact(
            carry.rwx, carry.rwy, carry.rwoid, carry.rwtag, carry.rwcur,
            rlive, lp[0], lp[1], lp[2], lp[3], lp[6], lp[7], radius,
            swap_pair=jnp.asarray(False),
            grid_n=grid_n, cap_w=cap_w, cap_c=cap_c, layers=layers,
            ppw=ppw, num_ids=num_ids, pair_sel=pair_sel,
        )
    else:
        fa, da, sa = _probe(
            carry.rwx, carry.rwy, carry.rwoid, carry.rwtag, t,
            lp[0], lp[1], lp[2], lp[3], lp[6], lp[7], radius,
            swap_pair=jnp.asarray(False),
            grid_n=grid_n, cap_w=cap_w, layers=layers, ppw=ppw,
            num_ids=num_ids, pair_sel=pair_sel,
        )
        ca = jnp.zeros((), jnp.int32)
    if axis_name is not None:
        fa, da = gather(fa), gather(da)
        sa = jax.lax.psum(sa, axis_name)
        ca = jax.lax.psum(ca, axis_name)
    Df = D.reshape(-1)
    Df = Df.at[fa].min(da, mode="drop")
    Bf = Bf.at[block_flat(fa)].min(da, mode="drop")

    lwx, lwy, lwoid, lwtag, lwcur, ov_l = _insert(
        carry.lwx, carry.lwy, carry.lwoid, carry.lwtag, carry.lwcur, t,
        lp_full[0], lp_full[1], lp_full[4], lp_full[5], lp_full[6],
        lp_full[7], cap_w=cap_w, ppw=ppw,
    )
    llive = _cell_counts(llive, lp_full[4], lp_full[7], num_cells, 1)

    # Direction B: new RIGHT pane × LEFT window (panes ≤ t — includes the
    # pane just inserted, so new×new pairs are counted exactly once).
    if cap_c > 0:
        fb, db, sb, cb = _probe_compact(
            lwx, lwy, lwoid, lwtag, lwcur, llive,
            rp[0], rp[1], rp[2], rp[3], rp[6], rp[7], radius,
            swap_pair=jnp.asarray(True),
            grid_n=grid_n, cap_w=cap_w, cap_c=cap_c, layers=layers,
            ppw=ppw, num_ids=num_ids, pair_sel=pair_sel,
        )
    else:
        fb, db, sb = _probe(
            lwx, lwy, lwoid, lwtag, t,
            rp[0], rp[1], rp[2], rp[3], rp[6], rp[7], radius,
            swap_pair=jnp.asarray(True),
            grid_n=grid_n, cap_w=cap_w, layers=layers, ppw=ppw,
            num_ids=num_ids, pair_sel=pair_sel,
        )
        cb = jnp.zeros((), jnp.int32)
    if axis_name is not None:
        fb, db = gather(fb), gather(db)
        sb = jax.lax.psum(sb, axis_name)
        cb = jax.lax.psum(cb, axis_name)
    Df = Df.at[fb].min(db, mode="drop")
    Bf = Bf.at[block_flat(fb)].min(db, mode="drop")
    D = Df.reshape(ppw, P)
    Bd = Bf.reshape(ppw // bs, P)

    rwx, rwy, rwoid, rwtag, rwcur, ov_r = _insert(
        carry.rwx, carry.rwy, carry.rwoid, carry.rwtag, carry.rwcur, t,
        rp_full[0], rp_full[1], rp_full[4], rp_full[5], rp_full[6],
        rp_full[7], cap_w=cap_w, ppw=ppw,
    )
    rlive = _cell_counts(rlive, rp_full[4], rp_full[7], num_cells, 1)

    new_carry = TJoinPaneCarry(
        lwx, lwy, lwoid, lwtag, lwcur, llive,
        rwx, rwy, rwoid, rwtag, rwcur, rlive,
        D, Bd,
        (carry.cap_overflow + ov_l + ov_r).astype(jnp.int32),
        (carry.sel_overflow + sa + sb).astype(jnp.int32),
        (carry.cmp_overflow + ca + cb).astype(jnp.int32),
    )
    # Window ending at pane t: min over every live earlier-pane digest,
    # via the block level (bit-exact — min of mins).
    wmin = jnp.min(Bd, axis=0)
    return new_carry, wmin


def expired_pane_fields(cells_arr, valid_arr, ppw: int):
    """(cell, valid) of the pane EXPIRING at each slide of a batch whose
    carry started EMPTY: pane s - ppw, i.e. the same arrays shifted by
    ``ppw`` slides with nothing expiring during warmup. Callers that
    chain scans from a non-empty carry (bench_suite's warm + steady
    split) must instead slice the expiring panes from the earlier batch
    and pass them explicitly — this zero-fill is only correct when the
    scan's own slides are the whole ring history."""
    S = cells_arr.shape[0]
    pad = min(ppw, S)
    zc = jnp.zeros((pad,) + cells_arr.shape[1:], cells_arr.dtype)
    zv = jnp.zeros((pad,) + valid_arr.shape[1:], valid_arr.dtype)
    if S > ppw:
        return (jnp.concatenate([zc, cells_arr[:S - ppw]], axis=0),
                jnp.concatenate([zv, valid_arr[:S - ppw]], axis=0))
    return zc, zv


def tjoin_pane_scan(
    carry: TJoinPaneCarry,
    ts, lps, rps,
    radius,
    grid_n: int,
    cap_w: int,
    layers: int,
    ppw: int,
    num_ids: int,
    pair_sel: int,
    cap_c: int = 0,
    lps_expire=None,
    rps_expire=None,
    mesh=None,
):
    """Scan ``tjoin_pane_step`` over a batch of slides in ONE program.

    ``ts``: (S,) pane indices; ``lps``/``rps``: per-field (S, PC) arrays
    (x, y, xi, yi, cell, rank, oid, valid). Returns (carry',
    (S, K²) per-window pair min dists).

    ``cap_c`` (static): the bucketed live-slot probe capacity
    (ops/compaction.py ladder; 0 = full-ring probe). One compiled
    program per bucket — the host picks the rung, the device program
    stays fixed-shape.

    ``lps_expire``/``rps_expire``: (cell, valid) pairs of the pane
    expiring at each slide, (S, PC) each — required when this scan
    continues a carry whose ring already holds panes from an earlier
    scan. Default None derives them from this batch's own panes
    (``expired_pane_fields`` — correct iff the carry started empty).

    ``mesh``: probe-parallel execution over the mesh's ``data`` axis —
    pane POINTS shard (PC must divide by the axis), window/digest state
    and the expiring panes replicate, per-slide contributions
    all-gather (see tjoin_pane_step's axis_name). Bit-identical to
    single-device, compacted or not.
    """
    if lps_expire is None:
        lps_expire = expired_pane_fields(lps[4], lps[7], ppw)
    if rps_expire is None:
        rps_expire = expired_pane_fields(rps[4], rps[7], ppw)
    if mesh is None:
        def body(c, x):
            return tjoin_pane_step(
                c, x, radius, grid_n=grid_n, cap_w=cap_w, layers=layers,
                ppw=ppw, num_ids=num_ids, pair_sel=pair_sel, cap_c=cap_c,
            )

        return jax.lax.scan(body, carry, (ts, lps, rps, lps_expire,
                                          rps_expire))

    # Shim handles both the symbol's home and check_rep→check_vma.
    from spatialflink_tpu.utils.shardmap_compat import shard_map
    from jax.sharding import PartitionSpec as P

    ndev = int(mesh.shape["data"])
    pc = lps[0].shape[1]
    if pc % ndev:
        raise ValueError(
            f"pane capacity ({pc}) must divide by the mesh data axis "
            f"({ndev})"
        )

    def local(c, ts_, lps_, rps_, lxp_, rxp_):
        def body(cc, x):
            return tjoin_pane_step(
                cc, x, radius, grid_n=grid_n, cap_w=cap_w, layers=layers,
                ppw=ppw, num_ids=num_ids, pair_sel=pair_sel, cap_c=cap_c,
                axis_name="data",
            )

        return jax.lax.scan(body, c, (ts_, lps_, rps_, lxp_, rxp_))

    carry_spec = TJoinPaneCarry(*(P() for _ in carry))
    pane_spec = tuple(P(None, "data") for _ in lps)
    expire_spec = (P(), P())  # replicated — live counts stay identical
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(carry_spec, P(), pane_spec, pane_spec, expire_spec,
                  expire_spec),
        out_specs=(carry_spec, P()),
        check_vma=False,
    )
    return fn(carry, ts, lps, rps, lps_expire, rps_expire)
