"""Pane-carry tJoin — the extreme-overlap sliding trajectory join.

The reference's windowBased tJoin re-walks the whole window per fire
(tJoin/PointPointTJoinQuery.java:183+); at the domain's extreme-overlap
configs (10 s windows sliding every 10 ms — Q2_BrakeMonitor's window
style, ppw = 1000) that is a 1000× redundant recompute per slide, and so
is this repo's ``run_soa`` (one full-window join per fire). This module
keeps the WINDOW STATE ON DEVICE and does only O(new-pane) join work per
slide:

- **Ring-buffer bucket planes** per stream side: (cells · capW) slots of
  x/y/oid/pane-tag with a per-cell write cursor. Inserting a pane is a
  small scatter; expiry is LAZY — probes mask slots whose pane tag left
  the window, and a slot is reused (cursor ring) long after it expired.
- **Min-pane-indexed pair digests**: ``D[m % ppw, lid·K + rid]`` = min
  point-pair distance among pairs whose EARLIER point sits in pane
  ``m``. A point pair (i ≤ j) is alive for window [s, s+ppw) iff i ≥ s,
  and every contribution discovered so far has j ≤ current pane — so at
  emission time ``min over m ∈ [s, t]`` of D is exactly the window's
  per-trajectory-pair min distance (the tStats min-pane argument,
  applied to a bilinear join).
- Per slide: probe the new LEFT pane against the RIGHT window planes,
  insert the left pane, probe the new RIGHT pane against the LEFT
  planes (now containing pane t — covers new×new exactly once), insert
  the right pane, then reduce the digest ring for the window ending at
  pane t. All of it is one ``lax.scan`` step — one dispatch per BATCH
  of slides, not per slide (the tunnel-dispatch lesson, CLAUDE.md).

Exactness contract (same family as the other join kernels): results
equal ``run_soa`` iff ``cap_overflow == 0`` (a live window slot was
never overwritten — grow ``capW``) and ``sel_overflow == 0`` (no probe
point matched more than ``pair_sel`` window points — grow
``pair_sel``). Digest memory is ``ppw · K² · 4`` bytes (K = interned
trajectory ids per side): extreme overlap trades memory for the 1000×
work cut, sized for the domain's dozens-to-hundreds of vehicles.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

import numpy as np

from spatialflink_tpu.ops.select import first_k_onehot, onehot_select_preferred


def pane_cell_ranks(pane: "np.ndarray", cell: "np.ndarray") -> "np.ndarray":
    """Within-(pane, cell) slot ranks, vectorized — the host half of
    ``_insert``'s ring-slot contract (a pane's same-cell points need
    distinct slots). ONE home, shared by the operator wrapper and the
    benchmark staging (drift here would silently change collision
    behavior between the product path and the measured path)."""
    n = len(pane)
    order = np.lexsort((cell, pane))
    ps, cs = pane[order], cell[order]
    newrun = np.ones(n, bool)
    if n > 1:
        newrun[1:] = (ps[1:] != ps[:-1]) | (cs[1:] != cs[:-1])
    run_id = np.cumsum(newrun) - 1
    pos = np.arange(n)
    rank = np.empty(n, np.int64)
    rank[order] = pos - pos[newrun][run_id]
    return rank


class TJoinPaneCarry(NamedTuple):
    lwx: jnp.ndarray  # (cells*capW,) left window planes
    lwy: jnp.ndarray
    lwoid: jnp.ndarray  # int32
    lwtag: jnp.ndarray  # int32 pane index, very negative = empty
    lwcur: jnp.ndarray  # (cells,) int32 ring cursor
    rwx: jnp.ndarray
    rwy: jnp.ndarray
    rwoid: jnp.ndarray
    rwtag: jnp.ndarray
    rwcur: jnp.ndarray
    digests: jnp.ndarray  # (ppw, K*K) min-pane-indexed pair min dists
    block_digests: jnp.ndarray  # (ppw/bs, K*K) per-block mins of `digests`
    cap_overflow: jnp.ndarray  # () int32
    sel_overflow: jnp.ndarray  # () int32


def block_size(ppw: int) -> int:
    """Digest-ring block length for the hierarchical window reduce: the
    divisor of ``ppw`` closest to √ppw, so the per-slide reduce cost
    bs·K² (one block recompute) + (ppw/bs)·K² (block-row min) is
    ~2√ppw·K² instead of the flat ppw·K² (16× at the 10s/10ms shape).
    ppw prime degenerates to bs=1 ≡ the flat reduce."""
    best = 1
    for d in range(1, int(ppw ** 0.5) + 1):
        if ppw % d == 0:
            best = d
    return max(best, 1)


def tjoin_pane_init(
    num_cells: int, cap_w: int, ppw: int, num_ids: int, dtype,
) -> TJoinPaneCarry:
    """Fresh carry. ``num_ids`` = interned trajectory-id bucket (shared
    by both sides); digest row m holds pairs whose earlier pane is m.
    ``block_digests`` row b is maintained as the min over digest rows
    [b·bs, (b+1)·bs) — exact at every step because min-scatters update
    both levels and the one row reset per slide triggers exactly one
    block recompute (see tjoin_pane_step)."""
    slots = num_cells * cap_w
    empty_tag = jnp.int32(-(1 << 30))
    plane_f = jnp.zeros((slots,), dtype)
    plane_i = jnp.zeros((slots,), jnp.int32)
    tags = jnp.full((slots,), empty_tag, jnp.int32)
    cur = jnp.zeros((num_cells,), jnp.int32)
    inf = jnp.asarray(jnp.inf, dtype)
    bs = block_size(ppw)
    return TJoinPaneCarry(
        plane_f, plane_f, plane_i, tags, cur,
        plane_f, plane_f, plane_i, tags, cur,
        jnp.full((ppw, num_ids * num_ids), inf, dtype),
        jnp.full((ppw // bs, num_ids * num_ids), inf, dtype),
        jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32),
    )


def _probe(wx, wy, woid, wtag, t, px, py, pxi, pyi, poid, pvalid, radius,
           swap_pair, grid_n: int, cap_w: int, layers: int, ppw: int,
           num_ids: int, pair_sel: int):
    """New-pane points × window planes → (digest flat idx, dist,
    sel_overflow). Row gathers only (span² cell rows per point, never
    element gathers); per-point first-``pair_sel`` match selection is
    backend-gated (one-hot on TPU, top_k on CPU — ops/select.py)."""
    span = 2 * layers + 1
    offs = jnp.arange(-layers, layers + 1, dtype=jnp.int32)
    nx = pxi[:, None, None] + offs[None, :, None]  # (PC, span, 1)
    ny = pyi[:, None, None] + offs[None, None, :]  # (PC, 1, span)
    in_grid = (
        (nx >= 0) & (nx < grid_n) & (ny >= 0) & (ny < grid_n)
    ).reshape(-1, span * span)
    rows = jnp.clip(nx * grid_n + ny, 0, grid_n * grid_n - 1).reshape(
        -1, span * span
    )  # (PC, span²)

    w2 = lambda a: a.reshape(grid_n * grid_n, cap_w)
    gx = w2(wx)[rows]  # (PC, span², capW) — row gathers
    gy = w2(wy)[rows]
    gtag = w2(wtag)[rows]

    d = jnp.sqrt(
        (gx - px[:, None, None]) ** 2 + (gy - py[:, None, None]) ** 2
    )
    alive = (gtag > t - ppw) & (gtag <= t)
    mask = (
        pvalid[:, None, None] & in_grid[:, :, None] & alive & (d <= radius)
    ).reshape(len(px), -1)  # (PC, C)
    dflat = d.reshape(len(px), -1)
    tflat = gtag.reshape(len(px), -1)

    if onehot_select_preferred():
        goid = w2(woid)[rows]
        oflat = goid.reshape(len(px), -1)
        hit, count, sel_over = first_k_onehot(mask, pair_sel)
        # one-hot sums select exactly one lane — bit-exact values.
        sd = jnp.sum(jnp.where(hit, dflat[:, :, None], 0), axis=1)
        so = jnp.sum(hit * oflat[:, :, None], axis=1)
        st = jnp.sum(hit * tflat[:, :, None], axis=1)
    else:
        count = jnp.sum(mask.astype(jnp.int32), axis=1)
        sel_over = jnp.sum(jnp.maximum(count - pair_sel, 0))
        _v, ci = jax.lax.top_k(mask.astype(jnp.int8), pair_sel)
        sd = jnp.take_along_axis(dflat, ci, axis=1)
        st = jnp.take_along_axis(tflat, ci, axis=1)
        # oid only matters for the ≤ pair_sel SELECTED slots — an
        # element gather through the global slot ids replaces the third
        # (PC, span², capW) row gather (25% of probe gather traffic).
        grows = jnp.take_along_axis(rows, ci // cap_w, axis=1)
        so = woid[grows * cap_w + ci % cap_w]
    svalid = (
        jnp.arange(pair_sel, dtype=jnp.int32)[None, :]
        < jnp.minimum(count, pair_sel)[:, None]
    )

    # Digest key: earlier pane = window slot's tag (window panes ≤ t).
    ring = jnp.where(st >= 0, st % ppw, (st % ppw + ppw) % ppw)
    a = poid[:, None]
    b = so
    lid = jnp.where(swap_pair, b, a)
    rid = jnp.where(swap_pair, a, b)
    flat = ring * (num_ids * num_ids) + lid * num_ids + rid
    sentinel = ppw * num_ids * num_ids  # drop lane
    flat = jnp.where(svalid, flat, sentinel)
    return flat.reshape(-1), sd.reshape(-1), sel_over


def _insert(wx, wy, woid, wtag, wcur, t, px, py, pcell, prank, poid, pvalid,
            cap_w: int, ppw: int):
    """Scatter one pane into a side's ring planes; returns the updated
    planes + the count of LIVE slots overwritten (exactness counter)."""
    cur = wcur[pcell]  # (PC,) row gather of the cursor
    slot = (cur + prank) % cap_w
    fi = jnp.where(pvalid, pcell * cap_w + slot, wx.shape[0])
    # Two loss modes feed the exactness counter: overwriting a slot whose
    # point is still inside the window, AND a single pane putting more
    # than cap_w points in one cell (ranks wrap modulo cap_w and collide
    # within this very scatter — invisible to the old-tag check).
    overwritten = (
        jnp.sum(jnp.where(
            pvalid & (wtag[jnp.clip(fi, 0, wx.shape[0] - 1)] > t - ppw),
            1, 0,
        ))
        + jnp.sum(jnp.where(pvalid & (prank >= cap_w), 1, 0))
    ).astype(jnp.int32)
    wx = wx.at[fi].set(px, mode="drop")
    wy = wy.at[fi].set(py, mode="drop")
    woid = woid.at[fi].set(poid, mode="drop")
    wtag = wtag.at[fi].set(t, mode="drop")
    wcur = wcur.at[jnp.where(pvalid, pcell, wcur.shape[0])].add(
        1, mode="drop"
    )
    return wx, wy, woid, wtag, wcur, overwritten


def tjoin_pane_step(
    carry: TJoinPaneCarry,
    xs,
    radius,
    grid_n: int,
    cap_w: int,
    layers: int,
    ppw: int,
    num_ids: int,
    pair_sel: int,
    axis_name=None,
):
    """One slide: probe/insert both sides, emit the window digest.

    ``xs`` = (t, left pane, right pane) where each pane is
    (x, y, xi, yi, cell, rank, oid, valid) fixed-capacity arrays.
    Returns (carry', per-pair window min dists (K²,)). Designed as a
    ``lax.scan`` body so a whole batch of slides is ONE dispatch.

    ``axis_name`` (inside shard_map): PROBE-parallel mesh execution —
    each shard receives its contiguous chunk of the new panes' points,
    probes it against the REPLICATED window planes (the probe's
    span²·capW gathers are the step's dominant cost and divide by the
    shard count), then all-gathers the (flat idx, dist) contributions
    so every shard applies the identical digest scatter and pane insert
    (tiled all_gather restores the original point order; scatter-min is
    order-free) — the carry stays replicated and bit-identical to the
    single-device step (tests/test_parallel_operators.py).
    """
    t, lp, rp = xs
    if axis_name is not None:
        gather = lambda a: jax.lax.all_gather(a, axis_name, tiled=True)
        lp_full = tuple(gather(f) for f in lp)
        rp_full = tuple(gather(f) for f in rp)
    else:
        gather = lambda a: a
        lp_full, rp_full = lp, rp
    P = num_ids * num_ids
    bs = block_size(ppw)
    inf = jnp.asarray(jnp.inf, carry.digests.dtype)
    r = t % ppw
    # Ring slot r held pane t-ppw — reset before this pane's writes.
    D = jax.lax.dynamic_update_index_in_dim(
        carry.digests, jnp.full((P,), inf, carry.digests.dtype),
        r, axis=0,
    )
    # Hierarchical reduce, level 2: the reset invalidated exactly one
    # block's min — recompute it from its bs digest rows (every other
    # block's invariant carries over; the scatter-mins below update both
    # levels, so Bd[b] == min over D rows of block b at every step and
    # the window min is the bs·K² recompute + (ppw/bs)·K² block min
    # instead of the flat ppw·K² (the r4 VERDICT throughput bound).
    blk = r // bs
    Bd = jax.lax.dynamic_update_index_in_dim(
        carry.block_digests,
        jnp.min(jax.lax.dynamic_slice(
            D, (blk * bs, jnp.zeros((), blk.dtype)), (bs, P)), axis=0),
        blk, axis=0,
    )
    Bf = Bd.reshape(-1)

    def block_flat(flat):
        # digest flat idx (ring·P + pair) → block flat idx; the drop
        # sentinel ppw·P maps to (ppw/bs)·P — also out of range, drops.
        return (flat // P) // bs * P + flat % P

    # Direction A: new LEFT pane × RIGHT window (panes < t).
    fa, da, sa = _probe(
        carry.rwx, carry.rwy, carry.rwoid, carry.rwtag, t,
        lp[0], lp[1], lp[2], lp[3], lp[6], lp[7], radius,
        swap_pair=jnp.asarray(False),
        grid_n=grid_n, cap_w=cap_w, layers=layers, ppw=ppw,
        num_ids=num_ids, pair_sel=pair_sel,
    )
    if axis_name is not None:
        fa, da = gather(fa), gather(da)
        sa = jax.lax.psum(sa, axis_name)
    Df = D.reshape(-1)
    Df = Df.at[fa].min(da, mode="drop")
    Bf = Bf.at[block_flat(fa)].min(da, mode="drop")

    lwx, lwy, lwoid, lwtag, lwcur, ov_l = _insert(
        carry.lwx, carry.lwy, carry.lwoid, carry.lwtag, carry.lwcur, t,
        lp_full[0], lp_full[1], lp_full[4], lp_full[5], lp_full[6],
        lp_full[7], cap_w=cap_w, ppw=ppw,
    )

    # Direction B: new RIGHT pane × LEFT window (panes ≤ t — includes the
    # pane just inserted, so new×new pairs are counted exactly once).
    fb, db, sb = _probe(
        lwx, lwy, lwoid, lwtag, t,
        rp[0], rp[1], rp[2], rp[3], rp[6], rp[7], radius,
        swap_pair=jnp.asarray(True),
        grid_n=grid_n, cap_w=cap_w, layers=layers, ppw=ppw,
        num_ids=num_ids, pair_sel=pair_sel,
    )
    if axis_name is not None:
        fb, db = gather(fb), gather(db)
        sb = jax.lax.psum(sb, axis_name)
    Df = Df.at[fb].min(db, mode="drop")
    Bf = Bf.at[block_flat(fb)].min(db, mode="drop")
    D = Df.reshape(ppw, P)
    Bd = Bf.reshape(ppw // bs, P)

    rwx, rwy, rwoid, rwtag, rwcur, ov_r = _insert(
        carry.rwx, carry.rwy, carry.rwoid, carry.rwtag, carry.rwcur, t,
        rp_full[0], rp_full[1], rp_full[4], rp_full[5], rp_full[6],
        rp_full[7], cap_w=cap_w, ppw=ppw,
    )

    new_carry = TJoinPaneCarry(
        lwx, lwy, lwoid, lwtag, lwcur,
        rwx, rwy, rwoid, rwtag, rwcur,
        D, Bd,
        (carry.cap_overflow + ov_l + ov_r).astype(jnp.int32),
        (carry.sel_overflow + sa + sb).astype(jnp.int32),
    )
    # Window ending at pane t: min over every live earlier-pane digest,
    # via the block level (bit-exact — min of mins).
    wmin = jnp.min(Bd, axis=0)
    return new_carry, wmin


def tjoin_pane_scan(
    carry: TJoinPaneCarry,
    ts, lps, rps,
    radius,
    grid_n: int,
    cap_w: int,
    layers: int,
    ppw: int,
    num_ids: int,
    pair_sel: int,
    mesh=None,
):
    """Scan ``tjoin_pane_step`` over a batch of slides in ONE program.

    ``ts``: (S,) pane indices; ``lps``/``rps``: per-field (S, PC) arrays
    (x, y, xi, yi, cell, rank, oid, valid). Returns (carry',
    (S, K²) per-window pair min dists).

    ``mesh``: probe-parallel execution over the mesh's ``data`` axis —
    pane POINTS shard (PC must divide by the axis), window/digest state
    replicates, per-slide contributions all-gather (see
    tjoin_pane_step's axis_name). Bit-identical to single-device.
    """
    if mesh is None:
        def body(c, x):
            return tjoin_pane_step(
                c, x, radius, grid_n=grid_n, cap_w=cap_w, layers=layers,
                ppw=ppw, num_ids=num_ids, pair_sel=pair_sel,
            )

        return jax.lax.scan(body, carry, (ts, lps, rps))

    # Shim handles both the symbol's home and check_rep→check_vma.
    from spatialflink_tpu.utils.shardmap_compat import shard_map
    from jax.sharding import PartitionSpec as P

    ndev = int(mesh.shape["data"])
    pc = lps[0].shape[1]
    if pc % ndev:
        raise ValueError(
            f"pane capacity ({pc}) must divide by the mesh data axis "
            f"({ndev})"
        )

    def local(c, ts_, lps_, rps_):
        def body(cc, x):
            return tjoin_pane_step(
                cc, x, radius, grid_n=grid_n, cap_w=cap_w, layers=layers,
                ppw=ppw, num_ids=num_ids, pair_sel=pair_sel,
                axis_name="data",
            )

        return jax.lax.scan(body, c, (ts_, lps_, rps_))

    carry_spec = TJoinPaneCarry(*(P() for _ in carry))
    pane_spec = tuple(P(None, "data") for _ in lps)
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(carry_spec, P(), pane_spec, pane_spec),
        out_specs=(carry_spec, P()),
        check_vma=False,
    )
    return fn(carry, ts, lps, rps)
