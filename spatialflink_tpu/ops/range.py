"""Batched range-query kernels.

Replaces the reference's per-cell windowed inner loops
(range/PointPointRangeQuery.java:111-187, range/PointPolygonRangeQuery.java:37-160)
with one fused XLA program per window batch:

  gather cell flag → guaranteed? emit : candidate? exact distance ≤ r.

GeoFlink's core pruning trick is kept exactly: points whose cell is in the
**guaranteed** set are emitted with no distance computation; only points in
**candidate** cells get exact distances (PointPointRangeQuery.java:152-186).
On TPU we compute the (masked) distances for all lanes anyway — branchless —
and the flag decides emission, which is both simpler and faster than a
gather/compact.

``approximate`` mode mirrors the reference's ``approximateQuery`` flag:
candidate-cell points are emitted without the exact distance check
(PointPolygonRangeQuery.java:76-80).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from spatialflink_tpu.ops.distances import pairwise_distance, point_polyline_distance
from spatialflink_tpu.ops.polygon import points_in_polygon

__all__ = [
    "range_query_kernel",
    "range_query_polygons_kernel",
    "range_query_polygons_pruned_kernel",
    "range_query_polylines_kernel",
    "geometry_range_query_kernel",
    "geometry_pair_distance",
    "range_points_fused",
    "range_polygons_fused",
    "range_polygons_pruned_fused",
    "range_polylines_fused",
]


def _emit_mask(valid, flags, min_dist, radius, approximate: bool):
    guaranteed = flags == 2
    candidate = flags == 1
    if approximate:
        hit = candidate
    else:
        hit = candidate & (min_dist <= radius)
    return valid & (guaranteed | hit)


def range_query_kernel(
    xy: jnp.ndarray,
    valid: jnp.ndarray,
    flags: jnp.ndarray,
    query_xy: jnp.ndarray,
    radius,
    approximate: bool = False,
):
    """Point stream vs point query set.

    ``xy``: (N, 2); ``valid``: (N,) bool; ``flags``: (N,) uint8 per-point
    pruning flags (gathered via ops.cells.gather_cell_flags); ``query_xy``:
    (Q, 2). Returns (keep (N,) bool, min_dist (N,)). min_dist for
    guaranteed-only emissions is still exact (computed branchlessly).
    """
    d = pairwise_distance(xy, query_xy)  # (N, Q)
    min_dist = jnp.min(d, axis=1)
    return _emit_mask(valid, flags, min_dist, radius, approximate), min_dist


def range_query_polygons_kernel(
    xy: jnp.ndarray,
    valid: jnp.ndarray,
    flags: jnp.ndarray,
    poly_verts: jnp.ndarray,
    poly_edge_valid: jnp.ndarray,
    radius,
    approximate: bool = False,
    poly_chunk: int = 32,
):
    """Point stream vs polygon query set (JTS-distance semantics: 0 inside).

    ``poly_verts``: (P, V, 2) packed rings per query polygon;
    ``poly_edge_valid``: (P, V-1). The batched form of
    PointPolygonRangeQuery's window loop (range/PointPolygonRangeQuery.java:37-101).

    Large query sets (the 1k-polygon benchmark config) are processed in
    ``poly_chunk``-polygon blocks via ``lax.map`` so the (chunk, N, E)
    intermediate stays bounded instead of materializing (P, N, E). When P
    isn't a multiple of the chunk, it is padded with all-invalid dummy
    polygons (infinite distance, never inside).
    """
    def one_poly(verts, ev):
        edge_d = point_polyline_distance(xy, verts, ev)
        inside = points_in_polygon(xy, verts, ev)
        return jnp.where(inside, jnp.zeros((), edge_d.dtype), edge_d)

    min_dist = _chunked_min_over_geoms(
        one_poly, poly_verts, poly_edge_valid, poly_chunk
    )
    return _emit_mask(valid, flags, min_dist, radius, approximate), min_dist


def range_query_polygons_pruned_kernel(
    xy: jnp.ndarray,
    valid: jnp.ndarray,
    flags: jnp.ndarray,
    poly_verts: jnp.ndarray,
    poly_edge_valid: jnp.ndarray,
    radius,
    cand: int = 8,
    point_chunk: int = 8192,
    approximate: bool = False,
):
    """Large-query-set point–polygon range via bbox-candidate pruning.

    The dense kernel evaluates every (point, polygon, edge) triple — P·E
    edge distances per point. For big query sets (the 1000-polygon config)
    almost all pairs are far apart, so this kernel does a cheap
    (N, P) bbox-distance pass, takes each point's ``cand`` nearest polygons
    by bbox distance (lax.top_k), and computes exact edge distances ONLY
    for those candidates — O(P + cand·E) per point instead of O(P·E).

    Exactness contract (mirrors the bucketed join's overflow/retry):
    bbox distance lower-bounds exact distance, so every polygon within
    ``radius`` of a point is among its bbox-candidates UNLESS more than
    ``cand`` polygon bboxes fall within radius — counted per point into
    ``overflow``. With overflow == 0, keep/min_dist are bit-exact for all
    kept lanes (dropped lanes report the min over their candidates only);
    otherwise retry with a larger ``cand``.

    Points stream through ``point_chunk``-sized lax.map blocks so the
    (chunk, P) bbox matrix stays bounded. Returns (keep, min_dist, overflow).
    """
    n = xy.shape[0]
    p = poly_verts.shape[0]
    cand = min(cand, p)
    vmask = _vert_valid(poly_edge_valid)  # (P, V)
    vx, vy = poly_verts[..., 0], poly_verts[..., 1]
    big = jnp.asarray(jnp.finfo(xy.dtype).max, xy.dtype)
    minx = jnp.min(jnp.where(vmask, vx, big), axis=1)
    maxx = jnp.max(jnp.where(vmask, vx, -big), axis=1)
    miny = jnp.min(jnp.where(vmask, vy, big), axis=1)
    maxy = jnp.max(jnp.where(vmask, vy, -big), axis=1)
    # All-invalid (padding) polygons: minx > maxx → clamped dx below stays
    # positive-huge, so they are never candidates within radius.
    dead = ~jnp.any(vmask, axis=1)

    def chunk_fn(args):
        xy_c, valid_c, flags_c = args
        x, y = xy_c[:, 0:1], xy_c[:, 1:2]  # (C, 1)
        dx = jnp.maximum(jnp.maximum(minx[None, :] - x, x - maxx[None, :]), 0.0)
        dy = jnp.maximum(jnp.maximum(miny[None, :] - y, y - maxy[None, :]), 0.0)
        bbox_d = jnp.where(dead[None, :], big, jnp.hypot(dx, dy))  # (C, P)
        neg_top, idx = jax.lax.top_k(-bbox_d, cand)  # nearest by bbox
        within = jnp.sum((bbox_d <= radius).astype(jnp.int32), axis=1)
        lanes = valid_c & (flags_c > 0)
        over = jnp.sum(
            jnp.where(lanes, jnp.maximum(within - cand, 0), 0)
        )
        cverts = poly_verts[idx]  # (C, cand, V, 2)
        cev = poly_edge_valid[idx]  # (C, cand, V-1)

        def one(p_xy, cv, ce):
            def per_cand(verts, ev):
                ed = point_polyline_distance(p_xy[None, :], verts, ev)[0]
                ins = points_in_polygon(p_xy[None, :], verts, ev)[0]
                return jnp.where(ins, jnp.zeros((), ed.dtype), ed)

            return jnp.min(jax.vmap(per_cand)(cv, ce))

        min_d = jax.vmap(one)(xy_c, cverts, cev)  # (C,)
        keep = _emit_mask(valid_c, flags_c, min_d, radius, approximate)
        return keep, min_d, over

    pad = (-n) % point_chunk
    if pad:
        xy = jnp.concatenate([xy, jnp.zeros((pad, 2), xy.dtype)])
        valid = jnp.concatenate([valid, jnp.zeros((pad,), bool)])
        flags = jnp.concatenate([flags, jnp.zeros((pad,), flags.dtype)])
    n_blocks = (n + pad) // point_chunk
    keep_b, dist_b, over_b = jax.lax.map(
        chunk_fn,
        (
            xy.reshape(n_blocks, point_chunk, 2),
            valid.reshape(n_blocks, point_chunk),
            flags.reshape(n_blocks, point_chunk),
        ),
    )
    return (
        keep_b.reshape(-1)[:n],
        dist_b.reshape(-1)[:n],
        jnp.sum(over_b),
    )


def range_polygons_pruned_fused(xy, valid, cell, flags_table, poly_verts,
                                poly_edge_valid, radius, cand: int = 8,
                                point_chunk: int = 8192,
                                approximate: bool = False):
    from spatialflink_tpu.ops.cells import gather_cell_flags

    return range_query_polygons_pruned_kernel(
        xy, valid, gather_cell_flags(cell, flags_table), poly_verts,
        poly_edge_valid, radius, cand=cand, point_chunk=point_chunk,
        approximate=approximate,
    )


def range_query_polygons_pruned_compact_kernel(
    xy: jnp.ndarray,
    valid: jnp.ndarray,
    flags: jnp.ndarray,
    poly_verts: jnp.ndarray,
    poly_edge_valid: jnp.ndarray,
    radius,
    budget: int,
    cand: int = 8,
    point_chunk: int = 8192,
):
    """Candidate-compacted form of the pruned kernel.

    Grid flags already exclude most of a window (typically >90% of lanes
    have flags == 0 and can never be emitted); this kernel gathers the
    ≤ ``budget`` candidate lanes on device and runs the bbox-pruned
    evaluation only on them — the one place compaction beats the
    mask-don't-compact default, because the per-lane work here
    (P bbox distances + top-cand + cand·E exact edges) is ~1000×
    an elementwise op.

    Returns (keep (N,), min_dist (N,) — +big on lanes that were not
    evaluated — cand_overflow, budget_overflow). Exactness contract:
    both overflows 0 ⇒ keep/min_dist(kept) are bit-exact; a nonzero
    ``budget_overflow`` means more than ``budget`` candidate lanes
    existed (retry with a bigger budget), a nonzero ``cand_overflow``
    means retry with bigger ``cand``. Exact mode only (the approximate
    keep-set is flag-driven and needs no distances — use the dense
    kernel's approximate path).
    """
    n = xy.shape[0]
    lanes = valid & (flags > 0)
    n_cand = jnp.sum(lanes.astype(jnp.int32))
    idx = jnp.nonzero(lanes, size=budget, fill_value=n)[0]
    in_range = idx < n
    safe = jnp.minimum(idx, n - 1)
    xy_c = jnp.where(in_range[:, None], xy[safe], 0.0)
    flags_c = jnp.where(in_range, flags[safe], 0)

    keep_c, dist_c, cand_over = range_query_polygons_pruned_kernel(
        xy_c, in_range, flags_c, poly_verts, poly_edge_valid, radius,
        cand=cand, point_chunk=min(point_chunk, budget),
    )

    big = jnp.asarray(jnp.finfo(dist_c.dtype).max, dist_c.dtype)
    # Scatter through the RAW indices: padding lanes carry idx == n, which
    # mode="drop" discards (clipped indices would overwrite lane n-1).
    keep = jnp.zeros(n, bool).at[idx].set(keep_c, mode="drop")
    dist = jnp.full(n, big, dist_c.dtype).at[idx].set(dist_c, mode="drop")
    budget_overflow = jnp.maximum(n_cand - budget, 0)
    return keep, dist, cand_over, budget_overflow


def range_polygons_pruned_compact_fused(
    xy, valid, cell, flags_table, poly_verts, poly_edge_valid, radius,
    budget: int, cand: int = 8, point_chunk: int = 8192,
):
    from spatialflink_tpu.ops.cells import gather_cell_flags

    return range_query_polygons_pruned_compact_kernel(
        xy, valid, gather_cell_flags(cell, flags_table), poly_verts,
        poly_edge_valid, radius, budget=budget, cand=cand,
        point_chunk=point_chunk,
    )


def _chunked_min_over_geoms(one_fn, verts, edge_valid, chunk):
    """min over geometries of per-geometry point distances, processed in
    ``chunk``-geometry lax.map blocks so the (chunk, N, E) intermediate
    stays bounded. Short sets take the plain vmap path; padding uses
    all-invalid dummies (infinite distance, never inside)."""
    p = verts.shape[0]
    if p <= chunk:
        return jnp.min(jax.vmap(one_fn)(verts, edge_valid), axis=0)
    pad = (-p) % chunk
    if pad:
        verts = jnp.concatenate(
            [verts, jnp.zeros((pad,) + verts.shape[1:], verts.dtype)], axis=0
        )
        edge_valid = jnp.concatenate(
            [edge_valid, jnp.zeros((pad,) + edge_valid.shape[1:], bool)], axis=0
        )
    vb = verts.reshape(-1, chunk, *verts.shape[1:])
    eb = edge_valid.reshape(-1, chunk, *edge_valid.shape[1:])
    block_min = jax.lax.map(
        lambda be: jnp.min(jax.vmap(one_fn)(be[0], be[1]), axis=0), (vb, eb)
    )  # (P/chunk, N)
    return jnp.min(block_min, axis=0)


def range_query_polylines_kernel(
    xy: jnp.ndarray,
    valid: jnp.ndarray,
    flags: jnp.ndarray,
    line_verts: jnp.ndarray,
    line_edge_valid: jnp.ndarray,
    radius,
    approximate: bool = False,
    line_chunk: int = 32,
):
    """Point stream vs linestring query set (min edge distance).

    Batched form of PointLineStringRangeQuery's loop
    (range/PointLineStringRangeQuery.java). Large query sets are chunked
    like range_query_polygons_kernel.
    """
    def one_line(v, e):
        return point_polyline_distance(xy, v, e)

    min_dist = _chunked_min_over_geoms(
        one_line, line_verts, line_edge_valid, line_chunk
    )
    return _emit_mask(valid, flags, min_dist, radius, approximate), min_dist


# Fused variants: cell-flag gather + query in ONE jitted program, so the
# per-window path costs a single dispatch (no eager gather round trip).


def range_points_fused(xy, valid, cell, flags_table, query_xy, radius,
                       approximate: bool = False):
    from spatialflink_tpu.ops.cells import gather_cell_flags

    return range_query_kernel(
        xy, valid, gather_cell_flags(cell, flags_table), query_xy, radius,
        approximate=approximate,
    )


def range_polygons_fused(xy, valid, cell, flags_table, poly_verts,
                         poly_edge_valid, radius, approximate: bool = False):
    from spatialflink_tpu.ops.cells import gather_cell_flags

    return range_query_polygons_kernel(
        xy, valid, gather_cell_flags(cell, flags_table), poly_verts,
        poly_edge_valid, radius, approximate=approximate,
    )


def range_polylines_fused(xy, valid, cell, flags_table, line_verts,
                          line_edge_valid, radius, approximate: bool = False):
    from spatialflink_tpu.ops.cells import gather_cell_flags

    return range_query_polylines_kernel(
        xy, valid, gather_cell_flags(cell, flags_table), line_verts,
        line_edge_valid, radius, approximate=approximate,
    )


def _vert_valid(edge_valid: jnp.ndarray) -> jnp.ndarray:
    """(..., V-1) edge mask → (..., V) vertex mask (a vertex is real if it
    bounds a real edge)."""
    z = jnp.zeros(edge_valid.shape[:-1] + (1,), bool)
    return (
        jnp.concatenate([edge_valid, z], axis=-1)
        | jnp.concatenate([z, edge_valid], axis=-1)
    )


def geometry_pair_distance(
    averts: jnp.ndarray,
    aev: jnp.ndarray,
    bverts: jnp.ndarray,
    bev: jnp.ndarray,
    a_polygonal: bool = False,
    b_polygonal: bool = False,
) -> jnp.ndarray:
    """JTS-compatible distance between two packed boundaries (scalars).

    Non-overlapping: min over vertex→other-boundary distances both ways
    (exact for polyline pairs, since the closest approach involves a vertex
    of one of them). Overlap/containment: JTS returns 0 when geometries
    intersect — detected here as any valid vertex of one polygonal geometry
    containing a vertex of the other (and vice versa). Edge-crossing overlap
    with no contained vertex yields a near-zero edge distance already.
    """
    big = jnp.asarray(jnp.finfo(averts.dtype).max, averts.dtype)
    a_ok = _vert_valid(aev)
    b_ok = _vert_valid(bev)
    d_ab = jnp.where(a_ok, point_polyline_distance(averts, bverts, bev), big)
    d_ba = jnp.where(b_ok, point_polyline_distance(bverts, averts, aev), big)
    d = jnp.minimum(jnp.min(d_ab), jnp.min(d_ba))
    zero = jnp.zeros((), averts.dtype)
    if b_polygonal:
        a_in_b = jnp.any(points_in_polygon(averts, bverts, bev) & a_ok)
        d = jnp.where(a_in_b, zero, d)
    if a_polygonal:
        b_in_a = jnp.any(points_in_polygon(bverts, averts, aev) & b_ok)
        d = jnp.where(b_in_a, zero, d)
    return d


def geometry_range_query_kernel(
    obj_verts: jnp.ndarray,
    obj_edge_valid: jnp.ndarray,
    valid: jnp.ndarray,
    flags: jnp.ndarray,
    query_verts: jnp.ndarray,
    query_edge_valid: jnp.ndarray,
    radius,
    approximate: bool = False,
    obj_polygonal: bool = False,
    query_polygonal: bool = False,
):
    """Geometry stream (polygons/linestrings) vs geometry query set.

    ``obj_verts``: (N, V, 2) per-object packed boundaries; distances via
    ``geometry_pair_distance`` (JTS semantics incl. overlap→0) — the batched
    form of e.g. PolygonPolygonRangeQuery's window loop.
    """
    def pair(averts, aev):
        return jax.vmap(
            lambda qverts, qev: geometry_pair_distance(
                averts, aev, qverts, qev, obj_polygonal, query_polygonal
            )
        )(query_verts, query_edge_valid)  # (Q,)

    d = jax.vmap(pair)(obj_verts, obj_edge_valid)  # (N, Q)
    min_dist = jnp.min(d, axis=1)
    return _emit_mask(valid, flags, min_dist, radius, approximate), min_dist
