"""Batched range-query kernels.

Replaces the reference's per-cell windowed inner loops
(range/PointPointRangeQuery.java:111-187, range/PointPolygonRangeQuery.java:37-160)
with one fused XLA program per window batch:

  gather cell flag → guaranteed? emit : candidate? exact distance ≤ r.

GeoFlink's core pruning trick is kept exactly: points whose cell is in the
**guaranteed** set are emitted with no distance computation; only points in
**candidate** cells get exact distances (PointPointRangeQuery.java:152-186).
On TPU we compute the (masked) distances for all lanes anyway — branchless —
and the flag decides emission, which is both simpler and faster than a
gather/compact.

``approximate`` mode mirrors the reference's ``approximateQuery`` flag:
candidate-cell points are emitted without the exact distance check
(PointPolygonRangeQuery.java:76-80).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from spatialflink_tpu.ops.distances import pairwise_distance, point_polyline_distance
from spatialflink_tpu.ops.polygon import points_in_polygon


def _emit_mask(valid, flags, min_dist, radius, approximate: bool):
    guaranteed = flags == 2
    candidate = flags == 1
    if approximate:
        hit = candidate
    else:
        hit = candidate & (min_dist <= radius)
    return valid & (guaranteed | hit)


def range_query_kernel(
    xy: jnp.ndarray,
    valid: jnp.ndarray,
    flags: jnp.ndarray,
    query_xy: jnp.ndarray,
    radius,
    approximate: bool = False,
):
    """Point stream vs point query set.

    ``xy``: (N, 2); ``valid``: (N,) bool; ``flags``: (N,) uint8 per-point
    pruning flags (gathered via ops.cells.gather_cell_flags); ``query_xy``:
    (Q, 2). Returns (keep (N,) bool, min_dist (N,)). min_dist for
    guaranteed-only emissions is still exact (computed branchlessly).
    """
    d = pairwise_distance(xy, query_xy)  # (N, Q)
    min_dist = jnp.min(d, axis=1)
    return _emit_mask(valid, flags, min_dist, radius, approximate), min_dist


def range_query_polygons_kernel(
    xy: jnp.ndarray,
    valid: jnp.ndarray,
    flags: jnp.ndarray,
    poly_verts: jnp.ndarray,
    poly_edge_valid: jnp.ndarray,
    radius,
    approximate: bool = False,
):
    """Point stream vs polygon query set (JTS-distance semantics: 0 inside).

    ``poly_verts``: (P, V, 2) packed rings per query polygon;
    ``poly_edge_valid``: (P, V-1). The batched form of
    PointPolygonRangeQuery's window loop (range/PointPolygonRangeQuery.java:37-101).
    """
    def one_poly(verts, ev):
        edge_d = point_polyline_distance(xy, verts, ev)
        inside = points_in_polygon(xy, verts, ev)
        return jnp.where(inside, jnp.zeros((), edge_d.dtype), edge_d)

    d = jax.vmap(one_poly)(poly_verts, poly_edge_valid)  # (P, N)
    min_dist = jnp.min(d, axis=0)
    return _emit_mask(valid, flags, min_dist, radius, approximate), min_dist


def range_query_polylines_kernel(
    xy: jnp.ndarray,
    valid: jnp.ndarray,
    flags: jnp.ndarray,
    line_verts: jnp.ndarray,
    line_edge_valid: jnp.ndarray,
    radius,
    approximate: bool = False,
):
    """Point stream vs linestring query set (min edge distance).

    Batched form of PointLineStringRangeQuery's loop
    (range/PointLineStringRangeQuery.java).
    """
    d = jax.vmap(lambda v, e: point_polyline_distance(xy, v, e))(
        line_verts, line_edge_valid
    )  # (L, N)
    min_dist = jnp.min(d, axis=0)
    return _emit_mask(valid, flags, min_dist, radius, approximate), min_dist


def geometry_range_query_kernel(
    obj_verts: jnp.ndarray,
    obj_edge_valid: jnp.ndarray,
    valid: jnp.ndarray,
    flags: jnp.ndarray,
    query_verts: jnp.ndarray,
    query_edge_valid: jnp.ndarray,
    radius,
    approximate: bool = False,
):
    """Geometry stream (polygons/linestrings) vs geometry query set.

    ``obj_verts``: (N, V, 2) per-object packed boundaries. Distance between
    two boundaries = min over vertex→other-boundary distances both ways —
    the exact JTS ``geometry.distance`` result for non-overlapping
    geometries, which is what the reference computes per pair in e.g.
    PolygonPolygonRangeQuery's window loop. Overlap (distance 0 in JTS) is
    approximated by near-zero edge distance; containment-without-touching is
    handled by the operator layer's host check when exactness is required.
    """
    def pair_dist(averts, aev):
        def to_query(qverts, qev):
            d_ab = point_polyline_distance(averts, qverts, qev)
            big = jnp.asarray(jnp.finfo(d_ab.dtype).max, d_ab.dtype)
            a_vert_valid = jnp.concatenate(
                [aev, jnp.zeros((1,), bool)]
            ) | jnp.concatenate([jnp.zeros((1,), bool), aev])
            d_ab = jnp.where(a_vert_valid, d_ab, big)
            d_ba = point_polyline_distance(qverts, averts, aev)
            q_vert_valid = jnp.concatenate(
                [qev, jnp.zeros((1,), bool)]
            ) | jnp.concatenate([jnp.zeros((1,), bool), qev])
            d_ba = jnp.where(q_vert_valid, d_ba, big)
            return jnp.minimum(jnp.min(d_ab), jnp.min(d_ba))

        return jax.vmap(to_query)(query_verts, query_edge_valid)  # (Q,)

    d = jax.vmap(pair_dist)(obj_verts, obj_edge_valid)  # (N, Q)
    min_dist = jnp.min(d, axis=1)
    return _emit_mask(valid, flags, min_dist, radius, approximate), min_dist
