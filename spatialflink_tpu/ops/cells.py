"""Grid-cell assignment and cell-flag gathering kernels.

Replaces the reference's per-record string-keyed cell assignment
(``HelperClass.assignGridCellID``, HelperClass.java:104-116, which builds a
zero-padded ``"xxxxxyyyyy"`` string key per point) with integer cell ids
computed in one vectorized op: ``flat = xi * n + yi``. String keys exist
only at the serde boundary (see grid.UniformGrid.cell_name).
"""

from __future__ import annotations

import jax.numpy as jnp


def assign_cells(
    xy: jnp.ndarray,
    min_x: float,
    min_y: float,
    cell_length: float,
    n: int,
) -> jnp.ndarray:
    """Assign each point a flat int32 cell id in [0, n*n]; n*n = out-of-grid.

    ``xy``: (..., 2). Mirrors the floor arithmetic of
    HelperClass.assignGridCellID (HelperClass.java:104-116): points outside
    the grid bbox get index n*n (one past the last real cell), which every
    flag table maps to "pruned" — the same net effect as the reference,
    where out-of-range keys never appear in any neighbor set.
    """
    xi = jnp.floor((xy[..., 0] - min_x) / cell_length).astype(jnp.int32)
    yi = jnp.floor((xy[..., 1] - min_y) / cell_length).astype(jnp.int32)
    inside = (xi >= 0) & (xi < n) & (yi >= 0) & (yi < n)
    flat = xi * jnp.int32(n) + yi
    return jnp.where(inside, flat, jnp.int32(n * n))


def gather_cell_flags(cell_ids: jnp.ndarray, flags: jnp.ndarray) -> jnp.ndarray:
    """Gather per-point pruning flags from a (n*n+1,) table.

    ``flags`` encodes the neighbor-set classification the reference computes
    driver-side as HashSets (UniformGrid.java:165-222, 368-426):
    0 = not a neighbor cell (prune), 1 = candidate (needs exact distance),
    2 = guaranteed (emit without distance). Entry n*n (out-of-grid) is 0.
    """
    return flags[cell_ids]
