"""Hand-written Pallas TPU kernels for the hot geometry ops.

``point_polyline_min_dist_pallas`` computes the min distance from a block
of points to every edge of a packed polyline/polygon boundary — the inner
loop of polygon range queries and geofence filters. Points stream through
(64, 128) VMEM tiles; edge endpoints are SMEM scalars consumed by a
``fori_loop`` with a running minimum, so no (N, E) intermediate exists.

Status: numerically identical to ops.distances.point_polyline_distance
(≤1e-6 f32) and functional on the real chip, but NOT the default — XLA's
own fusion of the broadcast+reduce form already keeps this op compute-bound
on v5e, and the scalar-edge loop underutilizes the VPU. The kernel is kept
as the template for ops XLA cannot fuse (candidates for later rounds: the
grid-hash join gather and multi-boundary batched containment). Measure
before switching defaults.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

try:  # pallas is an experimental namespace; import-guard it
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

_LANES = 128
_ROWS = 64
_BLOCK = _LANES * _ROWS  # points per grid step, one (64, 128) f32 tile


def _min_dist_kernel(ex1_ref, ey1_ref, ex2_ref, ey2_ref, evalid_ref,
                     px_ref, py_ref, out_ref):
    """One (8, 128) block of points vs all edges; edges are SMEM scalars
    streamed through a fori_loop with a running minimum — no (N, E)
    intermediate ever exists."""
    px = px_ref[:]
    py = py_ref[:]
    n_edges = ex1_ref.shape[0]

    def body(e, acc):
        x1 = ex1_ref[e]
        y1 = ey1_ref[e]
        x2 = ex2_ref[e]
        y2 = ey2_ref[e]
        ok = evalid_ref[e]
        ax = px - x1
        ay = py - y1
        cx = x2 - x1
        cy = y2 - y1
        len_sq = cx * cx + cy * cy
        dot = ax * cx + ay * cy
        # Degenerate segment → clamp to endpoint 1 (param < 0 path).
        param = jnp.where(len_sq > 0, dot / jnp.where(len_sq > 0, len_sq, 1.0), -1.0)
        t = jnp.clip(param, 0.0, 1.0)
        dx = px - (x1 + t * cx)
        dy = py - (y1 + t * cy)
        d2 = dx * dx + dy * dy
        d2 = jnp.where(ok > 0, d2, jnp.float32(np.inf))
        return jnp.minimum(acc, d2)

    min_d2 = jax.lax.fori_loop(
        0, n_edges, body, jnp.full(px.shape, np.inf, jnp.float32)
    )
    out_ref[:] = jnp.sqrt(min_d2)


def pallas_available() -> bool:
    if not _HAS_PALLAS:
        return False
    try:
        return jax.devices()[0].platform in ("tpu", "axon", "cpu")
    except Exception:  # pragma: no cover
        return False


@functools.partial(jax.jit, static_argnames=("interpret",))
def _run_pallas(px, py, ex1, ey1, ex2, ey2, evalid, interpret=False):
    n_rows = px.shape[0]  # (n_rows, 128)
    grid = (n_rows // _ROWS,)
    block2d = lambda: pl.BlockSpec((_ROWS, _LANES), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM)
    smem = lambda: pl.BlockSpec(memory_space=pltpu.SMEM)
    return pl.pallas_call(
        _min_dist_kernel,
        out_shape=jax.ShapeDtypeStruct((n_rows, _LANES), jnp.float32),
        grid=grid,
        in_specs=[smem(), smem(), smem(), smem(), smem(), block2d(), block2d()],
        out_specs=block2d(),
        interpret=interpret,
    )(ex1, ey1, ex2, ey2, evalid, px, py)


def point_polyline_min_dist_pallas(
    xy: jnp.ndarray,
    verts: jnp.ndarray,
    edge_valid: jnp.ndarray,
    interpret: bool = False,
) -> jnp.ndarray:
    """(N,) min distance from each point to the packed boundary's edges.

    Drop-in float32 equivalent of ops.distances.point_polyline_distance for
    a single boundary. ``interpret=True`` runs the Pallas interpreter (CPU
    testing).
    """
    n = xy.shape[0]
    pad = (-n) % _BLOCK
    px = jnp.pad(xy[:, 0].astype(jnp.float32), (0, pad)).reshape(-1, _LANES)
    py = jnp.pad(xy[:, 1].astype(jnp.float32), (0, pad)).reshape(-1, _LANES)
    ex1 = verts[:-1, 0].astype(jnp.float32)
    ey1 = verts[:-1, 1].astype(jnp.float32)
    ex2 = verts[1:, 0].astype(jnp.float32)
    ey2 = verts[1:, 1].astype(jnp.float32)
    ev = edge_valid.astype(jnp.int32)
    out = _run_pallas(px, py, ex1, ey1, ex2, ey2, ev, interpret=interpret)
    return out.reshape(-1)[:n]
