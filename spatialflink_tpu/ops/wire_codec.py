"""Delta-bitpacked wire-pane codec — fewer bytes on the ~28 MB/s tunnel.

The 6 B/pt wire format (streams/wire.py) already beats the reference's
~100 B/pt text serde, but the headline configs are still TUNNEL-bound:
the chip idles behind the host→device link (ROADMAP item 1). For the
SNCB GPS regime — slow-moving objects sampled every few seconds — most
of those 6 bytes are redundant: an object's quantized position moves a
handful of lattice steps per pane. This codec makes movement cost BITS,
not lanes:

- **delta-against-previous-pane**: each record's quantized (x, y) is
  predicted by the SAME object's last position in any earlier pane (a
  per-oid predictor table, init 0); the wire carries the zigzag-encoded
  mod-2^16 delta. Wraparound arithmetic makes the round trip exact for
  EVERY input — a never-seen object or a teleport just costs full
  width.
- **bitpacked lanes**: per pane, each of the three streams (zigzag-dx,
  zigzag-dy, oid bits) is packed at the smallest bit width that holds
  its max value (0..16), LSB-first into little-endian uint32 words —
  three word-aligned streams concatenated into ONE payload array.
  Worst case (incompressible pane) is raw width plus a few header
  bytes; a stationary fleet costs ~the oid stream alone.

Decode runs ON DEVICE as a fixed-shape jitted kernel
(:func:`decode_wire_pane`): word/offset arithmetic + gathers, no
data-dependent shapes — the pane capacity and word-count buckets ride
the shared compaction ladders (``wire_pane_bucket`` /
:func:`wire_word_bucket`), so variable pane sizes reuse ≤ladder-many
compiled programs. The per-oid predictor table lives ON DEVICE between
panes (carried like the digest ring, never re-shipped); the host
encoder maintains the bit-identical mirror it needs for delta
computation. Compression can therefore NEVER change results: the
decoded (3, n) uint16 pane is bit-identical to the raw pane the
uncompressed path would have shipped (padding lanes zeroed, like the
raw path's bucket padding), and everything downstream is unchanged.

A Pallas fast path for the bit extraction exists behind the same
self-check contract as the wire digest (ops/wire_knn.py): adopted only
when a sample pane decodes bit-identically to the jnp kernel; any
lowering failure stays on the always-correct jnp path.

Host/device split (CLAUDE.md): encode is host control plane (numpy,
runs where the bytes originate); decode is compute plane (jit-safe,
fuses into the consuming pipeline's dispatch stream).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

#: Fixed per-pane header cost charged to ``coded_bytes``: n (4 B) +
#: three bit widths (1 B each) + 1 B pad. The payload words are the
#: real wire traffic; the header rides the dispatch args.
HEADER_BYTES = 8

#: Floor for the payload word bucket (64 B) — keeps tiny panes from
#: minting one compiled shape per word count.
WORD_BUCKET_MIN = 16


# ---------------------------------------------------------------------------
# Host bit packing (encoder side)


def pack_bits(vals: np.ndarray, b: int) -> np.ndarray:
    """Pack ``(n,)`` unsigned values at ``b`` bits each, LSB-first, into
    little-endian uint32 words (``ceil(n*b/32)`` of them)."""
    n = int(len(vals))
    if b == 0 or n == 0:
        return np.zeros(0, np.uint32)
    v = np.asarray(vals, np.uint32)  # sfcheck: ok=trace-hygiene -- host encoder half (module docstring): packs producer-side numpy, never a tracer
    bits = ((v[:, None] >> np.arange(b, dtype=np.uint32)[None, :]) & 1)
    flat = bits.astype(np.uint8).ravel()
    words = -((-n * b) // 32)
    pad = words * 32 - flat.size
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, np.uint8)])
    return np.packbits(flat, bitorder="little").view(np.dtype("<u4"))


def unpack_bits_np(words: np.ndarray, n: int, b: int) -> np.ndarray:
    """Host twin of the device extraction (tests + reference decode)."""
    if b == 0 or n == 0:
        return np.zeros(n, np.uint32)
    flat = np.unpackbits(
        np.asarray(words, np.dtype("<u4")).view(np.uint8),  # sfcheck: ok=trace-hygiene -- host reference twin of the device extraction (docstring): numpy on host words
        bitorder="little",
    )
    take = flat[: n * b].reshape(n, b).astype(np.uint32)
    return (take << np.arange(b, dtype=np.uint32)[None, :]).sum(
        axis=1, dtype=np.uint32
    )


def _zigzag16(d: np.ndarray) -> np.ndarray:
    """int16 deltas → uint16 zigzag codes (small |d| → small code)."""
    d32 = d.astype(np.int32)
    return (((d32 << 1) ^ (d32 >> 15)) & 0xFFFF).astype(np.uint16)


def _bit_width(vals: np.ndarray) -> int:
    if len(vals) == 0:
        return 0
    return int(int(np.max(vals)).bit_length())


class EncodedPane(NamedTuple):
    """One compressed wire pane: payload words + the header scalars the
    decode kernel needs. ``raw_bytes``/``coded_bytes`` feed the
    compression gauges (telemetry.account_wire)."""

    words: np.ndarray  # (W,) uint32 payload (x-, y-, oid-stream concat)
    n: int             # record count
    bx: int            # zigzag-dx bit width (0..16)
    by: int            # zigzag-dy bit width (0..16)
    bo: int            # oid bit width (0..16)
    raw_bytes: int     # 6 * n — what the uncompressed wire would ship
    coded_bytes: int   # 4 * len(words) + HEADER_BYTES


class WirePaneEncoder:
    """Host-side stateful encoder — the control-plane half.

    Mirrors the device predictor table exactly: both sides update each
    oid's entry to its LAST position in the pane, so encoder deltas and
    device reconstruction agree bit-for-bit forever. ``state()`` /
    ``restore()`` snapshot the mirror for checkpoints (the device table
    is derived state — a resume re-ships the mirror once).
    """

    def __init__(self, num_segments: int):
        self.num_segments = int(num_segments)  # sfcheck: ok=trace-hygiene -- host control plane: the encoder is constructed with a host int, never traced
        self.pred_x = np.zeros(self.num_segments, np.uint16)
        self.pred_y = np.zeros(self.num_segments, np.uint16)

    def encode(self, wire_p: np.ndarray) -> EncodedPane:
        """(3, n) uint16 plane-major pane → :class:`EncodedPane`."""
        wire_p = np.asarray(wire_p)  # sfcheck: ok=trace-hygiene -- host encoder: panes arrive as producer-side numpy (module docstring)
        if wire_p.ndim != 2 or wire_p.shape[0] != 3 \
                or wire_p.dtype != np.uint16:
            raise ValueError(
                "encode expects a (3, n) uint16 plane-major pane, got "
                f"{wire_p.dtype} {wire_p.shape}"
            )
        n = int(wire_p.shape[1])
        if n == 0:
            return EncodedPane(np.zeros(0, np.uint32), 0, 0, 0, 0, 0,
                               HEADER_BYTES)
        x, y, o = wire_p[0], wire_p[1], wire_p[2]
        if int(np.max(o)) >= self.num_segments:
            raise ValueError(
                f"oid {int(np.max(o))} >= num_segments "
                f"{self.num_segments}: the predictor table cannot index "
                "it (intern ids densely, like the wire digest)"
            )
        oi = o.astype(np.int64)
        dx = (x.astype(np.int32) - self.pred_x[oi].astype(np.int32)) \
            .astype(np.int16)
        dy = (y.astype(np.int32) - self.pred_y[oi].astype(np.int32)) \
            .astype(np.int16)
        zx, zy = _zigzag16(dx), _zigzag16(dy)
        bx, by, bo = _bit_width(zx), _bit_width(zy), _bit_width(o)
        words = np.concatenate(
            [pack_bits(zx, bx), pack_bits(zy, by), pack_bits(o, bo)]
        )
        # Duplicate oids: numpy fancy assignment keeps the LAST write,
        # matching the device update's last-occurrence segment_max.
        self.pred_x[oi] = x
        self.pred_y[oi] = y
        return EncodedPane(
            words, n, bx, by, bo,
            raw_bytes=6 * n,
            coded_bytes=4 * int(len(words)) + HEADER_BYTES,
        )

    def state(self) -> dict:
        # Copies: the live tables mutate in place on the next encode —
        # a snapshot must not change after it is taken (and a shipped
        # table must never alias them; XLA:CPU zero-copies host
        # buffers).
        return {
            "num_segments": int(self.num_segments),
            "pred_x": self.pred_x.copy(),
            "pred_y": self.pred_y.copy(),
        }

    def restore(self, state: dict) -> None:
        if int(state["num_segments"]) != self.num_segments:
            raise ValueError(
                f"codec checkpoint num_segments {state['num_segments']} "
                f"!= this encoder's {self.num_segments} — predictor "
                "tables would silently misalign"
            )
        self.pred_x = np.asarray(state["pred_x"], np.uint16).copy()
        self.pred_y = np.asarray(state["pred_y"], np.uint16).copy()


#: Rungs per pane bucket in the word ladder: padding overhead is
#: bounded by worst_case/WORD_LADDER_RUNGS (~6%), compiled shapes per
#: pane bucket by WORD_LADDER_RUNGS+1.
WORD_LADDER_RUNGS = 16


def wire_word_bucket(w: int, pane_bucket: int,
                     minimum: int = WORD_BUCKET_MIN) -> int:
    """Payload word-count bucket — the codec twin of
    ops/compaction.py:wire_pane_bucket, with the same per-bucket
    occupancy telemetry. The rung granularity derives from the pane
    bucket's WORST-CASE payload (three 16-bit streams) split into
    ``WORD_LADDER_RUNGS`` steps, so compiled decode shapes stay bounded
    per pane bucket while padding overhead stays ≤ ~1/16 — a plain
    power-of-two ladder could pad a just-over-a-rung payload by ~2x,
    which would silently cost MORE wire bytes than the raw format (the
    shipped bucket bytes are what ``account_wire`` must charge)."""
    from spatialflink_tpu.telemetry import telemetry

    worst = 3 * ((int(pane_bucket) * 16 + 31) >> 5)  # sfcheck: ok=trace-hygiene -- host control plane: the pane bucket is a host int (wire_pane_bucket's pick), never traced
    grain = max(int(minimum), -(-worst // WORD_LADDER_RUNGS))  # sfcheck: ok=trace-hygiene -- same host-side rung arithmetic as above
    b = max(int(minimum), -(-int(w) // grain) * grain)  # sfcheck: ok=trace-hygiene -- host control plane: payload word count is a host int picking a static bucket (wire_pane_bucket twin)
    telemetry.record_compaction("wire_codec_words", b, int(w))  # sfcheck: ok=trace-hygiene -- same host-side bucket pick as above
    return b


def pad_words(words: np.ndarray, bucket: int) -> np.ndarray:
    """Pad the payload to its bucket (zero words are inert: every read
    past a stream's end is masked by the extraction's width mask)."""
    if len(words) >= bucket:
        return np.asarray(words, np.uint32)  # sfcheck: ok=trace-hygiene -- host control plane: pads the encoder's numpy payload before the ship
    out = np.zeros(bucket, np.uint32)
    out[: len(words)] = words
    return out


# ---------------------------------------------------------------------------
# Device decode (jit-safe, fixed shape)


def _extract_lanes(words, word_off, idx, b):
    """Extract ``b``-bit fields ``idx`` (LSB-first stream starting at
    ``words[word_off]``) — all of ``word_off``/``b`` traced, shapes
    static. Cross-word reads mask away foreign bits: when a field fits
    in one word the second word's contribution lands at bit ≥ b and the
    width mask kills it, so reading into the NEXT stream's words is
    harmless by construction."""
    n_words = words.shape[0]
    bitpos = idx * b
    w0 = jnp.clip(word_off + (bitpos >> 5), 0, n_words - 1)
    w1 = jnp.clip(word_off + (bitpos >> 5) + 1, 0, n_words - 1)
    s = (bitpos & 31).astype(jnp.uint32)
    bu = jnp.uint32(b)
    lo = jnp.take(words, w0) >> s
    hi = jnp.where(
        s == 0,
        jnp.uint32(0),
        jnp.take(words, w1) << ((jnp.uint32(32) - s) & jnp.uint32(31)),
    )
    mask = jnp.where(
        bu == 0, jnp.uint32(0), (jnp.uint32(1) << bu) - jnp.uint32(1)
    )
    return (lo | hi) & mask


def _unzigzag(z):
    """uint32 zigzag codes → int32 deltas."""
    zi = z.astype(jnp.int32)
    return (zi >> 1) ^ -(zi & 1)


def extract_streams(words, n_valid, bx, by, bo, *, n: int):
    """The bit-twiddle half of decode: payload words → (zx, zy, o)
    uint32 lanes for ``n`` (static bucket) lanes; lanes ≥ ``n_valid``
    carry garbage the caller masks. Split out so the Pallas fast path
    can replace exactly this function (the predictor arithmetic stays
    shared jnp)."""
    idx = jnp.arange(n, dtype=jnp.int32)
    wx = (n_valid * bx + 31) >> 5
    wy = (n_valid * by + 31) >> 5
    zx = _extract_lanes(words, jnp.int32(0), idx, bx)
    zy = _extract_lanes(words, wx, idx, by)
    o = _extract_lanes(words, wx + wy, idx, bo)
    return zx, zy, o


def decode_wire_pane(words, n_valid, bx, by, bo, pred_x, pred_y, *,
                     n: int, num_segments: int,
                     extract=extract_streams):
    """Fixed-shape device decode + predictor update — ONE dispatch.

    ``words``: (W,) uint32 bucket-padded payload; ``n_valid``/widths:
    traced scalars; ``pred_x``/``pred_y``: (num_segments,) uint16
    device-resident predictor tables. Returns ``(pane, pred_x2,
    pred_y2)`` where ``pane`` is the (3, n) uint16 plane-major pane,
    bit-identical to the raw pane the uncompressed path would ship
    (padding lanes zeroed — the raw path's bucket padding). The tables
    update to each oid's LAST position in the pane (deterministic
    last-occurrence ``segment_max``, never an unordered scatter), the
    exact rule the host encoder mirrors.

    ``extract``: the stream-extraction function (the Pallas fast path
    substitutes here after its self-check).
    """
    idx = jnp.arange(n, dtype=jnp.int32)
    valid = idx < n_valid
    zx, zy, o = extract(words, n_valid, bx, by, bo, n=n)
    o_safe = jnp.clip(o.astype(jnp.int32), 0, num_segments - 1)
    x = (jnp.take(pred_x, o_safe).astype(jnp.int32) + _unzigzag(zx)) \
        & 0xFFFF
    y = (jnp.take(pred_y, o_safe).astype(jnp.int32) + _unzigzag(zy)) \
        & 0xFFFF
    x = jnp.where(valid, x, 0).astype(jnp.uint16)
    y = jnp.where(valid, y, 0).astype(jnp.uint16)
    ou = jnp.where(valid, o, 0).astype(jnp.uint16)
    pane = jnp.stack([x, y, ou])

    # Last-occurrence predictor update: per-segment max position, then
    # gather that position's decoded coords. Invalid lanes rank into a
    # drop segment (the out-of-grid-slot idiom).
    seg = jnp.where(valid, o_safe, num_segments)
    last = jax.ops.segment_max(
        idx, seg, num_segments=num_segments + 1
    )[:num_segments]
    has = last >= 0
    gpos = jnp.clip(last, 0, n - 1)
    px2 = jnp.where(has, jnp.take(x, gpos), pred_x).astype(jnp.uint16)
    py2 = jnp.where(has, jnp.take(y, gpos), pred_y).astype(jnp.uint16)
    return pane, px2, py2


def decode_wire_pane_np(enc: EncodedPane, pred_x: np.ndarray,
                        pred_y: np.ndarray
                        ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host reference decode (numpy twin of :func:`decode_wire_pane`
    without padding): (3, n) pane + updated predictor copies."""
    n = enc.n
    wx = -((-n * enc.bx) // 32)
    wy = -((-n * enc.by) // 32)
    zx = unpack_bits_np(enc.words[:wx], n, enc.bx)
    zy = unpack_bits_np(enc.words[wx:wx + wy], n, enc.by)
    o = unpack_bits_np(enc.words[wx + wy:], n, enc.bo).astype(np.uint16)
    zi_x = zx.astype(np.int32)
    zi_y = zy.astype(np.int32)
    dx = (zi_x >> 1) ^ -(zi_x & 1)
    dy = (zi_y >> 1) ^ -(zi_y & 1)
    oi = o.astype(np.int64)
    x = ((pred_x[oi].astype(np.int32) + dx) & 0xFFFF).astype(np.uint16)
    y = ((pred_y[oi].astype(np.int32) + dy) & 0xFFFF).astype(np.uint16)
    px2, py2 = pred_x.copy(), pred_y.copy()
    px2[oi] = x
    py2[oi] = y
    return np.stack([x, y, o]), px2, py2


# ---------------------------------------------------------------------------
# Pallas fast path (bit extraction only; predictor arithmetic stays jnp)


def _extract_kernel(words_ref, meta_ref, zx_ref, zy_ref, zo_ref):
    """meta = [n_valid, bx, by, bo] in SMEM; one block, lane-parallel
    extraction (the same arithmetic as _extract_lanes)."""
    n_valid = meta_ref[0]
    bx, by, bo = meta_ref[1], meta_ref[2], meta_ref[3]
    words = words_ref[...]
    n = zx_ref.shape[0]
    idx = jax.lax.iota(jnp.int32, n)

    def extract(word_off, b):
        n_words = words.shape[0]
        bitpos = idx * b
        w0 = jnp.clip(word_off + (bitpos >> 5), 0, n_words - 1)
        w1 = jnp.clip(word_off + (bitpos >> 5) + 1, 0, n_words - 1)
        s = (bitpos & 31).astype(jnp.uint32)
        bu = jnp.uint32(b)
        lo = jnp.take(words, w0) >> s
        hi = jnp.where(
            s == 0,
            jnp.uint32(0),
            jnp.take(words, w1) << ((jnp.uint32(32) - s)
                                    & jnp.uint32(31)),
        )
        mask = jnp.where(
            bu == 0, jnp.uint32(0),
            (jnp.uint32(1) << bu) - jnp.uint32(1),
        )
        return (lo | hi) & mask

    wx = (n_valid * bx + 31) >> 5
    wy = (n_valid * by + 31) >> 5
    zx_ref[...] = extract(jnp.int32(0), bx)
    zy_ref[...] = extract(wx, by)
    zo_ref[...] = extract(wx + wy, bo)


def make_pallas_extract(*, interpret: bool = False):
    """Pallas form of :func:`extract_streams` (same signature after the
    keyword binding). Adoption is gated by :func:`select_wire_decoder`'s
    self-check — a lowering failure or disagreement never escapes it."""
    from jax.experimental import pallas as pl

    def extract(words, n_valid, bx, by, bo, *, n: int):
        meta = jnp.stack([
            n_valid.astype(jnp.int32) if hasattr(n_valid, "astype")
            else jnp.int32(n_valid),
            jnp.int32(bx), jnp.int32(by), jnp.int32(bo),
        ])
        out = jax.ShapeDtypeStruct((n,), jnp.uint32)
        return pl.pallas_call(
            _extract_kernel,
            out_shape=(out, out, out),
            interpret=interpret,
        )(words, meta)

    return extract


def codec_decodes_agree(a, b) -> bool:
    """Self-check predicate: two decoded (pane, px, py) triples must be
    BIT-identical — the codec has no FMA freedom, only integers.
    Host-side (fetches both)."""
    pa, xa, ya = jax.device_get(a)  # sfcheck: ok=trace-hygiene -- host-side self-check predicate (docstring): fetching both decodes IS the job (the wire_knn.digests_agree precedent)
    pb, xb, yb = jax.device_get(b)  # sfcheck: ok=trace-hygiene -- same host-side self-check fetch as above
    return (np.array_equal(pa, pb) and np.array_equal(xa, xb)
            and np.array_equal(ya, yb))


def select_wire_decoder(strategy: str = "auto", *,
                        interpret: bool = False,
                        sample_args: Optional[tuple] = None,
                        n: int = 0, num_segments: int = 0):
    """Pick the stream-extraction implementation with the bench.py
    self-check contract (ops/wire_knn.py:select_wire_digest_step):
    ``auto`` adopts Pallas on TPU (or under ``interpret``) only after a
    sample pane decodes bit-identically through both paths; any failure
    stays on the always-correct jnp extraction. Returns
    ``(kind, extract_fn)``."""
    import sys

    if strategy == "jnp":
        return "jnp", extract_streams
    on_tpu = False
    try:
        on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    except Exception:  # pragma: no cover - device discovery failure
        pass
    if strategy == "auto" and not (on_tpu or interpret):
        return "jnp", extract_streams
    try:
        pallas_extract = make_pallas_extract(interpret=interpret)
        if sample_args is not None:
            d_p = jax.jit(functools_partial_decode(
                pallas_extract, n=n, num_segments=num_segments
            ))(*sample_args)
            d_j = jax.jit(functools_partial_decode(
                extract_streams, n=n, num_segments=num_segments
            ))(*sample_args)
            if not codec_decodes_agree(d_p, d_j):
                sys.stderr.write(
                    "wire-codec self-check FAILED: pallas extraction "
                    "disagrees with the jnp path — staying on jnp\n"
                )
                if strategy == "pallas":
                    raise RuntimeError("pallas wire decode disagreed")
                return "jnp", extract_streams
        return "pallas", pallas_extract
    except Exception as e:
        if strategy == "pallas":
            raise
        sys.stderr.write(f"pallas wire decode disabled: {e!r}\n")
    return "jnp", extract_streams


def functools_partial_decode(extract, *, n: int, num_segments: int):
    """decode_wire_pane with statics + extraction bound (a named helper
    so the self-check and run_wire_panes build the identical step)."""
    import functools

    return functools.partial(
        decode_wire_pane, n=n, num_segments=num_segments, extract=extract,
    )
