"""Kafka ingress/egress.

The reference's transport is Kafka (FlinkKafkaConsumer/Producer,
StreamingJob.java:188-191,255; producers in Serialization.java). The
record boundary — lines of GeoJSON/WKT/CSV — is identical to the file/
socket sources, so the transport layer only moves bytes.

Backends, in order of preference:

1. ``kafka-python`` / ``confluent_kafka`` if importable (full consumer-
   group support);
2. the BUILT-IN wire-protocol client (streams/kafka_wire.py — metadata/
   produce/fetch/list-offsets over a raw socket, no pip; brokers
   0.10–3.x, NOT 4.0+ whose KIP-896 removed these protocol versions —
   a 4.0 broker surfaces a clear UNSUPPORTED_VERSION KafkaError).
   Always available, so ``kafka_available()`` is unconditionally True;
   partition assignment is explicit (all partitions of the topic,
   timestamp-merged per fetch round) rather than group-coordinated — the
   reference likewise relies on Flink's own partition assignment, not
   group rebalancing. Offsets follow Flink's CHECKPOINTED-consumer model
   (StreamingJob.java:255): ``WireKafkaSource`` exposes per-partition
   positions that snapshot/restore through checkpoint.py, so a killed
   ingest resumes gap-free and dup-free.
"""

from __future__ import annotations

from typing import Callable, Iterator, Optional, TypeVar

from spatialflink_tpu.faults import faults

T = TypeVar("T")


def _import_kafka():
    try:
        import kafka  # type: ignore

        return "kafka", kafka
    except ImportError:
        pass
    try:
        import confluent_kafka  # type: ignore

        return "confluent", confluent_kafka
    except ImportError:
        pass
    from spatialflink_tpu.streams import kafka_wire

    return "wire", kafka_wire


def kafka_available() -> bool:
    """Always True: the built-in wire client needs no external library."""
    return _import_kafka()[0] is not None


def kafka_source(
    topic: str,
    bootstrap_servers: str,
    parser: Callable[[str], T],
    group_id: str = "spatialflink-tpu",
    from_earliest: bool = True,
) -> Iterator[T]:
    """Consume a topic as parsed records (FlinkKafkaConsumer analog).

    Unparseable records are skipped (the reference's deserializers drop
    malformed lines the same way). With the built-in backend ``group_id``
    only labels the client; partitions are explicitly assigned.
    """
    kind, mod = _import_kafka()
    return _kafka_iter(kind, mod, topic, bootstrap_servers, parser,
                       group_id, from_earliest)


def _kafka_iter(kind, mod, topic, bootstrap_servers, parser, group_id,
                from_earliest) -> Iterator[T]:
    if kind == "kafka":
        consumer = mod.KafkaConsumer(
            topic,
            bootstrap_servers=bootstrap_servers.split(","),
            group_id=group_id,
            auto_offset_reset="earliest" if from_earliest else "latest",
        )
        try:
            for msg in consumer:
                try:
                    yield parser(msg.value.decode())
                except (ValueError, IndexError):
                    continue
        finally:
            consumer.close()
    elif kind == "confluent":
        consumer = mod.Consumer(
            {
                "bootstrap.servers": bootstrap_servers,
                "group.id": group_id,
                "auto.offset.reset": "earliest" if from_earliest else "latest",
            }
        )
        consumer.subscribe([topic])
        try:
            while True:
                msg = consumer.poll(1.0)
                if msg is None:
                    continue
                err = msg.error()
                if err:
                    # Transient partition events are skippable; fatal broker/
                    # auth errors must surface, not spin forever.
                    if getattr(err, "fatal", lambda: True)():
                        raise RuntimeError(f"Kafka consumer error: {err}")
                    continue
                try:
                    yield parser(msg.value().decode())
                except (ValueError, IndexError):
                    continue
        finally:
            consumer.close()
    else:  # built-in wire client
        src = WireKafkaSource(topic, bootstrap_servers, parser,
                              group_id=group_id, from_earliest=from_earliest)
        try:
            yield from src
        finally:
            src.close()


class WireKafkaSource:
    """Resumable built-in consumer: the FlinkKafkaConsumer's
    checkpointed-offsets role (StreamingJob.java:255 — Flink snapshots
    the consumer's partition offsets with every checkpoint so a restart
    replays from exactly where it left off).

    ``offsets`` (partition → NEXT offset to fetch) advances per record
    AS IT IS YIELDED — every record below ``offsets[p]`` has been handed
    to the pipeline, everything at/after it has not. Snapshotting
    ``offsets`` together with the downstream operator state
    (checkpoint.py:kafka_source_state) therefore gives gap-free,
    dup-free kill-and-resume: restore the operator, pass the snapshot
    back as ``start_offsets``, and the stream continues mid-window
    (tests/test_kafka_wire.py::test_kill_and_resume_replays_no_gap_no_dup).

    Log holes (compacted topics / retention): a fetched batch that
    STARTS past the requested position snaps the position to the
    batch's base offset, and within a batch the position advances along
    the offsets the broker actually delivered — deleted offsets are
    never waited for. The out-of-sequence parking below therefore
    guards only against the ts-sort reordering records of ONE fetched
    batch (the only case where an "earlier" record is still coming).

    Cross-partition timestamp ordering: within a fetch round, records
    from all partitions yield in event-time order (stable sort; the
    single-partition common case bypasses the buffer). Mid-round offset
    consistency assumes within-partition timestamps are monotone — the
    same in-order assumption the pane paths already make. Unparseable
    records and null tombstones advance their offset (they were
    consumed) without yielding.
    """

    #: Backpressure capability flag read by the dataflow driver's
    #: admission control (overload.py): a broker retains the log, so the
    #: consumer absorbs pressure by simply not issuing the next fetch
    #: round (the pull loop's natural pause) — it never needs the
    #: non-replayable shed path a live socket does.
    pausable = True

    def __init__(self, topic: str, bootstrap_servers: str,
                 parser: Callable[[str], T], group_id: str = "spatialflink-tpu",
                 from_earliest: bool = True,
                 start_offsets: Optional[dict] = None):
        from spatialflink_tpu.streams import kafka_wire

        self._mod = kafka_wire
        self.topic = topic
        self._parser = parser
        self._from_earliest = from_earliest
        self._offsets: dict = dict(start_offsets or {})
        self._client = kafka_wire.KafkaWireClient(
            bootstrap_servers, client_id=group_id
        )

    @property
    def offsets(self) -> dict:
        """Per-partition next-fetch offsets (snapshot-safe copy)."""
        return dict(self._offsets)

    def close(self) -> None:
        self._client.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __iter__(self) -> Iterator[T]:
        import time as _time

        client, topic, mod = self._client, self.topic, self._mod
        # A broker auto-creating the topic answers the first metadata
        # request with UNKNOWN_TOPIC_OR_PARTITION / LEADER_NOT_AVAILABLE
        # (dropped by metadata()); retry like the library consumers do.
        parts: list = []
        for _attempt in range(25):
            parts = client.metadata([topic]).get(topic, [])
            if parts:
                break
            _time.sleep(0.2)
        if not parts:
            raise RuntimeError(
                f"topic {topic!r} has no partitions (does it exist?)"
            )
        ts = mod.EARLIEST if self._from_earliest else mod.LATEST
        for p in parts:
            # Restored partitions keep their checkpointed position;
            # partitions unseen at snapshot time start per from_earliest.
            if p not in self._offsets:
                self._offsets[p] = client.list_offset(topic, p, ts)
        single = len(parts) == 1
        offsets = self._offsets  # mutated in place: `offsets` stays live
        while True:
            progressed = False
            # Merge each fetch round across partitions by message
            # timestamp: a fixed round-robin yield would interleave
            # partitions out of event-time order, and the pane paths
            # (query_panes rejects allowed_lateness) would silently
            # drop such records as late. Cost: a round's records are
            # held until every partition's fetch returns (idle
            # partitions long-poll max_wait_ms) — inherent to
            # cross-partition ordering. The sort key is timestamp ONLY
            # and the sort is stable, so a partition's producer order
            # survives for equal/monotone timestamps.
            round_msgs: list = []
            succ: dict = {}  # partition → offset → next fetch position
            for p in parts:
                if faults.armed:  # chaos injection point (faults.py)
                    faults.hit("kafka.fetch")
                msgs, _hw = client.fetch(topic, p, offsets[p])
                if msgs and msgs[0][0] > offsets[p]:
                    # The batch STARTS past our position: a log hole
                    # (compaction / retention deleted the offsets we
                    # asked for), not a reorder — the broker always
                    # serves the first available record at/after the
                    # requested offset. Snap the position to the fetch
                    # response's base offset so the contiguity rule
                    # below applies only WITHIN this fetched batch;
                    # without the snap, every record of a compacted
                    # topic past the first hole parks in `ahead`
                    # forever and each round re-fetches (and re-yields)
                    # the same records — a stall-plus-duplicate storm.
                    offsets[p] = msgs[0][0]
                if not single and msgs:
                    # Within-batch successor chain: the broker delivers
                    # a batch offset-ascending, so "contiguous" means
                    # the NEXT OFFSET PRESENT IN THE BATCH — holes the
                    # broker itself skipped (compacted-away records)
                    # are not missing data to wait for.
                    offs_p = [m[0] for m in msgs]
                    succ[p] = dict(
                        zip(offs_p, offs_p[1:] + [offs_p[-1] + 1])
                    )
                for off, ts_ms, _key, value in msgs:
                    progressed = True
                    if single:
                        offsets[p] = off + 1
                        if value is None:
                            continue
                        try:
                            rec = self._parser(value.decode())
                        except (ValueError, IndexError):
                            continue
                        yield rec
                    else:
                        round_msgs.append((ts_ms, p, off, value))
            round_msgs.sort(key=lambda m: m[0])
            # Positions advance CONTIGUOUSLY as records are handed over:
            # if within-partition timestamps are non-monotone (producer
            # retry / CreateTime skew) the ts sort can yield a later
            # offset first — advancing straight to it would make a
            # mid-round checkpoint SKIP the earlier, not-yet-yielded
            # record. Out-of-sequence yields park in `ahead` until the
            # gap closes; a mid-round resume then re-delivers them
            # (at-least-once under ts skew; exactly-once for the normal
            # monotone case — same degradation as any replaying source).
            ahead: dict = {}
            for _ts, p, off, value in round_msgs:
                if off == offsets[p]:
                    offsets[p] = succ[p][off]
                    parked = ahead.get(p)
                    while parked and offsets[p] in parked:
                        parked.remove(offsets[p])
                        offsets[p] = succ[p][offsets[p]]
                elif off > offsets[p]:
                    ahead.setdefault(p, set()).add(off)
                if value is None:
                    continue
                try:
                    rec = self._parser(value.decode())
                except (ValueError, IndexError):
                    continue
                yield rec
            if not progressed:
                # fetch() already long-polled max_wait_ms per partition;
                # loop again (a live stream source never terminates —
                # same contract as the library-backed branches).
                continue


class KafkaSink:
    """Produce rendered records to a topic (Serialization.java producers).

    The built-in backend buffers records and produces one message set per
    ``flush()`` (auto-flushes every ``batch`` records) — the analog of the
    library producers' internal batching.
    """

    def __init__(self, topic: str, bootstrap_servers: str,
                 formatter: Callable = str, partition: int = 0,
                 batch: int = 500):
        kind, mod = _import_kafka()
        self.topic = topic
        self.formatter = formatter
        self._kind = kind
        if kind == "kafka":
            self._producer = mod.KafkaProducer(
                bootstrap_servers=bootstrap_servers.split(",")
            )
            self._send = lambda v: self._producer.send(self.topic, v)
        elif kind == "confluent":
            self._producer = mod.Producer({"bootstrap.servers": bootstrap_servers})
            self._send = lambda v: self._producer.produce(self.topic, v)
        else:
            import weakref

            self._client = mod.KafkaWireClient(bootstrap_servers)
            self._partition = partition
            self._batch = batch
            self._buf: list = []
            self._send = self._buffer_send
            # The wire backend has no producer thread: records sit in
            # _buf until flush()/close(). Guarantee delivery even if the
            # owner drops the sink without closing (library backends
            # flush via their own threads) — the finalizer flushes at GC
            # or interpreter exit. close() detaches it.
            self._finalizer = weakref.finalize(
                self, KafkaSink._final_flush,
                self._client, self.topic, partition, self._buf,
            )

    @staticmethod
    def _final_flush(client, topic, partition, buf):
        # Bound object state only (weakref.finalize contract: no self).
        try:
            if buf:
                client.produce(topic, partition, list(buf))
                buf.clear()
            client.close()
        except Exception:
            pass  # interpreter teardown: sockets may already be gone

    def _buffer_send(self, value: bytes) -> None:
        import time as _time

        self._buf.append((value, None, int(_time.time() * 1000)))
        if len(self._buf) >= self._batch:
            self.flush()

    def __call__(self, record):
        self._send(self.formatter(record).encode())

    def flush(self):
        if self._kind in ("kafka", "confluent"):
            self._producer.flush()
        elif self._buf:
            self._client.produce(self.topic, self._partition, self._buf)
            self._buf.clear()  # in place: the finalizer holds this list

    def close(self):
        self.flush()
        if self._kind == "wire":
            self._finalizer.detach()
            self._client.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
