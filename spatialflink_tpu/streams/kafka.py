"""Kafka ingress/egress seam.

The reference's transport is Kafka (FlinkKafkaConsumer/Producer,
StreamingJob.java:188-191,255; producers in Serialization.java). This
environment ships no Kafka client library and no broker, so the connector
is gated: if ``kafka-python`` (or ``confluent_kafka``) is importable the
source/sink work as expected; otherwise construction raises with a clear
message pointing at the file/socket equivalents (the record boundary —
lines of GeoJSON/WKT/CSV — is identical, which is the actual seam the
framework depends on).
"""

from __future__ import annotations

from typing import Callable, Iterator, Optional, TypeVar

T = TypeVar("T")


def _import_kafka():
    try:
        import kafka  # type: ignore

        return "kafka", kafka
    except ImportError:
        pass
    try:
        import confluent_kafka  # type: ignore

        return "confluent", confluent_kafka
    except ImportError:
        return None, None


def kafka_available() -> bool:
    return _import_kafka()[0] is not None


_MISSING = (
    "No Kafka client library is available in this environment. Use "
    "streams.sources.csv_source / socket_source (same line-record boundary) "
    "or install kafka-python."
)


def kafka_source(
    topic: str,
    bootstrap_servers: str,
    parser: Callable[[str], T],
    group_id: str = "spatialflink-tpu",
    from_earliest: bool = True,
) -> Iterator[T]:
    """Consume a topic as parsed records (FlinkKafkaConsumer analog).

    Fails at call time (not first iteration) when no client is available.
    """
    kind, mod = _import_kafka()
    if kind is None:
        raise RuntimeError(_MISSING)
    return _kafka_iter(kind, mod, topic, bootstrap_servers, parser,
                       group_id, from_earliest)


def _kafka_iter(kind, mod, topic, bootstrap_servers, parser, group_id,
                from_earliest) -> Iterator[T]:
    if kind == "kafka":
        consumer = mod.KafkaConsumer(
            topic,
            bootstrap_servers=bootstrap_servers.split(","),
            group_id=group_id,
            auto_offset_reset="earliest" if from_earliest else "latest",
        )
        try:
            for msg in consumer:
                try:
                    yield parser(msg.value.decode())
                except (ValueError, IndexError):
                    continue
        finally:
            consumer.close()
    else:  # confluent
        consumer = mod.Consumer(
            {
                "bootstrap.servers": bootstrap_servers,
                "group.id": group_id,
                "auto.offset.reset": "earliest" if from_earliest else "latest",
            }
        )
        consumer.subscribe([topic])
        try:
            while True:
                msg = consumer.poll(1.0)
                if msg is None:
                    continue
                err = msg.error()
                if err:
                    # Transient partition events are skippable; fatal broker/
                    # auth errors must surface, not spin forever.
                    if getattr(err, "fatal", lambda: True)():
                        raise RuntimeError(f"Kafka consumer error: {err}")
                    continue
                try:
                    yield parser(msg.value().decode())
                except (ValueError, IndexError):
                    continue
        finally:
            consumer.close()


class KafkaSink:
    """Produce rendered records to a topic (Serialization.java producers)."""

    def __init__(self, topic: str, bootstrap_servers: str,
                 formatter: Callable = str):
        kind, mod = _import_kafka()
        if kind is None:
            raise RuntimeError(_MISSING)
        self.topic = topic
        self.formatter = formatter
        if kind == "kafka":
            self._producer = mod.KafkaProducer(
                bootstrap_servers=bootstrap_servers.split(",")
            )
            self._send = lambda v: self._producer.send(self.topic, v)
        else:
            self._producer = mod.Producer({"bootstrap.servers": bootstrap_servers})
            self._send = lambda v: self._producer.produce(self.topic, v)

    def __call__(self, record):
        self._send(self.formatter(record).encode())

    def flush(self):
        self._producer.flush()
