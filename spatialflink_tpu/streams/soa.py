"""Structure-of-arrays streaming: chunked ingest → vectorized windows.

The object-based WindowAssembler (streams/windows.py) is the semantics
reference; this module is the high-rate path. Sources deliver **chunks**
of SoA arrays (e.g. straight from the native C++ parser), the assembler
buffers them as arrays, and each fired window is a zero-copy-ish slice of
a ts-sorted consolidation — no per-event Python objects anywhere.

Semantics match the object assembler for in-order-within-lateness streams:
bounded-out-of-orderness watermark (wm = max_ts − ooo), a window fires when
the watermark passes its end, and every window containing ≥1 event fires
exactly once. Late events beyond the watermark at consolidation time are
dropped and counted (``dropped_late``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from spatialflink_tpu import overload, slo
from spatialflink_tpu.faults import faults
from spatialflink_tpu.telemetry import telemetry


def earliest_window_of(ts_val: int, size: int, slide: int) -> int:
    """Start of the earliest sliding window containing ``ts_val`` — the one
    firing-semantics formula both SoA assemblers share."""
    last = ts_val - ((ts_val % slide) + slide) % slide
    return last - size + slide


@dataclass
class SoaWindow:
    """One fired window: [start, end) and its event arrays."""

    start: int
    end: int
    arrays: Dict[str, np.ndarray]  # each (n,), same order, incl. "ts"

    @property
    def count(self) -> int:
        return len(self.arrays["ts"])


class _SlidingAssemblerBase:
    """The ONE sliding-window watermark state machine, shared by the point
    and ragged-geometry SoA assemblers. Subclasses supply the payload via
    four hooks: ``_ingest`` (store a chunk, return its ts array),
    ``_consolidate`` (merge+ts-sort the payload, return sorted ts),
    ``_window`` (materialize rows [lo:hi) of the consolidated payload as a
    fired window) and ``_evict`` (drop rows below ``keep_from``).

    Semantics (the object assembler in streams/windows.py is the
    reference): wm = max_ts − ooo; a window fires once when the watermark
    passes its end; every window containing ≥1 event fires exactly once;
    events older than every live window are dropped and counted.
    """

    def __init__(self, size_ms: int, slide_ms: int, ooo_ms: int = 0):
        if size_ms <= 0 or slide_ms <= 0:
            raise ValueError("size and slide must be positive")
        self.size = int(size_ms)
        self.slide = int(slide_ms)
        self.ooo = int(ooo_ms)
        self._max_ts: Optional[int] = None
        self._next_start: Optional[int] = None  # earliest unfired window start
        self.dropped_late = 0

    def feed(self, chunk):
        """Add one chunk; return the windows that fire."""
        if faults.armed:  # chaos injection point (faults.py)
            faults.hit("soa.feed")
        ts = self._ingest(chunk)
        if ts is None or len(ts) == 0:
            return []
        mx = int(ts.max())
        if self._max_ts is None or mx > self._max_ts:
            self._max_ts = mx
        if self._next_start is None:
            # Earliest window that could ever hold a non-late event: bounded
            # by both the first observed timestamp and the initial watermark
            # (later within-bound arrivals may precede the first event).
            horizon = min(int(ts.min()), self._max_ts - self.ooo)
            self._next_start = earliest_window_of(horizon, self.size, self.slide)
        return self._fire(self._max_ts - self.ooo)

    def flush(self):
        """End of stream: fire everything up to the last event."""
        if self._max_ts is None:
            return []
        # record_lag=False: the flush watermark is artificial (max_ts +
        # size + 1), not a late watermark — it must not pollute the gauge.
        return self._fire(self._max_ts + self.size + 1, record_lag=False)

    def stream(self, chunks):
        for c in chunks:
            yield from self.feed(c)
        yield from self.flush()

    def _fire(self, wm: int, record_lag: bool = True):
        out = []
        if self._next_start is None or self._next_start + self.size > wm:
            return out
        ts = self._consolidate()
        # Events older than the earliest live window start are late beyond
        # every remaining window: count and trim.
        late = int(np.searchsorted(ts, self._next_start, side="left"))
        if late:
            self.dropped_late += late
            telemetry.record_late_drop(late)
        while self._next_start + self.size <= wm:
            s, e = self._next_start, self._next_start + self.size
            lo = int(np.searchsorted(ts, s, side="left"))
            hi = int(np.searchsorted(ts, e, side="left"))
            if hi > lo:
                out.append(self._window(s, e, lo, hi))
                if record_lag:
                    # Event-time ms between window end and the watermark
                    # that fired it. The SLO hook rides the same fire
                    # site (free when no engine is installed).
                    telemetry.record_watermark_lag(wm - e)
                    slo.on_window_fired(hi - lo, lag_ms=wm - e)
                    # Overload hook, same fire site (free when no
                    # controller is installed).
                    overload.on_window_fired(hi - lo, lag_ms=wm - e,
                                             end=e)
                self._next_start += self.slide
            elif lo < len(ts):
                # Empty window: fast-forward to the earliest window holding
                # the next buffered event (no O(gap/slide) spinning).
                self._next_start = max(
                    self._next_start + self.slide,
                    earliest_window_of(int(ts[lo]), self.size, self.slide),
                )
            else:
                # No buffered events at/after s: wait for more data.
                self._next_start += self.slide
                break
        # Evict rows no live window can need.
        keep_from = int(np.searchsorted(ts, self._next_start, side="left"))
        if keep_from:
            self._evict(keep_from)
        return out


class SoaWindowAssembler(_SlidingAssemblerBase):
    """Sliding event-time windows over SoA chunks."""

    def __init__(self, size_ms: int, slide_ms: int, ooo_ms: int = 0):
        super().__init__(size_ms, slide_ms, ooo_ms)
        self._chunks: List[Dict[str, np.ndarray]] = []

    def _ingest(self, chunk: Dict[str, np.ndarray]):
        ts = np.asarray(chunk["ts"], np.int64)
        if len(ts) == 0:
            return None
        self._chunks.append({k: np.asarray(v) for k, v in chunk.items()})
        return ts

    def _consolidate(self) -> np.ndarray:
        if len(self._chunks) == 1:
            merged = self._chunks[0]
        else:
            merged = {
                k: np.concatenate([c[k] for c in self._chunks])
                for k in self._chunks[0]
            }
        ts = merged["ts"]
        if np.any(ts[:-1] > ts[1:]):  # in-order streams skip the sort
            order = np.argsort(ts, kind="stable")
            merged = {k: v[order] for k, v in merged.items()}
        self._chunks = [merged]
        return merged["ts"]

    def _window(self, s, e, lo, hi) -> SoaWindow:
        merged = self._chunks[0]
        return SoaWindow(s, e, {k: v[lo:hi] for k, v in merged.items()})

    def _evict(self, keep_from: int) -> None:
        self._chunks = [{k: v[keep_from:] for k, v in self._chunks[0].items()}]


def csv_chunk_source(path: str, parser, chunk_bytes: int = 1 << 22):
    """File → SoA chunks via a buffer-at-a-time parser (native.NativeGpsParser
    or NativePointParser): reads ~chunk_bytes at line boundaries."""
    with open(path, "rb") as f:
        rest = b""
        while True:
            block = f.read(chunk_bytes)
            if not block:
                if rest.strip():
                    yield parser.parse(rest)
                return
            block = rest + block
            cut = block.rfind(b"\n")
            if cut < 0:
                rest = block
                continue
            rest = block[cut + 1:]
            yield parser.parse(block[: cut + 1])


def _ragged_reorder(flat: np.ndarray, lengths: np.ndarray, order: np.ndarray):
    """Reorder a ragged array (``flat`` rows grouped into ``lengths``-sized
    runs) by a per-group ``order`` — fully vectorized."""
    starts = np.concatenate([[0], np.cumsum(lengths)])[:-1]
    new_lens = lengths[order]
    total = int(new_lens.sum())
    pos_base = np.repeat(np.cumsum(new_lens) - new_lens, new_lens)
    src = (
        np.repeat(starts[order], new_lens)
        + np.arange(total, dtype=np.int64)
        - pos_base
    )
    return flat[src], new_lens


@dataclass
class RaggedSoaWindow:
    """One fired geometry window: object rows + their flat boundary chains.

    ``lengths[i]`` vertices of object ``i`` occupy
    ``verts[offsets[i]:offsets[i+1]]`` where ``offsets = cumsum``;
    ``edge_valid`` (optional) is the matching flat (length−1)-run edge
    mask (multi-ring seams invalid).
    """

    start: int
    end: int
    ts: np.ndarray  # (n,)
    oid: np.ndarray  # (n,) dense int32
    lengths: np.ndarray  # (n,)
    verts: np.ndarray  # (sum lengths, 2)
    edge_valid: Optional[np.ndarray] = None  # (sum lengths - n,) bool

    @property
    def count(self) -> int:
        return len(self.ts)


class RaggedSoaWindowAssembler(_SlidingAssemblerBase):
    """Sliding event-time windows over ragged GEOMETRY chunks.

    Chunks are ``{"ts": (n,), "oid": (n,), "lengths": (n,),
    "verts": (sum lengths, 2)}`` — each object's packed single boundary
    chain (closed ring for polygons, open for polylines; multi-ring
    objects need the object path). Watermark/firing semantics come from
    the shared state machine (_SlidingAssemblerBase).
    """

    def __init__(self, size_ms: int, slide_ms: int, ooo_ms: int = 0):
        super().__init__(size_ms, slide_ms, ooo_ms)
        self._rows: List[Dict[str, np.ndarray]] = []
        self._verts: List[np.ndarray] = []
        self._edges: Optional[List[np.ndarray]] = None
        self._edge_mode: Optional[bool] = None  # fixed by the first chunk

    def _ingest(self, chunk: Dict[str, np.ndarray]):
        ts = np.asarray(chunk["ts"], np.int64)
        if len(ts) == 0:
            return None
        lengths = np.asarray(chunk["lengths"], np.int64)
        oid = np.asarray(chunk["oid"], np.int32)
        verts = np.asarray(chunk["verts"], np.float64)
        if not (len(ts) == len(oid) == len(lengths)):
            raise ValueError(
                f"ragged chunk row mismatch: ts={len(ts)} oid={len(oid)} "
                f"lengths={len(lengths)} must be equal"
            )
        if int(lengths.sum()) != len(verts):
            raise ValueError(
                f"ragged chunk mismatch: lengths sum to {int(lengths.sum())}"
                f" but verts has {len(verts)} rows — offsets for every later"
                " object would silently misalign"
            )
        edges = chunk.get("edge_valid")
        if self._edge_mode is None:
            self._edge_mode = edges is not None
        elif self._edge_mode != (edges is not None):
            # Both directions must fail loudly: a mode flip either way
            # would misalign masks against the edge offsets.
            raise ValueError(
                "all chunks of one stream must agree on carrying edge_valid"
            )
        if edges is not None:
            edges = np.asarray(edges, bool)
            if int((lengths - 1).sum()) != len(edges):
                raise ValueError(
                    f"ragged chunk edge-mask mismatch: lengths-1 sums to "
                    f"{int((lengths - 1).sum())} but edge_valid has "
                    f"{len(edges)} entries"
                )
            if self._edges is None:
                self._edges = []
            self._edges.append(edges)
        self._rows.append({"ts": ts, "oid": oid, "lengths": lengths})
        self._verts.append(verts)
        return ts

    def _consolidate(self) -> np.ndarray:
        if len(self._rows) > 1:
            rows = {
                k: np.concatenate([c[k] for c in self._rows])
                for k in ("ts", "oid", "lengths")
            }
            verts = np.concatenate(self._verts)
        else:
            rows = self._rows[0]
            verts = self._verts[0]
        edges = None
        if self._edges is not None:
            edges = (np.concatenate(self._edges) if len(self._edges) > 1
                     else self._edges[0])
        ts = rows["ts"]
        if np.any(ts[:-1] > ts[1:]):  # in-order streams skip the sort
            order = np.argsort(ts, kind="stable")
            verts, _ = _ragged_reorder(verts, rows["lengths"], order)
            if edges is not None:
                edges, _ = _ragged_reorder(edges, rows["lengths"] - 1, order)
            rows = {k: v[order] for k, v in rows.items()}
        self._rows = [rows]
        self._verts = [verts]
        if edges is not None:
            self._edges = [edges]
        self._offsets = np.concatenate([[0], np.cumsum(rows["lengths"])])
        self._e_offsets = np.concatenate(
            [[0], np.cumsum(rows["lengths"] - 1)])
        return rows["ts"]

    def _window(self, s, e, lo, hi) -> RaggedSoaWindow:
        rows = self._rows[0]
        offs = self._offsets
        ev = None
        if self._edges is not None:
            eo = self._e_offsets
            ev = self._edges[0][eo[lo]:eo[hi]]
        return RaggedSoaWindow(
            s, e, rows["ts"][lo:hi], rows["oid"][lo:hi],
            rows["lengths"][lo:hi],
            self._verts[0][offs[lo]:offs[hi]],
            edge_valid=ev,
        )

    def _evict(self, keep_from: int) -> None:
        rows = self._rows[0]
        offs = self._offsets
        if self._edges is not None:
            self._edges = [self._edges[0][self._e_offsets[keep_from]:]]
        self._rows = [{k: v[keep_from:] for k, v in rows.items()}]
        self._verts = [self._verts[0][offs[keep_from]:]]
