"""Compact binary ingest wire format: grid-relative uint16 coordinates.

The reference ships stream records as text — GeoJSON/WKT/CSV produced by
Serialization.java:17-726 and re-parsed by Deserialization.java — at
~100+ bytes/point; its ingest ceiling is the 20k EPS target of
BenchmarkRunner.java:25-26. This framework's ingest ceiling is link
bandwidth into the accelerator, so the hot wire format is binary:
quantized grid-relative ``uint16`` coordinates plus an interned ``int16``
object id — **6 bytes/point** — upcast to f32 on device inside the fused
window program.

Exactness contract (tests/test_wire.py):

- ``scale`` is chosen as ``m × 2^e`` with integer ``m ≤ 255`` (8
  significand bits), the smallest such value ≥ span/65535. A quantized
  coordinate ``q ≤ 65535`` (16 bits) times ``m`` (8 bits) needs ≤ 24
  significand bits, so ``q * scale`` is EXACT in f32 and
  ``origin + q * scale`` rounds exactly once — fused (FMA) and unfused
  evaluation, numpy on host and XLA on any backend, all produce
  bit-identical f32 coordinates. Device upcast therefore adds ZERO error
  on top of quantization.
- Quantization itself is the ingest precision: one lattice step is
  span/65535-ish (Beijing extent: ~3.2e-5° ≈ 3.6 m east-west), beneath
  civilian GPS accuracy. Every consumer of the same 6-byte records —
  this framework on any backend, or a host reference implementation —
  computes on exactly the same f32 coordinates.
"""

from __future__ import annotations

import math

import numpy as np

U16_MAX = 65535


def wire_scale(span: float) -> float:
    """Smallest ``m × 2^e`` ≥ span/65535 with integer ``m`` ≤ 8 bits.

    The 8-bit significand keeps ``uint16 × scale`` exactly representable
    in f32 (16 + 8 ≤ 24 significand bits) — see module docstring.
    """
    if not span > 0:
        raise ValueError(f"span must be positive, got {span}")
    target = span / U16_MAX
    e = math.floor(math.log2(target)) - 7
    m = math.ceil(target / 2.0 ** e)
    if m > 255:  # target/2^e landed exactly on 256
        m, e = 128, e + 1
    assert 128 <= m <= 255
    return m * 2.0 ** e


class WireFormat:
    """Quantizer/dequantizer for one grid extent.

    ``quantize`` runs host-side at the producer (serde/source layer);
    ``dequantize`` is jit-safe and fuses into the consuming kernel;
    ``dequantize_np`` is the host reference the parity tests compare
    against (bit-identical by the exactness contract above).
    """

    def __init__(self, min_x: float, max_x: float, min_y: float, max_y: float):
        self.origin = np.asarray([min_x, min_y], np.float32)
        self.scale = np.asarray(
            [wire_scale(max_x - min_x), wire_scale(max_y - min_y)], np.float32
        )
        # The f32 cast is exact for the scale (m×2^e) by construction; the
        # origin rounds to f32 once, identically for every consumer.

    @classmethod
    def for_grid(cls, grid) -> "WireFormat":
        return cls(grid.min_x, grid.max_x, grid.min_y, grid.max_y)

    def quantize(self, xy) -> np.ndarray:
        """(..., 2) float coords → (..., 2) uint16 (clipped to the bbox)."""
        xy64 = np.asarray(xy, np.float64)
        q = np.floor((xy64 - self.origin.astype(np.float64))
                     / self.scale.astype(np.float64))
        return np.clip(q, 0, U16_MAX).astype(np.uint16)

    def dequantize(self, q):
        """jit-safe device upcast: (..., 2) uint16 → f32 coords."""
        import jax.numpy as jnp

        return (q.astype(jnp.float32) * jnp.asarray(self.scale)
                + jnp.asarray(self.origin))

    def dequantize_np(self, q) -> np.ndarray:
        """Host reference dequant (bit-identical to ``dequantize``)."""
        return (np.asarray(q, np.float32) * self.scale + self.origin)

    @property
    def bytes_per_point(self) -> int:
        """uint16 x + uint16 y + int16 interned oid."""
        return 6


class WirePaneAssembler:
    """Stateful SoA → (3, n) uint16 PLANE-MAJOR pane binner.

    The producer half of the wire-pane operator seam: feeds
    ``PointPointKNNQuery.run_wire_panes`` (and the bench.py headline
    program) from any SoA chunk stream ``{"ts", "x", "y", "oid"}`` —
    e.g. the native CSV parser's arrays or a batched Kafka consumer.
    Pane i covers [start_ms + i·slide_ms, start_ms + (i+1)·slide_ms);
    EVERY pane in order is emitted, including empty (3, 0) panes in
    event-time gaps, so downstream window indexing stays aligned.

    In-order streams only (the pane-path contract): a pane is emitted
    once an event at/after its end arrives, so an event earlier than
    the current pane raises rather than being silently mis-binned.
    ``oid`` must already be interned into int16 range. ``flush()``
    emits the final, possibly partial, pane at end of stream.

    ``state()``/``restore()`` snapshot the OPEN pane's buffered events
    + position (checkpoint.py:wire_pane_assembler_state): together
    with the consumer offsets and the operator digest ring, the whole
    wire pipeline resumes
    (tests/test_kafka_wire.py::test_full_wire_pipeline_kill_and_resume).
    Snapshot ALIGNMENT: every pane ``feed()`` has returned must be
    drained downstream before snapshotting — a completed pane held
    in-flight (e.g. the second of a multi-pane burst across an
    event-time gap) lives in neither this state nor the operator's, so
    a snapshot taken mid-burst loses it. This is the pane-boundary
    barrier any checkpointing runtime imposes.
    """

    def __init__(self, wire_format: WireFormat, slide_ms: int,
                 start_ms: int):
        self._wf = wire_format
        self._slide = int(slide_ms)
        self._cur = int(start_ms)
        self._pend_ts = np.zeros(0, np.int64)
        self._pend_xy = np.zeros((0, 2), np.float64)
        self._pend_oid = np.zeros(0, np.int64)

    def _pack(self, xy, oid):
        q = self._wf.quantize(xy)
        o = np.asarray(oid, np.int16).view(np.uint16)
        return np.ascontiguousarray(
            np.concatenate([q, o[:, None]], axis=1).T
        )

    def feed(self, ch) -> list:
        """One SoA chunk in → the panes it completed (possibly [])."""
        ts = np.asarray(ch["ts"], np.int64)
        if len(ts) == 0:
            return []
        xy = np.stack(
            [np.asarray(ch["x"], np.float64),
             np.asarray(ch["y"], np.float64)], axis=1
        )
        oid = np.asarray(ch["oid"])
        # Full in-order check: against the open pane, against the
        # pending tail, AND within the chunk (searchsorted below is a
        # binary search — unsorted input would silently mis-bin).
        prev_last = (int(self._pend_ts[-1]) if len(self._pend_ts)
                     else self._cur)
        if int(ts[0]) < max(self._cur, prev_last) or (
                len(ts) > 1 and bool(np.any(np.diff(ts) < 0))):
            raise ValueError(
                "out-of-order event stream: wire panes require "
                "non-decreasing timestamps (the pane-path contract); "
                f"open pane starts at {self._cur} ms"
            )
        self._pend_ts = np.concatenate([self._pend_ts, ts])
        self._pend_xy = np.concatenate([self._pend_xy, xy])
        self._pend_oid = np.concatenate([self._pend_oid, oid])
        # Emit every pane strictly BEFORE the newest event's pane (the
        # in-order watermark: a later event closes all earlier panes).
        out = []
        newest = int(self._pend_ts[-1])
        while self._cur + self._slide <= newest:
            hi = int(np.searchsorted(
                self._pend_ts, self._cur + self._slide, "left"
            ))
            out.append(self._pack(self._pend_xy[:hi], self._pend_oid[:hi]))
            self._pend_ts = self._pend_ts[hi:]
            self._pend_xy = self._pend_xy[hi:]
            self._pend_oid = self._pend_oid[hi:]
            self._cur += self._slide
        return out

    def flush(self) -> list:
        """End of stream: the open pane's events as one final pane."""
        if not len(self._pend_ts):
            return []
        out = [self._pack(self._pend_xy, self._pend_oid)]
        self._pend_ts = np.zeros(0, np.int64)
        self._pend_xy = np.zeros((0, 2), np.float64)
        self._pend_oid = np.zeros(0, np.int64)
        self._cur += self._slide
        return out

    def state(self) -> dict:
        return {
            "cur": int(self._cur),
            "slide_ms": int(self._slide),
            # wire-format identity: a checkpoint quantized against one
            # grid extent must not restore into another
            "wire_origin": [float(v) for v in self._wf.origin],
            "wire_scale": [float(v) for v in self._wf.scale],
            "pend_ts": np.asarray(self._pend_ts),
            "pend_xy": np.asarray(self._pend_xy),
            "pend_oid": np.asarray(self._pend_oid),
        }

    def restore(self, state: dict) -> None:
        if int(state.get("slide_ms", self._slide)) != self._slide:
            raise ValueError(
                f"checkpoint slide_ms {state['slide_ms']} != this "
                f"assembler's {self._slide} — pane boundaries would "
                "silently shift"
            )
        want = ([float(v) for v in self._wf.origin],
                [float(v) for v in self._wf.scale])
        got = (state.get("wire_origin", want[0]),
               state.get("wire_scale", want[1]))
        if got != want:
            raise ValueError(
                "checkpoint wire format (origin/scale) does not match "
                "this assembler's grid extent"
            )
        self._cur = int(state["cur"])
        self._pend_ts = np.asarray(state["pend_ts"], np.int64)
        self._pend_xy = np.asarray(state["pend_xy"], np.float64)
        self._pend_oid = np.asarray(state["pend_oid"])


def wire_panes(chunks, wire_format: WireFormat, slide_ms: int,
               start_ms: int):
    """Generator form of ``WirePaneAssembler`` (see its docstring):
    chunks in, every completed pane out, final partial pane flushed at
    end of stream."""
    asm = WirePaneAssembler(wire_format, slide_ms, start_ms)
    for ch in chunks:
        yield from asm.feed(ch)
    yield from asm.flush()
