"""Event-time windowing — the host control plane.

The reference's windowing is Flink's: sliding/tumbling windows with
bounded-out-of-orderness watermarks and allowed lateness (e.g.
PointPointRangeQuery.java:127-133 assigns
``BoundedOutOfOrdernessTimestampExtractor(allowedLateness)`` then windows by
``SlidingProcessingTimeWindows.of(size, slide)``). Here windowing is an
explicit host-side assembler that buffers events per window and fires
batches when the watermark passes the window end — the batch then ships to
one TPU kernel call, replacing the per-record window ``apply`` loop.

Semantics notes (documented deviation, SURVEY.md §7 "hard parts"): the
reference mixes event-time watermark assignment with *processing-time*
window triggers in most window-based paths. This assembler implements true
event-time windows (the principled behavior) and a processing-time mode for
faithful benchmark comparison.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Generic, Iterable, Iterator, List, Optional, Tuple, TypeVar

from spatialflink_tpu import overload, slo
from spatialflink_tpu.faults import faults
from spatialflink_tpu.telemetry import telemetry

T = TypeVar("T")


@dataclass(frozen=True)
class WindowSpec:
    start: int  # ms, inclusive
    end: int  # ms, exclusive


@dataclass
class WindowBatch(Generic[T]):
    """A fired window: its span and the buffered events."""

    start: int
    end: int
    events: List[T]
    # Wall-clock time when the window fired (for latency accounting).
    fire_time: float = field(default_factory=time.time)


class SlidingEventTimeWindows:
    """Flink-compatible sliding window assignment.

    ``size``/``slide`` in ms. Window starts are the multiples of ``slide``
    (offset 0) with start > ts - size, start <= ts — the same assignment as
    Flink's SlidingEventTimeWindows (used via
    SlidingProcessingTimeWindows.of(Time.seconds(w), Time.seconds(s)) in
    e.g. PointPointRangeQuery.java:149).
    """

    def __init__(self, size_ms: int, slide_ms: int):
        if size_ms <= 0 or slide_ms <= 0:
            raise ValueError("size and slide must be positive")
        self.size = int(size_ms)
        self.slide = int(slide_ms)

    def assign(self, ts: int) -> List[WindowSpec]:
        last_start = ts - ((ts % self.slide) + self.slide) % self.slide
        out = []
        start = last_start
        while start > ts - self.size:
            out.append(WindowSpec(start, start + self.size))
            start -= self.slide
        return out


class TumblingEventTimeWindows(SlidingEventTimeWindows):
    """size == slide (StreamingJob wires window.type TIME, interval==step)."""

    def __init__(self, size_ms: int):
        super().__init__(size_ms, size_ms)


class CountWindows:
    """Per-key count windows (size, slide) — the CheckIn app uses
    countWindow(2, 1) and countWindow(1) (apps/CheckIn.java:26-60)."""

    def __init__(self, size: int, slide: Optional[int] = None):
        self.size = int(size)
        self.slide = int(slide) if slide is not None else self.size

    def feed(self, buf: List[T], event: T) -> List[List[T]]:
        """Append to a per-key buffer; return fired windows (lists)."""
        buf.append(event)
        fired = []
        while len(buf) >= self.size:
            fired.append(buf[: self.size])
            del buf[: self.slide]
            if self.slide == 0:
                break
        return fired


class WindowAssembler(Generic[T]):
    """Buffers timestamped events into sliding windows; fires on watermark.

    Watermark = max event time − max_out_of_orderness (Flink's
    BoundedOutOfOrdernessTimestampExtractor). A window fires when the
    watermark passes its end; events arriving after the fire but within
    ``allowed_lateness`` of the watermark re-fire the window with the late
    events included (Flink's allowed-lateness refire). Events later than
    that are dropped and counted.
    """

    def __init__(
        self,
        windows: SlidingEventTimeWindows,
        timestamp_fn: Callable[[T], int],
        max_out_of_orderness_ms: int = 0,
        allowed_lateness_ms: int = 0,
    ):
        self.windows = windows
        self.timestamp_fn = timestamp_fn
        self.ooo = int(max_out_of_orderness_ms)
        self.lateness = int(allowed_lateness_ms)
        self._buffers: Dict[WindowSpec, List[T]] = {}
        self._fired: Dict[WindowSpec, bool] = {}
        self._max_ts: Optional[int] = None
        self.dropped_late = 0

    @property
    def watermark(self) -> int:
        if self._max_ts is None:
            return -(2**62)
        return self._max_ts - self.ooo

    def feed(self, event: T) -> List[WindowBatch[T]]:
        """Add one event; return any windows that fire as a result."""
        if faults.armed:  # chaos injection point (faults.py)
            faults.hit("window.feed")
        ts = int(self.timestamp_fn(event))
        if self._max_ts is None or ts > self._max_ts:
            self._max_ts = ts
        wm = self.watermark

        fired: List[WindowBatch[T]] = []
        landed = False
        for spec in self.windows.assign(ts):
            if spec.end + self.lateness <= wm:
                continue
            landed = True
            buf = self._buffers.setdefault(spec, [])
            buf.append(event)
            if self._fired.get(spec):
                # Late-but-allowed: refire immediately with the late event.
                fired.append(WindowBatch(spec.start, spec.end, list(buf)))
        if not landed:
            # Flink's late-side-output semantics: an event counts as dropped
            # only when every window it belongs to is past the lateness
            # horizon — not once per expired window assignment.
            self.dropped_late += 1
            telemetry.record_late_drop()

        fired.extend(self._advance(wm))
        return fired

    def _advance(self, wm: int) -> List[WindowBatch[T]]:
        fired = []
        for spec in sorted(self._buffers, key=lambda s: s.end):
            if spec.end <= wm and not self._fired.get(spec):
                fired.append(WindowBatch(spec.start, spec.end, list(self._buffers[spec])))
                self._fired[spec] = True
                # Watermark lag: event-time ms between window end and the
                # watermark that fired it (how late the firing was). The
                # SLO hook rides the same fire site (free when no engine
                # is installed).
                telemetry.record_watermark_lag(wm - spec.end)
                slo.on_window_fired(len(self._buffers[spec]),
                                    lag_ms=wm - spec.end)
                # Overload hook, same fire site: drains the admission
                # burst and runs the lag shed-mode machine (free when no
                # controller is installed).
                overload.on_window_fired(len(self._buffers[spec]),
                                         lag_ms=wm - spec.end,
                                         end=spec.end)
        # Garbage-collect windows past the lateness horizon. The fired-flag
        # entry goes too: re-entry of a GC'd window is already blocked by the
        # spec.end + lateness <= wm check in feed(), and keeping the flags
        # would leak one entry per window forever on unbounded streams.
        for spec in [s for s in self._buffers if s.end + self.lateness <= wm]:
            if not self._fired.get(spec):
                fired.append(WindowBatch(spec.start, spec.end, list(self._buffers[spec])))
            del self._buffers[spec]
            self._fired.pop(spec, None)
        return fired

    def flush(self) -> List[WindowBatch[T]]:
        """End of stream: fire every remaining un-fired window."""
        out = []
        for spec in sorted(self._buffers, key=lambda s: s.end):
            if not self._fired.get(spec):
                out.append(WindowBatch(spec.start, spec.end, list(self._buffers[spec])))
                self._fired[spec] = True
        self._buffers.clear()
        return out

    def stream(self, source: Iterable[T]) -> Iterator[WindowBatch[T]]:
        """Convenience: drive a whole source through the assembler."""
        for ev in source:
            yield from self.feed(ev)
        yield from self.flush()
