"""Built-in Kafka wire-protocol client — no external library, no pip.

The reference's default transport is Kafka (FlinkKafkaConsumer/Producer,
StreamingJob.java:188-191,255; producer schemas Serialization.java:17-726).
This module speaks the Kafka binary protocol directly over a TCP socket so
the transport is a REAL capability in any environment with a broker:

- Metadata    (api_key 3, v0) — brokers + partition leaders
- Produce     (api_key 0, v2) — message format v1 (magic 1, CRC32,
                                 create-time timestamps)
- Fetch       (api_key 1, v2) — message format v1, partial trailing
                                 message handling
- ListOffsets (api_key 2, v0) — earliest (-2) / latest (-1)

Version support: these request versions are accepted by brokers 0.10
through 3.x (newer 3.x brokers down-convert the message format). Kafka
4.0 REMOVED pre-2.1 protocol versions and message format v1 (KIP-896 /
KIP-724); against a 4.0+ broker requests fail with UNSUPPORTED_VERSION
(error 35), which this client surfaces as a non-retriable KafkaError
naming the incompatibility — install kafka-python for 4.0+ brokers.
Consumer-group coordination is intentionally out of scope: the reference
relies on Flink's own partition assignment, and here partitions are
likewise assigned explicitly by the caller (streams/kafka.py round-robins
all partitions of the topic).

Frame grammar (big-endian): every request/response is int32-size-prefixed;
requests carry (api_key int16, api_version int16, correlation_id int32,
client_id string); responses echo the correlation id. Strings are
int16-length-prefixed (-1 = null); byte blobs int32-length-prefixed
(-1 = null); arrays int32-count-prefixed. Golden-frame tests:
tests/test_kafka_wire.py.
"""

from __future__ import annotations

import socket
import struct
import time
import zlib
from typing import Dict, List, Optional, Tuple

from spatialflink_tpu.faults import faults

API_PRODUCE = 0
API_FETCH = 1
API_LIST_OFFSETS = 2
API_METADATA = 3

EARLIEST = -2
LATEST = -1


# ---------- encoding ----------

def snappy_decompress(data: bytes) -> bytes:
    """Pure-python snappy decode — raw block format AND the xerial
    ("snappy-java") framing Kafka producers actually emit
    (magic ``\\x82SNAPPY\\x00`` + version/compat ints + length-prefixed
    raw blocks). No external library (environment contract); the decode
    is branch-light enough for the message sizes Kafka fetches carry.

    Raw format (google/snappy format_description.txt): varint
    uncompressed length, then tagged elements — tag & 3: 0 literal
    (length from the upper 6 bits, or 1-4 extra LE bytes when 60-63),
    1 copy with 11-bit offset / 4-11 length, 2 copy with 2-byte LE
    offset, 3 copy with 4-byte LE offset. Copies may overlap forward
    (byte-at-a-time semantics)."""
    if data[:8] == b"\x82SNAPPY\x00":
        out = bytearray()
        pos = 16  # magic + version + min-compat version
        while pos < len(data):
            if pos + 4 > len(data):
                raise ValueError("corrupt xerial snappy frame: truncated "
                                 "block length")
            (blen,) = struct.unpack(">i", data[pos:pos + 4])
            pos += 4
            if blen <= 0 or pos + blen > len(data):
                raise ValueError("corrupt xerial snappy frame: bad block "
                                 f"length {blen}")
            out += snappy_decompress(data[pos:pos + blen])
            pos += blen
        return bytes(out)

    # varint preamble: uncompressed length
    ulen = 0
    shift = 0
    pos = 0
    while True:
        b = data[pos]
        pos += 1
        ulen |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    out = bytearray()
    while pos < len(data):
        tag = data[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:  # literal
            ln = tag >> 2
            if ln >= 60:
                nb = ln - 59
                ln = int.from_bytes(data[pos:pos + nb], "little")
                pos += nb
            ln += 1
            out += data[pos:pos + ln]
            pos += ln
            continue
        if kind == 1:
            ln = ((tag >> 2) & 0x07) + 4
            off = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif kind == 2:
            ln = (tag >> 2) + 1
            off = int.from_bytes(data[pos:pos + 2], "little")
            pos += 2
        else:
            ln = (tag >> 2) + 1
            off = int.from_bytes(data[pos:pos + 4], "little")
            pos += 4
        if off == 0 or off > len(out):
            raise ValueError("corrupt snappy stream: bad copy offset")
        start = len(out) - off
        if off >= ln:
            out += out[start:start + ln]
        else:  # overlapping copy: byte-at-a-time semantics
            for i in range(ln):
                out.append(out[start + i])
    if len(out) != ulen:
        raise ValueError(
            f"corrupt snappy stream: got {len(out)} bytes, header says {ulen}"
        )
    return bytes(out)


def snappy_compress_literal(data: bytes) -> bytes:
    """Minimal VALID snappy encoder: the whole payload as literals (the
    format permits arbitrary element splits; compression optional).
    Test/round-trip helper — real producers send real compressors'
    output, which the decoder above handles."""
    out = bytearray()
    ulen = len(data)
    while True:
        b = ulen & 0x7F
        ulen >>= 7
        out.append(b | (0x80 if ulen else 0))
        if not ulen:
            break
    pos = 0
    while pos < len(data):
        chunk = data[pos:pos + 65536]
        ln = len(chunk) - 1
        if ln < 60:
            out.append(ln << 2)
        else:
            out.append(61 << 2)  # 61 ⇒ 2-byte little-endian length
            out += ln.to_bytes(2, "little")
        out += chunk
        pos += len(chunk)
    return bytes(out)


def _xxh32(data: bytes, seed: int = 0) -> int:
    """Pure-python xxHash32 — the checksum LZ4 frames carry (header HC,
    optional block and content checksums). Reference: the xxHash spec's
    32-bit algorithm; vectors pinned in tests/test_kafka_wire.py."""
    P1, P2, P3, P4, P5 = (
        2654435761, 2246822519, 3266489917, 668265263, 374761393,
    )
    mask = 0xFFFFFFFF

    def rotl(x, r):
        return ((x << r) | (x >> (32 - r))) & mask

    n = len(data)
    pos = 0
    if n >= 16:
        v1 = (seed + P1 + P2) & mask
        v2 = (seed + P2) & mask
        v3 = seed & mask
        v4 = (seed - P1) & mask
        while pos + 16 <= n:
            for i, v in enumerate((v1, v2, v3, v4)):
                lane = int.from_bytes(data[pos + 4 * i:pos + 4 * i + 4],
                                      "little")
                v = (v + lane * P2) & mask
                v = (rotl(v, 13) * P1) & mask
                if i == 0:
                    v1 = v
                elif i == 1:
                    v2 = v
                elif i == 2:
                    v3 = v
                else:
                    v4 = v
            pos += 16
        h = (rotl(v1, 1) + rotl(v2, 7) + rotl(v3, 12) + rotl(v4, 18)) & mask
    else:
        h = (seed + P5) & mask
    h = (h + n) & mask
    while pos + 4 <= n:
        h = (h + int.from_bytes(data[pos:pos + 4], "little") * P3) & mask
        h = (rotl(h, 17) * P4) & mask
        pos += 4
    while pos < n:
        h = (h + data[pos] * P5) & mask
        h = (rotl(h, 11) * P1) & mask
        pos += 1
    h ^= h >> 15
    h = (h * P2) & mask
    h ^= h >> 13
    h = (h * P3) & mask
    h ^= h >> 16
    return h


def lz4_block_decompress(data: bytes, out: bytearray) -> None:
    """LZ4 *block* format decode, appending into ``out`` in place.

    Sequences of [token | literal-length ext | literals | 2-byte LE
    match offset | match-length ext]; the final sequence carries
    literals only. Appending into the caller's rolling buffer lets
    block-DEPENDENT frames (Kafka's legacy Java producer default)
    reference matches across block boundaries."""
    n = len(data)
    pos = 0
    while pos < n:
        token = data[pos]
        pos += 1
        lit = token >> 4
        if lit == 15:
            while True:
                if pos >= n:
                    raise ValueError("corrupt lz4 block: truncated literal "
                                     "length")
                b = data[pos]
                pos += 1
                lit += b
                if b != 255:
                    break
        if pos + lit > n:
            raise ValueError("corrupt lz4 block: literals past end")
        out += data[pos:pos + lit]
        pos += lit
        if pos >= n:
            break  # final sequence: literals only
        if pos + 2 > n:
            raise ValueError("corrupt lz4 block: truncated match offset")
        off = int.from_bytes(data[pos:pos + 2], "little")
        pos += 2
        if off == 0 or off > len(out):
            raise ValueError(f"corrupt lz4 block: bad match offset {off}")
        mlen = token & 0x0F
        if mlen == 15:
            while True:
                if pos >= n:
                    raise ValueError("corrupt lz4 block: truncated match "
                                     "length")
                b = data[pos]
                pos += 1
                mlen += b
                if b != 255:
                    break
        mlen += 4
        start = len(out) - off
        if off >= mlen:
            out += out[start:start + mlen]
        else:  # overlapping copy: byte-at-a-time semantics
            for i in range(mlen):
                out.append(out[start + i])


def lz4_decompress(data: bytes) -> bytes:
    """Pure-python LZ4 *frame* decode — what Kafka codec 3 carries.

    Verifies the frame magic, version, block checksums and content
    checksum (xxHash32) when present. The header checksum accepts BOTH
    the spec value (over the descriptor) and the legacy Kafka value
    (over magic+descriptor): pre-KIP-57 Java producers wrote the broken
    form with message format v0/v1 — exactly the message versions this
    client speaks — and brokers accept both. Loud ValueError on
    anything corrupt."""
    if len(data) < 7:
        raise ValueError("corrupt lz4 frame: too short")
    if data[:4] != b"\x04\x22\x4d\x18":
        raise ValueError("corrupt lz4 frame: bad magic "
                         f"{data[:4].hex()}")
    pos = 4
    flg = data[pos]
    bd = data[pos + 1]
    if (flg >> 6) != 0b01:
        raise ValueError(f"corrupt lz4 frame: unsupported version {flg >> 6}")
    if flg & 0x02:
        raise ValueError("corrupt lz4 frame: FLG reserved bit set")
    # BD: bits 6-4 carry the block-max-size code (4-7); the rest reserved.
    if bd & 0x8F or not 4 <= (bd >> 4) & 0x7 <= 7:
        raise ValueError(f"corrupt lz4 frame: bad BD byte {bd:#04x}")
    has_b_checksum = bool(flg & 0x10)
    has_c_size = bool(flg & 0x08)
    has_c_checksum = bool(flg & 0x04)
    has_dict = bool(flg & 0x01)
    desc_start = pos
    pos += 2
    content_size = None
    if has_c_size:
        content_size = int.from_bytes(data[pos:pos + 8], "little")
        pos += 8
    if has_dict:
        pos += 4
    if pos >= len(data):
        raise ValueError("corrupt lz4 frame: truncated header")
    hc = data[pos]
    spec_hc = (_xxh32(data[desc_start:pos]) >> 8) & 0xFF
    legacy_hc = (_xxh32(data[:pos]) >> 8) & 0xFF  # pre-KIP-57 Kafka
    if hc not in (spec_hc, legacy_hc):
        raise ValueError(
            f"corrupt lz4 frame: header checksum {hc:#04x} matches "
            f"neither spec ({spec_hc:#04x}) nor legacy-Kafka "
            f"({legacy_hc:#04x})"
        )
    pos += 1
    out = bytearray()
    while True:
        if pos + 4 > len(data):
            raise ValueError("corrupt lz4 frame: missing EndMark")
        bsize = int.from_bytes(data[pos:pos + 4], "little")
        pos += 4
        if bsize == 0:  # EndMark
            break
        uncompressed = bool(bsize & 0x80000000)
        bsize &= 0x7FFFFFFF
        if pos + bsize > len(data):
            raise ValueError("corrupt lz4 frame: block past end")
        block = data[pos:pos + bsize]
        pos += bsize
        if has_b_checksum:
            want = int.from_bytes(data[pos:pos + 4], "little")
            pos += 4
            got = _xxh32(block)
            if got != want:
                raise ValueError(
                    f"corrupt lz4 frame: block checksum {got:#010x} != "
                    f"{want:#010x}"
                )
        if uncompressed:
            out += block
        else:
            lz4_block_decompress(block, out)
    if has_c_checksum:
        want = int.from_bytes(data[pos:pos + 4], "little")
        got = _xxh32(bytes(out))
        if got != want:
            raise ValueError(
                f"corrupt lz4 frame: content checksum {got:#010x} != "
                f"{want:#010x}"
            )
    if content_size is not None and len(out) != content_size:
        raise ValueError(
            f"corrupt lz4 frame: got {len(out)} bytes, header says "
            f"{content_size}"
        )
    return bytes(out)


def lz4_compress_literal(data: bytes, legacy_hc: bool = False,
                         block_checksum: bool = False) -> bytes:
    """Minimal VALID LZ4 frame encoder: literal-only compressed blocks,
    content checksum always present. Test/round-trip helper (real
    producers send real compressors' output — the decoder above handles
    matches, overlaps and uncompressed blocks). ``legacy_hc`` writes
    the pre-KIP-57 Kafka header checksum variant."""
    flg = 0x40 | 0x20 | 0x04  # v01, block-independent, content checksum
    if block_checksum:
        flg |= 0x10
    bd = 0x40  # 64 KB max block size
    header = bytes([flg, bd])
    magic = b"\x04\x22\x4d\x18"
    hc_src = magic + header if legacy_hc else header
    out = bytearray(magic + header)
    out.append((_xxh32(hc_src) >> 8) & 0xFF)
    pos = 0
    # Chunk so the STORED block (token + length ext + literals) stays
    # within the 64 KiB maximum the BD byte declares — a spec decoder
    # rejects oversized blocks (65200 literals need ≤ 257 header bytes).
    while pos < len(data):
        chunk = data[pos:pos + 65200]
        pos += len(chunk)
        block = bytearray()
        lit = len(chunk)
        token_lit = min(lit, 15)
        block.append(token_lit << 4)
        if token_lit == 15:
            rest = lit - 15
            while rest >= 255:
                block.append(255)
                rest -= 255
            block.append(rest)
        block += chunk
        out += len(block).to_bytes(4, "little")
        out += block
        if block_checksum:
            out += _xxh32(bytes(block)).to_bytes(4, "little")
    out += (0).to_bytes(4, "little")  # EndMark
    out += _xxh32(data).to_bytes(4, "little")
    return bytes(out)


def enc_string(s: Optional[str]) -> bytes:
    if s is None:
        return struct.pack(">h", -1)
    b = s.encode()
    return struct.pack(">h", len(b)) + b


def enc_bytes(b: Optional[bytes]) -> bytes:
    if b is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(b)) + b


def enc_array(items: List[bytes]) -> bytes:
    return struct.pack(">i", len(items)) + b"".join(items)


def encode_message_v1(value: Optional[bytes], key: Optional[bytes],
                      timestamp_ms: int) -> bytes:
    """One message (format v1): crc | magic=1 | attrs=0 | timestamp |
    key | value; crc covers everything after itself."""
    body = (
        struct.pack(">bbq", 1, 0, timestamp_ms)
        + enc_bytes(key)
        + enc_bytes(value)
    )
    return struct.pack(">I", zlib.crc32(body) & 0xFFFFFFFF) + body


def encode_message_set(messages: List[Tuple[Optional[bytes], Optional[bytes],
                                            int]]) -> bytes:
    """[(value, key, timestamp_ms)] → wire message set (offsets are
    producer-side placeholders; the broker assigns real ones)."""
    out = []
    for i, (value, key, ts) in enumerate(messages):
        msg = encode_message_v1(value, key, ts)
        out.append(struct.pack(">qi", i, len(msg)) + msg)
    return b"".join(out)


def encode_request(api_key: int, api_version: int, correlation_id: int,
                   client_id: str, body: bytes) -> bytes:
    payload = (
        struct.pack(">hhi", api_key, api_version, correlation_id)
        + enc_string(client_id)
        + body
    )
    return struct.pack(">i", len(payload)) + payload


def encode_metadata_request(topics: List[str]) -> bytes:
    return enc_array([enc_string(t) for t in topics])


def encode_produce_request(topic: str, partition: int, message_set: bytes,
                           acks: int = 1, timeout_ms: int = 10_000) -> bytes:
    part = (
        struct.pack(">i", partition)
        + struct.pack(">i", len(message_set))
        + message_set
    )
    topic_data = enc_string(topic) + enc_array([part])
    return struct.pack(">hi", acks, timeout_ms) + enc_array([topic_data])


def encode_fetch_request(topic: str, partition: int, offset: int,
                         max_bytes: int = 1 << 20, max_wait_ms: int = 500,
                         min_bytes: int = 1) -> bytes:
    part = struct.pack(">iqi", partition, offset, max_bytes)
    topic_data = enc_string(topic) + enc_array([part])
    return (
        struct.pack(">iii", -1, max_wait_ms, min_bytes)
        + enc_array([topic_data])
    )


def encode_list_offsets_request(topic: str, partition: int,
                                timestamp: int) -> bytes:
    part = struct.pack(">iqi", partition, timestamp, 1)  # max_offsets=1 (v0)
    topic_data = enc_string(topic) + enc_array([part])
    return struct.pack(">i", -1) + enc_array([topic_data])


# ---------- decoding ----------

class Reader:
    """Cursor over a response payload."""

    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def remaining(self) -> int:
        return len(self.data) - self.pos

    def _take(self, n: int) -> bytes:
        if self.remaining() < n:
            raise EOFError("short read in Kafka response")
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out

    def int8(self) -> int:
        return struct.unpack(">b", self._take(1))[0]

    def int16(self) -> int:
        return struct.unpack(">h", self._take(2))[0]

    def int32(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def int64(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def uint32(self) -> int:
        return struct.unpack(">I", self._take(4))[0]

    def string(self) -> Optional[str]:
        n = self.int16()
        if n == -1:
            return None
        return self._take(n).decode()

    def bytes_(self) -> Optional[bytes]:
        n = self.int32()
        if n == -1:
            return None
        return self._take(n)


def decode_message_set(data: bytes) -> List[Tuple[int, int, Optional[bytes],
                                                  Optional[bytes]]]:
    """Wire message set → [(offset, timestamp_ms, key, value)].

    A fetch response may end with a PARTIAL message (the broker truncates
    at max_bytes) — stop cleanly there. Message format v0 (magic 0, no
    timestamp → -1) and v1 both decode.

    GZIP-compressed sets (attributes codec 1) decode transparently: the
    wrapper's value is itself a message set, recursively decoded. With
    magic v1 wrappers the inner offsets are RELATIVE (KIP-31: wrapper
    offset = absolute offset of the LAST inner message) and a
    LogAppendTime wrapper (attr bit 0x08) overrides every inner
    timestamp — both per the Kafka message-format spec. Snappy sets
    (codec 2, raw or xerial-framed) decode via the pure-python
    ``snappy_decompress``; lz4 sets (codec 3, LZ4 frames incl. the
    pre-KIP-57 legacy header checksum) via ``lz4_decompress``. zstd
    (codec 4, KIP-110) requires message format v2, which this
    pre-2.1-protocol client never negotiates — it still raises (the
    reference gets every codec via the Flink Kafka connector's client,
    pom.xml:81)."""
    out = []
    r = Reader(data)
    while r.remaining() >= 12:
        offset = r.int64()
        size = r.int32()
        if r.remaining() < size:
            break  # partial trailing message
        msg = Reader(r._take(size))
        crc = msg.uint32()
        rest = msg.data[msg.pos:]
        if zlib.crc32(rest) & 0xFFFFFFFF != crc:
            raise ValueError(f"Kafka message CRC mismatch at offset {offset}")
        magic = msg.int8()
        attrs = msg.int8()
        codec = attrs & 0x07
        ts = msg.int64() if magic >= 1 else -1
        key = msg.bytes_()
        value = msg.bytes_()
        if codec == 0:
            out.append((offset, ts, key, value))
            continue
        if value is None:
            raise ValueError(
                f"compressed Kafka wrapper at offset {offset} has a null "
                "value (corrupt message set)"
            )
        if codec not in (1, 2, 3):
            name = {4: "zstd"}.get(codec, str(codec))
            raise NotImplementedError(
                f"{name}-compressed Kafka message sets are not supported "
                "by the built-in client (gzip, snappy and lz4 decode "
                "natively; zstd needs the v2 record-batch protocol — "
                "produce uncompressed or install kafka-python)"
            )
        if codec == 2:
            inner = decode_message_set(snappy_decompress(value))
        elif codec == 3:
            inner = decode_message_set(lz4_decompress(value))
        else:
            # wbits=47: auto-detect gzip or zlib framing.
            inner = decode_message_set(zlib.decompress(value, 47))
        if magic >= 1 and inner:
            base = offset - inner[-1][0]
            inner = [(base + o, t, k, v) for o, t, k, v in inner]
        if magic >= 1 and (attrs & 0x08) and ts >= 0:
            inner = [(o, ts, k, v) for o, _, k, v in inner]
        out.extend(inner)
    return out


class KafkaError(RuntimeError):
    def __init__(self, code: int, where: str):
        detail = ""
        if code == 35:  # UNSUPPORTED_VERSION
            detail = (
                " (the broker rejected this protocol version — Kafka 4.0+"
                " removed the pre-2.1 versions this built-in client"
                " speaks, KIP-896; install kafka-python for 4.0+ brokers)"
            )
        super().__init__(f"Kafka error code {code} in {where}{detail}")
        self.code = code


_RETRIABLE = {3, 5, 6, 7, 14, 15, 16}  # unknown topic/partition (during
# auto-create), leader-not-available, not-leader, request-timeout,
# coordinator codes — metadata refresh + retry territory.


class KafkaWireClient:
    """Minimal leader-routed client over raw sockets (one per broker)."""

    def __init__(self, bootstrap_servers: str,
                 client_id: str = "spatialflink-tpu",
                 timeout_s: float = 15.0):
        self.bootstrap: List[Tuple[str, int]] = []
        for hp in bootstrap_servers.split(","):
            hp = hp.strip()
            if hp.startswith("["):  # bracketed IPv6: [::1]:9092 or [::1]
                host, _, rest = hp[1:].partition("]")
                port = rest.lstrip(":") or "9092"
            elif ":" in hp:
                host, _, port = hp.rpartition(":")
            else:  # bare hostname → Kafka's default port
                host, port = hp, "9092"
            self.bootstrap.append((host or "localhost", int(port)))
        self.client_id = client_id
        self.timeout_s = timeout_s
        self._socks: Dict[Tuple[str, int], socket.socket] = {}
        self._corr = 0
        self._brokers: Dict[int, Tuple[str, int]] = {}
        self._leaders: Dict[Tuple[str, int], int] = {}  # (topic, part) → node

    # -- transport --

    def _sock(self, addr: Tuple[str, int]) -> socket.socket:
        s = self._socks.get(addr)
        if s is None:
            s = socket.create_connection(addr, timeout=self.timeout_s)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._socks[addr] = s
        return s

    def _drop(self, addr: Tuple[str, int]) -> None:
        s = self._socks.pop(addr, None)
        if s is not None:
            try:
                s.close()
            except OSError:
                pass

    def _roundtrip(self, addr: Tuple[str, int], api_key: int,
                   api_version: int, body: bytes) -> Reader:
        self._corr += 1
        corr = self._corr
        frame = encode_request(api_key, api_version, corr, self.client_id,
                               body)
        try:
            s = self._sock(addr)
            s.sendall(frame)
            size = struct.unpack(">i", self._recv_exact(s, 4))[0]
            payload = self._recv_exact(s, size)
        except OSError:
            self._drop(addr)
            raise
        r = Reader(payload)
        got = r.int32()
        if got != corr:
            self._drop(addr)
            raise RuntimeError(
                f"Kafka correlation id mismatch: sent {corr}, got {got}"
            )
        return r

    @staticmethod
    def _recv_exact(s: socket.socket, n: int) -> bytes:
        chunks = []
        while n:
            chunk = s.recv(n)
            if not chunk:
                raise OSError("Kafka broker closed the connection")
            chunks.append(chunk)
            n -= len(chunk)
        return b"".join(chunks)

    def close(self) -> None:
        for addr in list(self._socks):
            self._drop(addr)

    # -- protocol --

    def metadata(self, topics: List[str]) -> Dict[str, List[int]]:
        """Refresh broker + leader tables; returns {topic: [partitions]}."""
        last_err: Optional[Exception] = None
        for addr in self.bootstrap or [("localhost", 9092)]:
            try:
                r = self._roundtrip(
                    addr, API_METADATA, 0, encode_metadata_request(topics)
                )
            except OSError as e:
                last_err = e
                continue
            n_brokers = r.int32()
            for _ in range(n_brokers):
                node = r.int32()
                host = r.string()
                port = r.int32()
                self._brokers[node] = (host or "localhost", port)
            out: Dict[str, List[int]] = {}
            n_topics = r.int32()
            for _ in range(n_topics):
                terr = r.int16()
                name = r.string() or ""
                parts = []
                n_parts = r.int32()
                for _ in range(n_parts):
                    perr = r.int16()
                    pid = r.int32()
                    leader = r.int32()
                    for _ in range(r.int32()):  # replicas
                        r.int32()
                    for _ in range(r.int32()):  # isr
                        r.int32()
                    if perr == 0 and leader >= 0:
                        self._leaders[(name, pid)] = leader
                    parts.append(pid)
                if terr == 0:
                    out[name] = sorted(parts)
            return out
        raise last_err or RuntimeError("no bootstrap broker reachable")

    def _leader_addr(self, topic: str, partition: int) -> Tuple[str, int]:
        key = (topic, partition)
        if key not in self._leaders:
            self.metadata([topic])
        if key not in self._leaders:
            raise KafkaError(3, f"metadata for {topic}/{partition}")
        return self._brokers[self._leaders[key]]

    def _with_leader_retry(self, topic, partition, fn):
        last: Optional[Exception] = None
        for attempt in range(3):
            try:
                if faults.armed:  # chaos injection point (faults.py)
                    faults.hit("kafka.leader")
                return fn(self._leader_addr(topic, partition))
            except KafkaError as e:
                if e.code not in _RETRIABLE:
                    raise
                last = e
            except OSError as e:
                last = e
            self._leaders.pop((topic, partition), None)
            time.sleep(0.2 * (attempt + 1))
        raise last  # type: ignore[misc]

    def produce(self, topic: str, partition: int,
                messages: List[Tuple[Optional[bytes], Optional[bytes], int]],
                acks: int = 1) -> int:
        """[(value, key, timestamp_ms)] → base offset assigned (acks!=0)."""
        mset = encode_message_set(messages)
        body = encode_produce_request(topic, partition, mset, acks=acks)

        def go(addr):
            r = self._roundtrip(addr, API_PRODUCE, 2, body)
            base = -1
            for _ in range(r.int32()):  # topics
                r.string()
                for _ in range(r.int32()):  # partitions
                    r.int32()  # partition id
                    err = r.int16()
                    base = r.int64()
                    r.int64()  # log_append_time
                    if err:
                        raise KafkaError(err, f"produce {topic}/{partition}")
            r.int32()  # throttle_time_ms
            return base

        if acks == 0:
            # Fire-and-forget: no response frame follows.
            addr = self._leader_addr(topic, partition)
            s = self._sock(addr)
            self._corr += 1
            s.sendall(encode_request(API_PRODUCE, 2, self._corr,
                                     self.client_id, body))
            return -1
        return self._with_leader_retry(topic, partition, go)

    def fetch(self, topic: str, partition: int, offset: int,
              max_bytes: int = 1 << 20, max_wait_ms: int = 500,
              ) -> Tuple[List[Tuple[int, int, Optional[bytes],
                                    Optional[bytes]]], int]:
        """→ ([(offset, ts, key, value)], high_watermark)."""
        body = encode_fetch_request(topic, partition, offset,
                                    max_bytes=max_bytes,
                                    max_wait_ms=max_wait_ms)

        def go(addr):
            r = self._roundtrip(addr, API_FETCH, 2, body)
            r.int32()  # throttle_time_ms
            msgs: List = []
            hw = -1
            for _ in range(r.int32()):  # topics
                r.string()
                for _ in range(r.int32()):  # partitions
                    r.int32()  # partition id
                    err = r.int16()
                    hw = r.int64()
                    mset = r.bytes_() or b""
                    if err:
                        raise KafkaError(err, f"fetch {topic}/{partition}")
                    msgs.extend(decode_message_set(mset))
            return msgs, hw

        return self._with_leader_retry(topic, partition, go)

    def list_offset(self, topic: str, partition: int, timestamp: int) -> int:
        """EARLIEST (-2) or LATEST (-1) → offset."""
        body = encode_list_offsets_request(topic, partition, timestamp)

        def go(addr):
            r = self._roundtrip(addr, API_LIST_OFFSETS, 0, body)
            off = -1
            for _ in range(r.int32()):  # topics
                r.string()
                for _ in range(r.int32()):  # partitions
                    r.int32()
                    err = r.int16()
                    n_off = r.int32()
                    offs = [r.int64() for _ in range(n_off)]
                    if err:
                        raise KafkaError(
                            err, f"list_offsets {topic}/{partition}"
                        )
                    if offs:
                        off = offs[0]
            return off

        return self._with_leader_retry(topic, partition, go)
