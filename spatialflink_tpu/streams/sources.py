"""Stream sources.

The host-side ingress layer: in-memory fixtures, the seeded synthetic GPS
rate source (re-design of ``sncb/tests/SyntheticGpsSource.java:8-57``), CSV
replay (``MobilityQueryRunner``-style), and socket text streams
(``MobilityRunner.java:14-73``). All sources are plain Python iterators of
spatial objects / events — the WindowAssembler consumes them.
"""

from __future__ import annotations

import socket
import time
from typing import Callable, Iterable, Iterator, Optional, Sequence, TypeVar

import numpy as np

T = TypeVar("T")


def collection_source(items: Iterable[T]) -> Iterator[T]:
    """In-memory fixture source (env.fromCollection in LocalTestRunner)."""
    yield from items


def csv_source(
    path: str,
    parser: Callable[[str], T],
    skip_header: bool = False,
    limit: Optional[int] = None,
) -> Iterator[T]:
    """Replay a CSV/TSV file through a line parser, skipping bad lines
    (the reference's runners skip unparseable rows)."""
    n = 0
    with open(path, "r") as f:
        for i, line in enumerate(f):
            if skip_header and i == 0:
                continue
            line = line.strip()
            if not line:
                continue
            try:
                yield parser(line)
            except (ValueError, IndexError):
                continue
            n += 1
            if limit is not None and n >= limit:
                return


def socket_source(
    host: str, port: int, parser: Callable[[str], T], encoding: str = "utf-8"
) -> Iterator[T]:
    """Line-based TCP client source (socketTextStream analog,
    MobilityRunner.java:20). Yields parsed records until the peer closes."""
    with socket.create_connection((host, port)) as sock:
        buf = b""
        while True:
            chunk = sock.recv(65536)
            if not chunk:
                break
            buf += chunk
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                text = line.decode(encoding).strip()
                if not text:
                    continue
                try:
                    yield parser(text)
                except (ValueError, IndexError):
                    continue


class SyntheticGpsSource:
    """Deterministic synthetic GPS event source.

    Mirrors the contract of ``sncb/tests/SyntheticGpsSource.java``:
    seeded RNG (42), bbox-uniform positions, ``num_devices`` round-robin
    device ids, a target events-per-second rate and a fixed duration.
    ``realtime=False`` (default) emits as fast as possible with synthetic
    event times advancing at the target rate — the deterministic benchmark
    mode; ``realtime=True`` rate-limits against the wall clock in ≤1000
    event batches like the reference (SyntheticGpsSource.java:22-53).
    """

    def __init__(
        self,
        min_x: float,
        max_x: float,
        min_y: float,
        max_y: float,
        target_eps: int = 20_000,
        duration_ms: int = 30_000,
        num_devices: int = 10,
        seed: int = 42,
        start_ts: int = 0,
        realtime: bool = False,
        make_event: Optional[Callable[..., T]] = None,
    ):
        self.bbox = (min_x, max_x, min_y, max_y)
        self.target_eps = int(target_eps)
        self.duration_ms = int(duration_ms)
        self.num_devices = int(num_devices)
        self.seed = seed
        self.start_ts = int(start_ts)
        self.realtime = realtime
        self.make_event = make_event

    @property
    def total_events(self) -> int:
        return self.target_eps * self.duration_ms // 1000

    def __iter__(self):
        from spatialflink_tpu.models.objects import Point

        rng = np.random.default_rng(self.seed)
        n = self.total_events
        min_x, max_x, min_y, max_y = self.bbox
        xs = rng.uniform(min_x, max_x, n)
        ys = rng.uniform(min_y, max_y, n)
        speeds = rng.uniform(0.0, 120.0, n)
        # Event times advance uniformly at the target rate.
        ts = self.start_ts + (np.arange(n, dtype=np.int64) * 1000) // self.target_eps
        t_wall = time.time()
        for i in range(n):
            if self.realtime and i % 1000 == 0:
                expect = i / self.target_eps
                ahead = expect - (time.time() - t_wall)
                if ahead > 0:
                    time.sleep(ahead)
            dev = f"dev{i % self.num_devices}"
            if self.make_event is not None:
                yield self.make_event(
                    device_id=dev, x=float(xs[i]), y=float(ys[i]),
                    timestamp=int(ts[i]), speed=float(speeds[i]),
                )
            else:
                yield Point(
                    obj_id=dev, timestamp=int(ts[i]), x=float(xs[i]), y=float(ys[i]),
                    ingestion_time=time.time(),
                )
