"""Deserialization / Serialization facades — the reference's static-factory
API surface (``spatialStreams/Deserialization.java`` factories at
:47,:64,:82,:99,:588,:837,:1208 and ``Serialization.java`` output schemas).

Each factory turns an iterable of raw records (JSON/WKT/CSV text lines or
dicts — the Kafka ObjectNode analog) into an iterator of spatial objects of
the requested type, using the configured format. The reference variants:

  - ``point_stream`` / ``trajectory_stream`` (points; trajectory = with
    objID + timestamp extraction from configurable property names);
  - ``polygon_stream`` / ``linestring_stream`` / ``multipoint_stream`` /
    ``geometry_collection_stream``.

Output schemas render objects back to GeoJSON/WKT/CSV strings.
"""

from __future__ import annotations

from typing import Iterable, Iterator, Optional, Sequence, Type

from spatialflink_tpu.models.objects import (
    GeometryCollection,
    LineString,
    MultiPoint,
    Point,
    Polygon,
    SpatialObject,
)
from spatialflink_tpu.streams.serde import (
    parse_csv_point,
    parse_geojson,
    parse_wkt,
    to_csv_point,
    to_geojson,
    to_wkt,
)

_FORMATS = ("GeoJSON", "WKT", "CSV", "TSV")


def _typed_stream(
    records: Iterable,
    input_type: str,
    expected: Optional[Type[SpatialObject]],
    date_format: Optional[str],
    timestamp_property: str,
    objid_property: str,
    delimiter: str,
    csv_schema: Sequence[int],
) -> Iterator[SpatialObject]:
    if input_type not in _FORMATS:
        # Same failure mode as the reference's
        # IllegalArgumentException("inputType … is not support").
        raise ValueError(f"inputType {input_type!r} is not supported")
    for rec in records:
        try:
            if input_type == "GeoJSON":
                obj = parse_geojson(
                    rec, timestamp_property=timestamp_property,
                    objid_property=objid_property, date_format=date_format,
                )
            elif input_type == "WKT":
                obj = parse_wkt(rec if isinstance(rec, str) else str(rec))
            else:  # CSV / TSV → points only (the reference's CSVTSV mappers)
                delim = delimiter if input_type == "CSV" else "\t"
                obj = parse_csv_point(
                    rec, schema=csv_schema, delimiter=delim, date_format=date_format
                )
        except (ValueError, KeyError, IndexError):
            continue
        if expected is None or isinstance(obj, expected):
            yield obj


def point_stream(records, input_type="GeoJSON", date_format=None,
                 delimiter=",", csv_schema=(0, 1, 2, 3)):
    """Deserialization.PointStream (Deserialization.java:47)."""
    return _typed_stream(records, input_type, Point, date_format,
                         "timestamp", "oID", delimiter, csv_schema)


def trajectory_stream(records, input_type="GeoJSON", date_format=None,
                      delimiter=",", csv_schema=(0, 1, 2, 3),
                      timestamp_property="timestamp", objid_property="oID"):
    """Deserialization.TrajectoryStream (Deserialization.java:64) — points
    with objID/timestamp extracted from configurable property names."""
    return _typed_stream(records, input_type, Point, date_format,
                         timestamp_property, objid_property, delimiter, csv_schema)


def polygon_stream(records, input_type="GeoJSON", date_format=None,
                   timestamp_property="timestamp", objid_property="oID"):
    """Deserialization.PolygonStream (Deserialization.java:82)."""
    return _typed_stream(records, input_type, Polygon, date_format,
                         timestamp_property, objid_property, ",", (0, 1, 2, 3))


def linestring_stream(records, input_type="GeoJSON", date_format=None,
                      timestamp_property="timestamp", objid_property="oID"):
    """Deserialization.LineStringStream (Deserialization.java:588)."""
    return _typed_stream(records, input_type, LineString, date_format,
                         timestamp_property, objid_property, ",", (0, 1, 2, 3))


def multipoint_stream(records, input_type="GeoJSON", date_format=None,
                      timestamp_property="timestamp", objid_property="oID"):
    """Deserialization.MultiPointStream (Deserialization.java:1208)."""
    return _typed_stream(records, input_type, MultiPoint, date_format,
                         timestamp_property, objid_property, ",", (0, 1, 2, 3))


def geometry_collection_stream(records, input_type="GeoJSON", date_format=None,
                               timestamp_property="timestamp", objid_property="oID"):
    """Deserialization.GeometryCollectionStream (Deserialization.java:837)."""
    return _typed_stream(records, input_type, GeometryCollection, date_format,
                         timestamp_property, objid_property, ",", (0, 1, 2, 3))


# ---------------------------------------------------------------------------
# Output schemas (Serialization.java:17-726): object → wire format.


def to_output_record(obj: SpatialObject, output_format: str = "GeoJSON",
                     date_format=None, delimiter=",") -> str:
    if output_format == "GeoJSON":
        return to_geojson(obj, date_format=date_format)
    if output_format == "WKT":
        # The reference's WKT output schemas prepend objID + timestamp.
        return f"{obj.obj_id}{delimiter}{obj.timestamp}{delimiter}{to_wkt(obj)}"
    if output_format in ("CSV", "TSV"):
        d = delimiter if output_format == "CSV" else "\t"
        if isinstance(obj, Point):
            return to_csv_point(obj, delimiter=d)
        return f"{obj.obj_id}{d}{obj.timestamp}{d}{to_wkt(obj)}"
    raise ValueError(f"outputFormat {output_format!r} is not supported")
