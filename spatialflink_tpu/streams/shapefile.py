"""ESRI shapefile reader (``spatialStreams/ShapeFileInputFormat.java:1-253``).

Reads the binary .shp format for bounded streams: 100-byte header (file
code 9994 big-endian, version 1000 little-endian, shape type), then records
of (record number BE, content length BE in 16-bit words, shape type LE,
shape data LE). Supported shape types match the reference: 1 = Point,
3 = PolyLine, 5 = Polygon (+ 8 = MultiPoint); null shapes (0) are skipped.
Polygon rings are split into exterior/hole rings by winding order
(shapefile spec: clockwise = exterior).
"""

from __future__ import annotations

import struct
from typing import Iterator, List, Optional

import numpy as np

from spatialflink_tpu.models.objects import (
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
    SpatialObject,
)
from spatialflink_tpu.ops.polygon import signed_area

SHAPE_NULL = 0
SHAPE_POINT = 1
SHAPE_POLYLINE = 3
SHAPE_POLYGON = 5
SHAPE_MULTIPOINT = 8

_FILE_CODE = 9994
_VERSION = 1000


class ShapefileError(ValueError):
    pass


def _read_parts_points(body: bytes, offset: int):
    """Common PolyLine/Polygon layout: bbox(32B) numParts numPoints
    parts[numParts] points[numPoints*16B]."""
    num_parts, num_points = struct.unpack_from("<ii", body, offset + 32)
    parts = list(struct.unpack_from(f"<{num_parts}i", body, offset + 40))
    pts_off = offset + 40 + 4 * num_parts
    pts = np.frombuffer(body, dtype="<f8", count=num_points * 2, offset=pts_off)
    pts = pts.reshape(num_points, 2).astype(np.float64)
    parts.append(num_points)
    return [pts[parts[i] : parts[i + 1]] for i in range(num_parts)]


def read_shapefile(path: str) -> Iterator[SpatialObject]:
    """Yield spatial objects from a .shp file; objID = record number."""
    with open(path, "rb") as f:
        data = f.read()
    if len(data) < 100:
        raise ShapefileError("truncated shapefile header")
    file_code = struct.unpack_from(">i", data, 0)[0]
    if file_code != _FILE_CODE:
        raise ShapefileError(f"bad file code {file_code} (expected {_FILE_CODE})")
    version, shape_type = struct.unpack_from("<ii", data, 28)
    if version != _VERSION:
        raise ShapefileError(f"unsupported shapefile version {version}")

    pos = 100
    while pos + 8 <= len(data):
        rec_no, content_len = struct.unpack_from(">ii", data, pos)
        body_start = pos + 8
        body_len = content_len * 2  # 16-bit words → bytes
        pos = body_start + body_len
        if body_start + 4 > len(data):
            break
        rec_type = struct.unpack_from("<i", data, body_start)[0]
        oid = str(rec_no)
        if rec_type == SHAPE_NULL:
            continue
        if rec_type == SHAPE_POINT:
            x, y = struct.unpack_from("<dd", data, body_start + 4)
            yield Point(obj_id=oid, x=x, y=y)
        elif rec_type == SHAPE_MULTIPOINT:
            num_points = struct.unpack_from("<i", data, body_start + 36)[0]
            pts = np.frombuffer(
                data, dtype="<f8", count=num_points * 2, offset=body_start + 40
            ).reshape(num_points, 2)
            yield MultiPoint(obj_id=oid, coords=pts.astype(np.float64))
        elif rec_type == SHAPE_POLYLINE:
            parts = _read_parts_points(data, body_start + 4)
            if len(parts) == 1:
                yield LineString(obj_id=oid, coords=parts[0])
            else:
                yield MultiLineString(obj_id=oid, parts=parts)
        elif rec_type == SHAPE_POLYGON:
            parts = _read_parts_points(data, body_start + 4)
            # Group rings: clockwise (negative signed area) = exterior
            # starts a new polygon; counter-clockwise rings are holes of
            # the current polygon.
            polys: List[List[np.ndarray]] = []
            for ring in parts:
                if signed_area(ring) <= 0 or not polys:
                    polys.append([ring])
                else:
                    polys[-1].append(ring)
            if len(polys) == 1:
                yield Polygon(obj_id=oid, rings=polys[0])
            else:
                yield MultiPolygon.from_polygons(polys, obj_id=oid)
        else:
            raise ShapefileError(f"unsupported shape type {rec_type}")


def write_shapefile(path: str, objects: List[SpatialObject]) -> None:
    """Minimal .shp writer (testing + egress parity). Points, polylines,
    polygons, multipoints."""
    records = []
    shape_type = None
    for i, obj in enumerate(objects, start=1):
        if isinstance(obj, Point):
            st = SHAPE_POINT
            body = struct.pack("<idd", st, obj.x, obj.y)
        elif isinstance(obj, MultiPoint):
            st = SHAPE_MULTIPOINT
            pts = np.asarray(obj.coords, "<f8")
            bbox = (pts[:, 0].min(), pts[:, 1].min(), pts[:, 0].max(), pts[:, 1].max())
            body = struct.pack("<i4di", st, *bbox, len(pts)) + pts.tobytes()
        elif isinstance(obj, (Polygon, LineString)):
            st = SHAPE_POLYGON if isinstance(obj, Polygon) else SHAPE_POLYLINE
            if isinstance(obj, MultiLineString):
                parts = obj.parts
            elif isinstance(obj, Polygon):
                # Spec winding: exterior rings clockwise, holes
                # counter-clockwise. For a plain Polygon, rings[0] is the
                # exterior; a MultiPolygon's ring list alternates via parts
                # (each member's first ring exterior).
                exterior_idx = set()
                if isinstance(obj, MultiPolygon) and obj.parts:
                    i = 0
                    for n_rings in obj.parts:
                        exterior_idx.add(i)
                        i += n_rings
                else:
                    exterior_idx.add(0)
                parts = []
                for ri, r in enumerate(obj.rings):
                    r = np.asarray(r, float)
                    if not np.array_equal(r[0], r[-1]):
                        r = np.vstack([r, r[:1]])
                    want_cw = ri in exterior_idx
                    is_cw = signed_area(r) < 0
                    parts.append(r if is_cw == want_cw else r[::-1])
            else:
                parts = [obj.coords]
            allp = np.vstack(parts)
            bbox = (allp[:, 0].min(), allp[:, 1].min(), allp[:, 0].max(), allp[:, 1].max())
            offsets = np.cumsum([0] + [len(p) for p in parts[:-1]]).astype("<i4")
            pts = np.vstack(parts).astype("<f8")
            body = (
                struct.pack("<i4dii", st, *bbox, len(parts), len(pts))
                + offsets.tobytes()
                + pts.tobytes()
            )
        else:
            raise ShapefileError(f"cannot write {type(obj).__name__}")
        shape_type = shape_type or st
        content_len = len(body) // 2
        records.append(struct.pack(">ii", i, content_len) + body)

    payload = b"".join(records)
    total_words = (100 + len(payload)) // 2
    header = struct.pack(">i", _FILE_CODE) + b"\x00" * 20 + struct.pack(">i", total_words)
    header += struct.pack("<ii", _VERSION, shape_type or SHAPE_NULL)
    header += struct.pack("<8d", 0, 0, 0, 0, 0, 0, 0, 0)
    with open(path, "wb") as f:
        f.write(header + payload)
