"""Stream sinks: collection, CSV file, print, and latency-measuring sinks.

Counterparts of the reference's result sinks: StringResultCollectorSink
(sncb/tests/MobilityQueryRunner.java), per-query CSV file sinks
(MobilityRunner.java:40-66), and the Kafka latency sinks
(HelperClass.LatencySink*, HelperClass.java:455-529).
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, List, Optional


class CollectSink:
    """Collect results in memory (tests and runners)."""

    def __init__(self):
        self.items: List[Any] = []

    def __call__(self, item: Any):
        self.items.append(item)

    def __len__(self):
        return len(self.items)


class PrintSink:
    def __init__(self, prefix: str = ""):
        self.prefix = prefix
        self.count = 0

    def __call__(self, item: Any):
        print(f"{self.prefix}{item}")
        self.count += 1


class CsvFileSink:
    """Write one formatted line per record, flushing each write (the
    reference's file sinks flush per record for benchmark fidelity,
    com/mn/sinks/CountingLatencyFileSink.java:23-70)."""

    def __init__(
        self,
        path: str,
        formatter: Callable[[Any], str] = str,
        header: Optional[str] = None,
        flush_every: int = 1,
    ):
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self.path = path
        self.formatter = formatter
        self.flush_every = max(1, flush_every)
        self._f = open(path, "w")
        if header:
            self._f.write(header.rstrip("\n") + "\n")
        self.count = 0

    def __call__(self, item: Any):
        self._f.write(self.formatter(item) + "\n")
        self.count += 1
        if self.count % self.flush_every == 0:
            self._f.flush()

    def close(self):
        self._f.flush()
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class LatencySink:
    """Record per-item latency = now − event/ingestion time.

    ``time_fn(item)`` extracts the reference instant in seconds.
    The reference's Kafka latency sinks compute now − ingestionTime
    (HelperClass.java:455-529)."""

    def __init__(self, time_fn: Callable[[Any], float]):
        self.time_fn = time_fn
        self.latencies_ms: List[float] = []

    def __call__(self, item: Any):
        t = self.time_fn(item)
        if t is not None:
            self.latencies_ms.append((time.time() - t) * 1000.0)

    def percentile(self, q: float) -> float:
        if not self.latencies_ms:
            return 0.0
        import numpy as np

        return float(np.percentile(self.latencies_ms, q))
