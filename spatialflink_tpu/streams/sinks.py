"""Stream sinks: collection, CSV file, print, and latency-measuring sinks.

Counterparts of the reference's result sinks: StringResultCollectorSink
(sncb/tests/MobilityQueryRunner.java), per-query CSV file sinks
(MobilityRunner.java:40-66), and the Kafka latency sinks
(HelperClass.LatencySink*, HelperClass.java:455-529).
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, List, Optional

from spatialflink_tpu.faults import InjectedFault, faults


class CollectSink:
    """Collect results in memory (tests and runners)."""

    def __init__(self):
        self.items: List[Any] = []

    def __call__(self, item: Any):
        self.items.append(item)

    def __len__(self):
        return len(self.items)


class PrintSink:
    def __init__(self, prefix: str = ""):
        self.prefix = prefix
        self.count = 0

    def __call__(self, item: Any):
        print(f"{self.prefix}{item}")
        self.count += 1


class CsvFileSink:
    """Write one formatted line per record, flushing each write (the
    reference's file sinks flush per record for benchmark fidelity,
    com/mn/sinks/CountingLatencyFileSink.java:23-70)."""

    def __init__(
        self,
        path: str,
        formatter: Callable[[Any], str] = str,
        header: Optional[str] = None,
        flush_every: int = 1,
    ):
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self.path = path
        self.formatter = formatter
        self.flush_every = max(1, flush_every)
        self._f = open(path, "w")
        if header:
            self._f.write(header.rstrip("\n") + "\n")
        self.count = 0

    def __call__(self, item: Any):
        self._f.write(self.formatter(item) + "\n")
        self.count += 1
        if self.count % self.flush_every == 0:
            self._f.flush()

    def close(self):
        self._f.flush()
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class TransactionalFileSink:
    """Exactly-once epoch egress — the sink half of the pipeline
    checkpoint (driver.py).

    The reference inherits Flink's two-phase-commit sinks but never
    enables checkpointing (SURVEY §5), so its egress is effectively
    fire-and-forget. Here records **stage in memory** per window epoch
    (``stage``/``__call__``) and become durable only at ``commit()``:
    one append + flush + fsync, after which the committed byte/record
    marker is returned for the driver to embed in the SAME checkpoint as
    the operator/ingest snapshot. The recovery invariant that makes this
    exactly-once rather than at-least-once:

    - a crash BEFORE commit loses only staged records — the resumed run
      replays their windows and regenerates them;
    - a crash DURING/AFTER the append but BEFORE the checkpoint publish
      leaves a tail past the last checkpointed marker — ``restore()``
      truncates it, and the replay regenerates those records too;

    so the concatenated egress of any kill/resume sequence is
    byte-identical to an uninterrupted run: no gap, no duplicate, at the
    sink and not just the source (tests/test_chaos_matrix.py asserts
    this for every registered injection point).

    ``reset()`` starts a fresh file (+ optional header); ``restore()``
    resumes from a checkpointed marker. One of them must run before the
    first commit — the driver picks based on whether a checkpoint was
    loaded; standalone users get an implicit ``reset()``.
    """

    def __init__(self, path: str, formatter: Callable[[Any], str] = str,
                 header: Optional[str] = None):
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self.path = path
        self.formatter = formatter
        self.header = header
        self._pending: List[str] = []
        self.committed_bytes = 0
        self.committed_records = 0
        self.commits = 0
        self._initialized = False

    # -- staging ---------------------------------------------------------------

    def stage(self, record: Any) -> None:
        """Buffer one record for the NEXT commit (nothing touches disk)."""
        self._pending.append(self.formatter(record))

    __call__ = stage  # drop-in for the repo's callable-sink convention

    @property
    def pending(self) -> int:
        return len(self._pending)

    # -- epoch lifecycle -------------------------------------------------------

    def reset(self) -> None:
        """Fresh run: truncate to empty, write the header, fsync."""
        with open(self.path, "wb") as f:
            if self.header:
                f.write(self.header.rstrip("\n").encode() + b"\n")
            f.flush()
            os.fsync(f.fileno())
            self.committed_bytes = f.tell()
        self.committed_records = 0
        self._pending = []
        self._initialized = True

    def restore(self, state: Dict[str, Any]) -> None:
        """Resume from a checkpointed marker: any bytes past it are an
        uncommitted tail from a crashed epoch — truncate them (the replay
        regenerates those records). A file SHORTER than the marker means
        committed egress was lost out-of-band: corrupt, fail loudly."""
        from spatialflink_tpu.checkpoint import CheckpointCorruptError

        committed = int(state["bytes"])
        try:
            size = os.path.getsize(self.path)
        except OSError:
            size = -1
        if size == -1 and committed == 0:
            # Nothing was ever committed and the file is gone — an empty
            # epoch crashed before its first commit. Recreate empty.
            open(self.path, "wb").close()
            size = 0
        if size < committed:
            raise CheckpointCorruptError(
                self.path,
                f"egress file with >= {committed} committed bytes",
                f"{size if size >= 0 else 'no file'} — committed sink "
                "output was deleted or truncated out-of-band",
            )
        if size > committed:
            with open(self.path, "r+b") as f:
                f.truncate(committed)
                f.flush()
                os.fsync(f.fileno())
        self.committed_bytes = committed
        self.committed_records = int(state.get("records", 0))
        self._pending = []
        self._initialized = True

    def commit(self) -> Dict[str, int]:
        """Durably append every staged record; return the new committed
        marker (for the driver's checkpoint). Crash-safe at any instant:
        the marker only advances after the fsync returns, and a torn
        append past an OLD marker is exactly what ``restore()`` repairs.
        """
        if not self._initialized:
            self.reset()
        data = b"".join(line.encode() + b"\n" for line in self._pending)
        with open(self.path, "r+b") as f:
            f.seek(self.committed_bytes)
            if faults.armed:  # chaos injection point (faults.py)
                action = faults.hit("sink.write")
                if action == "partial_write":
                    # Cooperative torn append: half the bytes land (and
                    # are even fsync'd — durably torn), then the crash.
                    f.write(data[: max(len(data) // 2, 1)])
                    f.truncate()
                    f.flush()
                    os.fsync(f.fileno())
                    raise InjectedFault("sink.write", "partial_write")
            f.write(data)
            f.truncate()  # clear any stale tail from a repaired crash
            f.flush()
            os.fsync(f.fileno())
        self.committed_bytes += len(data)
        self.committed_records += len(self._pending)
        self.commits += 1
        self._pending = []
        return self.state()

    def state(self) -> Dict[str, int]:
        """The committed marker embedded in pipeline checkpoints."""
        return {"bytes": self.committed_bytes,
                "records": self.committed_records}

    def close(self) -> None:
        """Commit any staged tail (a convenience for non-checkpointed
        use; checkpointed drivers commit through their own cadence)."""
        if self._pending:
            self.commit()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        # Deliberately NOT committing on an exception path: staged
        # records of a failed epoch must be lost, not published.
        if exc[0] is None:
            self.close()


class MultiSink:
    """N named :class:`TransactionalFileSink`\\ s committed as ONE unit —
    the egress half of the DAG's atomic unit checkpoint
    (spatialflink_tpu/dag.py).

    Each node of a composed dataflow stages into its own sub-sink;
    ``commit()`` durably appends every sub-sink's staged records IN NAME
    ORDER and returns the combined marker map, which the driver embeds
    in the SAME checkpoint as every node's operator state. A crash
    between two sub-commits (the ``dag.commit`` injection point fires
    before EACH sub-append) leaves the earlier sinks with a tail past
    their last checkpointed marker and the later ones without —
    ``restore()`` truncates the former and leaves the latter, and the
    replay regenerates both, so kill-anywhere still yields byte-
    identical egress on EVERY sink. A sink file SHORTER than its marker
    (committed egress lost out-of-band, or a marker from a FUTURE
    checkpoint generation) stays loud: the sub-sink's restore raises
    ``CheckpointCorruptError`` naming the file.
    """

    def __init__(self, sinks: "Dict[str, TransactionalFileSink]"):
        #: name → sub-sink, committed in sorted-name order (the
        #: deterministic order the between-commit cut contract rides).
        self.sinks = dict(sinks)

    def __getitem__(self, name: str) -> TransactionalFileSink:
        return self.sinks[name]

    def stage(self, name: str, record: Any) -> None:
        self.sinks[name].stage(record)

    @property
    def pending(self) -> int:
        return sum(s.pending for s in self.sinks.values())

    def reset(self) -> None:
        for name in sorted(self.sinks):
            self.sinks[name].reset()

    def restore(self, state: Dict[str, Any]) -> None:
        """Resume every sub-sink from the checkpointed marker map. A
        sink the checkpoint has no marker for (a node added since) gets
        a fresh ``reset()`` — its whole history replays."""
        markers = state["sinks"]
        for name in sorted(self.sinks):
            if name in markers:
                self.sinks[name].restore(markers[name])
            else:
                self.sinks[name].reset()

    def commit(self) -> Dict[str, Any]:
        """The unit commit: every sub-sink's staged records append
        durably, in sorted-name order, each behind the ``dag.commit``
        injection point — then the combined marker map returns for the
        driver's checkpoint. Any crash mid-sequence is repaired by
        ``restore()`` exactly like a single sink's torn append."""
        out: Dict[str, Any] = {}
        for name in sorted(self.sinks):
            if faults.armed:  # chaos injection point (faults.py)
                faults.hit("dag.commit")
            out[name] = self.sinks[name].commit()
        return {"sinks": out}

    def state(self) -> Dict[str, Any]:
        return {"sinks": {name: s.state()
                          for name, s in sorted(self.sinks.items())}}

    def close(self) -> None:
        for name in sorted(self.sinks):
            self.sinks[name].close()


class LatencySink:
    """Record per-item latency = now − event/ingestion time.

    ``time_fn(item)`` extracts the reference instant in seconds.
    The reference's Kafka latency sinks compute now − ingestionTime
    (HelperClass.java:455-529)."""

    def __init__(self, time_fn: Callable[[Any], float]):
        self.time_fn = time_fn
        self.latencies_ms: List[float] = []

    def __call__(self, item: Any):
        t = self.time_fn(item)
        if t is not None:
            self.latencies_ms.append((time.time() - t) * 1000.0)

    def percentile(self, q: float) -> float:
        if not self.latencies_ms:
            return 0.0
        import numpy as np

        return float(np.percentile(self.latencies_ms, q))
