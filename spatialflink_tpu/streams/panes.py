"""Pane-decomposed sliding-window aggregation (vectorized).

The reference aggregates incrementally per record into every overlapping
window's accumulator — a 10s/10ms sliding window touches 1000 accumulators
per event (Flink AggregateFunction semantics, e.g. Q2_BrakeMonitor's
``SlidingEventTimeWindows.of(Time.seconds(10), Time.milliseconds(10))``).

Here the classic stream-slicing trick is vectorized end-to-end: events are
binned once into **panes** (one per slide step) with ``np.add.at``-style
scatter reductions, and every window aggregate is a rolling combine over
``size/slide`` consecutive panes — cumulative-sum differences for
sum/count/sumsq (O(events + panes × keys), overlap-independent), and
``sliding_window_view`` reductions for min/max (vectorized, but
O(panes × keys × overlap) arithmetic — still orders of magnitude cheaper
than per-record accumulator updates).

Requires ``size % slide == 0`` (true for every window config in the
reference: 10s/10ms, 10s/200ms, 3s/1s, 20s/2s, 45s/5s).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

import numpy as np
from numpy.lib.stride_tricks import sliding_window_view


@dataclass
class PaneWindows:
    """Aggregates for every fired window.

    ``starts``: (W,) window start timestamps (ms). All per-key matrices are
    (W, K). A window fires iff it contains ≥1 event of any key (Flink
    semantics: windows materialize per element).
    """

    starts: np.ndarray
    count: np.ndarray  # events per (window, key)
    sums: Dict[str, np.ndarray]
    sumsqs: Dict[str, np.ndarray]
    mins: Dict[str, np.ndarray]
    maxs: Dict[str, np.ndarray]

    @property
    def ends(self) -> np.ndarray:
        return self.starts + self._size_ms

    _size_ms: int = 0


def sliding_aggregate(
    ts: np.ndarray,
    key: np.ndarray,
    num_keys: int,
    size_ms: int,
    slide_ms: int,
    sum_fields: Optional[Dict[str, np.ndarray]] = None,
    minmax_fields: Optional[Dict[str, np.ndarray]] = None,
    sumsq: bool = False,
    min_fields: Optional[Dict[str, np.ndarray]] = None,
    max_fields: Optional[Dict[str, np.ndarray]] = None,
) -> PaneWindows:
    """Aggregate a whole (bounded) stream over all sliding windows at once.

    ``ts``: (N,) event times ms; ``key``: (N,) dense int key per event
    (device id etc.); ``sum_fields``: named (N,) float arrays to sum per
    (window, key); ``minmax_fields``: tracked on both sides;
    ``min_fields``/``max_fields``: tracked on one side only (half the
    scatter + rolling work when the other side is unused).
    """
    if size_ms % slide_ms != 0:
        raise ValueError("size must be a multiple of slide for pane slicing")
    ppw = size_ms // slide_ms
    sum_fields = sum_fields or {}
    minmax_fields = minmax_fields or {}
    min_only = dict(min_fields or {})
    max_only = dict(max_fields or {})

    ts = np.asarray(ts, np.int64)
    key = np.asarray(key, np.int64)
    if len(ts) == 0:
        empty = np.zeros((0, num_keys))
        return PaneWindows(
            np.zeros(0, np.int64), empty.astype(np.int64),
            {k: empty.copy() for k in sum_fields},
            {k: empty.copy() for k in sum_fields} if sumsq else {},
            {k: empty.copy() for k in minmax_fields},
            {k: empty.copy() for k in minmax_fields},
            _size_ms=size_ms,
        )

    pane = np.floor_divide(ts, slide_ms)
    p_lo = int(pane.min())
    p_hi = int(pane.max())
    # Windows whose pane range [s, s+ppw) intersects [p_lo, p_hi]:
    # start panes from p_lo - ppw + 1 to p_hi.
    n_panes = p_hi - p_lo + 1
    n_starts = n_panes + ppw - 1
    flat = (pane - p_lo) * num_keys + key

    def scatter_sum(vals, dtype=np.float64):
        out = np.zeros(n_panes * num_keys, dtype)
        np.add.at(out, flat, vals)
        return out.reshape(n_panes, num_keys)

    pane_count = scatter_sum(np.ones(len(ts), np.int64), np.int64)
    pane_sums = {k: scatter_sum(np.asarray(v, float)) for k, v in sum_fields.items()}
    pane_sumsqs = (
        {k: scatter_sum(np.asarray(v, float) ** 2) for k, v in sum_fields.items()}
        if sumsq
        else {}
    )
    pane_mins = {}
    pane_maxs = {}
    for k, v in {**minmax_fields, **min_only}.items():
        v = np.asarray(v, float)
        mn = np.full(n_panes * num_keys, np.inf)
        np.minimum.at(mn, flat, v)
        pane_mins[k] = mn.reshape(n_panes, num_keys)
    for k, v in {**minmax_fields, **max_only}.items():
        v = np.asarray(v, float)
        mx = np.full(n_panes * num_keys, -np.inf)
        np.maximum.at(mx, flat, v)
        pane_maxs[k] = mx.reshape(n_panes, num_keys)

    # Pad ppw-1 panes on each side so every intersecting window start has a
    # full ppw-pane view.
    def pad(a, fill):
        padding = np.full((ppw - 1, num_keys), fill, a.dtype)
        return np.concatenate([padding, a, padding], axis=0)

    def rolling_sum(a):
        # Cumulative-sum difference: O(panes × keys) regardless of ppw.
        p = pad(a, 0)
        c = np.concatenate([np.zeros((1, num_keys), p.dtype), np.cumsum(p, axis=0)])
        return c[ppw:] - c[:-ppw]

    def rolling_min(a):
        return sliding_window_view(pad(a, np.inf), ppw, axis=0).min(axis=-1)

    def rolling_max(a):
        return sliding_window_view(pad(a, -np.inf), ppw, axis=0).max(axis=-1)

    w_count = rolling_sum(pane_count)
    # Keep only windows with ≥1 event (any key).
    alive = w_count.sum(axis=1) > 0
    starts = ((np.arange(n_starts) + p_lo - (ppw - 1)) * slide_ms)[alive]

    return PaneWindows(
        starts=starts.astype(np.int64),
        count=w_count[alive],
        sums={k: rolling_sum(v)[alive] for k, v in pane_sums.items()},
        sumsqs={k: rolling_sum(v)[alive] for k, v in pane_sumsqs.items()},
        mins={k: rolling_min(v)[alive] for k, v in pane_mins.items()},
        maxs={k: rolling_max(v)[alive] for k, v in pane_maxs.items()},
        _size_ms=size_ms,
    )


@dataclass
class TrajPaneWindows:
    """Per-(window, oid) trajectory stats for every fired sliding window.

    ``spatial``/``temporal``: (W, K) sums of consecutive-point distance /
    time within the window; ``count``: (W, K) points per trajectory.
    """

    starts: np.ndarray
    spatial: np.ndarray
    temporal: np.ndarray
    count: np.ndarray
    _size_ms: int = 0

    @property
    def ends(self) -> np.ndarray:
        return self.starts + self._size_ms


def traj_stats_sliding(
    ts: np.ndarray,
    xy: np.ndarray,
    oid: np.ndarray,
    num_oids: int,
    size_ms: int,
    slide_ms: int,
) -> TrajPaneWindows:
    """Pane-decomposed sliding trajectory statistics — tStats through
    extreme-overlap windows (e.g. the reference's 10s/10ms configs) in
    O(events + panes × oids) instead of O(windows × window_size).

    Each consecutive same-trajectory segment is binned once into the pane
    of its LATER point; window sums are cumulative-sum differences over
    ``size/slide`` panes. A segment whose earlier point precedes a window's
    start must not count for that window (window semantics truncate
    trajectories at the start boundary, tStats/TStatsQuery.java:148-189's
    per-window walk), so an interval-add correction subtracts every segment
    from exactly the windows whose start boundary it crosses.

    Exactly equals TStatsQuery.run's per-window recompute (parity test).
    """
    if size_ms % slide_ms != 0:
        raise ValueError("size must be a multiple of slide for pane slicing")
    ppw = size_ms // slide_ms
    ts = np.asarray(ts, np.int64)
    oid = np.asarray(oid, np.int64)
    xy = np.asarray(xy, float)
    if len(ts) == 0:
        empty = np.zeros((0, num_oids))
        return TrajPaneWindows(
            np.zeros(0, np.int64), empty, empty.astype(np.int64),
            empty.astype(np.int64), _size_ms=size_ms,
        )

    ts_sorted = len(ts) <= 1 or bool(np.all(ts[1:] >= ts[:-1]))

    # Native single-pass engine (native/sfnative.cpp:sf_traj_stats):
    # counting sort + segment binning + prefix-sum windows fused per
    # trajectory, cache-resident — bit-identical to the numpy path below
    # (same float association order; parity test tests/test_native.py).
    try:
        from spatialflink_tpu import native as _native

        native_ok = _native.available()
    except Exception:  # pragma: no cover - import/build failure
        native_ok = False
    if native_ok:
        if ts_sorted:
            ts_s, xy_s, oid_s = ts, xy, oid
        else:
            order = np.argsort(ts, kind="stable")
            ts_s, xy_s, oid_s = ts[order], xy[order], oid[order]
        out = _native.traj_stats_native(
            ts_s, xy_s[:, 0], xy_s[:, 1], oid_s, num_oids, size_ms,
            slide_ms,
        )
        if out is not None:
            n_starts, w_d, w_dt, w_cnt = out
            p_lo = int(np.floor_divide(int(ts_s[0]), slide_ms))
            alive = w_cnt.sum(axis=1) > 0
            starts = (
                (np.arange(n_starts) + p_lo - (ppw - 1)) * slide_ms
            )[alive]
            return TrajPaneWindows(
                starts=starts.astype(np.int64),
                spatial=w_d[alive],
                temporal=w_dt[alive],
                count=w_cnt[alive],
                _size_ms=size_ms,
            )

    if ts_sorted:
        # Stream order is usually ts-sorted already: a stable radix sort
        # on oid alone preserves the ts order within each trajectory —
        # ~2× cheaper than the general two-key lexsort.
        order = np.argsort(oid, kind="stable")
    else:
        order = np.lexsort((ts, oid))
    t = ts[order]
    o = oid[order]
    p = xy[order]

    pane = np.floor_divide(t, slide_ms)
    p_lo = int(pane.min())
    p_hi = int(pane.max())
    n_panes = p_hi - p_lo + 1
    n_starts = n_panes + ppw - 1

    # Point counts per (pane, oid) — bincount is the fast scatter-add.
    cnt = np.bincount(
        (pane - p_lo) * num_oids + o, minlength=n_panes * num_oids
    ).astype(np.int64).reshape(n_panes, num_oids)

    # Consecutive same-trajectory segments.
    same = o[1:] == o[:-1]
    seg_d = np.hypot(p[1:, 0] - p[:-1, 0], p[1:, 1] - p[:-1, 1])[same]
    seg_dt = (t[1:] - t[:-1])[same]
    seg_oid = o[1:][same]
    seg_tprev = t[:-1][same]
    seg_pane = pane[1:][same]  # pane of the later point

    seg_flat = (seg_pane - p_lo) * num_oids + seg_oid

    def scatter(vals, dtype=float):
        if dtype is float:
            out = np.bincount(
                seg_flat, weights=vals, minlength=n_panes * num_oids
            )
        else:
            # Integer sums stay on add.at: bincount routes weights through
            # float64, which would round above 2^53 where int64 is exact.
            out = np.zeros(n_panes * num_oids, dtype)
            np.add.at(out, seg_flat, vals)
        return out.reshape(n_panes, num_oids)

    pane_d = scatter(seg_d)
    pane_dt = scatter(seg_dt, np.int64)

    # Window sums via ONE unpadded cumsum + clipped row gathers (the
    # padded-cumsum form allocates 2·(ppw−1) extra rows — ~1000 each for
    # the 10s/10ms configs).
    b = np.arange(n_starts) - (ppw - 1)  # window start pane indices
    row_hi = np.clip(b + ppw, 0, n_panes)
    row_lo = np.clip(b, 0, n_panes)

    def rolling_sum(a):
        c = np.concatenate(
            [np.zeros((1, num_oids), a.dtype), np.cumsum(a, axis=0)]
        )
        return c[row_hi] - c[row_lo]

    w_d = rolling_sum(pane_d)
    w_dt = rolling_sum(pane_dt)
    w_cnt = rolling_sum(cnt)

    # Start-boundary corrections: a segment is over-counted by every window
    # whose start lies in (t_prev, t_later] AND that still contains the
    # later point (start pane > seg_pane - ppw). Interval-add via
    # difference arrays + cumsum.
    first_b = np.maximum(seg_tprev // slide_ms + 1, seg_pane - ppw + 1)
    last_b = seg_pane
    has = first_b <= last_b
    if has.any():
        base = p_lo - (ppw - 1)  # window-start pane of start-index 0
        si0 = (first_b[has] - base).astype(np.int64)
        si1 = (last_b[has] - base).astype(np.int64) + 1

        idx = np.concatenate(
            [si0 * num_oids + seg_oid[has], si1 * num_oids + seg_oid[has]]
        )

        def interval_sub(w_mat, vals, dtype=float):
            if dtype is float:
                diff = np.bincount(
                    idx, weights=np.concatenate([vals, -vals]),
                    minlength=(n_starts + 1) * num_oids,
                )
            else:  # int64 exactness: see scatter()
                diff = np.zeros(((n_starts + 1) * num_oids,), dtype)
                np.add.at(diff, idx, np.concatenate([vals, -vals]))
            corr = np.cumsum(diff.reshape(n_starts + 1, num_oids), axis=0)
            return w_mat - corr[:n_starts]

        w_d = interval_sub(w_d, seg_d[has])
        w_dt = interval_sub(w_dt, seg_dt[has], np.int64)

    alive = w_cnt.sum(axis=1) > 0
    starts = ((np.arange(n_starts) + p_lo - (ppw - 1)) * slide_ms)[alive]
    return TrajPaneWindows(
        starts=starts.astype(np.int64),
        spatial=w_d[alive],
        temporal=w_dt[alive],
        count=w_cnt[alive],
        _size_ms=size_ms,
    )
