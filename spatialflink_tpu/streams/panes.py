"""Pane-decomposed sliding-window aggregation (vectorized).

The reference aggregates incrementally per record into every overlapping
window's accumulator — a 10s/10ms sliding window touches 1000 accumulators
per event (Flink AggregateFunction semantics, e.g. Q2_BrakeMonitor's
``SlidingEventTimeWindows.of(Time.seconds(10), Time.milliseconds(10))``).

Here the classic stream-slicing trick is vectorized end-to-end: events are
binned once into **panes** (one per slide step) with ``np.add.at``-style
scatter reductions, and every window aggregate is a rolling combine over
``size/slide`` consecutive panes — cumulative-sum differences for
sum/count/sumsq (O(events + panes × keys), overlap-independent), and
``sliding_window_view`` reductions for min/max (vectorized, but
O(panes × keys × overlap) arithmetic — still orders of magnitude cheaper
than per-record accumulator updates).

Requires ``size % slide == 0`` (true for every window config in the
reference: 10s/10ms, 10s/200ms, 3s/1s, 20s/2s, 45s/5s).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

import numpy as np
from numpy.lib.stride_tricks import sliding_window_view


@dataclass
class PaneWindows:
    """Aggregates for every fired window.

    ``starts``: (W,) window start timestamps (ms). All per-key matrices are
    (W, K). A window fires iff it contains ≥1 event of any key (Flink
    semantics: windows materialize per element).
    """

    starts: np.ndarray
    count: np.ndarray  # events per (window, key)
    sums: Dict[str, np.ndarray]
    sumsqs: Dict[str, np.ndarray]
    mins: Dict[str, np.ndarray]
    maxs: Dict[str, np.ndarray]

    @property
    def ends(self) -> np.ndarray:
        return self.starts + self._size_ms

    _size_ms: int = 0


def sliding_aggregate(
    ts: np.ndarray,
    key: np.ndarray,
    num_keys: int,
    size_ms: int,
    slide_ms: int,
    sum_fields: Optional[Dict[str, np.ndarray]] = None,
    minmax_fields: Optional[Dict[str, np.ndarray]] = None,
    sumsq: bool = False,
    min_fields: Optional[Dict[str, np.ndarray]] = None,
    max_fields: Optional[Dict[str, np.ndarray]] = None,
) -> PaneWindows:
    """Aggregate a whole (bounded) stream over all sliding windows at once.

    ``ts``: (N,) event times ms; ``key``: (N,) dense int key per event
    (device id etc.); ``sum_fields``: named (N,) float arrays to sum per
    (window, key); ``minmax_fields``: tracked on both sides;
    ``min_fields``/``max_fields``: tracked on one side only (half the
    scatter + rolling work when the other side is unused).
    """
    if size_ms % slide_ms != 0:
        raise ValueError("size must be a multiple of slide for pane slicing")
    ppw = size_ms // slide_ms
    sum_fields = sum_fields or {}
    minmax_fields = minmax_fields or {}
    min_only = dict(min_fields or {})
    max_only = dict(max_fields or {})

    ts = np.asarray(ts, np.int64)
    key = np.asarray(key, np.int64)
    if len(ts) == 0:
        empty = np.zeros((0, num_keys))
        return PaneWindows(
            np.zeros(0, np.int64), empty.astype(np.int64),
            {k: empty.copy() for k in sum_fields},
            {k: empty.copy() for k in sum_fields} if sumsq else {},
            {k: empty.copy() for k in minmax_fields},
            {k: empty.copy() for k in minmax_fields},
            _size_ms=size_ms,
        )

    pane = np.floor_divide(ts, slide_ms)
    p_lo = int(pane.min())
    p_hi = int(pane.max())
    # Windows whose pane range [s, s+ppw) intersects [p_lo, p_hi]:
    # start panes from p_lo - ppw + 1 to p_hi.
    n_panes = p_hi - p_lo + 1
    n_starts = n_panes + ppw - 1
    flat = (pane - p_lo) * num_keys + key

    def scatter_sum(vals, dtype=np.float64):
        out = np.zeros(n_panes * num_keys, dtype)
        np.add.at(out, flat, vals)
        return out.reshape(n_panes, num_keys)

    pane_count = scatter_sum(np.ones(len(ts), np.int64), np.int64)
    pane_sums = {k: scatter_sum(np.asarray(v, float)) for k, v in sum_fields.items()}
    pane_sumsqs = (
        {k: scatter_sum(np.asarray(v, float) ** 2) for k, v in sum_fields.items()}
        if sumsq
        else {}
    )
    pane_mins = {}
    pane_maxs = {}
    for k, v in {**minmax_fields, **min_only}.items():
        v = np.asarray(v, float)
        mn = np.full(n_panes * num_keys, np.inf)
        np.minimum.at(mn, flat, v)
        pane_mins[k] = mn.reshape(n_panes, num_keys)
    for k, v in {**minmax_fields, **max_only}.items():
        v = np.asarray(v, float)
        mx = np.full(n_panes * num_keys, -np.inf)
        np.maximum.at(mx, flat, v)
        pane_maxs[k] = mx.reshape(n_panes, num_keys)

    # Pad ppw-1 panes on each side so every intersecting window start has a
    # full ppw-pane view.
    def pad(a, fill):
        padding = np.full((ppw - 1, num_keys), fill, a.dtype)
        return np.concatenate([padding, a, padding], axis=0)

    def rolling_sum(a):
        # Cumulative-sum difference: O(panes × keys) regardless of ppw.
        p = pad(a, 0)
        c = np.concatenate([np.zeros((1, num_keys), p.dtype), np.cumsum(p, axis=0)])
        return c[ppw:] - c[:-ppw]

    def rolling_min(a):
        return sliding_window_view(pad(a, np.inf), ppw, axis=0).min(axis=-1)

    def rolling_max(a):
        return sliding_window_view(pad(a, -np.inf), ppw, axis=0).max(axis=-1)

    w_count = rolling_sum(pane_count)
    # Keep only windows with ≥1 event (any key).
    alive = w_count.sum(axis=1) > 0
    starts = ((np.arange(n_starts) + p_lo - (ppw - 1)) * slide_ms)[alive]

    return PaneWindows(
        starts=starts.astype(np.int64),
        count=w_count[alive],
        sums={k: rolling_sum(v)[alive] for k, v in pane_sums.items()},
        sumsqs={k: rolling_sum(v)[alive] for k, v in pane_sumsqs.items()},
        mins={k: rolling_min(v)[alive] for k, v in pane_mins.items()},
        maxs={k: rolling_max(v)[alive] for k, v in pane_maxs.items()},
        _size_ms=size_ms,
    )


@dataclass
class TrajPaneWindows:
    """Per-(window, oid) trajectory stats for every fired sliding window.

    ``spatial``/``temporal``: (W, K) sums of consecutive-point distance /
    time within the window; ``count``: (W, K) points per trajectory.
    """

    starts: np.ndarray
    spatial: np.ndarray
    temporal: np.ndarray
    count: np.ndarray
    _size_ms: int = 0

    @property
    def ends(self) -> np.ndarray:
        return self.starts + self._size_ms


def _device_backend_preferred() -> bool:
    """True when the default JAX backend is an accelerator — there the
    pane engine runs as one jitted program (ops/trajectory.py:
    traj_stats_pane_kernel); on CPU the native C++ single-pass engine
    wins (same gate shape as ops/join.pallas_join_supported)."""
    try:
        import jax

        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:  # pragma: no cover
        return False


def _traj_stats_sliding_device(ts, xy, oid, num_oids, size_ms, slide_ms,
                               mesh=None):
    """Device pane engine wrapper: host (oid, ts) sort + pad, ONE jitted
    dispatch, host alive-filter. Bit-parity with the numpy path in f64
    (tests); f32 on non-x64 devices (segment sums associate in the same
    pane order, spatial tolerance ~1e-6 relative). ``mesh``: shard
    trajectories (contiguous oid blocks) over the mesh's ``data`` axis
    (parallel/sharded.py:sharded_traj_stats_pane — bit-identical to
    single-device; ``num_oids`` must divide by the axis)."""
    import jax
    import jax.numpy as jnp

    from spatialflink_tpu.operators.base import jitted
    from spatialflink_tpu.ops.trajectory import traj_stats_pane_kernel
    from spatialflink_tpu.utils.padding import next_bucket

    ppw = size_ms // slide_ms
    ts = np.asarray(ts, np.int64)
    oid = np.asarray(oid, np.int64)
    xy = np.asarray(xy, np.float64)
    ts_sorted = len(ts) <= 1 or bool(np.all(ts[1:] >= ts[:-1]))
    if ts_sorted:
        order = np.argsort(oid, kind="stable")
    else:
        order = np.lexsort((ts, oid))
    t, o, p = ts[order], oid[order], xy[order]

    pane = np.floor_divide(t, slide_ms)
    p_lo = int(pane.min())
    n_panes = next_bucket(int(pane.max()) - p_lo + 1, minimum=8)
    # Rebase time HOST-side so epoch-ms values survive the int32 world
    # of a non-x64 device (raw ~1.7e12 ms would silently wrap; pane
    # arithmetic is shift-invariant). int32 covers ~24 days of stream
    # span — fail loudly beyond, don't wrap.
    t_rel = t - p_lo * slide_ms
    if len(t_rel) and int(t_rel.max()) >= np.iinfo(np.int32).max - slide_ms:
        raise ValueError(
            "stream span exceeds the device pane engine's int32 ms range "
            "(~24 days); use backend='native' or chunk the stream"
        )
    n = len(t)
    nb = next_bucket(n, minimum=8)
    pad = nb - n
    f_dtype = np.float64 if jax.config.jax_enable_x64 else np.float32
    tp = np.concatenate([t_rel, np.full(pad, t_rel[-1], np.int64)]
                        ).astype(np.int32)
    op_ = np.concatenate([o, np.full(pad, num_oids - 1, np.int64)]
                         ).astype(np.int32)
    xp = np.concatenate([p[:, 0], np.zeros(pad)]).astype(f_dtype)
    yp = np.concatenate([p[:, 1], np.zeros(pad)]).astype(f_dtype)
    vp = np.concatenate([np.ones(n, bool), np.zeros(pad, bool)])

    if mesh is not None:
        from spatialflink_tpu.parallel.sharded import sharded_traj_stats_pane

        res = sharded_traj_stats_pane(
            mesh, tp, xp, yp, op_, vp,
            num_oids=num_oids, slide_ms=slide_ms, ppw=ppw, n_panes=n_panes,
        )
    else:
        kernel = jitted(
            traj_stats_pane_kernel, "num_oids", "slide_ms", "ppw", "n_panes",
        )
        res = kernel(
            jnp.asarray(tp), jnp.asarray(xp), jnp.asarray(yp),
            jnp.asarray(op_), jnp.asarray(vp),
            num_oids=num_oids, slide_ms=slide_ms, ppw=ppw, n_panes=n_panes,
        )
    w_d = np.asarray(res.spatial).T
    w_dt = np.asarray(res.temporal).T.astype(np.int64)  # int32-exact sums
    w_cnt = np.asarray(res.count).T
    n_starts = n_panes + ppw - 1
    alive = w_cnt.sum(axis=1) > 0
    starts = ((np.arange(n_starts) + p_lo - (ppw - 1)) * slide_ms)[alive]
    return TrajPaneWindows(
        starts=starts.astype(np.int64),
        spatial=w_d[alive],
        temporal=w_dt[alive],
        count=w_cnt[alive].astype(np.int64),
        _size_ms=size_ms,
    )


def traj_stats_sliding(
    ts: np.ndarray,
    xy: np.ndarray,
    oid: np.ndarray,
    num_oids: int,
    size_ms: int,
    slide_ms: int,
    backend: str = "auto",
    mesh=None,
) -> TrajPaneWindows:
    """Pane-decomposed sliding trajectory statistics — tStats through
    extreme-overlap windows (e.g. the reference's 10s/10ms configs) in
    O(events + panes × oids) instead of O(windows × window_size).

    Each consecutive same-trajectory segment is binned once into the pane
    of its LATER point; window sums are cumulative-sum differences over
    ``size/slide`` panes. A segment whose earlier point precedes a window's
    start must not count for that window (window semantics truncate
    trajectories at the start boundary, tStats/TStatsQuery.java:148-189's
    per-window walk), so an interval-add correction subtracts every segment
    from exactly the windows whose start boundary it crosses.

    Exactly equals TStatsQuery.run's per-window recompute (parity test).

    ``backend``: "auto" picks the DEVICE pane engine when the default
    JAX backend is a TPU (one jitted sorted-segment-sum program,
    ops/trajectory.py:traj_stats_pane_kernel) and the native C++ engine
    on CPU hosts; "device" / "native" / "numpy" force a path (the
    parity-oracle contract: all three agree bit-identically in f64).
    """
    if size_ms % slide_ms != 0:
        raise ValueError("size must be a multiple of slide for pane slicing")
    ppw = size_ms // slide_ms
    ts = np.asarray(ts, np.int64)
    oid = np.asarray(oid, np.int64)
    xy = np.asarray(xy, float)
    if len(ts) == 0:
        empty = np.zeros((0, num_oids))
        return TrajPaneWindows(
            np.zeros(0, np.int64), empty, empty.astype(np.int64),
            empty.astype(np.int64), _size_ms=size_ms,
        )

    if backend not in ("auto", "device", "numpy", "native"):
        raise ValueError(f"unknown traj_stats backend {backend!r}")
    if mesh is not None and backend in ("numpy", "native"):
        raise ValueError(
            f"mesh execution requires the device backend, not {backend!r}"
        )
    # Active overload degradation rung (overload.py): bias "auto" away
    # from the device path — the native/numpy engines below answer
    # bit-identically (parity-oracle contract), freeing the loaded
    # device/tunnel. Forced backends are never overridden.
    from spatialflink_tpu import overload

    prefer_host = (backend == "auto" and mesh is None
                   and overload.pane_backend() in ("native", "numpy"))
    if mesh is not None or backend == "device" or (
            backend == "auto" and not prefer_host
            and _device_backend_preferred()):
        return _traj_stats_sliding_device(
            ts, xy, oid, num_oids, size_ms, slide_ms, mesh=mesh
        )

    ts_sorted = len(ts) <= 1 or bool(np.all(ts[1:] >= ts[:-1]))

    # Native single-pass engine (native/sfnative.cpp:sf_traj_stats):
    # counting sort + segment binning + prefix-sum windows fused per
    # trajectory, cache-resident — bit-identical to the numpy path below
    # (same float association order; parity test tests/test_native.py).
    try:
        from spatialflink_tpu import native as _native

        native_ok = _native.available() and backend != "numpy"
    except Exception:  # pragma: no cover - import/build failure
        native_ok = False
    if backend == "native" and not native_ok:
        raise RuntimeError(
            "backend='native' was forced but the native library is "
            "unavailable (build native/ with make) — refusing to "
            "silently measure the numpy path instead"
        )
    if native_ok:
        if ts_sorted:
            ts_s, xy_s, oid_s = ts, xy, oid
        else:
            order = np.argsort(ts, kind="stable")
            ts_s, xy_s, oid_s = ts[order], xy[order], oid[order]
        out = _native.traj_stats_native(
            ts_s, xy_s[:, 0], xy_s[:, 1], oid_s, num_oids, size_ms,
            slide_ms,
        )
        if out is not None:
            n_starts, w_d, w_dt, w_cnt = out
            p_lo = int(np.floor_divide(int(ts_s[0]), slide_ms))
            alive = w_cnt.sum(axis=1) > 0
            starts = (
                (np.arange(n_starts) + p_lo - (ppw - 1)) * slide_ms
            )[alive]
            return TrajPaneWindows(
                starts=starts.astype(np.int64),
                spatial=w_d[alive],
                temporal=w_dt[alive],
                count=w_cnt[alive],
                _size_ms=size_ms,
            )

    if ts_sorted:
        # Stream order is usually ts-sorted already: a stable radix sort
        # on oid alone preserves the ts order within each trajectory —
        # ~2× cheaper than the general two-key lexsort.
        order = np.argsort(oid, kind="stable")
    else:
        order = np.lexsort((ts, oid))
    t = ts[order]
    o = oid[order]
    p = xy[order]

    pane = np.floor_divide(t, slide_ms)
    p_lo = int(pane.min())
    p_hi = int(pane.max())
    n_panes = p_hi - p_lo + 1
    n_starts = n_panes + ppw - 1

    # Point counts per (pane, oid) — bincount is the fast scatter-add.
    cnt = np.bincount(
        (pane - p_lo) * num_oids + o, minlength=n_panes * num_oids
    ).astype(np.int64).reshape(n_panes, num_oids)

    # Consecutive same-trajectory segments.
    same = o[1:] == o[:-1]
    seg_d = np.hypot(p[1:, 0] - p[:-1, 0], p[1:, 1] - p[:-1, 1])[same]
    seg_dt = (t[1:] - t[:-1])[same]
    seg_oid = o[1:][same]
    seg_tprev = t[:-1][same]
    seg_pane = pane[1:][same]  # pane of the later point

    seg_flat = (seg_pane - p_lo) * num_oids + seg_oid

    def scatter(vals, dtype=float):
        if dtype is float:
            out = np.bincount(
                seg_flat, weights=vals, minlength=n_panes * num_oids
            )
        else:
            # Integer sums stay on add.at: bincount routes weights through
            # float64, which would round above 2^53 where int64 is exact.
            out = np.zeros(n_panes * num_oids, dtype)
            np.add.at(out, seg_flat, vals)
        return out.reshape(n_panes, num_oids)

    pane_d = scatter(seg_d)
    pane_dt = scatter(seg_dt, np.int64)

    # Window sums via ONE unpadded cumsum + clipped row gathers (the
    # padded-cumsum form allocates 2·(ppw−1) extra rows — ~1000 each for
    # the 10s/10ms configs).
    b = np.arange(n_starts) - (ppw - 1)  # window start pane indices
    row_hi = np.clip(b + ppw, 0, n_panes)
    row_lo = np.clip(b, 0, n_panes)

    def rolling_sum(a):
        c = np.concatenate(
            [np.zeros((1, num_oids), a.dtype), np.cumsum(a, axis=0)]
        )
        return c[row_hi] - c[row_lo]

    w_d = rolling_sum(pane_d)
    w_dt = rolling_sum(pane_dt)
    w_cnt = rolling_sum(cnt)

    # Start-boundary corrections: a segment is over-counted by every window
    # whose start lies in (t_prev, t_later] AND that still contains the
    # later point (start pane > seg_pane - ppw). Interval-add via
    # difference arrays + cumsum.
    first_b = np.maximum(seg_tprev // slide_ms + 1, seg_pane - ppw + 1)
    last_b = seg_pane
    has = first_b <= last_b
    if has.any():
        base = p_lo - (ppw - 1)  # window-start pane of start-index 0
        si0 = (first_b[has] - base).astype(np.int64)
        si1 = (last_b[has] - base).astype(np.int64) + 1

        idx = np.concatenate(
            [si0 * num_oids + seg_oid[has], si1 * num_oids + seg_oid[has]]
        )

        def interval_sub(w_mat, vals, dtype=float):
            if dtype is float:
                diff = np.bincount(
                    idx, weights=np.concatenate([vals, -vals]),
                    minlength=(n_starts + 1) * num_oids,
                )
            else:  # int64 exactness: see scatter()
                diff = np.zeros(((n_starts + 1) * num_oids,), dtype)
                np.add.at(diff, idx, np.concatenate([vals, -vals]))
            corr = np.cumsum(diff.reshape(n_starts + 1, num_oids), axis=0)
            return w_mat - corr[:n_starts]

        w_d = interval_sub(w_d, seg_d[has])
        w_dt = interval_sub(w_dt, seg_dt[has], np.int64)

    alive = w_cnt.sum(axis=1) > 0
    starts = ((np.arange(n_starts) + p_lo - (ppw - 1)) * slide_ms)[alive]
    return TrajPaneWindows(
        starts=starts.astype(np.int64),
        spatial=w_d[alive],
        temporal=w_dt[alive],
        count=w_cnt[alive],
        _size_ms=size_ms,
    )
