"""GeoJSON / WKT / CSV / TSV serde.

Implements the format contracts of the reference's
``spatialStreams/Deserialization.java`` (1593 LoC of hand-rolled JSON
coordinate walking) and ``Serialization.java`` (774 LoC of per-type Kafka
output schemas) as compact host-side parsers/emitters over the object model.

Contracts kept:
  - GeoJSON records may arrive in the Kafka JSON envelope
    ``{"key":..., "value": {feature}}`` or as a bare feature/geometry
    (Deserialization.GeoJSONToTSpatial, Deserialization.java:149-211).
  - Trajectory variants read objID/timestamp from configurable property
    names (``geoJSONSchemaAttr`` — conf/geoflink-conf.yml:19) with either a
    date format or epoch millis.
  - CSV/TSV schema = attribute positions [objID, timestamp, x, y]
    (``csvTsvSchemaAttr``, Deserialization.CSVTSVToTSpatial,
    Deserialization.java:291-325); quotes stripped, delimiter-with-spaces
    tolerated.
  - WKT records locate the geometry token anywhere in the line
    (Deserialization.WKTToTSpatial finds ``indexOf("POINT")``).
"""

from __future__ import annotations

import json
import re
from datetime import datetime, timezone
from typing import List, Optional, Sequence, Union

import numpy as np

from spatialflink_tpu.models.objects import (
    GeometryCollection,
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
    SpatialObject,
)

# ---------------------------------------------------------------------------
# timestamps


def _java_date_format(fmt: str) -> str:
    """Java SimpleDateFormat → strftime for the tokens the reference's
    configs use (yyyy-MM-dd HH:mm:ss)."""
    return (
        fmt.replace("yyyy", "%Y")
        .replace("MM", "%m")
        .replace("dd", "%d")
        .replace("HH", "%H")
        .replace("mm", "%M")
        .replace("ss", "%S")
    )


def parse_timestamp(value, date_format: Optional[str], strict: bool = False) -> int:
    """Property value → epoch ms. ``date_format`` uses Java SimpleDateFormat
    conventions from the config (e.g. "yyyy-MM-dd HH:mm:ss"); None/"null"
    means the value is already epoch millis.

    Default behavior is reference parity: unparseable timestamps become 0
    (the reference swallows ParseException, Deserialization.java:190-196).
    ``strict=True`` raises instead, which makes the sources drop the record
    (they skip lines that raise ValueError).
    """
    if value is None:
        if strict:
            raise ValueError("missing timestamp")
        return 0
    if date_format and date_format != "null":
        try:
            dt = datetime.strptime(str(value), _java_date_format(date_format))
            return int(dt.replace(tzinfo=timezone.utc).timestamp() * 1000)
        except ValueError:
            if strict:
                raise
            return 0
    try:
        return int(value)
    except (TypeError, ValueError):
        if strict:
            raise ValueError(f"unparseable timestamp: {value!r}")
        return 0


def format_timestamp(ts_ms: int, date_format: Optional[str]) -> str:
    if date_format and date_format != "null":
        return datetime.fromtimestamp(ts_ms / 1000, tz=timezone.utc).strftime(
            _java_date_format(date_format)
        )
    return str(ts_ms)


# ---------------------------------------------------------------------------
# GeoJSON


def _geometry_from_geojson(geom: dict, obj_id=None, ts=0) -> SpatialObject:
    gtype = geom.get("type", "")
    coords = geom.get("coordinates")
    if gtype == "Point":
        return Point(obj_id=obj_id, timestamp=ts, x=coords[0], y=coords[1])
    if gtype == "MultiPoint":
        return MultiPoint(obj_id=obj_id, timestamp=ts, coords=np.asarray(coords, float))
    if gtype == "LineString":
        return LineString(obj_id=obj_id, timestamp=ts, coords=np.asarray(coords, float))
    if gtype == "MultiLineString":
        return MultiLineString(
            obj_id=obj_id, timestamp=ts,
            parts=[np.asarray(p, float) for p in coords],
        )
    if gtype == "Polygon":
        return Polygon(
            obj_id=obj_id, timestamp=ts, rings=[np.asarray(r, float) for r in coords]
        )
    if gtype == "MultiPolygon":
        return MultiPolygon.from_polygons(
            [[np.asarray(r, float) for r in poly] for poly in coords],
            obj_id=obj_id, timestamp=ts,
        )
    if gtype == "GeometryCollection":
        return GeometryCollection(
            obj_id=obj_id, timestamp=ts,
            geometries=[_geometry_from_geojson(g) for g in geom.get("geometries", [])],
        )
    raise ValueError(f"unsupported GeoJSON geometry type: {gtype!r}")


def parse_geojson(
    record: Union[str, dict],
    timestamp_property: str = "timestamp",
    objid_property: str = "oID",
    date_format: Optional[str] = None,
) -> SpatialObject:
    """Parse a GeoJSON record (Kafka envelope, Feature, or bare geometry)."""
    obj = json.loads(record) if isinstance(record, str) else record
    if "value" in obj and isinstance(obj["value"], dict):  # Kafka envelope
        obj = obj["value"]
    props = obj.get("properties") or {}
    geom = obj.get("geometry", obj)  # Feature vs bare geometry
    oid = props.get(objid_property)
    if oid is not None:
        oid = str(oid)
    ts = parse_timestamp(props.get(timestamp_property), date_format)
    return _geometry_from_geojson(geom, obj_id=oid, ts=ts)


def _coords_to_geojson(obj: SpatialObject):
    if isinstance(obj, Point):
        return "Point", [obj.x, obj.y]
    if isinstance(obj, MultiPoint):
        return "MultiPoint", obj.coords.tolist()
    if isinstance(obj, MultiLineString):
        return "MultiLineString", [p.tolist() for p in (obj.parts or [obj.coords])]
    if isinstance(obj, LineString):
        return "LineString", obj.coords.tolist()
    if isinstance(obj, MultiPolygon):
        return "MultiPolygon", [
            [r.tolist() for r in poly.rings] for poly in obj.polygons()
        ]
    if isinstance(obj, Polygon):
        return "Polygon", [r.tolist() for r in obj.rings]
    raise TypeError(f"cannot serialize {type(obj).__name__}")


def to_geojson(
    obj: SpatialObject,
    timestamp_property: str = "timestamp",
    objid_property: str = "oID",
    date_format: Optional[str] = None,
) -> str:
    """Emit a GeoJSON Feature string (Serialization.java's output schemas)."""
    if isinstance(obj, GeometryCollection):
        geometry = {
            "type": "GeometryCollection",
            "geometries": [
                dict(zip(("type", "coordinates"), _coords_to_geojson(g)))
                for g in obj.geometries
            ],
        }
    else:
        gtype, coords = _coords_to_geojson(obj)
        geometry = {"type": gtype, "coordinates": coords}
    feature = {
        "type": "Feature",
        "geometry": geometry,
        "properties": {
            objid_property: obj.obj_id,
            timestamp_property: format_timestamp(obj.timestamp, date_format),
        },
    }
    return json.dumps(feature)


# ---------------------------------------------------------------------------
# WKT

_WKT_TYPES = (
    "GEOMETRYCOLLECTION",
    "MULTIPOLYGON",
    "MULTILINESTRING",
    "MULTIPOINT",
    "POLYGON",
    "LINESTRING",
    "POINT",
)


def _parse_coord_seq(body: str) -> np.ndarray:
    pts = []
    for tok in body.split(","):
        parts = tok.strip().lstrip("(").rstrip(")").split()
        pts.append([float(parts[0]), float(parts[1])])
    return np.asarray(pts, float)


def _split_groups(body: str) -> List[str]:
    """Split a parenthesized group list at depth 0 commas: "(a),(b)" → [a, b]."""
    groups, depth, cur = [], 0, []
    for ch in body:
        if ch == "(":
            if depth > 0:
                cur.append(ch)
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth > 0:
                cur.append(ch)
            elif depth == 0:
                groups.append("".join(cur))
                cur = []
        elif ch == "," and depth == 0:
            pass
        elif depth > 0:
            cur.append(ch)
    return groups


def parse_wkt(text: str, obj_id=None, timestamp: int = 0) -> SpatialObject:
    """Parse the first WKT geometry found anywhere in ``text``."""
    upper = text.upper()
    for wt in _WKT_TYPES:
        pos = upper.find(wt)
        if pos >= 0:
            # Guard against finding "POINT" inside "MULTIPOINT" handled by
            # ordering; extract the balanced-paren body after the tag.
            rest = text[pos + len(wt):].lstrip()
            if not rest.startswith("("):
                continue
            depth, end = 0, 0
            for i, ch in enumerate(rest):
                if ch == "(":
                    depth += 1
                elif ch == ")":
                    depth -= 1
                    if depth == 0:
                        end = i + 1
                        break
            body = rest[1 : end - 1]
            return _wkt_build(wt, body, obj_id, timestamp)
    raise ValueError(f"no WKT geometry in: {text[:80]!r}")


def _wkt_build(wt: str, body: str, obj_id, ts) -> SpatialObject:
    if wt == "POINT":
        xy = _parse_coord_seq(body)[0]
        return Point(obj_id=obj_id, timestamp=ts, x=xy[0], y=xy[1])
    if wt == "LINESTRING":
        return LineString(obj_id=obj_id, timestamp=ts, coords=_parse_coord_seq(body))
    if wt == "POLYGON":
        return Polygon(
            obj_id=obj_id, timestamp=ts,
            rings=[_parse_coord_seq(g) for g in _split_groups(body)],
        )
    if wt == "MULTIPOINT":
        if "(" in body:
            coords = np.concatenate(
                [_parse_coord_seq(g) for g in _split_groups(body)], axis=0
            )
        else:
            coords = _parse_coord_seq(body)
        return MultiPoint(obj_id=obj_id, timestamp=ts, coords=coords)
    if wt == "MULTILINESTRING":
        return MultiLineString(
            obj_id=obj_id, timestamp=ts,
            parts=[_parse_coord_seq(g) for g in _split_groups(body)],
        )
    if wt == "MULTIPOLYGON":
        polys = []
        for g in _split_groups(body):
            polys.append([_parse_coord_seq(r) for r in _split_groups(g)])
        return MultiPolygon.from_polygons(polys, obj_id=obj_id, timestamp=ts)
    if wt == "GEOMETRYCOLLECTION":
        geoms = []
        # Split at top-level geometry tags.
        idx = [
            m.start()
            for m in re.finditer(
                "|".join(_WKT_TYPES), body.upper()
            )
        ]
        # Keep only non-overlapping tag positions (MULTIPOINT contains POINT).
        starts = []
        for i in idx:
            if not starts or i >= starts[-1][1]:
                for wt2 in _WKT_TYPES:
                    if body.upper().startswith(wt2, i):
                        starts.append((i, i + len(wt2)))
                        break
        bounds = [s[0] for s in starts] + [len(body)]
        for a, b in zip(bounds[:-1], bounds[1:]):
            geoms.append(parse_wkt(body[a:b]))
        return GeometryCollection(obj_id=obj_id, timestamp=ts, geometries=geoms)
    raise ValueError(wt)


def _ring_wkt(r: np.ndarray) -> str:
    r = np.asarray(r, float)
    if not np.array_equal(r[0], r[-1]):
        r = np.vstack([r, r[:1]])
    return "(" + ", ".join(f"{x:g} {y:g}" for x, y in r) + ")"


def to_wkt(obj: SpatialObject) -> str:
    if isinstance(obj, Point):
        return f"POINT ({obj.x:g} {obj.y:g})"
    if isinstance(obj, MultiPoint):
        return "MULTIPOINT (" + ", ".join(f"{x:g} {y:g}" for x, y in obj.coords) + ")"
    if isinstance(obj, MultiLineString):
        parts = obj.parts or [obj.coords]
        return "MULTILINESTRING (" + ", ".join(
            "(" + ", ".join(f"{x:g} {y:g}" for x, y in p) + ")" for p in parts
        ) + ")"
    if isinstance(obj, LineString):
        return "LINESTRING (" + ", ".join(f"{x:g} {y:g}" for x, y in obj.coords) + ")"
    if isinstance(obj, MultiPolygon):
        return "MULTIPOLYGON (" + ", ".join(
            "(" + ", ".join(_ring_wkt(r) for r in p.rings) + ")" for p in obj.polygons()
        ) + ")"
    if isinstance(obj, Polygon):
        return "POLYGON (" + ", ".join(_ring_wkt(r) for r in obj.rings) + ")"
    if isinstance(obj, GeometryCollection):
        return "GEOMETRYCOLLECTION (" + ", ".join(to_wkt(g) for g in obj.geometries) + ")"
    raise TypeError(type(obj).__name__)


# ---------------------------------------------------------------------------
# CSV / TSV


def parse_csv_point(
    line: str,
    schema: Sequence[int] = (0, 1, 2, 3),
    delimiter: str = ",",
    date_format: Optional[str] = None,
    strict: bool = False,
) -> Point:
    """CSV/TSV → Point. ``schema`` = positions of [objID, timestamp, x, y]
    (csvTsvSchemaAttr; Deserialization.CSVTSVToTSpatial,
    Deserialization.java:291-325). Quotes stripped; whitespace around the
    delimiter tolerated."""
    fields = re.split(r"\s*" + re.escape(delimiter) + r"\s*", line.replace('"', "").strip())
    oid = fields[schema[0]]
    ts = parse_timestamp(fields[schema[1]], date_format, strict=strict)
    x = float(fields[schema[2]])
    y = float(fields[schema[3]])
    return Point(obj_id=oid, timestamp=ts, x=x, y=y)


def to_csv_point(p: Point, delimiter: str = ",") -> str:
    # repr(float(...)): plain floats keep full precision; numpy scalars
    # would render as "np.float64(…)" under numpy>=2.
    return delimiter.join(
        [str(p.obj_id), str(p.timestamp), repr(float(p.x)), repr(float(p.y))]
    )
