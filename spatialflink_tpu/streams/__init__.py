from spatialflink_tpu.streams.windows import (  # noqa: F401
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
    CountWindows,
    WindowAssembler,
    WindowBatch,
)
from spatialflink_tpu.streams.sources import (  # noqa: F401
    collection_source,
    csv_source,
    socket_source,
    SyntheticGpsSource,
)
from spatialflink_tpu.streams.sinks import (  # noqa: F401
    CollectSink,
    CsvFileSink,
    PrintSink,
)
