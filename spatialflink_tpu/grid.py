"""UniformGrid — the spatial index (host-side control plane).

A ground-up re-design of the reference's ``GeoFlink/spatialIndices/
UniformGrid.java``. The reference materializes neighbor cells as HashSets of
string keys per query object and tests set membership per record
(UniformGrid.java:165-222, 368-426). Here the same layer math produces a
dense uint8 **flag table** of shape (n*n+1,) once per (query, radius); the
TPU kernels gather from it per point (ops/cells.py), which replaces the
per-record hash lookups with one vectorized gather.

Layer math (kept numerically identical to the reference):
  - guaranteed layers L_g = floor(r / (cell * sqrt(2)) - 1)
    (UniformGrid.getGuaranteedNeighboringLayers, UniformGrid.java:428-439);
    -1 → no guaranteed cells, 0 → only the query cell, n → n layers.
  - candidate layers L_c = ceil(r / cell)
    (UniformGrid.getCandidateNeighboringLayers, UniformGrid.java:441-445);
    candidate set = L_c-square minus the guaranteed set
    (getCandidateNeighboringCells, UniformGrid.java:368-426).
"""

from __future__ import annotations

import math
from typing import Iterable, Sequence, Tuple

import numpy as np

FLAG_NONE = np.uint8(0)
FLAG_CANDIDATE = np.uint8(1)
FLAG_GUARANTEED = np.uint8(2)

_CELL_INDEX_STR_LENGTH = 5  # key format parity: UniformGrid.java CELLINDEXSTRLENGTH


class UniformGrid:
    """Square uniform grid over a bounding box.

    Two constructors, matching the reference:
      - ``UniformGrid.from_cell_length(cell_length, ...)`` — cell size in
        coordinate units (UniformGrid.java:47-73, incl. the square-grid bbox
        adjustment and cell-length recomputation);
      - ``UniformGrid(n_partitions, ...)`` — cell count per side
        (UniformGrid.java:75-85).
    """

    def __init__(
        self,
        num_partitions: int,
        min_x: float,
        max_x: float,
        min_y: float,
        max_y: float,
    ):
        if num_partitions < 1:
            raise ValueError("num_partitions must be >= 1")
        self.min_x = float(min_x)
        self.max_x = float(max_x)
        self.min_y = float(min_y)
        self.max_y = float(max_y)
        self.n = int(num_partitions)
        self.cell_length = (self.max_x - self.min_x) / self.n

    @classmethod
    def from_cell_length(
        cls, cell_length: float, min_x: float, max_x: float, min_y: float, max_y: float
    ) -> "UniformGrid":
        # Square-grid adjustment: stretch the shorter axis symmetrically so
        # both spans are equal (UniformGrid.adjustCoordinatesForSquareGrid,
        # UniformGrid.java:115-135).
        x_diff = max_x - min_x
        y_diff = max_y - min_y
        if x_diff > y_diff:
            pad = (x_diff - y_diff) / 2
            min_y, max_y = min_y - pad, max_y + pad
        elif y_diff > x_diff:
            pad = (y_diff - x_diff) / 2
            min_x, max_x = min_x - pad, max_x + pad
        n = max(1, math.ceil((max_x - min_x) / cell_length))
        return cls(n, min_x, max_x, min_y, max_y)

    # ---- cell id arithmetic -------------------------------------------------

    @property
    def num_cells(self) -> int:
        return self.n * self.n

    def cell_indices(self, x: float, y: float) -> Tuple[int, int]:
        """Floor indices, unclamped (HelperClass.java:104-116)."""
        xi = math.floor((x - self.min_x) / self.cell_length)
        yi = math.floor((y - self.min_y) / self.cell_length)
        return xi, yi

    def flat_cell(self, x: float, y: float) -> int:
        """Flat int id; num_cells means out-of-grid."""
        xi, yi = self.cell_indices(x, y)
        if 0 <= xi < self.n and 0 <= yi < self.n:
            return xi * self.n + yi
        return self.num_cells

    def cell_xy_indices_np(self, xy: np.ndarray) -> np.ndarray:
        """(N, 2) int32 unclamped (xi, yi) floor indices — the join kernel's
        left-side input (out-of-grid neighbors are masked device-side)."""
        xi = np.floor((xy[..., 0] - self.min_x) / self.cell_length).astype(np.int32)
        yi = np.floor((xy[..., 1] - self.min_y) / self.cell_length).astype(np.int32)
        return np.stack([xi, yi], axis=-1)

    def assign_cells_np(self, xy: np.ndarray) -> np.ndarray:
        """Vectorized host-side cell assignment, same contract as ops.assign_cells."""
        xi = np.floor((xy[..., 0] - self.min_x) / self.cell_length).astype(np.int64)
        yi = np.floor((xy[..., 1] - self.min_y) / self.cell_length).astype(np.int64)
        inside = (xi >= 0) & (xi < self.n) & (yi >= 0) & (yi < self.n)
        return np.where(inside, xi * self.n + yi, self.num_cells).astype(np.int32)

    def cell_name(self, flat: int) -> str:
        """String key parity with the reference ("xxxxxyyyyy", 5+5 digits)."""
        xi, yi = divmod(int(flat), self.n)
        w = _CELL_INDEX_STR_LENGTH
        return f"{xi:0{w}d}{yi:0{w}d}"

    def cell_from_name(self, name: str) -> int:
        w = _CELL_INDEX_STR_LENGTH
        return int(name[:w]) * self.n + int(name[w:])

    def bbox_cells(
        self, min_x: float, min_y: float, max_x: float, max_y: float
    ) -> np.ndarray:
        """All flat cells overlapped by a bbox, clipped to the grid.

        The reference's bbox→gridIDsSet assignment for Polygon/LineString
        (HelperClass.assignGridCellID(bBox,...), HelperClass.java:122-143).
        """
        x1, y1 = self.cell_indices(min_x, min_y)
        x2, y2 = self.cell_indices(max_x, max_y)
        x1, x2 = max(0, x1), min(self.n - 1, x2)
        y1, y2 = max(0, y1), min(self.n - 1, y2)
        if x1 > x2 or y1 > y2:
            return np.empty((0,), np.int32)
        xs = np.arange(x1, x2 + 1, dtype=np.int32)
        ys = np.arange(y1, y2 + 1, dtype=np.int32)
        return (xs[:, None] * self.n + ys[None, :]).reshape(-1)

    # ---- neighbor-layer math ------------------------------------------------

    def guaranteed_layers(self, radius: float) -> int:
        """floor(r / (cell*sqrt(2)) - 1); UniformGrid.java:428-439."""
        return math.floor(radius / (self.cell_length * math.sqrt(2.0)) - 1)

    def candidate_layers(self, radius: float) -> int:
        """ceil(r / cell); UniformGrid.java:441-445."""
        return math.ceil(radius / self.cell_length)

    def _square(self, xi: int, yi: int, layers: int, out: np.ndarray, flag: np.uint8):
        """Mark the (2*layers+1)^2 square around (xi, yi), grid-clipped."""
        if layers < 0:
            return
        x1, x2 = max(0, xi - layers), min(self.n - 1, xi + layers)
        y1, y2 = max(0, yi - layers), min(self.n - 1, yi + layers)
        if x1 > x2 or y1 > y2:
            return
        view = out[: self.num_cells].reshape(self.n, self.n)
        view[x1 : x2 + 1, y1 : y2 + 1] = flag

    def neighbor_flags(
        self, radius: float, query_cells: Iterable[int]
    ) -> np.ndarray:
        """Build the (num_cells+1,) uint8 flag table for a query.

        ``query_cells``: flat ids of the cells the query object overlaps (one
        cell for a point; the gridIDsSet for polygons/linestrings —
        UniformGrid.java:194-222). Guaranteed flags win over candidate
        (the sets are mutually exclusive in the reference,
        UniformGrid.java:161-164).
        """
        flags = np.zeros(self.num_cells + 1, np.uint8)
        lg = self.guaranteed_layers(radius)
        lc = self.candidate_layers(radius)
        cells = [c for c in query_cells if 0 <= c < self.num_cells]
        # Candidate square first, then overwrite with guaranteed square.
        for c in cells:
            xi, yi = divmod(int(c), self.n)
            self._square(xi, yi, lc, flags, FLAG_CANDIDATE)
        for c in cells:
            xi, yi = divmod(int(c), self.n)
            self._square(xi, yi, lg, flags, FLAG_GUARANTEED)
        flags[self.num_cells] = FLAG_NONE
        return flags

    def neighbor_cells(
        self, radius: float, query_cells: Iterable[int], guaranteed_only: bool = False
    ) -> np.ndarray:
        """Flat ids of guaranteed (∪ candidate) neighbor cells."""
        flags = self.neighbor_flags(radius, query_cells)
        if guaranteed_only:
            return np.nonzero(flags == FLAG_GUARANTEED)[0].astype(np.int32)
        return np.nonzero(flags != FLAG_NONE)[0].astype(np.int32)

    def neighbor_offsets(self, radius: float) -> np.ndarray:
        """(K, 2) int32 (dx, dy) offsets covering the candidate square.

        Static per (grid, radius): used by the bucketed join kernel to gather
        a point's neighbor-cell buckets (replaces the reference's query-
        replication flatMap, JoinQuery.java:73-90).
        """
        lc = self.candidate_layers(radius)
        r = np.arange(-lc, lc + 1, dtype=np.int32)
        dx, dy = np.meshgrid(r, r, indexing="ij")
        return np.stack([dx.reshape(-1), dy.reshape(-1)], axis=1)

    def cell_layer(self, cell_a: int, cell_b: int) -> int:
        """Chebyshev ring number of cell_b around cell_a
        (HelperClass.getCellLayerWRTQueryCell, HelperClass.java:278-296)."""
        ax, ay = divmod(int(cell_a), self.n)
        bx, by = divmod(int(cell_b), self.n)
        return max(abs(ax - bx), abs(ay - by))

    def __repr__(self) -> str:
        return (
            f"UniformGrid(n={self.n}, cell={self.cell_length:.6g}, "
            f"bbox=({self.min_x}, {self.min_y})..({self.max_x}, {self.max_y}))"
        )
