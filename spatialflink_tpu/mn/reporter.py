"""NES-format periodic stats reporter (``com/mn/metrics/reporter/
NESFileReporter.java:19-105``).

Appends lines of the exact NES shape every interval:

  METRICS ts=<iso-instant> eps_in_avg=<…> eps_out_avg=<…> \
  selectivity_e2e=<…> throughput_mb_s=<…>

to ``EngineStats_<queryId>_proc.stats``. Deltas are computed against the
previous snapshot like the reference's counter-delta logic
(NESFileReporter.java:54-99). ``report()`` can be driven manually (tests,
bounded replays) or by the built-in timer thread.
"""

from __future__ import annotations

import math
import os
import threading
import time
from datetime import datetime, timezone
from typing import Dict, Optional

from spatialflink_tpu.mn.metrics import MetricNames, MetricRegistry


class NESFileReporter:
    def __init__(
        self,
        registry: MetricRegistry,
        query_id: str,
        out_dir: str = ".",
        interval_s: float = 5.0,
    ):
        self.registry = registry
        self.query_id = query_id
        self.out_dir = out_dir
        self.interval_s = interval_s
        self._last: Dict[str, int] = {}
        # First interval measures from construction (real elapsed time, not
        # a fabricated interval_s).
        self._last_time: float = time.time()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        os.makedirs(out_dir, exist_ok=True)

    @property
    def stats_path(self) -> str:
        return os.path.join(self.out_dir, f"EngineStats_{self.query_id}_proc.stats")

    def report(self, now: Optional[float] = None) -> str:
        now = time.time() if now is None else now
        interval = max(now - self._last_time, 1e-9)
        self._last_time = now

        # Locked copy: the timer thread must not iterate the dict while
        # operator threads mutate it (mn/metrics.py:MetricRegistry).
        counters = self.registry.snapshot_counters()
        # First report counts everything since start (reference initializes
        # last to the current value on first sight, yielding 0 — we prefer
        # the informative first delta; both converge immediately after).
        def delta(name: str) -> int:
            return counters.get(name, 0) - self._last.get(name, 0)

        d_source = delta(MetricNames.SOURCE_IN)
        d_sink = delta(MetricNames.SINK_OUT)
        d_bytes = delta(MetricNames.OUT_BYTES)
        self._last = counters
        eps_in = d_source / interval
        eps_out = d_sink / interval
        sel = (d_sink / d_source) if d_source > 0 else math.nan
        mbps = d_bytes / interval / 1_000_000.0

        ts = datetime.fromtimestamp(now, tz=timezone.utc).isoformat()
        # float() wraps: numpy ≥2 scalars would print np.float64(…) into
        # the METRICS line (sfcheck fstring-numpy).
        line = (
            f"METRICS ts={ts} eps_in_avg={float(eps_in):.2f} "
            f"eps_out_avg={float(eps_out):.2f} "
            f"selectivity_e2e={float(sel):.4f} "
            f"throughput_mb_s={float(mbps):.4f}"
        )
        # Kernel-level counters (Point.java:220-235 distance-computation
        # analog) append when the global registry is enabled.
        from spatialflink_tpu.ops.counters import counters as opcounters

        if opcounters.enabled:
            line += (
                f" dist_comp_total={opcounters.dist_computations}"
                f" candidate_lanes_total={opcounters.candidate_lanes}"
            )
        # Telemetry columns (telemetry.py) append while the runtime
        # telemetry layer is enabled: watermark lag + late drops from the
        # window assemblers, compile count from the recompile detector,
        # device-boundary bytes from the operator shipping/fetch hooks.
        from spatialflink_tpu.telemetry import telemetry

        if telemetry.enabled:
            line += (
                f" watermark_lag_ms_max={telemetry.max_watermark_lag_ms}"
                f" late_dropped_total={telemetry.late_drops}"
                f" compiles_total={telemetry.compile_count}"
                f" h2d_bytes_total={telemetry.h2d_bytes}"
                f" d2h_bytes_total={telemetry.d2h_bytes}"
            )
        with open(self.stats_path, "a") as f:
            f.write(line + "\n")
        return line

    # -- optional timer-thread mode ------------------------------------------

    def start(self):
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                self.report()

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self):
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=self.interval_s + 1)
            self._thread = None
