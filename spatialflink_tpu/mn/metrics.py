"""NES-compatible metrics primitives (``com/mn/metrics/``).

``FixedBucketLatency`` keeps the exact NES bucket boundaries and percentile
semantics of FixedBucketLatency.java:13-67; ``MetricNames`` the canonical
names of MetricNames.java:6-35; ``MetricRegistry`` replaces Flink's
MetricGroup with a flat counter/gauge registry the reporter reads.
"""

from __future__ import annotations

import bisect
import math
import numbers
import threading
from typing import Callable, Dict, List

import numpy as np


def json_safe(value):
    """Builtin-type mirror of a metrics/telemetry value: numpy scalars →
    ``int``/``float``, arrays → lists, containers recursed. Every snapshot
    crosses this at its boundary so ``json.dumps(snapshot)`` can never
    raise (the ``np.float32`` f-string/serialization bug has shipped twice)
    and f-strings format cleanly under numpy ≥2."""
    if isinstance(value, dict):
        return {k: json_safe(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [json_safe(v) for v in value]
    if isinstance(value, (bool, int, float, str)) or value is None:
        return value
    if isinstance(value, np.ndarray):
        return value.tolist()
    if isinstance(value, np.bool_):  # not registered with numbers on np≥2
        return bool(value)
    if isinstance(value, numbers.Integral):  # np.int32/64, …
        return int(value)
    if isinstance(value, numbers.Real):  # np.float32/64, …
        return float(value)
    if isinstance(value, np.generic):  # any other numpy scalar
        return value.item()
    return value

# NES buckets in ms — upper bounds ("le" semantics), FixedBucketLatency.java:15-16.
BUCKETS_MS = [0, 1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1000, 2000, 5000,
              10000, 20000, 60000]


class MetricNames:
    """MetricNames.java:6-35."""

    THEORETICAL_EPS = "theoretical_eps"
    THEORETICAL_THROUGHPUT = "theoretical_throughput_mb_s"
    SOURCE_IN = "source_in_total"
    SINK_OUT = "sink_out_total"
    OUT_BYTES = "out_bytes_total"
    LATENCY_COUNT = "latency_count"
    LATENCY_SUM = "latency_sum_ms"
    LATENCY_P50 = "latency_p50_ms"
    LATENCY_P95 = "latency_p95_ms"
    LATENCY_P99 = "latency_p99_ms"

    @staticmethod
    def pipe_in(pipe_id: str) -> str:
        return f"pipe_{pipe_id}_in_total"

    @staticmethod
    def pipe_out(pipe_id: str) -> str:
        return f"pipe_{pipe_id}_out_total"


class MetricRegistry:
    """Counters + gauges, the host-side MetricGroup analog.

    Thread-safe: operator threads ``inc`` while the NESFileReporter timer
    thread snapshots — increments and copies share one lock (a bare
    ``dict(registry.counters)`` mid-resize raised RuntimeError and could
    tear read-modify-write increments)."""

    def __init__(self):
        self.counters: Dict[str, int] = {}
        self.gauges: Dict[str, Callable[[], float]] = {}
        self._lock = threading.Lock()

    def inc(self, name: str, n: int = 1):
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def counter(self, name: str) -> int:
        with self._lock:
            return self.counters.get(name, 0)

    def gauge(self, name: str, fn: Callable[[], float]):
        with self._lock:
            self.gauges[name] = fn

    def snapshot_counters(self) -> Dict[str, int]:
        """Consistent counter copy for reporter threads."""
        with self._lock:
            return dict(self.counters)

    def snapshot(self) -> Dict[str, float]:
        out: Dict[str, float] = self.snapshot_counters()
        with self._lock:
            gauges = list(self.gauges.items())
        for name, fn in gauges:
            out[name] = fn()
        return json_safe(out)


class FixedBucketLatency:
    """17-bucket latency histogram with p50/p95/p99 (FixedBucketLatency.java).

    ``observe`` places a sample in the first bucket whose bound is >= the
    value (binary search, overflow clamps to the last bucket); percentiles
    return the bucket bound at the ceil(p·n)-th cumulative sample.
    """

    def __init__(self, registry: MetricRegistry | None = None, prefix: str = ""):
        self.buckets = [0] * len(BUCKETS_MS)
        self.count = 0
        self.sum_ms = 0
        self.registry = registry
        self.prefix = prefix
        if registry is not None:
            registry.gauge(prefix + MetricNames.LATENCY_P50, lambda: self.percentile(0.50))
            registry.gauge(prefix + MetricNames.LATENCY_P95, lambda: self.percentile(0.95))
            registry.gauge(prefix + MetricNames.LATENCY_P99, lambda: self.percentile(0.99))

    def observe(self, latency_ms: float):
        idx = bisect.bisect_left(BUCKETS_MS, latency_ms)
        if idx >= len(BUCKETS_MS):
            idx = len(BUCKETS_MS) - 1
        self.buckets[idx] += 1
        self.count += 1
        self.sum_ms += int(latency_ms)
        if self.registry is not None:
            self.registry.inc(f"{self.prefix}latency_bucket_le_{BUCKETS_MS[idx]}")
            self.registry.inc(self.prefix + MetricNames.LATENCY_COUNT)
            self.registry.inc(self.prefix + MetricNames.LATENCY_SUM, int(latency_ms))

    def percentile(self, p: float) -> float:
        if self.count <= 0:
            return math.nan
        rank = math.ceil(p * self.count)
        cum = 0
        for i, c in enumerate(self.buckets):
            cum += c
            if cum >= rank:
                return float(BUCKETS_MS[i])
        return float(BUCKETS_MS[-1])
