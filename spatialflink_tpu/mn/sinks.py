"""Counting latency sinks (``com/mn/sinks/``)."""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Optional

from spatialflink_tpu.mn.metrics import FixedBucketLatency, MetricNames, MetricRegistry


class _CountingLatencySinkBase:
    """Measure sink_out, out_bytes, and per-record latency
    (now − ingestNs) into the histogram (CountingLatencyFileSink.java:23-70)."""

    def __init__(self, registry: MetricRegistry,
                 histogram: Optional[FixedBucketLatency] = None):
        self.registry = registry
        self.histogram = histogram or FixedBucketLatency(registry)
        # Sink-owned registries carry the runtime-telemetry gauges
        # (watermark lag, late drops, compiles, device-boundary bytes) so
        # registry.snapshot() gains the columns. Registered
        # unconditionally: the gauges read live singleton state, so
        # telemetry enabled AFTER the pipeline is built still reports
        # (zeros while disabled).
        from spatialflink_tpu.telemetry import telemetry

        telemetry.register_metrics(registry)

    def _account(self, rendered: str, ingest_ns: Optional[int]):
        self.registry.inc(MetricNames.SINK_OUT)
        self.registry.inc(MetricNames.OUT_BYTES, len(rendered) + 1)
        if ingest_ns is not None:
            # Whole-ms truncation like the reference (deltaNs / 1_000_000
            # as integer division) so bucket placement matches exactly.
            self.histogram.observe((time.monotonic_ns() - ingest_ns) // 1_000_000)


class CountingLatencyFileSink(_CountingLatencySinkBase):
    """Write + flush each record (CountingLatencyFileSink.java:23-70)."""

    def __init__(self, path: str, registry: MetricRegistry,
                 formatter: Callable[[Any], str] = str,
                 histogram: Optional[FixedBucketLatency] = None):
        super().__init__(registry, histogram)
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._f = open(path, "w")
        self.formatter = formatter

    def __call__(self, record: Any, ingest_ns: Optional[int] = None):
        line = self.formatter(record)
        self._f.write(line + "\n")
        self._f.flush()
        self._account(line, ingest_ns)

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class CountingLatencyPrintSink(_CountingLatencySinkBase):
    """Print variant (CountingLatencyPrintSink.java:17-48)."""

    def __init__(self, registry: MetricRegistry,
                 formatter: Callable[[Any], str] = str,
                 histogram: Optional[FixedBucketLatency] = None,
                 quiet: bool = False):
        super().__init__(registry, histogram)
        self.formatter = formatter
        self.quiet = quiet

    def __call__(self, record: Any, ingest_ns: Optional[int] = None):
        line = self.formatter(record)
        if not self.quiet:
            print(line)
        self._account(line, ingest_ns)
