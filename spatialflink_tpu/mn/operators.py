"""Instrumentation operators (``com/mn/operators/``)."""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Generic, Iterable, Iterator, Optional, TypeVar

from spatialflink_tpu.mn.metrics import MetricNames, MetricRegistry

T = TypeVar("T")


@dataclass
class Stamped(Generic[T]):
    """Record + monotonic ingest timestamp (Stamped.java:8-20)."""

    value: T
    ingest_ns: int


class CsvParseAndStamp(Generic[T]):
    """Parse CSV → T, count source_in_total, stamp ingest time
    (CsvParseAndStamp.java:14-53). Registers the theoretical EPS/MB-s
    gauges from the configured rate."""

    def __init__(
        self,
        parser: Callable[[str], T],
        registry: MetricRegistry,
        theoretical_rows_per_sec: int = 20_000,
        bytes_per_record: int = 128,
    ):
        self.parser = parser
        self.registry = registry
        registry.gauge(
            MetricNames.THEORETICAL_EPS, lambda: float(theoretical_rows_per_sec)
        )
        registry.gauge(
            MetricNames.THEORETICAL_THROUGHPUT,
            lambda: theoretical_rows_per_sec * bytes_per_record / 1_000_000.0,
        )

    def __call__(self, lines: Iterable[str]) -> Iterator[Stamped[T]]:
        for line in lines:
            try:
                v = self.parser(line)
            except (ValueError, IndexError):
                continue
            self.registry.inc(MetricNames.SOURCE_IN)
            yield Stamped(v, time.monotonic_ns())


class CountingStage(Generic[T]):
    """in/out counters around a pipeline stage for selectivity analysis
    (CountingMap.java:14-33 / CountingFlatMap.java:14-69). Wraps either a
    passthrough (count only) or a generator transform."""

    def __init__(self, pipe_id: str, registry: MetricRegistry):
        self.in_name = MetricNames.pipe_in(pipe_id)
        self.out_name = MetricNames.pipe_out(pipe_id)
        self.registry = registry

    def count_in(self, items: Iterable[T]) -> Iterator[T]:
        for it in items:
            self.registry.inc(self.in_name)
            yield it

    def count_out(self, items: Iterable[T]) -> Iterator[T]:
        for it in items:
            self.registry.inc(self.out_name)
            yield it

    def around(
        self, items: Iterable, transform: Callable[[Iterable], Iterable]
    ) -> Iterator:
        yield from self.count_out(transform(self.count_in(items)))
