"""Fully-instrumented MN query pipelines (``com/mn/queries/
InstrumentedMN_Q1..Q5.java``).

Each pipeline is: source lines → parse+stamp (``source_in_total``) →
counted stages (stable ids ``pipe_0_source`` … ``pipe_99_sink``) → query
logic → counting latency file sink + NES stats reporter. Configuration via
a properties dict with the reference's ``-D`` system-property names and
defaults (rows.per.sec=20000, tcp.host/port, query.lon/lat, output.file —
InstrumentedMN_Q1.java:86-95).

Latency semantics: window results carry the MIN ingest stamp of their
contributing events (InstrumentedMN_Q1.java:205-216) — e2e latency is
measured from the oldest event in the window.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, Iterator, List, Optional, Tuple

import numpy as np

from spatialflink_tpu.mn.metrics import FixedBucketLatency, MetricRegistry
from spatialflink_tpu.mn.operators import CountingStage, CsvParseAndStamp, Stamped
from spatialflink_tpu.mn.reporter import NESFileReporter
from spatialflink_tpu.mn.sinks import CountingLatencyFileSink
from spatialflink_tpu.sncb.common import GpsEvent, csv_to_gps_event
from spatialflink_tpu.sncb.ops import traj_speed, trajectory_wkt, variance
from spatialflink_tpu.streams.windows import SlidingEventTimeWindows, WindowAssembler

# The reference's -D system-property names and defaults
# (InstrumentedMN_Q1.java:86-95, InstrumentedMN_Q5.java:79-83).
_DEFAULTS = {
    "rows.per.sec": "20000",
    "tcp.host": "localhost",
    "tcp.port": "32323",
    "query.lon": "4.3658",
    "query.lat": "50.6456",
    # tolerance.meters has per-query defaults: Q1 = 100.0 (true meters via
    # the x111320 conversion), Q5 = 0.001 (degree-space, the reference's
    # "degrees approximation" — InstrumentedMN_Q5.java:83).
    "output.file": "metrics/mn_instrumented_results.txt",
    "stats.dir": "metrics",
    "bytes.per.input": "128",
}


@dataclass
class InstrumentedReport:
    query_id: str
    results: int
    metrics: Dict[str, float]
    p50_ms: float
    p95_ms: float
    p99_ms: float
    stats_lines: List[str] = field(default_factory=list)


def _props(overrides: Optional[Dict[str, str]]) -> Dict[str, str]:
    p = dict(_DEFAULTS)
    if overrides:
        p.update(overrides)
    return p


def _stamped_windows(stamped: Iterable[Stamped[GpsEvent]], size_ms: int,
                     slide_ms: int, lateness_ms: int = 2000):
    asm = WindowAssembler(
        SlidingEventTimeWindows(size_ms, slide_ms),
        timestamp_fn=lambda s: s.value.ts,
        max_out_of_orderness_ms=lateness_ms,
    )
    yield from asm.stream(stamped)


def _run(
    query_id: str,
    lines: Iterable[str],
    props: Optional[Dict[str, str]],
    pipeline: Callable[[Iterator[Stamped[GpsEvent]], MetricRegistry, Dict[str, str]],
                       Iterator[Tuple[object, Optional[int]]]],
    formatter: Callable[[object], str] = str,
) -> InstrumentedReport:
    p = _props(props)
    registry = MetricRegistry()
    hist = FixedBucketLatency(registry)
    parse = CsvParseAndStamp(
        lambda ln: csv_to_gps_event(ln),
        registry,
        theoretical_rows_per_sec=int(p["rows.per.sec"]),
        bytes_per_record=int(p["bytes.per.input"]),
    )
    reporter = NESFileReporter(registry, query_id, out_dir=p["stats.dir"])
    src_count = CountingStage("0_source", registry)
    sink_count = CountingStage("99_sink", registry)

    n_results = 0
    with CountingLatencyFileSink(
        p["output.file"], registry, formatter=formatter, histogram=hist
    ) as sink:
        stamped = parse(src_count.count_out(lines))
        for result, ingest_ns in pipeline(stamped, registry, p):
            registry.inc(sink_count.in_name)
            sink(result, ingest_ns)
            registry.inc(sink_count.out_name)
            n_results += 1
    line = reporter.report()
    return InstrumentedReport(
        query_id=query_id,
        results=n_results,
        metrics=registry.snapshot(),
        p50_ms=hist.percentile(0.50),
        p95_ms=hist.percentile(0.95),
        p99_ms=hist.percentile(0.99),
        stats_lines=[line],
    )


# ---------------------------------------------------------------------------


def instrumented_mn_q1(lines: Iterable[str],
                       props: Optional[Dict[str, str]] = None) -> InstrumentedReport:
    """Q1: proximity count. The range stage applies the degree→meter
    (×111320) Euclidean check — the only meters-true threshold in the
    reference (InstrumentedMN_Q1.java:176-190)."""

    def pipeline(stamped, registry, p):
        lon, lat = float(p["query.lon"]), float(p["query.lat"])
        tol_m = float(p.get("tolerance.meters", "100.0"))
        rng_count = CountingStage("6_range", registry)
        win_count = CountingStage("8_window", registry)

        def in_range(items):
            for s in items:
                registry.inc("range_queries")
                d_m = np.hypot(s.value.lon - lon, s.value.lat - lat) * 111_320.0
                if d_m <= tol_m:
                    yield s

        for win in _stamped_windows(rng_count.around(stamped, in_range),
                                    5000, 5000):
            registry.inc(win_count.in_name, len(win.events))
            ingest = min((s.ingest_ns for s in win.events), default=None)
            registry.inc(win_count.out_name)
            yield (win.start, win.end, len(win.events)), ingest

    return _run("mn_q1", lines, props, pipeline,
                formatter=lambda r: f"{r[0]},{r[1]},{r[2]}")


def instrumented_mn_q2(lines: Iterable[str],
                       props: Optional[Dict[str, str]] = None) -> InstrumentedReport:
    """Q2: global FA/FF variance, 10s/200ms sliding, spatial exclusion box
    (InstrumentedMN_Q2.java:216-217)."""

    def pipeline(stamped, registry, p):
        excl = CountingStage("3_exclude", registry)

        def exclude_box(items):
            for s in items:
                e = s.value
                if not (4.0 <= e.lon <= 4.6 and 50.0 <= e.lat <= 50.8):
                    yield s

        for win in _stamped_windows(excl.around(stamped, exclude_box),
                                    10_000, 200):
            n, var_fa, var_ff = variance([s.value for s in win.events])
            ingest = min((s.ingest_ns for s in win.events), default=None)
            yield (win.start, win.end, var_fa, var_ff, n), ingest

    return _run("mn_q2", lines, props, pipeline,
                formatter=lambda r: ",".join(map(str, r)))


def instrumented_mn_q3(lines: Iterable[str],
                       props: Optional[Dict[str, str]] = None) -> InstrumentedReport:
    """Q3: global trajectory, 3s/1s sliding windows."""

    def pipeline(stamped, registry, p):
        for win in _stamped_windows(stamped, 3000, 1000):
            wkt = trajectory_wkt([s.value for s in win.events])
            ingest = min((s.ingest_ns for s in win.events), default=None)
            yield (win.start, win.end, "ALL", wkt), ingest

    return _run("mn_q3", lines, props, pipeline,
                formatter=lambda r: ",".join(map(str, r)))


def instrumented_mn_q4(lines: Iterable[str],
                       props: Optional[Dict[str, str]] = None) -> InstrumentedReport:
    """Q4: Brussels-bbox-restricted global trajectory, 3s/1s windows
    (InstrumentedMN_Q4.java:99-101, :152)."""

    def pipeline(stamped, registry, p):
        flt = CountingStage("2_filter", registry)

        def bbox_time(items):
            for s in items:
                e = s.value
                # Brussels bounds (InstrumentedMN_Q4.java:99-101).
                if 4.287 <= e.lon <= 4.419 and 50.773 <= e.lat <= 50.896:
                    yield s

        for win in _stamped_windows(flt.around(stamped, bbox_time), 3000, 1000):
            wkt = trajectory_wkt([s.value for s in win.events])
            ingest = min((s.ingest_ns for s in win.events), default=None)
            yield (win.start, win.end, "ALL", wkt), ingest

    return _run("mn_q4", lines, props, pipeline,
                formatter=lambda r: ",".join(map(str, r)))


def instrumented_mn_q5(lines: Iterable[str],
                       props: Optional[Dict[str, str]] = None) -> InstrumentedReport:
    """Q5: buffered geofence + per-device 20s/2s traj+speed thresholds
    (InstrumentedMN_Q5.java:220-221)."""

    def pipeline(stamped, registry, p):
        from spatialflink_tpu.sncb.common import BufferedZone

        # Reference fence: {4.3,50.8} {4.4,50.8} {4.4,50.9} {4.3,50.9}
        # with configurable degree-space tolerance
        # (InstrumentedMN_Q5.java:83-87).
        fence_ring = [[4.3, 50.8], [4.4, 50.8], [4.4, 50.9], [4.3, 50.9],
                      [4.3, 50.8]]
        fence = BufferedZone(
            rings_metric=[np.asarray(fence_ring, float)],
            buffer_m=float(p.get("tolerance.meters", "0.001")),
        )
        fence_count = CountingStage("4_fence", registry)

        def in_fence(items):
            buf: List[Stamped[GpsEvent]] = []
            for s in items:
                buf.append(s)
                if len(buf) >= 4096:
                    keep = fence.contains_batch(
                        np.array([[b.value.lon, b.value.lat] for b in buf])
                    )
                    yield from (b for b, k in zip(buf, keep) if k)
                    buf = []
            if buf:
                keep = fence.contains_batch(
                    np.array([[b.value.lon, b.value.lat] for b in buf])
                )
                yield from (b for b, k in zip(buf, keep) if k)

        for win in _stamped_windows(fence_count.around(stamped, in_fence),
                                    20_000, 2000):
            by_dev: Dict[str, List[Stamped[GpsEvent]]] = {}
            for s in win.events:
                by_dev.setdefault(s.value.device_id, []).append(s)
            for dev in sorted(by_dev):
                evs = [s.value for s in by_dev[dev]]
                wkt, avg_speed, min_speed = traj_speed(evs)
                if avg_speed < 100.0 or (min_speed == min_speed and min_speed < 20.0):
                    ingest = min(s.ingest_ns for s in by_dev[dev])
                    yield (win.start, win.end, dev, avg_speed, min_speed, wkt), ingest

    return _run("mn_q5", lines, props, pipeline,
                formatter=lambda r: ",".join(map(str, r)))


INSTRUMENTED = {
    "q1": instrumented_mn_q1,
    "q2": instrumented_mn_q2,
    "q3": instrumented_mn_q3,
    "q4": instrumented_mn_q4,
    "q5": instrumented_mn_q5,
}
