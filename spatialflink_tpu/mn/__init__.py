from spatialflink_tpu.mn.metrics import (  # noqa: F401
    BUCKETS_MS,
    FixedBucketLatency,
    MetricNames,
    MetricRegistry,
)
from spatialflink_tpu.mn.operators import (  # noqa: F401
    Stamped,
    CsvParseAndStamp,
    CountingStage,
)
from spatialflink_tpu.mn.sinks import (  # noqa: F401
    CountingLatencyFileSink,
    CountingLatencyPrintSink,
)
from spatialflink_tpu.mn.reporter import NESFileReporter  # noqa: F401
